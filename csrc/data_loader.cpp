// Native data-loading runtime for lightgbm_tpu.
//
// The reference implements its parser/loader stack in C++
// (src/io/parser.{cpp,hpp}: CSV/TSV/LibSVM ParseOneLine; dataset_loader.cpp:
// two-round streaming + feature extraction; text_reader.h: chunked parallel
// reads).  This file is the TPU build's native equivalent: a multithreaded
// text parser producing a dense row-major float64 matrix (dense because the
// TPU data layer bins into dense feature-major arrays — see SURVEY.md §7
// step 2), plus the binning hot loop (value->bin binary search,
// bin.h:385-407) that turns raw columns into bin codes without holding the
// GIL.  Exposed through a plain C ABI consumed via ctypes
// (lightgbm_tpu/io/native.py); no pybind11 in this image.
//
// Format auto-detection mirrors Parser::CreateParser (parser.cpp:10-72):
// count ',' '\t' ':' occurrences in the probe lines; ':' dominance means
// LibSVM, else the more frequent of comma/tab.

#include <algorithm>
#include <atomic>
#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <limits>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// fast float parse (strtod is locale-dependent and slow; this is the usual
// hand-rolled parser, ~4x faster, matching Common::Atof behavior)
// ---------------------------------------------------------------------------
inline const char* skip_space(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t')) ++p;
  return p;
}

// Case-insensitive match of [b, e) against one of the NA / infinity
// spellings (the Python guard helpers' vocabulary, io/guard.py
// NA_TOKENS) — anything else starting with an alpha char is a
// *malformed* token, reported through first_bad_row so the guarded
// Python path re-parses with full diagnostics.
inline bool word_matches(const char* b, const char* e, const char* w) {
  while (b < e && *w) {
    if (std::tolower(static_cast<unsigned char>(*b)) != *w) return false;
    ++b;
    ++w;
  }
  return b == e && *w == '\0';
}

inline bool is_na_word(const char* b, const char* e) {
  return word_matches(b, e, "na") || word_matches(b, e, "nan") ||
         word_matches(b, e, "null") || word_matches(b, e, "none");
}

inline bool is_inf_word(const char* b, const char* e) {
  return word_matches(b, e, "inf") || word_matches(b, e, "infinity");
}

inline double parse_double(const char* p, const char* end, const char** out) {
  p = skip_space(p, end);
  const char* token_start = p;  // rewind point for degenerate tokens
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) {
    neg = (*p == '-');
    ++p;
  }
  double value = 0.0;
  bool consumed = false;
  while (p < end && *p >= '0' && *p <= '9') {
    value = value * 10.0 + (*p - '0');
    consumed = true;
    ++p;
  }
  if (p < end && *p == '.') {
    ++p;
    double frac = 0.1;
    while (p < end && *p >= '0' && *p <= '9') {
      value += (*p - '0') * frac;
      frac *= 0.1;
      consumed = true;
      ++p;
    }
  }
  if (consumed && p < end && (*p == 'e' || *p == 'E')) {
    const char* exp_start = p;
    ++p;
    bool eneg = false;
    if (p < end && (*p == '-' || *p == '+')) {
      eneg = (*p == '-');
      ++p;
    }
    int ex = 0;
    bool edigits = false;
    while (p < end && *p >= '0' && *p <= '9') {
      ex = ex * 10 + (*p - '0');
      edigits = true;
      ++p;
    }
    if (!edigits) {
      // "1e" / "2e+": not an exponent — leave the 'e' unconsumed so
      // the caller's whole-token check flags the row (Python
      // float("1e") is a classified bad token; parity)
      p = exp_start;
    } else {
      double scale = 1.0;
      double base = 10.0;
      int e = ex;
      while (e) {             // pow10 by squaring
        if (e & 1) scale *= base;
        base *= base;
        e >>= 1;
      }
      value = eneg ? value / scale : value * scale;
    }
  }
  // Word spellings: na/nan/null/none -> NaN (missing, the reference's
  // NA semantics — io/guard.py feature_value mirrors this), inf /
  // infinity -> inf.  Only the EXACT spellings consume; any other
  // alpha run is left unconsumed so the callers' whole-token checks
  // flag the row as malformed.
  if (!consumed && p < end &&
      (*p == 'n' || *p == 'N' || *p == 'i' || *p == 'I')) {
    const char* w = p;
    while (w < end && std::isalpha(static_cast<unsigned char>(*w))) ++w;
    if (is_na_word(p, w)) {
      value = std::numeric_limits<double>::quiet_NaN();
      consumed = true;
      p = w;
    } else if (is_inf_word(p, w)) {
      value = std::numeric_limits<double>::infinity();
      consumed = true;
      p = w;
    }
  }
  // Degenerate tokens ("-", "+", ".", "-."): nothing numeric was
  // consumed — rewind to the token start so the callers' whole-token
  // checks see leftover chars and flag the row instead of accepting
  // a phantom 0.0 (Python classifies these; parity).
  *out = consumed ? p : token_start;
  return neg ? -value : value;
}

inline long parse_long(const char* p, const char* end, const char** out) {
  p = skip_space(p, end);
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) {
    neg = (*p == '-');
    ++p;
  }
  long v = 0;
  while (p < end && *p >= '0' && *p <= '9') {
    v = v * 10 + (*p - '0');
    ++p;
  }
  *out = p;
  return neg ? -v : v;
}

// Does the first whitespace-delimited token contain ':'?  LibSVM rows whose
// first token is an index:value pair have no label (parser.py:67-71).
inline bool first_token_has_colon(const char* p, const char* e) {
  p = skip_space(p, e);
  while (p < e && *p != ' ' && *p != '\t') {
    if (*p == ':') return true;
    ++p;
  }
  return false;
}

struct LineIndex {
  std::vector<const char*> begin;
  std::vector<const char*> end;
};

// Split the buffer into lines (dropping \r), single pass.
LineIndex index_lines(const char* data, size_t size) {
  LineIndex idx;
  idx.begin.reserve(size / 64 + 1);
  idx.end.reserve(size / 64 + 1);
  const char* p = data;
  const char* bufend = data + size;
  while (p < bufend) {
    const char* nl = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(bufend - p)));
    const char* e = nl ? nl : bufend;
    const char* line_end = e;
    if (line_end > p && line_end[-1] == '\r') --line_end;
    if (line_end > p) {  // skip empty lines like TextReader does
      idx.begin.push_back(p);
      idx.end.push_back(line_end);
    }
    p = nl ? nl + 1 : bufend;
  }
  return idx;
}

int detect_format(const LineIndex& idx, size_t probe) {
  // 0 = csv, 1 = tsv, 2 = libsvm (parser.cpp:10-72)
  size_t n = std::min(probe, idx.begin.size());
  long commas = 0, tabs = 0, colons = 0;
  for (size_t i = 0; i < n; ++i) {
    for (const char* p = idx.begin[i]; p < idx.end[i]; ++p) {
      commas += (*p == ',');
      tabs += (*p == '\t');
      colons += (*p == ':');
    }
  }
  if (colons > 0 && colons >= std::max(commas, tabs)) return 2;
  if (tabs >= commas) return 1;
  return 0;
}

void parallel_for(int64_t n, const std::function<void(int64_t, int64_t)>& fn) {
  unsigned hw = std::thread::hardware_concurrency();
  int nthreads = static_cast<int>(std::max(1u, hw));
  if (n < 4096 || nthreads <= 1) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back(fn, lo, hi);
  }
  for (auto& th : ts) th.join();
}

}  // namespace

extern "C" {

// Parse a delimited/libsvm text file into a dense row-major [num_rows,
// num_cols] float64 matrix (caller-owned via lgbt_free) with the label
// column split out.  Returns 0 on success.
//   fmt_out: detected format (0 csv / 1 tsv / 2 libsvm)
//   num_cols = feature columns (label excluded)
//   first_bad_row_out: 1-based ordinal (among parsed data rows) of the
//     first malformed row — unparseable token, ragged field count, or a
//     bad LibSVM column index — or -1 when the file is clean.  The
//     native loader only *flags* dirt; the Python wrapper re-parses
//     flagged files through io/guard.py for classification, per-line
//     diagnostics, and the fail-fast/quarantine policy.
int lgbt_parse_file(const char* path, int has_header, int label_idx,
                    double** data_out, double** label_out,
                    int64_t* num_rows_out, int64_t* num_cols_out,
                    int* fmt_out, int64_t* first_bad_row_out) {
  *first_bad_row_out = -1;
  FILE* fh = fopen(path, "rb");
  if (!fh) return 1;
  fseek(fh, 0, SEEK_END);
  long fsize = ftell(fh);
  fseek(fh, 0, SEEK_SET);
  // +1 terminator: strtod in parse_double must not scan past the buffer
  std::vector<char> buf(static_cast<size_t>(fsize) + 1, '\0');
  if (fsize > 0 && fread(buf.data(), 1, static_cast<size_t>(fsize), fh) !=
                       static_cast<size_t>(fsize)) {
    fclose(fh);
    return 2;
  }
  fclose(fh);

  // Index only the true file bytes — buf has a +1 NUL terminator for the
  // float parser, and including it would turn the terminator into a phantom
  // 1-char final line (a bogus all-zero row on every newline-terminated file).
  LineIndex idx = index_lines(buf.data(), static_cast<size_t>(fsize));
  size_t first_row = has_header ? 1 : 0;
  if (idx.begin.size() <= first_row) {
    *num_rows_out = 0;
    *num_cols_out = 0;
    return 3;
  }
  int fmt = detect_format(idx, first_row + 32);
  *fmt_out = fmt;
  int64_t nrows = static_cast<int64_t>(idx.begin.size() - first_row);
  char delim = fmt == 0 ? ',' : '\t';

  // ---- column count from a probe pass (max over first rows + libsvm full
  // max-index scan, dataset_loader SetHeader role) ------------------------
  int64_t ncols = 0;
  if (fmt == 2) {
    std::atomic<int64_t> max_idx{-1};
    parallel_for(nrows, [&](int64_t lo, int64_t hi) {
      int64_t local = -1;
      for (int64_t r = lo; r < hi; ++r) {
        const char* p = idx.begin[first_row + r];
        const char* e = idx.end[first_row + r];
        const char* q;
        // A first token containing ':' is an index:value pair — the row has
        // no label (standard predict-time LibSVM; parser.py:67-71).
        if (!first_token_has_colon(p, e)) {
          const char* tok = skip_space(p, e);
          const char* tok_end = tok;
          while (tok_end < e && *tok_end != ' ' && *tok_end != '\t')
            ++tok_end;
          parse_double(tok, tok_end, &q);  // skip label
          p = tok_end;
        }
        while (p < e) {
          p = skip_space(p, e);
          if (p >= e) break;
          const char* tok_end = p;
          while (tok_end < e && *tok_end != ' ' && *tok_end != '\t')
            ++tok_end;
          // Only a FULLY valid digits:value token may raise the column
          // count — a malformed row must not inflate the matrix
          // allocation (the fill pass flags it for the Python path).
          const char* d = p;
          long k = 0;
          bool digits = false;
          while (d < tok_end && *d >= '0' && *d <= '9') {
            k = k * 10 + (*d - '0');
            digits = true;
            ++d;
            if (k > (1L << 31)) {  // absurd index: corrupt, not a column
              digits = false;
              break;
            }
          }
          if (digits && d < tok_end && *d == ':' && d + 1 < tok_end) {
            parse_double(d + 1, tok_end, &q);
            if (q == tok_end && k > local) local = k;
          }
          p = tok_end;
        }
      }
      int64_t cur = max_idx.load();
      while (local > cur && !max_idx.compare_exchange_weak(cur, local)) {
      }
    });
    ncols = max_idx.load() + 1;
  } else {
    // delimiter count on the first data line
    const char* p = idx.begin[first_row];
    const char* e = idx.end[first_row];
    int64_t fields = 1;
    for (; p < e; ++p) fields += (*p == delim);
    if (label_idx >= fields) return 5;  // caller falls back to Python
    ncols = fields - (label_idx >= 0 ? 1 : 0);
  }
  if (ncols < 0) ncols = 0;

  double* data =
      static_cast<double*>(malloc(sizeof(double) * nrows * ncols));
  double* label = static_cast<double*>(malloc(sizeof(double) * nrows));
  if (!data || !label) {
    free(data);
    free(label);
    return 4;
  }
  // label_idx < 0 means "no label column": leave labels at zero
  memset(label, 0, sizeof(double) * nrows);

  // first malformed data row (1-based ordinal), min across threads
  std::atomic<int64_t> first_bad{-1};
  auto flag_bad = [&first_bad](int64_t r) {
    int64_t ord = r + 1;
    int64_t cur = first_bad.load();
    while ((cur < 0 || ord < cur) &&
           !first_bad.compare_exchange_weak(cur, ord)) {
    }
  };
  // A numeric token must consume its WHOLE field — leftover chars
  // (after trailing spaces) mean garbage like "1.5x" or "abc".
  auto fully_parsed = [](const char* q, const char* fe) {
    q = skip_space(q, fe);
    return q == fe;
  };

  if (fmt == 2) {
    parallel_for(nrows, [&](int64_t lo, int64_t hi) {
      for (int64_t r = lo; r < hi; ++r) {
        const char* p = idx.begin[first_row + r];
        const char* e = idx.end[first_row + r];
        double* row = data + r * ncols;
        memset(row, 0, sizeof(double) * ncols);
        const char* q;
        bool bad = false;
        if (first_token_has_colon(p, e)) {
          label[r] = 0.0;  // label-less row (predict-time LibSVM)
        } else {
          const char* tok = skip_space(p, e);
          const char* tok_end = tok;
          while (tok_end < e && *tok_end != ' ' && *tok_end != '\t')
            ++tok_end;
          label[r] = parse_double(tok, tok_end, &q);
          bad = bad || (tok_end > tok && q != tok_end);
          p = tok_end;
        }
        while (p < e && !bad) {
          p = skip_space(p, e);
          if (p >= e) break;
          const char* tok_end = p;
          while (tok_end < e && *tok_end != ' ' && *tok_end != '\t')
            ++tok_end;
          // index: one or more bare digits (a leading '-' is the
          // negative-column corruption the guard classifies)
          const char* d = p;
          long k = 0;
          bool digits = false;
          while (d < tok_end && *d >= '0' && *d <= '9') {
            k = k * 10 + (*d - '0');
            digits = true;
            ++d;
            if (k > (1L << 31)) {  // absurd index: corrupt, not a column
              digits = false;
              break;
            }
          }
          if (!digits || d >= tok_end || *d != ':' || k >= ncols) {
            bad = true;
            break;
          }
          double v = parse_double(d + 1, tok_end, &q);
          if (d + 1 == tok_end || q != tok_end) {
            bad = true;  // empty or partially-consumed value token
            break;
          }
          row[k] = v;
          p = tok_end;
        }
        if (bad) flag_bad(r);
      }
    });
  } else {
    // expected field count: from the first data line (the probe above)
    const int64_t fields_expected =
        ncols + (label_idx >= 0 ? 1 : 0);
    parallel_for(nrows, [&](int64_t lo, int64_t hi) {
      for (int64_t r = lo; r < hi; ++r) {
        const char* p = idx.begin[first_row + r];
        const char* e = idx.end[first_row + r];
        double* row = data + r * ncols;
        int64_t col = 0;       // column in file incl. label position
        int64_t feat = 0;      // feature column
        bool bad = false;
        while (p <= e) {
          const char* field_end = static_cast<const char*>(
              memchr(p, delim, static_cast<size_t>(e - p)));
          if (!field_end) field_end = e;
          const char* fs = skip_space(p, field_end);
          const char* q;
          double v;
          if (fs == field_end) {
            // empty field: missing value (io/guard.py feature_value)
            v = std::numeric_limits<double>::quiet_NaN();
          } else {
            v = parse_double(fs, field_end, &q);
            if (!fully_parsed(q, field_end)) bad = true;
          }
          if (col == label_idx) {
            label[r] = v;
          } else if (feat < ncols) {
            row[feat++] = v;
          }
          ++col;
          p = field_end + 1;
          if (field_end == e) break;
        }
        if (col != fields_expected) bad = true;  // ragged row
        while (feat < ncols) row[feat++] = 0.0;
        if (bad) flag_bad(r);
      }
    });
  }

  *data_out = data;
  *label_out = label;
  *num_rows_out = nrows;
  *num_cols_out = ncols;
  *first_bad_row_out = first_bad.load();
  return 0;
}

void lgbt_free(void* p) { free(p); }

// Vectorized ValueToBin for a numerical feature (bin.h:385-407): for each
// value, the index of the first upper bound >= value (bounds[num_bin-1] is
// +inf).  Multithreaded over rows; writes uint8 or uint16 depending on
// out_is_u16.
void lgbt_values_to_bins(const double* values, int64_t n,
                         const double* upper_bounds, int num_bin,
                         uint8_t* out8, uint16_t* out16, int out_is_u16) {
  parallel_for(n, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      double v = values[i];
      // binary search: first bound >= v among bounds[0..num_bin-2]
      int l = 0, r = num_bin - 1;  // bounds[num_bin-1] = +inf catches rest
      while (l < r) {
        int m = (l + r) / 2;
        if (upper_bounds[m] < v) {
          l = m + 1;
        } else {
          r = m;
        }
      }
      if (out_is_u16) {
        out16[i] = static_cast<uint16_t>(l);
      } else {
        out8[i] = static_cast<uint8_t>(l);
      }
    }
  });
}

}  // extern "C"
