// Native data-loading runtime for lightgbm_tpu.
//
// The reference implements its parser/loader stack in C++
// (src/io/parser.{cpp,hpp}: CSV/TSV/LibSVM ParseOneLine; dataset_loader.cpp:
// two-round streaming + feature extraction; text_reader.h: chunked parallel
// reads).  This file is the TPU build's native equivalent: a multithreaded
// text parser producing a dense row-major float64 matrix (dense because the
// TPU data layer bins into dense feature-major arrays — see SURVEY.md §7
// step 2), plus the binning hot loop (value->bin binary search,
// bin.h:385-407) that turns raw columns into bin codes without holding the
// GIL.  Exposed through a plain C ABI consumed via ctypes
// (lightgbm_tpu/io/native.py); no pybind11 in this image.
//
// Format auto-detection mirrors Parser::CreateParser (parser.cpp:10-72):
// count ',' '\t' ':' occurrences in the probe lines; ':' dominance means
// LibSVM, else the more frequent of comma/tab.

#include <algorithm>
#include <atomic>
#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// fast float parse (strtod is locale-dependent and slow; this is the usual
// hand-rolled parser, ~4x faster, matching Common::Atof behavior)
// ---------------------------------------------------------------------------
inline const char* skip_space(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t')) ++p;
  return p;
}

inline double parse_double(const char* p, const char* end, const char** out) {
  p = skip_space(p, end);
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) {
    neg = (*p == '-');
    ++p;
  }
  double value = 0.0;
  while (p < end && *p >= '0' && *p <= '9') {
    value = value * 10.0 + (*p - '0');
    ++p;
  }
  if (p < end && *p == '.') {
    ++p;
    double frac = 0.1;
    while (p < end && *p >= '0' && *p <= '9') {
      value += (*p - '0') * frac;
      frac *= 0.1;
      ++p;
    }
  }
  if (p < end && (*p == 'e' || *p == 'E')) {
    ++p;
    bool eneg = false;
    if (p < end && (*p == '-' || *p == '+')) {
      eneg = (*p == '-');
      ++p;
    }
    int ex = 0;
    while (p < end && *p >= '0' && *p <= '9') {
      ex = ex * 10 + (*p - '0');
      ++p;
    }
    double scale = 1.0;
    double base = 10.0;
    int e = ex;
    while (e) {               // pow10 by squaring
      if (e & 1) scale *= base;
      base *= base;
      e >>= 1;
    }
    value = eneg ? value / scale : value * scale;
  }
  // Token spellings: na/nan/null -> 0.0 (matching the Python parser's
  // missing-value mapping, parser.py _parse_delimited); inf parses as inf.
  if (value == 0.0 && p < end &&
      (*p == 'n' || *p == 'N' || *p == 'i' || *p == 'I')) {
    if (p[0] == 'n' || p[0] == 'N') {
      value = 0.0;
      while (p < end && std::isalpha(static_cast<unsigned char>(*p))) ++p;
    } else {
      value = std::strtod(p, nullptr);
      while (p < end && std::isalpha(static_cast<unsigned char>(*p))) ++p;
    }
  }
  *out = p;
  return neg ? -value : value;
}

inline long parse_long(const char* p, const char* end, const char** out) {
  p = skip_space(p, end);
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) {
    neg = (*p == '-');
    ++p;
  }
  long v = 0;
  while (p < end && *p >= '0' && *p <= '9') {
    v = v * 10 + (*p - '0');
    ++p;
  }
  *out = p;
  return neg ? -v : v;
}

// Does the first whitespace-delimited token contain ':'?  LibSVM rows whose
// first token is an index:value pair have no label (parser.py:67-71).
inline bool first_token_has_colon(const char* p, const char* e) {
  p = skip_space(p, e);
  while (p < e && *p != ' ' && *p != '\t') {
    if (*p == ':') return true;
    ++p;
  }
  return false;
}

struct LineIndex {
  std::vector<const char*> begin;
  std::vector<const char*> end;
};

// Split the buffer into lines (dropping \r), single pass.
LineIndex index_lines(const char* data, size_t size) {
  LineIndex idx;
  idx.begin.reserve(size / 64 + 1);
  idx.end.reserve(size / 64 + 1);
  const char* p = data;
  const char* bufend = data + size;
  while (p < bufend) {
    const char* nl = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(bufend - p)));
    const char* e = nl ? nl : bufend;
    const char* line_end = e;
    if (line_end > p && line_end[-1] == '\r') --line_end;
    if (line_end > p) {  // skip empty lines like TextReader does
      idx.begin.push_back(p);
      idx.end.push_back(line_end);
    }
    p = nl ? nl + 1 : bufend;
  }
  return idx;
}

int detect_format(const LineIndex& idx, size_t probe) {
  // 0 = csv, 1 = tsv, 2 = libsvm (parser.cpp:10-72)
  size_t n = std::min(probe, idx.begin.size());
  long commas = 0, tabs = 0, colons = 0;
  for (size_t i = 0; i < n; ++i) {
    for (const char* p = idx.begin[i]; p < idx.end[i]; ++p) {
      commas += (*p == ',');
      tabs += (*p == '\t');
      colons += (*p == ':');
    }
  }
  if (colons > 0 && colons >= std::max(commas, tabs)) return 2;
  if (tabs >= commas) return 1;
  return 0;
}

void parallel_for(int64_t n, const std::function<void(int64_t, int64_t)>& fn) {
  unsigned hw = std::thread::hardware_concurrency();
  int nthreads = static_cast<int>(std::max(1u, hw));
  if (n < 4096 || nthreads <= 1) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back(fn, lo, hi);
  }
  for (auto& th : ts) th.join();
}

}  // namespace

extern "C" {

// Parse a delimited/libsvm text file into a dense row-major [num_rows,
// num_cols] float64 matrix (caller-owned via lgbt_free) with the label
// column split out.  Returns 0 on success.
//   fmt_out: detected format (0 csv / 1 tsv / 2 libsvm)
//   num_cols = feature columns (label excluded)
int lgbt_parse_file(const char* path, int has_header, int label_idx,
                    double** data_out, double** label_out,
                    int64_t* num_rows_out, int64_t* num_cols_out,
                    int* fmt_out) {
  FILE* fh = fopen(path, "rb");
  if (!fh) return 1;
  fseek(fh, 0, SEEK_END);
  long fsize = ftell(fh);
  fseek(fh, 0, SEEK_SET);
  // +1 terminator: strtod in parse_double must not scan past the buffer
  std::vector<char> buf(static_cast<size_t>(fsize) + 1, '\0');
  if (fsize > 0 && fread(buf.data(), 1, static_cast<size_t>(fsize), fh) !=
                       static_cast<size_t>(fsize)) {
    fclose(fh);
    return 2;
  }
  fclose(fh);

  // Index only the true file bytes — buf has a +1 NUL terminator for the
  // float parser, and including it would turn the terminator into a phantom
  // 1-char final line (a bogus all-zero row on every newline-terminated file).
  LineIndex idx = index_lines(buf.data(), static_cast<size_t>(fsize));
  size_t first_row = has_header ? 1 : 0;
  if (idx.begin.size() <= first_row) {
    *num_rows_out = 0;
    *num_cols_out = 0;
    return 3;
  }
  int fmt = detect_format(idx, first_row + 32);
  *fmt_out = fmt;
  int64_t nrows = static_cast<int64_t>(idx.begin.size() - first_row);
  char delim = fmt == 0 ? ',' : '\t';

  // ---- column count from a probe pass (max over first rows + libsvm full
  // max-index scan, dataset_loader SetHeader role) ------------------------
  int64_t ncols = 0;
  if (fmt == 2) {
    std::atomic<int64_t> max_idx{-1};
    parallel_for(nrows, [&](int64_t lo, int64_t hi) {
      int64_t local = -1;
      for (int64_t r = lo; r < hi; ++r) {
        const char* p = idx.begin[first_row + r];
        const char* e = idx.end[first_row + r];
        const char* q;
        // A first token containing ':' is an index:value pair — the row has
        // no label (standard predict-time LibSVM; parser.py:67-71).
        if (!first_token_has_colon(p, e)) {
          parse_double(p, e, &q);  // skip label
          p = q;
        }
        while (p < e) {
          p = skip_space(p, e);
          if (p >= e) break;
          long k = parse_long(p, e, &q);
          if (q < e && *q == ':') {
            if (k > local) local = k;
            p = q + 1;
            parse_double(p, e, &q);
            p = q;
          } else {
            p = q < e ? q + 1 : e;
          }
        }
      }
      int64_t cur = max_idx.load();
      while (local > cur && !max_idx.compare_exchange_weak(cur, local)) {
      }
    });
    ncols = max_idx.load() + 1;
  } else {
    // delimiter count on the first data line
    const char* p = idx.begin[first_row];
    const char* e = idx.end[first_row];
    int64_t fields = 1;
    for (; p < e; ++p) fields += (*p == delim);
    if (label_idx >= fields) return 5;  // caller falls back to Python
    ncols = fields - (label_idx >= 0 ? 1 : 0);
  }
  if (ncols < 0) ncols = 0;

  double* data =
      static_cast<double*>(malloc(sizeof(double) * nrows * ncols));
  double* label = static_cast<double*>(malloc(sizeof(double) * nrows));
  if (!data || !label) {
    free(data);
    free(label);
    return 4;
  }
  // label_idx < 0 means "no label column": leave labels at zero
  memset(label, 0, sizeof(double) * nrows);

  if (fmt == 2) {
    parallel_for(nrows, [&](int64_t lo, int64_t hi) {
      for (int64_t r = lo; r < hi; ++r) {
        const char* p = idx.begin[first_row + r];
        const char* e = idx.end[first_row + r];
        double* row = data + r * ncols;
        memset(row, 0, sizeof(double) * ncols);
        const char* q;
        if (first_token_has_colon(p, e)) {
          label[r] = 0.0;  // label-less row (predict-time LibSVM)
        } else {
          label[r] = parse_double(p, e, &q);
          p = q;
        }
        while (p < e) {
          p = skip_space(p, e);
          if (p >= e) break;
          long k = parse_long(p, e, &q);
          if (q < e && *q == ':') {
            p = q + 1;
            double v = parse_double(p, e, &q);
            if (k >= 0 && k < ncols) row[k] = v;
            p = q;
          } else {
            p = q < e ? q + 1 : e;
          }
        }
      }
    });
  } else {
    parallel_for(nrows, [&](int64_t lo, int64_t hi) {
      for (int64_t r = lo; r < hi; ++r) {
        const char* p = idx.begin[first_row + r];
        const char* e = idx.end[first_row + r];
        double* row = data + r * ncols;
        int64_t col = 0;       // column in file incl. label position
        int64_t feat = 0;      // feature column
        while (p <= e && col <= ncols) {
          const char* field_end = static_cast<const char*>(
              memchr(p, delim, static_cast<size_t>(e - p)));
          if (!field_end) field_end = e;
          const char* q;
          double v = parse_double(p, field_end, &q);
          if (col == label_idx) {
            label[r] = v;
          } else if (feat < ncols) {
            row[feat++] = v;
          }
          ++col;
          p = field_end + 1;
          if (field_end == e) break;
        }
        while (feat < ncols) row[feat++] = 0.0;
      }
    });
  }

  *data_out = data;
  *label_out = label;
  *num_rows_out = nrows;
  *num_cols_out = ncols;
  return 0;
}

void lgbt_free(void* p) { free(p); }

// Vectorized ValueToBin for a numerical feature (bin.h:385-407): for each
// value, the index of the first upper bound >= value (bounds[num_bin-1] is
// +inf).  Multithreaded over rows; writes uint8 or uint16 depending on
// out_is_u16.
void lgbt_values_to_bins(const double* values, int64_t n,
                         const double* upper_bounds, int num_bin,
                         uint8_t* out8, uint16_t* out16, int out_is_u16) {
  parallel_for(n, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      double v = values[i];
      // binary search: first bound >= v among bounds[0..num_bin-2]
      int l = 0, r = num_bin - 1;  // bounds[num_bin-1] = +inf catches rest
      while (l < r) {
        int m = (l + r) / 2;
        if (upper_bounds[m] < v) {
          l = m + 1;
        } else {
          r = m;
        }
      }
      if (out_is_u16) {
        out16[i] = static_cast<uint16_t>(l);
      } else {
        out8[i] = static_cast<uint8_t>(l);
      }
    }
  });
}

}  // extern "C"
