# End-to-end R smoke test: train / predict / save / load / dump /
# importance / model.dt.tree / interprete / RDS round-trip / callbacks.
# Run by CI wherever an R runtime with reticulate exists:
#
#   Rscript R-package/tests/smoke.R
#
# PYTHONPATH (or an installed lightgbm_tpu) must expose the Python core.

for (f in list.files("R-package/R", full.names = TRUE)) source(f)

set.seed(1)
n <- 600
X <- matrix(rnorm(n * 5), ncol = 5)
colnames(X) <- paste0("f", 1:5)
y <- as.numeric(X[, 1] + 0.5 * X[, 2] > 0)

ds <- lgb.Dataset(X, info = list(label = y))
bst <- lgb.train(list(objective = "binary", num_leaves = 7,
                      min_data_in_leaf = 20, verbose = -1),
                 data = ds, nrounds = 10,
                 callbacks = list(cb.record.evaluation()))
stopifnot(inherits(bst, "lgb.Booster"))

p <- bst$predict(X)
stopifnot(length(p) == n, all(is.finite(p)))
auc_ok <- mean((p > 0.5) == y) > 0.8
stopifnot(auc_ok)

# save / load round-trip
f_model <- tempfile(fileext = ".txt")
lgb.save(bst, f_model)
bst2 <- lgb.load(filename = f_model)
stopifnot(max(abs(bst2$predict(X) - p)) < 1e-10)

# dump + tree table + importance
dump <- bst$dump_model()
stopifnot(length(dump$tree_info) == 10)
tree_dt <- lgb.model.dt.tree(bst)
stopifnot(nrow(tree_dt) > 10, "split_feature" %in% colnames(tree_dt))
imp <- lgb.importance(bst)
stopifnot(nrow(imp) >= 1)

# interpretation of 3 rows
contrib <- lgb.interprete(bst, X, 1:3)
stopifnot(length(contrib) == 3,
          all(vapply(contrib, function(d) "Feature" %in% colnames(d),
                     TRUE)))

# RDS round-trip
f_rds <- tempfile(fileext = ".rds")
saveRDS.lgb.Booster(bst, f_rds)
bst3 <- readRDS.lgb.Booster(f_rds)
stopifnot(max(abs(bst3$predict(X) - p)) < 1e-10)

# Predictor + leaf indices
pred <- Predictor$new(bst, predleaf = TRUE)
leaves <- pred$predict(X[1:4, , drop = FALSE])
stopifnot(nrow(leaves) == 4)

cat("R-SMOKE-OK\n")
