# saveRDS/readRDS wrappers: an lgb.Booster holds a live Python handle that
# R serialization cannot capture, so the model travels as its reference
# text format inside the RDS payload.
#
# Reference surface: R-package/R/saveRDS.lgb.Booster.R and
# readRDS.lgb.Booster.R (which stash the C++ handle's raw model string the
# same way).

saveRDS.lgb.Booster <- function(object, file = "", ascii = FALSE,
                                version = NULL, compress = TRUE,
                                refhook = NULL) {
  lgb.check.r6(object, "lgb.Booster", "saveRDS.lgb.Booster")
  payload <- list(
    lgb_booster_model_str = object$save_model_to_string(),
    best_iter = object$best_iter,
    record_evals = object$record_evals)
  class(payload) <- "lgb.Booster.rds"
  saveRDS(payload, file = file, ascii = ascii, version = version,
          compress = compress, refhook = refhook)
}

readRDS.lgb.Booster <- function(file = "", refhook = NULL) {
  payload <- readRDS(file = file, refhook = refhook)
  if (!inherits(payload, "lgb.Booster.rds")) {
    # a plain RDS: return unchanged, like the reference
    return(payload)
  }
  booster <- lgb.load(model_str = payload$lgb_booster_model_str)
  booster$best_iter <- payload$best_iter
  booster$record_evals <- payload$record_evals
  booster
}
