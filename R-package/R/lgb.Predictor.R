# lgb.Predictor: internal prediction helper.
#
# Reference surface: R-package/R/lgb.Predictor.R (an R6 class owning a
# model handle + prediction parameters, used by Booster$predict and by
# Dataset construction with a predictor for continued training).  Here it
# wraps the Python Booster's predict with pinned parameters.

lgb.Predictor <- R6::R6Class(
  "lgb.Predictor",
  public = list(
    booster = NULL,
    num_iteration = -1L,
    rawscore = FALSE,
    predleaf = FALSE,

    initialize = function(booster, num_iteration = -1L,
                          rawscore = FALSE, predleaf = FALSE) {
      if (inherits(booster, "lgb.Booster")) {
        self$booster <- booster
      } else if (is.character(booster)) {
        self$booster <- lgb.load(filename = booster)
      } else {
        stop("lgb.Predictor: booster must be an lgb.Booster or a model ",
             "file path")
      }
      self$num_iteration <- as.integer(num_iteration)
      self$rawscore <- rawscore
      self$predleaf <- predleaf
      invisible(self)
    },

    current_iter = function() {
      as.integer(self$booster$py$num_trees()) %/%
        max(self$booster$num_class(), 1L)
    },

    predict = function(data, header = FALSE, reshape = TRUE) {
      self$booster$predict(data, num_iteration = self$num_iteration,
                           rawscore = self$rawscore,
                           predleaf = self$predleaf,
                           header = header, reshape = reshape)
    }
  )
)

# short internal alias (reference code and tests use Predictor$new)
Predictor <- lgb.Predictor
