# lgb.plot.importance / lgb.plot.interpretation: base-graphics barplots.
#
# Reference surface: R-package/R/lgb.plot.importance.R and
# lgb.plot.interpretation.R (graphics::barplot of the importance /
# interpretation tables, top_n rows, horizontal, labels in the margin).

lgb.plot.importance <- function(tree_imp, top_n = 10, measure = "Gain",
                                left_margin = 10, cex = NULL) {
  tree_imp <- as.data.frame(tree_imp)
  if (!measure %in% colnames(tree_imp)) {
    stop("lgb.plot.importance: measure must be one of ",
         paste(setdiff(colnames(tree_imp), "Feature"), collapse = ", "))
  }
  tree_imp <- tree_imp[order(-tree_imp[[measure]]), , drop = FALSE]
  n <- min(top_n, nrow(tree_imp))
  tree_imp <- tree_imp[seq_len(n), , drop = FALSE]
  op <- graphics::par(mar = c(3, left_margin, 3, 1))
  on.exit(graphics::par(op))
  graphics::barplot(rev(tree_imp[[measure]]),
                    names.arg = rev(tree_imp$Feature),
                    horiz = TRUE, las = 1, cex.names = cex,
                    main = "Feature Importance",
                    xlab = measure, border = NA)
  invisible(tree_imp)
}

lgb.plot.interpretation <- function(tree_interpretation_dt, top_n = 10,
                                    cols = 1, left_margin = 10,
                                    cex = NULL) {
  ti <- as.data.frame(tree_interpretation_dt)
  num_class <- ncol(ti) - 1L
  op <- graphics::par(mar = c(3, left_margin, 3, 1),
                      mfrow = c(ceiling(num_class / cols),
                                min(cols, num_class)))
  on.exit(graphics::par(op))
  for (k in seq_len(num_class)) {
    col <- colnames(ti)[k + 1L]
    ord <- order(-abs(ti[[col]]))
    sub <- ti[ord[seq_len(min(top_n, nrow(ti)))], , drop = FALSE]
    graphics::barplot(rev(sub[[col]]), names.arg = rev(sub$Feature),
                      horiz = TRUE, las = 1, cex.names = cex,
                      main = if (num_class > 1L) col
                             else "Feature Contribution",
                      xlab = "Contribution", border = NA)
  }
  invisible(tree_interpretation_dt)
}
