# Training callbacks for lgb.train/lgb.cv.
#
# Reference surface: R-package/R/callback.R (cb.reset.parameters,
# cb.print.evaluation, cb.record.evaluation, cb.early.stop closures run by
# the R training loop).  In this binding the boosting loop runs inside the
# Python engine, so each R constructor returns a TAG the training entries
# translate into the matching Python callback (lightgbm_tpu.callback);
# arbitrary R closures cannot run inside the Python loop and are rejected
# with a clear message by lgb.train.

cb.print.evaluation <- function(period = 1L) {
  structure(list(kind = "print_evaluation", period = as.integer(period)),
            class = "lgb.cb")
}

cb.record.evaluation <- function() {
  structure(list(kind = "record_evaluation"), class = "lgb.cb")
}

cb.reset.parameters <- function(new_params) {
  # new_params: named list; each entry either a numeric vector of length
  # nrounds or an R function(iter) -> value called with the 0-based
  # round index (the Python engine's reset_parameter contract,
  # lightgbm_tpu/callback.py)
  structure(list(kind = "reset_parameter", new_params = new_params),
            class = "lgb.cb")
}

cb.early.stop <- function(stopping_rounds, verbose = TRUE) {
  structure(list(kind = "early_stopping",
                 stopping_rounds = as.integer(stopping_rounds),
                 verbose = verbose),
            class = "lgb.cb")
}

# Internal: translate a list of lgb.cb tags into Python callbacks.
# Returns list(py_callbacks, record_env) where record_env$dict is the
# evals_result dict when cb.record.evaluation was requested.
lgb.cb2py <- function(callbacks) {
  lgb <- lgb.get.module()
  cb_mod <- reticulate::import("lightgbm_tpu.callback")
  out <- list()
  record <- NULL
  for (cb in callbacks) {
    if (!inherits(cb, "lgb.cb")) {
      stop("lgb.train: callbacks must be built by cb.print.evaluation / ",
           "cb.record.evaluation / cb.reset.parameters / cb.early.stop; ",
           "custom R closures cannot run inside the Python training loop")
    }
    if (cb$kind == "print_evaluation") {
      out[[length(out) + 1L]] <- cb_mod$print_evaluation(cb$period)
    } else if (cb$kind == "record_evaluation") {
      record <- reticulate::dict()
      out[[length(out) + 1L]] <- cb_mod$record_evaluation(record)
    } else if (cb$kind == "reset_parameter") {
      # length-1 numeric vectors convert to Python scalars; force lists
      # so the Python side always sees a schedule sequence
      vals <- lapply(cb$new_params, function(v)
        if (is.numeric(v)) as.list(v) else v)
      out[[length(out) + 1L]] <- do.call(cb_mod$reset_parameter, vals)
    } else if (cb$kind == "early_stopping") {
      out[[length(out) + 1L]] <- cb_mod$early_stopping(
        cb$stopping_rounds, verbose = cb$verbose)
    }
  }
  list(py_callbacks = out, record = record)
}
