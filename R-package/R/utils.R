# Bridge to the Python core.
#
# Reference: R-package/R/utils.R (lgb.call / lgb.params2str plumbing over
# the C API).  Here the binding rides reticulate directly into the
# lightgbm_tpu Python package: the Python surface (basic.Dataset,
# basic.Booster, engine.train/cv) is itself a faithful port of the
# reference python-package, so the R<->Python mapping stays 1:1 with the
# reference's R<->C mapping.

.lgb_env <- new.env(parent = emptyenv())

lgb.get.module <- function() {
  if (is.null(.lgb_env$module)) {
    .lgb_env$module <- reticulate::import("lightgbm_tpu", delay_load = FALSE)
  }
  .lgb_env$module
}

lgb.params2list <- function(params, ...) {
  extra <- list(...)
  for (k in names(extra)) {
    params[[k]] <- extra[[k]]
  }
  params
}

lgb.check.r6 <- function(x, cls, what) {
  if (!inherits(x, cls)) {
    stop(sprintf("%s: expected a %s object", what, cls))
  }
  invisible(x)
}

# data.frame/matrix -> numpy, keeping double precision
lgb.as.matrix <- function(data) {
  if (is.data.frame(data)) {
    data <- as.matrix(data)
  }
  storage.mode(data) <- "double"
  reticulate::np_array(data)
}
