# lgb.Dataset: R6 wrapper of lightgbm_tpu.Dataset.
#
# Reference surface: R-package/R/lgb.Dataset.R:404-738 (lgb.Dataset,
# lgb.Dataset.create.valid, lgb.Dataset.construct, lgb.Dataset.save,
# dim/dimnames/slice, getinfo/setinfo).

Dataset <- R6::R6Class(
  "lgb.Dataset",
  public = list(
    py = NULL,

    initialize = function(data, params = list(), reference = NULL,
                          colnames = NULL, categorical_feature = NULL,
                          free_raw_data = TRUE, info = list(), ...) {
      lgb <- lgb.get.module()
      info <- c(info, list(...))
      if (is.character(data)) {
        payload <- data               # file path, parsed by the core
      } else {
        payload <- lgb.as.matrix(data)
      }
      ref_py <- if (!is.null(reference)) reference$py else NULL
      feat <- if (is.null(colnames)) "auto" else as.list(colnames)
      # numeric feature indices are 1-based in R, 0-based in the core
      # (reference R-package does the same -1L)
      cat_feat <- if (is.null(categorical_feature)) "auto" else
        as.list(lapply(categorical_feature, function(x) {
          if (is.numeric(x)) as.integer(x) - 1L else x
        }))
      self$py <- lgb$Dataset(
        data = payload,
        label = info[["label"]],
        weight = info[["weight"]],
        group = info[["group"]],
        params = params,
        feature_name = feat,
        categorical_feature = cat_feat,
        free_raw_data = free_raw_data)
      if (!is.null(info[["init_score"]])) {
        self$setinfo("init_score", info[["init_score"]])
      }
      invisible(self)
    },

    construct = function() {
      self$py$construct()
      invisible(self)
    },

    create_valid = function(data, info = list(), ...) {
      info <- c(info, list(...))
      valid <- Dataset$new(data, reference = self)
      for (k in names(info)) {
        valid$setinfo(k, info[[k]])
      }
      valid
    },

    dim = function() {
      c(self$py$num_data(), self$py$num_feature())
    },

    get_colnames = function() {
      unlist(reticulate::py_to_r(self$py$construct()$`_binned`$feature_names))
    },

    setinfo = function(name, info) {
      switch(name,
             label = self$py$set_label(reticulate::np_array(as.double(info))),
             weight = self$py$set_weight(reticulate::np_array(as.double(info))),
             init_score = self$py$set_init_score(
               reticulate::np_array(as.double(info))),
             group = self$py$set_group(reticulate::np_array(as.integer(info))),
             stop(sprintf("setinfo: unknown field %s", name)))
      invisible(self)
    },

    getinfo = function(name) {
      out <- switch(name,
                    label = self$py$get_label(),
                    weight = self$py$get_weight(),
                    init_score = self$py$get_init_score(),
                    group = self$py$get_group(),
                    stop(sprintf("getinfo: unknown field %s", name)))
      if (is.null(out)) NULL else as.vector(reticulate::py_to_r(out))
    },

    slice = function(idxset) {
      sub <- Dataset$new(matrix(0, 1, 1))  # placeholder, replaced below
      sub$py <- self$py$subset(reticulate::np_array(
        as.integer(idxset) - 1L))          # R is 1-based
      sub
    },

    save_binary = function(fname) {
      self$py$save_binary(fname)
      invisible(self)
    },

    set_reference = function(reference) {
      lgb.check.r6(reference, "lgb.Dataset", "set_reference")
      self$py$set_reference(reference$py)
      invisible(self)
    },

    set_categorical_feature = function(categorical_feature) {
      self$py$set_categorical_feature(as.list(
        lapply(categorical_feature, function(x) {
          if (is.numeric(x)) as.integer(x) - 1L else x
        })))
      invisible(self)
    }
  )
)

#' Construct a lgb.Dataset (reference lgb.Dataset, lgb.Dataset.R:404)
lgb.Dataset <- function(data, params = list(), reference = NULL,
                        colnames = NULL, categorical_feature = NULL,
                        free_raw_data = TRUE, info = list(), ...) {
  Dataset$new(data, params, reference, colnames, categorical_feature,
              free_raw_data, info, ...)
}

lgb.Dataset.create.valid <- function(dataset, data, info = list(), ...) {
  lgb.check.r6(dataset, "lgb.Dataset", "lgb.Dataset.create.valid")
  dataset$create_valid(data, info, ...)
}

lgb.Dataset.construct <- function(dataset) {
  lgb.check.r6(dataset, "lgb.Dataset", "lgb.Dataset.construct")
  dataset$construct()
}

lgb.Dataset.save <- function(dataset, fname) {
  lgb.check.r6(dataset, "lgb.Dataset", "lgb.Dataset.save")
  dataset$save_binary(fname)
}

lgb.Dataset.set.categorical <- function(dataset, categorical_feature) {
  dataset$set_categorical_feature(categorical_feature)
}

lgb.Dataset.set.reference <- function(dataset, reference) {
  dataset$set_reference(reference)
}

setinfo <- function(dataset, name, info, ...) {
  dataset$setinfo(name, info)
}

getinfo <- function(dataset, name, ...) {
  dataset$getinfo(name)
}

dim.lgb.Dataset <- function(x, ...) {
  x$dim()
}

dimnames.lgb.Dataset <- function(x) {
  list(NULL, x$get_colnames())
}
