# lgb.train / lgb.cv / lightgbm: the training entries.
#
# Reference surface: R-package/R/lgb.train.R:49-175, lgb.cv.R:73-290,
# lightgbm.R:6-48.  The boosting loop, early stopping and evals_result
# recording run in the Python engine (engine.train / engine.cv), which the
# Python test-suite pins against the reference iteration for iteration.

lgb.train <- function(params = list(), data, nrounds = 10,
                      valids = list(),
                      obj = NULL,
                      eval = NULL,
                      verbose = 1,
                      record = TRUE,
                      eval_freq = 1L,
                      init_model = NULL,
                      colnames = NULL,
                      categorical_feature = NULL,
                      early_stopping_rounds = NULL,
                      callbacks = list(), ...) {
  lgb <- lgb.get.module()
  lgb.check.r6(data, "lgb.Dataset", "lgb.train")
  cb <- lgb.cb2py(callbacks)          # tags from callback.R -> Python
  params <- lgb.params2list(params, ...)
  if (!is.null(obj)) {
    params$objective <- obj
  }
  if (!is.null(eval)) {
    params$metric <- eval
  }
  if (!is.null(colnames)) {
    data$py$set_feature_name(as.list(colnames))
  }
  if (!is.null(categorical_feature)) {
    data$set_categorical_feature(categorical_feature)
  }
  params$verbose <- verbose
  valid_sets <- lapply(valids, function(v) v$py)
  valid_names <- names(valids)
  evals_result <- reticulate::dict()    # engine records into a Python dict
  init_tmp <- NULL
  init <- if (inherits(init_model, "lgb.Booster")) {
    init_tmp <- tempfile(fileext = ".txt")
    init_model$save_model(init_tmp)
    init_tmp
  } else {
    init_model
  }
  on.exit(if (!is.null(init_tmp)) unlink(init_tmp), add = TRUE)
  py_booster <- lgb$train(
    params = params,
    train_set = data$py,
    num_boost_round = as.integer(nrounds),
    valid_sets = if (length(valid_sets)) valid_sets else NULL,
    valid_names = if (length(valid_names)) as.list(valid_names) else NULL,
    early_stopping_rounds = if (is.null(early_stopping_rounds)) NULL else
      as.integer(early_stopping_rounds),
    evals_result = evals_result,
    verbose_eval = if (verbose > 0) as.integer(eval_freq) else FALSE,
    init_model = init,
    callbacks = if (length(cb$py_callbacks)) cb$py_callbacks else NULL)
  out <- Booster$new(py_handle = py_booster)
  out$best_iter <- py_booster$best_iteration
  if (record) {
    out$record_evals <- reticulate::py_to_r(evals_result)
  }
  if (!is.null(cb$record)) {
    out$record_evals <- utils::modifyList(out$record_evals,
                                          reticulate::py_to_r(cb$record))
  }
  out
}

lgb.cv <- function(params = list(), data, nrounds = 10, nfold = 3,
                   label = NULL, weight = NULL, obj = NULL, eval = NULL,
                   verbose = 1, record = TRUE, eval_freq = 1L,
                   showsd = TRUE, stratified = TRUE, folds = NULL,
                   init_model = NULL, colnames = NULL,
                   categorical_feature = NULL,
                   early_stopping_rounds = NULL, callbacks = list(), ...) {
  lgb <- lgb.get.module()
  if (length(callbacks)) {
    stop("lgb.cv: R-side callbacks are not supported by this binding")
  }
  if (!is.null(folds)) {
    stop("lgb.cv: custom folds are not supported by this binding")
  }
  if (!inherits(data, "lgb.Dataset")) {
    # reference lgb.cv accepts a raw matrix + label/weight
    data <- lgb.Dataset(data, info = list(label = label, weight = weight))
  }
  params <- lgb.params2list(params, ...)
  if (!is.null(obj)) {
    params$objective <- obj
  }
  if (!is.null(eval)) {
    params$metric <- eval
  }
  out <- lgb$cv(
    params = params,
    train_set = data$py,
    num_boost_round = as.integer(nrounds),
    nfold = as.integer(nfold),
    stratified = stratified,
    early_stopping_rounds = if (is.null(early_stopping_rounds)) NULL else
      as.integer(early_stopping_rounds),
    verbose_eval = if (verbose > 0) as.integer(eval_freq) else FALSE)
  reticulate::py_to_r(out)
}

lightgbm <- function(data, label = NULL, weight = NULL,
                     params = list(), nrounds = 10,
                     verbose = 1, eval_freq = 1L,
                     early_stopping_rounds = NULL,
                     save_name = "lightgbm.model",
                     init_model = NULL, callbacks = list(), ...) {
  dtrain <- if (inherits(data, "lgb.Dataset")) data else
    lgb.Dataset(data, info = list(label = label, weight = weight))
  booster <- lgb.train(params = params, data = dtrain, nrounds = nrounds,
                       verbose = verbose, eval_freq = eval_freq,
                       early_stopping_rounds = early_stopping_rounds,
                       init_model = init_model, callbacks = callbacks, ...)
  if (!is.null(save_name)) {
    booster$save_model(save_name)
  }
  booster
}
