# lgb.model.dt.tree: flatten a model dump into one table of nodes+leaves.
#
# Reference surface: R-package/R/lgb.model.dt.tree.R (jsonlite parse of
# lgb.dump + per-tree recursive flatten).  Here the Python dump_model()
# dict arrives through reticulate already parsed, so only the flatten
# remains.  Returns a data.table when data.table is installed, else a
# data.frame with the same columns.

lgb.model.dt.tree <- function(model, num_iteration = NULL) {
  lgb.check.r6(model, "lgb.Booster", "lgb.model.dt.tree")
  if (is.null(num_iteration)) num_iteration <- -1L
  dump <- model$dump_model(num_iteration)
  feature_names <- unlist(dump$feature_names)

  # accumulate one row (as a plain list) per node into `rows`, then build
  # the frame ONCE — per-node data.frame rbind is quadratic and makes a
  # 500-tree table take minutes
  rows <- vector("list", 0L)

  flatten_node <- function(node, tree_index, parent) {
    if (is.null(node$split_index)) {
      # leaf; a 1-leaf tree's root carries only leaf_value
      rows[[length(rows) + 1L]] <<- list(
        tree_index = tree_index,
        split_index = NA_integer_,
        split_feature = NA_character_,
        node_parent = NA_integer_,
        leaf_index = if (is.null(node$leaf_index)) 0L
                     else as.integer(node$leaf_index),
        leaf_parent = parent,
        split_gain = NA_real_,
        threshold = NA_real_,
        decision_type = NA_character_,
        internal_value = NA_real_,
        internal_count = NA_integer_,
        leaf_value = as.numeric(node$leaf_value),
        leaf_count = if (is.null(node$leaf_count)) NA_integer_
                     else as.integer(node$leaf_count))
      return(invisible(NULL))
    }
    idx <- as.integer(node$split_index)
    rows[[length(rows) + 1L]] <<- list(
      tree_index = tree_index,
      split_index = idx,
      split_feature = feature_names[as.integer(node$split_feature) + 1L],
      node_parent = parent,
      leaf_index = NA_integer_,
      leaf_parent = NA_integer_,
      split_gain = as.numeric(node$split_gain),
      threshold = as.numeric(node$threshold),
      decision_type = as.character(node$decision_type),
      internal_value = as.numeric(node$internal_value),
      internal_count = as.integer(node$internal_count),
      leaf_value = NA_real_,
      leaf_count = NA_integer_)
    flatten_node(node$left_child, tree_index, idx)
    flatten_node(node$right_child, tree_index, idx)
    invisible(NULL)
  }

  for (i in seq_along(dump$tree_info)) {
    flatten_node(dump$tree_info[[i]]$tree_structure, i - 1L, NA_integer_)
  }
  if (!length(rows)) {
    return(data.frame(tree_index = integer(0)))
  }
  cols <- names(rows[[1L]])
  out <- as.data.frame(
    stats::setNames(lapply(cols, function(cn)
      unlist(lapply(rows, `[[`, cn), use.names = FALSE)), cols),
    stringsAsFactors = FALSE)
  if (requireNamespace("data.table", quietly = TRUE)) {
    out <- data.table::as.data.table(out)
  }
  out
}
