# lgb.Booster: R6 wrapper of lightgbm_tpu.Booster.
#
# Reference surface: R-package/R/lgb.Booster.R:1-475 (update, rollback,
# eval, predict, save/load/dump, best_iter/record_evals) — here delegated
# to the Python Booster, whose semantics are already pinned against the
# reference by the Python test-suite.

Booster <- R6::R6Class(
  "lgb.Booster",
  public = list(
    py = NULL,
    best_iter = -1L,
    record_evals = list(),

    initialize = function(params = list(), train_set = NULL,
                          modelfile = NULL, model_str = NULL,
                          py_handle = NULL) {
      if (!is.null(py_handle)) {
        # wrap an existing Python Booster (used by lgb.train) without a
        # save/parse round-trip of the whole forest
        self$py <- py_handle
        return(invisible(self))
      }
      lgb <- lgb.get.module()
      if (!is.null(train_set)) {
        lgb.check.r6(train_set, "lgb.Dataset", "lgb.Booster")
        self$py <- lgb$Booster(params = params, train_set = train_set$py)
      } else if (!is.null(modelfile)) {
        self$py <- lgb$Booster(model_file = modelfile)
      } else if (!is.null(model_str)) {
        tmp <- tempfile(fileext = ".txt")
        writeLines(model_str, tmp)
        self$py <- lgb$Booster(model_file = tmp)
        unlink(tmp)
      } else {
        stop("lgb.Booster: need train_set, modelfile or model_str")
      }
      invisible(self)
    },

    add_valid = function(data, name) {
      lgb.check.r6(data, "lgb.Dataset", "add_valid")
      self$py$add_valid(data$py, name)
      invisible(self)
    },

    update = function(train_set = NULL, fobj = NULL) {
      if (!is.null(train_set)) {
        stop("update(train_set=...) is not supported; create a new booster")
      }
      if (is.null(fobj)) {
        self$py$update()
      } else {
        stop("custom fobj through R is not yet wired; use the Python API")
      }
      invisible(self)
    },

    rollback_one_iter = function() {
      self$py$rollback_one_iter()
      invisible(self)
    },

    current_iter = function() {
      self$py$current_iteration()
    },

    eval = function(data, name, feval = NULL) {
      lgb.check.r6(data, "lgb.Dataset", "eval")
      reticulate::py_to_r(self$py$eval(data$py, name))
    },

    eval_train = function(feval = NULL) {
      reticulate::py_to_r(self$py$eval_train())
    },

    eval_valid = function(feval = NULL) {
      reticulate::py_to_r(self$py$eval_valid())
    },

    save_model = function(filename, num_iteration = -1L) {
      self$py$save_model(filename, as.integer(num_iteration))
      invisible(self)
    },

    save_model_to_string = function(num_iteration = -1L) {
      self$py$model_to_string(as.integer(num_iteration))
    },

    dump_model = function(num_iteration = -1L) {
      reticulate::py_to_r(self$py$dump_model(as.integer(num_iteration)))
    },

    predict = function(data, num_iteration = NULL, rawscore = FALSE,
                       predleaf = FALSE, header = FALSE, reshape = TRUE) {
      if (is.null(num_iteration)) {
        num_iteration <- -1L
      }
      payload <- if (is.character(data)) data else lgb.as.matrix(data)
      out <- self$py$predict(
        payload, num_iteration = as.integer(num_iteration),
        raw_score = rawscore, pred_leaf = predleaf,
        data_has_header = header, is_reshape = reshape)
      reticulate::py_to_r(out)
    },

    num_class = function() {
      as.integer(reticulate::py_to_r(
        reticulate::py_get_attr(self$py, "_booster")$num_class))
    },

    feature_importance = function(importance_type = "split") {
      as.vector(reticulate::py_to_r(
        self$py$feature_importance(importance_type)))
    }
  )
)

#' Create a Booster (reference lgb.Booster.R)
lgb.Booster <- function(params = list(), train_set = NULL,
                        modelfile = NULL, model_str = NULL) {
  Booster$new(params, train_set, modelfile, model_str)
}

lgb.load <- function(filename = NULL, model_str = NULL) {
  Booster$new(modelfile = filename, model_str = model_str)
}

lgb.save <- function(booster, filename, num_iteration = -1L) {
  lgb.check.r6(booster, "lgb.Booster", "lgb.save")
  booster$save_model(filename, num_iteration)
}

lgb.dump <- function(booster, num_iteration = -1L) {
  lgb.check.r6(booster, "lgb.Booster", "lgb.dump")
  booster$dump_model(num_iteration)
}

lgb.importance <- function(model, percentage = TRUE) {
  # Reference table shape (R-package/R/lgb.importance.R): per-feature
  # Gain / Cover / Frequency aggregated over every split, sorted by Gain,
  # optionally normalized to proportions.
  lgb.check.r6(model, "lgb.Booster", "lgb.importance")
  td <- as.data.frame(lgb.model.dt.tree(model))
  nodes <- td[!is.na(td$split_index), , drop = FALSE]
  if (!nrow(nodes)) {
    return(data.frame(Feature = character(0), Gain = numeric(0),
                      Cover = numeric(0), Frequency = numeric(0)))
  }
  feats <- unique(nodes$split_feature)
  agg <- function(fun, col) vapply(feats, function(f)
    fun(nodes[[col]][nodes$split_feature == f]), 0.0)
  out <- data.frame(Feature = feats,
                    Gain = agg(sum, "split_gain"),
                    Cover = agg(sum, "internal_count"),
                    Frequency = vapply(feats, function(f)
                      sum(nodes$split_feature == f), 0L),
                    stringsAsFactors = FALSE)
  if (percentage) {
    for (col in c("Gain", "Cover", "Frequency")) {
      s <- sum(out[[col]])
      if (s > 0) out[[col]] <- out[[col]] / s
    }
  }
  out <- out[order(-out$Gain), , drop = FALSE]
  rownames(out) <- NULL
  if (requireNamespace("data.table", quietly = TRUE)) {
    out <- data.table::as.data.table(out)
  }
  out
}

lgb.get.eval.result <- function(booster, data_name, eval_name,
                                iters = NULL, is_err = FALSE) {
  rec <- booster$record_evals[[data_name]][[eval_name]]
  if (is.null(rec)) {
    stop(sprintf("no eval results for (%s, %s)", data_name, eval_name))
  }
  out <- unlist(rec$eval)
  if (!is.null(iters)) {
    out <- out[iters]
  }
  out
}

predict.lgb.Booster <- function(object, data, ...) {
  object$predict(data, ...)
}
