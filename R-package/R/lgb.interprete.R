# lgb.interprete: per-prediction feature contributions.
#
# Reference surface: R-package/R/lgb.interprete.R — for each selected row,
# follow its leaf path root->leaf in every tree and attribute each step's
# value change (child value - parent internal value) to the split feature;
# sum per feature, per class for multiclass.  The path is reconstructed
# from lgb.model.dt.tree plus predict(predleaf=TRUE).

lgb.interprete <- function(model, data, idxset, num_iteration = NULL) {
  lgb.check.r6(model, "lgb.Booster", "lgb.interprete")
  tree_dt <- lgb.model.dt.tree(model, num_iteration)
  tree_dt <- as.data.frame(tree_dt)
  num_class <- model$num_class()
  if (is.null(num_iteration)) num_iteration <- -1L

  rows <- data[idxset, , drop = FALSE]
  leaf_mat <- model$predict(rows, num_iteration = num_iteration,
                            predleaf = TRUE)
  leaf_mat <- matrix(as.integer(leaf_mat), nrow = nrow(rows))

  # parent/value/feature lookups per tree
  trees <- split(tree_dt, tree_dt$tree_index)

  contrib_one <- function(row_i) {
    acc <- new.env(parent = emptyenv())
    for (t_i in seq_along(trees)) {
      td <- trees[[t_i]]
      tree_index <- td$tree_index[1L]
      cls <- tree_index %% num_class
      leaf <- leaf_mat[row_i, tree_index + 1L]
      leaves <- td[!is.na(td$leaf_index), ]
      nodes <- td[!is.na(td$split_index), ]
      lrow <- leaves[leaves$leaf_index == leaf, ]
      if (!nrow(lrow)) next
      child_val <- lrow$leaf_value
      parent <- lrow$leaf_parent
      while (!is.na(parent)) {
        prow <- nodes[nodes$split_index == parent, ]
        if (!nrow(prow)) break
        key <- paste0(prow$split_feature, "\r", cls)
        delta <- child_val - prow$internal_value
        acc[[key]] <- (if (is.null(acc[[key]])) 0 else acc[[key]]) + delta
        child_val <- prow$internal_value
        parent <- prow$node_parent
      }
    }
    keys <- ls(acc)
    if (!length(keys)) {
      out <- data.frame(Feature = character(0))
      for (k in seq_len(num_class)) out[[paste0("Contribution",
          if (num_class > 1L) k - 1L else "")]] <- numeric(0)
      return(out)
    }
    split_keys <- strsplit(keys, "\r", fixed = TRUE)
    feats <- vapply(split_keys, `[[`, "", 1L)
    clss <- as.integer(vapply(split_keys, `[[`, "", 2L))
    vals <- vapply(keys, function(k) acc[[k]], 0.0)
    feat_u <- unique(feats)
    if (num_class == 1L) {
      out <- data.frame(Feature = feat_u,
                        Contribution = vapply(feat_u, function(f)
                          sum(vals[feats == f]), 0.0),
                        stringsAsFactors = FALSE)
      out <- out[order(-abs(out$Contribution)), ]
    } else {
      out <- data.frame(Feature = feat_u, stringsAsFactors = FALSE)
      for (k in 0:(num_class - 1L)) {
        out[[paste0("Class ", k)]] <- vapply(feat_u, function(f)
          sum(vals[feats == f & clss == k]), 0.0)
      }
      out <- out[order(-rowSums(abs(out[, -1L, drop = FALSE]))), ]
    }
    rownames(out) <- NULL
    if (requireNamespace("data.table", quietly = TRUE)) {
      out <- data.table::as.data.table(out)
    }
    out
  }

  lapply(seq_len(nrow(rows)), contrib_one)
}
