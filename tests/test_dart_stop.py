"""DART xgboost mode (dart.hpp:119-178), engine.train saturation stop,
and voting constraint integer-division semantics."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.models.dart import DART


def _small_ds(n=400, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] + 0.5 * X[:, 1] + rng.normal(scale=0.2, size=n) > 0)
    return X, y.astype(np.float64)


def _make_dart(xgboost_mode, n=400):
    X, y = _small_ds(n)
    cfg = Config({"objective": "binary", "num_leaves": 7, "max_bin": 32,
                  "min_data_in_leaf": 10, "learning_rate": 0.2,
                  "drop_rate": 0.5, "drop_seed": 4, "skip_drop": 0.0,
                  "xgboost_dart_mode": xgboost_mode, "metric": "none"})
    ds = BinnedDataset.from_matrix(X, y, max_bin=32, min_data_in_leaf=10)
    return DART(cfg, ds)


def test_dart_xgboost_shrinkage_rate():
    """xgboost mode: shrinkage = lr (no drops) or lr/(lr+k)
    (dart.hpp:119-127); normal mode: lr/(1+k)."""
    b = _make_dart(xgboost_mode=True)
    for _ in range(6):
        b.train_one_iter()
    lr = 0.2
    b._select_dropping_trees()
    k = len(b.drop_index)
    want = lr if k == 0 else lr / (lr + k)
    assert b.shrinkage_rate == pytest.approx(want)

    b2 = _make_dart(xgboost_mode=False)
    for _ in range(6):
        b2.train_one_iter()
    b2._select_dropping_trees()
    k2 = len(b2.drop_index)
    assert b2.shrinkage_rate == pytest.approx(lr / (1.0 + k2))


@pytest.mark.parametrize("xgboost_mode", [False, True])
def test_dart_scores_consistent_with_model(xgboost_mode):
    """After drop/normalize bookkeeping, the training score buffer must
    equal the sum of the (rescaled) model trees — the invariant the
    reference maintains via its 3-step Shrinkage dance."""
    b = _make_dart(xgboost_mode)
    X, _ = _small_ds()
    for _ in range(8):
        b.train_one_iter()
    # host_score crops the row-bucket pad (models/gbdt.py)
    score = b.train_data.host_score()[0]
    pred = b.predict_raw(X)[0]
    np.testing.assert_allclose(score, pred, rtol=1e-4, atol=1e-5)


def test_dart_modes_differ():
    a = _make_dart(False)
    b = _make_dart(True)
    for _ in range(8):
        a.train_one_iter()
        b.train_one_iter()
    sa = np.asarray(a.train_data.score)
    sb = np.asarray(b.train_data.score)
    assert not np.allclose(sa, sb)


def test_engine_train_stops_on_saturation():
    """train() must break out of the boosting loop once update() reports
    that no leaf can split (VERDICT weak #7): with min_data_in_leaf larger
    than the dataset no tree can ever grow."""
    X, y = _small_ds(n=100)
    ds = lgb.Dataset(X, label=y)
    calls = []

    def counter(env):
        calls.append(env.iteration)

    booster = lgb.train({"objective": "regression", "verbose": -1,
                         "min_gain_to_split": 1e12, "num_leaves": 7},
                        ds, num_boost_round=50, callbacks=[counter])
    assert len(calls) <= 2, f"loop ran {len(calls)} rounds after saturation"
    assert booster.current_iteration() == 0


def test_voting_constraint_floor_division():
    from lightgbm_tpu.parallel.comm import VotingParallelComm
    from lightgbm_tpu.ops.split import SplitParams
    comm = VotingParallelComm("data", 4, 8)
    sp = comm._local_sp(SplitParams(min_data_in_leaf=7,
                                    min_sum_hessian_in_leaf=6.0))
    assert sp.min_data_in_leaf == 1          # 7 // 4, not 1.75
    assert sp.min_sum_hessian_in_leaf == pytest.approx(1.5)


def test_rollback_with_pending_saturated_iteration():
    """rollback_one_iter must flush the pending (pipelined) iteration BEFORE
    its iter_ guard: a pending saturated iteration is popped by the flush,
    and rollback must then target the last REAL iteration (or no-op when
    none exists), not crash or double-pop."""
    from lightgbm_tpu.models.gbdt import GBDT
    X, y = _small_ds(n=100)
    cfg = Config({"objective": "regression", "num_leaves": 7,
                  "min_gain_to_split": 1e12, "metric": "none"})
    ds = BinnedDataset.from_matrix(X, y, max_bin=32, min_data_in_leaf=10)
    b = GBDT(cfg, ds)
    assert b.train_one_iter() is False          # saturated iter is pending
    b.rollback_one_iter()                       # must not raise
    assert b.iter_ == 0 and len(b.models) == 0

    # with one real iteration first: rollback pops THAT one exactly once
    cfg2 = Config({"objective": "regression", "num_leaves": 7,
                   "metric": "none"})
    b2 = GBDT(cfg2, ds)
    b2.train_one_iter()
    b2.config.min_gain_to_split = 1e12          # saturate future growth
    b2.reset_config(b2.config)
    b2.train_one_iter()                         # real iter flushed, new pend
    b2.train_one_iter()
    b2.rollback_one_iter()
    assert b2.iter_ == 0 and len(b2.models) == 0


def test_reset_config_flushes_before_num_leaves_change():
    """A pending iteration is packed under the OLD num_leaves; reset_config
    must flush it before swapping grow_params, else the packed vectors are
    unpacked at the wrong offsets (garbage trees)."""
    from lightgbm_tpu.models.gbdt import GBDT
    X, y = _small_ds(n=300)
    cfg = Config({"objective": "regression", "num_leaves": 15,
                  "metric": "none", "min_data_in_leaf": 10})
    ds = BinnedDataset.from_matrix(X, y, max_bin=32, min_data_in_leaf=10)
    b = GBDT(cfg, ds)
    b.train_one_iter()                          # pending, packed with L=15
    cfg2 = Config({"objective": "regression", "num_leaves": 5,
                   "metric": "none", "min_data_in_leaf": 10})
    b.reset_config(cfg2)                        # must flush with L=15
    b.train_one_iter()
    trees = b.models
    assert len(trees) == 2
    assert 1 < trees[0].num_leaves <= 15
    assert 1 < trees[1].num_leaves <= 5
    # leaf values of the first tree must be sane (not misaligned garbage)
    assert np.all(np.isfinite(trees[0].leaf_value))
    assert np.max(np.abs(trees[0].leaf_value)) < 100


def test_out_of_band_saturation_flush_is_delivered_not_destructive():
    """If reset_config/models-access flushes a pending saturated iteration,
    the NEXT train_one_iter must report the stop without discarding any
    newly grown trees or crashing."""
    from lightgbm_tpu.models.gbdt import GBDT
    X, y = _small_ds(n=100)
    cfg = Config({"objective": "regression", "num_leaves": 7,
                  "min_gain_to_split": 1e12, "metric": "none"})
    ds = BinnedDataset.from_matrix(X, y, max_bin=32, min_data_in_leaf=10)
    b = GBDT(cfg, ds)
    assert b.train_one_iter() is False        # saturated iteration pending
    _ = b.models                              # out-of-band flush detects it
    assert b.train_one_iter() is True         # signal delivered, no dispatch
    # a later explicit retry trains afresh (reference behavior)
    assert b.train_one_iter() is False
    assert b.iter_ >= 1
