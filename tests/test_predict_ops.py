"""ops/predict.py branch coverage.

``predict_binned_tree`` picks a per-row feature value two ways: a select
chain for F <= 64 (cheaper on TPU for narrow GBDT feature counts) and a
``take_along_axis`` gather for wide feature spaces.  The gather branch
had no coverage; these tests pin it to the select-chain branch on the
SAME forest (features above 64 unused, so padding the bin matrix wider
flips the branch without changing any routing) and to a host reference
walk.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.ops.predict import (predict_binned_forest,
                                      predict_binned_tree,
                                      predict_leaf_indices_forest)

pytestmark = pytest.mark.serve


def _toy_tree():
    """3-leaf tree: node0 splits feat 2 at bin 5 (left -> node1), node1
    splits feat 7 at bin 2.  Leaves: ~0, ~1, ~2."""
    sf = np.array([2, 7], np.int32)
    sb = np.array([5, 2], np.int32)
    ic = np.array([False, False])
    lc = np.array([1, ~0], np.int32)
    rc = np.array([~2, ~1], np.int32)
    lv = np.array([1.0, 2.0, 4.0], np.float32)
    return sf, sb, ic, lc, rc, lv


def _host_walk(sf, sb, lc, rc, lv, bins):
    out = np.zeros(bins.shape[1])
    for row in range(bins.shape[1]):
        node = 0
        while node >= 0:
            node = (lc[node] if bins[sf[node], row] <= sb[node]
                    else rc[node])
        out[row] = lv[~node]
    return out


def _random_bins(F, N, seed=0):
    return np.random.RandomState(seed).randint(0, 10, size=(F, N))


@pytest.mark.parametrize("F_wide", [65, 80, 128])
def test_gather_branch_matches_select_chain(F_wide):
    """Same forest, same rows: bins [10, N] takes the select chain,
    bins padded to [F_wide, N] takes the take_along_axis gather.  The
    outputs must be identical (the extra features are never split on)."""
    sf, sb, ic, lc, rc, lv = _toy_tree()
    bins10 = _random_bins(10, 257)
    wide = np.zeros((F_wide, 257), bins10.dtype)
    wide[:10] = bins10
    narrow_val, narrow_leaf = predict_binned_tree(
        jnp.asarray(sf), jnp.asarray(sb), jnp.asarray(ic),
        jnp.asarray(lc), jnp.asarray(rc), jnp.asarray(lv),
        jnp.asarray(bins10), max_steps=3)
    wide_val, wide_leaf = predict_binned_tree(
        jnp.asarray(sf), jnp.asarray(sb), jnp.asarray(ic),
        jnp.asarray(lc), jnp.asarray(rc), jnp.asarray(lv),
        jnp.asarray(wide), max_steps=3)
    assert np.array_equal(np.asarray(narrow_val), np.asarray(wide_val))
    assert np.array_equal(np.asarray(narrow_leaf), np.asarray(wide_leaf))
    np.testing.assert_allclose(np.asarray(wide_val),
                               _host_walk(sf, sb, lc, rc, lv, bins10))


def test_gather_branch_forest_and_leaf_indices():
    """Forest-level wrappers through the gather branch (F=70), against
    the host walk and the narrow branch."""
    sf, sb, ic, lc, rc, lv = _toy_tree()
    # two stacked trees with different thresholds
    sf2 = np.stack([sf, sf])
    sb2 = np.stack([sb, np.array([3, 7], np.int32)])
    ic2 = np.stack([ic, ic])
    lc2 = np.stack([lc, lc])
    rc2 = np.stack([rc, rc])
    lv2 = np.stack([lv, lv * 10])
    bins10 = _random_bins(10, 64, seed=3)
    wide = np.zeros((70, 64), bins10.dtype)
    wide[:10] = bins10
    want = (_host_walk(sf2[0], sb2[0], lc2[0], rc2[0], lv2[0], bins10)
            + _host_walk(sf2[1], sb2[1], lc2[1], rc2[1], lv2[1], bins10))
    for b in (bins10, wide):
        got = predict_binned_forest(
            jnp.asarray(sf2), jnp.asarray(sb2), jnp.asarray(ic2),
            jnp.asarray(lc2), jnp.asarray(rc2), jnp.asarray(lv2),
            jnp.asarray(b), max_steps=3)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)
    leaves_narrow = predict_leaf_indices_forest(
        jnp.asarray(sf2), jnp.asarray(sb2), jnp.asarray(ic2),
        jnp.asarray(lc2), jnp.asarray(rc2), jnp.asarray(lv2),
        jnp.asarray(bins10), max_steps=3)
    leaves_wide = predict_leaf_indices_forest(
        jnp.asarray(sf2), jnp.asarray(sb2), jnp.asarray(ic2),
        jnp.asarray(lc2), jnp.asarray(rc2), jnp.asarray(lv2),
        jnp.asarray(wide), max_steps=3)
    assert np.array_equal(np.asarray(leaves_narrow),
                          np.asarray(leaves_wide))


def test_gather_branch_categorical_nodes():
    """Categorical routing (bin == threshold goes left) through the wide
    gather branch."""
    sf = np.array([66], np.int32)              # split on a high feature
    sb = np.array([4], np.int32)
    ic = np.array([True])
    lc = np.array([~0], np.int32)
    rc = np.array([~1], np.int32)
    lv = np.array([10.0, 20.0], np.float32)
    bins = np.zeros((70, 9), np.int32)
    bins[66] = np.array([4, 0, 4, 7, -1, 4, 2, 4, 3])
    val, leaf = predict_binned_tree(
        jnp.asarray(sf), jnp.asarray(sb), jnp.asarray(ic),
        jnp.asarray(lc), jnp.asarray(rc), jnp.asarray(lv),
        jnp.asarray(bins), max_steps=2)
    want = np.where(bins[66] == 4, 10.0, 20.0)
    np.testing.assert_allclose(np.asarray(val), want)
