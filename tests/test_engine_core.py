"""End-to-end training quality gates, modeled on the reference's
tests/python_package_test/test_engine.py thresholds, plus deterministic
parity gates against golden numbers measured from the compiled reference CLI
on the bundled example datasets (same conf, sampling disabled)."""

import os

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.io.parser import parse_file
from lightgbm_tpu.models import GBDT, create_boosting

_EXAMPLES = "/root/reference/examples"
_HAS_EXAMPLES = os.path.isdir(_EXAMPLES)


def _make_synthetic_binary(n=3000, f=10, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    logit = X[:, 0] * 1.5 - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
    y = (logit + rng.normal(scale=0.5, size=n) > 0).astype(np.float64)
    return X, y


def _train(cfg_dict, X, y, Xv=None, yv=None, side=None):
    cfg = Config(cfg_dict)
    ds = BinnedDataset.from_matrix(X, y, max_bin=cfg.max_bin,
                                   min_data_in_leaf=cfg.min_data_in_leaf)
    if side:
        ds.metadata.load_side_files(side)
    booster = create_boosting(cfg, ds)
    if Xv is not None:
        vs = ds.create_valid(Xv, yv)
        if side:
            pass
        booster.add_valid_dataset(vs)
    booster.train(cfg.num_iterations)
    return booster


def test_synthetic_binary_quality():
    X, y = _make_synthetic_binary()
    Xv, yv = _make_synthetic_binary(seed=8)
    b = _train({"objective": "binary", "metric": "binary_logloss,auc",
                "num_leaves": 31, "num_iterations": 50, "min_data_in_leaf": 20,
                "min_sum_hessian_in_leaf": 1.0, "max_bin": 63}, X, y, Xv, yv)
    m = b.eval_metrics()["valid_1"]
    assert m["auc"] > 0.93
    assert m["binary_logloss"] < 0.35


def test_synthetic_regression_quality():
    rng = np.random.RandomState(0)
    X = rng.normal(size=(3000, 8))
    y = X[:, 0] * 2 + np.sin(X[:, 1] * 3) + rng.normal(scale=0.1, size=3000)
    Xv = rng.normal(size=(500, 8))
    yv = Xv[:, 0] * 2 + np.sin(Xv[:, 1] * 3) + rng.normal(scale=0.1, size=500)
    b = _train({"objective": "regression", "metric": "l2", "num_leaves": 63,
                "num_iterations": 60, "min_data_in_leaf": 10,
                "min_sum_hessian_in_leaf": 0.1, "max_bin": 127,
                "learning_rate": 0.1}, X, y, Xv, yv)
    # reference "l2" metric is RMSE
    assert b.eval_metrics()["valid_1"]["l2"] < 0.35


def test_early_stopping_and_best_iteration():
    X, y = _make_synthetic_binary(n=1200)
    Xv, yv = _make_synthetic_binary(n=600, seed=9)
    cfg = Config({"objective": "binary", "metric": "binary_logloss",
                  "num_leaves": 63, "num_iterations": 200,
                  "min_data_in_leaf": 5, "min_sum_hessian_in_leaf": 0.1,
                  "max_bin": 63, "learning_rate": 0.3,
                  "early_stopping_round": 5})
    ds = BinnedDataset.from_matrix(X, y, max_bin=63, min_data_in_leaf=5)
    b = GBDT(cfg, ds)
    b.add_valid_dataset(ds.create_valid(Xv, yv))
    b.train(200)
    # stopped early, with a recorded best iteration
    assert b.iter_ < 200
    assert 0 < b.best_iteration <= b.iter_


def test_model_save_load_predict_roundtrip(tmp_path):
    X, y = _make_synthetic_binary(n=1500)
    b = _train({"objective": "binary", "num_leaves": 15, "num_iterations": 10,
                "min_data_in_leaf": 10, "min_sum_hessian_in_leaf": 1.0,
                "max_bin": 63}, X, y)
    pred = b.predict(X)
    text = b.save_model_to_string()
    b2 = GBDT(Config({"objective": "binary"}), None)
    b2.load_model_from_string(text)
    pred2 = b2.predict(X)
    np.testing.assert_allclose(pred, pred2, rtol=1e-9)
    # file round trip
    path = str(tmp_path / "model.txt")
    b.save_model_to_file(path)
    b3 = GBDT(Config({}), None)
    b3.load_model_from_string(open(path).read())
    np.testing.assert_allclose(b.predict_raw(X), b3.predict_raw(X), rtol=1e-9)


def test_bagging_and_feature_fraction_still_learn():
    X, y = _make_synthetic_binary()
    b = _train({"objective": "binary", "metric": "auc", "num_leaves": 31,
                "num_iterations": 40, "min_data_in_leaf": 20,
                "min_sum_hessian_in_leaf": 1.0, "max_bin": 63,
                "bagging_fraction": 0.7, "bagging_freq": 2,
                "feature_fraction": 0.7, "is_training_metric": True}, X, y)
    assert b.eval_metrics()["training"]["auc"] > 0.95


def test_dart_goss_learn():
    X, y = _make_synthetic_binary(n=2000)
    for bt in ("dart", "goss"):
        b = _train({"objective": "binary", "metric": "auc",
                    "boosting_type": bt, "num_leaves": 15,
                    "num_iterations": 30, "min_data_in_leaf": 20,
                    "min_sum_hessian_in_leaf": 1.0, "max_bin": 63,
                    "learning_rate": 0.25, "is_training_metric": True}, X, y)
        assert b.eval_metrics()["training"]["auc"] > 0.9, bt


def test_goss_sampling_actually_runs():
    """Regression (round-3 review): the fused train step must not bypass
    GOSS._gradients — after the warmup period the row mask must be a real
    top-gradient subsample (some zero weights, amplified small-gradient
    rows), not all ones."""
    import numpy as np
    X, y = _make_synthetic_binary(n=2000)
    # GOSS warmup lasts 1/learning_rate rounds: lr=0.5 -> sampling from
    # round 2 on
    b = _train({"objective": "binary", "boosting_type": "goss",
                "top_rate": 0.2, "other_rate": 0.1, "num_leaves": 7,
                "num_iterations": 8, "min_data_in_leaf": 20,
                "max_bin": 63, "learning_rate": 0.5}, X, y)
    w = np.asarray(b._row_weight)
    kept = np.count_nonzero(w)
    assert kept < len(w), "GOSS never sampled: row weights are all ones"
    # kept fraction ~ top_rate + other_rate (amplification rides the
    # gradients, so the mask itself is 0/1)
    assert kept <= int(0.45 * len(w))
    assert kept >= int(0.15 * len(w))


def test_multiclass_quality():
    rng = np.random.RandomState(3)
    n = 3000
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int) + \
        (X[:, 2] > -0.5).astype(int)  # 4 classes 0..3
    b = _train({"objective": "multiclass", "num_class": 4,
                "metric": "multi_logloss,multi_error", "num_leaves": 31,
                "num_iterations": 30, "min_data_in_leaf": 10,
                "min_sum_hessian_in_leaf": 0.1, "max_bin": 63,
                "is_training_metric": True}, X, y)
    m = b.eval_metrics()["training"]
    assert m["multi_error"] < 0.05
    prob = b.predict(X)
    assert prob.shape == (4, n)
    np.testing.assert_allclose(prob.sum(axis=0), 1.0, rtol=1e-5)


def test_rollback_one_iter():
    X, y = _make_synthetic_binary(n=1000)
    b = _train({"objective": "binary", "num_leaves": 15, "num_iterations": 5,
                "min_data_in_leaf": 10, "min_sum_hessian_in_leaf": 1.0,
                "max_bin": 63, "is_training_metric": True,
                "metric": "binary_logloss"}, X, y)
    before = b.eval_metrics()["training"]["binary_logloss"]
    score_before = np.asarray(b.train_data.score).copy()
    b.train_one_iter()
    b.rollback_one_iter()
    np.testing.assert_allclose(np.asarray(b.train_data.score), score_before,
                               atol=1e-6)
    assert b.num_trees() == 5


@pytest.mark.skipif(not _HAS_EXAMPLES, reason="reference examples not present")
def test_reference_binary_parity_deterministic():
    """Golden-number gate: deterministic run (no sampling) on the reference's
    binary example must match the compiled reference CLI's printed metrics
    (measured in this environment) to 4 decimal places at iteration 30:
      training auc 0.933725, logloss 0.415342;
      valid auc 0.818853, logloss 0.525583."""
    y, X, _ = parse_file(f"{_EXAMPLES}/binary_classification/binary.train")
    yt, Xt, _ = parse_file(f"{_EXAMPLES}/binary_classification/binary.test")
    cfg = Config({"objective": "binary", "metric": ["auc", "binary_logloss"],
                  "num_leaves": 63, "num_iterations": 30, "max_bin": 255,
                  "min_data_in_leaf": 50, "min_sum_hessian_in_leaf": 5.0,
                  "learning_rate": 0.1, "is_training_metric": True,
                  "feature_fraction": 1.0, "bagging_freq": 0})
    ds = BinnedDataset.from_matrix(X, y, max_bin=255, min_data_in_leaf=50)
    ds.metadata.load_side_files(f"{_EXAMPLES}/binary_classification/binary.train")
    vs = ds.create_valid(Xt, yt)
    vs.metadata.load_side_files(f"{_EXAMPLES}/binary_classification/binary.test")
    b = GBDT(cfg, ds)
    b.add_valid_dataset(vs)
    b.train(30)
    m = b.eval_metrics()
    assert abs(m["training"]["auc"] - 0.933725) < 1e-4
    assert abs(m["training"]["binary_logloss"] - 0.415342) < 1e-4
    assert abs(m["valid_1"]["auc"] - 0.818853) < 1e-4
    assert abs(m["valid_1"]["binary_logloss"] - 0.525583) < 1e-4


@pytest.mark.skipif(not _HAS_EXAMPLES, reason="reference examples not present")
def test_reference_regression_parity_deterministic():
    """Reference CLI (sampling disabled) golden numbers for the regression
    example: sqrt-L2 at iter 100 (measured in this environment)."""
    y, X, _ = parse_file(f"{_EXAMPLES}/regression/regression.train")
    cfg = Config({"objective": "regression", "metric": "l2", "num_leaves": 31,
                  "num_iterations": 30, "max_bin": 255,
                  "min_data_in_leaf": 100, "min_sum_hessian_in_leaf": 5.0,
                  "learning_rate": 0.05, "is_training_metric": True,
                  "feature_fraction": 1.0, "bagging_freq": 0})
    ds = BinnedDataset.from_matrix(X, y, max_bin=255, min_data_in_leaf=100)
    b = GBDT(cfg, ds)
    b.train(30)
    # golden: measured from .refbuild/lightgbm with identical flags
    golden = _reference_cli_regression_golden()
    if golden is not None:
        assert abs(b.eval_metrics()["training"]["l2"] - golden) < 2e-4
    else:
        assert b.eval_metrics()["training"]["l2"] < 0.55


def _reference_cli_regression_golden():
    """Runs the compiled reference CLI if present to produce the golden
    number; returns None when unavailable."""
    import subprocess, tempfile, re
    exe = "/root/repo/.refbuild/lightgbm"
    if not os.path.exists(exe):
        return None
    with tempfile.TemporaryDirectory() as td:
        out = subprocess.run(
            [exe, "task=train", "objective=regression", "metric=l2",
             "num_leaves=31", "num_trees=30", "max_bin=255",
             "min_data_in_leaf=100", "min_sum_hessian_in_leaf=5.0",
             "learning_rate=0.05", "is_training_metric=true",
             "feature_fraction=1.0", "bagging_freq=0",
             f"data={_EXAMPLES}/regression/regression.train",
             f"output_model={td}/m.txt"],
            capture_output=True, text=True, cwd=td)
        matches = re.findall(r"Iteration:30, training l2 : ([0-9.]+)",
                             out.stdout + out.stderr)
        return float(matches[-1]) if matches else None


def test_goss_keeps_exactly_top_cnt_on_ties():
    """ArgMaxAtK semantics (goss.hpp:79-124): with massively tied |g*h|
    the kept top set must still be exactly top_rate*N rows (round-2
    VERDICT weak #8: a >= threshold rule kept every tie)."""
    import jax.numpy as jnp
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.models.goss import GOSS

    rng = np.random.RandomState(0)
    n = 1000
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] > 0).astype(np.float64)
    cfg = Config({"objective": "binary", "boosting": "goss",
                  "top_rate": 0.2, "other_rate": 0.0,
                  "num_leaves": 4, "min_data_in_leaf": 10})
    ds = BinnedDataset.from_matrix(X, y, max_bin=16, min_data_in_leaf=10)
    g = GOSS(cfg, ds)
    # all gradients identical in magnitude -> every row ties
    grad = jnp.ones((1, n), jnp.float32)
    hess = jnp.ones((1, n), jnp.float32)
    mask, _, _ = g._sample(grad, hess)
    assert int(np.count_nonzero(np.asarray(mask))) == int(0.2 * n)


def test_goss_samples_with_custom_fobj():
    """GOSS sampling is objective-agnostic (reference Bagging step runs
    for custom objectives too): a custom fobj must still trigger the
    draw via _transform_host_gradients."""
    import lightgbm_tpu as lgb

    X, y = _make_synthetic_binary(n=1500)

    def fobj(preds, ds_):
        p = 1.0 / (1.0 + np.exp(-preds))
        return p - ds_.get_label(), p * (1 - p)

    bst = lgb.train({"objective": "none", "boosting": "goss",
                     "top_rate": 0.2, "other_rate": 0.1, "num_leaves": 7,
                     "learning_rate": 0.5, "verbose": -1,
                     "min_data_in_leaf": 20},
                    lgb.Dataset(X, label=y), num_boost_round=8, fobj=fobj)
    w = np.asarray(bst._booster._row_weight)
    assert np.count_nonzero(w) < len(w), \
        "GOSS never sampled under a custom fobj"
