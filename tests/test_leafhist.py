"""Leaf-proportional integer histogram engine (ops/leafhist.py):
quantization round-trip, scatter/pallas parity, compaction, and the
exact-subtraction property that replaces the reference's f64 accumulators
(bin.h:25-27)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops import leafhist as lh


def _data(n=5000, f=6, b=64, seed=0):
    rng = np.random.RandomState(seed)
    bins = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    g = rng.normal(size=n).astype(np.float32) * 3
    h = rng.uniform(0.05, 0.3, size=n).astype(np.float32)
    w = (rng.uniform(size=n) < 0.8).astype(np.float32)
    return bins, g, h, w


def _ref_hist(bins, vals, b):
    """f64 numpy reference histogram [F, B, 3]."""
    f = bins.shape[1]
    out = np.zeros((f, b, 3), np.float64)
    for fi in range(f):
        for v in range(3):
            out[fi, :, v] = np.bincount(
                bins[:, fi].astype(np.int64),
                weights=vals[v].astype(np.float64), minlength=b)[:b]
    return out


def test_quantize_roundtrip():
    _, g, h, w = _data()
    scales = lh.compute_scales(jnp.asarray(g), jnp.asarray(h), jnp.asarray(w))
    digits = np.asarray(lh.quantize_digits(
        jnp.asarray(g), jnp.asarray(h), jnp.asarray(w), scales))
    assert digits.shape == (g.size, 9) and digits.dtype == np.int8
    sc = np.asarray(scales)
    for v, x in enumerate([g, h, w]):
        rec = (digits[:, 3 * v].astype(np.int64) * 65536
               + digits[:, 3 * v + 1].astype(np.int64) * 256
               + digits[:, 3 * v + 2]).astype(np.float64)
        rec = rec * sc[v] / (1 << lh.QBITS)
        np.testing.assert_allclose(rec, x, atol=sc[v] * 2.0**-lh.QBITS)


def test_digit_histogram_matches_f64_reference():
    b = 64
    bins, g, h, w = _data(b=b)
    scales = lh.compute_scales(jnp.asarray(g), jnp.asarray(h), jnp.asarray(w))
    digits = lh.quantize_digits(jnp.asarray(g), jnp.asarray(h),
                                jnp.asarray(w), scales)
    sums = lh.digit_histogram(jnp.asarray(bins), digits, b)
    hist = np.asarray(lh.combine_digit_sums(sums, scales))   # [F, B, 3]
    hist = hist.transpose(0, 2, 1)                           # [F, 3, B]
    ref = _ref_hist(bins, [g, h, w], b).transpose(0, 2, 1)
    np.testing.assert_allclose(hist, ref, atol=2e-4 * np.abs(ref).max())


def test_pallas_interpret_matches_scatter():
    b = 128
    bins, g, h, w = _data(n=4096, b=b)
    scales = lh.compute_scales(jnp.asarray(g), jnp.asarray(h), jnp.asarray(w))
    digits = lh.quantize_digits(jnp.asarray(g), jnp.asarray(h),
                                jnp.asarray(w), scales)
    via_scatter = np.asarray(
        lh.digit_histogram_scatter(jnp.asarray(bins), digits, b))
    via_pallas = np.asarray(lh.digit_histogram_pallas(
        jnp.asarray(bins), digits, b, n_blk=1024, interpret=True))
    # both are exact integer sums -> bit-identical
    np.testing.assert_array_equal(via_scatter, via_pallas)


def test_compact_rows():
    rng = np.random.RandomState(3)
    mask = jnp.asarray(rng.uniform(size=1000) < 0.3)
    idx, valid = lh.compact_rows(mask, 512)
    want = np.nonzero(np.asarray(mask))[0]
    got = np.asarray(idx)[np.asarray(valid)]
    np.testing.assert_array_equal(np.sort(got), want)


def test_leaf_histogram_sizes_and_subtraction_exactness():
    """Parent digit sums == left + right digit sums EXACTLY (int32), the
    property the reference needs f64 for."""
    b = 32
    n = 20000
    bins, g, h, w = _data(n=n, b=b, seed=7)
    leaf = (np.random.RandomState(1).uniform(size=n) < 0.23)
    scales = lh.compute_scales(jnp.asarray(g), jnp.asarray(h), jnp.asarray(w))
    digits = lh.quantize_digits(jnp.asarray(g), jnp.asarray(h),
                                jnp.asarray(w), scales)
    classes = lh.size_classes(n, min_size=1024)
    parent = lh.digit_histogram(jnp.asarray(bins), digits, b)
    small = lh.leaf_histogram(jnp.asarray(bins), digits, jnp.asarray(leaf),
                              jnp.asarray(leaf.sum(), jnp.int32), b, classes)
    large = lh.leaf_histogram(jnp.asarray(bins), digits, jnp.asarray(~leaf),
                              jnp.asarray((~leaf).sum(), jnp.int32), b,
                              classes)
    np.testing.assert_array_equal(np.asarray(parent),
                                  np.asarray(small) + np.asarray(large))
    # derived sibling == directly built sibling, exactly
    np.testing.assert_array_equal(np.asarray(parent) - np.asarray(small),
                                  np.asarray(large))


def test_size_classes():
    assert lh.size_classes(1_000_000) == (8192, 16384, 32768, 65536,
                                          131072, 262144, 524288)
    assert lh.size_classes(10000, min_size=1024) == (1024, 2048, 4096, 8192)
    assert lh.size_classes(100, min_size=8192) == (64,)
