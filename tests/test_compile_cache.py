"""Warmup-tax fixes: persistent compile cache setup, shared row buckets,
process-wide training programs, and score-buffer donation.

The tier-1 acceptance for round 7 (ISSUE 7): training the same config
twice in one process — and once more after a snapshot-resume — must show
ZERO new ``train_step``/``grow_tree`` XLA compiles in the compile ledger
on the repeat run, and the donated score buffer must not be
double-allocated round to round.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from lightgbm_tpu.utils import compile_cache  # noqa: E402
from lightgbm_tpu.obs import compile_ledger  # noqa: E402

# programs whose re-compilation on a repeat run would mean the warmup
# tax is back (growth programs inline into train_step on the fused path
# but are listed for the per-stage paths too)
TRAIN_PROGRAMS = {"train_step", "train_gradients", "grow_tree",
                  "grow_tree_ordered", "pack_words", "pack_tree",
                  "bag_mask", "finite_guard", "score_update"}


def _train_events():
    return [e for e in compile_ledger.events()
            if e["program"] in TRAIN_PROGRAMS]


def _make_binary(n=1237, f=7, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] + rng.normal(scale=0.5, size=n) > 0)
    return X, y.astype(np.float64)


def _booster(X, y, **extra):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.models.gbdt import GBDT
    params = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 20,
              "max_bin": 63, "num_iterations": 4}
    params.update(extra)
    ds = BinnedDataset.from_matrix(X, y, max_bin=63, min_data_in_leaf=20)
    return GBDT(Config(params), ds)


# ---------------------------------------------------------------------------
# bucket_rows: the shared shape ladder


def test_bucket_rows_basics():
    assert compile_cache.bucket_rows(0) == 0
    assert compile_cache.bucket_rows(1) == 1
    for n in (2, 31, 32, 33, 1000, 987, 65_537, 1_000_000):
        b = compile_cache.bucket_rows(n)
        assert b >= n
        # overhead bounded by 2^(1-ROW_BUCKET_BITS) (worst just past a
        # power of two, where the step doubles)
        assert b - n < max(n / (1 << (compile_cache.ROW_BUCKET_BITS - 1))
                           + 1, 2)
        # idempotent: a bucket is its own bucket
        assert compile_cache.bucket_rows(b) == b


def test_bucket_rows_collapses_nearby_sizes():
    """The whole point: many nearby row counts -> few shapes."""
    buckets = {compile_cache.bucket_rows(n)
               for n in range(1_000_000, 1_015_000)}
    assert len(buckets) <= 2


# ---------------------------------------------------------------------------
# setup(): one helper for every entry point


def test_resolve_dir_precedence(monkeypatch):
    monkeypatch.delenv(compile_cache.ENV_DIR, raising=False)
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    assert compile_cache.resolve_dir() == compile_cache.DEFAULT_CACHE_DIR
    assert compile_cache.resolve_dir("/x") == "/x"
    assert compile_cache.resolve_dir("off") is None
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/jaxdir")
    assert compile_cache.resolve_dir() == "/jaxdir"
    assert compile_cache.resolve_dir("/x") == "/x"
    monkeypatch.setenv(compile_cache.ENV_DIR, "/envdir")
    assert compile_cache.resolve_dir("/x") == "/envdir"
    monkeypatch.setenv(compile_cache.ENV_DIR, "none")
    assert compile_cache.resolve_dir("/x") is None


def test_setup_applies_and_disables(tmp_path, monkeypatch):
    monkeypatch.delenv(compile_cache.ENV_DIR, raising=False)
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    d = str(tmp_path / "cache")
    assert compile_cache.setup(d) == d
    assert compile_cache.configured_dir() == d
    assert jax.config.jax_compilation_cache_dir == d
    assert compile_cache.setup("off") is None
    assert compile_cache.configured_dir() is None


# ---------------------------------------------------------------------------
# zero recompiles on repeat runs (the tier-1 acceptance)


def test_second_training_run_zero_train_compiles():
    X, y = _make_binary()
    b1 = _booster(X, y)
    for _ in range(4):
        b1.train_one_iter()
    m1 = b1.eval_metrics()
    before = len(_train_events())
    assert before > 0 or len(compile_ledger.events()) >= 0  # ledger alive

    # fresh dataset object, fresh booster, same config: every training
    # program must come from the shared in-process registry
    b2 = _booster(X, y)
    for _ in range(4):
        b2.train_one_iter()
    new = _train_events()[before:]
    assert new == [], f"repeat run recompiled: {new}"
    assert b2.eval_metrics() == m1


def test_training_after_snapshot_resume_zero_train_compiles(tmp_path):
    import lightgbm_tpu as lgb

    X, y = _make_binary(n=1151, seed=3)
    params = {"objective": "binary", "num_leaves": 7,
              "min_data_in_leaf": 20, "max_bin": 63, "verbose": -1,
              "snapshot_dir": str(tmp_path), "snapshot_freq": 2}
    bst = lgb.train(dict(params), lgb.Dataset(X, label=y),
                    num_boost_round=4)
    assert bst.current_iteration() == 4
    before = len(_train_events())

    # same command again: auto-resumes from the newest snapshot and
    # trains the remaining rounds with ZERO new training-program compiles
    bst2 = lgb.train(dict(params), lgb.Dataset(X, label=y),
                     num_boost_round=6)
    assert bst2.current_iteration() == 6
    new = _train_events()[before:]
    assert new == [], f"resumed run recompiled: {new}"


# ---------------------------------------------------------------------------
# donation: the round-to-round score buffer is updated in place


def test_donation_gated_to_accelerators(monkeypatch):
    """XLA:CPU's input-output aliasing corrupts donated buffers on this
    jax build (intermittent segfaults in later host reads), so donation
    must be OFF on the cpu backend by default, env-overridable, and ON
    for accelerator backends."""
    from lightgbm_tpu.models import gbdt as gbdt_mod

    monkeypatch.delenv("LIGHTGBM_TPU_DONATION", raising=False)
    assert jax.default_backend() == "cpu"
    assert not gbdt_mod._donation_enabled()
    monkeypatch.setenv("LIGHTGBM_TPU_DONATION", "1")
    assert gbdt_mod._donation_enabled()
    monkeypatch.setenv("LIGHTGBM_TPU_DONATION", "0")
    assert not gbdt_mod._donation_enabled()


def test_score_buffer_not_donated_on_cpu():
    """The gate in action: on the cpu backend the previous score buffer
    must survive an iteration (donating it is what corrupted memory)."""
    X, y = _make_binary(n=911, seed=1)
    b = _booster(X, y)
    b.train_one_iter()
    s0 = b.train_data.score
    b.train_one_iter()
    assert not s0.is_deleted()


def test_shared_step_registered_without_donation_under_guard():
    """nan_policy keeps a pre-iteration reference for rollback, so the
    guarded step must be registered donate=False regardless of backend;
    the guarded path still trains finite scores."""
    from lightgbm_tpu.models.gbdt import _SHARED_JITS

    X, y = _make_binary(n=911, seed=2)
    b = _booster(X, y, nan_policy="skip_tree")
    b.train_one_iter()
    s0 = b.train_data.score
    b.train_one_iter()
    assert not s0.is_deleted()
    # key layout: ("train_step", obj_key, num_class, guard, kind,
    # params, donate) — every guarded registration must be donate=False
    keys = [k for k in _SHARED_JITS if k[0] == "train_step"]
    assert any(k[3] for k in keys), "no guarded train_step registered"
    assert all(not k[-1] for k in keys if k[3])
    assert np.isfinite(b.train_data.host_score()).all()


def test_peak_live_bytes_flat_across_rounds():
    """memwatch bound: with donation, continuing to train must not grow
    the live-array watermark by more than one score buffer's worth of
    slack — a round-to-round double-allocation leak would."""
    from lightgbm_tpu.obs import memwatch

    X, y = _make_binary(n=1499, seed=4)
    b = _booster(X, y)
    for _ in range(3):
        b.train_one_iter()
    jax.block_until_ready(b.train_data.score)
    memwatch.reset_peak()
    base = memwatch.sample("test")["peak_live_bytes"]
    for _ in range(8):
        b.train_one_iter()
    jax.block_until_ready(b.train_data.score)
    peak = memwatch.sample("test")["peak_live_bytes"]
    score_bytes = int(np.asarray(b.train_data.score).nbytes)
    # the pipelined pending iteration legitimately holds one packed tree
    # + deltas; two score buffers of slack is far below the leak regime
    assert peak - base <= 2 * score_bytes + (1 << 20), \
        f"live watermark grew {peak - base} bytes over 8 rounds"


# ---------------------------------------------------------------------------
# row buckets: padded state invariants


def test_row_bucket_padding_preserves_model_and_crops_reads():
    X, y = _make_binary(n=987, seed=5)
    b_pad = _booster(X, y)
    b_off = _booster(X, y, row_buckets=False)
    assert b_pad._padded_rows == compile_cache.bucket_rows(987)
    assert b_off._padded_rows == 987
    for _ in range(3):
        b_pad.train_one_iter()
        b_off.train_one_iter()
    # identical split structure (exact int histogram sums); leaf values
    # may wiggle in the last float bit (reduction order vs shape)
    for t_pad, t_off in zip(b_pad.models, b_off.models):
        assert t_pad.num_leaves == t_off.num_leaves
        np.testing.assert_array_equal(t_pad.split_feature,
                                      t_off.split_feature)
        np.testing.assert_allclose(t_pad.leaf_value, t_off.leaf_value,
                                   rtol=1e-5, atol=1e-7)
    # host reads crop the pad
    assert b_pad.train_data.host_score().shape == (1, 987)
    assert np.asarray(b_pad.train_data.score).shape[1] == \
        compile_cache.bucket_rows(987)


def test_legacy_objective_subclass_still_trains():
    """Back-compat: a custom objective written against the pre-round-7
    contract (override gradients() only) must keep training — routed
    outside the shared registry (id-keyed) with row bucketing off, so
    its closure-captured arrays still match the score shapes."""
    import jax.numpy as jnp
    from lightgbm_tpu.objective import ObjectiveFunction

    class LegacySquares(ObjectiveFunction):
        name = "legacy_l2"

        def gradients(self, score):
            g = score[0] - self.label
            return g[None], jnp.ones_like(g)[None]

    X, y = _make_binary(n=640, seed=6)
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.models.gbdt import GBDT

    cfg = Config({"objective": "regression", "num_leaves": 7,
                  "min_data_in_leaf": 20, "max_bin": 63, "metric": "l2"})
    ds = BinnedDataset.from_matrix(X, y, max_bin=63, min_data_in_leaf=20)
    obj = LegacySquares()
    assert obj.uses_legacy_gradients()
    b = GBDT(cfg, ds, objective=obj)
    assert b._padded_rows == b.num_data  # bucketing opts out
    traj = []
    for _ in range(3):
        b.train_one_iter()
        traj.append(b.eval_metrics()["training"]["l2"])
    assert len(b.models) == 3
    assert np.isfinite(traj).all()
    assert traj[2] < traj[1] < traj[0], f"l2 not improving: {traj}"


def test_program_holder_drops_dataset_arrays():
    """The shared registry retains scalar-only holders: the per-dataset
    device arrays must NOT be reachable from a holder (registry pinning
    a dead dataset's HBM was the round-7 review finding)."""
    X, y = _make_binary(n=512, seed=7)
    b = _booster(X, y)
    holder = b.objective.program_holder()
    assert not hasattr(holder, "label")
    assert not hasattr(holder, "weights")
    # and the holder still traces: its gradients_with reads arrays from
    # the argument pytree only
    arrs = b.objective.gradient_arrays(b._padded_rows)
    g, h = holder.gradients_with(arrs, b.train_data.score)
    assert g.shape == b.train_data.score.shape


def test_bagging_never_draws_pad_rows():
    from lightgbm_tpu.models.gbdt import _device_bag_mask

    key = jax.random.PRNGKey(0)
    n_real, n_pad = 1000, 1024
    mask = np.asarray(_device_bag_mask(key, n_pad, 700, n_real))
    assert mask.shape == (n_pad,)
    assert int(mask.sum()) == 700
    assert mask[n_real:].sum() == 0
