"""R-binding contract: the build image has no R runtime, so the R package
(R-package/) cannot be executed here.  This test pins its contract with
the Python core instead — every Python attribute the R code calls must
exist with a compatible signature, so R-side breakage can only come from
the R files themselves, which are thin R6 delegations."""

import inspect
import os
import re

import lightgbm_tpu as lgb
from lightgbm_tpu.basic import Booster, Dataset

R_DIR = os.path.join(os.path.dirname(__file__), "..", "R-package", "R")


def test_r_package_files_present():
    files = os.listdir(R_DIR)
    for needed in ("lgb.Dataset.R", "lgb.Booster.R", "lgb.train.R",
                   "utils.R"):
        assert needed in files
    desc = open(os.path.join(R_DIR, "..", "DESCRIPTION")).read()
    assert "reticulate" in desc


def test_booster_surface_for_r():
    for method in ("add_valid", "update", "rollback_one_iter",
                   "current_iteration", "eval", "eval_train", "eval_valid",
                   "save_model", "model_to_string", "dump_model", "predict",
                   "feature_importance"):
        assert callable(getattr(Booster, method)), method
    sig = inspect.signature(Booster.predict)
    for kw in ("num_iteration", "raw_score", "pred_leaf", "data_has_header",
               "is_reshape"):
        assert kw in sig.parameters, kw
    sig = inspect.signature(Booster.__init__)
    for kw in ("params", "train_set", "model_file"):
        assert kw in sig.parameters, kw


def test_dataset_surface_for_r():
    for method in ("construct", "num_data", "num_feature", "set_label",
                   "set_weight", "set_init_score", "set_group", "get_label",
                   "get_weight", "get_init_score", "get_group", "subset",
                   "save_binary", "set_reference",
                   "set_categorical_feature"):
        assert callable(getattr(Dataset, method)), method
    sig = inspect.signature(Dataset.__init__)
    for kw in ("data", "label", "weight", "group", "params", "feature_name",
               "categorical_feature", "free_raw_data"):
        assert kw in sig.parameters, kw


def test_train_cv_surface_for_r():
    sig = inspect.signature(lgb.train)
    for kw in ("params", "train_set", "num_boost_round", "valid_sets",
               "valid_names", "early_stopping_rounds", "evals_result",
               "verbose_eval", "init_model"):
        assert kw in sig.parameters, kw
    sig = inspect.signature(lgb.cv)
    for kw in ("params", "train_set", "num_boost_round", "nfold",
               "stratified", "early_stopping_rounds", "verbose_eval"):
        assert kw in sig.parameters, kw


def test_r_code_calls_only_existing_python_attrs():
    """Grep the R sources for `$py$<name>(` and `lgb$<name>(` call sites
    and check each against the Python objects."""
    calls_py = set()
    calls_mod = set()
    for fname in os.listdir(R_DIR):
        src = open(os.path.join(R_DIR, fname)).read()
        calls_py.update(re.findall(r"\$py\$([A-Za-z_]+)\(", src))
        calls_py.update(re.findall(r"self\$py\$`?([A-Za-z_]+)`?\$", src))
        calls_mod.update(re.findall(r"lgb\$([A-Za-z_]+)\(", src))
    for name in calls_mod:
        assert hasattr(lgb, name), f"lightgbm_tpu.{name} missing (R calls it)"
    for name in calls_py:
        assert (hasattr(Booster, name) or hasattr(Dataset, name)
                or name in ("_binned",)), \
            f"Booster/Dataset.{name} missing (R calls it)"
