"""R-binding contract: the build image has no R runtime, so the R package
(R-package/) cannot be executed here.  This test pins its contract with
the Python core instead — every Python attribute the R code calls must
exist with a compatible signature, so R-side breakage can only come from
the R files themselves, which are thin R6 delegations."""

import inspect
import os
import re

import lightgbm_tpu as lgb
from lightgbm_tpu.basic import Booster, Dataset

R_DIR = os.path.join(os.path.dirname(__file__), "..", "R-package", "R")


def test_r_package_files_present():
    files = os.listdir(R_DIR)
    for needed in ("lgb.Dataset.R", "lgb.Booster.R", "lgb.train.R",
                   "utils.R"):
        assert needed in files
    desc = open(os.path.join(R_DIR, "..", "DESCRIPTION")).read()
    assert "reticulate" in desc


def test_booster_surface_for_r():
    for method in ("add_valid", "update", "rollback_one_iter",
                   "current_iteration", "eval", "eval_train", "eval_valid",
                   "save_model", "model_to_string", "dump_model", "predict",
                   "feature_importance"):
        assert callable(getattr(Booster, method)), method
    sig = inspect.signature(Booster.predict)
    for kw in ("num_iteration", "raw_score", "pred_leaf", "data_has_header",
               "is_reshape"):
        assert kw in sig.parameters, kw
    sig = inspect.signature(Booster.__init__)
    for kw in ("params", "train_set", "model_file"):
        assert kw in sig.parameters, kw


def test_dataset_surface_for_r():
    for method in ("construct", "num_data", "num_feature", "set_label",
                   "set_weight", "set_init_score", "set_group", "get_label",
                   "get_weight", "get_init_score", "get_group", "subset",
                   "save_binary", "set_reference",
                   "set_categorical_feature"):
        assert callable(getattr(Dataset, method)), method
    sig = inspect.signature(Dataset.__init__)
    for kw in ("data", "label", "weight", "group", "params", "feature_name",
               "categorical_feature", "free_raw_data"):
        assert kw in sig.parameters, kw


def test_train_cv_surface_for_r():
    sig = inspect.signature(lgb.train)
    for kw in ("params", "train_set", "num_boost_round", "valid_sets",
               "valid_names", "early_stopping_rounds", "evals_result",
               "verbose_eval", "init_model"):
        assert kw in sig.parameters, kw
    sig = inspect.signature(lgb.cv)
    for kw in ("params", "train_set", "num_boost_round", "nfold",
               "stratified", "early_stopping_rounds", "verbose_eval"):
        assert kw in sig.parameters, kw


def test_r_package_depth_files_present():
    """VERDICT round-2 item 6: the reference's analysis/persistence layer
    must exist R-side, not just the training entries."""
    files = os.listdir(R_DIR)
    for needed in ("lgb.model.dt.tree.R", "lgb.interprete.R",
                   "lgb.plot.importance.R", "saveRDS.lgb.Booster.R",
                   "callback.R", "lgb.Predictor.R"):
        assert needed in files, needed
    ns = open(os.path.join(R_DIR, "..", "NAMESPACE")).read()
    for export in ("lgb.model.dt.tree", "lgb.interprete",
                   "lgb.plot.importance", "lgb.plot.interpretation",
                   "saveRDS.lgb.Booster", "readRDS.lgb.Booster",
                   "cb.reset.parameters", "cb.early.stop",
                   "lgb.Predictor"):
        assert export in ns, export
    assert os.path.exists(os.path.join(R_DIR, "..", "tests", "smoke.R"))


def test_callback_surface_for_r():
    """callback.R translates R callback tags into these Python entries."""
    from lightgbm_tpu import callback as cb
    assert "period" in inspect.signature(cb.print_evaluation).parameters
    assert callable(cb.record_evaluation)
    assert callable(cb.reset_parameter)
    sig = inspect.signature(cb.early_stopping)
    assert "stopping_rounds" in sig.parameters
    assert "verbose" in sig.parameters


def test_dump_model_shape_for_r_tree_table():
    """lgb.model.dt.tree/lgb.interprete parse dump_model(): pin the node
    field names they read."""
    import numpy as np
    X = np.random.RandomState(0).normal(size=(300, 4))
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 4,
                     "min_data_in_leaf": 20, "verbose": -1},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    dump = bst.dump_model()
    assert "feature_names" in dump and "tree_info" in dump
    node = dump["tree_info"][0]["tree_structure"]
    for key in ("split_index", "split_feature", "split_gain", "threshold",
                "decision_type", "internal_value", "internal_count",
                "left_child", "right_child"):
        assert key in node, key
    leaf = node["left_child"]
    while "leaf_index" not in leaf:
        leaf = leaf["left_child"]
    for key in ("leaf_index", "leaf_parent", "leaf_value", "leaf_count"):
        assert key in leaf, key
    # lgb.Booster$num_class() reads the private GBDT handle
    assert bst._booster.num_class == 1
    assert callable(bst.num_trees)


def test_r_code_calls_only_existing_python_attrs():
    """Grep the R sources for `$py$<name>(` and `lgb$<name>(` call sites
    and check each against the Python objects."""
    calls_py = set()
    calls_mod = set()
    for fname in os.listdir(R_DIR):
        src = open(os.path.join(R_DIR, fname)).read()
        calls_py.update(re.findall(r"\$py\$([A-Za-z_]+)\(", src))
        calls_py.update(re.findall(r"self\$py\$`?([A-Za-z_]+)`?\$", src))
        calls_mod.update(re.findall(r"lgb\$([A-Za-z_]+)\(", src))
    for name in calls_mod:
        assert hasattr(lgb, name), f"lightgbm_tpu.{name} missing (R calls it)"
    for name in calls_py:
        assert (hasattr(Booster, name) or hasattr(Dataset, name)
                or name in ("_binned",)), \
            f"Booster/Dataset.{name} missing (R calls it)"
