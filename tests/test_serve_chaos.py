"""Serving chaos suite (serve/health.py + testing/faults.py injectors).

Kill replicas under sustained load and pin the survival contract:

- a WEDGED replica (device predict blocks forever) is ejected within
  the watchdog interval, its queued work hedges onto the survivors with
  ZERO failed client requests, and after the wedge lifts a synthetic
  probe re-admits it on probation — all asserted via the new prom
  counters (``serve_ejections_total`` / ``serve_retries_total`` /
  ``serve_readmissions_total``);
- a POISONED replica (predict raises) is ejected via the
  consecutive-error rule, again with zero client-visible failures;
- a SLOW replica (straggler) is ejected by the EWMA latency-outlier
  rule;
- at ZERO healthy replicas the fleet fails fast with 503 — never hangs
  — and recovers once a probe succeeds;
- requests with an expired ``deadline_ms`` return 504 with zero
  device-predict spans in their causal trace;
- a hot reload whose warmup raises (``fail_warmup``) leaves the
  serving generation, its predictions (bit-match), and the compile
  ledger untouched;
- a restarted server boots from the last-good model recorded in
  ``serve_state_file``, not the stale ``input_model``.

Stub forests drive the scheduling chaos (deterministic, fast); the
reload-rollback and restart-restore tests run real ``CompiledForest``s.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import compile_ledger, prom, tracing
from lightgbm_tpu.serve import (DeadlineExpired, Fleet, NoHealthyReplicas,
                                PredictServer, Replica, ReplicaSet)
from lightgbm_tpu.serve.forest import CompiledForest
from lightgbm_tpu.serve.health import EJECTED, HEALTHY, PROBATION
from lightgbm_tpu.testing import faults

pytestmark = [pytest.mark.serve, pytest.mark.fleet, pytest.mark.chaos]

BUCKETS = [16, 64]


class StubForest:
    """Duck-typed CompiledForest: constant predictions, optional fixed
    service time — deterministic fuel for the chaos scheduling tests."""

    num_trees = 1
    num_class = 1

    def __init__(self, service_s=0.0, value=1.0, num_features=4,
                 device=None):
        self.service_s = float(service_s)
        self.value = float(value)
        self.num_features = int(num_features)
        self.device = device

    def batched_fn(self):
        def fn(rows):
            if self.service_s:
                time.sleep(self.service_s)
            out = np.full((1, rows.shape[0]), self.value, np.float32)
            return out, out
        return fn

    def to_device(self, device):
        return StubForest(self.service_s, self.value, self.num_features,
                          device)

    def warmup(self, buckets=None, max_bucket=None):
        return self

    def info(self):
        return {"num_trees": 1, "num_class": 1,
                "num_features": self.num_features}


def _stub_fleet(n_replicas=2, service_s=0.0, watchdog_s=0.05,
                stall_s=0.25, retry_limit=2, **kw):
    reps = [Replica(StubForest(service_s), i, "primary", 1,
                    max_batch=256, max_delay_s=0.0, max_queue=0)
            for i in range(n_replicas)]
    return Fleet(ReplicaSet(reps, "primary", 1),
                 watchdog_interval_s=watchdog_s, stall_s=stall_s,
                 retry_limit=retry_limit, **kw), reps


def _prom_counter(name):
    """Read one unlabeled counter back out of the Prometheus exposition
    (the chaos gates are asserted via the scrapeable series, not just
    the in-process registry)."""
    parsed = prom.parse_text(prom.render())
    vals = [v for n, labels, v in parsed["samples"]
            if n == f"lightgbm_tpu_{name}" and not labels]
    return vals[0] if vals else 0.0


def _hammer(fleet, n_threads, stop_evt, errors, served):
    def client():
        while not stop_evt.is_set():
            try:
                res = fleet.submit(np.ones((1, 4), np.float32),
                                   timeout=30.0)
                served.append(float(np.asarray(res.out)[0, 0]))
            except Exception as exc:   # any client-visible failure
                errors.append(repr(exc))
                return
    threads = [threading.Thread(target=client) for _ in range(n_threads)]
    for t in threads:
        t.start()
    return threads


def _wait_until(pred, timeout_s=8.0, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval_s)
    return pred()


# ---------------------------------------------------------------------------
# the acceptance gate: wedge under load -> eject -> hedge -> readmit


def test_wedged_replica_ejected_hedged_readmitted_zero_failures():
    fleet, reps = _stub_fleet(n_replicas=2)
    e0 = _prom_counter("serve_ejections_total")
    r0 = _prom_counter("serve_readmissions_total")
    h0 = _prom_counter("serve_retries_total")
    errors, served = [], []
    stop_evt = threading.Event()
    try:
        with faults.wedge_replica(fleet, 0):
            threads = _hammer(fleet, 4, stop_evt, errors, served)
            # ejected within the watchdog interval (+ stall threshold)
            assert _wait_until(lambda: reps[0].health == EJECTED,
                               timeout_s=5.0), \
                f"wedged replica never ejected: {reps[0].health}"
            t_eject = time.monotonic()
            # traffic keeps flowing on the survivor while 0 is wedged
            n = len(served)
            assert _wait_until(lambda: len(served) > n + 20)
        # wedge lifted -> the pending probe completes -> probation
        assert _wait_until(
            lambda: reps[0].health in (PROBATION, HEALTHY), timeout_s=8.0), \
            f"ejected replica never re-admitted: {reps[0].health}"
        # probation traffic heals it fully
        assert _wait_until(lambda: reps[0].health == HEALTHY,
                           timeout_s=8.0)
        assert time.monotonic() - t_eject < 8.0
    finally:
        stop_evt.set()
        for t in threads:
            t.join()
        fleet.close()
    assert errors == [], errors[:3]              # ZERO failed requests
    assert _prom_counter("serve_ejections_total") - e0 == 1
    assert _prom_counter("serve_readmissions_total") - r0 == 1
    # the wedged replica's queued work was hedged onto the survivor
    assert _prom_counter("serve_retries_total") - h0 >= 1
    st = fleet.stats()
    healths = {r["replica"]: r["health"] for r in st["replicas"]}
    assert healths[0] == HEALTHY and healths[1] == HEALTHY
    assert st["replicas"][0]["ejections"] == 1


def test_poisoned_replica_ejected_zero_failures():
    fleet, reps = _stub_fleet(n_replicas=2, error_threshold=3)
    errors, served = [], []
    stop_evt = threading.Event()
    try:
        with faults.poison_predict(fleet, 1) as stats:
            threads = _hammer(fleet, 4, stop_evt, errors, served)
            assert _wait_until(lambda: reps[1].health == EJECTED,
                               timeout_s=5.0), reps[1].health
            assert stats["calls"] >= 3           # errors drove the verdict
        assert _wait_until(
            lambda: reps[1].health in (PROBATION, HEALTHY), timeout_s=8.0)
    finally:
        stop_evt.set()
        for t in threads:
            t.join()
        fleet.close()
    assert errors == [], errors[:3]
    assert all(v == 1.0 for v in served)         # every answer was real


def test_probation_error_reejects_with_one_strike():
    """One error during probation must send the replica back to
    ejected — even though it is far below serve_error_threshold — via
    the sticky probation_failed flag (a flapping replica cannot
    oscillate its way back to full traffic)."""
    fleet, reps = _stub_fleet(n_replicas=2, error_threshold=3)
    errors, served = [], []
    stop_evt = threading.Event()
    try:
        with faults.poison_predict(fleet, 1):
            threads = _hammer(fleet, 3, stop_evt, errors, served)
            assert _wait_until(lambda: reps[1].health == EJECTED,
                               timeout_s=5.0)
            stop_evt.set()
            for t in threads:
                t.join()
        # fault lifted, no traffic: the probe re-admits it and it STAYS
        # on probation (nothing serves, so nothing counts it down)
        assert _wait_until(lambda: reps[1].health == PROBATION,
                           timeout_s=8.0), reps[1].health
        ej0 = reps[1].ejections
        with faults.poison_predict(fleet, 1):
            # a few requests: ones landing on replica 1 error (hedged to
            # 0), tripping the one-strike probation rule
            for _ in range(4):
                res = fleet.submit(np.ones((1, 4), np.float32),
                                   timeout=10.0)
                assert float(np.asarray(res.out)[0, 0]) == 1.0
            assert _wait_until(lambda: reps[1].ejections > ej0,
                               timeout_s=5.0), \
                (reps[1].health, reps[1].consecutive_errors)
        assert reps[1].health == EJECTED or reps[1].ejections > ej0
    finally:
        stop_evt.set()
        fleet.close()
    assert errors == []


def test_slow_replica_latency_outlier_ejected():
    fleet, reps = _stub_fleet(n_replicas=2, service_s=0.002,
                              stall_s=30.0)    # stall rule out of the way
    errors, served = [], []
    stop_evt = threading.Event()
    try:
        with faults.slow_replica(fleet, 0, delay_s=0.25):
            threads = _hammer(fleet, 4, stop_evt, errors, served)
            assert _wait_until(lambda: reps[0].health == EJECTED,
                               timeout_s=8.0), \
                (reps[0].health, reps[0].ewma_service_s,
                 reps[1].ewma_service_s)
    finally:
        stop_evt.set()
        for t in threads:
            t.join()
        fleet.close()
    assert errors == [], errors[:3]


def test_zero_healthy_replicas_fails_fast_503_then_recovers():
    fleet, reps = _stub_fleet(n_replicas=1, retry_limit=1)
    srv = PredictServer(fleet, port=0).start()
    host, port = srv.address
    base = f"http://{host}:{port}"
    payload = json.dumps({"rows": [[0.0] * 4]}).encode()
    try:
        with faults.wedge_replica(fleet, 0):
            # one sacrificial in-flight request feeds the stall
            # detector (an idle wedged replica is indistinguishable
            # from a healthy idle one); it fails over to... nobody, so
            # it errors — the contract under test is the 503 after it
            sacrifice = []

            def _sacrificial():
                try:
                    fleet.submit(np.ones((1, 4), np.float32), timeout=30.0)
                    sacrifice.append("ok")
                except Exception as exc:
                    sacrifice.append(type(exc).__name__)

            t_sac = threading.Thread(target=_sacrificial)
            t_sac.start()
            assert _wait_until(lambda: reps[0].health == EJECTED,
                               timeout_s=5.0)
            t_sac.join(timeout=10.0)
            assert not t_sac.is_alive(), "ejection left a request hanging"
            # degraded to ZERO replicas: fail fast, not hang
            t0 = time.monotonic()
            with pytest.raises(NoHealthyReplicas):
                fleet.submit(np.ones((1, 4), np.float32), timeout=30.0)
            assert time.monotonic() - t0 < 2.0
            req = urllib.request.Request(
                base + "/predict", data=payload,
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=10)
            assert err.value.code == 503
            assert err.value.headers.get("X-Request-Id") is not None
            err.value.read()
        # fault lifted: probe -> probation -> serving again
        assert _wait_until(
            lambda: reps[0].health in (PROBATION, HEALTHY), timeout_s=8.0)
        req = urllib.request.Request(
            base + "/predict", data=payload,
            headers={"Content-Type": "application/json"})
        resp = json.loads(urllib.request.urlopen(req, timeout=30).read())
        assert resp["predictions"] == [1.0]
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# deadlines


@pytest.fixture
def tracer(tmp_path, monkeypatch):
    path = tmp_path / "trace_events.json"
    tracing.TRACER.reset()
    monkeypatch.setenv(tracing.ENV_PATH, str(path))
    tracing.TRACER.configure()
    yield path
    tracing.TRACER.disable()
    tracing.TRACER.reset()
    tracing.TRACER.path = None


def test_expired_deadline_504_zero_device_spans(tracer):
    """The deadline acceptance gate: an already-expired ``deadline_ms``
    returns 504 and its trace contains NO device-predict span — the
    request was shed before consuming device time."""
    fleet, _ = _stub_fleet(n_replicas=1, watchdog_s=0.0)
    srv = PredictServer(fleet, port=0).start()
    host, port = srv.address
    base = f"http://{host}:{port}"
    d0 = obs.get_counter("serve_deadline_expired_total")
    expired_ids = []
    try:
        for _ in range(3):
            body = json.dumps({"rows": [[0.0] * 4],
                               "deadline_ms": 0.0}).encode()
            req = urllib.request.Request(
                base + "/predict", data=body,
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=10)
            assert err.value.code == 504
            rid = err.value.headers.get("X-Request-Id")
            assert rid is not None
            expired_ids.append(int(rid))
            err.value.read()
        # a live request afterwards still works (the 504s shed cleanly)
        body = json.dumps({"rows": [[0.0] * 4],
                           "deadline_ms": 30000.0}).encode()
        req = urllib.request.Request(
            base + "/predict", data=body,
            headers={"Content-Type": "application/json"})
        resp = json.loads(urllib.request.urlopen(req, timeout=30).read())
        assert resp["num_rows"] == 1
    finally:
        srv.stop()
    assert obs.get_counter("serve_deadline_expired_total") - d0 == 3
    events = tracing.read_trace(str(tracer))
    spans = [e for e in events if e.get("ph") == "X"]
    by_request = {e["args"]["request_id"]: e["args"]["trace_id"]
                  for e in spans if e["name"] == "Serve::request"
                  and "request_id" in (e.get("args") or {})}
    predict_traces = {e["args"].get("trace_id") for e in spans
                      if e["name"] == "Predict::forest"}
    for rid in expired_ids:
        assert rid in by_request, f"request {rid} left no closed span"
        assert by_request[rid] not in predict_traces, \
            f"expired request {rid} reached the device"


def test_deadline_expired_in_queue_sheds_before_device():
    """A queued request whose deadline passes while an earlier batch
    occupies the device is shed (504) and never coalesced."""
    calls = []

    def slow_fn(rows):
        calls.append(int(rows.shape[0]))
        time.sleep(0.3)
        out = np.zeros((1, rows.shape[0]), np.float32)
        return out, out

    from lightgbm_tpu.serve.batcher import MicroBatcher
    mb = MicroBatcher(slow_fn, max_batch=4, max_delay_s=0.0)
    t = threading.Thread(
        target=lambda: mb.submit(np.ones((1, 4)), timeout=10))
    t.start()
    time.sleep(0.05)                  # worker is now inside slow_fn
    with pytest.raises(DeadlineExpired):
        mb.submit(np.ones((1, 4)), deadline=time.monotonic() + 0.05)
    t.join()
    time.sleep(0.4)                   # give a (buggy) coalesce a chance
    mb.close()
    assert calls == [1], calls        # the expired member never ran


# ---------------------------------------------------------------------------
# reload rollback + restart restore (real forests)


def _train_and_save(tmp_path, name, rounds, lr=0.1, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(800, 6))
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                     "min_data_in_leaf": 20, "learning_rate": lr},
                    lgb.Dataset(X, label=y), num_boost_round=rounds)
    path = str(tmp_path / name)
    bst.save_model(path)
    return path, X


def test_reload_warmup_failure_rolls_back(tmp_path):
    """The reload-rollback acceptance gate: a reload whose warmup raises
    leaves the generation, /predict output (bit-match), and the compile
    ledger unchanged."""
    path_a, X = _train_and_save(tmp_path, "a.txt", rounds=3)
    path_b, _ = _train_and_save(tmp_path, "b.txt", rounds=5, lr=0.3)
    rows5 = X[:5].astype(np.float32)
    forest = CompiledForest.from_booster(lgb.Booster(model_file=path_a),
                                         buckets=BUCKETS)
    forest.warmup(max_bucket=64)
    fleet = Fleet.build(forest, devices=[None], max_batch=64,
                        max_delay_s=0.001, warm=False)
    srv = PredictServer(fleet, port=0).start()
    host, port = srv.address
    base = f"http://{host}:{port}"
    payload = json.dumps({"rows": rows5.tolist()}).encode()

    def _predict():
        req = urllib.request.Request(
            base + "/predict", data=payload,
            headers={"Content-Type": "application/json"})
        return json.loads(urllib.request.urlopen(req, timeout=30).read())

    try:
        before = _predict()
        assert before["generation"] == 1
        n_ledger = len(compile_ledger.events())
        with faults.fail_warmup(times=1) as stats:
            req = urllib.request.Request(
                base + "/reload",
                data=json.dumps({"model": path_b}).encode())
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=60)
            assert err.value.code == 500
            err.value.read()
        assert stats["failed"] == 1
        after = _predict()
        # generation untouched, predictions bit-match, ledger flat
        assert after["generation"] == 1
        assert np.array_equal(
            np.asarray(after["predictions"], np.float32),
            np.asarray(before["predictions"], np.float32))
        assert len(compile_ledger.events()) == n_ledger
        # and the fleet still reloads FINE once the fault is gone
        req = urllib.request.Request(
            base + "/reload", data=json.dumps({"model": path_b}).encode())
        resp = json.loads(urllib.request.urlopen(req, timeout=180).read())
        assert resp["generation"] == 2
    finally:
        srv.stop()


def test_restart_restores_last_good_model(tmp_path):
    """serve_state_file: a reload records the last-good model; a server
    RESTART with the same (now stale) input_model boots the last-good
    model instead."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.serve.server import serve_from_config

    path_a, X = _train_and_save(tmp_path, "a.txt", rounds=3)
    path_b, _ = _train_and_save(tmp_path, "b.txt", rounds=5, lr=0.3)
    state = tmp_path / "serve_state.json"
    conf = {"task": "serve", "input_model": path_a, "serve_port": 0,
            "serve_state_file": str(state), "serve_max_batch": 64,
            "predict_buckets": [16, 64], "serve_watchdog_ms": 0,
            "verbose": -1}
    srv = serve_from_config(Config(dict(conf))).start()
    try:
        assert srv._ready.wait(120.0)          # background warm finishes
        assert json.loads(state.read_text())["primary"]["model"] == path_a
        gen = srv.manager.reload(path_b)
        assert gen == 2
        assert json.loads(state.read_text())["primary"]["model"] == path_b
    finally:
        srv.stop()
    # "restart": same config, same input_model=a — boots b (last good)
    srv2 = serve_from_config(Config(dict(conf))).start()
    try:
        assert srv2._ready.wait(120.0)
        b_trees = lgb.Booster(model_file=path_b).num_trees()
        assert srv2.forest.num_trees == b_trees
        host, port = srv2.address
        body = json.dumps({"rows": X[:3].tolist()}).encode()
        req = urllib.request.Request(
            f"http://{host}:{port}/predict", data=body,
            headers={"Content-Type": "application/json"})
        resp = json.loads(urllib.request.urlopen(req, timeout=30).read())
        want = CompiledForest.from_booster(
            lgb.Booster(model_file=path_b), buckets=[16, 64]).predict(
                X[:3].astype(np.float32), device_binning=True)
        np.testing.assert_allclose(
            np.asarray(resp["predictions"], np.float32),
            np.asarray(want, np.float32), rtol=1e-6, atol=1e-6)
    finally:
        srv2.stop()


def test_readiness_gates_traffic_while_warming():
    """Liveness vs readiness: /healthz is 200 from the first instant,
    /readyz (and /predict) are 503 until the background warm completes,
    and /readyz flips to 503 "draining" once shutdown is requested."""
    release = threading.Event()

    class SlowWarmForest(StubForest):
        def warmup(self, buckets=None, max_bucket=None):
            release.wait(10.0)
            return self

    fleet = Fleet(ReplicaSet(
        [Replica(SlowWarmForest(), 0, "primary", 1, max_batch=64,
                 max_delay_s=0.0, max_queue=0)], "primary", 1))
    srv = PredictServer(fleet, port=0, warm_in_background=True).start()
    host, port = srv.address
    base = f"http://{host}:{port}"
    payload = json.dumps({"rows": [[0.0] * 4]}).encode()
    try:
        health = json.loads(urllib.request.urlopen(
            base + "/healthz", timeout=10).read())
        assert health["status"] == "ok" and health["ready"] is False
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(base + "/readyz", timeout=10)
        assert err.value.code == 503
        assert json.loads(err.value.read())["status"] == "warming"
        req = urllib.request.Request(
            base + "/predict", data=payload,
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 503
        err.value.read()
        release.set()
        assert srv._ready.wait(10.0)
        ready = json.loads(urllib.request.urlopen(
            base + "/readyz", timeout=10).read())
        assert ready["status"] == "ready"
        req = urllib.request.Request(
            base + "/predict", data=payload,
            headers={"Content-Type": "application/json"})
        resp = json.loads(urllib.request.urlopen(req, timeout=30).read())
        assert resp["predictions"] == [1.0]
        # drain: readiness drops BEFORE the sockets close
        srv._stop_requested.set()
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(base + "/readyz", timeout=10)
        assert err.value.code == 503
        assert json.loads(err.value.read())["status"] == "draining"
        health = json.loads(urllib.request.urlopen(
            base + "/healthz", timeout=10).read())
        assert health["status"] == "ok"          # still LIVE
    finally:
        srv.stop()
