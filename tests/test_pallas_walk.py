"""Fused Pallas forest-walk serving strategy (ops/pallas_walk.py +
``serve_walk``, docs/SERVING.md §Serving strategies).

Tier-1 CPU pins, all interpreter-mode (``pl.pallas_call(interpret=True)``
— the same kernel body a TPU runs, minus the mosaic lowering):

- fused vs gather parity ≤1e-6 across the bucket ladder (n=1..700,
  binned + raw + transformed), on constant, linear, categorical/NaN,
  DART and multiclass forests — the strategies must be swappable per
  forest with nothing downstream noticing;
- bin quantization: bf16 leaf storage activates only under the
  QUANTIZE_LEAF_ATOL bound and pins to it; past the bound the forest
  falls back to f32 and the named ``forest_quantize_fallback`` counter
  records why;
- gather byte-identity: ``serve_walk=gather`` builds/compiles ZERO
  walk-named programs (ledger delta empty) and keeps the atol=0
  ``Booster.predict`` contract bit-for-bit;
- warmup covers every dispatchable bucket: a ``max_bucket`` strictly
  between ladder rungs warms the rung ABOVE it (where bucket_for routes
  the largest admitted requests), pinned by a zero-compile ledger delta
  on the first such request — both strategies;
- the bench_regress ``--latency-threshold`` gate trips on a p99
  regression per (strategy, batch) point and skips with a note when a
  side lacks the ``latency_sweep`` block.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import LightGBMError, obs
from lightgbm_tpu.serve import CompiledForest

pytestmark = pytest.mark.walk

BUCKETS = [32, 128, 512]
# crosses every rung boundary; 700 > max bucket streams chunked
SIZES = [1, 33, 129, 700]


def _train(n=800, num_class=1, seed=0, num_boost_round=4, extra=None):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, 6))
    X[:, 3] = np.round(X[:, 3] * 4) / 4       # boundary-tied values
    params = {"num_leaves": 7, "verbose": -1, "min_data_in_leaf": 20}
    if num_class > 1:
        y = np.digitize(X[:, 0], [-0.5, 0.5]).astype(np.float64)
        params.update({"objective": "multiclass", "num_class": num_class})
    else:
        y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
        params["objective"] = "binary"
    params.update(extra or {})
    bst = lgb.train(params, lgb.Dataset(X, label=y),
                    num_boost_round=num_boost_round)
    return bst, X


def _pair(bst, **kw):
    fused = CompiledForest.from_booster(bst, buckets=BUCKETS,
                                        serve_walk="fused", **kw)
    gather = CompiledForest.from_booster(bst, buckets=BUCKETS,
                                         serve_walk="gather")
    assert fused.walk_strategy == "fused"
    assert gather.walk_strategy == "gather"
    return fused, gather


def _assert_parity(fused, gather, X, sizes=SIZES, atol=1e-6):
    for n in sizes:
        Xn = X[:n]
        np.testing.assert_allclose(
            fused.raw_scores(Xn), gather.raw_scores(Xn),
            rtol=0, atol=atol, err_msg=f"binned raw_scores n={n}")
        fr, fo = fused._device_scores(Xn)
        gr, go = gather._device_scores(Xn)
        np.testing.assert_allclose(fr, gr, rtol=0, atol=atol,
                                   err_msg=f"raw-path margins n={n}")
        np.testing.assert_allclose(fo, go, rtol=0, atol=atol,
                                   err_msg=f"transformed n={n}")


# ---------------------------------------------------------------------------
# fused vs gather parity across the ladder


@pytest.mark.parametrize("num_class", [1, 3])
def test_fused_matches_gather_across_ladder(num_class):
    bst, X = _train(num_class=num_class)
    fused, gather = _pair(bst)
    _assert_parity(fused, gather, X)
    # and through the public surface, shaped like Booster.predict
    np.testing.assert_allclose(
        fused.predict(X[:300], raw_score=True),
        gather.predict(X[:300], raw_score=True), rtol=0, atol=1e-6)


def test_fused_matches_gather_nan_and_categorical():
    rng = np.random.RandomState(3)
    X = rng.normal(size=(1000, 6))
    X[:, 1] = rng.randint(0, 8, size=1000)    # categorical codes
    y = ((X[:, 0] > 0) ^ (X[:, 1] >= 4)).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1, "min_data_in_leaf": 20},
                    lgb.Dataset(X, label=y, categorical_feature=[1]),
                    num_boost_round=4)
    X = X.copy()
    X[rng.rand(*X.shape) < 0.05] = np.nan     # missing values
    X[::50, 1] = 97.0                         # unseen category
    fused, gather = _pair(bst)
    _assert_parity(fused, gather, X, sizes=[1, 129, 700])


def test_fused_matches_gather_linear_forest():
    # regression target with real structure so leaves carry affine fits
    rng = np.random.RandomState(1)
    X = rng.normal(size=(800, 6))
    y = X[:, 0] * 2.0 + np.abs(X[:, 1]) + rng.normal(scale=0.1, size=800)
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "verbose": -1, "min_data_in_leaf": 20,
                     "linear_tree": True, "linear_lambda": 0.01},
                    lgb.Dataset(X, label=y), num_boost_round=4)
    fused, gather = _pair(bst)
    assert fused._has_linear and fused._walk_aff_dev is not None
    _assert_parity(fused, gather, X, sizes=[1, 129, 700])


def test_fused_matches_gather_dart():
    bst, X = _train(extra={"boosting": "dart", "drop_rate": 0.4,
                           "drop_seed": 5}, num_boost_round=6)
    fused, gather = _pair(bst)
    _assert_parity(fused, gather, X, sizes=[1, 700])


# ---------------------------------------------------------------------------
# bin quantization: atol pin + named fallback


def test_quantized_leaves_activate_within_atol_pin():
    rng = np.random.RandomState(2)
    X = rng.normal(size=(800, 6))
    y = (X[:, 0] + 0.2 * X[:, 1]) * 1e-4      # tiny-magnitude leaves
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "verbose": -1, "min_data_in_leaf": 20},
                    lgb.Dataset(X, label=y), num_boost_round=4)
    fused, gather = _pair(bst, quantize_leaves=True)
    assert fused.leaf_dtype == "bfloat16"
    assert fused.info()["leaf_dtype"] == "bfloat16"
    # the documented bound: quantized output within QUANTIZE_LEAF_ATOL
    # of the exact (gather) scores, on every path
    atol = CompiledForest.QUANTIZE_LEAF_ATOL
    for n in (1, 700):
        np.testing.assert_allclose(fused.raw_scores(X[:n]),
                                   gather.raw_scores(X[:n]),
                                   rtol=0, atol=atol)
        fr, _ = fused._device_scores(X[:n])
        gr, _ = gather._device_scores(X[:n])
        np.testing.assert_allclose(fr, gr, rtol=0, atol=atol)


def test_quantize_falls_back_to_f32_past_atol():
    rng = np.random.RandomState(4)
    X = rng.normal(size=(800, 6))
    y = (X[:, 0] + 0.2 * X[:, 1]) * 50000.0   # bf16 error >> atol
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "verbose": -1, "min_data_in_leaf": 20},
                    lgb.Dataset(X, label=y), num_boost_round=4)
    before = obs.snapshot()["counters"].get("forest_quantize_fallback", 0)
    fused, gather = _pair(bst, quantize_leaves=True)
    after = obs.snapshot()["counters"].get("forest_quantize_fallback", 0)
    assert after == before + 1                # the refusal is named
    assert fused.leaf_dtype == "float32"      # precision kept, not lost
    _assert_parity(fused, gather, X, sizes=[129])


# ---------------------------------------------------------------------------
# gather byte-identity: zero new programs, bit-identical output


def test_gather_builds_no_walk_programs_and_keeps_atol0_contract():
    bst, X = _train()
    before = obs.snapshot()["counters"]
    gather = CompiledForest.from_booster(bst, buckets=BUCKETS,
                                         serve_walk="gather")
    gather.warmup()
    gather.predict(X[:100], raw_score=True)
    gather.predict(X[:100], device_binning=True)
    after = obs.snapshot()["counters"]
    delta = {k for k in after if after[k] != before.get(k, 0)}
    walked = {k for k in delta if "walk" in k}
    assert walked == set(), f"gather touched walk programs: {walked}"
    assert gather._walk_dev is None           # no fused operands frozen
    # compiles landed only under the pre-strategy program names
    compiled = {k for k in delta if "compiles" in k}
    assert compiled and all(
        k.startswith(("predict_forest_compiles", "serve_forest_compiles"))
        for k in compiled), compiled
    # bit-identity: an explicit serve_walk=gather forest and a default
    # build (no strategy kwargs — every pre-existing caller) produce
    # byte-identical output on every path; the strategy layer added
    # dispatch indirection, not arithmetic
    default = CompiledForest.from_booster(bst, buckets=BUCKETS)
    assert np.array_equal(gather.raw_scores(X),
                          default.raw_scores(X))
    gr, go = gather._device_scores(X)
    dr, do = default._device_scores(X)
    assert np.array_equal(gr, dr) and np.array_equal(go, do)


# ---------------------------------------------------------------------------
# warmup: every dispatchable bucket, both strategies


@pytest.mark.parametrize("strategy", ["gather", "fused"])
def test_warmup_covers_rung_above_max_bucket(strategy):
    bst, X = _train()
    cf = CompiledForest.from_booster(bst, buckets=BUCKETS,
                                     serve_walk=strategy)
    # 200 sits strictly between rungs 128 and 512: bucket_for routes a
    # 200-row request to 512, so warmup(max_bucket=200) must compile 512
    cf.warmup(max_bucket=200)
    before = obs.snapshot()["counters"]
    cf.predict(X[:200], raw_score=True)
    cf.predict(X[:200], device_binning=True)
    after = obs.snapshot()["counters"]
    new = {k: after[k] - before.get(k, 0) for k in after
           if "compiles" in k and after[k] != before.get(k, 0)}
    assert new == {}, f"post-warmup hot-path compiles ({strategy}): {new}"


# ---------------------------------------------------------------------------
# strategy resolution + config plumbing


def test_auto_resolves_gather_off_tpu_and_info_reports():
    bst, _ = _train(num_boost_round=2)
    auto = CompiledForest.from_booster(bst, buckets=[32],
                                       serve_walk="auto")
    assert auto.serve_walk_requested == "auto"
    assert auto.walk_strategy == "gather"     # no TPU attached in tier-1
    assert auto.info()["serve_walk"] == "gather"
    assert "walk_vmem_bytes" not in auto.info()
    fused = CompiledForest.from_booster(bst, buckets=[32],
                                        serve_walk="fused")
    info = fused.info()
    assert info["serve_walk"] == "fused"
    assert info["walk_vmem_bytes"] > 0
    assert info["bin_dtype"] == "uint8"       # max_bin 255 fits u8 bins
    assert info["leaf_dtype"] == "float32"    # quantize not requested


def test_serve_walk_param_plumbs_from_config():
    rng = np.random.RandomState(5)
    X = rng.normal(size=(400, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1, "min_data_in_leaf": 20,
                     "serve_walk": "fused"},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    cf = bst.compile(buckets=[32])
    assert cf.walk_strategy == "fused"        # config reached the freeze
    with pytest.raises(LightGBMError):
        CompiledForest.from_booster(bst, buckets=[32],
                                    serve_walk="sideways")


def test_serve_walk_config_validation():
    with pytest.raises(ValueError):
        lgb.train({"objective": "binary", "serve_walk": "sideways",
                   "verbose": -1},
                  lgb.Dataset(np.zeros((50, 2)), label=np.zeros(50)),
                  num_boost_round=1)


# ---------------------------------------------------------------------------
# bench_regress --latency-threshold gate


def _bench(value, sweep=None):
    res = {"metric": "predict_rows_per_sec", "value": value,
           "unit": "rows/s"}
    if sweep is not None:
        res["latency_sweep"] = {"active": "fused", "strategies": sweep}
    return res


def test_bench_regress_latency_threshold_gates():
    from tools.bench_regress import compare
    base = _bench(1000.0, {"gather": {"1": {"p99_ms": 2.0},
                                      "64": {"p99_ms": 5.0}},
                           "fused": {"1": {"p99_ms": 1.0}}})
    cand = _bench(1000.0, {"gather": {"1": {"p99_ms": 2.1},
                                      "64": {"p99_ms": 7.0}},  # +40%
                           "fused": {"1": {"p99_ms": 1.0},
                                     "256": {"p99_ms": 9.0}}})  # new pt
    v = compare(base, cand, 10.0, latency_threshold_pct=10.0)
    assert v["ok"] is False and v["latency_ok"] is False
    assert v["latency_delta"]["gather/64"]["ok"] is False
    assert v["latency_delta"]["gather/64"]["delta_pct"] == pytest.approx(
        40.0)
    assert v["latency_delta"]["gather/1"]["ok"] is True
    # points on one side only are not compared (no gate on new batches)
    assert "fused/256" not in v["latency_delta"]
    wide = compare(base, cand, 10.0, latency_threshold_pct=50.0)
    assert wide["ok"] is True and wide["latency_ok"] is True


def test_bench_regress_latency_gate_skips_without_block():
    from tools.bench_regress import compare
    old = _bench(1000.0)                      # pre-sweep baseline
    cand = _bench(1000.0, {"gather": {"1": {"p99_ms": 2.0}}})
    v = compare(old, cand, 10.0, latency_threshold_pct=10.0)
    assert v["ok"] is True and v["latency_ok"] is True
    assert "baseline" in v["latency_note"]
    # and without the flag the block is ignored entirely
    v2 = compare(old, cand, 10.0)
    assert "latency_ok" not in v2
