"""Compile ledger (lightgbm_tpu/obs/compile_ledger.py): instrumented
jits count compiles exactly — cache hits record nothing, shape misses
record one event with program name, abstract shapes, and seconds — the
events feed the registry (compile_count / compile_seconds, rendered at
/metrics), the JSONL sink, and the obs-report --compile section.

Process-global state (registry + in-memory ledger) is asserted by DELTA
so this file composes with the rest of the tier-1 run.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import compile_ledger


@pytest.fixture
def fresh_train_programs(monkeypatch):
    """Order-independence for the end-to-end training test: round 7
    made ``train_step``/``pack_words`` PROCESS-WIDE shared programs
    (models/gbdt.py ``_SHARED_JITS`` + module-level jits), so any
    earlier test that trained over the same shapes leaves them warm and
    a later training run legitimately records ZERO new compiles —
    which is exactly what this file must not depend on.  Swap in an
    empty shared-jit registry and fresh module-level pack jits for the
    duration, so the test observes a cold process no matter what ran
    before it (the originals — and their warm executable caches — are
    restored afterwards)."""
    from lightgbm_tpu.models import gbdt

    monkeypatch.setattr(gbdt, "_SHARED_JITS", {})
    # re-jitting the SAME function object would hit jax's
    # function-identity executable cache and still record nothing; a
    # fresh closure breaks the identity so the compile really happens
    raw_pack_words = gbdt._pack_words_padded._fn.__wrapped__
    raw_pack_tree = gbdt._PACK_TREE._fn.__wrapped__

    def fresh_pack_words(rm, pad):
        return raw_pack_words(rm, pad)

    def fresh_pack_tree(*args, **kwargs):
        return raw_pack_tree(*args, **kwargs)

    monkeypatch.setattr(
        gbdt, "_pack_words_padded",
        obs.instrumented_jit(fresh_pack_words, program="pack_words",
                             static_argnames=("pad",)))
    monkeypatch.setattr(
        gbdt, "_PACK_TREE",
        obs.instrumented_jit(fresh_pack_tree, program="pack_tree"))


@pytest.fixture
def ledger_file(tmp_path, monkeypatch):
    """Route the JSONL sink to a temp file for the duration of a test
    via the env var (which wins inside ``configure`` — so an
    engine.train call mid-test cannot clear it; configure is otherwise
    authoritative per run)."""
    path = tmp_path / "compile_ledger.jsonl"
    monkeypatch.setenv(compile_ledger.ENV_PATH, str(path))
    compile_ledger.configure()
    yield path
    monkeypatch.delenv(compile_ledger.ENV_PATH)
    compile_ledger.configure()             # back to in-memory only


def _deltas():
    return (obs.get_counter("compile_count"),
            (obs.get_histogram("compile_seconds") or {}).get("count", 0),
            len(compile_ledger.events()))


def test_cache_hit_vs_shape_miss_counting(ledger_file):
    c0, h0, e0 = _deltas()
    fn = obs.instrumented_jit(lambda x: x * 2 + 1, program="t_double")
    fn(jnp.ones(4))                        # compile 1
    fn(jnp.ones(4) * 3)                    # cache hit: same shape
    fn(jnp.ones(4))                        # cache hit again
    fn(jnp.ones(8))                        # compile 2: shape miss
    c1, h1, e1 = _deltas()
    assert c1 - c0 == 2
    assert h1 - h0 == 2
    assert e1 - e0 == 2
    mine = compile_ledger.events()[e0:]
    assert [e["program"] for e in mine] == ["t_double", "t_double"]
    assert mine[0]["shapes"] == "f32[4]"
    assert mine[1]["shapes"] == "f32[8]"
    assert all(e["seconds"] > 0 for e in mine)
    # per-program counter landed too
    assert obs.get_counter("compile_count_t_double") >= 2


def test_ledger_jsonl_roundtrip(ledger_file):
    fn = obs.instrumented_jit(lambda x: x - 1, program="t_file")
    fn(jnp.ones(3))
    fn(jnp.ones(5))
    evs = compile_ledger.read_ledger(str(ledger_file))
    assert [e["program"] for e in evs] == ["t_file", "t_file"]
    assert {e["shapes"] for e in evs} == {"f32[3]", "f32[5]"}
    # every line is independently parseable (append-only, flushed)
    with open(ledger_file) as fh:
        for line in fh:
            json.loads(line)


def test_static_args_and_kwargs_in_shapes():
    fn = obs.instrumented_jit(lambda x, n: x[:n].sum(), program="t_static",
                              static_argnames=("n",))
    e0 = len(compile_ledger.events())
    fn(jnp.arange(6.0), n=3)
    ev = compile_ledger.events()[e0]
    assert "f32[6]" in ev["shapes"] and "3" in ev["shapes"]


def test_nested_jit_calls_not_double_counted():
    """An instrumented jit called while another jit traces it inlines —
    it must NOT record a compile of its own."""
    inner = obs.instrumented_jit(lambda x: x * 3, program="t_inner")
    outer = obs.instrumented_jit(lambda x: inner(x) + 1, program="t_outer")
    e0 = len(compile_ledger.events())
    outer(jnp.ones(7))
    progs = [e["program"] for e in compile_ledger.events()[e0:]]
    assert progs == ["t_outer"]


def test_training_populates_ledger(ledger_file, fresh_train_programs):
    """End to end: a warmed-then-rerun training session leaves a
    populated ledger (every event has name, shapes, seconds) and re-runs
    on identical shapes add nothing (acceptance criterion).  Runs
    against fresh shared training programs so it passes in ANY tier-1
    order (an earlier training test would otherwise have pre-compiled
    the process-wide train_step/pack_words jits)."""
    rng = np.random.RandomState(3)
    X = rng.normal(size=(500, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
              "min_data_in_leaf": 20}
    e0 = len(compile_ledger.events())
    lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3)
    mine = compile_ledger.events()[e0:]
    assert mine, "training compiled nothing according to the ledger"
    assert {"train_step", "pack_words"} <= {e["program"] for e in mine}
    for e in mine:
        assert e["program"] and e["shapes"] and e["seconds"] > 0
    # identical second run: the jit caches are warm per-instance only
    # for the booster-owned jits, but module-level programs (bag_mask,
    # grow via train_step closure) re-trace per closure — so assert the
    # cheap invariant: the ledger file carries exactly the in-memory
    # events appended since this test's file was installed
    disk = compile_ledger.read_ledger(str(ledger_file))
    assert [e["program"] for e in disk] == \
        [e["program"] for e in compile_ledger.events()[e0:]]


def test_counting_jit_feeds_ledger():
    """serve/batcher.py CountingJit rides the shared detection: its
    per-bucket counters AND the ledger record the same compile."""
    import jax
    from lightgbm_tpu.serve.batcher import CountingJit
    cj = CountingJit(jax.jit(lambda x: x.sum(axis=0)), "t_bucketed")
    c0 = obs.get_counter("t_bucketed_compiles")
    e0 = len(compile_ledger.events())
    cj(16, jnp.ones((16, 2)))
    cj(16, jnp.ones((16, 2)))              # warm
    cj(32, jnp.ones((32, 2)))
    assert obs.get_counter("t_bucketed_compiles") - c0 == 2
    assert obs.get_counter("t_bucketed_compiles_bucket_16") >= 1
    assert obs.get_counter("t_bucketed_compiles_bucket_32") >= 1
    progs = [e["program"] for e in compile_ledger.events()[e0:]]
    assert progs == ["t_bucketed", "t_bucketed"]


def test_compile_series_rendered_at_metrics():
    """The ledger's registry series render in the Prometheus exposition
    (what a /metrics scrape of a training run serves)."""
    from lightgbm_tpu.obs import prom
    fn = obs.instrumented_jit(lambda x: -x, program="t_prom")
    fn(jnp.ones(2))
    text = prom.render()
    assert "lightgbm_tpu_compile_count " in text
    assert "lightgbm_tpu_compile_seconds_bucket" in text
    assert "lightgbm_tpu_compile_count_t_prom" in text
    parsed = prom.parse_text(text)
    hist = prom.histogram_series(parsed, "lightgbm_tpu_compile_seconds")
    assert hist["count"] >= 1


def test_obs_report_compile_section(tmp_path):
    """obs-report --compile: totals, per-program seconds, slowest with
    shapes."""
    from lightgbm_tpu.obs.report import summarize_compile
    path = tmp_path / "ledger.jsonl"
    with open(path, "w") as fh:
        for prog, shapes, sec in (("grow_tree", "u8[28,100]", 120.5),
                                  ("grow_tree", "u8[28,200]", 60.25),
                                  ("train_gradients", "f32[1,100]", 1.5)):
            fh.write(json.dumps({"program": prog, "shapes": shapes,
                                 "seconds": sec}) + "\n")
    rep = summarize_compile(str(path), top_k=2)
    assert rep["count"] == 3
    assert rep["seconds_total"] == pytest.approx(182.25)
    assert rep["programs"]["grow_tree"]["count"] == 2
    assert rep["programs"]["grow_tree"]["seconds"] == pytest.approx(180.75)
    assert rep["slowest"][0] == {"program": "grow_tree",
                                 "shapes": "u8[28,100]", "seconds": 120.5}
