"""1M-row golden parity gate vs the compiled reference CLI (VERDICT/round-2
"close the parity risk at scale": AUC within 1e-4 of the reference at the
bench operating point, per BASELINE.json tolerances).

Opt-in (LGBT_SCALE_PARITY=1 + a compiled reference CLI): the run needs
~15 min and the reference binary, which is built out-of-tree from the
read-only mount with two missing-#include fixes:

    cp -r /root/reference /tmp/refsrc && chmod -R u+w /tmp/refsrc
    sed -i 's|#include <cstdio>|#include <cstdio>\\n#include <limits>\\n#include <cstdint>|' \\
        /tmp/refsrc/include/LightGBM/utils/common.h
    cmake -S /tmp/refsrc -B /tmp/refbuild -DCMAKE_BUILD_TYPE=Release
    cmake --build /tmp/refbuild -j    # binary lands at /tmp/refsrc/lightgbm

Measured 2026-07-30 on this box (recorded in docs/BENCH_NOTES_r02.md):
reference training auc @40 iters = 0.838636, ours matched within 1e-4.
"""

import os
import re
import subprocess

import numpy as np
import pytest

REF_BIN = os.environ.get("LGBT_REFERENCE_CLI", "/tmp/refsrc/lightgbm")

pytestmark = pytest.mark.skipif(
    not os.environ.get("LGBT_SCALE_PARITY") or not os.path.exists(REF_BIN),
    reason="scale parity gate is opt-in (LGBT_SCALE_PARITY=1 + compiled "
           "reference CLI, see module docstring)")

CONF = """task = train
objective = binary
metric = auc
data = {data}
num_trees = 40
num_leaves = 63
max_bin = 255
learning_rate = 0.1
min_data_in_leaf = 50
is_training_metric = true
metric_freq = 5
output_model = {model}
"""


def _last_auc(text: str) -> float:
    aucs = re.findall(r"training auc\s*:\s*([0-9.]+)", text)
    assert aucs, text[-2000:]
    return float(aucs[-1])


def test_higgslike_1m_auc_parity(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from bench import make_higgs_like
    X, y = make_higgs_like(1_000_000)
    data_path = str(tmp_path / "higgs1m.tsv")
    np.savetxt(data_path, np.column_stack([y, X.astype(np.float32)]),
               fmt="%.7g", delimiter="\t")

    ref_conf = str(tmp_path / "ref.conf")
    open(ref_conf, "w").write(CONF.format(
        data=data_path, model=str(tmp_path / "ref_model.txt")))
    ref_out = subprocess.run([REF_BIN, f"config={ref_conf}"],
                             capture_output=True, text=True, cwd=tmp_path,
                             timeout=1800).stdout

    our_conf = str(tmp_path / "ours.conf")
    open(our_conf, "w").write(CONF.format(
        data=data_path, model=str(tmp_path / "our_model.txt")))
    env = dict(os.environ)
    our_out = subprocess.run(
        ["python", "-m", "lightgbm_tpu", f"config={our_conf}"],
        capture_output=True, text=True, cwd=tmp_path, env=env,
        timeout=1800).stderr

    ref_auc = _last_auc(ref_out)
    our_auc = _last_auc(our_out)
    assert abs(ref_auc - our_auc) < 1e-4, (ref_auc, our_auc)
