"""Distributed tree-growth parity on an 8-virtual-device CPU mesh.

The reference validates parallel learning only by running two local
processes by hand (examples/parallel_learning/); here every parallel
learner is checked for exact structural parity against the serial grower
on the same data — the strongest guarantee the reference's design implies
(data/feature-parallel are mathematically exact reformulations; voting is
exact whenever the elected set contains the true best feature).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from lightgbm_tpu.ops.grow import GrowParams, grow_tree
from lightgbm_tpu.parallel import make_parallel_grow


def _make_data(seed=0, n=512, f=6, B=16):
    rng = np.random.RandomState(seed)
    bins = rng.randint(0, B, size=(f, n)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = np.abs(rng.normal(size=n)).astype(np.float32) + 0.1
    return bins, g, h


def _mesh(n=8, axis="shard"):
    devs = jax.devices()
    assert len(devs) >= n, "conftest must force 8 CPU devices"
    return Mesh(np.array(devs[:n]), (axis,))


def _grow_serial(bins, g, h, params, B):
    F, N = bins.shape
    num_bin = jnp.full((F,), B, jnp.int32)
    is_cat = jnp.zeros((F,), bool)
    feat_mask = jnp.ones((F,), bool)
    w = jnp.ones((N,), jnp.float32)
    return grow_tree(jnp.asarray(bins), num_bin, is_cat, feat_mask,
                     jnp.asarray(g), jnp.asarray(h), w,
                     jnp.float32(0.1), params)


def _grow_parallel(mode, bins, g, h, params, B, n_dev=8, **kw):
    mesh = _mesh(n_dev)
    F, N = bins.shape
    fn = make_parallel_grow(mesh, mode, params, **kw)
    num_bin = jnp.full((F,), B, jnp.int32)
    is_cat = jnp.zeros((F,), bool)
    feat_mask = jnp.ones((F,), bool)
    w = jnp.ones((N,), jnp.float32)
    return fn(jnp.asarray(bins), num_bin, is_cat, feat_mask,
              jnp.asarray(g), jnp.asarray(h), w, jnp.float32(0.1))


def _assert_tree_equal(ta, tb, structural_only=False):
    assert int(ta.num_leaves) == int(tb.num_leaves)
    np.testing.assert_array_equal(np.asarray(ta.split_feature),
                                  np.asarray(tb.split_feature))
    np.testing.assert_array_equal(np.asarray(ta.split_bin),
                                  np.asarray(tb.split_bin))
    np.testing.assert_array_equal(np.asarray(ta.left_child),
                                  np.asarray(tb.left_child))
    np.testing.assert_array_equal(np.asarray(ta.right_child),
                                  np.asarray(tb.right_child))
    if not structural_only:
        np.testing.assert_allclose(np.asarray(ta.leaf_value),
                                   np.asarray(tb.leaf_value),
                                   rtol=2e-4, atol=2e-6)
        np.testing.assert_array_equal(np.asarray(ta.leaf_count),
                                      np.asarray(tb.leaf_count))


PARAMS = GrowParams(num_leaves=15, max_bin=16, min_data_in_leaf=5,
                    min_sum_hessian_in_leaf=1e-3)


@pytest.mark.parametrize("hist_reduce", ["psum", "reduce_scatter"])
def test_data_parallel_matches_serial(hist_reduce):
    bins, g, h = _make_data()
    ts, leaf_s, delta_s = _grow_serial(bins, g, h, PARAMS, 16)
    tp, leaf_p, delta_p = _grow_parallel("data", bins, g, h, PARAMS, 16,
                                         hist_reduce=hist_reduce)
    _assert_tree_equal(ts, tp)
    np.testing.assert_array_equal(np.asarray(leaf_s), np.asarray(leaf_p))
    np.testing.assert_allclose(np.asarray(delta_s), np.asarray(delta_p),
                               rtol=2e-4, atol=2e-6)


def test_feature_parallel_matches_serial():
    bins, g, h = _make_data(seed=1)
    ts, _, _ = _grow_serial(bins, g, h, PARAMS, 16)
    tp, leaf_p, _ = _grow_parallel("feature", bins, g, h, PARAMS, 16)
    _assert_tree_equal(ts, tp)


def test_feature_parallel_uneven_features():
    # 6 features over 8 shards and 10 features over 8 shards (padding paths)
    for f in (6, 10):
        bins, g, h = _make_data(seed=2, f=f)
        ts, _, _ = _grow_serial(bins, g, h, PARAMS, 16)
        tp, _, _ = _grow_parallel("feature", bins, g, h, PARAMS, 16)
        _assert_tree_equal(ts, tp)


def test_data_parallel_uneven_rows():
    bins, g, h = _make_data(seed=3, n=509)  # not divisible by 8
    ts, leaf_s, delta_s = _grow_serial(bins, g, h, PARAMS, 16)
    tp, leaf_p, delta_p = _grow_parallel("data", bins, g, h, PARAMS, 16)
    _assert_tree_equal(ts, tp)
    np.testing.assert_array_equal(np.asarray(leaf_s), np.asarray(leaf_p))


def test_voting_parallel_matches_serial_with_full_topk():
    # top_k >= F makes the election lossless -> exact parity with serial.
    bins, g, h = _make_data(seed=4)
    ts, _, _ = _grow_serial(bins, g, h, PARAMS, 16)
    tp, _, _ = _grow_parallel("voting", bins, g, h, PARAMS, 16, top_k=6)
    _assert_tree_equal(ts, tp)


def test_voting_election_uses_per_feature_max_not_sum():
    """GlobalVoting keeps the per-feature MAX of count-weighted local gains
    over machines, then top-k (voting_parallel_tree_learner.cpp:157-186).

    Planted data, 8 shards x 64 rows, 2 features, top_k=1:
      * feature 0: mild gain 16 on EVERY shard (sum rule would score
        8*16=128 and elect it),
      * feature 1: gain 64 on shard 0 only, constant elsewhere (max rule
        scores it 64 > 16 and elects it).
    All shard leaf counts equal mean_num_data, so the weights are the raw
    local gains.  The root split feature therefore reveals the election
    rule: max -> 1, sum -> 0 (0 is also the serial/global-gain choice)."""
    n, per = 512, 64
    g = np.zeros(n, np.float32)
    f0 = np.zeros(n, np.int32)
    f1 = np.zeros(n, np.int32)
    for s in range(8):
        lo = s * per
        # f0: bins 0/1 halves; 24-of-32 label agreement -> G=+-16, gain 16
        f0[lo:lo + 32] = 0
        f0[lo + 32:lo + per] = 1
        g[lo:lo + 24] = -1.0
        g[lo + 24:lo + 32] = 1.0
        g[lo + 32:lo + 56] = 1.0
        g[lo + 56:lo + per] = -1.0
    # f1: perfect separation on shard 0 (gain 64), constant elsewhere
    f1[:per] = (g[:per] > 0).astype(np.int32)
    bins = np.stack([f0, f1])
    h = np.ones(n, np.float32)
    params = GrowParams(num_leaves=2, max_bin=16, min_data_in_leaf=5,
                        min_sum_hessian_in_leaf=1e-3)
    ts, _, _ = _grow_serial(bins, g, h, params, 16)
    assert int(ts.split_feature[0]) == 0  # global gain prefers feature 0
    tp, _, _ = _grow_parallel("voting", bins, g, h, params, 16, top_k=1)
    assert int(tp.num_leaves) == 2
    assert int(tp.split_feature[0]) == 1  # max-rule election won


def test_voting_parallel_small_topk_reasonable():
    # With top_k < F voting is approximate; the tree must still be a valid
    # gainful tree (num_leaves grown, finite leaf values).
    bins, g, h = _make_data(seed=5, f=12)
    tp, leaf_p, delta_p = _grow_parallel("voting", bins, g, h, PARAMS, 16,
                                         top_k=3)
    assert int(tp.num_leaves) > 1
    assert np.isfinite(np.asarray(tp.leaf_value)).all()
    assert np.isfinite(np.asarray(delta_p)).all()


@pytest.mark.parametrize("learner", ["data", "feature", "voting"])
def test_end_to_end_distributed_training_matches_serial(learner):
    """Full GBDT training with a distributed tree_learner produces the same
    model (all split decisions + leaf values) as serial training."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.models.gbdt import GBDT

    rng = np.random.RandomState(7)
    X = rng.normal(size=(600, 8))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] + 0.1 * rng.normal(size=600) > 0)
    base = {"objective": "binary", "num_leaves": 8, "max_bin": 32,
            "min_data_in_leaf": 10, "min_sum_hessian_in_leaf": 1e-3,
            "num_iterations": 5, "top_k": 8}
    ds = BinnedDataset.from_matrix(X, y.astype(np.float32), max_bin=32,
                                   min_data_in_leaf=10)
    gb_s = GBDT(Config(dict(base)), ds)
    gb_s.train(5)
    gb_p = GBDT(Config(dict(base, tree_learner=learner, num_machines=8)), ds)
    gb_p.train(5)
    assert len(gb_s.models) == len(gb_p.models)
    for ts, tp in zip(gb_s.models, gb_p.models):
        assert ts.num_leaves == tp.num_leaves
        np.testing.assert_array_equal(ts.split_feature, tp.split_feature)
        np.testing.assert_allclose(ts.leaf_value, tp.leaf_value,
                                   rtol=2e-4, atol=2e-6)


def test_mesh_size_2_and_4():
    bins, g, h = _make_data(seed=6)
    ts, _, _ = _grow_serial(bins, g, h, PARAMS, 16)
    for n_dev in (2, 4):
        tp, _, _ = _grow_parallel("data", bins, g, h, PARAMS, 16, n_dev=n_dev)
        _assert_tree_equal(ts, tp)
