"""C API parity: the reference tests/c_api_test/test.py flow through the
compiled lib_lightgbm_tpu.so (ctypes, exactly like a C caller).

Reference: include/LightGBM/c_api.h:37-717, src/c_api.cpp, and
tests/c_api_test/test.py (create-from-file / -mat / -CSR / -CSC, save
binary, booster create, 30-iteration train loop with GetEval, save
model, reload, PredictForMat / PredictForFile, PushRows streaming).

The library is driven from a SUBPROCESS (tests/c_api_worker.py), not
in-process: the cffi embedding boots an embedded CPython on its first
call, and that native boot spins forever when the host process already
holds an initialized jax — which pytest's conftest guarantees.  This was
ROADMAP item 6: the in-process version of this file hung the whole
tier-1 suite at its timeout.  The pytest process only *builds* the
shared library (compilation never touches the embedded runtime); one
worker subprocess then runs every scenario against it — one clean boot,
one set of jit compiles — and writes per-scenario verdicts this module
asserts on.

The in-process surface (lightgbm_tpu.capi.impl) stays covered through
the library: the embedded init code dispatches every LGBM_* symbol to
impl.py.
"""

import json
import os
import subprocess
import sys

import pytest

# referenced by the scenarios that need the read-only /root/reference
# mount; conftest skips those tests per-item when it is absent, and the
# worker double-checks so the module fixture stays runnable either way
BINARY_TRAIN = "/root/reference/examples/binary_classification/binary.train"
BINARY_TEST = "/root/reference/examples/binary_classification/binary.test"

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "c_api_worker.py")

# a clean process completes the embedded boot + full train flow in well
# under a minute on this box; the cap exists so a reintroduced boot hang
# fails THIS file instead of eating the tier-1 suite's whole budget
_WORKER_TIMEOUT_S = 420


@pytest.fixture(scope="module")
def capi_results(tmp_path_factory):
    """Build the library in-process (safe: compile only, no load), run
    every scenario in one clean subprocess, return its verdicts."""
    from lightgbm_tpu.capi import build_library
    lib_path = build_library()
    out = tmp_path_factory.mktemp("capi") / "results.json"
    data_dir = tmp_path_factory.mktemp("capi_data")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, _WORKER, lib_path, str(out), str(data_dir)],
            timeout=_WORKER_TIMEOUT_S, capture_output=True, text=True,
            env=env)
    except subprocess.TimeoutExpired:
        pytest.fail(f"c_api worker exceeded {_WORKER_TIMEOUT_S}s — the "
                    f"embedded-interpreter boot hang is back?")
    assert proc.returncode == 0, (proc.stdout[-1000:], proc.stderr[-2000:])
    return json.loads(out.read_text())


def _scenario(results, name):
    rec = results[name]
    if rec["status"] == "skip":
        pytest.skip(rec.get("detail", "skipped by worker"))
    assert rec["status"] == "ok", rec.get("detail", "")


def test_error_reporting(capi_results):
    """LGBM_GetLastError carries the failure of a bad CreateFromFile."""
    _scenario(capi_results, "error_reporting")


def test_push_rows_flow(capi_results):
    """CreateFromSampledColumn + PushRows streaming construction
    (c_api.cpp:341-415) produces the same bins as CreateFromMat."""
    _scenario(capi_results, "push_rows")


def test_dataset_file_mat_csr_csc(capi_results):
    """Dataset creation from BINARY_TRAIN file / mat / CSR / CSC plus
    save-binary round trip."""
    _scenario(capi_results, "dataset_io")


def test_booster_train_save_predict(capi_results):
    """30-iteration train loop on BINARY_TRAIN with GetEval, model
    save/reload, PredictForMat/ForFile, leaf-index predict, and parity
    against the Python Booster surface."""
    _scenario(capi_results, "train_predict")
