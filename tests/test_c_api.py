"""C API parity: the reference tests/c_api_test/test.py flow, driven both
through the compiled lib_lightgbm_tpu.so (ctypes, exactly like a C caller)
and in-process against capi.impl.

Reference: include/LightGBM/c_api.h:37-717, src/c_api.cpp, and
tests/c_api_test/test.py (the flow replicated here: create-from-file /
-mat / -CSR / -CSC, save binary, booster create, 30-iteration train loop
with GetEval, save model, reload, PredictForMat / PredictForFile)."""

import ctypes
import os

import numpy as np
import pytest

BINARY_TRAIN = "/root/reference/examples/binary_classification/binary.train"
BINARY_TEST = "/root/reference/examples/binary_classification/binary.test"

dtype_float32 = 0
dtype_float64 = 1
dtype_int32 = 2
dtype_int64 = 3


def _load_tsv(path):
    d = np.loadtxt(path)
    return d[:, 1:], d[:, 0].astype(np.float32)


@pytest.fixture(scope="module")
def LIB():
    from lightgbm_tpu.capi import build_library
    path = build_library()
    lib = ctypes.cdll.LoadLibrary(path)
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    return lib


def c_str(s):
    return ctypes.c_char_p(s.encode("ascii"))


def _check(lib, ret):
    assert ret == 0, lib.LGBM_GetLastError()


def _mat_handle(lib, X, y, params, reference=None):
    X = np.ascontiguousarray(X, np.float64)
    handle = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), dtype_float64,
        ctypes.c_int32(X.shape[0]), ctypes.c_int32(X.shape[1]), 1,
        c_str(params), reference, ctypes.byref(handle)))
    if y is not None:
        y = np.ascontiguousarray(y, np.float32)
        _check(lib, lib.LGBM_DatasetSetField(
            handle, c_str("label"), y.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(len(y)), dtype_float32))
    return handle


def test_dataset_file_mat_csr_csc(LIB, tmp_path):
    # from file
    train = ctypes.c_void_p()
    _check(LIB, LIB.LGBM_DatasetCreateFromFile(
        c_str(BINARY_TRAIN), c_str("max_bin=15"), None, ctypes.byref(train)))
    num_data = ctypes.c_int(0)
    num_feat = ctypes.c_int(0)
    _check(LIB, LIB.LGBM_DatasetGetNumData(train, ctypes.byref(num_data)))
    _check(LIB, LIB.LGBM_DatasetGetNumFeature(train, ctypes.byref(num_feat)))
    assert num_data.value == 7000 and num_feat.value == 28

    X, y = _load_tsv(BINARY_TEST)

    # from mat, aligned to train's mappers
    test_h = _mat_handle(LIB, X, y, "max_bin=15", train)
    _check(LIB, LIB.LGBM_DatasetGetNumData(test_h, ctypes.byref(num_data)))
    assert num_data.value == 500
    _check(LIB, LIB.LGBM_DatasetFree(test_h))

    # from CSR
    from scipy import sparse
    csr = sparse.csr_matrix(X)
    h = ctypes.c_void_p()
    _check(LIB, LIB.LGBM_DatasetCreateFromCSR(
        csr.indptr.ctypes.data_as(ctypes.c_void_p), dtype_int32,
        csr.indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        csr.data.ctypes.data_as(ctypes.c_void_p), dtype_float64,
        ctypes.c_int64(len(csr.indptr)), ctypes.c_int64(csr.nnz),
        ctypes.c_int64(X.shape[1]), c_str("max_bin=15"), train,
        ctypes.byref(h)))
    _check(LIB, LIB.LGBM_DatasetGetNumData(h, ctypes.byref(num_data)))
    assert num_data.value == 500
    _check(LIB, LIB.LGBM_DatasetFree(h))

    # from CSC
    csc = sparse.csc_matrix(X)
    _check(LIB, LIB.LGBM_DatasetCreateFromCSC(
        csc.indptr.ctypes.data_as(ctypes.c_void_p), dtype_int32,
        csc.indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        csc.data.ctypes.data_as(ctypes.c_void_p), dtype_float64,
        ctypes.c_int64(len(csc.indptr)), ctypes.c_int64(csc.nnz),
        ctypes.c_int64(X.shape[0]), c_str("max_bin=15"), train,
        ctypes.byref(h)))
    _check(LIB, LIB.LGBM_DatasetGetNumData(h, ctypes.byref(num_data)))
    assert num_data.value == 500
    _check(LIB, LIB.LGBM_DatasetFree(h))

    # save binary, reload
    bin_path = str(tmp_path / "train.binary.bin")
    _check(LIB, LIB.LGBM_DatasetSaveBinary(train, c_str(bin_path)))
    _check(LIB, LIB.LGBM_DatasetFree(train))
    _check(LIB, LIB.LGBM_DatasetCreateFromFile(
        c_str(bin_path), c_str("max_bin=15"), None, ctypes.byref(train)))
    _check(LIB, LIB.LGBM_DatasetGetNumData(train, ctypes.byref(num_data)))
    assert num_data.value == 7000
    _check(LIB, LIB.LGBM_DatasetFree(train))


def test_booster_train_save_predict(LIB, tmp_path):
    Xtr, ytr = _load_tsv(BINARY_TRAIN)
    Xte, yte = _load_tsv(BINARY_TEST)
    train = _mat_handle(LIB, Xtr, ytr, "max_bin=63")
    test = _mat_handle(LIB, Xte, yte, "max_bin=63", train)

    booster = ctypes.c_void_p()
    _check(LIB, LIB.LGBM_BoosterCreate(
        train, c_str("app=binary metric=auc num_leaves=15 verbose=-1"),
        ctypes.byref(booster)))
    _check(LIB, LIB.LGBM_BoosterAddValidData(booster, test))

    n_classes = ctypes.c_int(0)
    _check(LIB, LIB.LGBM_BoosterGetNumClasses(booster, ctypes.byref(n_classes)))
    assert n_classes.value == 1

    is_finished = ctypes.c_int(0)
    aucs = []
    for _ in range(30):
        _check(LIB, LIB.LGBM_BoosterUpdateOneIter(booster,
                                                  ctypes.byref(is_finished)))
        result = np.zeros(1, dtype=np.float64)
        out_len = ctypes.c_int(0)
        _check(LIB, LIB.LGBM_BoosterGetEval(
            booster, 1, ctypes.byref(out_len),
            result.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        assert out_len.value == 1
        aucs.append(result[0])
    assert aucs[-1] > 0.80 and aucs[-1] >= aucs[0]

    it = ctypes.c_int(0)
    _check(LIB, LIB.LGBM_BoosterGetCurrentIteration(booster, ctypes.byref(it)))
    assert it.value == 30

    # eval names
    cnt = ctypes.c_int(0)
    _check(LIB, LIB.LGBM_BoosterGetEvalCounts(booster, ctypes.byref(cnt)))
    assert cnt.value == 1
    bufs = [ctypes.create_string_buffer(255)]
    arr = (ctypes.c_char_p * 1)(*map(ctypes.addressof, bufs))
    _check(LIB, LIB.LGBM_BoosterGetEvalNames(booster, ctypes.byref(cnt), arr))
    assert bufs[0].value == b"auc"

    model_path = str(tmp_path / "model.txt")
    _check(LIB, LIB.LGBM_BoosterSaveModel(booster, -1, c_str(model_path)))
    _check(LIB, LIB.LGBM_BoosterFree(booster))
    _check(LIB, LIB.LGBM_DatasetFree(train))
    _check(LIB, LIB.LGBM_DatasetFree(test))

    # reload + predict
    booster2 = ctypes.c_void_p()
    n_iters = ctypes.c_int(0)
    _check(LIB, LIB.LGBM_BoosterCreateFromModelfile(
        c_str(model_path), ctypes.byref(n_iters), ctypes.byref(booster2)))
    assert n_iters.value == 30

    flat = np.ascontiguousarray(Xte, np.float64)
    preb = np.zeros(Xte.shape[0], dtype=np.float64)
    num_preb = ctypes.c_int64(0)
    _check(LIB, LIB.LGBM_BoosterPredictForMat(
        booster2, flat.ctypes.data_as(ctypes.c_void_p), dtype_float64,
        ctypes.c_int32(Xte.shape[0]), ctypes.c_int32(Xte.shape[1]), 1,
        0, -1, ctypes.byref(num_preb),
        preb.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert num_preb.value == Xte.shape[0]
    assert 0.0 <= preb.min() and preb.max() <= 1.0

    # parity vs the python surface on the same model
    import lightgbm_tpu as lgb
    pyb = lgb.Booster(model_file=model_path)
    np.testing.assert_allclose(preb, pyb.predict(Xte), rtol=1e-10)

    # file predict
    out_path = str(tmp_path / "preb.txt")
    _check(LIB, LIB.LGBM_BoosterPredictForFile(
        booster2, c_str(BINARY_TEST), 0, 0, -1, c_str(out_path)))
    file_pred = np.loadtxt(out_path)
    assert file_pred.shape[0] == Xte.shape[0]
    np.testing.assert_allclose(file_pred, preb, atol=5e-6)

    # leaf index predictions
    n_pred = ctypes.c_int64(0)
    _check(LIB, LIB.LGBM_BoosterCalcNumPredict(booster2, 5, 2, -1,
                                               ctypes.byref(n_pred)))
    leaves = np.zeros(int(n_pred.value), dtype=np.float64)
    _check(LIB, LIB.LGBM_BoosterPredictForMat(
        booster2, flat.ctypes.data_as(ctypes.c_void_p), dtype_float64,
        ctypes.c_int32(5), ctypes.c_int32(Xte.shape[1]), 1,
        2, -1, ctypes.byref(num_preb),
        leaves.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert num_preb.value == 5 * 30
    assert np.all(leaves >= 0) and np.all(leaves < 15)
    _check(LIB, LIB.LGBM_BoosterFree(booster2))


def test_error_reporting(LIB):
    handle = ctypes.c_void_p()
    ret = LIB.LGBM_DatasetCreateFromFile(
        c_str("/nonexistent/file.txt"), c_str(""), None, ctypes.byref(handle))
    assert ret == -1
    assert b"" != LIB.LGBM_GetLastError()


def test_push_rows_flow(LIB):
    """CreateFromSampledColumn + PushRows streaming construction
    (c_api.cpp:341-415) must produce the same bins as CreateFromMat."""
    rng = np.random.RandomState(7)
    X = rng.normal(size=(400, 3)).astype(np.float64)
    y = (X[:, 0] > 0).astype(np.float32)

    cols = [np.ascontiguousarray(X[:, i]) for i in range(3)]
    col_ptrs = (ctypes.POINTER(ctypes.c_double) * 3)(
        *[c.ctypes.data_as(ctypes.POINTER(ctypes.c_double)) for c in cols])
    idxs = [np.arange(400, dtype=np.int32) for _ in range(3)]
    idx_ptrs = (ctypes.POINTER(ctypes.c_int) * 3)(
        *[i.ctypes.data_as(ctypes.POINTER(ctypes.c_int)) for i in idxs])
    num_per_col = (ctypes.c_int * 3)(400, 400, 400)

    handle = ctypes.c_void_p()
    _check(LIB, LIB.LGBM_DatasetCreateFromSampledColumn(
        col_ptrs, idx_ptrs, ctypes.c_int32(3), num_per_col,
        ctypes.c_int32(400), ctypes.c_int32(400),
        c_str("max_bin=31 min_data_in_leaf=5"), ctypes.byref(handle)))
    # push in two chunks
    for start, stop in ((0, 250), (250, 400)):
        chunk = np.ascontiguousarray(X[start:stop])
        _check(LIB, LIB.LGBM_DatasetPushRows(
            handle, chunk.ctypes.data_as(ctypes.c_void_p), dtype_float64,
            ctypes.c_int32(stop - start), ctypes.c_int32(3),
            ctypes.c_int32(start)))
    _check(LIB, LIB.LGBM_DatasetSetField(
        handle, c_str("label"), y.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int(len(y)), dtype_float32))

    direct = _mat_handle(LIB, X, y, "max_bin=31 min_data_in_leaf=5")

    # verify by training boosters on both and comparing one iteration
    b1 = ctypes.c_void_p()
    b2 = ctypes.c_void_p()
    params = "app=binary num_leaves=7 verbose=-1 min_data_in_leaf=5"
    _check(LIB, LIB.LGBM_BoosterCreate(handle, c_str(params),
                                       ctypes.byref(b1)))
    _check(LIB, LIB.LGBM_BoosterCreate(direct, c_str(params),
                                       ctypes.byref(b2)))
    fin = ctypes.c_int(0)
    for b in (b1, b2):
        _check(LIB, LIB.LGBM_BoosterUpdateOneIter(b, ctypes.byref(fin)))
    out = []
    for b in (b1, b2):
        pred = np.zeros(400, dtype=np.float64)
        n = ctypes.c_int64(0)
        _check(LIB, LIB.LGBM_BoosterPredictForMat(
            b, X.ctypes.data_as(ctypes.c_void_p), dtype_float64,
            ctypes.c_int32(400), ctypes.c_int32(3), 1, 1, -1,
            ctypes.byref(n),
            pred.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        out.append(pred)
    np.testing.assert_allclose(out[0], out[1], rtol=1e-12)
    _check(LIB, LIB.LGBM_BoosterFree(b1))
    _check(LIB, LIB.LGBM_BoosterFree(b2))
    _check(LIB, LIB.LGBM_DatasetFree(handle))
    _check(LIB, LIB.LGBM_DatasetFree(direct))
