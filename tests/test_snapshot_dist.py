"""Coordinated snapshots under multihost (snapshot.py): the rank-0-writes
discipline (no concurrent-writer races into one snapshot_dir), the
``world`` block, the corrupt-file skip accounting, and the cross-rank
resume consensus — simulated ranks here; the real 2-process path is
pinned by tests/test_dist_chaos.py."""

import glob
import os

import numpy as np
import pytest

from lightgbm_tpu import Dataset, LightGBMError, obs
from lightgbm_tpu import train as lgb_train
from lightgbm_tpu.snapshot import (coordinated_resume, is_snapshot_writer,
                                   list_snapshots, load_latest_snapshot,
                                   read_snapshot, replicated_state_digest,
                                   snapshot_path)
from lightgbm_tpu.testing import faults

pytestmark = pytest.mark.faults

PARAMS = {"objective": "binary", "metric": ["binary_logloss"],
          "num_leaves": 5, "min_data_in_leaf": 5, "max_bin": 31,
          "verbose": -1}


def _train(rounds=3):
    rng = np.random.RandomState(5)
    X = rng.normal(size=(150, 4))
    y = (X[:, 0] + 0.4 * X[:, 1] > 0).astype(np.float64)
    return lgb_train(dict(PARAMS), Dataset(X, label=y),
                     num_boost_round=rounds, verbose_eval=False)


def _fake_world(monkeypatch, rank, world):
    import lightgbm_tpu.parallel.multihost as mh
    monkeypatch.setattr(mh, "process_rank_world", lambda: (rank, world))


def _canned_allgather(monkeypatch, responses):
    """Serve scripted [world, ...] gathers in call order; later calls
    echo (all ranks agree with this one)."""
    import lightgbm_tpu.parallel.comm as comm
    canned = list(responses)

    def fake(x):
        if canned:
            return np.asarray(canned.pop(0))
        x = np.asarray(x)
        return np.stack([x, x])
    monkeypatch.setattr(comm, "allgather_host_array", fake)


# ---------------------------------------------------------------------------
# rank-0-writes discipline


def test_non_zero_rank_never_writes(monkeypatch, tmp_path):
    bst = _train()
    _fake_world(monkeypatch, 1, 2)
    assert not is_snapshot_writer()
    assert bst.save_snapshot(str(tmp_path)) is None
    # nothing raced into the directory: no snapshot, no torn temp file
    assert os.listdir(tmp_path) == []


def test_non_zero_rank_skips_even_a_torn_write(monkeypatch, tmp_path):
    # the discipline gates BEFORE the file layer: a write that would
    # have torn never even creates the .tmp a concurrent prune could eat
    bst = _train()
    _fake_world(monkeypatch, 0, 2)
    first = bst.save_snapshot(str(tmp_path))
    assert first and read_snapshot(first) is not None
    _fake_world(monkeypatch, 1, 2)
    with faults.torn_snapshot_write(after_bytes=16) as stats:
        assert bst.save_snapshot(str(tmp_path), rounds_done=9) is None
    assert stats["torn"] == []
    assert glob.glob(str(tmp_path / "*.tmp")) == []
    # and the rank-0 file is untouched
    assert [p for _, p in list_snapshots(str(tmp_path))] == [first]


def test_rank0_torn_write_falls_back_to_previous(monkeypatch, tmp_path):
    bst = _train()
    _fake_world(monkeypatch, 0, 2)
    first = bst.save_snapshot(str(tmp_path))
    with faults.torn_snapshot_write(after_bytes=16):
        with pytest.raises(faults.InjectedCrash):
            bst.save_snapshot(str(tmp_path), rounds_done=9)
    found = load_latest_snapshot(str(tmp_path))
    assert found is not None and found[0] == first


def test_world_block_recorded(monkeypatch, tmp_path):
    bst = _train()
    _fake_world(monkeypatch, 0, 2)
    path = bst.save_snapshot(str(tmp_path))
    state = read_snapshot(path)
    w = state["world"]
    assert w["num_processes"] == 2 and w["rank"] == 0
    assert len(w["digest"]) == 64
    # the digest is the desync detector's field fingerprint, cheap and
    # reproducible from the live state for cross-rank log comparison
    assert w["digest"] == replicated_state_digest(bst._booster)


# ---------------------------------------------------------------------------
# corrupt-file skip accounting


def test_corrupt_skip_counts_and_names_file(tmp_path, capfd):
    bst = _train()
    good = bst.save_snapshot(str(tmp_path), rounds_done=2)
    bad = bst.save_snapshot(str(tmp_path), rounds_done=3)
    faults.flip_byte(bad)
    before = obs.get_counter("snapshot_corrupt_skipped_total")
    found = load_latest_snapshot(str(tmp_path))
    assert found is not None and found[0] == good
    assert obs.get_counter("snapshot_corrupt_skipped_total") == before + 1
    assert os.path.basename(bad) in capfd.readouterr().err


# ---------------------------------------------------------------------------
# resume consensus (simulated 2-rank gathers)


def _snapshot_dir(tmp_path, monkeypatch, rounds=(2, 3)):
    bst = _train()
    _fake_world(monkeypatch, 0, 2)
    for r in rounds:
        bst.save_snapshot(str(tmp_path), rounds_done=r)
    return bst


def test_consensus_agreement(monkeypatch, tmp_path):
    _snapshot_dir(tmp_path, monkeypatch)
    _canned_allgather(monkeypatch, [np.int64([3, 3])])
    path, state = coordinated_resume(str(tmp_path))
    assert path == snapshot_path(str(tmp_path), 3)
    assert state["rounds_done"] == 3


def test_consensus_takes_minimum_common_iteration(monkeypatch, tmp_path):
    # the other rank's disk only replicated up to round 2: the pod must
    # agree on 2, not this rank's newer 3
    _snapshot_dir(tmp_path, monkeypatch)
    _canned_allgather(monkeypatch, [np.int64([3, 2])])
    path, state = coordinated_resume(str(tmp_path))
    assert state["rounds_done"] == 2
    assert path == snapshot_path(str(tmp_path), 2)


def test_consensus_fresh_start_when_any_rank_has_none(monkeypatch,
                                                      tmp_path, capfd):
    _snapshot_dir(tmp_path, monkeypatch)
    _canned_allgather(monkeypatch, [np.int64([3, -1])])
    assert coordinated_resume(str(tmp_path)) is None
    assert "starts FRESH" in capfd.readouterr().err


def test_consensus_refuses_diverged_replicas(monkeypatch, tmp_path):
    _snapshot_dir(tmp_path, monkeypatch)
    _canned_allgather(monkeypatch, [
        np.int64([3, 3]),
        np.uint64([1, 2]),           # ranks loaded different bytes
    ])
    with pytest.raises(LightGBMError, match="differs across ranks"):
        coordinated_resume(str(tmp_path))


def test_consensus_refuses_world_size_mismatch(monkeypatch, tmp_path):
    bst = _train()
    _fake_world(monkeypatch, 0, 4)      # written by a 4-process pod
    bst.save_snapshot(str(tmp_path), rounds_done=2)
    _fake_world(monkeypatch, 0, 2)      # restarted with 2
    _canned_allgather(monkeypatch, [np.int64([2, 2])])
    with pytest.raises(LightGBMError, match="4-process"):
        coordinated_resume(str(tmp_path))


def test_consensus_single_process_is_plain_load(tmp_path):
    bst = _train()
    path = bst.save_snapshot(str(tmp_path))
    found = coordinated_resume(str(tmp_path))
    assert found is not None and found[0] == path
