"""Worker for tests/test_multiprocess.py: one of two cooperating local
processes training ``tree_learner=data`` over a real ``jax.distributed``
runtime (the reference's demonstrated bar: two local socket-linked
processes, examples/parallel_learning/ + linkers_socket.cpp:20-61).

Each process brings up the runtime from the SAME machine-list file
(parallel/multihost.py), trains the distributed model (cross-process
psum/all_gather over gloo), trains a serial model on the same data, and
asserts exact structural parity before writing its model dump."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np  # noqa: E402


def main() -> None:
    mlist_path, out_path = sys.argv[1], sys.argv[2]
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.parallel.multihost import maybe_initialize_distributed

    base = {"objective": "binary", "num_leaves": 8, "max_bin": 32,
            "min_data_in_leaf": 10, "min_sum_hessian_in_leaf": 1e-3,
            "num_iterations": 4}
    dist_cfg = Config(dict(base, tree_learner="data", num_machines=2,
                           machine_list_file=mlist_path))
    assert maybe_initialize_distributed(dist_cfg), \
        "distributed bring-up did not run"
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 2, jax.devices()

    rng = np.random.RandomState(7)
    X = rng.normal(size=(600, 8))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
         + 0.1 * rng.normal(size=600) > 0).astype(np.float32)
    ds = BinnedDataset.from_matrix(X, y, max_bin=32, min_data_in_leaf=10)

    gb_p = GBDT(dist_cfg, ds)
    gb_p.train(4)
    gb_s = GBDT(Config(dict(base)), ds)
    gb_s.train(4)

    assert len(gb_p.models) == len(gb_s.models) == 4
    for ts, tp in zip(gb_s.models, gb_p.models):
        assert ts.num_leaves == tp.num_leaves
        np.testing.assert_array_equal(ts.split_feature, tp.split_feature)
        np.testing.assert_array_equal(ts.threshold_in_bin, tp.threshold_in_bin)
        np.testing.assert_allclose(ts.leaf_value, tp.leaf_value,
                                   rtol=2e-4, atol=2e-6)

    with open(out_path, "w") as fh:
        fh.write("PARITY_OK\n")
        fh.write(gb_p.save_model_to_string())


if __name__ == "__main__":
    main()
