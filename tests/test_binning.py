import numpy as np
import pytest

from lightgbm_tpu.io.binning import BinMapper, CATEGORICAL, NUMERICAL


def test_distinct_value_fast_path():
    # 4 distinct values, plenty of max_bin: each distinct value its own bin,
    # boundaries at midpoints (bin.cpp:116-131).
    vals = np.repeat([1.0, 2.0, 3.0, 4.0], 10)
    m = BinMapper().find_bin(vals, total_sample_cnt=40, max_bin=255,
                             min_data_in_bin=1, min_split_data=1)
    assert m.num_bin == 4
    np.testing.assert_allclose(m.bin_upper_bound[:-1], [1.5, 2.5, 3.5])
    assert np.isinf(m.bin_upper_bound[-1])
    assert not m.is_trivial
    bins = m.value_to_bin([0.5, 1.0, 1.6, 2.5, 3.9, 100.0])
    np.testing.assert_array_equal(bins, [0, 0, 1, 1, 3, 3])


def test_zero_handling_inserted():
    # zeros implied by total_sample_cnt > len(values) get their own distinct
    # value spliced into sorted position (bin.cpp:83-110).
    vals = np.array([1.0, 1.0, 2.0, 2.0])
    m = BinMapper().find_bin(vals, total_sample_cnt=10, max_bin=255,
                             min_data_in_bin=1, min_split_data=1)
    # distinct = [0, 1, 2]
    assert m.num_bin == 3
    assert m.value_to_bin(0.0) == 0
    assert m.default_bin == 0


def test_zero_between_negative_positive():
    vals = np.array([-2.0, -1.0, 1.0, 2.0])
    m = BinMapper().find_bin(vals, total_sample_cnt=8, max_bin=255,
                             min_data_in_bin=1, min_split_data=1)
    # distinct = [-2, -1, 0, 1, 2]
    assert m.num_bin == 5
    assert m.default_bin == m.value_to_bin(0.0) == 2


def test_greedy_equal_count():
    rng = np.random.RandomState(0)
    vals = rng.uniform(0.001, 1.0, size=10000)
    m = BinMapper().find_bin(vals, total_sample_cnt=10000, max_bin=16,
                             min_data_in_bin=1, min_split_data=1)
    assert 2 <= m.num_bin <= 16
    bins = m.value_to_bin(vals)
    counts = np.bincount(bins, minlength=m.num_bin)
    # roughly equal-count: no bin is more than 3x the mean
    assert counts.max() < 3 * counts.mean()
    # bins are monotone in value
    order = np.argsort(vals)
    assert np.all(np.diff(bins[order]) >= 0)


def test_min_data_in_bin_merges():
    vals = np.concatenate([np.repeat(1.0, 100), np.repeat(2.0, 2),
                           np.repeat(3.0, 100)])
    m = BinMapper().find_bin(vals, total_sample_cnt=202, max_bin=255,
                             min_data_in_bin=5, min_split_data=1)
    # value 2.0 alone has < 5 samples, so it merges with 3.0's bin
    assert m.num_bin == 2
    assert m.value_to_bin(2.0) == m.value_to_bin(3.0) == 1


def test_trivial_single_value():
    vals = np.repeat(5.0, 50)
    m = BinMapper().find_bin(vals, total_sample_cnt=50, max_bin=255,
                             min_data_in_bin=1, min_split_data=1)
    assert m.is_trivial


def test_trivial_filter_min_split_data():
    # 3 rows total but min_split_data=10: no usable split (bin.cpp:47-69).
    vals = np.array([1.0, 2.0, 3.0])
    m = BinMapper().find_bin(vals, total_sample_cnt=3, max_bin=255,
                             min_data_in_bin=1, min_split_data=10)
    assert m.is_trivial


def test_categorical_basic():
    vals = np.repeat([3.0, 7.0, 7.0, 9.0], [50, 30, 70, 20])
    m = BinMapper().find_bin(vals, total_sample_cnt=170, max_bin=255,
                             min_data_in_bin=1, min_split_data=1,
                             bin_type=CATEGORICAL)
    # sorted by count desc: 7 (100), 3 (50), 9 (20)
    assert m.bin_2_categorical[0] == 7
    assert m.value_to_bin(7.0) == 0
    assert m.value_to_bin(3.0) == 1
    assert m.value_to_bin(9.0) == 2
    # unseen category maps to last bin (bin.h:400-406)
    assert m.value_to_bin(12345.0) == m.num_bin - 1


def test_roundtrip_state():
    vals = np.random.RandomState(1).normal(size=500)
    m = BinMapper().find_bin(vals, total_sample_cnt=600, max_bin=32,
                             min_data_in_bin=3, min_split_data=2)
    m2 = BinMapper.from_state(m.to_state())
    x = np.linspace(-3, 3, 101)
    np.testing.assert_array_equal(m.value_to_bin(x), m2.value_to_bin(x))
    assert m2.default_bin == m.default_bin


def test_bin_to_value_upper_bound():
    vals = np.repeat([1.0, 2.0, 4.0], 10)
    m = BinMapper().find_bin(vals, total_sample_cnt=30, max_bin=255,
                             min_data_in_bin=1, min_split_data=1)
    assert m.bin_to_value(0) == pytest.approx(1.5)
    assert m.bin_to_value(1) == pytest.approx(3.0)
