"""Lambdarank size-class bucketing (objective/__init__.py): per-class
padding must not change the math — gradients are identical to padding
every query to the global maximum, and per-query lambda sums are zero
(pairwise antisymmetry, rank_objective.hpp:83-137)."""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import Metadata
from lightgbm_tpu.objective import LambdarankNDCG


def _make(seed=0):
    rng = np.random.RandomState(seed)
    # heavily skewed query sizes: 17 small, one big (pad classes 4x apart)
    sizes = [5, 9, 17, 33] * 4 + [210]
    n = sum(sizes)
    label = rng.randint(0, 4, size=n).astype(np.float32)
    md = Metadata(n)
    md.set_label(label)
    md.set_query(np.asarray(sizes))
    score = rng.normal(size=(1, n)).astype(np.float32)
    return md, n, score


def test_bucketing_matches_single_class_padding(monkeypatch):
    md, n, score = _make()
    cfg = Config({"objective": "lambdarank"})

    obj = LambdarankNDCG(cfg)
    obj.init(md, n)
    assert len(obj.query_classes) > 1    # bucketing actually happened
    g1, h1 = obj.gradients(score)

    # force one global class: re-pad every bucket to the same width
    big = 256
    obj3 = LambdarankNDCG(cfg)
    obj3.init(md, n)
    import jax.numpy as jnp
    merged_idx, merged_valid, merged_label, merged_inv = [], [], [], []
    for cls in obj3.query_classes:
        P = cls["P"]
        pad = big - P
        merged_idx.append(np.pad(np.asarray(cls["doc_idx"]),
                                 ((0, 0), (0, pad))))
        merged_valid.append(np.pad(np.asarray(cls["doc_valid"]),
                                   ((0, 0), (0, pad))))
        merged_label.append(np.pad(np.asarray(cls["label"]),
                                   ((0, 0), (0, pad))))
        merged_inv.append(np.asarray(cls["inv_max_dcg"]))
    obj3.query_classes = [{
        "P": big,
        "doc_idx": jnp.asarray(np.concatenate(merged_idx)),
        "doc_valid": jnp.asarray(np.concatenate(merged_valid)),
        "label": jnp.asarray(np.concatenate(merged_label)),
        "inv_max_dcg": jnp.asarray(np.concatenate(merged_inv)),
    }]
    g2, h2 = obj3.gradients(score)

    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h2),
                               rtol=1e-5, atol=1e-6)


def test_per_query_lambda_sum_is_zero():
    md, n, score = _make(seed=3)
    cfg = Config({"objective": "lambdarank"})
    obj = LambdarankNDCG(cfg)
    obj.init(md, n)
    g, h = obj.gradients(score)
    g = np.asarray(g)[0]
    h = np.asarray(h)[0]
    qb = np.asarray(md.query_boundaries)
    for q in range(len(qb) - 1):
        seg = g[qb[q]:qb[q + 1]]
        np.testing.assert_allclose(seg.sum(), 0.0, atol=1e-4)
    assert np.all(h >= 0)
    assert np.isfinite(g).all() and np.isfinite(h).all()


def test_rank_metrics_vectorized_match_naive_loop():
    """NDCG@k / MAP@k: the bucket-vectorized eval (round-3, replacing the
    per-query Python loop of round-2 VERDICT weak #7) must match a naive
    per-query reference on ragged weighted queries, including all-zero-
    relevance queries (NDCG 1.0 per the reference) and k > query size."""
    import numpy as np
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import Metadata
    from lightgbm_tpu.metric import create_metric

    rng = np.random.RandomState(5)
    sizes = rng.randint(1, 40, size=120)
    n = int(sizes.sum())
    labels = rng.randint(0, 5, size=n).astype(np.float64)
    # a few queries with zero relevance everywhere
    qb = np.concatenate([[0], np.cumsum(sizes)])
    for q in (3, 17):
        labels[qb[q]:qb[q + 1]] = 0
    scores = rng.normal(size=n)
    qweights = rng.uniform(0.5, 2.0, size=len(sizes))

    md = Metadata(n)
    md.set_label(labels)
    md.set_query(list(sizes))
    md.query_weights = qweights

    cfg = Config({"objective": "lambdarank", "metric": "ndcg,map",
                  "ndcg_at": "1,3,5,10,100"})
    ndcg = create_metric("ndcg", cfg)
    m_ap = create_metric("map", cfg)
    ndcg.init(md, n)
    m_ap.init(md, n)
    got_ndcg = ndcg.eval(scores[None, :])
    got_map = m_ap.eval(scores[None, :])

    gains = ndcg.label_gain
    eval_at = ndcg.eval_at
    want_ndcg = np.zeros(len(eval_at))
    want_map = np.zeros(len(eval_at))
    for q in range(len(sizes)):
        lbl = labels[qb[q]:qb[q + 1]].astype(np.int64)
        sc = scores[qb[q]:qb[q + 1]]
        nq = len(lbl)
        disc = 1.0 / np.log2(np.arange(nq) + 2.0)
        order = np.argsort(-sc, kind="stable")
        ideal = np.sort(lbl)[::-1]
        rel = lbl[order] > 0
        hits = np.cumsum(rel)
        prec = hits / (np.arange(nq) + 1.0)
        for i, k in enumerate(eval_at):
            kk = min(k, nq)
            max_dcg = (gains[ideal[:kk]] * disc[:kk]).sum()
            if max_dcg <= 0:
                want_ndcg[i] += qweights[q]
            else:
                dcg = (gains[lbl[order[:kk]]] * disc[:kk]).sum()
                want_ndcg[i] += dcg / max_dcg * qweights[q]
            nh = hits[kk - 1] if kk > 0 else 0
            want_map[i] += ((prec[:kk] * rel[:kk]).sum() / nh
                            if nh > 0 else 0.0) * qweights[q]
    sw = qweights.sum()
    np.testing.assert_allclose(got_ndcg, want_ndcg / sw, rtol=1e-9)
    np.testing.assert_allclose(got_map, want_map / sw, rtol=1e-9)
