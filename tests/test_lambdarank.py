"""Lambdarank size-class bucketing (objective/__init__.py): per-class
padding must not change the math — gradients are identical to padding
every query to the global maximum, and per-query lambda sums are zero
(pairwise antisymmetry, rank_objective.hpp:83-137)."""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import Metadata
from lightgbm_tpu.objective import LambdarankNDCG


def _make(seed=0):
    rng = np.random.RandomState(seed)
    # heavily skewed query sizes: 17 small, one big (pad classes 4x apart)
    sizes = [5, 9, 17, 33] * 4 + [210]
    n = sum(sizes)
    label = rng.randint(0, 4, size=n).astype(np.float32)
    md = Metadata(n)
    md.set_label(label)
    md.set_query(np.asarray(sizes))
    score = rng.normal(size=(1, n)).astype(np.float32)
    return md, n, score


def test_bucketing_matches_single_class_padding(monkeypatch):
    md, n, score = _make()
    cfg = Config({"objective": "lambdarank"})

    obj = LambdarankNDCG(cfg)
    obj.init(md, n)
    assert len(obj.query_classes) > 1    # bucketing actually happened
    g1, h1 = obj.gradients(score)

    # force one global class: re-pad every bucket to the same width
    big = 256
    obj3 = LambdarankNDCG(cfg)
    obj3.init(md, n)
    import jax.numpy as jnp
    merged_idx, merged_valid, merged_label, merged_inv = [], [], [], []
    for cls in obj3.query_classes:
        P = cls["P"]
        pad = big - P
        merged_idx.append(np.pad(np.asarray(cls["doc_idx"]),
                                 ((0, 0), (0, pad))))
        merged_valid.append(np.pad(np.asarray(cls["doc_valid"]),
                                   ((0, 0), (0, pad))))
        merged_label.append(np.pad(np.asarray(cls["label"]),
                                   ((0, 0), (0, pad))))
        merged_inv.append(np.asarray(cls["inv_max_dcg"]))
    obj3.query_classes = [{
        "P": big,
        "doc_idx": jnp.asarray(np.concatenate(merged_idx)),
        "doc_valid": jnp.asarray(np.concatenate(merged_valid)),
        "label": jnp.asarray(np.concatenate(merged_label)),
        "inv_max_dcg": jnp.asarray(np.concatenate(merged_inv)),
    }]
    g2, h2 = obj3.gradients(score)

    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h2),
                               rtol=1e-5, atol=1e-6)


def test_per_query_lambda_sum_is_zero():
    md, n, score = _make(seed=3)
    cfg = Config({"objective": "lambdarank"})
    obj = LambdarankNDCG(cfg)
    obj.init(md, n)
    g, h = obj.gradients(score)
    g = np.asarray(g)[0]
    h = np.asarray(h)[0]
    qb = np.asarray(md.query_boundaries)
    for q in range(len(qb) - 1):
        seg = g[qb[q]:qb[q + 1]]
        np.testing.assert_allclose(seg.sum(), 0.0, atol=1e-4)
    assert np.all(h >= 0)
    assert np.isfinite(g).all() and np.isfinite(h).all()
