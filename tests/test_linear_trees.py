"""Piece-wise linear trees (docs/LINEAR_TREES.md): affine leaves with
batched on-device ridge solves, end to end from training to serving.

Pins:

- **fewer trees**: on a piece-wise linear synthetic, the linear booster
  reaches the constant booster's best training l2 in <= half the trees;
- **serving parity**: ``CompiledForest.predict == Booster.predict``
  within 1e-6 across the bucket ladder, and save -> load ->
  ``CompiledForest.from_booster`` round-trips exactly;
- **identity**: ``linear_max_leaf_features=0`` produces a BYTE-identical
  model to ``linear_tree=false``; ``linear_tree=false`` runs never
  compile a linear program;
- **compile ledger**: after warmup, linear rounds record ZERO new XLA
  programs (the K-padded fit shares one program across trees/rounds);
- **single scaling point**: merge + shrinkage_decay on a linear forest
  predicts exactly ``base + d * delta`` (slopes scale with intercepts);
- **fallbacks**: data-starved leaves fall back to constant values and
  count into ``linear_fallback_total``;
- **named refusals**: missing raw feature values, truncated model-text
  coefficient sections.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import LightGBMError
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.models.tree import Tree
from lightgbm_tpu.serve.forest import CompiledForest

pytestmark = [pytest.mark.linear]


def _piecewise(n=3000, f=8, seed=0):
    """Piece-wise linear response whose slopes are on the SPLIT features
    (leaf models fit over root-to-leaf path features, so slopes on
    non-split features are invisible to them): affine leaves capture
    each segment in one fit; constant leaves must staircase it."""
    rng = np.random.RandomState(seed)
    X = rng.uniform(-2.0, 2.0, size=(n, f))
    y = (np.where(X[:, 1] > 0.0, 2.5 * X[:, 1], -1.0 * X[:, 1])
         + np.where(X[:, 2] > 0.5, 1.5 * (X[:, 2] - 0.5), 0.0))
    y = y + 0.05 * rng.normal(size=n)
    return X, y


def _params(linear=True, **over):
    p = {"objective": "regression", "metric": "l2", "num_leaves": 15,
         "learning_rate": 0.15, "min_data_in_leaf": 20, "verbose": -1,
         "seed": 7}
    if linear:
        p.update({"linear_tree": True, "linear_lambda": 0.01,
                  "linear_max_leaf_features": 4})
    p.update(over)
    return p


def _train(X, y, rounds, linear=True, **over):
    return lgb.train(_params(linear, **over), lgb.Dataset(X, label=y),
                     num_boost_round=rounds)


# ---------------------------------------------------------------------------
# fewer-trees demo: the point of the subsystem
# ---------------------------------------------------------------------------

def test_linear_reaches_const_best_with_half_the_trees():
    import jax
    X, y = _piecewise()
    const_rounds = 40
    ds = BinnedDataset.from_matrix(X, y, max_bin=63, min_data_in_leaf=20,
                                   keep_raw=True)

    def l2_curve(linear, rounds):
        # lr=0.5: per-round progress is bounded by lr * (tree fit
        # quality); a damped lr hides the fit-quality gap until the
        # constant staircase's approximation floor, so the fewer-trees
        # effect shows at moderate-to-high learning rates
        cfg = Config(_params(linear, num_iterations=rounds, max_bin=63,
                             learning_rate=0.5))
        gb = GBDT(cfg, ds)
        curve = []
        for _ in range(rounds):
            gb.train_one_iter()
            jax.block_until_ready(gb.train_data.score)
            curve.append(float(gb.eval_metrics()["training"]["l2"]))
        return curve

    const_curve = l2_curve(False, const_rounds)
    target = min(const_curve)
    lin_curve = l2_curve(True, const_rounds // 2)
    reached = next((i + 1 for i, v in enumerate(lin_curve)
                    if v <= target), None)
    assert reached is not None and reached <= const_rounds // 2, (
        f"linear never reached the constant run's best l2 {target:.6f} "
        f"within {const_rounds // 2} trees (best "
        f"{min(lin_curve):.6f}) — the fewer-trees demo regressed")


# ---------------------------------------------------------------------------
# serving parity + round trips
# ---------------------------------------------------------------------------

def test_compiled_forest_parity_across_bucket_ladder():
    X, y = _piecewise(n=700)
    bst = _train(X, y, rounds=12)
    ref = bst.predict(X, raw_score=True)
    cf = CompiledForest.from_booster(bst, buckets=[16, 64, 256])
    # sizes below / at / straddling bucket boundaries, incl. remainders
    for n in (1, 15, 16, 17, 64, 200, 257, 700):
        got = cf.predict(X[:n], raw_score=True)
        assert np.abs(got - ref[:n]).max() <= 1e-6, (
            f"linear forest parity broke at n={n}")
    # transformed output goes through the same epilogue
    assert np.abs(cf.predict(X[:100]) - bst.predict(X[:100])).max() <= 1e-6


def test_booster_predict_routes_linear_through_compiled_forest():
    # >=4096 rows auto-freezes a CompiledForest: the fast path must
    # carry the affine stacks (this is exactly the path that scored
    # wrong before serving support landed)
    X, y = _piecewise(n=5000)
    bst = _train(X, y, rounds=10)
    big = bst.predict(X, raw_score=True)
    small = np.concatenate([bst.predict(X[i:i + 500], raw_score=True)
                            for i in range(0, len(X), 500)])
    assert np.abs(big - small).max() <= 1e-6


def test_save_load_compiled_forest_round_trip(tmp_path):
    X, y = _piecewise(n=600)
    bst = _train(X, y, rounds=8)
    assert any(t.has_linear()
               for t in bst._booster.models), "no affine leaf fit"
    path = tmp_path / "linear.txt"
    bst.save_model(str(path))
    loaded = lgb.Booster(model_file=str(path))
    assert loaded.model_to_string() == bst.model_to_string()
    ref = CompiledForest.from_booster(bst).predict(X, raw_score=True)
    got = CompiledForest.from_booster(loaded).predict(X, raw_score=True)
    assert np.array_equal(ref, got)
    assert CompiledForest.from_booster(loaded).info()["linear"] is True


def test_old_model_files_without_linear_sections_load(tmp_path):
    X, y = _piecewise(n=400)
    bst = _train(X, y, rounds=4, linear=False)
    text = bst.model_to_string()
    assert "leaf_coeff" not in text          # constant models stay clean
    path = tmp_path / "const.txt"
    bst.save_model(str(path))
    loaded = lgb.Booster(model_file=str(path))
    assert not any(t.has_linear() for t in loaded._booster.models)
    assert np.array_equal(loaded.predict(X[:100]), bst.predict(X[:100]))


# ---------------------------------------------------------------------------
# identity pins: off means OFF
# ---------------------------------------------------------------------------

def test_k0_is_byte_identical_to_linear_tree_false():
    X, y = _piecewise(n=500)
    off = _train(X, y, rounds=6, linear=False)
    k0 = _train(X, y, rounds=6, linear=True, linear_max_leaf_features=0)
    assert k0.model_to_string() == off.model_to_string()


def test_linear_off_never_compiles_a_linear_program():
    from lightgbm_tpu.obs import compile_ledger
    n0 = len(compile_ledger.events())
    X, y = _piecewise(n=500)
    _train(X, y, rounds=4, linear=False)
    for ev in compile_ledger.events()[n0:]:
        assert ev["program"] != "linear_fit", (
            "a linear_tree=false run compiled the linear-fit program")


# ---------------------------------------------------------------------------
# compile-ledger flatness: the K-padding contract
# ---------------------------------------------------------------------------

def test_linear_rounds_compile_nothing_after_warmup():
    import jax
    from lightgbm_tpu.obs import compile_ledger
    X, y = _piecewise(n=800)
    ds = BinnedDataset.from_matrix(X, y, max_bin=63, min_data_in_leaf=20,
                                   keep_raw=True)
    cfg = Config(_params(num_iterations=10, max_bin=63))
    gb = GBDT(cfg, ds)
    for _ in range(3):                       # warmup: compile everything
        gb.train_one_iter()
    jax.block_until_ready(gb.train_data.score)
    gb._flush_pending()                      # drain the pipeline
    n0 = len(compile_ledger.events())
    for _ in range(5):
        gb.train_one_iter()
    jax.block_until_ready(gb.train_data.score)
    gb._flush_pending()
    new = compile_ledger.events()[n0:]
    assert not new, (
        "steady-state linear rounds recompiled: "
        + ", ".join(f"{e['program']}({e['shapes']})" for e in new))


# ---------------------------------------------------------------------------
# single scaling point: merge / shrinkage / negation
# ---------------------------------------------------------------------------

def test_merge_with_shrinkage_scales_slopes_with_intercepts():
    X, y = _piecewise(n=600)
    base = _train(X, y, rounds=4)
    delta = _train(X, y, rounds=3, learning_rate=0.3)
    pb = base.predict(X, raw_score=True)
    pd = delta.predict(X, raw_score=True)
    merged = base.merge(delta, shrinkage_decay=0.5)
    pm = merged.predict(X, raw_score=True)
    assert np.abs(pm - (pb + 0.5 * pd)).max() <= 1e-6, (
        "merge+shrinkage on a linear forest drifted — leaf_coeff is "
        "not scaling through Tree.scale_leaf_outputs")
    # the merged model text still carries the (scaled) coefficients
    assert "leaf_coeff" in merged.model_to_string()


def test_scaled_copy_scales_coefficients_and_leaves():
    text = (
        "num_leaves=3\n"
        "split_feature=1 0\n"
        "split_gain=1.5 0.75\n"
        "threshold=0.25 -1.5\n"
        "decision_type=0 0\n"
        "left_child=1 -1\n"
        "right_child=-2 -3\n"
        "leaf_parent=1 0 1\n"
        "leaf_value=0.1 -0.2 0.3\n"
        "leaf_count=10 20 30\n"
        "internal_value=0.05 0.15\n"
        "internal_count=60 30\n"
        "shrinkage=0.1\n"
        "num_linear_features=2\n"
        "leaf_feat=1 0 -1 -1 0 1\n"
        "leaf_coeff=0.5 -0.25 0 0 1.5 0.125\n")
    t = Tree.from_string(text)
    s = t.scaled_copy(0.5)
    assert np.array_equal(s.leaf_value, t.leaf_value * 0.5)
    assert np.array_equal(s.leaf_coeff, t.leaf_coeff * 0.5)
    assert np.array_equal(s.leaf_feat, t.leaf_feat)      # indices fixed
    assert np.array_equal(t.leaf_coeff[0], [0.5, -0.25])  # original kept
    # factors multiply exactly through repeated scaling (DART, merge)
    d = t.scaled_copy(0.5).scale_leaf_outputs(2.0)
    X = np.random.RandomState(3).normal(size=(50, 3))
    assert np.allclose(d.predict(X), t.predict(X), atol=1e-12)


# ---------------------------------------------------------------------------
# fallbacks
# ---------------------------------------------------------------------------

def test_data_starved_leaves_fall_back_and_count():
    from lightgbm_tpu import obs
    before = obs.get_counter("linear_fallback_total")
    X, y = _piecewise(n=60)
    # K=16 needs >= 18 rows per leaf; these leaves hold 10-15
    over = dict(num_leaves=31, min_data_in_leaf=2,
                linear_max_leaf_features=16)
    bst = _train(X, y, rounds=5, **over)
    assert obs.get_counter("linear_fallback_total") > before
    # every leaf fell back, so no tree kept a model and the run is
    # byte-identical to linear_tree=false (the all-fallback identity)
    assert not any(t.has_linear() for t in bst._booster.models)
    const = _train(X, y, rounds=5, linear=False, **{
        k: v for k, v in over.items() if k != "linear_max_leaf_features"})
    assert bst.model_to_string() == const.model_to_string()


# ---------------------------------------------------------------------------
# named refusals
# ---------------------------------------------------------------------------

def test_linear_without_raw_values_is_refused():
    X, y = _piecewise(n=300)
    ds = BinnedDataset.from_matrix(X, y, max_bin=63, min_data_in_leaf=20)
    assert ds.raw is None
    with pytest.raises(LightGBMError, match="raw feature values"):
        GBDT(Config(_params(num_iterations=2, max_bin=63)), ds)


def test_valid_set_without_raw_values_is_refused():
    X, y = _piecewise(n=300)
    ds = BinnedDataset.from_matrix(X, y, max_bin=63, min_data_in_leaf=20,
                                   keep_raw=True)
    gb = GBDT(Config(_params(num_iterations=2, max_bin=63)), ds)
    Xv, yv = _piecewise(n=100, seed=1)
    dv = ds.create_valid(Xv, yv)
    dv.raw = None         # e.g. restored from a raw-less binary snapshot
    with pytest.raises(LightGBMError, match="raw feature values"):
        gb.add_valid_dataset(dv)


def test_truncated_coefficient_section_is_a_named_error():
    text = (
        "num_leaves=2\n"
        "split_feature=0\n"
        "split_gain=1.0\n"
        "threshold=0.0\n"
        "decision_type=0\n"
        "left_child=-1\n"
        "right_child=-2\n"
        "leaf_parent=0 0\n"
        "leaf_value=0.1 -0.2\n"
        "leaf_count=10 20\n"
        "internal_value=0.05\n"
        "internal_count=30\n"
        "shrinkage=0.1\n"
        "num_linear_features=2\n"
        "leaf_feat=1 0 -1 -1\n"
        "leaf_coeff=0.5 -0.25 0\n")          # 3 of 4 values: truncated
    with pytest.raises(LightGBMError, match="leaf_coeff"):
        Tree.from_string(text)


def test_bad_linear_feature_count_is_a_named_error():
    text = (
        "num_leaves=2\n"
        "split_feature=0\n"
        "split_gain=1.0\n"
        "threshold=0.0\n"
        "decision_type=0\n"
        "left_child=-1\n"
        "right_child=-2\n"
        "leaf_parent=0 0\n"
        "leaf_value=0.1 -0.2\n"
        "leaf_count=10 20\n"
        "internal_value=0.05\n"
        "internal_count=30\n"
        "shrinkage=0.1\n"
        "num_linear_features=banana\n"
        "leaf_feat=1 0\n"
        "leaf_coeff=0.5 -0.25\n")
    with pytest.raises(LightGBMError, match="num_linear_features"):
        Tree.from_string(text)


# ---------------------------------------------------------------------------
# bench_regress passthrough (informational `linear` BENCH block)
# ---------------------------------------------------------------------------

def test_bench_regress_passes_linear_block_through(tmp_path, capsys):
    import importlib.util
    import json
    import pathlib
    spec = importlib.util.spec_from_file_location(
        "bench_regress", pathlib.Path(__file__).resolve().parent.parent
        / "tools" / "bench_regress.py")
    bench_regress = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_regress)

    base = {"metric": "m", "value": 10.0, "unit": "iters/sec"}
    cand = {"metric": "m", "value": 10.2, "unit": "iters/sec",
            "linear": {"trees_to_const_best": 17, "fallback_rate": 0.02,
                       "fit_s_per_round_median": 0.01}}
    b = tmp_path / "b.json"
    c = tmp_path / "c.json"
    b.write_text(json.dumps(base))
    c.write_text(json.dumps(cand))
    rc = bench_regress.main(["--baseline", str(b), "--candidate", str(c),
                             "--threshold", "5"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    verdict = json.loads(out)
    assert rc == 0 and verdict["ok"]
    assert verdict["linear_candidate"]["trees_to_const_best"] == 17
    assert "linear_baseline" not in verdict
