"""In-data column roles: weight/group/ignore/categorical columns by index
or name: prefix (dataset_loader.cpp SetHeader, :22-157), through the
one-round loader, the two-round streaming loader, and the CLI."""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.basic import LightGBMError
from lightgbm_tpu.io.column_roles import (qid_to_query_sizes,
                                          resolve_label_idx, resolve_roles)


def _write(path, rows, header=None):
    with open(path, "w") as fh:
        if header:
            fh.write("\t".join(header) + "\n")
        for r in rows:
            fh.write("\t".join(f"{v:g}" for v in r) + "\n")


def _make_file(tmp_path, header):
    """label, f0, weight w, f1, qid: label = f0 > 0 (f1 is noise)."""
    rng = np.random.RandomState(3)
    n = 400
    f0 = rng.normal(size=n)
    f1 = rng.normal(size=n)
    w = rng.uniform(0.5, 2.0, size=n)
    qid = np.repeat(np.arange(20), 20)
    y = (f0 > 0).astype(float)
    rows = np.column_stack([y, f0, w, f1, qid])
    path = tmp_path / ("roles_h.tsv" if header else "roles.tsv")
    _write(path, rows,
           header=["lab", "a", "w", "b", "qid"] if header else None)
    return str(path), y, w, qid


# ---------------------------------------------------------------------------
# resolver unit semantics
# ---------------------------------------------------------------------------

def test_resolver_name_and_index_spaces():
    full = ["lab", "a", "w", "b", "qid"]
    assert resolve_label_idx("name:lab", full) == 0
    assert resolve_label_idx("2", full) == 2
    assert resolve_label_idx("", full) == 0
    feats = ["a", "w", "b", "qid"]   # label-removed space
    r = resolve_roles(weight_column="name:w", group_column="name:qid",
                      ignore_column="name:b", categorical_column="2",
                      feature_names=feats)
    assert r.weight_idx == 1 and r.group_idx == 3
    # weight+group join the ignore set (dataset_loader.cpp:111,131)
    assert r.ignore == {1, 2, 3}
    assert r.categorical == {2}


def test_resolver_errors():
    with pytest.raises(LightGBMError):
        resolve_roles(weight_column="name:nope", feature_names=["a", "b"])
    with pytest.raises(LightGBMError):
        resolve_roles(ignore_column="notanumber", feature_names=None)
    with pytest.raises(LightGBMError):
        resolve_label_idx("name:lab", None)


def test_qid_run_lengths():
    assert qid_to_query_sizes([1, 1, 2, 2, 2, 7]) == [2, 3, 1]
    assert qid_to_query_sizes([]) == []


# ---------------------------------------------------------------------------
# end-to-end through the loaders
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("two_round", [False, True])
@pytest.mark.parametrize("by_name", [False, True])
def test_roles_through_loader(tmp_path, two_round, by_name):
    path, y, w, qid = _make_file(tmp_path, header=by_name)
    if by_name:
        params = {"has_header": True, "label_column": "name:lab",
                  "weight_column": "name:w", "group_column": "name:qid",
                  "ignore_column": "name:b"}
    else:
        params = {"label_column": "0", "weight_column": "1",
                  "group_column": "3", "ignore_column": "2"}
    params["verbose"] = -1
    if two_round:
        params["two_round"] = True
    ds = lgb.Dataset(path, params=params).construct()
    binned = ds._binned
    md = binned.metadata
    np.testing.assert_allclose(np.asarray(md.label, np.float64), y,
                               atol=1e-6)
    # the file carries %g (6 significant digits)
    np.testing.assert_allclose(np.asarray(md.weights, np.float64), w,
                               rtol=1e-5)
    sizes = np.diff(np.asarray(md.query_boundaries))
    np.testing.assert_array_equal(sizes, np.full(20, 20))
    # weight/group/ignored columns must not be usable features: only f0
    # (and maybe the noise f1... f1 is ignored by index 2? no: ignored is
    # the *b* column) — usable features exclude w, qid, and b.
    used_real = set(binned.used_feature_map)
    # feature space order: [a, w, b, qid] -> w=1, b=2, qid=3 excluded
    assert used_real <= {0}
    assert 0 in used_real


def test_roles_training_weights_differ(tmp_path):
    """Training with an in-data weight column must differ from unweighted
    training on the same features (the weights actually flow in)."""
    path, y, w, qid = _make_file(tmp_path, header=False)
    common = {"objective": "binary", "num_leaves": 7, "verbose": -1,
              "min_data_in_leaf": 10, "label_column": "0",
              "ignore_column": "1,3"}
    bw = lgb.train({**common, "weight_column": "1",
                    "ignore_column": "3"},
                   lgb.Dataset(path, params={**common, "weight_column": "1",
                                             "ignore_column": "3"}),
                   num_boost_round=5)
    bu = lgb.train(common, lgb.Dataset(path, params=common),
                   num_boost_round=5)
    s_w = bw.model_to_string()
    s_u = bu.model_to_string()
    assert s_w != s_u


def test_roles_through_cli(tmp_path):
    """A conf with weight_column/group_column/ignore_column by name trains
    and the model ignores the role columns (header + name: path)."""
    from lightgbm_tpu import cli
    path, y, w, qid = _make_file(tmp_path, header=True)
    conf = tmp_path / "train.conf"
    conf.write_text(
        "task = train\nobjective = binary\nmetric = auc\n"
        f"data = {path}\nheader = true\nlabel = name:lab\n"
        "weight = name:w\ngroup = name:qid\nignore_column = name:b\n"
        "num_trees = 3\nnum_leaves = 7\nmin_data_in_leaf = 10\n"
        "verbosity = -1\noutput_model = roles_model.txt\n")
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        rc = cli.main([f"config={conf}"])
    finally:
        os.chdir(cwd)
    assert rc == 0
    text = (tmp_path / "roles_model.txt").read_text()
    # the only splittable feature is column a
    assert "split_feature=" in text
    for line in text.splitlines():
        if line.startswith("split_feature="):
            vals = {int(v) for v in line.split("=")[1].split()
                    if v.strip()}
            assert vals <= {0}
