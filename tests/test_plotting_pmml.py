"""plot_importance / plot_metric / plot_tree (reference
test_plotting.py shapes) and the PMML converter."""

import os
import sys
import xml.etree.ElementTree as ET

import numpy as np
import pytest

matplotlib = pytest.importorskip("matplotlib")
matplotlib.use("Agg")

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.RandomState(0)
    X = rng.normal(size=(500, 6))
    y = X[:, 0] * 2 + X[:, 1] + 0.1 * rng.normal(size=500)
    params = {"objective": "regression", "num_leaves": 7,
              "min_data_in_leaf": 10, "verbose": 0}
    ds = lgb.Dataset(X[:400], y[:400], params=params)
    vs = ds.create_valid(X[400:], y[400:])
    res = {}
    booster = lgb.train(params, ds, num_boost_round=10, valid_sets=[vs],
                        evals_result=res, verbose_eval=False)
    return booster, res


def test_plot_importance(fitted):
    booster, _ = fitted
    ax = lgb.plot_importance(booster)
    assert ax.get_title() == "Feature importance"
    assert len(ax.patches) >= 1


def test_plot_metric(fitted):
    _, res = fitted
    ax = lgb.plot_metric(res)
    assert ax.get_ylabel() == "l2"
    assert len(ax.lines) == 1


def test_plot_tree(fitted):
    booster, _ = fitted
    ax = lgb.plot_tree(booster, tree_index=0)
    assert len(ax.texts) >= booster.dump_model()["tree_info"][0]["num_leaves"]


def test_pmml_converter(fitted, tmp_path):
    booster, _ = fitted
    model_path = str(tmp_path / "model.txt")
    booster.save_model(model_path)
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "pmml"))
    try:
        import pmml as pmml_mod
    finally:
        sys.path.pop(0)
    out = pmml_mod.model_to_pmml(open(model_path).read())
    root = ET.fromstring(out)
    ns = "{http://www.dmg.org/PMML-4_3}"
    segments = root.findall(f".//{ns}Segment")
    assert len(segments) == 10
    nodes = root.findall(f".//{ns}Node")
    assert len(nodes) > 10 * 7  # >= leaves+internals per tree
