"""Sharded ingestion + distributed FindBin (parallel/ingest.py): mappers
agreed over an 8-virtual-device CPU mesh must be IDENTICAL to the
single-host BinnedDataset.from_matrix result, and the assembled bins must
match column-for-column (dataset_loader.cpp:549-655, 723-816 parity)."""

import numpy as np
import pytest
import jax
from jax.sharding import Mesh

from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.parallel import ingest

REF_REGRESSION = "/root/reference/examples/regression/regression.train"


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-virtual-device CPU mesh (conftest)")
    return Mesh(np.asarray(devs[:8]), ("data",))


def _single_host(X, y, **kw):
    return BinnedDataset.from_matrix(X, y, **kw)


def test_mappers_match_single_host(mesh):
    shards, _ = ingest.load_file_sharded(REF_REGRESSION, 8)
    X = np.concatenate([s[0] for s in shards])
    y = np.concatenate([s[1] for s in shards])
    kw = dict(max_bin=63, min_data_in_leaf=20,
              bin_construct_sample_cnt=3000, data_random_seed=1)

    single = _single_host(X, y, **kw)
    dist_mappers = ingest.distributed_find_bin(
        mesh, "data", [s[0] for s in shards], **{
            "max_bin": 63, "min_data_in_leaf": 20,
            "bin_construct_sample_cnt": 3000, "data_random_seed": 1})

    # identical used-feature set and identical mapper state per feature
    for f in range(X.shape[1]):
        inner = single.real_to_inner[f]
        dm = dist_mappers[f]
        if inner < 0:
            assert dm is None or dm.is_trivial
            continue
        sm = single.mappers[inner]
        assert dm is not None and not dm.is_trivial
        assert dm.num_bin == sm.num_bin
        np.testing.assert_array_equal(dm.bin_upper_bound, sm.bin_upper_bound)
        assert dm.default_bin == sm.default_bin
        assert dm.min_val == sm.min_val and dm.max_val == sm.max_val


def test_binned_dataset_from_shards_matches(mesh):
    shards, _ = ingest.load_file_sharded(REF_REGRESSION, 5)
    X = np.concatenate([s[0] for s in shards])
    y = np.concatenate([s[1] for s in shards])
    kw = dict(max_bin=63, min_data_in_leaf=20,
              bin_construct_sample_cnt=3000, data_random_seed=1)

    single = _single_host(X, y, **kw)
    # 5 row-shards agreed over the 8-device mesh? shards must divide the
    # mesh axis: re-split into 8 for the collective
    shards8, _ = ingest.load_file_sharded(REF_REGRESSION, 8)
    dist = ingest.binned_dataset_from_shards(
        mesh, "data", shards8, max_bin=63, min_data_in_leaf=20,
        bin_construct_sample_cnt=3000, data_random_seed=1)

    assert dist.used_feature_map == single.used_feature_map
    np.testing.assert_array_equal(dist.bins, single.bins)
    np.testing.assert_array_equal(dist.metadata.label, single.metadata.label)

    # device-sharded placement over the mesh rows axis
    arr = ingest.shard_bins_to_devices(mesh, "data", dist)
    assert arr.shape[0] == dist.bins.shape[0]
    assert arr.sharding.spec == jax.sharding.PartitionSpec(None, "data")


def test_row_partition_balanced():
    parts = ingest.row_partition(10, 3)
    assert parts == [(0, 4), (4, 7), (7, 10)]
    assert ingest.row_partition(8, 8) == [(i, i + 1) for i in range(8)]


def test_mapper_codec_roundtrip():
    rng = np.random.RandomState(0)
    from lightgbm_tpu.io.binning import NUMERICAL, BinMapper
    m = BinMapper().find_bin(rng.normal(size=500), 500, 31, 3, 0, NUMERICAL)
    row = ingest.encode_mapper(m, 31)
    m2 = ingest.decode_mapper(row)
    assert m2.num_bin == m.num_bin
    np.testing.assert_array_equal(m2.bin_upper_bound, m.bin_upper_bound)
    assert ingest.decode_mapper(ingest.encode_mapper(None, 31)) is None
