"""Device-time attribution (lightgbm_tpu/obs/devprof.py + devcaps.py):

- the OFF state is ledger-pinned free: no devprof series, no forced
  syncs, no new compile events beyond the function's own, and the
  outputs stay bit-identical when profiling toggles on;
- the sampling correction is unbiased: under a deterministic clock,
  ``sample:N`` and ``full`` agree exactly on the estimated total;
- compile cost fields (flops / bytes_accessed / output_bytes) ride the
  ledger JSONL present-or-None in every mode;
- roofline math (devcaps) is unit-pinned;
- serve per-bucket device-seconds series render as valid Prometheus
  text;
- ``tools/bench_regress.py --program-threshold`` gates a synthetic
  per-program regression and leaves profile-less baselines untouched.

Process-global state (registry, ledger, devprof accumulators) is
asserted by DELTA so this file composes with the rest of tier-1.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu import obs
from lightgbm_tpu.obs import compile_ledger, devcaps, devprof, prom, registry

pytestmark = pytest.mark.devprof


@pytest.fixture(autouse=True)
def devprof_pristine(monkeypatch):
    """Every test starts disarmed with a clean env and leaves no mode
    behind; accumulators reset on both sides (registry series persist —
    tests use unique program names and delta assertions)."""
    monkeypatch.delenv(devprof.ENV, raising=False)
    devprof.reset()
    devprof.configure(None)
    yield
    devprof.reset()
    devprof.configure(None)


class _FakeClock:
    """Deterministic perf_counter stand-in: advances 1.0 per call, so a
    sampled dispatch (two reads) always measures dt == 1.0 regardless of
    host load — which makes the sampling-correction identity EXACT."""

    def __init__(self):
        self.t = 0.0

    def perf_counter(self):
        self.t += 1.0
        return self.t


def _counters(*names):
    return tuple(obs.get_counter(n) for n in names)


# -- off is free ---------------------------------------------------------

def test_off_is_ledger_pinned_free():
    assert devprof.ENABLED is False and devprof.MODE == "off"
    fn = obs.instrumented_jit(lambda x: x * 2 + 1, program="t_dp_off")
    x = jnp.arange(16, dtype=jnp.float32)

    c0 = _counters("devprof_dispatches_total", "devprof_samples_total",
                   "devprof_forced_syncs_total")
    compiles0 = obs.get_counter("compile_count")
    events0 = len(compile_ledger.events())

    out_off = np.asarray(fn(x))
    out_off2 = np.asarray(fn(x))

    # exactly the function's own compile, nothing from devprof
    assert obs.get_counter("compile_count") - compiles0 == 1
    assert len(compile_ledger.events()) - events0 == 1
    assert _counters("devprof_dispatches_total", "devprof_samples_total",
                     "devprof_forced_syncs_total") == c0
    assert devprof.estimates() == {}

    # toggling profiling ON must not create new XLA programs for an
    # already-compiled function, and outputs stay bit-identical
    devprof.enable("full")
    out_on = np.asarray(fn(x))
    assert obs.get_counter("compile_count") - compiles0 == 1
    assert len(compile_ledger.events()) - events0 == 1
    np.testing.assert_array_equal(out_off, out_on)
    np.testing.assert_array_equal(out_off, out_off2)


# -- sampling correction -------------------------------------------------

def test_sampled_matches_full_under_deterministic_clock(monkeypatch):
    x = jnp.arange(8, dtype=jnp.float32)
    fn = obs.instrumented_jit(lambda v: v + 1, program="t_dp_full")
    fn2 = obs.instrumented_jit(lambda v: v + 2, program="t_dp_samp")
    fn(x), fn2(x)   # compile while disarmed: measure warm dispatches only

    monkeypatch.setattr(devprof, "time", _FakeClock())
    devprof.enable("full")
    for _ in range(6):
        fn(x)
    full = devprof.estimates()["t_dp_full"]
    assert full["dispatches"] == 6 and full["samples"] == 6
    assert full["device_seconds_est"] == pytest.approx(6.0)

    devprof.reset()
    devprof.enable("sample:2")
    assert devprof.MODE == "sample:2"
    for _ in range(6):
        fn2(x)
    samp = devprof.estimates()["t_dp_samp"]
    # every 2nd dispatch sampled, each dt corrected x2: exact agreement
    assert samp["dispatches"] == 6 and samp["samples"] == 3
    assert samp["device_seconds_est"] == pytest.approx(
        full["device_seconds_est"])


def test_compiling_dispatch_sample_is_discarded():
    """Compile seconds are the ledger's account: a sample landing on
    the compiling dispatch must not pollute the device-time estimate."""
    devprof.enable("full")
    skipped0 = obs.get_counter("devprof_samples_skipped_compile")
    fn = obs.instrumented_jit(lambda v: v * 9, program="t_dp_skip")
    x = jnp.arange(8, dtype=jnp.float32)
    fn(x)                                     # compiles: sample discarded
    assert "t_dp_skip" not in devprof.estimates()
    assert obs.get_counter("devprof_samples_skipped_compile") == skipped0 + 1
    fn(x)                                     # warm: sample lands
    st = devprof.estimates()["t_dp_skip"]
    assert st["dispatches"] == 2 and st["samples"] == 1


def test_sample_interval_gauge_carries_mode():
    assert obs.get_gauge("devprof_sample_interval") == 0
    devprof.enable("sample:4")
    assert obs.get_gauge("devprof_sample_interval") == 4
    devprof.enable("full")
    assert obs.get_gauge("devprof_sample_interval") == 1


def test_env_wins_and_malformed_env_disarms(monkeypatch):
    monkeypatch.setenv(devprof.ENV, "sample:3")
    assert devprof.configure("full") == "sample:3"
    monkeypatch.setenv(devprof.ENV, "sideways")
    assert devprof.configure("full") == "off"      # warn + disarm
    with pytest.raises(ValueError):
        devprof.parse_mode("sample:0")
    with pytest.raises(ValueError):
        devprof.parse_mode("sideways")


# -- cost fields in the ledger -------------------------------------------

@pytest.fixture
def ledger_file(tmp_path, monkeypatch):
    path = tmp_path / "compile_ledger.jsonl"
    monkeypatch.setenv(compile_ledger.ENV_PATH, str(path))
    compile_ledger.configure()
    yield path
    monkeypatch.delenv(compile_ledger.ENV_PATH)
    compile_ledger.configure()


def test_cost_fields_round_trip_jsonl(ledger_file):
    x = jnp.arange(32, dtype=jnp.float32)

    devprof.enable("full")
    obs.instrumented_jit(lambda v: v * 3, program="t_dp_cost_on")(x)
    devprof.configure(None)
    obs.instrumented_jit(lambda v: v * 5, program="t_dp_cost_off")(x)

    rows = {}
    with open(ledger_file) as fh:
        for line in fh:
            ev = json.loads(line)
            rows[ev["program"]] = ev
    on, off = rows["t_dp_cost_on"], rows["t_dp_cost_off"]
    # keys are ALWAYS present; values populate only while profiling
    for ev in (on, off):
        assert {"flops", "bytes_accessed", "output_bytes"} <= set(ev)
    assert on["flops"] is not None and on["flops"] > 0   # CPU cost model
    assert off["flops"] is None

    # and the gauges mirror the non-None fields for snapshot transport
    assert obs.get_gauge("devprof_flops_t_dp_cost_on") == on["flops"]


# -- roofline math -------------------------------------------------------

def test_roofline_units():
    caps = {"peak_flops": 1e12, "peak_bytes_per_sec": 1e11}
    rl = devcaps.roofline(1e9, 1e8, 0.01, caps)
    assert rl["achieved_flops"] == pytest.approx(1e11)
    # ideal time = max(1e9/1e12, 1e8/1e11) = 1ms; took 10ms -> 10%
    assert rl["roofline_pct"] == pytest.approx(10.0)

    mem_bound = devcaps.roofline(1e6, 1e9, 0.1, caps)
    # memory term dominates: 1e9/1e11 = 10ms ideal over 100ms -> 10%
    assert mem_bound["roofline_pct"] == pytest.approx(10.0)

    assert devcaps.roofline(None, 1e8, 0.01, caps)["achieved_flops"] is None
    assert devcaps.roofline(1e9, 1e8, 0.0, caps)["roofline_pct"] is None
    none_caps = {"peak_flops": None, "peak_bytes_per_sec": None}
    assert devcaps.roofline(1e9, 1e8, 0.01, none_caps)["roofline_pct"] is None


def test_devcaps_env_override(monkeypatch):
    monkeypatch.setenv(devcaps.ENV_PEAK_FLOPS, "2.5e14")
    monkeypatch.setenv(devcaps.ENV_PEAK_BYTES, "1.5e12")
    caps = devcaps.capabilities()
    assert caps["peak_flops"] == pytest.approx(2.5e14)
    assert caps["peak_bytes_per_sec"] == pytest.approx(1.5e12)
    assert caps["source"] == "env"


# -- serve per-bucket series at /metrics ---------------------------------

def test_bucket_series_renders_valid_prometheus():
    fn = obs.instrumented_jit(lambda v: v - 1, program="t_dp_bkt")
    x = jnp.arange(64, dtype=jnp.float32)
    fn(x)   # compile while disarmed
    devprof.enable("full")
    with devprof.bucket_scope(256):
        fn(x)
    fn(x)   # outside any bucket: must not land in the bucket series

    snap = registry.snapshot()
    series = "device_seconds_t_dp_bkt_bucket_256"
    assert snap["histograms"][series]["count"] == 1
    assert snap["histograms"]["device_seconds_t_dp_bkt"]["count"] == 2

    parsed = prom.parse_text(prom.render(snap))
    fam = prom.metric_name(series)
    hist = prom.histogram_series(parsed, fam)
    assert hist and hist["count"] == 1


def test_bucket_scope_restores_on_exit():
    with devprof.bucket_scope(128):
        with devprof.bucket_scope(512):
            assert devprof._tls.bucket == 512
        assert devprof._tls.bucket == 128
    assert devprof._tls.bucket is None


# -- round decomposition -------------------------------------------------

def test_round_scope_partitions_wall_time(monkeypatch):
    monkeypatch.setattr(devprof, "time", _FakeClock())
    devprof.enable("full")
    h0 = (obs.get_histogram("devprof_round_device_seconds") or {})
    n0 = h0.get("count", 0)
    fn = obs.instrumented_jit(lambda v: v * 7, program="t_dp_round")
    with devprof.round_scope():
        fn(jnp.arange(8, dtype=jnp.float32))
    hd = obs.get_histogram("devprof_round_device_seconds")
    hh = obs.get_histogram("devprof_round_host_seconds")
    assert hd["count"] == n0 + 1 and hh["count"] >= 1
    # fake clock: round wall == 3 ticks (one inter-read tick + sampled
    # dispatch dt 1.0); device est 1.0 clamps inside [0, wall]
    assert 0.0 <= hd["sum"] <= hh["sum"] + hd["sum"]


def test_round_scope_off_is_noop():
    n0 = obs.get_counter("devprof_rounds_total")
    with devprof.round_scope():
        pass
    assert obs.get_counter("devprof_rounds_total") == n0


# -- transfer accounting -------------------------------------------------

def test_transfer_bumps_legacy_and_per_phase_names():
    before = _counters("host_to_device_bytes", "h2d_bytes_total",
                       "h2d_bytes_serve", "device_to_host_bytes",
                       "d2h_bytes_total")
    devprof.transfer("h2d", "serve", 4096, transfers=2)
    devprof.transfer("d2h", "serve", 512)
    after = _counters("host_to_device_bytes", "h2d_bytes_total",
                      "h2d_bytes_serve", "device_to_host_bytes",
                      "d2h_bytes_total")
    assert tuple(a - b for a, b in zip(after, before)) == (
        4096, 4096, 4096, 512, 512)
    with pytest.raises(ValueError):
        devprof.transfer("sideways", "serve", 1)


# -- bench_regress --program-threshold -----------------------------------

def _bench_result(value, programs=None):
    res = {"metric": "rows_per_sec", "value": value, "unit": "rows/s"}
    if programs is not None:
        res["profile"] = {"mode": "sample:4", "rounds": 8,
                          "device_seconds_est_total": sum(
                              p["device_seconds_est"]
                              for p in programs.values()),
                          "programs": programs}
        res["device"] = {"platform": "cpu", "device_kind": "cpu",
                         "jax_version": "x"}
    return res


def test_bench_regress_program_threshold_gates():
    from tools.bench_regress import compare
    base = _bench_result(1000.0, {
        "train_step": {"device_seconds_est": 0.8},
        "grow_tree": {"device_seconds_est": 0.2}})
    cand = _bench_result(1010.0, {
        "train_step": {"device_seconds_est": 0.82},
        "grow_tree": {"device_seconds_est": 0.48}})   # +140%

    v = compare(base, cand, 10.0, program_threshold_pct=25.0)
    assert v["ok"] is False and v["programs_ok"] is False
    assert v["programs_delta"]["grow_tree"]["ok"] is False
    assert v["programs_delta"]["grow_tree"]["delta_pct"] == pytest.approx(
        140.0)
    assert v["programs_delta"]["train_step"]["ok"] is True

    wide = compare(base, cand, 10.0, program_threshold_pct=200.0)
    assert wide["ok"] is True and wide["programs_ok"] is True


def test_bench_regress_old_baselines_unaffected():
    from tools.bench_regress import compare
    old = _bench_result(1000.0)                      # pre-r16: no profile
    cand = _bench_result(1010.0, {
        "train_step": {"device_seconds_est": 5.0}})

    v = compare(old, cand, 10.0, program_threshold_pct=25.0)
    assert v["ok"] is True and v["programs_ok"] is True
    assert "programs_note" in v and "baseline" in v["programs_note"]
    # informational passthrough rides only on the side that has it
    assert "profile_candidate" in v and "profile_baseline" not in v

    # without the flag the verdict carries no per-program keys at all
    plain = compare(old, cand, 10.0)
    assert "programs_ok" not in plain and "programs_delta" not in plain
