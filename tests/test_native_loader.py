"""Native C++ data loader: build, then parity with the Python parser on
the real reference example files (CSV/TSV/LibSVM, weights, ragged rows)."""

import numpy as np
import pytest

from lightgbm_tpu.io.native import (get_lib, parse_file_native,
                                    values_to_bins_native)
from lightgbm_tpu.io import parser as pyparser

REF = "/root/reference/examples"

needs_native = pytest.mark.skipif(get_lib() is None,
                                  reason="native toolchain unavailable")


def _python_parse(path, **kw):
    """The pure-Python reference path (bypassing the native fast path)."""
    import unittest.mock as mock
    with mock.patch("lightgbm_tpu.io.native.parse_file_native",
                    return_value=None):
        return pyparser.parse_file(path, **kw)


@needs_native
@pytest.mark.parametrize("path", [
    f"{REF}/regression/regression.train",        # tsv
    f"{REF}/regression/regression.test",
    f"{REF}/binary_classification/binary.train",
    f"{REF}/binary_classification/binary.test",  # tsv
    f"{REF}/multiclass_classification/multiclass.train",
    f"{REF}/multiclass_classification/multiclass.test",
    f"{REF}/lambdarank/rank.train",              # libsvm
    f"{REF}/lambdarank/rank.test",
    f"{REF}/parallel_learning/binary.train",
    f"{REF}/parallel_learning/binary.test",
])
def test_native_matches_python_on_reference_files(path):
    y_n, X_n, _, bad = parse_file_native(path)
    assert bad == -1
    y_p, X_p, _ = _python_parse(path)
    assert X_n.shape == X_p.shape
    np.testing.assert_allclose(y_n, y_p, rtol=1e-12)
    np.testing.assert_allclose(X_n, X_p, rtol=1e-9, atol=1e-12)


@needs_native
def test_native_no_trailing_newline(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("1,0.5,2\n0,1.5,3")  # last line unterminated
    y, X, _, bad = parse_file_native(str(p))
    assert bad == -1
    np.testing.assert_allclose(y, [1, 0])
    np.testing.assert_allclose(X, [[0.5, 2.0], [1.5, 3.0]])


@needs_native
def test_native_libsvm_label_less_rows(tmp_path):
    # Predict-time LibSVM: first token is an index:value pair, so the row
    # has no label (parser.py:67-71); label must default to 0 and feature 0
    # must NOT swallow the first pair.
    p = tmp_path / "d.svm"
    p.write_text("0:1.5 2:2.5\n1:3.5\n")
    y_n, X_n, fmt, bad = parse_file_native(str(p))
    assert fmt == "libsvm"
    assert bad == -1
    y_p, X_p, _ = _python_parse(str(p))
    assert X_n.shape == X_p.shape == (2, 3)
    np.testing.assert_allclose(y_n, [0.0, 0.0])
    np.testing.assert_allclose(X_n, [[1.5, 0.0, 2.5], [0.0, 3.5, 0.0]])
    np.testing.assert_allclose(X_n, X_p)
    np.testing.assert_allclose(y_n, y_p)


@needs_native
def test_native_csv_with_header_and_exponents(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("label,a,b\n1,0.5,-2e3\n0,1.25e-2,3\n2,-0.75,+4.5\n")
    y, X, header = pyparser.parse_file(str(p), has_header=True)
    np.testing.assert_allclose(y, [1, 0, 2])
    np.testing.assert_allclose(X, [[0.5, -2000.0], [0.0125, 3.0],
                                   [-0.75, 4.5]])
    assert header == ["a", "b"]


@needs_native
def test_values_to_bins_matches_searchsorted():
    rng = np.random.RandomState(0)
    values = rng.normal(size=100_000) * 10
    bounds = np.sort(rng.normal(size=31) * 10)
    bounds = np.concatenate([bounds, [np.inf]])
    got = values_to_bins_native(values, bounds, np.uint8)
    want = np.searchsorted(bounds[:-1], values, side="left")
    np.testing.assert_array_equal(got.astype(np.int64), want)


@needs_native
def test_values_to_bins_u16():
    rng = np.random.RandomState(1)
    values = rng.uniform(0, 1000, size=70_000)
    bounds = np.concatenate([np.linspace(1, 999, 999), [np.inf]])
    got = values_to_bins_native(values, bounds, np.uint16)
    want = np.searchsorted(bounds[:-1], values, side="left")
    np.testing.assert_array_equal(got.astype(np.int64), want)


@needs_native
def test_native_nan_token_and_no_label(tmp_path):
    # NA spellings and empty fields are MISSING values (NaN) — the
    # reference's parser semantics, mirrored by io/guard.feature_value;
    # the bin mappers route NaN to bin 0 (test_binning_nan_goes_to_bin_zero)
    p = tmp_path / "d.csv"
    p.write_text("1,nan,2\n0,3,na\n1,NULL,\n")
    y, X, _, bad = parse_file_native(str(p))
    assert bad == -1          # NA tokens are clean input, not dirt
    np.testing.assert_allclose(
        X, [[np.nan, 2.0], [3.0, np.nan], [np.nan, np.nan]])
    # label_idx=-1: no label column, all columns are features
    y2, X2, _, _ = parse_file_native(str(p), label_idx=-1)
    np.testing.assert_allclose(y2, [0.0, 0.0, 0.0])
    assert X2.shape == (3, 3)


@needs_native
def test_native_na_parity_with_python(tmp_path):
    """Native-vs-Python parser parity on a file containing NA tokens:
    both must emit NaN for na/NaN/NULL/none and empty fields."""
    p = tmp_path / "na.csv"
    p.write_text("1,na,2.5\n0,3.5,NaN\n1,NULL,none\n0,,4.5\n")
    y_n, X_n, _, bad = parse_file_native(str(p))
    assert bad == -1
    y_p, X_p, _ = _python_parse(str(p))
    np.testing.assert_allclose(y_n, y_p)
    np.testing.assert_allclose(X_n, X_p)
    assert np.isnan(X_p[0, 0]) and np.isnan(X_p[1, 1])
    assert np.isnan(X_p[2, 0]) and np.isnan(X_p[2, 1])
    assert np.isnan(X_p[3, 0])


@needs_native
def test_native_flags_malformed_rows(tmp_path):
    """The native loader reports the first malformed row instead of
    silently parsing garbage to 0.0 — the flag is what reroutes dirty
    files through the guarded Python path."""
    p = tmp_path / "dirty.csv"
    p.write_text("1,0.5,2\n0,xx,3\n1,4,5\n")
    assert parse_file_native(str(p))[3] == 2
    r = tmp_path / "ragged.csv"
    r.write_text("1,0.5,2\n0,3\n")
    assert parse_file_native(str(r))[3] == 2
    s = tmp_path / "neg.svm"
    s.write_text("1 0:1.5\n0 -2:3.0\n")
    assert parse_file_native(str(s))[3] == 2
    c = tmp_path / "clean.csv"
    c.write_text("1,0.5,2\n0,1.5,3\n")
    assert parse_file_native(str(c))[3] == -1


def test_binning_nan_goes_to_bin_zero():
    from lightgbm_tpu.io.binning import BinMapper
    rng = np.random.RandomState(0)
    m = BinMapper().find_bin(rng.normal(size=500), 500, 16, 3, 0)
    vals = np.array([np.nan, 0.0, 1.0])
    bins = m.value_to_bin(vals)
    assert bins[0] == 0
