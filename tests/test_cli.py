"""CLI application tests: the reference's examples/*/train.conf must run
unmodified (SURVEY.md §7 step 5), in-process via lightgbm_tpu.cli.main."""

import os

import numpy as np
import pytest

from lightgbm_tpu import cli

REF_EXAMPLES = "/root/reference/examples"


def _run_in(tmp_path, conf_dir, conf, extra=()):
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        return cli.main([f"config={os.path.join(conf_dir, conf)}", *extra])
    finally:
        os.chdir(cwd)


@pytest.mark.skipif(not os.path.isdir(REF_EXAMPLES),
                    reason="reference examples not mounted")
def test_regression_conf_train_and_predict(tmp_path):
    conf_dir = os.path.join(REF_EXAMPLES, "regression")
    rc = _run_in(tmp_path, conf_dir, "train.conf",
                 [f"data={conf_dir}/regression.train",
                  f"valid_data={conf_dir}/regression.test",
                  "num_trees=5"])
    assert rc == 0
    model = tmp_path / "LightGBM_model.txt"
    assert model.exists()
    text = model.read_text()
    assert text.startswith("gbdt") or text.startswith("tree")
    assert "Tree=0" in text

    rc = _run_in(tmp_path, conf_dir, "predict.conf",
                 [f"data={conf_dir}/regression.test",
                  f"input_model={model}"])
    assert rc == 0
    out = np.loadtxt(tmp_path / "LightGBM_predict_result.txt")
    assert out.shape[0] == 500
    assert np.isfinite(out).all()


@pytest.mark.skipif(not os.path.isdir(REF_EXAMPLES),
                    reason="reference examples not mounted")
def test_binary_conf_with_weights(tmp_path):
    conf_dir = os.path.join(REF_EXAMPLES, "binary_classification")
    rc = _run_in(tmp_path, conf_dir, "train.conf",
                 [f"data={conf_dir}/binary.train",
                  f"valid_data={conf_dir}/binary.test",
                  "num_trees=5"])
    assert rc == 0
    assert (tmp_path / "LightGBM_model.txt").exists()


@pytest.mark.skipif(not os.path.isdir(REF_EXAMPLES),
                    reason="reference examples not mounted")
def test_lambdarank_conf_with_query(tmp_path):
    conf_dir = os.path.join(REF_EXAMPLES, "lambdarank")
    rc = _run_in(tmp_path, conf_dir, "train.conf",
                 [f"data={conf_dir}/rank.train",
                  f"valid_data={conf_dir}/rank.test",
                  "num_trees=5"])
    assert rc == 0
    assert (tmp_path / "LightGBM_model.txt").exists()


@pytest.mark.skipif(not os.path.isdir(REF_EXAMPLES),
                    reason="reference examples not mounted")
def test_multiclass_conf(tmp_path):
    conf_dir = os.path.join(REF_EXAMPLES, "multiclass_classification")
    rc = _run_in(tmp_path, conf_dir, "train.conf",
                 [f"data={conf_dir}/multiclass.train",
                  f"valid_data={conf_dir}/multiclass.test",
                  "num_trees=5"])
    assert rc == 0
    assert (tmp_path / "LightGBM_model.txt").exists()


def _write_tsv(path, y, X):
    with open(path, "w") as fh:
        for yi, row in zip(y, X):
            fh.write(f"{yi:g}\t" + "\t".join(f"{v:.6g}" for v in row) + "\n")


def test_chunked_file_predict_and_num_iteration_predict(tmp_path,
                                                        monkeypatch):
    """File prediction streams in O(chunk) pieces (predictor.hpp:81-129)
    and the CLI honors num_iteration_predict (config.h:97): predictions
    must match the in-memory path exactly, across chunk boundaries, and a
    truncated model must differ from the full one."""
    from lightgbm_tpu import Dataset, train
    from lightgbm_tpu.basic import Booster

    rng = np.random.RandomState(7)
    n, f = 1000, 8
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    train_file = tmp_path / "chunk.train"
    _write_tsv(train_file, y, X)
    bst = train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                 "min_data_in_leaf": 20}, Dataset(X, label=y),
                num_boost_round=12)
    model = tmp_path / "model.txt"
    bst.save_model(str(model))

    # chunk the file into many pieces and compare with in-memory predict
    monkeypatch.setattr(Booster, "_PREDICT_CHUNK_ROWS", 64)
    loaded = Booster(model_file=str(model))
    via_file = loaded.predict(str(train_file))
    in_mem = loaded.predict(X)
    np.testing.assert_allclose(via_file, in_mem, rtol=0, atol=0)

    # CLI respects num_iteration_predict
    conf = tmp_path / "predict.conf"
    conf.write_text("task = predict\n"
                    f"data = {train_file}\n"
                    f"input_model = {model}\n"
                    "num_iteration_predict = 3\n")
    rc = _run_in(tmp_path, str(tmp_path), "predict.conf")
    assert rc == 0
    out3 = np.loadtxt(tmp_path / "LightGBM_predict_result.txt")
    # the CLI writes %g (6 significant digits)
    np.testing.assert_allclose(out3, loaded.predict(X, num_iteration=3),
                               rtol=1e-5, atol=1e-7)
    assert not np.allclose(out3, in_mem)
