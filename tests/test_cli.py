"""CLI application tests: the reference's examples/*/train.conf must run
unmodified (SURVEY.md §7 step 5), in-process via lightgbm_tpu.cli.main."""

import os

import numpy as np
import pytest

from lightgbm_tpu import cli

REF_EXAMPLES = "/root/reference/examples"


def _run_in(tmp_path, conf_dir, conf, extra=()):
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        return cli.main([f"config={os.path.join(conf_dir, conf)}", *extra])
    finally:
        os.chdir(cwd)


@pytest.mark.skipif(not os.path.isdir(REF_EXAMPLES),
                    reason="reference examples not mounted")
def test_regression_conf_train_and_predict(tmp_path):
    conf_dir = os.path.join(REF_EXAMPLES, "regression")
    rc = _run_in(tmp_path, conf_dir, "train.conf",
                 [f"data={conf_dir}/regression.train",
                  f"valid_data={conf_dir}/regression.test",
                  "num_trees=5"])
    assert rc == 0
    model = tmp_path / "LightGBM_model.txt"
    assert model.exists()
    text = model.read_text()
    assert text.startswith("gbdt") or text.startswith("tree")
    assert "Tree=0" in text

    rc = _run_in(tmp_path, conf_dir, "predict.conf",
                 [f"data={conf_dir}/regression.test",
                  f"input_model={model}"])
    assert rc == 0
    out = np.loadtxt(tmp_path / "LightGBM_predict_result.txt")
    assert out.shape[0] == 500
    assert np.isfinite(out).all()


@pytest.mark.skipif(not os.path.isdir(REF_EXAMPLES),
                    reason="reference examples not mounted")
def test_binary_conf_with_weights(tmp_path):
    conf_dir = os.path.join(REF_EXAMPLES, "binary_classification")
    rc = _run_in(tmp_path, conf_dir, "train.conf",
                 [f"data={conf_dir}/binary.train",
                  f"valid_data={conf_dir}/binary.test",
                  "num_trees=5"])
    assert rc == 0
    assert (tmp_path / "LightGBM_model.txt").exists()


@pytest.mark.skipif(not os.path.isdir(REF_EXAMPLES),
                    reason="reference examples not mounted")
def test_lambdarank_conf_with_query(tmp_path):
    conf_dir = os.path.join(REF_EXAMPLES, "lambdarank")
    rc = _run_in(tmp_path, conf_dir, "train.conf",
                 [f"data={conf_dir}/rank.train",
                  f"valid_data={conf_dir}/rank.test",
                  "num_trees=5"])
    assert rc == 0
    assert (tmp_path / "LightGBM_model.txt").exists()


@pytest.mark.skipif(not os.path.isdir(REF_EXAMPLES),
                    reason="reference examples not mounted")
def test_multiclass_conf(tmp_path):
    conf_dir = os.path.join(REF_EXAMPLES, "multiclass_classification")
    rc = _run_in(tmp_path, conf_dir, "train.conf",
                 [f"data={conf_dir}/multiclass.train",
                  f"valid_data={conf_dir}/multiclass.test",
                  "num_trees=5"])
    assert rc == 0
    assert (tmp_path / "LightGBM_model.txt").exists()
