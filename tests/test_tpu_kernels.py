"""Opt-in ON-DEVICE Pallas kernel gate (VERDICT round-2 weak #5): the
Mosaic-compiled kernels are otherwise exercised only through bench.py's
end-to-end AUC; this runs them against the scatter references on a real
TPU.

    LGBT_TPU_KERNELS=1 python -m pytest tests/test_tpu_kernels.py -q

Must run WITHOUT tests/conftest.py's CPU forcing, so this module restores
the TPU platform when the gate env var is set (the conftest override only
applies to the default run)."""

import os

import numpy as np
import pytest

_GATE = bool(os.environ.get("LGBT_TPU_KERNELS"))

if _GATE:
    os.environ["JAX_PLATFORMS"] = os.environ.get("LGBT_TPU_PLATFORM", "")
    import jax
    if os.environ["JAX_PLATFORMS"] == "":
        del os.environ["JAX_PLATFORMS"]
    jax.config.update("jax_platforms", None)

pytestmark = pytest.mark.skipif(
    not _GATE, reason="on-TPU kernel gate is opt-in (LGBT_TPU_KERNELS=1)")


def _require_tpu():
    import jax
    if jax.devices()[0].platform != "tpu":
        pytest.skip("no TPU device available")


def test_digit_histogram_mosaic_matches_scatter():
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.ops import leafhist as lh

    _require_tpu()
    rng = np.random.RandomState(0)
    n, f, b = 100_000, 28, 255
    bins = jnp.asarray(rng.randint(0, b, size=(n, f)), jnp.uint8)
    g = jnp.asarray(rng.normal(size=n), jnp.float32)
    h = jnp.abs(g) + 0.1
    w = jnp.asarray((rng.uniform(size=n) < 0.8), jnp.float32)
    scales = lh.compute_scales(g, h, w)
    digits = lh.quantize_digits(g, h, w, scales)
    got = np.asarray(lh.digit_histogram_pallas(bins, digits, b))
    want = np.asarray(lh.digit_histogram_scatter(bins, digits, b))
    np.testing.assert_array_equal(got, want)


def test_children_histograms_mosaic_matches_reference():
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import build_children_histograms
    from lightgbm_tpu.ops.pallas_histogram import children_histograms_pallas

    _require_tpu()
    rng = np.random.RandomState(1)
    n, f, b = 50_000, 8, 64
    bins = jnp.asarray(rng.randint(0, b, size=(f, n)), jnp.int32)
    g = jnp.asarray(rng.normal(size=n), jnp.float32)
    h = jnp.abs(g) + 0.1
    w = jnp.ones(n, jnp.float32)
    leaf = jnp.asarray(rng.randint(0, 5, size=n), jnp.int32)
    want = np.asarray(build_children_histograms(bins, g, h, w, leaf, 1, 3, b))
    got = np.asarray(children_histograms_pallas(bins, g, h, w, leaf, 1, 3, b))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-3)


def test_ordered_grower_on_device_matches_cpu_reference():
    """One full tree grown on the TPU must match the CPU-grown tree: the
    Mosaic kernel + segment sorts + packed bookkeeping, end to end."""
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.ops.grow import GrowParams
    from lightgbm_tpu.ops.ordered_grow import grow_tree_ordered

    _require_tpu()
    rng = np.random.RandomState(2)
    n, f, b = 60_000, 10, 64
    bins_rm = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    g = rng.normal(size=n).astype(np.float32)
    h = (np.abs(rng.normal(size=n)) + 0.1).astype(np.float32)
    params = GrowParams(num_leaves=31, max_bin=b, min_data_in_leaf=20,
                        min_sum_hessian_in_leaf=1.0)

    def run(device, force_scatter):
        from lightgbm_tpu.ops import leafhist
        orig = leafhist._on_tpu
        if force_scatter:
            # the platform dispatcher is process-global; the CPU reference
            # run must take the scatter path explicitly
            leafhist._on_tpu = lambda: False
        try:
            with jax.default_device(device):
                t, leaf, delta = grow_tree_ordered(
                    jnp.asarray(bins_rm.T), jnp.full((f,), b, jnp.int32),
                    jnp.zeros((f,), bool), jnp.ones((f,), bool),
                    jnp.asarray(g), jnp.asarray(h),
                    jnp.ones((n,), jnp.float32),
                    jnp.float32(0.1), params, bins_rm=jnp.asarray(bins_rm))
                return (np.asarray(t.split_feature),
                        np.asarray(t.split_bin),
                        np.asarray(leaf), np.asarray(delta))
        finally:
            leafhist._on_tpu = orig

    tpu_out = run(jax.devices("tpu")[0], force_scatter=False)
    cpu_out = run(jax.devices("cpu")[0], force_scatter=True)
    np.testing.assert_array_equal(tpu_out[0], cpu_out[0])
    np.testing.assert_array_equal(tpu_out[1], cpu_out[1])
    np.testing.assert_array_equal(tpu_out[2], cpu_out[2])
    # identical splits and routing; leaf VALUES round differently in f32
    # across backends (measured <= 1e-4 relative on <0.1% of rows)
    np.testing.assert_allclose(tpu_out[3], cpu_out[3], rtol=2e-4, atol=1e-6)
