"""Guarded model lifecycle acceptance (serve/lifecycle.py).

The chaos gates from the issue:

- under sustained multi-threaded load, a canary poisoned with
  ``slow_replica`` auto-rolls-back inside the observation window with
  ZERO failed client requests; the reason is named in ``/stats``-shape
  controller stats AND the ``Serve::verdict`` trace span, and
  ``lifecycle_rollbacks_total`` moves by exactly 1;
- a ``skew_predictions``-poisoned canary is convicted by the labeled
  feedback quality gate (rolling logloss), not by latency or errors;
- a clean canary auto-PROMOTES, and the post-swap predictions bit-match
  a manual ``Fleet.promote`` of the same model — with the compile ledger
  pinned flat across the whole begin→verdict cycle (the controller is
  host-side bookkeeping, zero new XLA programs);
- a restart mid-window serves the last-good primary and demotes the
  unvetted candidate to un-promoted (never half-promoted, never
  resurrected as primary);
- shadow scoring never degrades real traffic: with the canary wedged,
  primary requests keep succeeding fast while shadow work is dropped
  and counted;
- an unproven candidate is extended, then rolled back at the hard
  window bound, and the post-rollback cooldown backs off exponentially
  and convicts an immediate re-reload with reason ``cooldown``.

Stub forests drive the scheduling chaos (deterministic, fast); the
promote-bit-match and restart tests run real ``CompiledForest``s.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import compile_ledger, prom, tracing
from lightgbm_tpu.serve import (Fleet, GuardrailPolicy, FeedbackTracker,
                                PredictServer, PromotionController, Replica,
                                ReplicaSet, ShadowScorer)
from lightgbm_tpu.serve.fleet import ModelManager
from lightgbm_tpu.serve.forest import CompiledForest
from lightgbm_tpu.serve.lifecycle import IDLE, OBSERVING
from lightgbm_tpu.testing import faults

pytestmark = [pytest.mark.serve, pytest.mark.lifecycle]

BUCKETS = [16, 64]


@pytest.fixture
def tracer(tmp_path, monkeypatch):
    """Arm the process tracer (same pattern as tests/test_fleet.py)."""
    path = tmp_path / "trace_events.json"
    tracing.TRACER.reset()
    monkeypatch.setenv(tracing.ENV_PATH, str(path))
    tracing.TRACER.configure()
    yield path
    tracing.TRACER.disable()
    tracing.TRACER.reset()
    tracing.TRACER.path = None


class StubForest:
    """Duck-typed CompiledForest: constant predictions, optional fixed
    service time (the test_serve_chaos.py stub)."""

    num_trees = 1
    num_class = 1

    def __init__(self, service_s=0.0, value=1.0, num_features=4,
                 device=None):
        self.service_s = float(service_s)
        self.value = float(value)
        self.num_features = int(num_features)
        self.device = device

    def batched_fn(self):
        def fn(rows):
            if self.service_s:
                time.sleep(self.service_s)
            out = np.full((1, rows.shape[0]), self.value, np.float32)
            return out, out
        return fn

    def to_device(self, device):
        return StubForest(self.service_s, self.value, self.num_features,
                          device)

    def warmup(self, buckets=None, max_bucket=None):
        return self

    def info(self):
        return {"num_trees": 1, "num_class": 1,
                "num_features": self.num_features}


def _canary_fleet(n_primary=2, canary_value=2.0, canary_weight=0.25,
                  primary_value=1.0, **kw):
    """A stub fleet WITH a canary slot (generation 2), watchdog off —
    verdicts must come from the lifecycle controller, not the health
    state machine."""
    preps = [Replica(StubForest(value=primary_value), i, "primary", 1,
                     max_batch=256, max_delay_s=0.0, max_queue=0)
             for i in range(n_primary)]
    crep = Replica(StubForest(value=canary_value), 0, "canary", 2,
                   max_batch=256, max_delay_s=0.0, max_queue=0)
    fleet = Fleet(ReplicaSet(preps, "primary", 1),
                  ReplicaSet([crep], "canary", 2,
                             model_path="stub-canary.txt"),
                  canary_weight=canary_weight,
                  watchdog_interval_s=0.0, **kw)
    return fleet, preps, crep


def _prom_counter(name):
    parsed = prom.parse_text(prom.render())
    vals = [v for n, labels, v in parsed["samples"]
            if n == f"lightgbm_tpu_{name}" and not labels]
    return vals[0] if vals else 0.0


def _hammer(fleet, n_threads, stop_evt, errors, served):
    def client():
        while not stop_evt.is_set():
            try:
                res = fleet.submit(np.ones((1, 4), np.float32),
                                   timeout=30.0)
                served.append(float(np.asarray(res.out)[0, 0]))
            except Exception as exc:   # any client-visible failure
                errors.append(repr(exc))
                return
    threads = [threading.Thread(target=client) for _ in range(n_threads)]
    for t in threads:
        t.start()
    return threads


def _wait_until(pred, timeout_s=8.0, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval_s)
    return pred()


def _train_and_save(tmp_path, name, rounds, lr=0.1, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(800, 6))
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                     "min_data_in_leaf": 20, "learning_rate": lr},
                    lgb.Dataset(X, label=y), num_boost_round=rounds)
    path = str(tmp_path / name)
    bst.save_model(path)
    return path, X


# ---------------------------------------------------------------------------
# the acceptance gate: slow canary under load -> auto-rollback, zero failures


def test_slow_canary_rolls_back_under_load_zero_failures(tmp_path, tracer):
    fleet, _preps, _crep = _canary_fleet()
    manager = ModelManager(fleet, state_file=str(tmp_path / "state.json"))
    policy = GuardrailPolicy(min_samples=12, latency_ratio=3.0,
                             error_rate=1.0)
    ctrl = PromotionController(fleet, manager, policy, window_s=1.0,
                               max_window_s=8.0, cooldown_s=60.0,
                               interval_s=0.05)
    r0 = _prom_counter("lifecycle_rollbacks_total")
    lr0 = _prom_counter("lifecycle_rollback_latency_ratio")
    errors, served = [], []
    stop_evt = threading.Event()
    try:
        with faults.slow_replica(fleet, 0, 0.05, model="canary"):
            ctrl.begin("stub-canary.txt", 2)
            assert ctrl.stats()["phase"] == OBSERVING
            threads = _hammer(fleet, 4, stop_evt, errors, served)
            assert _wait_until(lambda: not fleet.has_canary(),
                               timeout_s=10.0), \
                f"slow canary never rolled back: {ctrl.stats()}"
            # traffic keeps flowing on the primary after the rollback
            n_after = len(served)
            assert _wait_until(lambda: len(served) > n_after + 50,
                               timeout_s=5.0)
            stop_evt.set()
            for t in threads:
                t.join(timeout=10.0)
    finally:
        stop_evt.set()
        ctrl.close()
        fleet.close(drain=False)

    assert errors == [], f"client-visible failures during rollback: {errors[:3]}"
    assert served, "no requests served at all"
    # exactly one verdict, reason named everywhere it should be
    assert _prom_counter("lifecycle_rollbacks_total") == r0 + 1
    assert _prom_counter("lifecycle_rollback_latency_ratio") == lr0 + 1
    stats = ctrl.stats()
    assert stats["phase"] == IDLE
    assert stats["last_verdict"]["outcome"] == "rollback"
    assert stats["last_verdict"]["reason"] == "latency_ratio"
    gate = stats["last_verdict"]["verdict"]["gates"]["latency_ratio"]
    assert gate["armed"] and not gate["ok"]
    # verdict reaches the event stream
    verdicts = [e for e in tracing.TRACER.events()
                if e.get("name") == "Serve::verdict"]
    assert any((e.get("args") or {}).get("outcome") == "rollback"
               and (e.get("args") or {}).get("reason") == "latency_ratio"
               for e in verdicts), verdicts
    # and the state file carries no half-promoted candidate
    state = json.loads((tmp_path / "state.json").read_text())
    assert "canary" not in state
    assert state["lifecycle"]["phase"] == IDLE
    assert state["lifecycle"]["consecutive_rollbacks"] == 1


# ---------------------------------------------------------------------------
# quality gate: a skewed canary is convicted by labeled feedback


def test_skewed_canary_convicted_by_quality_gate(tmp_path):
    fleet, _preps, _crep = _canary_fleet(canary_value=0.5,
                                         canary_weight=0.5,
                                         primary_value=0.5)
    manager = ModelManager(fleet, state_file=str(tmp_path / "state.json"))
    fb = FeedbackTracker()
    policy = GuardrailPolicy(min_samples=8, latency_ratio=0.0,
                             error_rate=1.0)
    ctrl = PromotionController(fleet, manager, policy, window_s=30.0,
                               cooldown_s=0.0, feedback=fb,
                               interval_s=30.0)
    q0 = _prom_counter("lifecycle_rollback_quality")
    try:
        # primary answers 0.5 (logloss ln2); the skewed canary answers
        # ~0.99 — confidently wrong on every label-0 request
        with faults.skew_predictions(fleet, 0.49, model="canary") as stats:
            assert stats["offset"] == pytest.approx(0.49)
            ctrl.begin("stub-canary.txt", 2)
            rows = np.ones((1, 4), np.float32)
            # labels alternate PER MODEL (independent of how routing
            # interleaves the two models): both windows see a 50/50
            # label mix, so only the scores differ
            seen = {"primary": 0, "canary": 0}
            for i in range(64):
                res = fleet.submit(rows, timeout=30.0)
                score = float(np.asarray(res.out)[0, 0])
                fb.note(i, res.model, score)
                assert fb.feedback(i, float(seen[res.model] % 2))
                seen[res.model] += 1
            quality = fb.quality()
            assert quality["canary"]["n"] >= policy.min_samples
            assert quality["primary"]["n"] >= policy.min_samples
            assert quality["canary"]["logloss"] > \
                quality["primary"]["logloss"] + 0.05
            ctrl.tick()
    finally:
        ctrl.close()
        fleet.close(drain=False)
    stats = ctrl.stats()
    assert stats["last_verdict"]["outcome"] == "rollback"
    assert stats["last_verdict"]["reason"] == "quality"
    assert not fleet.has_canary()
    assert _prom_counter("lifecycle_rollback_quality") == q0 + 1
    # the rolling-quality gauges that fed the verdict are published
    assert obs.get_gauge(obs.labeled_name("lifecycle_quality_logloss",
                                          model="canary")) is not None


# ---------------------------------------------------------------------------
# clean canary -> auto-promote, bit-match vs manual Fleet.promote


def test_clean_canary_auto_promotes_bitmatch_manual(tmp_path):
    path_a, X = _train_and_save(tmp_path, "a.txt", rounds=3)
    path_b, _ = _train_and_save(tmp_path, "b.txt", rounds=5, lr=0.3)
    rows5 = X[:5].astype(np.float32)

    def _build():
        fa = CompiledForest.from_booster(lgb.Booster(model_file=path_a),
                                         buckets=BUCKETS)
        fb_ = CompiledForest.from_booster(lgb.Booster(model_file=path_b),
                                          buckets=BUCKETS)
        fa.warmup(max_bucket=64)
        fb_.warmup(max_bucket=64)
        return fb_, Fleet.build(fa, devices=[None], canary_forest=fb_,
                                canary_weight=0.5, max_batch=64,
                                max_delay_s=0.001, warm=False)

    forest_b1, fleet = _build()        # the controller promotes this one
    forest_b2, fleet_manual = _build()  # the operator promotes this one
    manager = ModelManager(fleet, state_file=str(tmp_path / "state.json"))
    policy = GuardrailPolicy(min_samples=5, latency_ratio=0.0,
                             error_rate=1.0)
    ctrl = None
    p0 = _prom_counter("lifecycle_promotions_total")
    try:
        # everything compiled and warmed BEFORE the cycle under test
        fleet.submit(rows5, timeout=30.0)
        fleet_manual.submit(rows5, timeout=30.0)
        fleet_manual.promote(forest_b2, target="primary",
                             model_path=path_b)
        want = np.asarray(fleet_manual.submit(rows5, timeout=30.0).out)

        n_ledger = len(compile_ledger.events())
        ctrl = PromotionController(fleet, manager, policy, window_s=0.4,
                                   max_window_s=4.0, cooldown_s=60.0,
                                   interval_s=0.05)
        ctrl.begin(path_b, 2)

        def _feed():
            if fleet.has_canary():
                fleet.submit(rows5, timeout=30.0)
                return False
            return True
        assert _wait_until(_feed, timeout_s=20.0), \
            f"clean canary never promoted: {ctrl.stats()}"
        res = fleet.submit(rows5, timeout=30.0)
        # the promoted primary IS the canary forest: bit-match against
        # the manually promoted fleet, same generation arithmetic
        assert res.model == "primary"
        assert np.array_equal(np.asarray(res.out), want)
        assert fleet.generation == fleet_manual.generation == 3
        # zero new XLA programs across begin -> verdict -> post-swap serve
        assert len(compile_ledger.events()) == n_ledger
    finally:
        if ctrl is not None:
            ctrl.close()
        fleet.close(drain=False)
        fleet_manual.close(drain=False)

    stats = ctrl.stats()
    assert stats["last_verdict"]["outcome"] == "promote"
    assert stats["last_verdict"]["candidate"] == path_b
    assert _prom_counter("lifecycle_promotions_total") == p0 + 1
    state = json.loads((tmp_path / "state.json").read_text())
    assert state["primary"]["model"] == path_b
    assert "canary" not in state
    assert state["lifecycle"]["phase"] == IDLE


# ---------------------------------------------------------------------------
# crash safety: restart mid-window -> last-good primary, candidate demoted


def test_restart_mid_window_serves_last_good_primary(tmp_path):
    """SIGKILL-shaped restart between ``/reload target=canary`` and the
    verdict: the relaunched server serves the last-good primary, the
    unvetted candidate is NOT resurrected (neither as canary nor as a
    half-promoted primary), and the interruption is named."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.serve.server import serve_from_config

    path_a, X = _train_and_save(tmp_path, "a.txt", rounds=3)
    path_b, _ = _train_and_save(tmp_path, "b.txt", rounds=5, lr=0.3)
    state = tmp_path / "serve_state.json"
    conf = {"task": "serve", "input_model": path_a, "serve_port": 0,
            "serve_state_file": str(state), "serve_max_batch": 64,
            "predict_buckets": [16, 64], "serve_watchdog_ms": 0,
            "serve_canary_weight": 0.2, "lifecycle_window_s": 60.0,
            "verbose": -1}
    srv = serve_from_config(Config(dict(conf))).start()
    try:
        assert srv._ready.wait(120.0)
        assert srv.controller is not None
        host, port = srv.address
        req = urllib.request.Request(
            f"http://{host}:{port}/reload",
            data=json.dumps({"model": path_b,
                             "target": "canary"}).encode())
        resp = json.loads(urllib.request.urlopen(req, timeout=180).read())
        assert resp["target"] == "canary"
        assert srv.controller.stats()["phase"] == OBSERVING
        persisted = json.loads(state.read_text())
        assert persisted["lifecycle"]["phase"] == OBSERVING
        assert persisted["lifecycle"]["candidate"] == path_b
    finally:
        # stop() without a verdict: the state file still says a window
        # was open — exactly what a SIGKILL mid-evaluation leaves behind
        srv.stop()

    i0 = _prom_counter("lifecycle_interrupted_total")
    srv2 = serve_from_config(Config(dict(conf))).start()
    try:
        assert srv2._ready.wait(120.0)
        # last-good primary (model A), candidate demoted to un-promoted
        a_trees = lgb.Booster(model_file=path_a).num_trees()
        assert srv2.forest.num_trees == a_trees
        assert not srv2.fleet.has_canary()
        assert _prom_counter("lifecycle_interrupted_total") == i0 + 1
        verdict = srv2.controller.stats()["last_verdict"]
        assert verdict["outcome"] == "interrupted"
        assert verdict["reason"] == "restart_mid_window"
        assert verdict["candidate"] == path_b
        # the re-persisted record no longer claims an open window
        assert json.loads(state.read_text())["lifecycle"]["phase"] == IDLE
        # served predictions come from model A, not the candidate
        host, port = srv2.address
        body = json.dumps({"rows": X[:3].tolist()}).encode()
        req = urllib.request.Request(
            f"http://{host}:{port}/predict", data=body,
            headers={"Content-Type": "application/json"})
        resp = json.loads(urllib.request.urlopen(req, timeout=30).read())
        want = CompiledForest.from_booster(
            lgb.Booster(model_file=path_a), buckets=[16, 64]).predict(
                X[:3].astype(np.float32), device_binning=True)
        np.testing.assert_allclose(
            np.asarray(resp["predictions"], np.float32),
            np.asarray(want, np.float32), rtol=1e-6, atol=1e-6)
    finally:
        srv2.stop()


# ---------------------------------------------------------------------------
# shadow isolation: a wedged canary cannot slow or shed real traffic


def test_shadow_never_degrades_primary_traffic():
    fleet, _preps, _crep = _canary_fleet(canary_weight=0.0)
    scorer = ShadowScorer(fleet, fraction=1.0, queue_max=4, timeout_s=0.2)
    d0 = _prom_counter("lifecycle_shadow_dropped_total")
    errors, latencies = [], []
    stop_evt = threading.Event()

    def client():
        rows = np.ones((1, 4), np.float32)
        while not stop_evt.is_set():
            t0 = time.monotonic()
            try:
                fleet.submit(rows, timeout=5.0)
            except Exception as exc:
                errors.append(repr(exc))
                return
            latencies.append(time.monotonic() - t0)
            scorer.offer(rows)

    try:
        with faults.wedge_replica(fleet, 0, model="canary"):
            threads = [threading.Thread(target=client) for _ in range(3)]
            for t in threads:
                t.start()
            assert _wait_until(
                lambda: _prom_counter("lifecycle_shadow_dropped_total")
                > d0, timeout_s=8.0), "shadow queue never dropped"
            time.sleep(0.3)
            stop_evt.set()
            for t in threads:
                t.join(timeout=10.0)
    finally:
        stop_evt.set()
        scorer.close()
        fleet.close(drain=False)
    assert errors == [], f"shadow load failed real requests: {errors[:3]}"
    assert len(latencies) > 100
    p99 = float(np.percentile(np.asarray(latencies), 99))
    assert p99 < 0.5, f"primary p99 degraded to {p99:.3f}s under shadow"
    assert _prom_counter("lifecycle_shadow_dropped_total") > d0


def test_shadow_fraction_sampling_and_bounds():
    fleet, _preps, _crep = _canary_fleet(canary_weight=0.0)
    try:
        with pytest.raises(ValueError, match="serve_shadow"):
            ShadowScorer(fleet, fraction=1.5)
        scorer = ShadowScorer(fleet, fraction=0.25, queue_max=64)
        try:
            rows = np.ones((1, 4), np.float32)
            picks = [scorer.offer(rows) for _ in range(20)]
            # deterministic accumulator: exactly every 4th offer mirrors
            assert sum(picks) == 5
            assert picks[3] and picks[7]
        finally:
            scorer.close()
    finally:
        fleet.close(drain=False)


# ---------------------------------------------------------------------------
# extend -> bounded -> insufficient_samples rollback -> cooldown backoff


def test_unproven_candidate_extends_then_cooldown_backoff(tmp_path):
    fleet, _preps, _crep = _canary_fleet()
    manager = ModelManager(fleet, state_file=str(tmp_path / "state.json"))
    policy = GuardrailPolicy(min_samples=10**6, latency_ratio=0.0,
                             error_rate=1.0)
    ctrl = PromotionController(fleet, manager, policy, window_s=0.08,
                               max_window_s=0.2, cooldown_s=60.0,
                               interval_s=30.0)
    e0 = _prom_counter("lifecycle_extensions_total")
    r0 = _prom_counter("lifecycle_rollbacks_total")
    c0 = _prom_counter("lifecycle_rollback_cooldown")
    i0 = _prom_counter("lifecycle_rollback_insufficient_samples")
    try:
        ctrl.begin("stub-canary.txt", 2)
        ctrl.tick()                      # inside the window: no action
        assert ctrl.stats()["phase"] == OBSERVING
        time.sleep(0.1)
        ctrl.tick()                      # past window, under hard end
        assert _prom_counter("lifecycle_extensions_total") == e0 + 1
        assert ctrl.stats()["phase"] == OBSERVING
        time.sleep(0.2)
        ctrl.tick()                      # past the hard bound: verdict
        stats = ctrl.stats()
        assert stats["phase"] == IDLE
        assert stats["last_verdict"]["reason"] == "insufficient_samples"
        assert not fleet.has_canary()
        assert _prom_counter("lifecycle_rollbacks_total") == r0 + 1
        assert _prom_counter(
            "lifecycle_rollback_insufficient_samples") == i0 + 1
        # an immediate re-reload hits the sticky cooldown, and the
        # backoff doubles: 60s -> 120s
        ctrl.begin("stub-canary.txt", 3)
        stats = ctrl.stats()
        assert stats["last_verdict"]["reason"] == "cooldown"
        assert stats["consecutive_rollbacks"] == 2
        assert stats["last_verdict"]["cooldown_s"] == pytest.approx(120.0)
        assert stats["cooldown_remaining_s"] > 60.0
        assert _prom_counter("lifecycle_rollback_cooldown") == c0 + 1
        # persisted for the next boot: a crash cannot launder the history
        persisted = json.loads((tmp_path / "state.json").read_text())
        assert persisted["lifecycle"]["consecutive_rollbacks"] == 2
        assert persisted["lifecycle"]["cooldown_until_t"] is not None
    finally:
        ctrl.close()
        fleet.close(drain=False)


# ---------------------------------------------------------------------------
# HTTP surface: POST /feedback joins labels, /stats carries the block


def test_feedback_endpoint_and_stats_block():
    preps = [Replica(StubForest(value=0.8), i, "primary", 1,
                     max_batch=256, max_delay_s=0.0, max_queue=0)
             for i in range(1)]
    fleet = Fleet(ReplicaSet(preps, "primary", 1))
    srv = PredictServer(fleet, port=0).start()
    host, port = srv.address
    base = f"http://{host}:{port}"

    def _post(path, payload, timeout=30):
        req = urllib.request.Request(
            base + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        return json.loads(urllib.request.urlopen(req, timeout=timeout)
                          .read())

    try:
        resp = _post("/predict", {"rows": [[1.0, 1.0, 1.0, 1.0]]})
        req_id = resp["request_id"]
        assert resp["model"] == "primary"
        ack = _post("/feedback", {"request_id": req_id, "label": 1})
        assert ack["status"] == "ok" and ack["request_id"] == req_id
        # a second delivery for the same id is a 404 (already joined)
        with pytest.raises(urllib.error.HTTPError) as err:
            _post("/feedback", {"request_id": req_id, "label": 1})
        assert err.value.code == 404
        err.value.read()
        # malformed label -> 400
        with pytest.raises(urllib.error.HTTPError) as err:
            _post("/feedback", {"request_id": 1, "label": "nan"})
        assert err.value.code == 400
        err.value.read()
        # the stats block carries the rolling quality the label fed
        stats = json.loads(urllib.request.urlopen(
            base + "/stats", timeout=30).read())
        assert "lifecycle" in stats
        quality = stats["lifecycle"]["quality"]
        assert quality["primary"]["n"] == 1
        assert stats["lifecycle"]["controller"] is None  # not configured
        assert obs.get_gauge(obs.labeled_name(
            "lifecycle_quality_logloss", model="primary")) is not None
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# satellite: EFB multi-host refusal is now a visible gauge


def test_efb_disabled_multihost_gauge(monkeypatch):
    from lightgbm_tpu.io.bundling import plan_bundles
    from lightgbm_tpu.parallel import multihost

    monkeypatch.setattr(multihost, "process_rank_world", lambda: (0, 2))
    obs.set_gauge("efb_disabled_multihost", 0)
    sample = np.zeros((4, 1))
    plan = plan_bundles(sample, [object()], [0],
                        max_conflict_rate=0.0, max_total_bin=255)
    assert plan is None
    assert obs.get_gauge("efb_disabled_multihost") == 1
