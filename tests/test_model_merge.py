"""Safe forest merging + warm-start delta training (the retrain half of
the guarded lifecycle).

- ``Booster.merge(other, shrinkage_decay=d)`` predicts exactly
  ``base + d * delta`` (raw scores, bit-equal: d is a power of two and
  the scaled copies carry exact leaf values), and the merged model
  round-trips through the model TEXT unchanged;
- incompatible merges refuse with NAMED errors — num_class, feature
  width, objective, a shrinkage_decay outside (0, 1] — from Python AND
  through ``LGBM_BoosterMerge`` (C API return -1 + LGBM_GetLastError);
- ``engine.train_delta(base, fresh_data, num_trees=)`` continues from
  the base model: its first ``base.num_trees()`` trees bit-match the
  base's model text.
"""

import re

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import LightGBMError

pytestmark = [pytest.mark.lifecycle]


def _make_data(seed=0, n=600, width=6):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, width))
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
    return X, y


def _train(X, y, rounds, lr=0.1, objective="binary", **params):
    p = {"objective": objective, "num_leaves": 7, "verbose": -1,
         "min_data_in_leaf": 20, "learning_rate": lr}
    p.update(params)
    return lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=rounds)


def _trees(model_text):
    """Split a model text into its Tree= blocks (footer stripped, block
    numbering and trailing whitespace normalized so position-independent
    content compares byte for byte)."""
    body = model_text.split("feature importances:", 1)[0]
    blocks = [b for b in re.split(r"(?=Tree=\d+\n)", body)
              if b.startswith("Tree=")]
    return [re.sub(r"^Tree=\d+\n", "", b).rstrip("\n") for b in blocks]


# ---------------------------------------------------------------------------
# exact merge arithmetic + model-text round trip


def test_merge_predicts_base_plus_decayed_delta(tmp_path):
    X, y = _make_data()
    base = _train(X, y, rounds=4)
    other = _train(X, y, rounds=3, lr=0.3)
    pb = base.predict(X, raw_score=True)
    po = other.predict(X, raw_score=True)

    path_b = str(tmp_path / "base.txt")
    path_o = str(tmp_path / "other.txt")
    base.save_model(path_b)
    other.save_model(path_o)

    merged = lgb.Booster(model_file=path_b)
    out = merged.merge(lgb.Booster(model_file=path_o), shrinkage_decay=0.5)
    assert out is merged
    assert merged.num_trees() == base.num_trees() + other.num_trees()
    pm = merged.predict(X, raw_score=True)
    assert np.array_equal(pm, pb + 0.5 * po), \
        f"max dev {np.max(np.abs(pm - (pb + 0.5 * po)))}"

    # full decay keeps the other model verbatim
    merged1 = lgb.Booster(model_file=path_b)
    merged1.merge(lgb.Booster(model_file=path_o), shrinkage_decay=1.0)
    assert np.array_equal(merged1.predict(X, raw_score=True), pb + po)

    # round trip through the model text: same trees, same predictions
    path_m = str(tmp_path / "merged.txt")
    merged.save_model(path_m)
    reloaded = lgb.Booster(model_file=path_m)
    assert reloaded.num_trees() == merged.num_trees()
    assert np.array_equal(reloaded.predict(X, raw_score=True), pm)


def test_merge_uses_config_shrinkage_decay_by_default(tmp_path):
    X, y = _make_data()
    base = _train(X, y, rounds=3)
    other = _train(X, y, rounds=2, lr=0.3)
    path_b = str(tmp_path / "base.txt")
    path_o = str(tmp_path / "other.txt")
    base.save_model(path_b)
    other.save_model(path_o)
    pb = base.predict(X, raw_score=True)
    po = other.predict(X, raw_score=True)

    merged = lgb.Booster(model_file=path_b, params={"shrinkage_decay": 0.25})
    merged.merge(lgb.Booster(model_file=path_o))
    assert np.array_equal(merged.predict(X, raw_score=True),
                          pb + 0.25 * po)


# ---------------------------------------------------------------------------
# named refusals (Python surface)


def test_merge_refusals_are_named():
    X, y = _make_data()
    base = _train(X, y, rounds=2)

    # feature width mismatch
    Xw, yw = _make_data(seed=1, width=9)
    wide = _train(Xw, yw, rounds=2)
    with pytest.raises(LightGBMError, match="feature width mismatch"):
        base.merge(wide)

    # objective mismatch (same width)
    reg = _train(X, y, rounds=2, objective="regression")
    with pytest.raises(LightGBMError, match="objective mismatch"):
        base.merge(reg)

    # num_class mismatch (multiclass vs binary, same width)
    ym = (np.arange(len(y)) % 3).astype(np.float64)
    multi = _train(X, ym, rounds=2, objective="multiclass", num_class=3)
    with pytest.raises(LightGBMError, match="num_class mismatch"):
        multi.merge(base)

    # shrinkage_decay outside (0, 1]
    other = _train(X, y, rounds=2, lr=0.3)
    for bad in (0.0, -0.5, 1.5):
        with pytest.raises(LightGBMError, match="shrinkage_decay"):
            base.merge(other, shrinkage_decay=bad)


# ---------------------------------------------------------------------------
# named refusals through the C API (LGBM_BoosterMerge, satellite)


def test_c_api_booster_merge_routes_validated_merge(tmp_path):
    cffi = pytest.importorskip("cffi")
    from lightgbm_tpu.capi import impl

    X, y = _make_data()
    base = _train(X, y, rounds=3)
    other = _train(X, y, rounds=2, lr=0.3)
    Xw, yw = _make_data(seed=1, width=9)
    wide = _train(Xw, yw, rounds=2)
    paths = {}
    for name, bst in (("base", base), ("other", other), ("wide", wide)):
        paths[name] = str(tmp_path / f"{name}.txt")
        bst.save_model(paths[name])

    f = cffi.FFI()
    impl.bind(f, register_externs=False)

    def _load(path):
        out_iter = f.new("int *")
        out = f.new("void **")
        assert impl.LGBM_BoosterCreateFromModelfile(
            f.new("char[]", path.encode()), out_iter, out) == 0
        return out[0]

    h_base = _load(paths["base"])
    h_other = _load(paths["other"])
    h_wide = _load(paths["wide"])
    try:
        # incompatible: -1 + the named error through LGBM_GetLastError
        assert impl.LGBM_BoosterMerge(h_base, h_wide) == -1
        err = f.string(impl.LGBM_GetLastError()).decode()
        assert "feature width mismatch" in err

        # compatible: 0, trees appended (reference MergeFrom semantics)
        n_before = base.num_trees()
        assert impl.LGBM_BoosterMerge(h_base, h_other) == 0
        out_n = f.new("int *")
        assert impl.LGBM_BoosterGetCurrentIteration(h_base, out_n) == 0
        assert out_n[0] == n_before + other.num_trees()
    finally:
        for h in (h_base, h_other, h_wide):
            impl.LGBM_BoosterFree(h)


# ---------------------------------------------------------------------------
# warm-start delta training: base trees preserved bit-for-bit


def test_train_delta_preserves_base_trees(tmp_path):
    X, y = _make_data()
    base = _train(X, y, rounds=4)
    path_b = str(tmp_path / "base.txt")
    base.save_model(path_b)

    X2, y2 = _make_data(seed=7)
    delta = lgb.train_delta(path_b, lgb.Dataset(X2, label=y2), num_trees=3,
                            params={"objective": "binary", "num_leaves": 7,
                                    "verbose": -1, "min_data_in_leaf": 20})
    assert delta.num_trees() == base.num_trees() + 3

    path_d = str(tmp_path / "delta.txt")
    delta.save_model(path_d)
    base_trees = _trees(open(path_b).read())
    delta_trees = _trees(open(path_d).read())
    assert len(base_trees) == base.num_trees()
    assert len(delta_trees) == delta.num_trees()
    # the continuation never rewrites history: the first num_trees()
    # blocks of the delta model ARE the base model's, byte for byte
    assert delta_trees[:len(base_trees)] == base_trees
