"""Collective watchdog unit tests (parallel/watchdog.py): the heartbeat
mesh, the staleness/deadline trips, and the abort machinery — all
in-process (two meshes on localhost stand in for two ranks; the real
2-process path is the ``dist_chaos`` suite)."""

import socket
import time

import pytest

from lightgbm_tpu.parallel.watchdog import (DISTRIBUTED_ABORT_EXIT_CODE,
                                            CollectiveWatchdog,
                                            DistributedAborted,
                                            HeartbeatMesh)

pytestmark = pytest.mark.faults


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _machines(ports):
    return [("127.0.0.1", p) for p in ports]


def _wait_for(cond, timeout_s=5.0, step=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(step)
    return cond()


class FakeMesh:
    """Scripted peer ages for deadline-path tests."""

    def __init__(self, ages):
        self.ages = dict(ages)
        self.stopped = False

    def peer_ages(self):
        return dict(self.ages)

    def stop(self):
        self.stopped = True


def test_heartbeat_mesh_sees_live_peer_and_ages_dead_one():
    machines = _machines(_free_ports(2))
    m0 = HeartbeatMesh(machines, 0, interval_s=0.05)
    m1 = HeartbeatMesh(machines, 1, interval_s=0.05)
    try:
        # both directions converge to fresh heartbeats
        assert _wait_for(lambda: m0.peer_ages().get(1, 99) < 0.5)
        assert _wait_for(lambda: m1.peer_ages().get(0, 99) < 0.5)
        # kill rank 1: its age at rank 0 grows monotonically
        m1.stop()
        time.sleep(0.4)
        assert m0.peer_ages()[1] > 0.3
    finally:
        m0.stop()
        m1.stop()


def test_cooperative_check_raises_named_distributed_aborted():
    machines = _machines(_free_ports(2))
    m0 = HeartbeatMesh(machines, 0, interval_s=0.05)
    m1 = HeartbeatMesh(machines, 1, interval_s=0.05)
    wd = CollectiveWatchdog(0, 2, mesh=m0, heartbeat_s=0.05,
                            timeout_s=0.4, abort_fn=lambda code: None)
    try:
        # rank 1 was heard, then died: staleness is real evidence
        assert _wait_for(lambda: m0.peer_ages().get(1, 99) < 0.5)
        m1.stop()
        assert _wait_for(lambda: bool(wd.stale_peers()), timeout_s=3.0)
        with pytest.raises(DistributedAborted) as ei:
            wd.check("Comm::grow")
        err = ei.value
        assert err.rank == 1
        assert err.phase == "Comm::grow"
        assert err.last_seen > 0.3
        assert "rank 1" in str(err)
        # phase entry runs the same cooperative check
        with pytest.raises(DistributedAborted):
            with wd.phase("Comm::grow"):
                pass
    finally:
        wd.stop()
        m1.stop()


def test_never_heard_peers_degrade_instead_of_aborting():
    # no peer process ever existed: an undeliverable heartbeat channel
    # (blocked UDP) must NOT abort a healthy pod — it warns once and
    # leaves the deadline as the only detector
    from lightgbm_tpu.utils import log as lgb_log
    lgb_log.reset_warn_once()
    machines = _machines(_free_ports(2))
    m0 = HeartbeatMesh(machines, 0, interval_s=0.05)
    aborts = []
    wd = CollectiveWatchdog(0, 2, mesh=m0, heartbeat_s=0.05,
                            timeout_s=0.3, tick_s=0.05,
                            abort_fn=aborts.append)
    try:
        time.sleep(0.6)
        assert wd.stale_peers() == []
        assert m0.unheard_peers() == [1]
        wd.check("Comm::grow")          # no raise
        with wd._lock:
            wd._phase = ["Comm::grow", time.monotonic(), None, False]
        time.sleep(0.5)
        assert aborts == []             # no hard abort either
        assert "watchdog_channel_silent" in lgb_log._warned_once
    finally:
        wd.stop()


def test_hard_abort_fires_in_phase_on_stale_peer_and_flushes():
    machines = _machines(_free_ports(2))
    m0 = HeartbeatMesh(machines, 0, interval_s=0.05)
    m1 = HeartbeatMesh(machines, 1, interval_s=0.05)
    aborts = []
    flushed = []
    wd = CollectiveWatchdog(0, 2, mesh=m0, heartbeat_s=0.05,
                            timeout_s=0.5, tick_s=0.05,
                            abort_fn=aborts.append)
    wd.register_flush(lambda: flushed.append(True))
    try:
        assert _wait_for(lambda: m0.peer_ages().get(1, 99) < 0.5)
        m1.stop()
        # out of phase: a stale peer must NOT hard-abort (the next phase
        # entry raises cooperatively instead)
        time.sleep(0.8)
        assert aborts == []
        # simulate being wedged inside the collective: enter the phase
        # without the cooperative check (which would raise here)
        with wd._lock:
            wd._phase = ["Comm::grow", time.monotonic(), None, False]
        assert _wait_for(lambda: aborts, timeout_s=3.0)
        assert aborts[0] == DISTRIBUTED_ABORT_EXIT_CODE
        assert flushed == [True]
    finally:
        wd.stop()
        m1.stop()


def test_guard_classifies_collective_errors_and_passes_own_errors():
    from lightgbm_tpu.basic import LightGBMError
    fake = FakeMesh({1: 0.01})
    aborts = []
    wd = CollectiveWatchdog(0, 2, mesh=fake, heartbeat_s=0.05,
                            timeout_s=0.3, tick_s=10.0,
                            abort_fn=aborts.append)
    try:
        # peers alive: a genuine error re-raises after the wait window
        with pytest.raises(RuntimeError, match="xla exploded"):
            with wd.guard("Dist::resume"):
                raise RuntimeError("xla exploded")
        assert aborts == []
        # our own diagnostics pass through untouched, no classify wait
        t0 = time.monotonic()
        with pytest.raises(LightGBMError, match="deliberate"):
            with wd.guard("Dist::resume"):
                raise LightGBMError("deliberate diagnostic")
        assert time.monotonic() - t0 < 0.2
        # peer goes silent right as the collective errors: abort path
        with pytest.raises(RuntimeError):
            with wd.guard("Dist::resume"):
                fake.ages[1] = 99.0
                raise RuntimeError("connection reset by peer")
        assert aborts == [DISTRIBUTED_ABORT_EXIT_CODE]
    finally:
        wd.stop()


def test_phase_deadline_trips_without_peer_death():
    # peers look perfectly alive; only the round deadline expires
    wd = CollectiveWatchdog(0, 2, mesh=FakeMesh({1: 0.01}),
                            heartbeat_s=0.05, timeout_s=0.3, tick_s=0.05,
                            abort_fn=lambda code: None)
    aborts = []
    wd._abort_fn = aborts.append
    try:
        with wd._lock:
            wd._phase = ["Comm::grow", time.monotonic(),
                         time.monotonic() + 0.2, False]
        assert _wait_for(lambda: aborts, timeout_s=3.0)
    finally:
        wd.stop()


def test_effective_timeout_policy():
    wd = CollectiveWatchdog(0, 2, mesh=None, heartbeat_s=0.5,
                            timeout_s=0.0, abort_fn=lambda code: None)
    try:
        # auto mode floors at 60s and, before any EWMA sample, sets NO
        # per-phase deadline (round 1 includes its XLA compile)
        assert wd.effective_timeout() == pytest.approx(60.0)
        assert wd._phase_deadline() is None
        # the EWMA can only RAISE the bound, never tighten under the floor
        wd.note_comm_seconds(0.5)
        assert wd.effective_timeout() == pytest.approx(60.0)
        assert wd._phase_deadline() == pytest.approx(60.0)
        for _ in range(50):
            wd.note_comm_seconds(30.0)
        assert wd.effective_timeout() > 60.0
    finally:
        wd.stop()
    # explicit collective_timeout_s wins over everything
    wd2 = CollectiveWatchdog(0, 2, mesh=None, heartbeat_s=0.5,
                             timeout_s=7.0, abort_fn=lambda code: None)
    try:
        wd2.note_comm_seconds(30.0)
        assert wd2.effective_timeout() == pytest.approx(7.0)
        assert wd2._phase_deadline() == pytest.approx(7.0)
    finally:
        wd2.stop()


def test_abort_is_once_and_counts():
    from lightgbm_tpu import obs
    before = obs.get_counter("distributed_aborts_total")
    aborts = []
    wd = CollectiveWatchdog(0, 2, mesh=FakeMesh({1: 100.0}),
                            heartbeat_s=0.05, timeout_s=0.1, tick_s=0.02,
                            abort_fn=aborts.append)
    try:
        with wd._lock:
            wd._phase = ["Comm::grow", time.monotonic(), None, False]
        assert _wait_for(lambda: aborts, timeout_s=3.0)
        time.sleep(0.2)
        assert len(aborts) == 1          # latched: one abort only
        assert obs.get_counter("distributed_aborts_total") == before + 1
    finally:
        wd.stop()
