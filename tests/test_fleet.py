"""Serving fleet (lightgbm_tpu/serve/fleet.py).

Tier-1 CPU tests for the fleet layer: least-loaded dispatch under
skewed per-replica load, zero-downtime hot reload while clients hammer
``/predict`` (zero failed requests, old generation drains, predictions
bit-match the generation that served them, ZERO post-swap XLA compiles
asserted via the compile ledger), admission control (429 + sane
``Retry-After``, admitted-request p99 bounded), canary A/B split with
per-``model=`` metric labels parsed via ``obs/prom.py``, and the
request-id/trace-span guarantees on every error path.

Stub forests (constant predictions, controllable service time) drive
the scheduling/overload tests so they are deterministic and fast; the
hot-reload and warmup tests run real ``CompiledForest``s.
"""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import compile_ledger, prom, tracing
from lightgbm_tpu.serve import (Fleet, ModelManager, Overloaded,
                                PredictServer, Replica, ReplicaSet)
from lightgbm_tpu.serve.forest import CompiledForest

pytestmark = [pytest.mark.serve, pytest.mark.fleet]

BUCKETS = [16, 64]


@pytest.fixture
def tracer(tmp_path, monkeypatch):
    """Arm the process tracer (same pattern as tests/test_tracing.py)."""
    path = tmp_path / "trace_events.json"
    tracing.TRACER.reset()
    monkeypatch.setenv(tracing.ENV_PATH, str(path))
    tracing.TRACER.configure()
    yield path
    tracing.TRACER.disable()
    tracing.TRACER.reset()
    tracing.TRACER.path = None


class StubForest:
    """Duck-typed CompiledForest: constant predictions, fixed service
    time — deterministic fuel for dispatch/admission tests."""

    num_trees = 1
    num_class = 1

    def __init__(self, service_s=0.0, value=1.0, num_features=4,
                 device=None):
        self.service_s = float(service_s)
        self.value = float(value)
        self.num_features = int(num_features)
        self.device = device

    def batched_fn(self):
        def fn(rows):
            if self.service_s:
                time.sleep(self.service_s)
            out = np.full((1, rows.shape[0]), self.value, np.float32)
            return out, out
        return fn

    def to_device(self, device):
        return StubForest(self.service_s, self.value, self.num_features,
                          device)

    def warmup(self, buckets=None, max_bucket=None):
        return self

    def info(self):
        return {"num_trees": 1, "num_class": 1,
                "num_features": self.num_features}


def _stub_replicas(service_times, model="primary", generation=1,
                   max_queue=0, value=1.0):
    return [Replica(StubForest(s, value=value), i, model, generation,
                    max_batch=256, max_delay_s=0.0, max_queue=max_queue)
            for i, s in enumerate(service_times)]


def _train_and_save(tmp_path, name, rounds, lr=0.1, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(800, 6))
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                     "min_data_in_leaf": 20, "learning_rate": lr},
                    lgb.Dataset(X, label=y), num_boost_round=rounds)
    path = str(tmp_path / name)
    bst.save_model(path)
    return path, X


def _post(base, path, payload, timeout=60):
    body = (payload if isinstance(payload, bytes)
            else json.dumps(payload).encode())
    req = urllib.request.Request(base + path, data=body,
                                 headers={"Content-Type":
                                          "application/json"})
    resp = urllib.request.urlopen(req, timeout=timeout)
    return json.loads(resp.read()), dict(resp.headers)


# ---------------------------------------------------------------------------
# dispatch


def test_least_loaded_dispatch_skews_toward_fast_replica():
    """A 10x-slower replica must organically receive far less traffic:
    the load score is outstanding work x EWMA service time."""
    slow, fast = _stub_replicas([0.05, 0.005])
    fleet = Fleet(ReplicaSet([slow, fast], "primary", 1))
    stop = time.monotonic() + 1.5

    def client():
        while time.monotonic() < stop:
            res = fleet.submit(np.ones((2, 4), np.float32), timeout=10.0)
            assert res.generation == 1 and res.model == "primary"

    threads = [threading.Thread(target=client) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    fleet.close()
    assert fast.requests > 2 * slow.requests, \
        (slow.requests, fast.requests)
    st = fleet.stats()
    assert {r["replica"] for r in st["replicas"]} == {0, 1}
    assert all(r["inflight"] == 0 for r in st["replicas"])


def test_fleet_submit_after_close_raises():
    fleet = Fleet(ReplicaSet(_stub_replicas([0.0]), "primary", 1))
    fleet.close()
    with pytest.raises(RuntimeError):
        fleet.submit(np.ones((1, 4), np.float32))


# ---------------------------------------------------------------------------
# admission control


def test_inflight_cap_sheds_with_retry_hint():
    reps = _stub_replicas([0.1, 0.1], max_queue=8)
    fleet = Fleet(ReplicaSet(reps, "primary", 1), max_inflight=2)
    before = obs.get_counter("serve_shed_total")
    before_lbl = obs.get_counter(
        obs.labeled_name("serve_shed_total", model="primary"))
    shed, ok = [], []

    def client():
        for _ in range(6):
            try:
                fleet.submit(np.ones((1, 4), np.float32), timeout=10.0)
                ok.append(1)
            except Overloaded as exc:
                assert exc.retry_after_s > 0
                shed.append(1)
                time.sleep(0.01)

    threads = [threading.Thread(target=client) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    fleet.close()
    assert shed and ok, (len(shed), len(ok))
    delta = obs.get_counter("serve_shed_total") - before
    assert delta == len(shed)
    # ... and the same count landed in the model= labeled series
    assert obs.get_counter(obs.labeled_name(
        "serve_shed_total", model="primary")) - before_lbl == len(shed)


def test_bounded_replica_queue_sheds():
    """serve_queue_depth -> MicroBatcher(max_queue): with one replica
    wedged, the queue bound converts pile-up into Overloaded."""
    (rep,) = _stub_replicas([0.2], max_queue=1)
    fleet = Fleet(ReplicaSet([rep], "primary", 1))
    outcomes = []

    def client():
        try:
            fleet.submit(np.ones((1, 4), np.float32), timeout=10.0)
            outcomes.append("ok")
        except Overloaded:
            outcomes.append("shed")

    threads = [threading.Thread(target=client) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    fleet.close()
    assert "shed" in outcomes and "ok" in outcomes, outcomes


def test_overload_http_429_retry_after_and_bounded_p99():
    """The overload acceptance gate: at ~4x capacity, shed requests get
    429 + integral Retry-After >= 1, and the p99 of ADMITTED requests
    (read from the model-labeled serve_latency_seconds histogram)
    stays within 2x the unloaded p99 — admission control bends the
    tail instead of letting the queue stretch it."""
    model = "p99stub"
    reps = [Replica(StubForest(0.15), i, model, 1, max_batch=256,
                    max_delay_s=0.0, max_queue=8) for i in range(2)]
    fleet = Fleet(ReplicaSet(reps, model, 1), max_inflight=2)
    srv = PredictServer(fleet, port=0).start()
    host, port = srv.address
    base = f"http://{host}:{port}"
    series = obs.labeled_name("serve_latency_seconds", model=model)
    rows = {"rows": [[0.0, 0.0, 0.0, 0.0]]}

    def _hist_delta(h1, h0):
        counts0 = h0["counts"] if h0 else [0] * len(h1["counts"])
        return {"buckets": h1["buckets"],
                "counts": [a - b for a, b in zip(h1["counts"], counts0)],
                "sum": h1["sum"] - (h0["sum"] if h0 else 0.0),
                "count": h1["count"] - (h0["count"] if h0 else 0)}

    try:
        # unloaded phase: sequential requests
        h0 = obs.get_histogram(series)
        for _ in range(8):
            _post(base, "/predict", rows)
        h_unloaded = _hist_delta(obs.get_histogram(series), h0)
        p99_unloaded = obs.histogram_quantile(h_unloaded, 0.99)

        # loaded phase: ~4x capacity
        h1 = obs.get_histogram(series)
        sheds, retry_afters = [], []

        def client():
            admitted = attempts = 0
            while admitted < 4 and attempts < 60:
                attempts += 1
                try:
                    _, hdrs = _post(base, "/predict", rows)
                    admitted += 1
                except urllib.error.HTTPError as err:
                    assert err.code == 429, err.code
                    ra = err.headers.get("Retry-After")
                    assert ra is not None, "429 without Retry-After"
                    retry_afters.append(int(ra))
                    assert err.headers.get("X-Request-Id") is not None
                    sheds.append(1)
                    err.read()
                    time.sleep(0.02)

        threads = [threading.Thread(target=client) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        h_loaded = _hist_delta(obs.get_histogram(series), h1)
        p99_loaded = obs.histogram_quantile(h_loaded, 0.99)
    finally:
        srv.stop()
    assert sheds, "4x capacity never shed"
    assert all(1 <= ra <= 60 for ra in retry_afters), retry_afters
    assert p99_unloaded is not None and p99_loaded is not None
    assert p99_loaded <= 2.0 * p99_unloaded, \
        f"admitted p99 {p99_loaded:.3f}s vs unloaded {p99_unloaded:.3f}s"


# ---------------------------------------------------------------------------
# hot reload


def test_hot_reload_under_sustained_load(tmp_path):
    """The reload acceptance gate: clients hammer /predict across a
    POST /reload — zero failed requests, every response's predictions
    bit-match the generation that served it, the old generation drains,
    and the compile ledger records ZERO compiles after the swap (the
    new generation warmed on its replica's device first)."""
    import jax

    path_a, X = _train_and_save(tmp_path, "a.txt", rounds=3)
    path_b, _ = _train_and_save(tmp_path, "b.txt", rounds=6, lr=0.3)
    rows5 = X[:5].astype(np.float32)

    def _ref(path):
        cf = CompiledForest.from_booster(lgb.Booster(model_file=path),
                                         buckets=BUCKETS)
        return np.asarray(cf.predict(rows5, device_binning=True),
                          np.float32)

    ref = {1: _ref(path_a), 2: _ref(path_b)}
    assert np.abs(ref[1] - ref[2]).max() > 1e-3   # models distinguishable

    forest = CompiledForest.from_booster(lgb.Booster(model_file=path_a),
                                         buckets=BUCKETS)
    fleet = Fleet.build(forest, devices=jax.local_devices()[:1],
                        max_batch=64, max_delay_s=0.001, max_queue=256)
    srv = PredictServer(fleet, port=0).start()
    host, port = srv.address
    base = f"http://{host}:{port}"
    payload = {"rows": rows5.tolist()}

    results, errors = [], []
    stop_evt = threading.Event()

    def hammer():
        while not stop_evt.is_set():
            try:
                resp, hdrs = _post(base, "/predict", payload)
                results.append((resp["generation"], resp["predictions"],
                                hdrs.get("X-Request-Id")))
            except Exception as exc:  # any failure breaks the gate
                errors.append(repr(exc))
                return

    threads = [threading.Thread(target=hammer) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.4)
        drained_before = obs.get_counter("serve_generations_drained")
        resp, _ = _post(base, "/reload", {"model": path_b}, timeout=180)
        assert resp["status"] == "ok" and resp["generation"] == 2
        n_ledger = len(compile_ledger.events())
        time.sleep(0.4)
    finally:
        stop_evt.set()
        for t in threads:
            t.join()
    # post-swap traffic only hits warmed programs
    for _ in range(5):
        resp, _ = _post(base, "/predict", payload)
        results.append((resp["generation"], resp["predictions"], "x"))
    stats, _ = json.loads(urllib.request.urlopen(
        base + "/stats", timeout=30).read()), None
    srv.stop()

    assert errors == [], errors[:3]
    gens = sorted({g for g, _, _ in results})
    assert gens == [1, 2], gens                  # both generations served
    for gen, preds, req_id in results:
        assert req_id is not None
        got = np.asarray(preds, np.float32)
        assert np.array_equal(got, ref[gen]), \
            f"generation {gen} response does not bit-match its forest"
    assert len(compile_ledger.events()) == n_ledger, \
        "XLA compiled on the serving path after the swap"
    assert obs.get_counter("serve_generations_drained") \
        == drained_before + 1
    fleet_stats = stats["fleet"]
    assert fleet_stats["generation"] == 2
    assert all(r["generation"] == 2 for r in fleet_stats["replicas"])


def test_hedged_retry_on_replica_failure():
    """A predict failure on one replica is retried on a different one
    (bounded by retry_limit), counted in serve_retries_total, and the
    client sees only the good answer."""

    class FlakyForest(StubForest):
        def __init__(self):
            super().__init__(value=7.0)
            self.calls = 0

        def batched_fn(self):
            def fn(rows):
                self.calls += 1
                raise ValueError("injected replica fault")
            return fn

    flaky = FlakyForest()
    good = StubForest(value=7.0)
    reps = [Replica(flaky, 0, "primary", 1, max_batch=64,
                    max_delay_s=0.0, max_queue=0),
            Replica(good, 1, "primary", 1, max_batch=64,
                    max_delay_s=0.0, max_queue=0)]
    fleet = Fleet(ReplicaSet(reps, "primary", 1), retry_limit=2)
    r0 = obs.get_counter("serve_retries_total")
    r0_lbl = obs.get_counter(obs.labeled_name("serve_retries_total",
                                              model="primary"))
    # drive until the least-loaded pick lands on the flaky replica at
    # least once; every submit must still succeed via the hedge
    for _ in range(8):
        res = fleet.submit(np.ones((1, 4), np.float32), timeout=10.0)
        assert float(np.asarray(res.out)[0, 0]) == 7.0
        assert res.replica == 1                  # the answer came from good
    assert flaky.calls >= 1, "flaky replica never picked"
    retries = obs.get_counter("serve_retries_total") - r0
    assert retries >= flaky.calls
    assert obs.get_counter(obs.labeled_name(
        "serve_retries_total", model="primary")) - r0_lbl == retries
    # the errors marked the replica suspect (watchdog would eject it)
    assert reps[0].consecutive_errors >= 1 or reps[0].health != "healthy"
    fleet.close()


def test_retry_limit_exhaustion_propagates_original_error():
    class BrokenForest(StubForest):
        def __init__(self):
            super().__init__()
            self.calls = 0

        def batched_fn(self):
            def fn(rows):
                self.calls += 1
                raise ValueError("always broken")
            return fn

    reps = [Replica(BrokenForest(), i, "primary", 1, max_batch=64,
                    max_delay_s=0.0, max_queue=0) for i in range(2)]
    fleet = Fleet(ReplicaSet(reps, "primary", 1), retry_limit=1)
    with pytest.raises(ValueError, match="always broken"):
        fleet.submit(np.ones((1, 4), np.float32), timeout=10.0)
    fleet.close()

    # single-replica fleet: NO retry against the one replica that just
    # failed — the original error propagates after exactly one predict,
    # and the error account grows by one, not retry_limit+1
    lone = Replica(BrokenForest(), 0, "primary", 1, max_batch=64,
                   max_delay_s=0.0, max_queue=0)
    fleet = Fleet(ReplicaSet([lone], "primary", 1), retry_limit=2)
    r0 = obs.get_counter("serve_retries_total")
    with pytest.raises(ValueError, match="always broken"):
        fleet.submit(np.ones((1, 4), np.float32), timeout=10.0)
    assert lone.forest.calls == 1
    assert lone.consecutive_errors == 1
    assert obs.get_counter("serve_retries_total") - r0 == 0
    fleet.close()


def test_canary_with_zero_replicas_falls_back_to_primary():
    """An all-ejected canary must not turn its traffic share into hard
    503s while healthy primary capacity sits idle: the canary slice
    falls back (counted), and recovers once the canary is healthy."""
    primary = ReplicaSet(_stub_replicas([0.0], value=1.0), "primary", 1)
    canary = ReplicaSet(_stub_replicas([0.0], model="canary",
                                       generation=2, value=2.0),
                        "canary", 2)
    fleet = Fleet(primary, canary, canary_weight=0.5)
    from lightgbm_tpu.serve.health import EJECTED, HEALTHY
    with fleet._cond:
        canary.replicas[0].health = EJECTED
    f0 = obs.get_counter("serve_canary_fallback_total")
    for _ in range(8):
        res = fleet.submit(np.ones((1, 4), np.float32), timeout=10.0)
        assert res.model == "primary"            # every request lands
        assert float(np.asarray(res.out)[0, 0]) == 1.0
    assert obs.get_counter("serve_canary_fallback_total") - f0 == 4
    # canary re-admitted: its share comes back
    with fleet._cond:
        canary.replicas[0].health = HEALTHY
    served = {"primary": 0, "canary": 0}
    for _ in range(8):
        served[fleet.submit(np.ones((1, 4), np.float32),
                            timeout=10.0).model] += 1
    assert served["canary"] == 4
    fleet.close()


def test_reload_failure_paths_leave_generation_untouched(tmp_path):
    """ModelManager.reload failure matrix (satellite): corrupt model
    file, warmup raising, width mismatch mid-swap — each error leaves
    the serving generation and its predictions untouched, and the fleet
    keeps serving."""
    from lightgbm_tpu.testing import faults

    path_a, X = _train_and_save(tmp_path, "a.txt", rounds=3)
    rows3 = X[:3].astype(np.float32)
    forest = CompiledForest.from_booster(lgb.Booster(model_file=path_a),
                                         buckets=BUCKETS)
    forest.warmup(max_bucket=64)
    # a zero-weight canary pins the request schema so the width-mismatch
    # arm of the matrix has a live "other" model to collide with
    fleet = Fleet.build(forest, devices=[None], max_batch=64,
                        max_delay_s=0.001, warm=False,
                        canary_forest=forest, canary_weight=0.0)
    manager = ModelManager(fleet)
    want = np.asarray(fleet.submit(rows3).out)

    def _assert_untouched():
        assert fleet.generation == 1
        res = fleet.submit(rows3)
        assert res.generation == 1
        assert np.array_equal(np.asarray(res.out), want)

    # corrupt model file: loader raises, nothing was built
    corrupt = tmp_path / "corrupt.txt"
    corrupt.write_bytes(b"\x00\xffnot a model\x13\x37" * 16)
    with pytest.raises(Exception):
        manager.reload(str(corrupt))
    _assert_untouched()

    # warmup raising mid-build: half-built replicas are closed, the
    # swap never happens
    path_b, _ = _train_and_save(tmp_path, "b.txt", rounds=5, lr=0.3)
    with faults.fail_warmup(times=1):
        with pytest.raises(faults.InjectedCrash):
            manager.reload(str(path_b))
    _assert_untouched()

    # width mismatch mid-swap (against the OTHER live model's schema)
    from lightgbm_tpu.utils.log import LightGBMError
    with pytest.raises(LightGBMError, match="request schema"):
        fleet.promote(StubForest(num_features=9), target="primary")
    _assert_untouched()

    # and a clean reload still works after all three failures
    # (generation 3: the zero-weight canary holds generation 2)
    assert manager.reload(str(path_b)) == 3
    assert fleet.generation == 3
    fleet.close()


def test_restore_path_tolerates_malformed_state(tmp_path):
    """A damaged/hand-edited state file degrades to the boot model —
    it must never keep the server from starting."""
    p = tmp_path / "state.json"
    for content in (json.dumps({"primary": "old.txt"}),      # not a dict
                    json.dumps({"primary": {"model": 123}}),  # not a str
                    json.dumps(["not", "a", "dict"]),
                    "{broken json"):
        p.write_text(content)
        assert ModelManager.restore_path(str(p)) is None
    assert ModelManager.restore_path(str(tmp_path / "missing.json")) \
        is None


def test_reload_error_paths(tmp_path):
    fleet = Fleet(ReplicaSet(_stub_replicas([0.0]), "primary", 1))
    srv = PredictServer(fleet, port=0).start()
    host, port = srv.address
    base = f"http://{host}:{port}"
    try:
        for payload in ({}, {"model": str(tmp_path / "missing.txt")}):
            req = urllib.request.Request(
                base + "/reload", data=json.dumps(payload).encode())
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=30)
            assert err.value.code == 400
            assert err.value.headers.get("X-Request-Id") is not None
            err.value.read()
    finally:
        srv.stop()


def test_reload_rejects_width_mismatch():
    fleet = Fleet(ReplicaSet(_stub_replicas([0.0]), "primary", 1),
                  canary=ReplicaSet(_stub_replicas([0.0], model="canary",
                                                   generation=2),
                                    "canary", 2),
                  canary_weight=0.5)
    from lightgbm_tpu.utils.log import LightGBMError
    with pytest.raises(LightGBMError, match="request schema"):
        fleet.promote(StubForest(num_features=9), target="primary")
    fleet.close()


# ---------------------------------------------------------------------------
# canary routing + model= labels


def test_canary_split_and_model_labels():
    """25% canary weight -> an exact deterministic 1-in-4 split, with
    every serve metric labeled per model and parseable back out of the
    Prometheus exposition (obs/prom.py)."""
    primary = ReplicaSet(_stub_replicas([0.0], value=1.0), "primary", 1)
    canary = ReplicaSet(_stub_replicas([0.0], model="canary",
                                       generation=2, value=2.0),
                        "canary", 2)
    before = {m: obs.get_counter(obs.labeled_name("serve_requests",
                                                  model=m))
              for m in ("primary", "canary")}
    fleet = Fleet(primary, canary, canary_weight=0.25)
    n = 200
    served = {"primary": 0, "canary": 0}
    for _ in range(n):
        res = fleet.submit(np.ones((1, 4), np.float32), timeout=10.0)
        served[res.model] += 1
        # the canary's constant prediction proves the response really
        # came from the model it claims
        want = 1.0 if res.model == "primary" else 2.0
        assert float(np.asarray(res.out)[0, 0]) == want
    fleet.close()
    assert served["canary"] == n // 4            # deterministic rotation
    assert served["primary"] == n - n // 4

    text = prom.render()
    parsed = prom.parse_text(text)
    for m in ("primary", "canary"):
        got = [v for name, labels, v in parsed["samples"]
               if name == "lightgbm_tpu_serve_requests"
               and labels.get("model") == m]
        assert got, f"no model={m} labeled serve_requests sample"
        assert got[0] - before[m] == served[m]
        hist = prom.histogram_series(
            parsed, "lightgbm_tpu_serve_latency_seconds",
            match={"model": m})
        assert hist["count"] is not None and hist["count"] >= served[m]


# ---------------------------------------------------------------------------
# error paths: X-Request-Id + Serve::request span closure (satellite fix)


def test_error_responses_echo_request_id_and_close_span(tracer):
    """Shed (429), bad input (400) and unknown-path (404) responses all
    carry X-Request-Id, and their Serve::request spans land CLOSED in
    the trace export with the response status recorded."""
    (rep,) = _stub_replicas([0.3], max_queue=1)
    fleet = Fleet(ReplicaSet([rep], "primary", 1), max_inflight=1)
    srv = PredictServer(fleet, port=0).start()
    host, port = srv.address
    base = f"http://{host}:{port}"
    codes = {}
    try:
        # wedge the single replica so the next requests shed
        blocker = threading.Thread(
            target=lambda: _post(base, "/predict",
                                 {"rows": [[0.0] * 4]}, timeout=30))
        blocker.start()
        time.sleep(0.1)
        got429 = 0
        for _ in range(6):
            try:
                _post(base, "/predict", {"rows": [[0.0] * 4]}, timeout=30)
            except urllib.error.HTTPError as err:
                assert err.code == 429
                assert err.headers.get("X-Request-Id") is not None
                codes[int(err.headers["X-Request-Id"])] = 429
                got429 += 1
                err.read()
        blocker.join()
        assert got429 > 0
        # bad input: wrong feature width
        try:
            _post(base, "/predict", {"rows": [[1.0, 2.0]]})
        except urllib.error.HTTPError as err:
            assert err.code == 400
            assert err.headers.get("X-Request-Id") is not None
            codes[int(err.headers["X-Request-Id"])] = 400
            err.read()
        # malformed body
        try:
            _post(base, "/predict", b"{nope")
        except urllib.error.HTTPError as err:
            assert err.code == 400
            assert err.headers.get("X-Request-Id") is not None
            codes[int(err.headers["X-Request-Id"])] = 400
            err.read()
    finally:
        srv.stop()
    assert any(c == 400 for c in codes.values())
    events = tracing.read_trace(str(tracer))
    spans = {e["args"]["request_id"]: e for e in events
             if e.get("ph") == "X" and e["name"] == "Serve::request"
             and "request_id" in (e.get("args") or {})}
    for req_id, code in codes.items():
        ev = spans.get(req_id)
        assert ev is not None, \
            f"request {req_id} ({code}) has no closed Serve::request span"
        assert ev["args"].get("status") == code, (req_id, ev["args"])


# ---------------------------------------------------------------------------
# device placement (satellite fix: warmup on the target device)


def test_to_device_copy_warms_without_hotpath_compiles(tmp_path):
    """CompiledForest.to_device + warmup() must leave NOTHING for the
    serving path to compile — the mechanism behind zero post-swap
    compiles in the reload test, pinned in isolation here."""
    import jax

    path, X = _train_and_save(tmp_path, "m.txt", rounds=3)
    base = CompiledForest.from_booster(lgb.Booster(model_file=path),
                                       buckets=BUCKETS)
    dev = jax.local_devices()[0]
    rep = base.to_device(dev)
    assert rep.device is dev
    assert "device" in rep.info()
    rep.warmup(max_bucket=64)
    n_ledger = len(compile_ledger.events())
    fn = rep.batched_fn()
    for n in (1, 3, 16, 33, 64):
        raw, out = fn(X[:n].astype(np.float32))
        assert raw.shape == (1, n)
    assert len(compile_ledger.events()) == n_ledger, \
        "warmed to_device replica compiled on the hot path"
    # the copy serves the same predictions as the original
    want = base.predict(X[:20].astype(np.float32), device_binning=True)
    got = rep.predict(X[:20].astype(np.float32), device_binning=True)
    assert np.array_equal(np.asarray(got), np.asarray(want))


# ---------------------------------------------------------------------------
# bench plumbing (satellite: BENCH JSON keys)


def test_bench_regress_accepts_fleet_keys(tmp_path, capsys):
    """Old baseline (no fleet keys) vs new candidate (with them) must
    compare cleanly, and the fleet curve rides into the verdict."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import bench_regress
    finally:
        sys.path.pop(0)
    baseline = {"metric": "serve_rows_per_sec_x", "value": 1000.0,
                "unit": "rows/sec", "warmup_s": 10.0}
    candidate = {"metric": "serve_rows_per_sec_x", "value": 1100.0,
                 "unit": "rows/sec", "warmup_s": 9.0,
                 "concurrency": 4,
                 "fleet": {"1": {"rows_per_sec": 500.0, "shed_rate": 0.0},
                           "2": {"rows_per_sec": 900.0,
                                 "shed_rate": 0.01}},
                 "availability": {"serve_retries_total": 3,
                                  "serve_ejections_total": 1,
                                  "serve_deadline_expired_total": 0}}
    b = tmp_path / "base.json"
    c = tmp_path / "cand.json"
    b.write_text(json.dumps(baseline))
    c.write_text(json.dumps(candidate))
    rc = bench_regress.main(["--baseline", str(b), "--candidate", str(c),
                             "--threshold", "5",
                             "--warmup-threshold", "50"])
    assert rc == 0
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["ok"]
    assert verdict["fleet_candidate_rows_per_sec"] == {"1": 500.0,
                                                       "2": 900.0}
    assert verdict["fleet_candidate_shed_rate"] == {"2": 0.01}
    assert "fleet_baseline_rows_per_sec" not in verdict
    # round 9: the availability counters pass through informationally on
    # whichever side carries them — and never gate the verdict
    assert verdict["availability_candidate"] == {
        "serve_retries_total": 3, "serve_ejections_total": 1,
        "serve_deadline_expired_total": 0}
    assert "availability_baseline" not in verdict
