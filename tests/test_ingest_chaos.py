"""Ingest chaos suite (docs/FAULT_TOLERANCE.md §Data boundary): the
corpus injectors in lightgbm_tpu/testing/faults.py drive real dirt
through the real loaders, and containment is pinned end to end.

Acceptance gates (ISSUE 13):

- training on a 5%-mangled file under ``bad_data_policy=quarantine``
  BIT-MATCHES training on the clean subset, with ``bad_rows_total``
  equal to the mangled count and every rejected line present in the
  quarantine file with a reason;
- ``fail_fast`` on the same file raises ``LightGBMError`` naming the
  file, the first bad line, and the offending token;
- serve-side malformed / oversized / non-finite payloads return
  structured 400/413 with ZERO ``Predict::forest`` spans in the
  request trace.
"""

import json
import urllib.error
import urllib.request
from unittest import mock

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.io.guard import IngestGuard, read_quarantine
from lightgbm_tpu.io.streaming import load_file_two_round
from lightgbm_tpu.obs import tracing
from lightgbm_tpu.serve import PredictServer
from lightgbm_tpu.testing import faults
from lightgbm_tpu.utils.log import LightGBMError

pytestmark = pytest.mark.ingest_chaos

GARBAGE = "##garbage##"


def _write_train_file(path, n=400, seed=7):
    """A TSV training file: label = f0 > 0, three informative-ish
    features, %.6f so every reload parses bit-identically."""
    rng = np.random.RandomState(seed)
    rows = []
    for _ in range(n):
        f = rng.normal(size=3)
        rows.append("\t".join([f"{int(f[0] > 0)}"]
                              + [f"{v:.6f}" for v in f]))
    path.write_text("\n".join(rows) + "\n")
    return rows


TRAIN_PARAMS = {"objective": "binary", "num_iterations": 5,
                "num_leaves": 7, "min_data_in_leaf": 10,
                "learning_rate": 0.2, "verbose": -1}


def _train_on(path, extra_params):
    params = {**TRAIN_PARAMS, **extra_params}
    ds = lgb.Dataset(str(path), params=params)
    bst = lgb.train(params, ds)
    return bst


# ---------------------------------------------------------------------------
# THE acceptance test: quarantine == clean subset, bit for bit
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("two_round", [True, False])
def test_mangled_quarantine_bitmatches_clean_subset(tmp_path, two_round):
    dirty = tmp_path / "train.tsv"
    rows = _write_train_file(dirty)
    mangled = faults.mangle_rows(str(dirty), fraction=0.05, seed=3,
                                 token=GARBAGE)
    assert len(mangled) == 20          # 5% of 400

    clean = tmp_path / "clean.tsv"
    keep = [r for i, r in enumerate(rows, start=1) if i not in mangled]
    clean.write_text("\n".join(keep) + "\n")

    extra = {"two_round": two_round, "bad_data_policy": "quarantine"}
    base = obs.get_counter("bad_rows_total")
    # both runs on the PYTHON parser: the dirty file reroutes there
    # anyway (the native loader flags it), and the native fast-atof
    # differs from float() by ~1 ulp — the bit-match contract is
    # "same parser, same rows" (the documented two-round caveat,
    # io/streaming.py module docstring)
    with mock.patch("lightgbm_tpu.io.native.parse_file_native",
                    return_value=None):
        bst_dirty = _train_on(dirty, extra)
    assert obs.get_counter("bad_rows_total") - base == len(mangled)

    # every rejected line is in the quarantine file, with a reason
    recs = read_quarantine(str(dirty))
    assert sorted(r["line"] for r in recs) == mangled
    assert all(r["reason"] == "unparseable_token" for r in recs)
    assert all(GARBAGE in r["raw"] for r in recs)

    with mock.patch("lightgbm_tpu.io.native.parse_file_native",
                    return_value=None):
        bst_clean = _train_on(clean, {"two_round": two_round})
    assert bst_dirty._booster.save_model_to_string() == \
        bst_clean._booster.save_model_to_string()


@pytest.mark.parametrize("two_round", [True, False])
def test_mangled_fail_fast_names_file_line_token(tmp_path, two_round):
    dirty = tmp_path / "train.tsv"
    _write_train_file(dirty)
    mangled = faults.mangle_rows(str(dirty), fraction=0.05, seed=3,
                                 token=GARBAGE)
    with pytest.raises(LightGBMError) as ei:
        _train_on(dirty, {"two_round": two_round})
    msg = str(ei.value)
    assert f"{dirty}:{mangled[0]}" in msg
    assert GARBAGE in msg
    assert "fail_fast" in msg


def test_error_budget_stops_a_mostly_garbage_file(tmp_path):
    dirty = tmp_path / "train.tsv"
    _write_train_file(dirty)
    faults.mangle_rows(str(dirty), fraction=0.5, seed=1, token=GARBAGE)
    with pytest.raises(LightGBMError) as ei:
        _train_on(dirty, {"bad_data_policy": "quarantine",
                          "max_bad_row_fraction": 0.1})
    assert "budget exhausted" in str(ei.value)
    # absolute budget too
    with pytest.raises(LightGBMError) as ei2:
        _train_on(dirty, {"bad_data_policy": "quarantine",
                          "max_bad_row_fraction": 0.0,
                          "max_bad_rows": 5})
    assert "max_bad_rows=5" in str(ei2.value)


def test_ragged_and_truncated_rows_quarantined(tmp_path):
    dirty = tmp_path / "train.tsv"
    _write_train_file(dirty, n=120)
    ragged = faults.ragged_rows(str(dirty), fraction=0.05, seed=2,
                                mode="drop")
    trunc_line = faults.truncate_mid_row(str(dirty))
    g = IngestGuard(str(dirty), policy="quarantine")
    ds = load_file_two_round(str(dirty), max_bin=63, min_data_in_leaf=10,
                             guard=g)
    want_bad = sorted(set(ragged) | {trunc_line})
    assert sorted(r["line"] for r in read_quarantine(str(dirty))) == \
        want_bad
    assert ds.metadata.num_data == 120 - len(want_bad)


def test_chunked_prediction_rows_align_with_blank_lines(tmp_path):
    """Satellite pin: blank lines must not drift chunked prediction —
    chunk counts ride the real parsed rows, so chunked output row
    counts equal the whole-file parse row for row."""
    train = tmp_path / "train.tsv"
    _write_train_file(train)
    bst = _train_on(train, {})
    pred = tmp_path / "pred.tsv"
    rng = np.random.RandomState(5)
    lines = []
    for i in range(30):
        f = rng.normal(size=3)
        lines.append("\t".join([f"{int(f[0] > 0)}"]
                               + [f"{v:.6f}" for v in f]))
        if i % 4 == 0:
            lines.append("")           # interior blank lines
    pred.write_text("\n".join(lines) + "\n\n")
    old_chunk = type(bst)._PREDICT_CHUNK_ROWS
    type(bst)._PREDICT_CHUNK_ROWS = 7  # force many chunks
    try:
        chunks = list(bst.predict_chunks(str(pred)))
    finally:
        type(bst)._PREDICT_CHUNK_ROWS = old_chunk
    total = sum(c.shape[1] for c in chunks)
    assert total == 30                 # one prediction per DATA row
    whole = bst.predict(np.asarray(
        [[float(v) for v in ln.split("\t")[1:]]
         for ln in lines if ln.strip()], np.float64))
    np.testing.assert_allclose(
        np.concatenate([c.reshape(-1) for c in chunks]), whole,
        rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# two-round drift: a concurrent producer mutating the file mid-load
# ---------------------------------------------------------------------------

def test_concurrent_append_is_named_drift_error(tmp_path):
    p = tmp_path / "train.tsv"
    _write_train_file(p, n=100)
    with faults.concurrent_append(str(p), "1\t0.5\t0.5\t0.5\n",
                                  after_reads=2) as st:
        with pytest.raises(LightGBMError) as ei:
            load_file_two_round(str(p), max_bin=63, min_data_in_leaf=10)
    assert st["appended"]
    assert "changed between rounds" in str(ei.value)
    assert str(p) in str(ei.value)
    # the file is quiescent now: the SAME call succeeds (101 rows)
    ds = load_file_two_round(str(p), max_bin=63, min_data_in_leaf=10)
    assert ds.metadata.num_data == 101


# ---------------------------------------------------------------------------
# model-artifact corruption -> clean client errors
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["truncate_tree", "chop_footer",
                                  "garbage_field"])
def test_corrupt_model_file_is_clean_load_error(tmp_path, mode):
    dirty = tmp_path / "train.tsv"
    _write_train_file(dirty)
    bst = _train_on(dirty, {})
    mpath = tmp_path / "model.txt"
    bst.save_model(str(mpath))
    what = faults.corrupt_model_file(str(mpath), mode=mode)
    with pytest.raises(LightGBMError) as ei:
        lgb.Booster(model_file=str(mpath))
    msg = str(ei.value)
    assert "model file" in msg.lower() or "Tree=" in msg, (what, msg)


def test_corrupt_model_reload_is_400_and_keeps_serving(tmp_path):
    dirty = tmp_path / "train.tsv"
    _write_train_file(dirty)
    bst = _train_on(dirty, {})
    good = tmp_path / "good.txt"
    bst.save_model(str(good))
    bad = tmp_path / "bad.txt"
    bad.write_text(good.read_text())
    faults.corrupt_model_file(str(bad), mode="truncate_tree")

    cf = bst.compile(buckets=[16, 64])
    cf.warmup(max_bucket=64)
    srv = PredictServer(cf, port=0, max_batch=64, max_delay_ms=1.0).start()
    host, port = srv.address
    base = f"http://{host}:{port}"
    X = np.array([[0.1, -0.2, 0.3]], np.float32)
    try:
        gen0 = json.loads(urllib.request.urlopen(
            base + "/healthz", timeout=10).read())["generation"]
        req = urllib.request.Request(
            base + "/reload", data=json.dumps(
                {"model": str(bad)}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=60)
        assert err.value.code == 400
        body = json.loads(err.value.read())
        assert "reload failed" in body["error"]
        # generation untouched, traffic still served
        health = json.loads(urllib.request.urlopen(
            base + "/healthz", timeout=10).read())
        assert health["generation"] == gen0
        req2 = urllib.request.Request(
            base + "/predict",
            data=json.dumps({"rows": X.tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        resp = json.loads(urllib.request.urlopen(req2, timeout=30).read())
        assert resp["num_rows"] == 1
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# serve ingress: malformed / oversized / non-finite payloads shed
# before ANY device time (zero Predict::forest spans, trace-pinned)
# ---------------------------------------------------------------------------

@pytest.fixture
def tracer(tmp_path, monkeypatch):
    path = tmp_path / "trace_events.json"
    tracing.TRACER.reset()
    monkeypatch.setenv(tracing.ENV_PATH, str(path))
    tracing.TRACER.configure()
    yield path
    tracing.TRACER.disable()
    tracing.TRACER.reset()
    tracing.TRACER.path = None


def _post_expect_error(base, payload, code, body_bytes=None,
                       timeout=30):
    data = body_bytes if body_bytes is not None \
        else json.dumps(payload).encode()
    req = urllib.request.Request(
        base + "/predict", data=data,
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(req, timeout=timeout)
    assert err.value.code == code, err.value.read()[:200]
    rid = err.value.headers.get("X-Request-Id")
    body = json.loads(err.value.read())
    assert "error" in body
    return int(rid), body["error"]


def test_serve_ingress_shedding_zero_device_spans(tmp_path, tracer):
    dirty = tmp_path / "train.tsv"
    _write_train_file(dirty)
    bst = _train_on(dirty, {})
    cf = bst.compile(buckets=[16, 64])
    cf.warmup(max_bucket=64)
    srv = PredictServer(cf, port=0, max_batch=64, max_delay_ms=1.0,
                        max_body_bytes=4096,
                        nonfinite_policy="reject").start()
    host, port = srv.address
    base = f"http://{host}:{port}"
    bad_ids = []
    try:
        # ragged width: 400 naming the offending ROW INDEX
        rid, msg = _post_expect_error(
            base, {"rows": [[0.1, 0.2, 0.3], [0.1, 0.2]]}, 400)
        assert "row 1" in msg
        bad_ids.append(rid)
        # non-numeric element: 400 naming row + feature
        rid, msg = _post_expect_error(
            base, {"rows": [[0.1, 0.2, 0.3], [0.1, "x", 0.3]]}, 400)
        assert "row 1" in msg and "non-numeric" in msg
        bad_ids.append(rid)
        # non-finite under reject: 400 naming the row + the policy
        rid, msg = _post_expect_error(
            base, {"rows": [[0.1, 0.2, 0.3],
                            [0.1, float("nan"), 0.3]]}, 400)
        assert "row 1" in msg and "serve_nonfinite_policy" in msg
        bad_ids.append(rid)
        # oversized body: 413 before parsing
        huge = b'{"rows": [' + b"[0.1, 0.2, 0.3]," * 2000 \
            + b"[0.1, 0.2, 0.3]]}"
        rid, msg = _post_expect_error(base, None, 413, body_bytes=huge)
        assert "serve_max_body_bytes" in msg
        bad_ids.append(rid)
        assert obs.get_counter("serve_oversize_requests") >= 1
        # a clean request still works on the same server
        req = urllib.request.Request(
            base + "/predict",
            data=json.dumps({"rows": [[0.1, -0.2, 0.3]]}).encode(),
            headers={"Content-Type": "application/json"})
        resp = json.loads(urllib.request.urlopen(req, timeout=30).read())
        assert resp["num_rows"] == 1
    finally:
        srv.stop()
    events = tracing.read_trace(str(tracer))
    spans = [e for e in events if e.get("ph") == "X"]
    by_request = {e["args"]["request_id"]: e["args"]["trace_id"]
                  for e in spans if e["name"] == "Serve::request"
                  and "request_id" in (e.get("args") or {})}
    predict_traces = {e["args"].get("trace_id") for e in spans
                      if e["name"] == "Predict::forest"}
    assert len(bad_ids) == 4
    for rid in bad_ids:
        assert rid in by_request, f"request {rid} left no closed span"
        assert by_request[rid] not in predict_traces, \
            f"rejected request {rid} reached the device"


def test_serve_malformed_content_length_is_400(tmp_path):
    """Review pin: a non-integer Content-Length is a structured 400,
    not an uncaught ValueError aborting the connection."""
    import http.client
    dirty = tmp_path / "train.tsv"
    _write_train_file(dirty)
    bst = _train_on(dirty, {})
    cf = bst.compile(buckets=[16])
    cf.warmup(max_bucket=16)
    srv = PredictServer(cf, port=0, max_batch=16, max_delay_ms=1.0).start()
    host, port = srv.address
    try:
        for path in ("/predict", "/reload"):
            conn = http.client.HTTPConnection(host, port, timeout=10)
            conn.putrequest("POST", path)
            conn.putheader("Content-Type", "application/json")
            conn.putheader("Content-Length", "abc")
            conn.endheaders()
            resp = conn.getresponse()
            body = json.loads(resp.read())
            assert resp.status == 400, (path, resp.status)
            assert "Content-Length" in body["error"]
            conn.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# bench plumbing (satellite: bad_rows BENCH block passthrough)


def test_bench_regress_passes_bad_rows_through(tmp_path, capsys):
    """A candidate whose train run quarantined rows carries the
    ``bad_rows`` block into the verdict informationally — never gated,
    never an error when the (older) baseline lacks it."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import bench_regress
    finally:
        sys.path.pop(0)
    baseline = {"metric": "boosting_iters_per_sec_x", "value": 7.0,
                "unit": "iters/sec", "warmup_s": 30.0}
    candidate = {"metric": "boosting_iters_per_sec_x", "value": 7.2,
                 "unit": "iters/sec", "warmup_s": 28.0,
                 "bad_rows": {"total": 17, "unparseable_token": 12,
                              "ragged_row": 5}}
    b = tmp_path / "base.json"
    c = tmp_path / "cand.json"
    b.write_text(json.dumps(baseline))
    c.write_text(json.dumps(candidate))
    rc = bench_regress.main(["--baseline", str(b), "--candidate", str(c),
                             "--threshold", "5"])
    assert rc == 0
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["ok"]
    assert verdict["bad_rows_candidate"] == {"total": 17,
                                             "unparseable_token": 12,
                                             "ragged_row": 5}
    assert "bad_rows_baseline" not in verdict


def test_serve_nonfinite_propagate_reaches_the_forest(tmp_path):
    dirty = tmp_path / "train.tsv"
    _write_train_file(dirty)
    bst = _train_on(dirty, {})
    cf = bst.compile(buckets=[16, 64])
    cf.warmup(max_bucket=64)
    srv = PredictServer(cf, port=0, max_batch=64, max_delay_ms=1.0,
                        nonfinite_policy="propagate").start()
    host, port = srv.address
    base = f"http://{host}:{port}"
    try:
        X = np.array([[np.nan, -0.2, 0.3]], np.float32)
        req = urllib.request.Request(
            base + "/predict",
            data=json.dumps({"rows": [[None if np.isnan(v) else
                                       float(v) for v in X[0]]]}
                            ).replace("null", "NaN").encode(),
            headers={"Content-Type": "application/json"})
        resp = json.loads(urllib.request.urlopen(req, timeout=30).read())
        want = cf.predict(X, device_binning=True)
        np.testing.assert_allclose(resp["predictions"],
                                   np.asarray(want).ravel(),
                                   rtol=1e-6, atol=1e-6)
    finally:
        srv.stop()
