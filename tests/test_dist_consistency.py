"""Desync detection (models/gbdt.py `distributed_consistency_check`):
digest determinism, the fail_fast/resync policies against simulated
multi-rank gathers, the zero-overhead single-process contract
(compile-ledger pinned), and the rank stamp on event-stream records.
The real 2-process detection path is pinned by tests/test_dist_chaos.py."""

import pickle

import numpy as np
import pytest

from lightgbm_tpu import Dataset, LightGBMError, obs
from lightgbm_tpu import train as lgb_train
from lightgbm_tpu.obs import compile_ledger

pytestmark = pytest.mark.faults

PARAMS = {"objective": "binary", "metric": ["binary_logloss"],
          "num_leaves": 5, "min_data_in_leaf": 5, "max_bin": 31,
          "learning_rate": 0.2, "verbose": -1}


def _data(seed=11, n=160, f=4):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] - 0.5 * X[:, 1] + 0.3 * rng.normal(size=n) > 0)
    return X, y.astype(np.float64)


def _train(params, rounds=4, callbacks=None):
    X, y = _data()
    return lgb_train(dict(PARAMS, **params), Dataset(X, label=y),
                     num_boost_round=rounds, callbacks=callbacks,
                     verbose_eval=False)


def _fake_world(monkeypatch, rank, world):
    import lightgbm_tpu.parallel.multihost as mh
    monkeypatch.setattr(mh, "process_rank_world", lambda: (rank, world))


# ---------------------------------------------------------------------------
# single-process contract: the gate short-circuits before jax


def test_single_process_pays_zero_overhead():
    # warm the shared programs so the pinned run's ledger delta is honest
    base = _train({})
    before = len(compile_ledger.events())
    desync_before = obs.get_counter("desync_detected_total")
    checked = _train({"distributed_consistency_check": 2})
    # no new compiles, no detections — K>0 in a 1-process run is free
    assert len(compile_ledger.events()) == before
    assert obs.get_counter("desync_detected_total") == desync_before
    assert (base._booster.save_model_to_string()
            == checked._booster.save_model_to_string())


# ---------------------------------------------------------------------------
# digest semantics


def test_consistency_digests_deterministic_and_field_sensitive():
    a = _train({})._booster
    b = _train({})._booster
    da, db = a._consistency_digests(), b._consistency_digests()
    assert list(da) == ["iter", "trees", "score", "rng"]
    assert da == db                     # identical runs, identical digests
    # perturb ONE replicated field: exactly that digest moves
    a.train_data.score = a.train_data.score.at[0, 0].add(1.0)
    dc = a._consistency_digests()
    assert dc["score"] != da["score"]
    assert dc["trees"] == da["trees"]
    assert dc["rng"] == da["rng"]
    assert dc["iter"] == da["iter"]


# ---------------------------------------------------------------------------
# policies against simulated 2-rank gathers


def _divergent_allgather(monkeypatch, field_index, times=1):
    """Patch the host allgather: rank 1's digest for one field differs
    on the first ``times`` calls, then the pod looks consistent."""
    import lightgbm_tpu.parallel.comm as comm
    calls = []

    def fake(x):
        x = np.asarray(x)
        g = np.stack([x, x.copy()])
        calls.append(g)
        if len(calls) <= times:
            g[1, field_index] ^= np.uint64(1)
        return g

    monkeypatch.setattr(comm, "allgather_host_array", fake)
    return calls


def test_fail_fast_names_rank_and_field(monkeypatch):
    _fake_world(monkeypatch, 0, 2)
    calls = _divergent_allgather(monkeypatch, field_index=2, times=99)
    before = obs.get_counter("desync_detected_total")
    with pytest.raises(LightGBMError) as ei:
        _train({"distributed_consistency_check": 2,
                "desync_policy": "fail_fast"})
    msg = str(ei.value)
    assert "desync" in msg
    assert "'score'" in msg             # names the diverged field...
    assert "rank(s) [1]" in msg         # ...and the diverged rank
    assert obs.get_counter("desync_detected_total") == before + 1
    assert len(calls) == 1              # died at the first divergent check


def test_resync_rank0_continues_with_own_state(monkeypatch):
    _fake_world(monkeypatch, 0, 2)
    _divergent_allgather(monkeypatch, field_index=2, times=1)
    import lightgbm_tpu.parallel.comm as comm
    broadcasts = []

    def fake_broadcast(payload, is_source):
        assert is_source               # rank 0 is the resync source
        broadcasts.append(len(payload))
        return payload

    monkeypatch.setattr(comm, "broadcast_host_bytes", fake_broadcast)
    before = obs.get_counter("desync_resyncs_total")
    bst = _train({"distributed_consistency_check": 2,
                  "desync_policy": "resync"})
    ref = _train({})
    assert broadcasts                   # the resync really broadcast
    assert obs.get_counter("desync_resyncs_total") == before + 1
    # rank 0 IS the source of truth: its trajectory is untouched
    assert (bst._booster.save_model_to_string()
            == ref._booster.save_model_to_string())


def test_resync_nonzero_rank_restores_broadcast_state(monkeypatch):
    _fake_world(monkeypatch, 1, 2)
    _divergent_allgather(monkeypatch, field_index=2, times=1)
    import lightgbm_tpu.parallel.comm as comm
    restored = []
    orig_restore = None

    def fake_broadcast(payload, is_source):
        assert not is_source           # rank 1 receives
        # stand in for rank 0: serve this rank's own (clean) state back,
        # which must restore as an identity round-trip
        return pickle.dumps(holder[0].snapshot_state(),
                            protocol=pickle.HIGHEST_PROTOCOL)

    monkeypatch.setattr(comm, "broadcast_host_bytes", fake_broadcast)

    holder = []

    def grab(env):
        if not holder:
            holder.append(env.model._booster)
            nonlocal orig_restore
            orig_restore = holder[0].restore_state

            def counting_restore(state):
                restored.append(int(state["iter_"]))
                return orig_restore(state)
            holder[0].restore_state = counting_restore
    grab.before_iteration = True
    grab.order = -50

    bst = _train({"distributed_consistency_check": 2,
                  "desync_policy": "resync"}, callbacks=[grab])
    ref = _train({})
    assert restored == [2]              # restore ran, at the check point
    assert (bst._booster.save_model_to_string()
            == ref._booster.save_model_to_string())


def test_resync_refuses_when_rank0_is_the_diverged_one(monkeypatch):
    # 3-process pod, majority votes rank 0 the bad one: broadcasting
    # rank 0's state would propagate the corruption — must fail instead
    _fake_world(monkeypatch, 0, 3)
    import lightgbm_tpu.parallel.comm as comm

    def fake(x):
        x = np.asarray(x)
        g = np.stack([x, x.copy(), x.copy()])
        g[0, 2] ^= np.uint64(1)         # rank 0's 'score' digest is odd
        return g
    monkeypatch.setattr(comm, "allgather_host_array", fake)
    with pytest.raises(LightGBMError) as ei:
        _train({"distributed_consistency_check": 2,
                "desync_policy": "resync"})
    msg = str(ei.value)
    assert "rank 0" in msg and "refusing" in msg


def test_broadcast_host_bytes_round_trips_odd_lengths():
    from lightgbm_tpu.parallel.comm import broadcast_host_bytes
    payload = b"\x00\x01hello desync resync payload!\xff" * 3 + b"x"
    assert len(payload) % 4 != 0        # exercise the word padding
    assert broadcast_host_bytes(payload, is_source=True) == payload


# ---------------------------------------------------------------------------
# rank-level injector mechanics (testing/faults.py) — the kill path is
# exercised for real by tests/test_dist_chaos.py; here: rank gating,
# the straggler delay, the hang release valve, and tree corruption


def test_rank_injectors_gate_on_rank_and_fire_in_order():
    import time as _time
    import types

    from lightgbm_tpu.testing import faults
    env = types.SimpleNamespace(iteration=2, model=None)
    # wrong rank: pure no-op (this single process is rank 0)
    wrong = faults.delay_rank(2, delay_s=30.0, rank=7)
    t0 = _time.perf_counter()
    wrong(env)
    assert _time.perf_counter() - t0 < 1.0
    assert wrong.fired[0] == 0
    # kill_rank on another rank must also be inert
    faults.kill_rank(2, rank=7)(env)
    # matching rank: delay fires exactly `times` times
    slow = faults.delay_rank(2, delay_s=0.01, times=2, rank=0)
    for it in (1, 2, 3, 4):
        slow(types.SimpleNamespace(iteration=it, model=None))
    assert slow.fired[0] == 2
    # hang_rank blocks on its release valve; a pre-set valve = no hang
    hung = faults.hang_rank(2, rank=0, hang_s=30.0)
    hung.release.set()
    t0 = _time.perf_counter()
    hung(env)
    assert _time.perf_counter() - t0 < 1.0


def test_corrupt_rank_state_tree_field_moves_only_tree_digest():
    import types

    from lightgbm_tpu.testing import faults
    bst = _train({})
    gb = bst._booster
    before = gb._consistency_digests()
    cb = faults.corrupt_rank_state(1, rank=0, field="tree", scale=3.0)
    cb(types.SimpleNamespace(iteration=1, model=bst))
    after = gb._consistency_digests()
    assert cb.fired[0]
    assert after["trees"] != before["trees"]
    assert after["score"] == before["score"]
    assert after["rng"] == before["rng"]


# ---------------------------------------------------------------------------
# event-stream rank stamping (obs/events.py)


def test_event_records_carry_rank_under_multihost(monkeypatch, tmp_path):
    _fake_world(monkeypatch, 3, 4)
    rec = obs.EventRecorder(str(tmp_path / "events.jsonl"))
    # the path is suffixed per rank: N ranks sharing one conf would
    # otherwise truncate each other's streams
    assert rec.path == str(tmp_path / "events.rank3.jsonl")
    rec.note(0, wall_s=0.1)
    rec.note(1, wall_s=0.2)
    rec.close()
    evs = obs.read_events(rec.path)
    assert [e["rank"] for e in evs] == [3, 3]


def test_event_records_plain_single_process(tmp_path):
    path = tmp_path / "events.jsonl"
    rec = obs.EventRecorder(str(path))
    rec.note(0, wall_s=0.1)
    rec.close()
    assert "rank" not in obs.read_events(str(path))[0]
