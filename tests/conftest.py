"""Test config: force an 8-virtual-device CPU platform so data/feature/voting
parallel paths are testable without a TPU pod (SURVEY.md §4).

Note: this environment force-registers a TPU platform plugin ("axon") via
sitecustomize and presets JAX_PLATFORMS, so a plain env-var setdefault is not
enough — override the env var AND the live config before any test imports jax.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
