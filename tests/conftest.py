"""Test config: force an 8-virtual-device CPU platform so data/feature/voting
parallel paths are testable without a TPU pod (SURVEY.md §4).

Note: this environment force-registers a TPU platform plugin ("axon") via
sitecustomize and presets JAX_PLATFORMS, so a plain env-var setdefault is not
enough — override the env var AND the live config before any test imports jax.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


import os  # noqa: E402

import pytest  # noqa: E402

_terminal_reporter = None


def pytest_configure(config):
    global _terminal_reporter
    _terminal_reporter = config.pluginmanager.getplugin("terminalreporter")


def pytest_runtest_logreport(report):
    """The tier-1 harness greps progress dots from a piped log; piped
    stdout is block-buffered, so a timeout kill silently drops every
    completed test still in the buffer.  Flush after each test so the
    log reflects what actually ran."""
    if report.when != "teardown" or _terminal_reporter is None:
        return
    try:
        _terminal_reporter._tw._file.flush()
    except Exception:
        pass


def pytest_collection_modifyitems(config, items):
    """Tests driving the reference's example data need the read-only
    /root/reference mount of the dev box; skip PER TEST elsewhere
    (container / CI runners) so self-contained tests in the same module
    still run."""
    if os.path.exists("/root/reference"):
        return
    import inspect
    import re

    skip = pytest.mark.skip(reason="/root/reference mount not available")
    for item in items:
        fn = getattr(item, "function", None)
        if fn is None:
            continue
        try:
            src = inspect.getsource(fn)
        except (OSError, TypeError):
            continue
        # direct literal use, or use of a module-level constant that
        # holds a reference path (REF, BINARY_TRAIN, CASES, ...)
        needs = "/root/reference" in src
        if not needs:
            for name, val in vars(item.module).items():
                if "/root/reference" in str(val) and \
                        re.search(rf"\b{re.escape(name)}\b", src):
                    needs = True
                    break
        if needs:
            item.add_marker(skip)
