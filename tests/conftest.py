"""Test config: force an 8-virtual-device CPU platform so data/feature/voting
parallel paths are testable without a TPU pod (SURVEY.md §4)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
