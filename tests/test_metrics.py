"""Metrics pipeline (lightgbm_tpu/obs/): histogram metric semantics,
Prometheus text exposition + minimal parser, the standalone training
/metrics listener (scraped mid-flight), serve-server /metrics + full
/stats, span timers, snapshot/resume histogram round-trips, registry
concurrency under a live scraper, the obs-report CLI, and the
bench-regression gate tool."""

import json
import socket
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import prom
from lightgbm_tpu.obs.metrics_server import MetricsServer
from lightgbm_tpu.utils import timetag


def _data(n=400, f=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(url: str, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8"), resp.headers.get("Content-Type")


def _assert_valid_histograms(text: str):
    """Parse an exposition; for every histogram family and every LABEL
    SET within it (the fleet's ``model=`` dimension renders labeled and
    unlabeled series in one family) assert cumulative buckets are
    monotone and the +Inf bucket equals _count.  Returns the parsed
    structure and the set of histogram family names."""
    parsed = prom.parse_text(text)
    families = {name for name, t in parsed["types"].items()
                if t == "histogram"}
    assert families, "exposition carries no histogram"
    for fam in families:
        # one label group = the exact non-le label set of a _count line
        groups = [labels for name, labels, _ in parsed["samples"]
                  if name == fam + "_count"]
        assert groups, f"{fam}: no _count sample"
        for want in groups:
            buckets, cnt, total = [], None, None
            for name, labels, value in parsed["samples"]:
                nle = {k: v for k, v in labels.items() if k != "le"}
                if nle != want:
                    continue
                if name == fam + "_bucket":
                    buckets.append((prom._parse_value(labels["le"]), value))
                elif name == fam + "_count":
                    cnt = value
                elif name == fam + "_sum":
                    total = value
            assert cnt is not None and total is not None, (fam, want)
            buckets.sort(key=lambda t: t[0])
            values = [v for _, v in buckets]
            assert values == sorted(values), \
                f"{fam}{want}: non-monotone buckets"
            assert buckets[-1][0] == float("inf"), (fam, want)
            assert buckets[-1][1] == cnt, \
                f"{fam}{want}: +Inf bucket != _count"
    return parsed, families


# ---------------------------------------------------------------------------
# histogram metric type
# ---------------------------------------------------------------------------

def test_histogram_observe_buckets_sum_count():
    r = obs.Registry()
    r.observe("lat", 0.5, buckets=[1.0, 2.0, 4.0])
    r.observe("lat", 1.0)            # == bound -> le-inclusive bucket
    r.observe("lat", 3.0)
    r.observe("lat", 99.0)           # overflow
    h = r.get_histogram("lat")
    assert h["buckets"] == [1.0, 2.0, 4.0]
    assert h["counts"] == [2, 0, 1, 1]
    assert h["count"] == 4
    assert h["sum"] == pytest.approx(103.5)
    # bucket layout is fixed by the first observe
    r.observe("lat", 0.1, buckets=[7.0])
    assert r.get_histogram("lat")["buckets"] == [1.0, 2.0, 4.0]
    assert r.get_histogram("missing") is None


def test_histogram_merge_identical_and_rebucket():
    a = obs.Registry()
    b = obs.Registry()
    for v in (0.5, 1.5, 9.0):
        a.observe("h", v, buckets=[1.0, 2.0])
        b.observe("h", v, buckets=[1.0, 2.0])
    # fold-worker style: identical layouts add element-wise
    a.merge(b.snapshot())
    h = a.get_histogram("h")
    assert h["counts"] == [2, 2, 2] and h["count"] == 6
    assert h["sum"] == pytest.approx(22.0)
    # differing layouts re-bucket at the incoming upper edge (never down)
    c = obs.Registry()
    c.observe("h", 0.2, buckets=[0.25, 1.0, 2.0, 50.0])
    c.merge(a.snapshot())
    hc = c.get_histogram("h")
    assert hc["count"] == 7
    # le-1.0 pair -> le-1.0, le-2.0 pair -> le-2.0; the incoming +Inf
    # overflow pair has no upper edge to re-bucket by, so it stays +Inf
    assert hc["counts"] == [1, 2, 2, 0, 2]
    assert hc["sum"] == pytest.approx(22.2)
    # a histogram absent locally is copied wholesale
    d = obs.Registry()
    d.merge(a.snapshot())
    assert d.get_histogram("h") == a.get_histogram("h")


def test_histogram_restore_overwrites_bit_exact():
    a = obs.Registry()
    for v in (0.001, 0.7, 1e-9, 123.456):
        a.observe("h", v)
    snap = a.snapshot()
    b = obs.Registry()
    b.observe("h", 5.0)              # pre-existing state is replaced
    b.restore(snap)
    assert b.get_histogram("h") == a.get_histogram("h")
    # float sum restores bit-exactly, not approximately
    assert b.get_histogram("h")["sum"] == a.get_histogram("h")["sum"]


def test_histogram_quantile_interpolation():
    r = obs.Registry()
    for v in [0.1] * 50 + [0.9] * 50:
        r.observe("q", v, buckets=[0.25, 1.0])
    h = r.get_histogram("q")
    # p25 inside the first bucket, p75 inside the second
    assert 0.0 < obs.histogram_quantile(h, 0.25) <= 0.25
    assert 0.25 < obs.histogram_quantile(h, 0.75) <= 1.0
    assert obs.histogram_quantile(None, 0.5) is None
    assert obs.histogram_quantile({"count": 0}, 0.5) is None


def test_snapshot_resume_preserves_histogram_state(tmp_path):
    """Crash-safe resume (lightgbm_tpu/snapshot.py) restores the FULL
    registry: counters, gauges, and histogram bucket state bit-exactly."""
    from lightgbm_tpu.snapshot import (load_latest_snapshot,
                                       restore_booster_state)
    X, y = _data(300, 4, seed=7)
    ds = lgb.Dataset(X, label=y)
    booster = lgb.train({"objective": "binary", "num_leaves": 4,
                         "verbose": -1}, ds, num_boost_round=2)
    obs.observe("custom_series", 0.125)
    obs.observe("custom_series", 7.25)
    before_hist = obs.get_histogram("custom_series")
    before_iters = obs.get_counter("iterations")
    assert before_hist["count"] == 2
    booster.save_snapshot(str(tmp_path))

    obs.reset()
    assert obs.get_histogram("custom_series") is None
    # fresh same-config booster, as a crash-restarted process would build
    booster2 = lgb.Booster(params={"objective": "binary", "num_leaves": 4,
                                   "verbose": -1}, train_set=ds)
    _, state = load_latest_snapshot(str(tmp_path))
    restore_booster_state(booster2, state)
    assert obs.get_histogram("custom_series") == before_hist
    assert obs.get_histogram("custom_series")["sum"] == before_hist["sum"]
    assert obs.get_counter("iterations") == before_iters


# ---------------------------------------------------------------------------
# Prometheus exposition + parser
# ---------------------------------------------------------------------------

def test_prom_render_and_parse_roundtrip():
    r = obs.Registry()
    r.inc("iterations", 3)
    r.set_gauge("hbm_budget_bytes", 1024)
    r.set_gauge("non_numeric", "skipped")
    for v in (0.01, 0.2, 500.0):
        r.observe("phase_seconds_gbdt_tree", v)
    text = prom.render(r.snapshot(), labels={"rank": "2"})
    parsed, fams = _assert_valid_histograms(text)
    assert "lightgbm_tpu_phase_seconds_gbdt_tree" in fams
    samples = {(n, tuple(sorted(lab.items()))): v
               for n, lab, v in parsed["samples"]}
    assert samples[("lightgbm_tpu_iterations", (("rank", "2"),))] == 3
    assert parsed["types"]["lightgbm_tpu_iterations"] == "counter"
    assert parsed["types"]["lightgbm_tpu_hbm_budget_bytes"] == "gauge"
    # every sample carries the rank label
    assert all(lab.get("rank") == "2" for _, lab, _ in parsed["samples"])
    # the non-numeric gauge was dropped, not rendered invalidly
    assert "non_numeric" not in text


def test_prom_metric_name_sanitization():
    assert prom.metric_name("GBDT::tree") == "lightgbm_tpu_gbdt_tree"
    assert prom.metric_name("serve-latency.p50") == \
        "lightgbm_tpu_serve_latency_p50"
    assert prom.metric_name("9lives").startswith("lightgbm_tpu__9")


def test_prom_parser_rejects_garbage():
    with pytest.raises(ValueError):
        prom.parse_text("this is not { valid\n")
    with pytest.raises(ValueError):
        prom.parse_text('m{le="0.1} 3\n')


def test_prom_label_escape_roundtrip():
    """render -> parse is an identity on label values, including a
    literal backslash before 'n' or a quote (single-pass unescape)."""
    for value in ('a\\nb', 'a\nb', 'back\\slash', 'quo"te', '\\\\'):
        r = obs.Registry()
        r.inc("c")
        text = prom.render(r.snapshot(), labels={"tag": value})
        parsed = prom.parse_text(text)
        got = [lab["tag"] for n, lab, _ in parsed["samples"]
               if n == "lightgbm_tpu_c"]
        assert got == [value], (value, got)


# ---------------------------------------------------------------------------
# standalone metrics listener
# ---------------------------------------------------------------------------

def test_metrics_server_scrape_and_shutdown():
    obs.observe("phase_seconds_gbdt_tree", 0.05)
    srv = MetricsServer(port=0).start()
    try:
        host, port = srv.address
        text, ctype = _get(f"http://{host}:{port}/metrics")
        assert "version=0.0.4" in ctype
        _assert_valid_histograms(text)
        health, _ = _get(f"http://{host}:{port}/healthz")
        assert json.loads(health)["status"] == "ok"
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(f"http://{host}:{port}/nope")
        assert err.value.code == 404
    finally:
        srv.stop()
    srv.stop()                                   # idempotent
    with pytest.raises(Exception):
        _get(f"http://{host}:{port}/healthz", timeout=1)


def test_training_scrapeable_midflight():
    """engine.train(metrics_port=...) serves live Prometheus exposition
    WHILE the boosting loop runs, and tears the listener down on exit."""
    X, y = _data(300, 4, seed=3)
    ds = lgb.Dataset(X, label=y)
    port = _free_port()
    seen = {}

    def scrape_midflight(env):
        if env.iteration >= 1 and "text" not in seen:
            seen["text"], seen["ctype"] = _get(
                f"http://127.0.0.1:{port}/metrics")
    scrape_midflight.order = 99

    lgb.train({"objective": "binary", "num_leaves": 4, "verbose": -1,
               "metrics_port": port}, ds, num_boost_round=4,
              callbacks=[scrape_midflight])
    assert "text" in seen, "mid-training scrape never ran"
    assert "version=0.0.4" in seen["ctype"]
    parsed, fams = _assert_valid_histograms(seen["text"])
    # the migrated iteration wall-time bookkeeping is a live histogram
    assert "lightgbm_tpu_phase_seconds_gbdt_iteration" in fams
    h = prom.histogram_series(parsed,
                              "lightgbm_tpu_phase_seconds_gbdt_iteration")
    assert h["count"] >= 1
    counters = {n: v for n, lab, v in parsed["samples"]
                if n == "lightgbm_tpu_iterations"}
    assert counters["lightgbm_tpu_iterations"] >= 1
    # listener is gone once train() returns
    with pytest.raises(Exception):
        _get(f"http://127.0.0.1:{port}/healthz", timeout=1)


def test_metrics_env_var_and_bind_failure(monkeypatch):
    from lightgbm_tpu.obs import metrics_server as ms
    monkeypatch.setenv(ms.ENV_PORT, "not-a-port")
    assert ms.resolve_port({"metrics_port": 0}) == 0
    monkeypatch.setenv(ms.ENV_PORT, "12345")
    assert ms.resolve_port({"metrics_port": 0}) == 12345
    # an EXPLICIT env 0 disables, beating a param that asks for a port
    monkeypatch.setenv(ms.ENV_PORT, "0")
    assert ms.resolve_port({"metrics_port": 7}) == 0
    monkeypatch.delenv(ms.ENV_PORT)
    assert ms.resolve_port({"metrics_port": "7"}) == 7
    # a taken port degrades to None + warning, never an exception
    srv = MetricsServer(port=0).start()
    try:
        assert ms.maybe_start({"metrics_port": srv.address[1]}) is None
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# serve server: /metrics + full /stats
# ---------------------------------------------------------------------------

@pytest.mark.serve
def test_serve_metrics_and_full_stats():
    from lightgbm_tpu.serve.server import PredictServer
    X, y = _data(300, 4, seed=5)
    booster = lgb.train({"objective": "binary", "num_leaves": 4,
                         "verbose": -1}, lgb.Dataset(X, label=y),
                        num_boost_round=2)
    cf = booster.compile(buckets=[16, 64])
    cf.warmup(max_bucket=64)
    srv = PredictServer(cf, port=0, max_batch=64, max_delay_ms=1.0).start()
    try:
        host, port = srv.address
        base = f"http://{host}:{port}"
        body = json.dumps({"rows": X[:5].tolist()}).encode()
        for _ in range(3):
            req = urllib.request.Request(
                base + "/predict", data=body,
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=30).read()

        text, ctype = _get(base + "/metrics")
        assert "version=0.0.4" in ctype
        parsed, fams = _assert_valid_histograms(text)
        assert "lightgbm_tpu_serve_latency_seconds" in fams
        h = prom.histogram_series(parsed,
                                  "lightgbm_tpu_serve_latency_seconds")
        assert h["count"] >= 3

        # /stats is the FULL registry snapshot: counters + gauges +
        # histogram summaries — plus the fleet topology (round 8) — so
        # new metric names can never drift out
        stats = json.loads(_get(base + "/stats")[0])
        assert set(stats) == {"counters", "gauges", "histograms", "fleet",
                              "lifecycle", "drift"}
        assert stats["drift"] == {"enabled": False}  # off is the default
        assert stats["fleet"]["generation"] >= 1
        assert stats["fleet"]["replicas"], "fleet topology missing"
        assert stats["counters"]["serve_requests"] >= 3
        # non-serve counters appear too (full snapshot, not hand-picked)
        assert "iterations" in stats["counters"]
        lat = stats["histograms"]["serve_latency_seconds"]
        assert lat["count"] >= 3 and lat["sum"] > 0
        assert lat["p50"] is not None and lat["p99"] is not None
        # old gauge names survive as derived values
        assert stats["gauges"]["serve_latency_p50_ms"] > 0
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# concurrency: writers hammering one histogram under a live scraper
# ---------------------------------------------------------------------------

def test_histogram_concurrency_under_scraper():
    reg = obs.Registry()
    n_threads, per_thread = 8, 2000
    # seed the series so the scraper always sees >= 1 histogram, even if
    # it wins the race to the first render
    reg.observe("hammered_seconds", 0.5)
    stop = threading.Event()
    scrape_errors = []

    def writer(seed):
        rng = np.random.RandomState(seed)
        for _ in range(per_thread):
            reg.observe("hammered_seconds", float(rng.uniform(0, 2.0)))
            reg.inc("hammered_total")

    def scraper():
        while not stop.is_set():
            try:
                _assert_valid_histograms(prom.render(reg.snapshot()))
            except AssertionError as exc:      # pragma: no cover - failure
                scrape_errors.append(exc)
                return

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(n_threads)]
    scr = threading.Thread(target=scraper)
    scr.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    scr.join()
    assert not scrape_errors
    h = reg.get_histogram("hammered_seconds")
    assert h["count"] == n_threads * per_thread + 1
    assert sum(h["counts"]) == n_threads * per_thread + 1
    assert reg.get_counter("hammered_total") == n_threads * per_thread
    _assert_valid_histograms(prom.render(reg.snapshot()))


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

def test_span_series_mapping():
    assert obs.span_series("GBDT::tree") == "phase_seconds_gbdt_tree"
    assert obs.span_series("Serve::batch") == "phase_seconds_serve_batch"
    assert obs.span_series("free form!") == "phase_seconds_free_form"
    # every declared phase resolves (the lint enforces this too)
    for name in obs.HOST_PHASES | obs.DEVICE_PHASES:
        assert obs.span_series(name).startswith("phase_seconds_")


def test_span_and_timed_feed_histograms():
    reg = obs.Registry()
    with obs.span("GBDT::metric", reg=reg):
        pass
    h = reg.get_histogram("phase_seconds_gbdt_metric")
    assert h["count"] == 1 and h["sum"] >= 0.0

    calls = []

    @obs.timed("Report::render")
    def work(x):
        calls.append(x)
        return x * 2

    before = (obs.get_histogram("phase_seconds_report_render")
              or {"count": 0})["count"]
    assert work(21) == 42
    h2 = obs.get_histogram("phase_seconds_report_render")
    assert h2["count"] == before + 1 and calls == [21]


def test_span_feeds_timetag_when_serializing():
    timetag.enable(True)
    timetag.reset()
    try:
        with obs.span("GBDT::metric"):
            pass
        assert "GBDT::metric" in timetag.get_timings()
        # timetag.scope mirrors into the same histogram series
        before = obs.get_histogram("phase_seconds_gbdt_metric")["count"]
        with timetag.scope("GBDT::metric"):
            pass
        after = obs.get_histogram("phase_seconds_gbdt_metric")["count"]
        assert after == before + 1
    finally:
        timetag.enable(False)
        timetag.reset()


# ---------------------------------------------------------------------------
# obs-report
# ---------------------------------------------------------------------------

def test_obs_report_real_training_run(tmp_path, capsys):
    from lightgbm_tpu.obs import report
    X, y = _data(400, 5, seed=11)
    path = str(tmp_path / "events.jsonl")
    ds = lgb.Dataset(X, label=y)
    vs = ds.create_valid(X[:100], y[:100])
    timetag.enable(True)
    timetag.reset()
    try:
        lgb.train({"objective": "binary", "num_leaves": 6, "verbose": -1,
                   "metric": "auc"}, ds, num_boost_round=4,
                  valid_sets=[vs], events_file=path)
    finally:
        timetag.enable(False)
        timetag.reset()

    rep = report.summarize([path], top_k=2)
    events = obs.read_events(path)
    # reproduces the run's totals from the stream alone
    assert rep["iterations"] == 4 and rep["events"] == len(events)
    assert rep["wall_s_total"] == pytest.approx(
        sum(e["wall_s"] for e in events), rel=1e-6)
    want_tree = sum(e["phases"].get("GBDT::tree", 0.0) for e in events)
    assert rep["phase_seconds"]["GBDT::tree"] == pytest.approx(
        want_tree, abs=1e-5)
    assert len(rep["slowest"]) == 2
    assert rep["slowest"][0]["wall_s"] >= rep["slowest"][1]["wall_s"]
    auc = rep["eval"]["valid_0"]["auc"]
    assert auc["n"] == 4 and 0.0 <= auc["last"] <= 1.0
    assert rep["incidents"]["nan"] == []

    # CLI entry: both formats, through the real __main__ router
    from lightgbm_tpu import cli
    assert cli.main(["obs-report", path, "--format=json"]) == 0
    out = capsys.readouterr().out
    assert json.loads(out)["iterations"] == 4
    assert cli.main(["obs-report", path, "--format=table", "--top=3"]) == 0
    out = capsys.readouterr().out
    assert "per-phase wall time" in out and "eval trajectory" in out
    assert cli.main(["obs-report"]) == 2
    assert cli.main(["obs-report", path, "--format=yaml"]) == 2


def test_obs_report_comm_totals_sum_per_file(tmp_path):
    """Each event file is an independent cumulative comm account
    (per-rank / per-fold): totals are the SUM of per-file maxima, not
    the max over the concatenation."""
    from lightgbm_tpu.obs import report
    paths = []
    for rank, total in enumerate((1000, 3000)):
        p = tmp_path / f"rank{rank}.jsonl"
        with open(p, "w") as fh:
            for it, frac in enumerate((0.5, 1.0)):
                fh.write(json.dumps({
                    "iter": it, "wall_s": 0.01,
                    "comm_bytes_cum": int(total * frac),
                    "comm_calls_cum": 2 * (it + 1)}) + "\n")
        paths.append(str(p))
    rep = report.summarize(paths)
    assert rep["comm"]["bytes_cum"] == 4000       # 1000 + 3000
    assert rep["comm"]["calls_cum"] == 8          # 4 + 4


def test_obs_report_torn_events_file(tmp_path, capsys):
    """A torn final JSONL line (crashed writer) exits 1 with a one-line
    error, not a JSONDecodeError traceback."""
    from lightgbm_tpu import cli
    p = tmp_path / "torn.jsonl"
    p.write_text('{"iter": 0, "wall_s": 0.1}\n{"iter": 1, "wal')
    assert cli.main(["obs-report", str(p)]) == 1
    err = capsys.readouterr().err
    assert err.startswith("obs-report:") and "Traceback" not in err


@pytest.mark.faults
def test_obs_report_nan_incidents(tmp_path):
    """A real nan_policy=skip_tree run's poisoned round shows up in the
    report's incident list (acceptance: obs-report reproduces nan_policy
    incidents recorded by the fault-tolerance layer)."""
    from lightgbm_tpu.obs import report
    from lightgbm_tpu.testing import faults
    X, y = _data(300, 4, seed=13)
    path = str(tmp_path / "events.jsonl")
    ds = lgb.Dataset(X, label=y)
    booster = lgb.Booster(params={"objective": "binary", "num_leaves": 4,
                                  "verbose": -1,
                                  "nan_policy": "skip_tree"}, train_set=ds)
    rec = obs.EventRecorder(path)
    booster.set_event_recorder(rec)
    with faults.poison_gradients(booster, at_iteration=1):
        for _ in range(4):
            booster.update()
    booster.num_trees()                  # flush the pipelined iteration
    rec.close()
    booster.set_event_recorder(None)

    rep = report.summarize([path])
    assert rep["incidents"]["nan"] == [
        {"iter": 1, "what": "gradients/hessians", "policy": "skip_tree"}]
    # 4 updates, one dropped+retried at the same index -> 3 committed
    assert rep["iterations"] == 3
    table = report.render_table(rep)
    assert "non-finite gradients/hessians" in table


# ---------------------------------------------------------------------------
# bench regression gate
# ---------------------------------------------------------------------------

def test_bench_regress_gate(tmp_path, capsys):
    import importlib.util
    import pathlib
    spec = importlib.util.spec_from_file_location(
        "bench_regress", pathlib.Path(__file__).resolve().parent.parent
        / "tools" / "bench_regress.py")
    br = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(br)

    def write(name, obj):
        p = tmp_path / name
        p.write_text(json.dumps(obj))
        return str(p)

    base = write("base.json", {"metric": "m", "value": 10.0,
                               "unit": "iters/sec"})
    # driver-envelope form (BENCH_rNN.json): result under "parsed"
    ok = write("ok.json", {"n": 5, "rc": 0,
                           "parsed": {"metric": "m", "value": 9.7,
                                      "unit": "iters/sec"}})
    bad = write("bad.json", {"metric": "m", "value": 9.0,
                             "unit": "iters/sec"})
    better = write("better.json", {"metric": "m", "value": 12.0,
                                   "unit": "iters/sec"})
    other = write("other.json", {"metric": "other", "value": 9.9})

    assert br.main(["--baseline", base, "--candidate", ok,
                    "--threshold", "5"]) == 0
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["ok"] and verdict["delta_pct"] == pytest.approx(-3.0)
    assert br.main(["--baseline", base, "--candidate", bad,
                    "--threshold", "5"]) == 1
    assert br.main(["--baseline", base, "--candidate", better,
                    "--threshold", "5"]) == 0
    assert br.main(["--baseline", base, "--candidate", other,
                    "--threshold", "5"]) == 2
    # tail-transcript envelope form
    tail = write("tail.json", {"tail": "noise\n" + json.dumps(
        {"metric": "m", "value": 9.9, "unit": "iters/sec"}) + "\n# done"})
    assert br.main(["--baseline", base, "--candidate", tail,
                    "--threshold", "5"]) == 0
