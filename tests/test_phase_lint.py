"""tools/lint_phase_scopes.py as a tier-1 test: the host timetag phase
taxonomy and the device named_scope taxonomy must both match
lightgbm_tpu/obs/phases.py, so the two accounts can't silently drift."""

import importlib.util
import pathlib


def _load_lint():
    path = (pathlib.Path(__file__).resolve().parent.parent
            / "tools" / "lint_phase_scopes.py")
    spec = importlib.util.spec_from_file_location("lint_phase_scopes", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_phase_taxonomies_in_sync():
    assert _load_lint().check() == []


def test_lint_catches_undeclared_scope(tmp_path, monkeypatch):
    """Sanity: a scope name outside the taxonomy is reported."""
    lint = _load_lint()
    pkg = tmp_path / "lightgbm_tpu"
    (pkg / "obs").mkdir(parents=True)
    (pkg / "ops").mkdir()
    real_phases = (pathlib.Path(lint.__file__).resolve().parent.parent
                   / "lightgbm_tpu" / "obs" / "phases.py")
    (pkg / "obs" / "phases.py").write_text(real_phases.read_text())
    (pkg / "models.py").write_text(
        'with timetag.scope("GBDT::rogue"):\n    pass\n')
    (pkg / "ops" / "grow.py").write_text(
        'with jax.named_scope("hist"):\n    pass\n'
        'with jax.named_scope("find_split"):\n    pass\n'
        'with jax.named_scope("split"):\n    pass\n')
    (pkg / "ops" / "ordered_grow.py").write_text("")
    monkeypatch.setattr(lint, "ROOT", tmp_path)
    monkeypatch.setattr(lint, "PKG", pkg)
    errors = lint.check()
    assert any("GBDT::rogue" in e for e in errors)
