"""tools/lint_phase_scopes.py as a tier-1 test: the host timetag phase
taxonomy and the device named_scope taxonomy must both match
lightgbm_tpu/obs/phases.py, so the two accounts can't silently drift."""

import importlib.util
import pathlib


def _load_lint():
    path = (pathlib.Path(__file__).resolve().parent.parent
            / "tools" / "lint_phase_scopes.py")
    spec = importlib.util.spec_from_file_location("lint_phase_scopes", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_phase_taxonomies_in_sync():
    assert _load_lint().check() == []


def test_lint_recognizes_obs_span_sites():
    """obs.span("X") counts as a host-phase user alongside
    timetag.scope("X") — the always-on span API feeds the same account."""
    lint = _load_lint()
    m = lint.SCOPE_RE.search('with obs.span("GBDT::iteration"):')
    assert m and m.group(1) == "GBDT::iteration"
    m = lint.SCOPE_RE.search('with timetag.scope("GBDT::tree") as tt:')
    assert m and m.group(1) == "GBDT::tree"


def test_lint_recognizes_trace_span_sites():
    """The causal-tracing call forms (obs/tracing.py) count as phase
    users too: a span name invented at a tracing call site must fail
    the lint instead of minting an unregistered series."""
    lint = _load_lint()
    m = lint.SCOPE_RE.search('with obs.trace_span("Serve::request"):')
    assert m and m.group(1) == "Serve::request"
    m = lint.SCOPE_RE.search('obs.trace_begin("Serve::queue",')
    assert m and m.group(1) == "Serve::queue"
    m = lint.SCOPE_RE.search('with TRACER.span("GBDT::iteration"):')
    assert m and m.group(1) == "GBDT::iteration"


def test_lint_catches_undeclared_trace_span(tmp_path, monkeypatch):
    """A tracing span name outside the taxonomy is a lint error."""
    lint = _load_lint()
    pkg = tmp_path / "lightgbm_tpu"
    (pkg / "obs").mkdir(parents=True)
    (pkg / "ops").mkdir()
    real = (pathlib.Path(lint.__file__).resolve().parent.parent
            / "lightgbm_tpu" / "obs" / "phases.py")
    (pkg / "obs" / "phases.py").write_text(real.read_text())
    (pkg / "server.py").write_text(
        'with obs.trace_span("Serve::rogue"):\n    pass\n')
    (pkg / "ops" / "grow.py").write_text("")
    (pkg / "ops" / "ordered_grow.py").write_text("")
    monkeypatch.setattr(lint, "ROOT", tmp_path)
    monkeypatch.setattr(lint, "PKG", pkg)
    errors = lint.check()
    assert any("Serve::rogue" in e for e in errors)


def test_every_phase_resolves_to_unique_span_series():
    """Check 4: the phase taxonomy maps 1:1 onto valid histogram series
    names, so the metrics namespace cannot diverge from phases.py."""
    import pathlib
    import importlib.util
    path = (pathlib.Path(__file__).resolve().parent.parent
            / "lightgbm_tpu" / "obs" / "phases.py")
    spec = importlib.util.spec_from_file_location("phases_standalone", path)
    phases = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(phases)          # no package/jax import
    lint = _load_lint()
    seen = {}
    for name in phases.HOST_PHASES | phases.DEVICE_PHASES:
        series = phases.span_series(name)
        assert lint.SERIES_RE.match(series), (name, series)
        assert series not in seen, (name, seen[series])
        seen[series] = name


def test_lint_catches_span_series_collision(tmp_path, monkeypatch):
    """Two phases aliasing onto one series name is a lint error."""
    lint = _load_lint()
    pkg = tmp_path / "lightgbm_tpu"
    (pkg / "obs").mkdir(parents=True)
    (pkg / "ops").mkdir()
    real = (pathlib.Path(lint.__file__).resolve().parent.parent
            / "lightgbm_tpu" / "obs" / "phases.py")
    # "Gbdt.tree" sanitizes to the same series as "GBDT::tree"
    (pkg / "obs" / "phases.py").write_text(
        real.read_text()
        + '\nHOST_PHASES = frozenset(HOST_PHASES | {"Gbdt.tree"})\n')
    (pkg / "ops" / "grow.py").write_text("")
    (pkg / "ops" / "ordered_grow.py").write_text("")
    monkeypatch.setattr(lint, "ROOT", tmp_path)
    monkeypatch.setattr(lint, "PKG", pkg)
    errors = lint.check()
    assert any("collide" in e and "Gbdt.tree" in e for e in errors)


def test_lint_catches_undeclared_scope(tmp_path, monkeypatch):
    """Sanity: a scope name outside the taxonomy is reported."""
    lint = _load_lint()
    pkg = tmp_path / "lightgbm_tpu"
    (pkg / "obs").mkdir(parents=True)
    (pkg / "ops").mkdir()
    real_phases = (pathlib.Path(lint.__file__).resolve().parent.parent
                   / "lightgbm_tpu" / "obs" / "phases.py")
    (pkg / "obs" / "phases.py").write_text(real_phases.read_text())
    (pkg / "models.py").write_text(
        'with timetag.scope("GBDT::rogue"):\n    pass\n')
    (pkg / "ops" / "grow.py").write_text(
        'with jax.named_scope("hist"):\n    pass\n'
        'with jax.named_scope("find_split"):\n    pass\n'
        'with jax.named_scope("split"):\n    pass\n')
    (pkg / "ops" / "ordered_grow.py").write_text("")
    monkeypatch.setattr(lint, "ROOT", tmp_path)
    monkeypatch.setattr(lint, "PKG", pkg)
    errors = lint.check()
    assert any("GBDT::rogue" in e for e in errors)
