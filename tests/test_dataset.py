import numpy as np

from lightgbm_tpu.io.dataset import BinnedDataset, Metadata


def _toy(n=500, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    X[:, 3] = 1.0  # trivial feature
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


def test_from_matrix_shapes_and_trivial_drop():
    X, y = _toy()
    ds = BinnedDataset.from_matrix(X, y, max_bin=63, min_data_in_leaf=5)
    assert ds.num_total_features == 6
    assert ds.num_features == 5  # trivial column dropped
    assert ds.real_to_inner[3] == -1
    assert ds.bins.shape == (5, 500)
    assert ds.bins.dtype == np.uint8
    assert (ds.num_bin_per_feature() <= 63).all()


def test_bins_monotone_in_value():
    X, y = _toy()
    ds = BinnedDataset.from_matrix(X, y, max_bin=16, min_data_in_leaf=5)
    col = X[:, 0]
    bins = ds.bins[ds.real_to_inner[0]]
    order = np.argsort(col)
    assert np.all(np.diff(bins[order].astype(int)) >= 0)


def test_create_valid_aligned():
    X, y = _toy()
    Xv, yv = _toy(seed=1)
    ds = BinnedDataset.from_matrix(X, y, max_bin=32, min_data_in_leaf=5)
    valid = ds.create_valid(Xv, yv)
    assert valid.bins.shape[0] == ds.bins.shape[0]
    # same mapper objects => identical binning of identical values
    f0 = ds.used_feature_map[0]
    np.testing.assert_array_equal(
        valid.bins[0], ds.mappers[0].value_to_bin(Xv[:, f0]).astype(ds.bins.dtype))


def test_subset():
    X, y = _toy()
    ds = BinnedDataset.from_matrix(X, y, max_bin=32, min_data_in_leaf=5)
    ds.metadata.set_weights(np.arange(500, dtype=np.float64))
    idx = np.arange(0, 500, 5)
    sub = ds.subset(idx)
    assert sub.num_data == 100
    np.testing.assert_array_equal(sub.bins, ds.bins[:, idx])
    np.testing.assert_allclose(sub.metadata.weights, np.arange(0, 500, 5))


def test_binary_roundtrip(tmp_path):
    X, y = _toy()
    ds = BinnedDataset.from_matrix(X, y, max_bin=32, min_data_in_leaf=5)
    path = str(tmp_path / "ds.bin")
    ds.save_binary(path)
    assert BinnedDataset.is_binary_file(path)
    ds2 = BinnedDataset.load_binary(path)
    np.testing.assert_array_equal(ds.bins, ds2.bins)
    np.testing.assert_allclose(ds.metadata.label, ds2.metadata.label)
    assert ds2.feature_infos() == ds.feature_infos()


def test_metadata_query():
    md = Metadata(10)
    md.set_query([3, 3, 4])
    np.testing.assert_array_equal(md.query_boundaries, [0, 3, 6, 10])
    md2 = Metadata(6)
    md2.set_query_id([1, 1, 2, 2, 2, 5])
    np.testing.assert_array_equal(md2.query_boundaries, [0, 2, 5, 6])


def test_subset_rebuilds_query_boundaries():
    X, y = _toy(n=100)
    ds = BinnedDataset.from_matrix(X, y, max_bin=16, min_data_in_leaf=5)
    ds.metadata.set_query([30, 30, 40])
    sub = ds.subset(np.arange(25, 70))  # spans queries 0..2 partially
    np.testing.assert_array_equal(sub.metadata.query_boundaries, [0, 5, 35, 45])


def test_filter_cnt_scaled_to_sample():
    # 150 rows, min_data_in_leaf=100: reference filter_cnt = 0.95*100/150*150
    # = 95 < 150, so a balanced feature must survive (it would be wrongly
    # dropped if the unscaled min_data_in_leaf were used).
    rng = np.random.RandomState(0)
    X = rng.normal(size=(150, 2))
    y = np.zeros(150)
    ds = BinnedDataset.from_matrix(X, y, max_bin=16, min_data_in_leaf=100)
    assert ds.num_features == 2
