"""EMA-FS gain-informed feature screening — pins (docs/SPARSE.md).

Contract: screening disabled is the bit-identical baseline; enabled it
keeps higgslike holdout AUC within 0.002 while masking a big share of
the feature space; masks are runtime arguments and the compacted view
rides a fixed shape budget, so mask toggles and refresh rounds record
ZERO new XLA programs after warmup (the compile-ledger pin)."""

import os
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from lightgbm_tpu import obs
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.models.screening import GainScreener
from lightgbm_tpu.obs import compile_ledger

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from bench import make_higgs_like  # noqa: E402

pytestmark = pytest.mark.sparse


def _auc(y, s):
    order = np.argsort(s)
    r = np.empty(len(s))
    r[order] = np.arange(1, len(s) + 1)
    pos = y > 0.5
    npos, nneg = pos.sum(), (~pos).sum()
    return (r[pos].sum() - npos * (npos + 1) / 2) / (npos * nneg)


def _train(X, y, extra=None, iters=40):
    p = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 20,
         "learning_rate": 0.1, "num_iterations": iters}
    p.update(extra or {})
    ds = BinnedDataset.from_matrix(X, y, max_bin=255, min_data_in_leaf=20)
    b = GBDT(Config(p), ds)
    for _ in range(iters):
        b.train_one_iter()
    b._flush_pending()
    return b


# ---------------------------------------------------------------------------
# screener unit behavior
# ---------------------------------------------------------------------------

def test_schedule_warmup_refresh_screened():
    s = GainScreener(8, 8, np.arange(8), ratio=0.5, refresh=4, warmup=3,
                     decay=0.9)
    modes = [s.round_mode(i) for i in range(12)]
    assert modes[:3] == ["warmup"] * 3
    assert modes[3] == "refresh"
    assert modes[4:7] == ["screened"] * 3
    assert modes[7] == "refresh"
    assert s.period(4) == 0 and s.period(7) == 1 and s.period(8) == 1


def test_active_columns_follow_gains():
    s = GainScreener(6, 6, np.arange(6), ratio=0.5, refresh=4, warmup=0,
                     decay=0.5)
    s.ewma = np.array([0.0, 9.0, 1.0, 8.0, 0.1, 7.0])
    cols = s.active_columns()
    assert list(cols) == [1, 3, 5]            # top ceil(0.5*6)=3, sorted
    mask = s.screen_mask(cols)
    assert mask.tolist() == [False, True, False, True, False, True]


def test_column_granularity_with_bundles():
    # features 0,1 share column 0; feature 2 owns column 1: the column's
    # score is the max member EWMA, and masks are column-granular
    s = GainScreener(3, 2, np.array([0, 0, 1]), ratio=0.5, refresh=4,
                     warmup=0, decay=0.5)
    s.ewma = np.array([0.0, 5.0, 1.0])
    cols = s.active_columns()
    assert list(cols) == [0]                  # keep ceil(0.5*2)=1 column
    assert s.screen_mask(cols).tolist() == [True, True, False]


def test_ewma_update_from_trees():
    class FakeTree:
        num_leaves = 3
        split_feature_inner = np.array([1, 4])
        split_gain = np.array([10.0, 2.0])

    s = GainScreener(6, 6, np.arange(6), ratio=0.5, refresh=4, warmup=0,
                     decay=0.5)
    s.observe_trees([FakeTree()])
    assert s.ewma[1] == pytest.approx(5.0)
    assert s.ewma[4] == pytest.approx(1.0)
    assert s.ewma[0] == 0.0
    state = s.state()
    s2 = GainScreener(6, 6, np.arange(6), ratio=0.5, refresh=4, warmup=0,
                      decay=0.5)
    s2.restore(state)
    assert np.array_equal(s2.ewma, s.ewma)


# ---------------------------------------------------------------------------
# training pins
# ---------------------------------------------------------------------------

def test_screening_disabled_is_bit_identical_baseline():
    X, y = make_higgs_like(2500)
    b0 = _train(X, y, iters=8)
    b1 = _train(X, y, {"feature_screen_ratio": 0.0}, iters=8)
    assert b1.save_model_to_string() == b0.save_model_to_string()


def test_screening_keeps_higgslike_auc_within_pin():
    X, y = make_higgs_like(12000)
    Xt, yt, Xv, yv = X[:9000], y[:9000], X[9000:], y[9000:]
    b0 = _train(Xt, yt, iters=40)
    b1 = _train(Xt, yt, {"feature_screen_ratio": 0.25,
                         "feature_screen_warmup": 15,
                         "feature_screen_refresh": 5}, iters=40)
    a0 = _auc(yv, b0.predict_raw(Xv)[0])
    a1 = _auc(yv, b1.predict_raw(Xv)[0])
    assert abs(a0 - a1) <= 0.002, (a0, a1)
    # screening actually masked features on screened rounds
    assert obs.get_gauge("screen_active_features") < X.shape[1]
    assert obs.get_counter("screen_refresh_total") > 0


def test_compile_ledger_flat_across_mask_and_refresh_toggles():
    X, y = make_higgs_like(3000)
    p = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 20,
         "learning_rate": 0.1, "num_iterations": 40,
         "feature_screen_ratio": 0.5, "feature_screen_warmup": 3,
         "feature_screen_refresh": 3}
    ds = BinnedDataset.from_matrix(X, y, max_bin=255, min_data_in_leaf=20)
    b = GBDT(Config(p), ds)
    # warm through: warmup rounds, the first refresh, the first screened
    # round (the compacted view's one-time trace), and a second refresh
    for _ in range(8):
        b.train_one_iter()
    jax.block_until_ready(b.train_data.score)
    n0 = len(compile_ledger.events())
    # many more rounds: the EWMA moves, masks toggle, the active set is
    # re-drawn every refresh period, full refresh rounds interleave
    for _ in range(14):
        b.train_one_iter()
    b._flush_pending()
    jax.block_until_ready(b.train_data.score)
    assert len(compile_ledger.events()) == n0


def test_screening_composes_with_bundling():
    from tests.test_bundling import one_hot_data
    X, y = one_hot_data(n=2000, blocks=10, block_size=6, seed=13)
    p = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 20,
         "min_sum_hessian_in_leaf": 1e-3, "max_bin": 63,
         "num_iterations": 12, "feature_screen_ratio": 0.4,
         "feature_screen_warmup": 4, "feature_screen_refresh": 4}
    ds = BinnedDataset.from_matrix(X, y, max_bin=63, min_data_in_leaf=20,
                                   enable_bundle=True)
    assert ds.bundle_plan is not None
    b = GBDT(Config(p), ds)
    for _ in range(12):
        b.train_one_iter()
    b._flush_pending()
    assert np.isfinite(b.predict_raw(X[:200])).all()
    assert obs.get_gauge("screen_active_features") <= ds.num_features


def test_screener_state_rides_snapshots():
    X, y = make_higgs_like(2500)
    p = {"feature_screen_ratio": 0.3, "feature_screen_warmup": 2,
         "feature_screen_refresh": 3}
    b = _train(X, y, p, iters=6)
    state = b.snapshot_state()
    assert state["screen_state"] is not None
    assert float(np.asarray(state["screen_state"]["ewma"]).sum()) > 0
    ds = BinnedDataset.from_matrix(X, y, max_bin=255, min_data_in_leaf=20)
    cfg = Config({"objective": "binary", "num_leaves": 15,
                  "min_data_in_leaf": 20, "learning_rate": 0.1, **p})
    b2 = GBDT(cfg, ds)
    b2.restore_state(state)
    assert np.array_equal(b2._screener.ewma, b._screener.ewma)


def test_config_validates_screening_params():
    with pytest.raises(ValueError):
        Config({"feature_screen_ratio": 1.0})
    with pytest.raises(ValueError):
        Config({"feature_screen_ratio": -0.1})
    with pytest.raises(ValueError):
        Config({"feature_screen_refresh": 0})
    with pytest.raises(ValueError):
        Config({"feature_screen_warmup": -1})
    with pytest.raises(ValueError):
        Config({"feature_screen_decay": 0.0})
    Config({"feature_screen_ratio": 0.5, "feature_screen_refresh": 2,
            "feature_screen_warmup": 0, "feature_screen_decay": 1.0})
