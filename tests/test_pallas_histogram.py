"""Parity of the Pallas TPU histogram kernel against the scatter reference,
run in the Pallas interpreter so the TPU production path is checked on CPU
(including the row-padding and max_bin->lane-multiple cropping paths)."""

import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.histogram import (build_children_histograms,
                                        build_root_histogram)
from lightgbm_tpu.ops.pallas_histogram import (children_histograms_pallas,
                                               root_histogram_pallas)


def _data(seed, n, f, B):
    rng = np.random.RandomState(seed)
    bins = jnp.asarray(rng.randint(0, B, size=(f, n)), jnp.int32)
    g = jnp.asarray(rng.normal(size=n), jnp.float32)
    h = jnp.abs(g) + 0.1
    w = jnp.asarray((rng.rand(n) > 0.3), jnp.float32)  # bagging-style mask
    leaf = jnp.asarray(rng.randint(0, 5, size=n), jnp.int32)
    return bins, g, h, w, leaf


@pytest.mark.parametrize("n,B,n_blk", [
    (1024, 16, 256),      # exact block multiple
    (1000, 16, 256),      # row padding path
    (700, 255, 256),      # max_bin not a lane multiple -> crop path
])
def test_children_parity_interpret(n, B, n_blk):
    bins, g, h, w, leaf = _data(0, n, 5, B)
    want = np.asarray(build_children_histograms(bins, g, h, w, leaf, 1, 3, B))
    got = np.asarray(children_histograms_pallas(bins, g, h, w, leaf, 1, 3, B,
                                                n_blk=n_blk, interpret=True))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


def test_root_parity_interpret():
    bins, g, h, w, _ = _data(1, 900, 4, 32)
    want = np.asarray(build_root_histogram(bins, g, h, w, 32))
    got = np.asarray(root_histogram_pallas(bins, g, h, w, 32, n_blk=256,
                                           interpret=True))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)
