"""Memory guardrails: fail fast with an HBM estimate instead of dying in
XLA allocation (the dense-only design's replacement for the reference's
sparse bins, sparse_bin.hpp:67-384, and LRU histogram pool,
feature_histogram.hpp:299-455)."""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.models.gbdt import GBDT, estimate_train_memory
from lightgbm_tpu.utils.log import LightGBMError


def _tiny_dataset(n=400, f=6):
    rng = np.random.RandomState(0)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] > 0).astype(np.float32)
    return BinnedDataset.from_matrix(X, y, max_bin=32, min_data_in_leaf=5)


def test_estimate_components_scale_with_problem():
    small = estimate_train_memory(1000, 8, 31, 64, 1)
    big_rows = estimate_train_memory(100_000, 8, 31, 64, 1)
    big_cache = estimate_train_memory(1000, 8, 1023, 256, 1)
    assert set(small) == {"bins_device", "packed_payload",
                         "scores_and_gradients", "score_double_buffer",
                         "histogram_cache", "vmem_scratch", "linear_fit",
                         "working", "total"}
    assert all(v >= 0 for v in small.values())
    assert big_rows["bins_device"] > small["bins_device"]
    assert big_rows["total"] > small["total"]
    # cache term is exactly L * F * 9 * B * 4 bytes
    assert big_cache["histogram_cache"] == 1023 * 8 * 9 * 256 * 4
    assert small["total"] == sum(v for k, v in small.items() if k != "total")


def test_estimate_linear_component():
    """linear_tree (docs/LINEAR_TREES.md §Memory): linear_k bills the
    raw f32 copy, the phi gathers, and the [L, K+1, K+1] normal
    equations; linear_k=0 (the default) is exactly the old estimate."""
    base = estimate_train_memory(1000, 8, 31, 64, 1)
    lin = estimate_train_memory(1000, 8, 31, 64, 1, linear_k=4)
    assert base["linear_fit"] == 0
    m = 5
    assert lin["linear_fit"] == (1000 * 8 * 4 + 2 * 1000 * m * 4
                                 + 3 * 31 * m * m * 4)
    assert lin["total"] == base["total"] + lin["linear_fit"]
    assert lin["total"] == sum(v for k, v in lin.items() if k != "total")


def test_estimate_flags_zero_their_components():
    base = estimate_train_memory(1000, 8, 31, 64, 1)
    donated = estimate_train_memory(1000, 8, 31, 64, 1, donate_score=True)
    nocache = estimate_train_memory(1000, 8, 31, 64, 1, leaf_cache=False)
    fused = estimate_train_memory(1000, 8, 31, 64, 1, fused_scratch=True)
    assert base["score_double_buffer"] == 1000 * 4
    assert donated["score_double_buffer"] == 0
    assert donated["total"] == base["total"] - base["score_double_buffer"]
    assert nocache["histogram_cache"] == 0
    assert nocache["total"] == base["total"] - base["histogram_cache"]
    assert base["vmem_scratch"] == 0
    assert fused["vmem_scratch"] == 2 * 8 * 64 * 3 * 4
    for est in (base, donated, nocache, fused):
        assert est["total"] == sum(v for k, v in est.items()
                                   if k != "total")


def test_oversize_config_fails_fast_with_breakdown(monkeypatch):
    ds = _tiny_dataset()
    monkeypatch.setenv("LGBT_DEVICE_MEMORY_BYTES", "1000000")  # 1 MB budget
    cfg = Config({"objective": "binary", "num_leaves": 4095, "max_bin": 255,
                  "min_data_in_leaf": 1, "num_iterations": 1})
    with pytest.raises(LightGBMError) as ei:
        GBDT(cfg, ds)
    msg = str(ei.value)
    assert "exceeds the device budget" in msg
    assert "histogram_cache" in msg          # the breakdown is actionable
    assert "num_leaves" in msg               # and says what to shrink


def test_within_budget_trains(monkeypatch):
    ds = _tiny_dataset()
    monkeypatch.setenv("LGBT_DEVICE_MEMORY_BYTES", str(1 << 33))  # 8 GB
    cfg = Config({"objective": "binary", "num_leaves": 7, "max_bin": 32,
                  "min_data_in_leaf": 5, "num_iterations": 2})
    gb = GBDT(cfg, ds)
    gb.train(2)
    assert len(gb.models) == 2


def test_histogram_pool_size_warns_loudly(capsys, monkeypatch):
    from lightgbm_tpu.utils import log
    log.reset_warn_once()   # the warning is one-shot per process now
    ds = _tiny_dataset()
    monkeypatch.delenv("LGBT_DEVICE_MEMORY_BYTES", raising=False)
    cfg = Config({"objective": "binary", "num_leaves": 255, "max_bin": 32,
                  "min_data_in_leaf": 5, "num_iterations": 1,
                  "histogram_pool_size": 0.001})
    GBDT(cfg, ds)
    err = capsys.readouterr().err
    assert "histogram_pool_size" in err
    assert "does NOT bound memory" in err


def test_histogram_pool_size_default_is_silent(capsys):
    ds = _tiny_dataset()
    cfg = Config({"objective": "binary", "num_leaves": 7, "max_bin": 32,
                  "min_data_in_leaf": 5, "num_iterations": 1})
    GBDT(cfg, ds)
    assert "histogram_pool_size" not in capsys.readouterr().err
