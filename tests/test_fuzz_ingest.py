"""Ingest fuzzer (marker ``fuzz``, tier-1-fast subset): ~200 seeded
random byte/line mutations of valid CSV / TSV / LibSVM files pushed
through ``parse_file``, ``load_file_two_round``, and
``Tree.from_string``.

THE contract under test: every outcome is either a successful parse or
a ``LightGBMError`` — any other exception type (bare ValueError,
IndexError, UnicodeDecodeError, OverflowError, MemoryError from a
corrupt-digit allocation...) fails the test.  That is the whole data
boundary in one sentence: dirt is a NAMED, CLASSIFIED event, never an
unclassified crash.
"""

import numpy as np
import pytest

from lightgbm_tpu.io.guard import IngestGuard
from lightgbm_tpu.io.parser import parse_file
from lightgbm_tpu.io.streaming import load_file_two_round
from lightgbm_tpu.models.tree import Tree
from lightgbm_tpu.utils.log import LightGBMError

pytestmark = pytest.mark.fuzz

#: bytes the mutator splices in: format chars, signs, digits, NA-ish
#: letters, raw garbage — the alphabet real corruption is made of
_SPLICE = (b",\t:; -+.eE0123456789naNAxz#\x00\xff\n"
           b"infNULL@")


def _csv_seed():
    rng = np.random.RandomState(11)
    rows = ["lab,a,b,c"]
    for i in range(20):
        rows.append(",".join([f"{i % 2}"]
                             + [f"{v:.4f}" for v in rng.normal(size=3)]))
    return ("\n".join(rows) + "\n").encode(), {"has_header": True}


def _tsv_seed():
    rng = np.random.RandomState(12)
    rows = []
    for i in range(20):
        rows.append("\t".join([f"{i % 2}"]
                              + [f"{v:.4f}" for v in rng.normal(size=4)]))
    return ("\n".join(rows) + "\n").encode(), {}


def _libsvm_seed():
    rng = np.random.RandomState(13)
    rows = []
    for i in range(20):
        pairs = [f"{c}:{rng.normal():.4f}"
                 for c in sorted(rng.choice(8, size=3, replace=False))]
        rows.append(" ".join([f"{i % 2}"] + pairs))
    return ("\n".join(rows) + "\n").encode(), {}


def _mutate(blob: bytes, rng: np.random.RandomState) -> bytes:
    """One random structural or byte-level mutation."""
    b = bytearray(blob)
    op = rng.randint(6)
    if op == 0 and b:                      # flip random bytes
        for _ in range(rng.randint(1, 8)):
            b[rng.randint(len(b))] ^= 1 << rng.randint(8)
    elif op == 1 and b:                    # splice random bytes in
        pos = rng.randint(len(b))
        ins = bytes(_SPLICE[rng.randint(len(_SPLICE))]
                    for _ in range(rng.randint(1, 12)))
        b[pos:pos] = ins
    elif op == 2 and b:                    # delete a span
        lo = rng.randint(len(b))
        hi = min(len(b), lo + rng.randint(1, 32))
        del b[lo:hi]
    elif op == 3:                          # truncate
        b = b[:rng.randint(len(b) + 1)]
    elif op == 4:                          # duplicate + shuffle lines
        lines = bytes(b).split(b"\n")
        lines.append(lines[rng.randint(len(lines))])
        rng.shuffle(lines)
        b = bytearray(b"\n".join(lines))
    else:                                  # overwrite a span w/ splice
        if b:
            lo = rng.randint(len(b))
            hi = min(len(b), lo + rng.randint(1, 16))
            for i in range(lo, hi):
                b[i] = _SPLICE[rng.randint(len(_SPLICE))]
    return bytes(b)


def _check_outcome(fn, what, i):
    try:
        fn()
    except LightGBMError:
        pass                               # the NAMED outcome: allowed
    except Exception as exc:               # noqa: BLE001 - the contract
        pytest.fail(f"mutation {i} ({what}): {type(exc).__name__} "
                    f"escaped the data boundary: {exc!r}")


@pytest.mark.parametrize("seed_fn", [_csv_seed, _tsv_seed, _libsvm_seed],
                         ids=["csv", "tsv", "libsvm"])
def test_parsers_never_escape_lightgbmerror(tmp_path, seed_fn):
    blob, kw = seed_fn()
    rng = np.random.RandomState(hash(seed_fn.__name__) % (2 ** 31))
    p = tmp_path / "fuzz.dat"
    for i in range(55):
        p.write_bytes(_mutate(blob, rng))
        _check_outcome(
            lambda: parse_file(str(p), **kw), "parse_file", i)
        _check_outcome(
            lambda: parse_file(
                str(p), guard=IngestGuard(str(p), policy="quarantine",
                                          max_bad_row_fraction=0.5),
                **kw),
            "parse_file/quarantine", i)
        _check_outcome(
            lambda: load_file_two_round(
                str(p), max_bin=15, min_data_in_leaf=5,
                has_header=bool(kw.get("has_header"))),
            "load_file_two_round", i)


def test_tree_from_string_never_escapes_lightgbmerror():
    # a real tree text as the seed: structurally valid, then mutated
    seed = (
        "num_leaves=3\n"
        "split_feature=1 0\n"
        "split_gain=1.5 0.75\n"
        "threshold=0.25 -1.5\n"
        "decision_type=0 0\n"
        "left_child=1 -1\n"
        "right_child=-2 -3\n"
        "leaf_parent=1 0 1\n"
        "leaf_value=0.1 -0.2 0.3\n"
        "leaf_count=10 20 30\n"
        "internal_value=0.05 0.15\n"
        "internal_count=60 30\n"
        "shrinkage=0.1\n").encode()
    rng = np.random.RandomState(99)
    for i in range(40):
        text = _mutate(seed, rng).decode("utf-8", errors="replace")
        try:
            t = Tree.from_string(text)
            assert t.num_leaves >= 1
        except LightGBMError:
            pass
        except Exception as exc:  # noqa: BLE001 - the contract
            pytest.fail(f"mutation {i}: {type(exc).__name__} escaped "
                        f"Tree.from_string: {exc!r}")


def test_linear_tree_sections_never_escape_lightgbmerror():
    """Affine-leaf model sections (docs/LINEAR_TREES.md): a structurally
    valid linear tree text, then mutated — every outcome must be a
    successful parse or a LightGBMError (truncated/garbled leaf_coeff /
    leaf_feat / num_linear_features must all be NAMED refusals)."""
    seed = (
        "num_leaves=3\n"
        "split_feature=1 0\n"
        "split_gain=1.5 0.75\n"
        "threshold=0.25 -1.5\n"
        "decision_type=0 0\n"
        "left_child=1 -1\n"
        "right_child=-2 -3\n"
        "leaf_parent=1 0 1\n"
        "leaf_value=0.1 -0.2 0.3\n"
        "leaf_count=10 20 30\n"
        "internal_value=0.05 0.15\n"
        "internal_count=60 30\n"
        "shrinkage=0.1\n"
        "num_linear_features=2\n"
        "leaf_feat=1 0 -1 -1 0 1\n"
        "leaf_coeff=0.5 -0.25 0 0 1.5 0.125\n").encode()
    rng = np.random.RandomState(1234)
    # mutation sweep biased at the linear tail: 20 whole-text mutations
    # plus 20 mutations of ONLY the three linear lines (kept appended to
    # the intact constant body, so the linear parser is what's exercised)
    body, linear_tail = seed.split(b"num_linear_features=", 1)
    linear_tail = b"num_linear_features=" + linear_tail
    cases = [_mutate(seed, rng) for _ in range(20)]
    cases += [body + _mutate(linear_tail, rng) for _ in range(20)]
    for i, blob in enumerate(cases):
        text = blob.decode("utf-8", errors="replace")
        try:
            t = Tree.from_string(text)
            assert t.num_leaves >= 1
        except LightGBMError:
            pass
        except Exception as exc:  # noqa: BLE001 - the contract
            pytest.fail(f"linear mutation {i}: {type(exc).__name__} "
                        f"escaped Tree.from_string: {exc!r}")


def test_fingerprint_sections_never_escape_lightgbmerror(tmp_path):
    """Drift fingerprint tail sections (docs/OBSERVABILITY.md §Drift):
    a real saved model carrying a ``data_fingerprint`` section, then
    mutated — every outcome must be a clean parse, a clean absence
    (``None``), or a NAMED ``LightGBMError``.  30 cases: 10 whole-text
    mutations plus 20 biased at the fingerprint tail (intact tree body,
    so the section parser is what's exercised), each driven through
    both ``parse_model_fingerprint`` and the full ``Booster`` loader."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs.drift import parse_model_fingerprint

    rng0 = np.random.RandomState(5)
    X = rng0.normal(size=(80, 3))
    y = (X[:, 0] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "min_data_in_leaf": 5, "num_leaves": 4},
                    lgb.Dataset(X, label=y), num_boost_round=1)
    seed = bst.model_to_string().encode()
    assert b"\ndata_fingerprint\n" in seed
    body, tail = seed.split(b"\ndata_fingerprint\n", 1)
    body += b"\n"
    tail = b"data_fingerprint\n" + tail
    rng = np.random.RandomState(4321)
    cases = [(_mutate(seed, rng), "whole") for _ in range(10)]
    cases += [(body + _mutate(tail, rng), "tail") for _ in range(20)]
    p = tmp_path / "fp_fuzz.txt"
    for i, (blob, what) in enumerate(cases):
        text = blob.decode("utf-8", errors="replace")
        try:
            fp = parse_model_fingerprint(text)
            assert fp is None or fp.num_rows >= 0
        except LightGBMError:
            pass
        except Exception as exc:  # noqa: BLE001 - the contract
            pytest.fail(f"fingerprint mutation {i} ({what}): "
                        f"{type(exc).__name__} escaped "
                        f"parse_model_fingerprint: {exc!r}")
        if what == "tail":
            # intact tree body + garbled section through the FULL model
            # loader: load cleanly or refuse by name, never crash
            p.write_text(text)
            try:
                lgb.Booster(model_file=str(p))
            except LightGBMError:
                pass
            except Exception as exc:  # noqa: BLE001 - the contract
                pytest.fail(f"fingerprint mutation {i}: "
                            f"{type(exc).__name__} escaped the Booster "
                            f"loader: {exc!r}")
    # absent section = clean absence, and a pre-fingerprint model file
    # loads with predictions unchanged
    start = seed.index(b"\ndata_fingerprint\n")
    end = seed.index(b"end data_fingerprint\n") \
        + len(b"end data_fingerprint\n")
    stripped = (seed[:start + 1] + seed[end:]).decode()
    assert "data_fingerprint" not in stripped
    assert parse_model_fingerprint(stripped) is None
    old = tmp_path / "pre_fingerprint.txt"
    old.write_text(stripped)
    loaded = lgb.Booster(model_file=str(old))
    np.testing.assert_array_equal(loaded.predict(X), bst.predict(X))
