"""CPU-interpreter parity of the fused histogram->split-gain kernel.

The fused kernel (ops/pallas_histogram.py fused_children_split_candidates
_pallas) must produce EXACTLY the BestSplit the two-program path does —
same Pallas histogram accumulation, then per_feature_scan inside the
kernel epilogue instead of a separate program over the [2, F, B, 3]
tensor in HBM.  Both paths run the identical scan code (ops/split.py),
so agreement is bit-for-bit, and these tests pin it across numerical and
categorical features and the constraint edge cases (min_data_in_leaf,
lambda_l1, min_sum_hessian, min_gain_to_split, masked features).
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from lightgbm_tpu.ops.histogram import (build_children_histograms,  # noqa: E402
                                        children_split_candidates)
from lightgbm_tpu.ops.pallas_histogram import (  # noqa: E402
    children_histograms_pallas, fused_children_split_candidates_pallas)
from lightgbm_tpu.ops.split import (BestSplit, FeatureCandidates,  # noqa: E402
                                    SplitParams, combine_feature_candidates,
                                    find_best_split, per_feature_candidates)

N_BLK = 256  # small kernel blocks: interpreter speed


def _scenario(seed=0, n=700, f=6, max_bin=21, n_cat=2):
    rng = np.random.RandomState(seed)
    bins = rng.randint(0, max_bin, size=(f, n)).astype(np.uint8)
    grad = rng.normal(size=n).astype(np.float32)
    hess = rng.uniform(0.2, 1.5, size=n).astype(np.float32)
    weight = (rng.uniform(size=n) > 0.25).astype(np.float32)
    leaf_id = rng.randint(0, 3, size=n).astype(np.int32)  # leaves 0,1,2
    num_bin = rng.randint(2, max_bin + 1, size=f).astype(np.int32)
    is_cat = np.zeros(f, bool)
    is_cat[:n_cat] = True
    feat_mask = np.ones(f, bool)
    return (jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(weight), jnp.asarray(leaf_id), jnp.asarray(num_bin),
            jnp.asarray(is_cat), jnp.asarray(feat_mask))


def _totals(grad, hess, weight, leaf_id, parent, right):
    g = grad * weight
    h = hess * weight
    out = []
    for leaf in (parent, right):
        m = (leaf_id == leaf).astype(jnp.float32)
        out.append([float(jnp.sum(g * m)), float(jnp.sum(h * m)),
                    float(jnp.sum(weight * m))])
    return jnp.asarray(out, jnp.float32)


def _both_paths(scn, max_bin, sp, parent=0, right=1, can=(True, True)):
    """(reference BestSplit, fused BestSplit) for one scenario."""
    bins, grad, hess, weight, leaf_id, num_bin, is_cat, feat_mask = scn
    totals = _totals(grad, hess, weight, leaf_id, parent, right)
    can = jnp.asarray(can)

    hist = children_histograms_pallas(bins, grad, hess, weight, leaf_id,
                                      parent, right, max_bin, n_blk=N_BLK,
                                      interpret=True)
    ref = find_best_split(hist, totals[:, 0], totals[:, 1], totals[:, 2],
                          num_bin, is_cat, feat_mask, can, sp)

    raw = fused_children_split_candidates_pallas(
        bins, grad, hess, weight, leaf_id, parent, right, totals,
        num_bin, is_cat, feat_mask, max_bin, sp, n_blk=N_BLK,
        interpret=True)
    cand = FeatureCandidates(gain=raw[:, :, 0],
                             threshold=raw[:, :, 1].astype(jnp.int32),
                             left_g=raw[:, :, 2], left_h=raw[:, :, 3],
                             left_c=raw[:, :, 4])
    fused = combine_feature_candidates(cand, totals[:, 0], totals[:, 1],
                                       can, sp)
    return ref, fused


def _assert_split_equal(ref: BestSplit, fused: BestSplit):
    np.testing.assert_array_equal(np.asarray(ref.gain),
                                  np.asarray(fused.gain))
    np.testing.assert_array_equal(np.asarray(ref.feature),
                                  np.asarray(fused.feature))
    np.testing.assert_array_equal(np.asarray(ref.threshold),
                                  np.asarray(fused.threshold))
    # left sums are meaningful only for splittable leaves (neither path
    # masks them; on an unsplittable leaf they are whatever the masked
    # -inf argmax landed on, which may differ over the lane pad)
    ok = np.isfinite(np.asarray(ref.gain))
    for a, b in ((ref.left_sum_g, fused.left_sum_g),
                 (ref.left_sum_h, fused.left_sum_h),
                 (ref.left_count, fused.left_count)):
        np.testing.assert_array_equal(np.asarray(a)[ok], np.asarray(b)[ok])


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fused_matches_find_best_split(seed):
    scn = _scenario(seed=seed)
    sp = SplitParams(min_data_in_leaf=5, min_sum_hessian_in_leaf=1e-3)
    ref, fused = _both_paths(scn, max_bin=21, sp=sp)
    assert np.isfinite(np.asarray(ref.gain)).any(), "degenerate scenario"
    _assert_split_equal(ref, fused)


def test_fused_matches_with_l1_and_min_gain():
    scn = _scenario(seed=3, n=900, max_bin=17)
    sp = SplitParams(min_data_in_leaf=10, min_sum_hessian_in_leaf=0.5,
                     lambda_l1=0.3, lambda_l2=0.7, min_gain_to_split=0.05)
    _assert_split_equal(*_both_paths(scn, max_bin=17, sp=sp))


def test_fused_matches_min_data_edge():
    """min_data_in_leaf near the leaf size: most candidates invalid, the
    valid frontier decides — the exact region a masking bug would hit."""
    scn = _scenario(seed=4, n=400)
    sp = SplitParams(min_data_in_leaf=60, min_sum_hessian_in_leaf=10.0)
    _assert_split_equal(*_both_paths(scn, max_bin=21, sp=sp))


def test_fused_all_unsplittable():
    """Impossible constraints: both paths must report -inf gain and the
    masked sentinel feature/threshold."""
    scn = _scenario(seed=5, n=300)
    sp = SplitParams(min_data_in_leaf=10_000)
    ref, fused = _both_paths(scn, max_bin=21, sp=sp)
    assert not np.isfinite(np.asarray(ref.gain)).any()
    np.testing.assert_array_equal(np.asarray(fused.gain),
                                  np.asarray(ref.gain))
    np.testing.assert_array_equal(np.asarray(fused.feature), [-1, -1])
    np.testing.assert_array_equal(np.asarray(fused.threshold), [0, 0])


def test_fused_respects_feature_mask_and_can_split():
    bins, grad, hess, weight, leaf_id, num_bin, is_cat, _ = _scenario(seed=6)
    fm = np.ones(bins.shape[0], bool)
    fm[2:] = False                      # only features 0,1 usable
    scn = (bins, grad, hess, weight, leaf_id, num_bin, is_cat,
           jnp.asarray(fm))
    sp = SplitParams(min_data_in_leaf=5, min_sum_hessian_in_leaf=1e-3)
    ref, fused = _both_paths(scn, max_bin=21, sp=sp, can=(True, False))
    _assert_split_equal(ref, fused)
    assert np.asarray(fused.feature)[0] in (-1, 0, 1)
    assert np.asarray(fused.feature)[1] == -1  # can_split=False masks


def test_categorical_one_vs_rest_semantics():
    """A pure-categorical scenario where the winning one-vs-rest bin is
    known: category 0 carries all the negative gradient mass."""
    n, f, max_bin = 512, 2, 8
    rng = np.random.RandomState(7)
    cats = rng.randint(0, 4, size=n)
    bins = np.stack([cats, rng.randint(0, max_bin, size=n)]).astype(np.uint8)
    grad = np.where(cats == 0, -2.0, 1.0).astype(np.float32)
    hess = np.ones(n, np.float32)
    weight = np.ones(n, np.float32)
    leaf_id = np.zeros(n, np.int32)
    num_bin = np.asarray([4, max_bin], np.int32)
    is_cat = np.asarray([True, False])
    scn = tuple(jnp.asarray(a) for a in
                (bins, grad, hess, weight, leaf_id, num_bin, is_cat,
                 np.ones(f, bool)))
    sp = SplitParams(min_data_in_leaf=5, min_sum_hessian_in_leaf=1e-3)
    ref, fused = _both_paths(scn, max_bin=max_bin, sp=sp, parent=0, right=-2,
                             can=(True, False))
    _assert_split_equal(ref, fused)
    assert int(np.asarray(fused.feature)[0]) == 0
    assert int(np.asarray(fused.threshold)[0]) == 0  # "cat == 0 goes left"


def test_cpu_dispatcher_matches_scatter_path():
    """children_split_candidates off-TPU == scatter histogram + the
    shared per-feature scan (identical code, pinned anyway so the
    dispatcher cannot drift)."""
    bins, grad, hess, weight, leaf_id, num_bin, is_cat, fm = _scenario(8)
    sp = SplitParams(min_data_in_leaf=5, min_sum_hessian_in_leaf=1e-3)
    totals = _totals(grad, hess, weight, leaf_id, 0, 1)
    cand = children_split_candidates(bins, grad, hess, weight, leaf_id,
                                     0, 1, totals, num_bin, is_cat, fm,
                                     21, sp)
    hist = build_children_histograms(bins, grad, hess, weight, leaf_id,
                                     0, 1, 21)
    want = per_feature_candidates(hist, totals[:, 0], totals[:, 1],
                                  totals[:, 2], num_bin, is_cat, fm, sp)
    for a, b in zip(cand, want):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_grow_tree_fused_comm_matches_plain_full_pass():
    """End to end: grow_tree with the fused-gain comm produces the same
    tree as the plain full-pass comm (identical scatter histograms feed
    both on CPU, and the gain math is shared)."""
    from lightgbm_tpu.ops.grow import GrowParams, SerialComm, grow_tree

    rng = np.random.RandomState(9)
    n, f, max_bin = 800, 5, 15
    bins = jnp.asarray(rng.randint(0, max_bin, size=(f, n)).astype(np.uint8))
    grad = jnp.asarray(rng.normal(size=n).astype(np.float32))
    hess = jnp.asarray(rng.uniform(0.5, 1.5, size=n).astype(np.float32))
    w = jnp.ones(n, jnp.float32)
    num_bin = jnp.full(f, max_bin, jnp.int32)
    is_cat = jnp.zeros(f, bool)
    fm = jnp.ones(f, bool)
    params = GrowParams(num_leaves=8, max_bin=max_bin, min_data_in_leaf=20,
                        min_sum_hessian_in_leaf=1e-3)
    args = (bins, num_bin, is_cat, fm, grad, hess, w, jnp.float32(0.1))
    ta_plain, leaf_plain, delta_plain = grow_tree(
        *args, params, SerialComm(leaf_cache=False))
    ta_fused, leaf_fused, delta_fused = grow_tree(
        *args, params, SerialComm(leaf_cache=False, fused_gain=True))
    for a, b in zip(ta_plain, ta_fused):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(leaf_plain),
                                  np.asarray(leaf_fused))
    np.testing.assert_array_equal(np.asarray(delta_plain),
                                  np.asarray(delta_fused))
    assert int(ta_fused.num_leaves) > 1
