import pytest

from lightgbm_tpu.config import Config, apply_aliases, parse_cli_args


def test_aliases_resolve():
    params = apply_aliases({"num_tree": 50, "min_child_samples": 7,
                            "colsample_bytree": 0.5})
    assert params == {"num_iterations": 50, "min_data_in_leaf": 7,
                      "feature_fraction": 0.5}


def test_canonical_wins_over_alias():
    params = apply_aliases({"num_iterations": 10, "num_round": 99})
    assert params["num_iterations"] == 10


def test_defaults():
    cfg = Config()
    assert cfg.num_leaves == 127
    assert cfg.max_bin == 255
    assert cfg.learning_rate == pytest.approx(0.1)
    assert cfg.min_data_in_leaf == 100
    assert cfg.min_sum_hessian_in_leaf == pytest.approx(10.0)
    assert cfg.objective == "regression"
    assert cfg.metric == ["l2"]  # derived from objective


def test_metric_defaults_from_objective():
    assert Config({"objective": "binary"}).metric == ["binary_logloss"]
    assert Config({"objective": "lambdarank"}).metric == ["ndcg"]
    assert Config({"objective": "multiclass", "num_class": 3}).metric == ["multi_logloss"]


def test_objective_aliases():
    assert Config({"objective": "mse"}).objective == "regression"
    assert Config({"objective": "mae"}).objective == "regression_l1"


def test_type_coercion_from_strings():
    cfg = Config({"num_leaves": "31", "learning_rate": "0.05",
                  "is_unbalance": "true", "metric": "l2,auc"})
    assert cfg.num_leaves == 31
    assert cfg.learning_rate == pytest.approx(0.05)
    assert cfg.is_unbalance is True
    assert cfg.metric == ["l2", "auc"]


def test_conflicts():
    with pytest.raises(ValueError):
        Config({"objective": "multiclass", "num_class": 1})
    with pytest.raises(ValueError):
        Config({"num_leaves": 1})
    with pytest.raises(ValueError):
        Config({"tree_learner": "bogus"})
    with pytest.raises(ValueError):
        Config({"boosting_type": "goss", "bagging_fraction": 0.5,
                "bagging_freq": 1})


def test_max_depth_caps_leaves():
    cfg = Config({"max_depth": 3, "num_leaves": 127})
    assert cfg.num_leaves == 8


def test_parallel_derivation():
    cfg = Config({"tree_learner": "data", "num_machines": 4})
    assert cfg.is_parallel and cfg.is_parallel_find_bin
    cfg = Config({"tree_learner": "data", "num_machines": 1})
    assert not cfg.is_parallel
    cfg = Config({"tree_learner": "feature", "num_machines": 2})
    assert cfg.is_parallel and not cfg.is_parallel_find_bin


def test_parse_cli_args_and_config_file(tmp_path):
    conf = tmp_path / "train.conf"
    conf.write_text("task = train\n# comment\nnum_trees = 25\n"
                    "objective = binary  # trailing comment\n")
    params = parse_cli_args([f"config={conf}", "num_leaves=31"])
    cfg = Config(params)
    assert cfg.num_iterations == 25
    assert cfg.objective == "binary"
    assert cfg.num_leaves == 31


def test_multiclass_requires_more_than_two_classes():
    with pytest.raises(ValueError):
        Config({"objective": "multiclass", "num_class": 2})
    assert Config({"objective": "multiclass", "num_class": 3}).num_class == 3


def test_tree_learner_normalized_to_serial():
    cfg = Config({"tree_learner": "data", "num_machines": 1})
    assert cfg.tree_learner == "serial"


def test_objective_metric_mismatch():
    with pytest.raises(ValueError):
        Config({"objective": "binary", "metric": "multi_logloss"})
    with pytest.raises(ValueError):
        Config({"objective": "multiclass", "num_class": 3, "metric": "auc"})
