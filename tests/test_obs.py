"""Telemetry layer (lightgbm_tpu/obs/): registry semantics, the JSONL
per-iteration event stream, static collective-traffic accounting checked
against hand-computed histogram payload sizes on the 8-virtual-device
mesh, trace capture, and the log warn_once / stdlib-bridge satellites."""

import logging
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.utils import log as lgb_log
from lightgbm_tpu.utils import timetag


def _data(n=400, f=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_counters_gauges_merge_reset():
    r = obs.Registry()
    r.inc("x")
    r.inc("x", 4)
    r.set_gauge("g", 7.5)
    snap = r.snapshot()
    assert snap["counters"]["x"] == 5
    assert snap["gauges"]["g"] == 7.5
    # merge: counters add, gauges last-write-wins
    r.merge({"counters": {"x": 2, "y": 1}, "gauges": {"g": 1.0}})
    snap = r.snapshot()
    assert snap["counters"] == {"x": 7, "y": 1}
    assert snap["gauges"]["g"] == 1.0
    r.reset()
    assert r.snapshot()["counters"] == {}
    assert r.snapshot()["gauges"] == {}


def test_process_registry_survives_reset_config():
    """reset_config rebuilds learner state; the run's telemetry account
    must persist across it (counters are process-scoped, not booster-
    scoped)."""
    X, y = _data(300, 4, seed=1)
    ds = lgb.Dataset(X, label=y)
    booster = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbose": -1}, ds, num_boost_round=2)
    before = booster.telemetry()["counters"]["iterations"]
    assert before >= 2
    booster.reset_parameter({"learning_rate": 0.05})
    booster.update()
    after = booster.telemetry()["counters"]["iterations"]
    assert after >= before + 1
    # HBM gauges from estimate_train_memory were recorded at setup
    gauges = booster.telemetry()["gauges"]
    assert gauges["hbm_train_estimate_bytes"] > 0
    assert gauges["hbm_histogram_cache_bytes"] > 0


# ---------------------------------------------------------------------------
# JSONL event stream
# ---------------------------------------------------------------------------

def test_events_jsonl_roundtrip(tmp_path):
    """3-iteration CPU train -> one record per iteration with phase
    timings, eval values, tree shape, cumulative collective bytes."""
    X, y = _data()
    path = str(tmp_path / "events.jsonl")
    ds = lgb.Dataset(X, label=y)
    vs = ds.create_valid(X[:100], y[:100])
    timetag.enable(True)
    timetag.reset()
    try:
        booster = lgb.train(
            {"objective": "binary", "num_leaves": 7, "verbose": -1,
             "metric": "auc"},
            ds, num_boost_round=3, valid_sets=[vs], events_file=path)
    finally:
        timetag.enable(False)
        timetag.reset()
    events = obs.read_events(path)
    assert [e["iter"] for e in events] == [0, 1, 2]
    for e in events:
        assert e["schema"] == obs.SCHEMA_VERSION
        assert e["wall_s"] > 0
        # TIMETAG was on: the per-phase breakdown folds in
        assert "GBDT::tree" in e["phases"]
        assert e["bag_cnt"] == 400          # bagging off -> full data
        assert e["comm_bytes_cum"] == 0     # serial learner, no collectives
        assert e["comm_calls_cum"] == 0
        assert len(e["trees"]) == 1         # binary: one tree per iter
        assert e["trees"][0]["num_leaves"] >= 2
        assert e["trees"][0]["max_depth"] >= 1
        assert 0.0 <= e["eval"]["valid_0"]["auc"] <= 1.0
    assert booster.num_trees() == 3


def test_events_bag_cnt_tracks_bagging(tmp_path):
    X, y = _data(500, 4, seed=3)
    path = str(tmp_path / "events.jsonl")
    ds = lgb.Dataset(X, label=y)
    lgb.train({"objective": "binary", "num_leaves": 4, "verbose": -1,
               "bagging_fraction": 0.5, "bagging_freq": 1},
              ds, num_boost_round=2, events_file=path)
    events = obs.read_events(path)
    assert [e["bag_cnt"] for e in events] == [250, 250]


def test_event_recorder_commit_on_advance(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    rec = obs.EventRecorder(path)
    rec.note(0, wall_s=0.1)
    rec.note(0, eval={"valid_0": {"auc": 0.9}})
    assert rec.events_written == 0          # nothing later noted yet
    rec.note(1, wall_s=0.2)
    assert rec.events_written == 1          # iter 0 committed on advance
    rec.close()                             # drains the rest
    events = obs.read_events(path)
    assert events[0]["eval"] == {"valid_0": {"auc": 0.9}}
    assert events[0]["wall_s"] == 0.1
    assert events[1]["iter"] == 1 and events[1]["wall_s"] == 0.2


# ---------------------------------------------------------------------------
# collective-traffic accounting (static shape math)
# ---------------------------------------------------------------------------

def test_comm_traffic_hand_computed():
    from lightgbm_tpu.parallel.comm import (DataParallelComm,
                                            FeatureParallelComm,
                                            VotingParallelComm,
                                            traffic_totals)
    F, B, L, k = 6, 16, 8, 8
    steps = L - 1
    # data-parallel / reduce_scatter: one histogram pass over the
    # interconnect per split.  Features pad to a multiple of 8 shards;
    # each bin entry is <sum_g, sum_h, count> f32 = 12 bytes.
    F_pad = 8
    hist_b = F_pad * B * 3 * 4
    t = DataParallelComm("d", k, "reduce_scatter").traffic_per_tree(F, B, L)
    assert t["psum_scatter"]["calls"] == 1 + steps
    assert t["psum_scatter"]["bytes"] == hist_b * (1 + 2 * steps)
    assert t["psum"] == {"calls": 3, "bytes": 12}  # root <g,h,c> scalars
    # SplitInfo tournament: 6 scalar fields, root 1 leaf + 2 per step
    assert t["all_gather"]["calls"] == 6 * (1 + steps)
    assert t["all_gather"]["bytes"] == 6 * 4 * (1 + 2 * steps)

    # psum mode allreduces the FULL (unpadded) histogram every split
    t2 = DataParallelComm("d", k, "psum").traffic_per_tree(F, B, L)
    assert t2["psum"]["bytes"] == 12 + F * B * 12 * (1 + 2 * steps)
    assert "psum_scatter" not in t2 and "all_gather" not in t2

    # feature-parallel ships ONLY SplitInfos — zero histogram bytes
    t3 = FeatureParallelComm("f", k, 1).traffic_per_tree(F_pad, B, L)
    assert set(t3) == {"all_gather"}
    assert t3["all_gather"]["bytes"] == 6 * 4 * (1 + 2 * steps)

    # voting: O(top_k) election lists + elected-features-only psum
    K = min(20, F)
    t4 = VotingParallelComm("d", k, 20).traffic_per_tree(F, B, L)
    assert t4["psum"]["bytes"] == 12 + K * B * 12 * (1 + 2 * steps)
    assert t4["all_gather"]["calls"] == 2 * (1 + steps)
    assert t4["all_gather"]["bytes"] == 2 * K * 4 * (1 + 2 * steps)

    calls, total = traffic_totals(t)
    assert calls == sum(v["calls"] for v in t.values())
    assert total == sum(v["bytes"] for v in t.values())
    assert traffic_totals({}) == (0, 0)


def test_comm_traffic_through_parallel_grow():
    import jax
    from jax.sharding import Mesh
    from lightgbm_tpu.ops.grow import GrowParams
    from lightgbm_tpu.parallel import make_parallel_grow
    devs = jax.devices()
    assert len(devs) >= 8, "conftest must force 8 CPU devices"
    mesh = Mesh(np.array(devs[:8]), ("data",))
    params = GrowParams(num_leaves=8, max_bin=16, min_data_in_leaf=1,
                        min_sum_hessian_in_leaf=0.0)
    fn = make_parallel_grow(mesh, "data", params)
    t = fn.traffic_per_tree(6)
    assert t["psum_scatter"]["bytes"] == 8 * 16 * 3 * 4 * (1 + 2 * 7)


def test_gbdt_accumulates_comm_bytes(tmp_path):
    """End-to-end: a 2-round data-parallel train on the 8-virtual-device
    mesh reports exactly 2x the static per-tree account, in both the
    Booster accessor and the event stream."""
    X, y = _data(600, 6, seed=2)
    path = str(tmp_path / "events.jsonl")
    ds = lgb.Dataset(X, label=y)
    booster = lgb.train(
        {"objective": "binary", "num_leaves": 4, "verbose": -1,
         "tree_learner": "data", "num_machines": 8, "max_bin": 16,
         "min_data_in_leaf": 5},
        ds, num_boost_round=2, events_file=path)
    tele = booster.telemetry()
    per_tree = sum(v["bytes"] for v in tele["comm"]["per_tree"].values())
    assert per_tree > 0
    assert tele["comm"]["bytes_cum"] == 2 * per_tree
    events = obs.read_events(path)
    assert events[-1]["comm_bytes_cum"] == tele["comm"]["bytes_cum"]
    assert events[0]["comm_bytes_cum"] == per_tree


# ---------------------------------------------------------------------------
# device trace capture
# ---------------------------------------------------------------------------

def test_trace_capture_window(tmp_path):
    trace_dir = str(tmp_path / "trace")
    X, y = _data(200, 3, seed=5)
    ds = lgb.Dataset(X, label=y)
    lgb.train({"objective": "binary", "num_leaves": 4, "verbose": -1,
               "trace_dir": trace_dir, "trace_start_iter": 0,
               "trace_num_iters": 1}, ds, num_boost_round=2)
    files = [os.path.join(r, f)
             for r, _, fs in os.walk(trace_dir) for f in fs]
    assert files, "trace window produced no profiler output"


def test_trace_window_counts_from_actual_start(tmp_path, monkeypatch):
    """Continued training resumes past start_iter; the window must span
    num_iters from where the trace actually started, not be truncated by
    the configured start_iter arithmetic."""
    import jax
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append("start"))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append("stop"))
    tc = obs.TraceCapture(str(tmp_path), start_iter=5, num_iters=2)
    tc.iter_begin(20)                   # resume point far past start_iter
    assert calls == ["start"]
    tc.iter_end(20)                     # only 1 iteration inside: stay open
    assert calls == ["start"]
    tc.iter_end(21)                     # 2 iterations inside: close
    assert calls == ["start", "stop"]
    tc.close()                          # idempotent
    assert calls == ["start", "stop"]


def test_trace_env_var_wins(tmp_path, monkeypatch):
    env_dir = str(tmp_path / "envtrace")
    monkeypatch.setenv("LIGHTGBM_TPU_TRACE_DIR", env_dir)
    tc = obs.TraceCapture.from_config(
        lgb.Config({"trace_dir": "/ignored", "trace_start_iter": 1,
                    "trace_num_iters": 3}))
    assert tc.trace_dir == env_dir
    assert tc.start_iter == 1 and tc.num_iters == 3
    monkeypatch.delenv("LIGHTGBM_TPU_TRACE_DIR")
    assert obs.TraceCapture.from_config(lgb.Config({})) is None


# ---------------------------------------------------------------------------
# log satellites: warn_once + stdlib bridge
# ---------------------------------------------------------------------------

def test_warn_once_dedupes(capsys):
    lgb_log.reset_warn_once()
    lgb_log.warn_once("k1", "warn-once payload %d", 1)
    lgb_log.warn_once("k1", "warn-once payload %d", 2)
    lgb_log.warn_once("k2", "other key")
    err = capsys.readouterr().err
    assert err.count("warn-once payload") == 1
    assert "other key" in err
    lgb_log.reset_warn_once()


def test_stdlib_bridge_mirrors_records():
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    logger = lgb_log.enable_stdlib_bridge("lightgbm_tpu_test_bridge")
    handler = _Capture()
    logger.addHandler(handler)
    logger.setLevel(logging.DEBUG)
    try:
        lgb_log.set_verbosity(-1)   # console fully suppressed...
        lgb_log.info("bridged %s", "yes")
        lgb_log.warning("bridged warning")
        with pytest.raises(lgb.LightGBMError):
            lgb_log.fatal("bridged fatal")
    finally:
        lgb_log.set_verbosity(1)
        lgb_log.disable_stdlib_bridge()
        logger.removeHandler(handler)
    msgs = [r.getMessage() for r in records]
    assert "bridged yes" in msgs           # ...but the bridge still sees all
    assert "bridged warning" in msgs
    assert "bridged fatal" in msgs
    levels = {r.getMessage(): r.levelno for r in records}
    assert levels["bridged warning"] == logging.WARNING
    assert levels["bridged fatal"] == logging.CRITICAL
