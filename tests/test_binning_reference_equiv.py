"""Property test: the O(max_bin) skip-ahead greedy binning must produce the
same boundaries as a straight per-distinct-value transcription of the
reference scan (bin.cpp:132-191)."""

import numpy as np
import pytest

from lightgbm_tpu.io.binning import BinMapper


def _reference_greedy(distinct_values, counts, total_sample_cnt, max_bin,
                      min_data_in_bin, zero_cnt, num_sample_values):
    # Direct re-statement of bin.cpp:132-191 for testing only.
    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_sample_cnt // min_data_in_bin))
    mean_bin_size = total_sample_cnt / max_bin
    if zero_cnt > mean_bin_size:
        max_bin = min(max_bin, 1 + num_sample_values // max(1, min_data_in_bin))
    num_distinct = len(distinct_values)
    rest_bin_cnt = max_bin
    rest_sample_cnt = total_sample_cnt
    is_big = [c >= mean_bin_size for c in counts]
    for i in range(num_distinct):
        if is_big[i]:
            rest_bin_cnt -= 1
            rest_sample_cnt -= counts[i]
    mean_bin_size = rest_sample_cnt / max(1, rest_bin_cnt)
    upper_bounds = [np.inf] * max_bin
    lower_bounds = [np.inf] * max_bin
    bin_cnt = 0
    lower_bounds[0] = distinct_values[0]
    cur_cnt = 0
    for i in range(num_distinct - 1):
        if not is_big[i]:
            rest_sample_cnt -= counts[i]
        cur_cnt += counts[i]
        if (is_big[i] or cur_cnt >= mean_bin_size or
                (is_big[i + 1] and cur_cnt >= max(1.0, mean_bin_size * 0.5))):
            upper_bounds[bin_cnt] = distinct_values[i]
            bin_cnt += 1
            lower_bounds[bin_cnt] = distinct_values[i + 1]
            if bin_cnt >= max_bin - 1:
                break
            cur_cnt = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                mean_bin_size = rest_sample_cnt / max(1, rest_bin_cnt)
    bin_cnt += 1
    bounds = [np.inf] * bin_cnt
    for i in range(bin_cnt - 1):
        bounds[i] = (upper_bounds[i] + lower_bounds[i + 1]) / 2.0
    return np.asarray(bounds)


@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize("max_bin", [4, 16, 63, 255])
def test_greedy_matches_reference_scan(seed, max_bin):
    rng = np.random.RandomState(seed)
    # mixture: continuous + repeated spikes + negatives, plus implied zeros
    n = rng.randint(500, 4000)
    vals = np.concatenate([
        rng.normal(size=n),
        np.repeat(rng.choice([-1.5, 0.25, 3.0], 3, replace=False),
                  rng.randint(50, 400, size=3)),
    ])
    vals = vals[vals != 0.0]
    zero_cnt = rng.randint(0, 500)
    total = len(vals) + zero_cnt

    m = BinMapper().find_bin(vals, total, max_bin, min_data_in_bin=3,
                             min_split_data=1)
    if m.num_bin >= len(np.unique(vals)) + 1:
        pytest.skip("hit distinct fast path")

    uniq, ucnt = np.unique(vals, return_counts=True)
    if zero_cnt > 0 and 0.0 not in uniq:
        pos = int(np.searchsorted(uniq, 0.0))
        uniq = np.insert(uniq, pos, 0.0)
        ucnt = np.insert(ucnt, pos, zero_cnt)
    expected = _reference_greedy(uniq.tolist(), ucnt.tolist(), total, max_bin,
                                 3, zero_cnt, len(vals))
    np.testing.assert_allclose(m.bin_upper_bound, expected)
