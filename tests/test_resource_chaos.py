"""Resource-exhaustion chaos suite (marker ``resource_chaos``):
the one classic failure class PRs 2/9/11/13 skipped — running out of a
resource (docs/FAULT_TOLERANCE.md §Resource exhaustion).

What is pinned here:

1. **ENOSPC mid-run is contained**: a full training run with the disk
   failing under every telemetry/state sink at round k finishes all
   rounds BIT-IDENTICAL to an uninjected run, the last-good snapshot
   stays readable, every disabled sink is named in a warning, no
   orphaned ``.tmp`` survives, and ``sink_write_errors_total`` matches
   the injection count exactly.
2. **Device OOM is a diagnosis, not a backtrace**: an injected
   ``RESOURCE_EXHAUSTED`` at the jit dispatch boundary surfaces as a
   named ``DeviceOOM`` (a ``LightGBMError``) carrying the program name,
   the abstract call shapes, a memwatch snapshot and the admission
   gate's per-component memory table.
3. **The admission gate + degrade ladder** refuse/degrade as
   documented, and — with the guarded-writer layer — record ZERO new
   XLA programs (resource handling is host-side by construction).
4. **Estimate accuracy**: ``estimate_train_memory`` agrees with the
   memwatch-measured live-array peak within a bounded factor, so the
   gate cannot silently rot as new device buffers are added.
"""

import errno
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.models.gbdt import GBDT, estimate_train_memory
from lightgbm_tpu.obs import compile_ledger
from lightgbm_tpu.testing import faults
from lightgbm_tpu.utils import diskguard, log
from lightgbm_tpu.utils.log import LightGBMError
from lightgbm_tpu.utils.resource import (DEGRADE_STEPS, DeviceOOM,
                                         MemoryBudgetExceeded)

pytestmark = pytest.mark.resource_chaos


def _data(n=400, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float32)
    return X, y


def _params(tmp_path, **over):
    p = {"objective": "binary", "num_leaves": 7, "max_bin": 32,
         "min_data_in_leaf": 5, "verbose": -1}
    p.update(over)
    return p


@pytest.fixture(autouse=True)
def _fresh_sinks():
    """Each test starts with every sink armed and one-shot warnings
    re-armed (the chaos assertions read both)."""
    diskguard.reset_disabled()
    log.reset_warn_once()
    yield
    diskguard.reset_disabled()


# ---------------------------------------------------------------------------
# 1. ENOSPC injected mid-run: contained, bit-identical, last-good intact
# ---------------------------------------------------------------------------

def _train_full(tmp_path, X, y, subdir, inject_at=None):
    """One instrumented training run (events + compile ledger +
    snapshots), optionally with every guarded write under ``subdir``
    failing ENOSPC from iteration ``inject_at`` on.  Returns
    (model_text, injector stats or None)."""
    d = tmp_path / subdir
    d.mkdir()
    params = _params(tmp_path,
                     events_file=str(d / "events.jsonl"),
                     compile_ledger_file=str(d / "ledger.jsonl"),
                     snapshot_dir=str(d / "snaps"), snapshot_freq=2)
    train = lgb.Dataset(X, y)
    if inject_at is None:
        booster = lgb.train(params, train, num_boost_round=8)
        return booster.model_to_string(), None
    with faults.fail_writes(errno.ENOSPC, str(d / "*"),
                            armed=False) as stats:
        def arm(env):
            if env.iteration >= inject_at:
                stats["armed"] = True
        arm.before_iteration = True
        arm.order = -99
        booster = lgb.train(params, train, num_boost_round=8,
                            callbacks=[arm])
    return booster.model_to_string(), stats


def test_enospc_mid_run_is_contained_and_bit_identical(tmp_path, capsys):
    X, y = _data()
    clean_model, _ = _train_full(tmp_path, X, y, "clean")
    c0 = obs.get_counter("sink_write_errors_total")
    programs0 = {e["program"] for e in compile_ledger.events()}
    injected_model, stats = _train_full(tmp_path, X, y, "injected",
                                        inject_at=5)
    # -- the chaos acceptance, clause by clause -----------------------
    # all rounds finished, bit-identical to the uninjected run
    assert injected_model == clean_model
    # the injection actually struck (events sink + >=1 snapshot write)
    assert stats["fired"] >= 2
    # sink_write_errors_total matches the injection count exactly
    assert obs.get_counter("sink_write_errors_total") - c0 \
        == stats["fired"]
    # every disabled sink is named in a warning
    err = capsys.readouterr().err
    assert "sink 'events'" in err
    assert "sink 'snapshot'" in err
    assert "disk_full" in err
    # the last-good snapshot (written before the injection) is readable
    from lightgbm_tpu.snapshot import load_latest_snapshot
    found = load_latest_snapshot(str(tmp_path / "injected" / "snaps"))
    assert found is not None
    assert found[1]["rounds_done"] == 4
    # no orphaned .tmp survives the failed writes
    snaps = os.listdir(tmp_path / "injected" / "snaps")
    assert not [f for f in snaps if f.endswith(".tmp")]
    # the events records committed BEFORE the strike are on disk intact
    recs = [json.loads(ln) for ln in
            open(tmp_path / "injected" / "events.jsonl") if ln.strip()]
    assert len(recs) >= 3
    assert [r["iter"] for r in recs] == list(range(len(recs)))
    # compile-ledger pin: the injected run introduced no new XLA
    # programs over the clean run (resource handling is host-side)
    assert {e["program"] for e in compile_ledger.events()} == programs0


def test_disk_full_after_budget_strikes_the_events_sink(tmp_path):
    X, y = _data(n=300)
    ev = tmp_path / "events.jsonl"
    params = _params(tmp_path, events_file=str(ev))
    c0 = obs.get_counter("sink_write_errors_events")
    with faults.disk_full_after(600, str(ev)) as stats:
        booster = lgb.train(params, lgb.Dataset(X, y), num_boost_round=6)
    assert booster.num_trees() == 6          # the run survived
    assert stats["fired"] >= 1
    assert obs.get_counter("sink_write_errors_events") - c0 >= 1
    # the bytes that fit are valid JSONL (no torn half-line commits at
    # the guarded layer: a failed write drops the whole record)
    got = [json.loads(ln) for ln in open(ev) if ln.strip()]
    assert all("iter" in r for r in got)


def test_crash_without_close_keeps_committed_events(tmp_path):
    """Satellite pin (torn_snapshot_write-style kill): the recorder is
    line-buffered + flushed per committed record, so a run that dies
    without ever calling close() keeps every record committed before
    the crash — the tail you need to debug the crash."""
    from lightgbm_tpu.obs import EventRecorder
    path = tmp_path / "ev.jsonl"
    rec = EventRecorder(str(path))
    for it in range(6):
        rec.note(it, wall_s=0.1 * it)
    # records 0..4 committed (5 still pending); simulate a hard crash:
    # no close(), no flush — read the file as another process would
    got = [json.loads(ln) for ln in open(path) if ln.strip()]
    assert [r["iter"] for r in got] == [0, 1, 2, 3, 4]
    rec.close()


def test_events_flush_every_batches_flushes(tmp_path):
    from lightgbm_tpu.obs import EventRecorder
    path = tmp_path / "ev.jsonl"
    rec = EventRecorder(str(path), flush_every=3)
    for it in range(8):
        rec.note(it, wall_s=1.0)
    rec.close()
    got = [json.loads(ln) for ln in open(path) if ln.strip()]
    assert len(got) == 8                     # close() drains everything


def test_quarantine_sink_enospc_keeps_accounting(tmp_path):
    """The quarantine SINK dying must not break the error-budget
    accounting (the in-memory verdicts are the contract; the file is
    telemetry)."""
    from lightgbm_tpu.io.guard import IngestGuard
    g = IngestGuard(str(tmp_path / "data.tsv"), policy="quarantine",
                    max_bad_rows=10)
    with faults.fail_writes(errno.ENOSPC, str(tmp_path / "*")) as stats:
        assert g.bad_row(3, "x\ty", "ragged_row", "5 != 6") is True
        assert g.bad_row(7, "a\tb", "unparseable_token", "'zz'") is True
    assert stats["fired"] >= 1
    assert g.bad_total == 2
    assert g.by_reason == {"ragged_row": 1, "unparseable_token": 1}
    g.finish()


def test_serve_state_write_failure_keeps_last_good(tmp_path):
    from lightgbm_tpu.serve.fleet import ModelManager
    state = tmp_path / "serve_state.json"
    mgr = ModelManager.__new__(ModelManager)
    mgr.state_file = str(state)
    mgr.note_good("/models/a.txt", target="primary", generation=3)
    assert ModelManager.restore_path(str(state)) is None  # file missing
    # write a real model path so restore_path can see it exists
    model = tmp_path / "m.txt"
    model.write_text("x")
    mgr.note_good(str(model), target="primary", generation=4)
    assert ModelManager.restore_path(str(state)) == str(model)
    c0 = obs.get_counter("sink_write_errors_serve_state")
    with faults.fail_writes(errno.EDQUOT, str(tmp_path / "*")):
        mgr.note_good("/models/never.txt", target="primary", generation=5)
    assert obs.get_counter("sink_write_errors_serve_state") - c0 == 1
    # the last-good file survived the failed write, no .tmp orphan
    assert ModelManager.restore_path(str(state)) == str(model)
    assert not (tmp_path / "serve_state.json.tmp").exists()


def test_compile_ledger_sink_disables_not_crashes(tmp_path):
    path = tmp_path / "ledger.jsonl"
    compile_ledger.configure(str(path))
    try:
        c0 = obs.get_counter("sink_write_errors_compile_ledger")
        with faults.fail_writes(errno.EROFS, str(tmp_path / "*")):
            compile_ledger.record("prog_a", "f32[8]", 0.1)
            compile_ledger.record("prog_b", "f32[8]", 0.1)
        # first failure disabled the sink; the second never attempted
        assert obs.get_counter(
            "sink_write_errors_compile_ledger") - c0 == 1
        # the in-memory account kept both events
        assert {"prog_a", "prog_b"} <= {e["program"]
                                        for e in compile_ledger.events()}
        assert not path.exists()
    finally:
        compile_ledger.configure(None)


def test_tracing_export_failure_disables_tracer(tmp_path):
    from lightgbm_tpu.obs.tracing import Tracer
    t = Tracer()
    t.path = str(tmp_path / "trace.json")
    t.enabled = True
    with t.span("GBDT::iteration"):
        pass
    with faults.fail_writes(errno.ENOSPC, str(tmp_path / "*")):
        assert t.maybe_export() is None
    assert t.enabled is False                # re-collecting is pointless
    assert not (tmp_path / "trace.json").exists()


def test_predict_output_enospc_is_a_named_fatal(tmp_path):
    """CLI task=predict: the output stream is an artifact — a full disk
    FAILS the task with a named diagnosis reporting rows written."""
    from lightgbm_tpu.cli import main as cli_main
    X, y = _data(n=300)
    booster = lgb.train(_params(tmp_path), lgb.Dataset(X, y),
                        num_boost_round=3)
    model = tmp_path / "model.txt"
    booster.save_model(str(model))
    data = tmp_path / "pred.tsv"
    with open(data, "w") as fh:
        for row in X:
            fh.write("0\t" + "\t".join(f"{v:g}" for v in row) + "\n")
    (tmp_path / "out").mkdir()
    out = tmp_path / "out" / "result.txt"
    with faults.fail_writes(errno.ENOSPC, str(tmp_path / "out" / "*")):
        with pytest.raises(LightGBMError) as ei:
            cli_main([f"task=predict", f"input_model={model}",
                      f"data={data}", f"output_result={out}"])
    msg = str(ei.value)
    assert "row(s) were written" in msg
    assert "disk_full" in msg


def test_sink_error_policy_fatal_flips_unpinned_sinks(tmp_path):
    """Post-review pin: ``sink_error_policy=fatal`` is not a no-op —
    the policy-unpinned sinks (events here) raise the classified
    ``SinkWriteError`` instead of disabling themselves, for runs where
    lost telemetry is unacceptable."""
    from lightgbm_tpu.obs import EventRecorder
    old = diskguard.default_policy()
    try:
        diskguard.set_default_policy("fatal")
        rec = EventRecorder(str(tmp_path / "ev.jsonl"))
        with faults.fail_writes(errno.ENOSPC, str(tmp_path / "*")):
            with pytest.raises(diskguard.SinkWriteError) as ei:
                rec.note(0, wall_s=1.0)
                rec.note(1, wall_s=1.0)   # commits record 0 -> raises
        assert ei.value.sink == "events"
        assert ei.value.classification == "disk_full"
    finally:
        diskguard.set_default_policy(old)


def test_model_file_save_failure_keeps_last_good(tmp_path):
    """Post-review pin: ``save_model`` used to truncate the destination
    in place, so an ENOSPC halfway through the save destroyed the
    previous good model.  The atomic artifact write keeps last-good and
    the failure is a named, classified ``SinkWriteError``."""
    X, y = _data(n=300)
    booster = lgb.train(_params(tmp_path), lgb.Dataset(X, y),
                        num_boost_round=3)
    model = tmp_path / "model.txt"
    booster.save_model(str(model))
    good = model.read_bytes()
    c0 = obs.get_counter("sink_write_errors_model_file")
    with faults.fail_writes(errno.ENOSPC, str(tmp_path / "*")):
        with pytest.raises(diskguard.SinkWriteError) as ei:
            booster.save_model(str(model))
    assert ei.value.sink == "model_file"
    assert ei.value.classification == "disk_full"
    # artifact failures are COUNTED like every other guarded failure
    assert obs.get_counter("sink_write_errors_model_file") - c0 == 1
    assert model.read_bytes() == good            # last-good survived
    assert not (tmp_path / "model.txt.tmp").exists()


def test_binary_dataset_save_failure_keeps_last_good(tmp_path):
    X, y = _data(n=200)
    ds = BinnedDataset.from_matrix(X, y, max_bin=32, min_data_in_leaf=5)
    path = tmp_path / "train.bin"
    ds.save_binary(str(path))
    good = path.read_bytes()
    with faults.fail_writes(errno.EDQUOT, str(tmp_path / "*")):
        with pytest.raises(diskguard.SinkWriteError) as ei:
            ds.save_binary(str(path))
    assert ei.value.sink == "binary_dataset"
    assert ei.value.classification == "quota_exceeded"
    assert path.read_bytes() == good             # last-good survived
    assert not (tmp_path / "train.bin.tmp").exists()


def test_reset_training_data_reruns_admission_gate(tmp_path, monkeypatch):
    """Post-review pin: ``ResetTrainingData`` re-runs the HBM admission
    gate — a swapped dataset cannot sneak past the pre-flight check the
    constructor ran (it would die hours later in an opaque XLA
    RESOURCE_EXHAUSTED), and a degrade ladder applied at construction
    is re-walked instead of silently undone by the recomputed pad."""
    X, y = _data()
    ds = BinnedDataset.from_matrix(X, y, max_bin=32, min_data_in_leaf=5)
    gb = GBDT(Config(_params(tmp_path, num_leaves=31)), ds)
    gb.train(2)
    monkeypatch.setenv("LGBT_DEVICE_MEMORY_BYTES", "1000")
    with pytest.raises(MemoryBudgetExceeded):
        gb.reset_training_data(ds)
    monkeypatch.delenv("LGBT_DEVICE_MEMORY_BYTES")
    # under memory_policy=degrade the reset walks the ladder again
    # (already-applied steps are skipped, not re-counted) and trains
    log.reset_warn_once()
    floor = estimate_train_memory(ds.num_data, ds.num_columns, 31, 32, 1,
                                  bin_itemsize=ds.bins.dtype.itemsize,
                                  leaf_cache=False)
    monkeypatch.setenv("LGBT_DEVICE_MEMORY_BYTES",
                       str(int(floor["total"] * 1.05)))
    gb2 = GBDT(Config(_params(tmp_path, num_leaves=31,
                              memory_policy="degrade")), ds)
    assert "hist_cache" in gb2._degrade_steps
    gb2.reset_training_data(ds)
    assert gb2._degrade_leaf_cache_off   # the degrade survived the reset
    gb2.train(2)
    assert len(gb2.models) == 2


def test_snapshot_tmp_sweep(tmp_path):
    """Satellite: stale .tmp files (a hard crash before os.replace) are
    swept by prune_snapshots instead of accumulating per retry."""
    from lightgbm_tpu import snapshot as snapmod
    d = tmp_path / "snaps"
    d.mkdir()
    snapmod.write_snapshot(str(d / "snapshot_0000000002.bin"),
                           {"booster": {}, "rounds_done": 2})
    (d / "snapshot_0000000004.bin.tmp").write_bytes(b"torn")
    (d / "snapshot_0000000006.bin.tmp").write_bytes(b"torn too")
    snapmod.prune_snapshots(str(d), keep=0)   # keep=0: sweep only
    left = sorted(os.listdir(d))
    assert left == ["snapshot_0000000002.bin"]


# ---------------------------------------------------------------------------
# 2. device OOM: a named diagnosis at the jit dispatch boundary
# ---------------------------------------------------------------------------

def test_injected_oom_is_a_named_diagnosis(tmp_path):
    X, y = _data()
    ds = BinnedDataset.from_matrix(X, y, max_bin=32, min_data_in_leaf=5)
    cfg = Config(_params(tmp_path))
    gb = GBDT(cfg, ds)
    gb.train_one_iter()                      # warm: programs compiled
    c0 = obs.get_counter("device_oom_total")
    with faults.oom_on_program("train_step") as stats:
        with pytest.raises(DeviceOOM) as ei:
            gb.train_one_iter()
    assert stats["fired"] == 1
    err = ei.value
    assert isinstance(err, LightGBMError)    # one catchable family
    # the diagnosis names the program and its abstract shapes
    assert err.program == "train_step"
    assert "train_step" in str(err)
    assert "f32[" in err.shapes or "u8[" in err.shapes
    # ...the admission gate's per-component memory table...
    assert "admission estimate" in str(err)
    assert "histogram_cache" in str(err)
    assert "bins_device" in str(err)
    # ...and a memwatch snapshot of what the host/device held
    assert "memwatch" in str(err)
    assert obs.get_counter("device_oom_total") - c0 == 1
    # containment, not corruption: the booster state survived (the
    # poisoned dispatch never committed) and training can continue
    n0 = len(gb.models)
    gb.train_one_iter()
    assert len(gb.models) >= n0


def test_oom_classifier_ignores_ordinary_errors():
    from lightgbm_tpu.utils.resource import is_resource_exhausted
    assert is_resource_exhausted(
        faults.make_resource_exhausted("p"))
    assert is_resource_exhausted(MemoryError())
    assert not is_resource_exhausted(ValueError("shape mismatch"))
    assert not is_resource_exhausted(OSError(28, "No space left"))


def test_non_oom_dispatch_errors_pass_through():
    """The containment wrapper must re-raise everything else untouched
    — masking a real bug as an OOM would be worse than the backtrace."""
    from lightgbm_tpu.obs.compile_ledger import InstrumentedJit

    def boom():
        raise ValueError("a real bug")

    j = InstrumentedJit.__new__(InstrumentedJit)
    j._fn = boom
    j.program = "boom"
    j._seen_keys = set()
    with pytest.raises(ValueError, match="a real bug"):
        j._call_guarded()


# ---------------------------------------------------------------------------
# 3. admission gate + degrade ladder
# ---------------------------------------------------------------------------

def test_degrade_ladder_applies_in_order_and_counts(tmp_path, monkeypatch):
    X, y = _data()
    ds = BinnedDataset.from_matrix(X, y, max_bin=32, min_data_in_leaf=5)
    # a budget the full config misses but the degraded one fits: compute
    # the no-cache, no-pad footprint and allow a little headroom
    floor = estimate_train_memory(ds.num_data, ds.num_columns, 31, 32, 1,
                                  bin_itemsize=ds.bins.dtype.itemsize,
                                  leaf_cache=False)
    monkeypatch.setenv("LGBT_DEVICE_MEMORY_BYTES",
                       str(int(floor["total"] * 1.05)))
    log.reset_warn_once()
    c0 = obs.get_counter("resource_degrade_total")
    cfg = Config(_params(tmp_path, num_leaves=31,
                         memory_policy="degrade"))
    gb = GBDT(cfg, ds)
    # the ladder fired (hist_cache at least; score_donation is
    # unavailable on CPU — aliasing is unsafe there — and row_pad only
    # if still needed), in documented order
    assert "hist_cache" in gb._degrade_steps
    assert list(gb._degrade_steps) == sorted(
        gb._degrade_steps, key=DEGRADE_STEPS.index)
    took = obs.get_counter("resource_degrade_total") - c0
    assert took == len(gb._degrade_steps) >= 1
    assert obs.get_counter("resource_degrade_hist_cache") >= 1
    # the degraded booster actually trains, and the cacheless learner
    # picks the SAME splits as the cached one (the cache is a reuse
    # strategy, not a model change; leaf aggregates re-associate in
    # f32, so values agree to float tolerance rather than bit-exactly)
    gb.train(3)
    assert len(gb.models) == 3
    monkeypatch.delenv("LGBT_DEVICE_MEMORY_BYTES")
    cfg2 = Config(_params(tmp_path, num_leaves=31))
    gb2 = GBDT(cfg2, ds)
    gb2.train(3)
    assert len(gb2.models) == 3
    for ta, tb in zip(gb.models, gb2.models):
        assert ta.num_leaves == tb.num_leaves
        np.testing.assert_array_equal(ta.split_feature, tb.split_feature)
        np.testing.assert_allclose(ta.threshold, tb.threshold, rtol=0,
                                   atol=0)
    np.testing.assert_allclose(gb.predict_raw(X), gb2.predict_raw(X),
                               rtol=1e-4, atol=1e-5)


def test_degrade_exhausted_refuses_with_table(tmp_path, monkeypatch):
    X, y = _data()
    ds = BinnedDataset.from_matrix(X, y, max_bin=32, min_data_in_leaf=5)
    monkeypatch.setenv("LGBT_DEVICE_MEMORY_BYTES", "1024")  # 1 KB: hopeless
    cfg = Config(_params(tmp_path, memory_policy="degrade"))
    with pytest.raises(MemoryBudgetExceeded) as ei:
        GBDT(cfg, ds)
    err = ei.value
    assert "exceeds the device budget" in str(err)
    assert "Degrade ladder already applied" in str(err)
    assert err.limit == 1024
    assert set(err.estimate) >= {"bins_device", "histogram_cache",
                                 "total"}
    assert err.steps_taken                   # at least one step tried


def test_histogram_pool_size_is_a_real_bound_under_degrade(tmp_path,
                                                           monkeypatch):
    X, y = _data()
    ds = BinnedDataset.from_matrix(X, y, max_bin=32, min_data_in_leaf=5)
    monkeypatch.delenv("LGBT_DEVICE_MEMORY_BYTES", raising=False)
    log.reset_warn_once()
    cfg = Config(_params(tmp_path, num_leaves=255,
                         histogram_pool_size=0.001,
                         memory_policy="degrade"))
    gb = GBDT(cfg, ds)
    assert "hist_cache" in gb._degrade_steps
    gb.train(2)
    assert len(gb.models) == 2


def test_score_donation_step_fires_where_aliasing_is_safe(tmp_path,
                                                          monkeypatch):
    """On an accelerator backend (simulated) with donation env'd off,
    the first ladder step re-enables it and drops the double buffer."""
    import lightgbm_tpu.models.gbdt as gbdt_mod
    X, y = _data()
    ds = BinnedDataset.from_matrix(X, y, max_bin=32, min_data_in_leaf=5)
    monkeypatch.setenv("LIGHTGBM_TPU_DONATION", "0")
    monkeypatch.setattr(gbdt_mod, "_donation_safe", lambda: True)
    full = estimate_train_memory(ds.num_data, ds.num_columns, 7, 32, 1,
                                 bin_itemsize=ds.bins.dtype.itemsize)
    # budget that fits once ONLY the double buffer goes away
    monkeypatch.setenv(
        "LGBT_DEVICE_MEMORY_BYTES",
        str(int(full["total"] - full["score_double_buffer"] // 2)))
    log.reset_warn_once()
    cfg = Config(_params(tmp_path, memory_policy="degrade"))
    gb = GBDT(cfg, ds)
    assert gb._degrade_steps == ("score_donation",)
    assert gb._donation_on() is True


def test_admission_and_diskguard_record_zero_xla_programs(tmp_path):
    """Compile-ledger pin: estimates, the gate, the degrade accounting
    and the guarded-writer layer are host-side — zero compile events."""
    n0 = len(compile_ledger.events())
    estimate_train_memory(100_000, 64, 255, 255, 2)
    estimate_train_memory(100_000, 64, 255, 255, 2, donate_score=True,
                          fused_scratch=True, leaf_cache=False)
    from lightgbm_tpu.utils import resource
    resource.set_budget_table({"total": 1, "bins_device": 1}, "pin")
    resource.format_table({"total": 1, "bins_device": 1})
    w = diskguard.GuardedWriter(str(tmp_path / "s.jsonl"), sink="pin_sink")
    w.write('{"a": 1}\n')
    w.close()
    diskguard.append_line(str(tmp_path / "l.jsonl"), "{}", sink="pin_l")
    diskguard.write_file_atomic(str(tmp_path / "f.bin"), b"x", sink="pin_f")
    assert len(compile_ledger.events()) == n0


# ---------------------------------------------------------------------------
# 4. estimate accuracy vs memwatch (the gate cannot silently rot)
# ---------------------------------------------------------------------------

def test_estimate_tracks_memwatch_measured_peak(tmp_path):
    """``estimate_train_memory`` vs the memwatch-measured live-array
    peak over a real CPU training run: the estimate must be an UPPER
    bound on what Python holds live (it also budgets XLA working set
    the live-array walk cannot see), yet within a bounded factor — if a
    future PR adds a device buffer the estimate misses, the measured
    peak creeps toward/over the estimate and this pin fails before the
    admission gate rots."""
    import jax
    from lightgbm_tpu.obs import memwatch
    X, y = _data(n=4000, f=16, seed=3)
    base = memwatch.sample().get("live_bytes", 0)
    ds = BinnedDataset.from_matrix(X, y, max_bin=64, min_data_in_leaf=5)
    cfg = Config(_params(tmp_path, num_leaves=15, max_bin=64))
    gb = GBDT(cfg, ds)
    est = gb._train_mem_est
    peak = 0
    for _ in range(4):
        gb.train_one_iter()
        jax.block_until_ready(gb.train_data.score)
        peak = max(peak, memwatch.sample().get("live_bytes", 0) - base)
    assert peak > 0
    # upper bound: everything Python holds live fits the estimate
    assert est >= peak, (
        f"estimate {est}B < measured live peak {peak}B — a device "
        f"buffer is missing from estimate_train_memory")
    # bounded factor: the estimate may not balloon into meaninglessness
    assert est <= 64 * peak, (
        f"estimate {est}B is >64x the measured live peak {peak}B — "
        f"the admission gate would refuse configs that fit easily")


# ---------------------------------------------------------------------------
# bench_regress passthrough (informational `resource` BENCH block)
# ---------------------------------------------------------------------------

def test_bench_regress_passes_resource_block_through(tmp_path, capsys):
    import importlib.util
    import pathlib
    spec = importlib.util.spec_from_file_location(
        "bench_regress", pathlib.Path(__file__).resolve().parent.parent
        / "tools" / "bench_regress.py")
    bench_regress = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_regress)

    base = {"metric": "m", "value": 10.0, "unit": "iters/sec"}
    cand = {"metric": "m", "value": 10.2, "unit": "iters/sec",
            "resource": {"estimated_peak_bytes": 123456,
                         "measured_peak_bytes": 65536,
                         "degrade_steps": ["hist_cache"],
                         "sink_write_errors": 0}}
    b = tmp_path / "b.json"
    c = tmp_path / "c.json"
    b.write_text(json.dumps(base))
    c.write_text(json.dumps(cand))
    rc = bench_regress.main(["--baseline", str(b), "--candidate", str(c),
                             "--threshold", "5"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    verdict = json.loads(out)
    assert rc == 0 and verdict["ok"]
    # informational: rides along on the side that carries it, never
    # gated, never required (old baselines keep comparing)
    assert verdict["resource_candidate"]["degrade_steps"] == ["hist_cache"]
    assert "resource_baseline" not in verdict
