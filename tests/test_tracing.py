"""Causal tracing (lightgbm_tpu/obs/tracing.py) and HBM memwatch
(lightgbm_tpu/obs/memwatch.py):

- Chrome trace export round-trip: emit -> parse -> validate parent/child
  structure and trace-ID continuity through a coalesced MicroBatcher
  batch (the many-to-one edge is explicit);
- a serve HTTP round trip yields a Perfetto-loadable trace whose request
  span tree links queue -> coalesced batch -> device predict (acceptance
  criterion);
- training gets one trace per boosting round for free via obs.span;
- memwatch gauges appear in a /metrics scrape when enabled.

The tracer is process-global: every test arms it against a temp path and
disarms + clears in a fixture so this file composes with the tier-1 run.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import memwatch, tracing


@pytest.fixture
def tracer(tmp_path, monkeypatch):
    """Arm the process tracer via the env var (which wins inside
    ``configure`` — so an engine.train call mid-test keeps it armed;
    configure is otherwise authoritative per run)."""
    path = tmp_path / "trace_events.json"
    tracing.TRACER.reset()
    monkeypatch.setenv(tracing.ENV_PATH, str(path))
    tracing.TRACER.configure()
    yield path
    tracing.TRACER.disable()
    tracing.TRACER.reset()
    tracing.TRACER.path = None


def _train(n=400, rounds=3):
    rng = np.random.RandomState(5)
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] + 0.2 * X[:, 1] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                     "min_data_in_leaf": 20},
                    lgb.Dataset(X, label=y), num_boost_round=rounds)
    return bst, X


# ---------------------------------------------------------------------------
# tracer core


def test_span_tree_roundtrip(tracer):
    with obs.trace_span("GBDT::iteration") as root:
        with obs.trace_span("GBDT::tree") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
    with obs.trace_span("GBDT::iteration") as root2:
        assert root2.trace_id != root.trace_id     # fresh root, fresh trace
    out = tracing.TRACER.export()
    assert out == str(tracer)
    events = tracing.read_trace(out)
    tree = tracing.span_trees(events)
    assert len(tree["roots"]) == 2
    assert len(tree["traces"]) == 2
    r = next(s for s in tree["roots"]
             if tree["children"].get(s))
    (kid,) = tree["children"][r]
    assert tree["spans"][kid]["name"] == "GBDT::tree"
    # chrome-format invariants Perfetto relies on
    for e in events:
        if e.get("ph") == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0 and "pid" in e


def test_configure_is_authoritative(tmp_path, monkeypatch):
    """A run configured WITHOUT the switches disarms them — a second
    engine.train in one process cannot inherit the previous run's
    instrumentation (or keep appending to its files)."""
    from lightgbm_tpu.obs import compile_ledger
    monkeypatch.delenv(tracing.ENV_PATH, raising=False)
    monkeypatch.delenv(compile_ledger.ENV_PATH, raising=False)
    monkeypatch.delenv(memwatch.ENV, raising=False)
    assert tracing.TRACER.configure(str(tmp_path / "t.json")) is True
    assert tracing.TRACER.configure(None) is False
    lpath = str(tmp_path / "l.jsonl")
    assert compile_ledger.configure(lpath) == lpath
    assert compile_ledger.configure(None) is None
    assert memwatch.configure(True) is True
    assert memwatch.configure(None) is False


def test_disabled_tracer_is_inert():
    assert not tracing.TRACER.enabled
    with obs.trace_span("GBDT::iteration") as h:
        assert h is None
    assert obs.trace_begin("Serve::queue") is None
    obs.trace_end(None)
    obs.trace_link(None, None)
    with obs.span("GBDT::iteration") as sp:
        assert sp.trace is None


def test_cross_thread_end_and_link(tracer):
    """begin() in one thread, end()/link() in another — the batcher's
    exact usage."""
    q = obs.trace_begin("Serve::queue")

    def worker():
        with obs.trace_span("Serve::batch") as b:
            obs.trace_link(q, b)
            obs.trace_end(q)
    t = threading.Thread(target=worker)
    t.start()
    t.join()
    tree = tracing.span_trees(tracing.TRACER.events())
    batch = next(s for s, e in tree["spans"].items()
                 if e["name"] == "Serve::batch")
    queue = next(s for s, e in tree["spans"].items()
                 if e["name"] == "Serve::queue")
    assert tree["coalesced_into"][queue] == batch
    assert tree["spans"][batch]["args"]["member_trace_ids"] == \
        [tree["spans"][queue]["args"]["trace_id"]]


# ---------------------------------------------------------------------------
# micro-batcher coalescing


def test_microbatcher_coalesce_edges(tracer):
    """Trace-ID continuity through a coalesced batch: N concurrent
    requests -> one device batch, recorded as N explicit edges."""
    from lightgbm_tpu.serve.batcher import MicroBatcher
    release = threading.Event()

    def predict_fn(rows):
        return np.zeros((1, rows.shape[0]), np.float32)

    mb = MicroBatcher(predict_fn, max_batch=64, max_delay_s=0.15)
    results = []

    def client(i):
        with obs.trace_span("Serve::request", args={"request_id": i}):
            release.wait(5.0)
            results.append(mb.submit(np.ones((2, 3), np.float32)))

    threads = [threading.Thread(target=client, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.02)
    release.set()
    for t in threads:
        t.join()
    mb.close()
    assert len(results) == 3

    tree = tracing.span_trees(tracing.TRACER.events())
    reqs = {s: e for s, e in tree["spans"].items()
            if e["name"] == "Serve::request"}
    queues = {s: e for s, e in tree["spans"].items()
              if e["name"] == "Serve::queue"}
    batches = {s: e for s, e in tree["spans"].items()
               if e["name"] == "Serve::batch"}
    assert len(reqs) == 3 and len(queues) == 3
    # each queue span is the child of its request span, same trace
    for qs, qe in queues.items():
        parent = qe["args"]["parent_id"]
        assert parent in reqs
        assert qe["args"]["trace_id"] == \
            reqs[parent]["args"]["trace_id"]
        # and coalesces into some batch span
        assert qs in tree["coalesced_into"]
        assert tree["coalesced_into"][qs] in batches
    # all three rode batches whose member lists cover every request trace
    member_traces = set()
    for be in batches.values():
        member_traces.update(be["args"].get("member_trace_ids", []))
    assert member_traces == {e["args"]["trace_id"] for e in reqs.values()}
    # the device predict is a child of a batch span
    preds = [e for e in tree["spans"].values()
             if e["name"] == "Predict::forest"]
    assert preds and all(p["args"]["parent_id"] in batches for p in preds)


def test_shed_request_closes_queue_span(tracer):
    """A request shed on timeout still closes its queue span (marked
    shed) — unfinished spans would silently vanish from the export."""
    from lightgbm_tpu.serve.batcher import MicroBatcher
    gate = threading.Event()

    def slow_predict(rows):
        gate.wait(3.0)
        return np.zeros((1, rows.shape[0]), np.float32)

    mb = MicroBatcher(slow_predict, max_batch=2, max_delay_s=2.0)
    # first request opens a batch window the worker sits in; the second
    # stays queued past its timeout and is shed
    t1 = threading.Thread(
        target=lambda: mb.submit(np.ones((2, 2), np.float32), timeout=5.0))
    t1.start()
    time.sleep(0.05)
    with pytest.raises(TimeoutError):
        mb.submit(np.ones((1, 2), np.float32), timeout=0.05)
    gate.set()
    t1.join()
    mb.close()
    shed = [e for e in tracing.TRACER.events()
            if e.get("ph") == "X" and e["name"] == "Serve::queue"
            and (e.get("args") or {}).get("shed")]
    assert len(shed) == 1


# ---------------------------------------------------------------------------
# serve HTTP round trip (acceptance criterion)


def test_http_round_trip_trace(tracer, tmp_path):
    from lightgbm_tpu.serve import CompiledForest, PredictServer
    bst, X = _train()
    forest = CompiledForest.from_booster(bst, buckets=[16, 64]).warmup()
    srv = PredictServer(forest, port=0, max_delay_ms=30.0).start()
    host, port = srv.address

    def post():
        body = json.dumps({"rows": X[:3].tolist()}).encode()
        req = urllib.request.Request(
            f"http://{host}:{port}/predict", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            assert r.headers.get("X-Request-Id")
            assert json.loads(r.read())["num_rows"] == 3

    threads = [threading.Thread(target=post) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    srv.stop()                 # exports the trace on shutdown

    events = tracing.read_trace(str(tracer))
    tree = tracing.span_trees(events)
    reqs = [s for s, e in tree["spans"].items()
            if e["name"] == "Serve::request"]
    assert len(reqs) == 2
    for r in reqs:
        assert tree["spans"][r]["args"]["request_id"]
        # request -> queue
        kids = [tree["spans"][k]["name"]
                for k in tree["children"].get(r, [])]
        assert "Serve::queue" in kids
        # queue -> coalesced batch -> device predict (critical path
        # walks the coalesce edge)
        names = [s["name"] for s in tracing.critical_path(tree, r)]
        assert names[:2] == ["Serve::request", "Serve::queue"]
        assert "Serve::batch" in names and "Predict::forest" in names


# ---------------------------------------------------------------------------
# training: one trace per boosting round


def test_training_rounds_are_traces(tracer):
    # engine.train exports at exit AND clears the buffer (one export
    # per run), so the assertion reads the exported file
    _train(rounds=4)
    assert not tracing.TRACER.events()
    tree = tracing.span_trees(tracing.read_trace(str(tracer)))
    iters = [s for s, e in tree["spans"].items()
             if e["name"] == "GBDT::iteration"]
    assert len(iters) == 4
    # each round is its own root with its own trace id
    assert all(s in tree["roots"] for s in iters)
    assert len({tree["spans"][s]["args"]["trace_id"]
                for s in iters}) == 4


def test_summarize_traces(tracer):
    _train(rounds=3)                       # exports on engine exit
    rep = tracing.summarize_traces([str(tracer)], top_k=2)
    assert rep["traces"] >= 3
    assert rep["roots"]["GBDT::iteration"]["count"] == 3
    assert len(rep["slowest"]) == 2
    assert rep["slowest"][0]["critical_path"][0]["name"] == \
        "GBDT::iteration"


# ---------------------------------------------------------------------------
# memwatch


@pytest.fixture
def memwatch_on(monkeypatch):
    monkeypatch.setenv(memwatch.ENV, "1")
    memwatch.configure()
    yield
    memwatch.enable(False)


def test_memwatch_gauges_in_metrics_scrape(memwatch_on):
    import jax.numpy as jnp
    from lightgbm_tpu.obs import prom
    keep = jnp.ones((128, 8), jnp.float32)      # noqa: F841 - held live
    with obs.span("GBDT::iteration"):
        pass                                    # exit samples memwatch
    live = obs.get_gauge("memwatch_live_bytes")
    assert live is not None and live >= keep.nbytes
    assert obs.get_gauge("memwatch_peak_live_bytes") >= live
    assert obs.get_gauge(
        "memwatch_live_bytes_gbdt_iteration") is not None
    text = prom.render()
    assert "lightgbm_tpu_memwatch_live_bytes " in text
    assert "lightgbm_tpu_memwatch_live_bytes_gbdt_iteration " in text
    prom.parse_text(text)                       # stays format-valid


def test_memwatch_off_by_default_and_env_config(monkeypatch):
    assert memwatch.ENABLED is False
    assert memwatch.configure(None) is False    # nothing set -> stays off
    assert memwatch.configure("true") is True   # param flag
    memwatch.enable(False)
    monkeypatch.setenv(memwatch.ENV, "1")
    assert memwatch.configure(False) is True    # env wins over param
    memwatch.enable(False)


def test_memwatch_training_sample(memwatch_on):
    """A real training run leaves per-phase watermarks behind (the span
    hook fires on GBDT::iteration every round)."""
    _train(rounds=2)
    live = obs.get_gauge("memwatch_live_bytes_gbdt_iteration")
    assert live is not None and live > 0
