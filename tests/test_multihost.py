"""Machine-list discovery for multi-host bring-up (parallel/multihost.py):
the parsing + rank-election logic the reference implements in
linkers_socket.cpp Construct, minus the actual TCP (jax.distributed owns
transport).  Real multi-process init cannot run in one test process; the
single-process no-op contract is pinned instead."""

import os

import pytest

from lightgbm_tpu.basic import LightGBMError
from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel.multihost import (find_process_id,
                                             maybe_initialize_distributed,
                                             parse_machine_list)


def test_parse_machine_list(tmp_path):
    p = tmp_path / "mlist.txt"
    p.write_text("# cluster\n10.0.0.1 12400\n10.0.0.2,12401\n\n"
                 "worker-3 12402\n")
    assert parse_machine_list(str(p)) == [
        ("10.0.0.1", 12400), ("10.0.0.2", 12401), ("worker-3", 12402)]


def test_parse_machine_list_malformed_names_file_and_line(tmp_path):
    p = tmp_path / "mlist.txt"
    p.write_text("# header\n10.0.0.1 12400\n10.0.0.1\n")
    with pytest.raises(LightGBMError) as ei:
        parse_machine_list(str(p))
    assert str(p) in str(ei.value)
    assert "line 3" in str(ei.value)


def test_parse_machine_list_bad_port_names_file_and_line(tmp_path):
    p = tmp_path / "mlist.txt"
    p.write_text("10.0.0.1 http\n")
    with pytest.raises(LightGBMError) as ei:
        parse_machine_list(str(p))
    assert str(p) in str(ei.value)
    assert "line 1" in str(ei.value)


def test_parse_machine_list_rejects_duplicates_at_parse_time(tmp_path):
    # a duplicated line used to fall through to find_process_id's
    # confusing "matches this host N times"; it must die HERE, named
    p = tmp_path / "mlist.txt"
    p.write_text("10.0.0.1 12400\n10.0.0.2 12400\n10.0.0.1 12400\n")
    with pytest.raises(LightGBMError) as ei:
        parse_machine_list(str(p))
    msg = str(ei.value)
    assert str(p) in msg
    assert "line 3" in msg and "line 1" in msg
    assert "10.0.0.1 12400" in msg


def test_parse_machine_list_same_host_distinct_ports_ok(tmp_path):
    # several processes per machine (same IP, different ports) is a
    # legitimate layout — only exact (host, port) repeats are fatal
    p = tmp_path / "mlist.txt"
    p.write_text("10.0.0.1 12400\n10.0.0.1 12401\n")
    assert parse_machine_list(str(p)) == [
        ("10.0.0.1", 12400), ("10.0.0.1", 12401)]


def test_find_process_id_env_override(monkeypatch):
    monkeypatch.setenv("LIGHTGBM_TPU_PROCESS_ID", "2")
    assert find_process_id([("a", 1), ("b", 2), ("c", 3)]) == 2


def test_find_process_id_localhost():
    # 127.0.0.1 always matches a local address
    machines = [("10.99.0.1", 12400), ("127.0.0.1", 12401)]
    assert find_process_id(machines) == 1
    assert find_process_id([("10.99.0.1", 12400)]) is None


def test_single_process_is_noop():
    cfg = Config({"task": "train", "objective": "binary"})
    assert maybe_initialize_distributed(cfg) is False
    cfg2 = Config({"task": "train", "objective": "binary",
                   "num_machines": 4, "tree_learner": "data"})
    # num_machines > 1 but no machine list: local-mesh mode, no init
    assert maybe_initialize_distributed(cfg2) is False
