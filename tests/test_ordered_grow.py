"""Leaf-ordered grower (ops/ordered_grow.py) must produce EXACTLY the
same tree as the unordered cached learner (ops/grow.py SerialComm): both
accumulate identical int32 fixed-point digit sums over identical row
sets, so every split decision, leaf value, leaf assignment and score
delta matches bit-for-bit."""

import numpy as np
import pytest
import jax.numpy as jnp

from lightgbm_tpu.ops.grow import GrowParams, grow_tree
from lightgbm_tpu.ops.ordered_grow import grow_tree_ordered


def _data(n=20000, f=6, seed=0, cat_feature=False):
    rng = np.random.RandomState(seed)
    bins = rng.randint(0, 32, size=(f, n)).astype(np.uint8)
    grad = rng.normal(size=n).astype(np.float32)
    hess = (np.abs(rng.normal(size=n)) + 0.1).astype(np.float32)
    w = np.ones(n, np.float32)
    num_bin = np.full(f, 32, np.int32)
    is_cat = np.zeros(f, bool)
    if cat_feature:
        is_cat[1] = True
    feat_mask = np.ones(f, bool)
    return (jnp.asarray(bins), jnp.asarray(num_bin), jnp.asarray(is_cat),
            jnp.asarray(feat_mask), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(w))


@pytest.mark.parametrize("num_leaves,cat", [(15, False), (31, True),
                                            (7, False)])
def test_ordered_matches_unordered(num_leaves, cat):
    bins, num_bin, is_cat, feat_mask, g, h, w = _data(cat_feature=cat)
    params = GrowParams(num_leaves=num_leaves, max_bin=32,
                        min_data_in_leaf=20, min_sum_hessian_in_leaf=1.0)
    bins_rm = jnp.asarray(np.ascontiguousarray(np.asarray(bins).T))
    lr = jnp.float32(0.1)

    t_ref, leaf_ref, delta_ref = grow_tree(bins, num_bin, is_cat, feat_mask,
                                           g, h, w, lr, params,
                                           bins_rm=bins_rm)
    t_ord, leaf_ord, delta_ord = grow_tree_ordered(
        bins, num_bin, is_cat, feat_mask, g, h, w, lr, params,
        bins_rm=bins_rm)

    assert int(t_ord.num_leaves) == int(t_ref.num_leaves)
    for field in ("split_feature", "split_bin", "left_child", "right_child",
                  "leaf_count", "leaf_parent", "leaf_depth"):
        np.testing.assert_array_equal(
            np.asarray(getattr(t_ord, field)),
            np.asarray(getattr(t_ref, field)), err_msg=field)
    for field in ("split_gain", "internal_value", "leaf_value"):
        np.testing.assert_allclose(
            np.asarray(getattr(t_ord, field)),
            np.asarray(getattr(t_ref, field)), rtol=1e-6, atol=1e-7,
            err_msg=field)
    np.testing.assert_array_equal(np.asarray(leaf_ord),
                                  np.asarray(leaf_ref))
    np.testing.assert_allclose(np.asarray(delta_ord),
                               np.asarray(delta_ref), rtol=1e-6, atol=1e-7)


def test_ordered_with_bagging_weights():
    bins, num_bin, is_cat, feat_mask, g, h, w = _data(n=9000)
    rng = np.random.RandomState(1)
    w = jnp.asarray((rng.uniform(size=9000) < 0.7).astype(np.float32))
    params = GrowParams(num_leaves=15, max_bin=32, min_data_in_leaf=20,
                        min_sum_hessian_in_leaf=1.0)
    bins_rm = jnp.asarray(np.ascontiguousarray(np.asarray(bins).T))
    lr = jnp.float32(0.1)
    t_ref, leaf_ref, _ = grow_tree(bins, num_bin, is_cat, feat_mask,
                                   g, h, w, lr, params, bins_rm=bins_rm)
    t_ord, leaf_ord, _ = grow_tree_ordered(bins, num_bin, is_cat, feat_mask,
                                           g, h, w, lr, params,
                                           bins_rm=bins_rm)
    assert int(t_ord.num_leaves) == int(t_ref.num_leaves)
    np.testing.assert_array_equal(np.asarray(t_ord.split_feature),
                                  np.asarray(t_ref.split_feature))
    np.testing.assert_array_equal(np.asarray(leaf_ord),
                                  np.asarray(leaf_ref))


def test_compact_inactive_matches_riding():
    """compact_inactive=True (bagging compaction, gbdt.cpp:271-278) must
    produce the identical tree AND identical leaf routing / deltas for
    EVERY row — active rows via segments, zero-weight rows via the
    out-of-bag tree walk."""
    bins, num_bin, is_cat, feat_mask, g, h, w = _data(n=9000, cat_feature=True)
    rng = np.random.RandomState(5)
    w = jnp.asarray((rng.uniform(size=9000) < 0.35).astype(np.float32))
    base = GrowParams(num_leaves=15, max_bin=32, min_data_in_leaf=20,
                      min_sum_hessian_in_leaf=1.0)
    bins_rm = jnp.asarray(np.ascontiguousarray(np.asarray(bins).T))
    lr = jnp.float32(0.1)
    t_ref, leaf_ref, delta_ref = grow_tree_ordered(
        bins, num_bin, is_cat, feat_mask, g, h, w, lr, base,
        bins_rm=bins_rm)
    t_cmp, leaf_cmp, delta_cmp = grow_tree_ordered(
        bins, num_bin, is_cat, feat_mask, g, h, w, lr,
        base._replace(compact_inactive=True), bins_rm=bins_rm)
    assert int(t_cmp.num_leaves) == int(t_ref.num_leaves)
    for field in ("split_feature", "split_bin", "left_child", "right_child",
                  "leaf_count", "leaf_parent", "leaf_depth"):
        np.testing.assert_array_equal(
            np.asarray(getattr(t_cmp, field)),
            np.asarray(getattr(t_ref, field)), err_msg=field)
    np.testing.assert_array_equal(np.asarray(leaf_cmp), np.asarray(leaf_ref))
    np.testing.assert_allclose(np.asarray(delta_cmp), np.asarray(delta_ref),
                               rtol=1e-6, atol=1e-7)


def test_ordered_saturation_stops():
    bins, num_bin, is_cat, feat_mask, g, h, w = _data(n=512)
    params = GrowParams(num_leaves=31, max_bin=32, min_data_in_leaf=300,
                        min_sum_hessian_in_leaf=1.0)
    t, leaf, delta = grow_tree_ordered(bins, num_bin, is_cat, feat_mask,
                                       g, h, w, jnp.float32(0.1), params)
    assert int(t.num_leaves) == 1
    np.testing.assert_array_equal(np.asarray(leaf), 0)
    np.testing.assert_array_equal(np.asarray(delta), 0.0)


def test_uint16_bins_fall_back_to_cached_learner():
    """max_bin > 256 stores uint16 bins; the ordered grower's i32 lane
    packing is uint8-only, so GBDT must route to the cached learner and
    still train correctly."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    X = rng.normal(size=(3000, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "max_bin": 500,
                     "num_leaves": 15, "verbose": -1,
                     "min_data_in_leaf": 20},
                    lgb.Dataset(X, label=y, params={"max_bin": 500}),
                    num_boost_round=5)
    assert bst.num_trees() == 5
    p = bst.predict(X[:50])
    assert np.isfinite(p).all()


def test_serial_grow_config_knob():
    """serial_grow=cached selects the original-order learner; results
    match the ordered default exactly."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(1)
    X = rng.normal(size=(3000, 4))
    y = (X[:, 0] + 0.2 * X[:, 1] > 0).astype(np.float64)
    preds = []
    for strategy in ("ordered", "cached"):
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "verbose": -1, "min_data_in_leaf": 20,
                         "serial_grow": strategy},
                        lgb.Dataset(X, label=y), num_boost_round=5)
        preds.append(bst.predict(X[:200], raw_score=True))
    np.testing.assert_allclose(preds[0], preds[1], rtol=1e-6, atol=1e-7)


def test_misaligned_valid_set_rejected():
    """AddValidData with independently binned data must fatal
    (Dataset::CheckAlign semantics), not silently mis-score."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.models.gbdt import GBDT
    rng = np.random.RandomState(2)
    X = rng.normal(size=(500, 3))
    y = (X[:, 0] > 0).astype(np.float64)
    X2 = rng.normal(size=(300, 3)) * 5.0      # different value range
    ds = BinnedDataset.from_matrix(X, y, max_bin=32, min_data_in_leaf=10)
    bad = BinnedDataset.from_matrix(X2, y[:300], max_bin=32,
                                    min_data_in_leaf=10)
    good = ds.create_valid(X2, y[:300])
    cfg = Config({"objective": "binary", "num_leaves": 7, "metric": "auc"})
    b = GBDT(cfg, ds)
    b.add_valid_dataset(good)                 # aligned: fine
    with pytest.raises(Exception):
        b.add_valid_dataset(bad)
