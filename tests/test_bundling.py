"""Exclusive feature bundling (EFB) — parity pins (docs/SPARSE.md).

The acceptance contract of the wide-sparse subsystem:
  * zero-conflict bundling trains BIT-IDENTICAL models to unbundled
    training on the same data (the integer digit-sum expansion makes
    this exact, ops/bundle.py),
  * ``max_conflict_rate=0`` on dense data is a no-op (no bundles, plain
    layout, baseline bit-match by construction),
  * a bundled-trained model lives entirely in ORIGINAL feature space:
    raw predict, the CompiledForest serve path, and a model-file
    round-trip all bit-match each other.
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.bundling import BundlePlan, plan_bundles
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.models.gbdt import GBDT

pytestmark = pytest.mark.sparse


def one_hot_data(n=2500, blocks=8, block_size=6, seed=0, act=0.7,
                 levels=5):
    """One-hot-ish blocks: at most one active feature per block per row,
    small integer levels — perfectly exclusive within a block."""
    rng = np.random.RandomState(seed)
    F = blocks * block_size
    X = np.zeros((n, F))
    for b in range(blocks):
        choice = rng.randint(0, block_size, n)
        vals = rng.randint(1, levels, n).astype(float)
        on = rng.rand(n) < act
        X[np.arange(n)[on], (b * block_size + choice)[on]] = vals[on]
    logit = (X[:, 0] - 0.5 * X[:, block_size + 1]
             + 0.3 * X[:, 2 * block_size + 1]
             + rng.normal(0, 0.5, n))
    y = (logit > np.median(logit)).astype(np.float64)
    return X, y


def train_gbdt(X, y, *, enable_bundle, iters=6, grow="cached", extra=None,
               max_bin=63):
    p = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 20,
         "min_sum_hessian_in_leaf": 1e-3, "serial_grow": grow,
         "max_bin": max_bin, "num_iterations": iters}
    p.update(extra or {})
    ds = BinnedDataset.from_matrix(X, y, max_bin=max_bin,
                                   min_data_in_leaf=20,
                                   enable_bundle=enable_bundle)
    booster = GBDT(Config(p), ds)
    for _ in range(iters):
        booster.train_one_iter()
    booster._flush_pending()
    return booster, ds


# ---------------------------------------------------------------------------
# planner
# ---------------------------------------------------------------------------

def test_planner_bundles_exclusive_features():
    X, y = one_hot_data()
    ds = BinnedDataset.from_matrix(X, y, max_bin=63, min_data_in_leaf=20,
                                   enable_bundle=True)
    plan = ds.bundle_plan
    assert plan is not None
    assert plan.sample_conflicts == 0
    assert ds.num_columns < ds.num_features
    assert plan.features_bundled > 0
    # every used feature appears in exactly one column
    seen = sorted(f for m in plan.column_members for f in m)
    assert seen == list(range(ds.num_features))
    # offsets of a bundle carve disjoint sub-ranges within max_bin
    for members, offs in zip(plan.column_members, plan.column_offsets):
        if len(members) == 1:
            continue
        end = 1
        for f, o in zip(members, offs):
            assert o == end
            end += ds.mappers[f].num_bin - 1
        assert end <= 63 + 1


def test_dense_data_builds_no_bundles():
    rng = np.random.RandomState(3)
    X = rng.normal(size=(1500, 10))
    y = (X[:, 0] > 0).astype(float)
    ds = BinnedDataset.from_matrix(X, y, max_bin=63, min_data_in_leaf=20,
                                   enable_bundle=True)
    assert ds.bundle_plan is None
    assert ds.num_columns == ds.num_features


def test_is_enable_sparse_false_disables_bundling():
    X, y = one_hot_data()
    ds = BinnedDataset.from_matrix(X, y, max_bin=63, min_data_in_leaf=20,
                                   enable_bundle=True,
                                   is_enable_sparse=False)
    assert ds.bundle_plan is None


def test_max_conflict_rate_budget():
    # two sparse features that conflict on ~10% of rows: rate 0 keeps
    # them apart, a generous rate bundles them
    rng = np.random.RandomState(5)
    n = 2000
    X = np.zeros((n, 2))
    a = rng.rand(n) < 0.15
    b = rng.rand(n) < 0.15
    X[a, 0] = rng.randint(1, 4, a.sum())
    X[b, 1] = rng.randint(1, 4, b.sum())
    sample = X.copy()
    from lightgbm_tpu.io.dataset import build_mappers_from_sample
    mappers = build_mappers_from_sample(
        sample, n, max_bin=63, min_data_in_bin=1, min_data_in_leaf=1)
    strict = plan_bundles(sample, mappers, [0, 1],
                          max_conflict_rate=0.0, max_total_bin=63)
    loose = plan_bundles(sample, mappers, [0, 1],
                         max_conflict_rate=0.5, max_total_bin=63)
    overlap = int(np.count_nonzero(a & b))
    assert overlap > 0
    assert strict is None                      # conflicts forbid merging
    assert loose is not None and len(loose.bundles) == 1
    assert loose.sample_conflicts == overlap


def test_config_validates_max_conflict_rate():
    with pytest.raises(ValueError):
        Config({"max_conflict_rate": -0.1})
    with pytest.raises(ValueError):
        Config({"max_conflict_rate": 1.0})
    Config({"max_conflict_rate": 0.99})        # in range: fine


# ---------------------------------------------------------------------------
# training parity pins
# ---------------------------------------------------------------------------

def test_zero_conflict_bundled_training_bit_identical():
    X, y = one_hot_data()
    b0, ds0 = train_gbdt(X, y, enable_bundle=False)
    b1, ds1 = train_gbdt(X, y, enable_bundle=True)
    assert ds1.bundle_plan is not None and ds1.bundle_plan.sample_conflicts == 0
    assert ds1.num_columns < ds0.num_columns
    assert b1.save_model_to_string() == b0.save_model_to_string()
    p0 = b0.predict(X[:400])
    p1 = b1.predict(X[:400])
    assert np.array_equal(p0, p1)


def test_default_grow_bundled_matches_unbundled_ordered():
    # default serial_grow=ordered falls back to the cached learner for
    # bundled datasets; exact cross-grower parity keeps the models
    # bit-identical anyway
    X, y = one_hot_data(seed=1)
    b0, _ = train_gbdt(X, y, enable_bundle=False, grow="ordered")
    b1, _ = train_gbdt(X, y, enable_bundle=True, grow="ordered")
    assert b1.save_model_to_string() == b0.save_model_to_string()


def test_fused_grow_composes_with_bundling():
    X, y = one_hot_data(seed=2)
    b1, ds1 = train_gbdt(X, y, enable_bundle=True, grow="fused")
    assert ds1.bundle_plan is not None
    assert len(b1.models) == 6
    raw = b1.predict_raw(X[:200])
    assert np.isfinite(raw).all()


def test_goss_and_dart_compose_with_bundling():
    from lightgbm_tpu.models.dart import DART
    from lightgbm_tpu.models.goss import GOSS
    X, y = one_hot_data(seed=4)
    for cls, extra in ((GOSS, {"boosting_type": "goss"}),
                       (DART, {"boosting_type": "dart"})):
        p = {"objective": "binary", "num_leaves": 15,
             "min_data_in_leaf": 20, "min_sum_hessian_in_leaf": 1e-3,
             "max_bin": 63, "num_iterations": 4, **extra}
        ds = BinnedDataset.from_matrix(X, y, max_bin=63,
                                       min_data_in_leaf=20,
                                       enable_bundle=True)
        assert ds.bundle_plan is not None
        b = cls(Config(p), ds)
        for _ in range(4):
            b.train_one_iter()
        assert np.isfinite(b.predict_raw(X[:100])).all()


def test_bagging_composes_with_bundling():
    X, y = one_hot_data(seed=6)
    b0, _ = train_gbdt(X, y, enable_bundle=False,
                       extra={"bagging_fraction": 0.6, "bagging_freq": 1})
    b1, _ = train_gbdt(X, y, enable_bundle=True,
                       extra={"bagging_fraction": 0.6, "bagging_freq": 1})
    # same RNG streams + exact expansion -> bagged runs stay bit-equal
    assert b1.save_model_to_string() == b0.save_model_to_string()


def test_valid_set_rides_training_bundles():
    X, y = one_hot_data(seed=7)
    Xv, yv = one_hot_data(n=800, seed=8)
    p = {"objective": "binary", "metric": "auc", "num_leaves": 15,
         "min_data_in_leaf": 20, "min_sum_hessian_in_leaf": 1e-3,
         "max_bin": 63, "num_iterations": 5}
    ds = BinnedDataset.from_matrix(X, y, max_bin=63, min_data_in_leaf=20,
                                   enable_bundle=True)
    valid = ds.create_valid(Xv, yv)
    assert valid.bundle_plan is ds.bundle_plan
    b = GBDT(Config(p), ds)
    b.add_valid_dataset(valid)
    for _ in range(5):
        b.train_one_iter()
    vals = b.eval_metrics()
    assert np.isfinite(vals["valid_1"]["auc"])
    # device-replayed valid scores == host predict on the raw rows
    host = b.predict_raw(Xv)[0]
    dev = b.valid_data[0].host_score()[0]
    np.testing.assert_allclose(dev, host, rtol=0, atol=2e-4)


@pytest.mark.parametrize("learner", ["data", "feature", "voting"])
def test_parallel_learners_compose_with_bundling(learner):
    # conftest forces 8 virtual CPU devices; every distributed strategy
    # must accept the bundled column matrix (expansion happens after the
    # reduce / before the election — docs/SPARSE.md strategy matrix)
    if len(jax.devices()) < 2:
        pytest.skip("needs virtual devices")
    X, y = one_hot_data(n=1000, seed=21)
    p = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 20,
         "min_sum_hessian_in_leaf": 1e-3, "max_bin": 63,
         "num_iterations": 2, "tree_learner": learner, "num_machines": 2}
    ds = BinnedDataset.from_matrix(X, y, max_bin=63, min_data_in_leaf=20,
                                   enable_bundle=True)
    assert ds.bundle_plan is not None
    b = GBDT(Config(p), ds)
    for _ in range(2):
        b.train_one_iter()
    b._flush_pending()
    assert len(b.models) == 2
    F = ds.num_total_features
    for t in b.models:
        n = t.num_leaves - 1
        assert (t.split_feature[:n] < F).all()
    assert np.isfinite(b.predict_raw(X[:100])).all()


# ---------------------------------------------------------------------------
# model artifacts stay in original feature space
# ---------------------------------------------------------------------------

def test_bundled_model_predict_paths_bit_match(tmp_path):
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serve.forest import CompiledForest
    X, y = one_hot_data(seed=9)
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 20, "min_sum_hessian_in_leaf": 1e-3,
              "max_bin": 63, "verbose": -1, "enable_bundle": True}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=6)
    assert bst._booster.train_set.bundle_plan is not None
    # trees store original feature indices only
    F = X.shape[1]
    for t in bst._booster.models:
        n = t.num_leaves - 1
        assert (t.split_feature[:n] >= 0).all()
        assert (t.split_feature[:n] < F).all()

    Xq = X[:512]
    bst.compile()
    raw = bst.predict(Xq, raw_score=True)          # Booster.predict path
    cf = CompiledForest.from_booster(bst)
    raw_cf = cf.predict(Xq, raw_score=True)        # the serve /predict path
    assert np.array_equal(raw, raw_cf)

    # model-file round-trip: loaded model predicts bit-identically
    path = str(tmp_path / "bundled.txt")
    bst.save_model(path)
    loaded = lgb.Booster(model_file=path)
    loaded.compile()
    raw_loaded = loaded.predict(Xq, raw_score=True)
    assert np.array_equal(raw, raw_loaded)


# ---------------------------------------------------------------------------
# loaders agree
# ---------------------------------------------------------------------------

def test_two_round_loader_builds_identical_bundles(tmp_path):
    X, y = one_hot_data(n=1200, seed=11)
    path = str(tmp_path / "sparse.tsv")
    with open(path, "w") as fh:
        for i in range(X.shape[0]):
            fh.write("\t".join([f"{y[i]:g}"] +
                               [f"{v:g}" for v in X[i]]) + "\n")
    from lightgbm_tpu.io.streaming import load_file_two_round
    ds_mem = BinnedDataset.from_matrix(X, y, max_bin=63,
                                       min_data_in_leaf=20,
                                       enable_bundle=True)
    ds_str = load_file_two_round(path, max_bin=63, min_data_in_leaf=20,
                                 enable_bundle=True)
    assert ds_mem.bundle_plan is not None and ds_str.bundle_plan is not None
    assert ds_str.bundle_plan.signature() == ds_mem.bundle_plan.signature()
    assert np.array_equal(ds_str.bins, ds_mem.bins)
    assert np.array_equal(ds_str.metadata.label, ds_mem.metadata.label)


def test_binary_cache_roundtrips_bundle_plan(tmp_path):
    X, y = one_hot_data(n=1000, seed=12)
    ds = BinnedDataset.from_matrix(X, y, max_bin=63, min_data_in_leaf=20,
                                   enable_bundle=True)
    path = str(tmp_path / "ds.bin")
    ds.save_binary(path)
    back = BinnedDataset.load_binary(path)
    assert back.bundle_plan is not None
    assert back.bundle_plan.signature() == ds.bundle_plan.signature()
    assert np.array_equal(back.bins, ds.bins)
    assert back.num_features == ds.num_features
    assert back.num_columns == ds.num_columns


def test_bundle_plan_state_roundtrip():
    plan = BundlePlan([[0, 2], [1]], [[1, 4], [0]], 3, sample_conflicts=7)
    back = BundlePlan.from_state(plan.to_state())
    assert back.signature() == plan.signature()
    assert back.sample_conflicts == 7
    assert BundlePlan.from_state(None) is None


# ---------------------------------------------------------------------------
# bench_regress passthrough (informational keys)
# ---------------------------------------------------------------------------

def test_bench_regress_passes_sparse_keys_through():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_regress", os.path.join(os.path.dirname(__file__), "..",
                                      "tools", "bench_regress.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    base = {"metric": "boosting_iters_per_sec_ctrlike500k", "value": 2.0,
            "unit": "iters/sec", "auc": 0.761,
            "efb": {"enabled": False, "columns": 2000,
                    "num_features": 2000, "bundles": 0},
            "screening": {"ratio": 0.0, "active_features_last": 2000}}
    cand = {"metric": "boosting_iters_per_sec_ctrlike500k", "value": 5.0,
            "unit": "iters/sec", "auc": 0.760,
            "efb": {"enabled": True, "columns": 40,
                    "num_features": 2000, "bundles": 38},
            "screening": {"ratio": 0.5, "active_features_last": 1000}}
    verdict = mod.compare(base, cand, threshold_pct=5.0)
    assert verdict["ok"]
    assert verdict["efb_candidate"]["columns"] == 40
    assert verdict["efb_baseline"]["columns"] == 2000
    assert verdict["screening_candidate"]["ratio"] == 0.5
    assert verdict["auc_baseline"] == 0.761
    # old baselines without the keys stay comparable
    old = {"metric": "boosting_iters_per_sec_ctrlike500k", "value": 2.0,
           "unit": "iters/sec"}
    v2 = mod.compare(old, cand, threshold_pct=5.0)
    assert v2["ok"] and "efb_baseline" not in v2
