"""Compiled-forest inference subsystem (lightgbm_tpu/serve/).

Tier-1 CPU tests for the serving stack: CompiledForest freeze parity
(atol=0 against Booster.predict raw scores, binary AND multiclass — the
PR's acceptance gate), the shape-bucketed compile cache (zero new XLA
compiles across 10 batch sizes after warmup(), read from the per-bucket
obs counters), micro-batcher coalescing, and an HTTP round trip through
the stdlib server.  Also pins the degenerate forests (1-leaf trees,
empty batches) and the CLI's streaming task=predict.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.serve import (BatcherClosed, BucketLadder, CompiledForest,
                                MicroBatcher, PredictServer, default_ladder)

pytestmark = pytest.mark.serve

BUCKETS = [32, 128, 512, 2048]


def _train(n=2000, num_class=1, seed=0, num_boost_round=8):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, 6))
    X[:, 3] = np.round(X[:, 3] * 4) / 4       # boundary-tied values
    if num_class > 1:
        y = np.digitize(X[:, 0], [-0.5, 0.5]).astype(np.float64)
        params = {"objective": "multiclass", "num_class": num_class}
    else:
        y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
        params = {"objective": "binary"}
    params.update({"num_leaves": 7, "verbose": -1, "min_data_in_leaf": 20})
    bst = lgb.train(params, lgb.Dataset(X, label=y),
                    num_boost_round=num_boost_round)
    return bst, X


# ---------------------------------------------------------------------------
# bucket ladder


def test_bucket_ladder_shapes():
    lad = BucketLadder([64, 16, 256, 16])
    assert lad.sizes == [16, 64, 256]
    assert lad.bucket_for(1) == 16
    assert lad.bucket_for(16) == 16
    assert lad.bucket_for(17) == 64
    assert lad.bucket_for(10_000) == 256      # oversize -> largest
    # oversize inputs stream through the largest bucket + remainder
    assert lad.chunks(600) == [(0, 256, 256), (256, 256, 256),
                               (512, 88, 256)]
    assert lad.chunks(5) == [(0, 5, 16)]
    assert lad.chunks(0) == [(0, 0, 16)]
    d = default_ladder(16, 65536)
    assert d[0] == 16 and d[-1] == 65536
    assert all(b == 2 * a for a, b in zip(d, d[1:]))
    with pytest.raises(ValueError):
        BucketLadder([0, 16])


# ---------------------------------------------------------------------------
# CompiledForest parity (acceptance: atol=0 vs Booster.predict raw)


@pytest.mark.parametrize("num_class", [1, 3])
def test_compiled_forest_matches_booster_raw(num_class):
    """The PR's API contract: after compile(), Booster.predict and the
    artifact are the same program, so raw scores agree at atol=0 at any
    batch size.  This deliberately shares the code path — the
    INDEPENDENT routing check against the f64 host walk is
    test_compiled_forest_matches_host_walk below."""
    bst, X = _train(num_class=num_class)
    cf = bst.compile(buckets=BUCKETS)
    got = cf.predict(X, raw_score=True)
    want = bst.predict(X, raw_score=True)
    assert got.shape == want.shape
    assert np.array_equal(got, want)          # atol=0, the acceptance gate
    # ... including across bucket boundaries / sizes
    for n in (1, 31, 32, 33, 700):
        assert np.array_equal(cf.predict(X[:n], raw_score=True),
                              bst.predict(X[:n], raw_score=True))
    # transformed output parity (sigmoid / softmax in f64 on this path)
    assert np.allclose(cf.predict(X), bst.predict(X), rtol=1e-12, atol=0)


@pytest.mark.parametrize("num_class", [1, 3])
def test_compiled_forest_matches_host_walk(num_class):
    """Routing parity with the per-tree f64 host walk: the cut-table
    binning must reproduce `value <= threshold` exactly."""
    bst, X = _train(num_class=num_class)
    b = bst._booster
    host = np.zeros((b.num_class, X.shape[0]), np.float64)
    for i, t in enumerate(b.models):
        host[i % b.num_class] += t.predict(X)
    cf = CompiledForest.from_booster(bst, buckets=BUCKETS)
    raw = cf.raw_scores(X)
    np.testing.assert_allclose(raw, host, rtol=2e-6, atol=2e-6)
    # NaN rows must route right, like the host walk
    Xn = X.copy()
    Xn[:50, 1] = np.nan
    hostn = np.zeros((b.num_class, X.shape[0]), np.float64)
    for i, t in enumerate(b.models):
        hostn[i % b.num_class] += t.predict(Xn)
    np.testing.assert_allclose(cf.raw_scores(Xn), hostn,
                               rtol=2e-6, atol=2e-6)


def test_compiled_forest_from_loaded_model_file(tmp_path):
    """Model files (no training mappers) compile too: the cut tables
    come from the forest's own thresholds."""
    bst, X = _train()
    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    loaded = lgb.Booster(model_file=path)
    cf = loaded.compile(buckets=BUCKETS)
    got = cf.predict(X, raw_score=True)
    want = bst.compile(buckets=BUCKETS).predict(X, raw_score=True)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_device_binned_path_close_to_host():
    """The fully fused raw-float program (f32 on-device binning) stays
    within f32 tolerance of the exact path on generic data."""
    bst, X = _train()
    cf = bst.compile(buckets=BUCKETS)
    dev = cf.predict(X, raw_score=True, device_binning=True)
    exact = cf.predict(X, raw_score=True)
    np.testing.assert_allclose(dev, exact, rtol=2e-6, atol=2e-6)
    prob = cf.predict(X, device_binning=True)
    np.testing.assert_allclose(prob, cf.predict(X), rtol=2e-5, atol=2e-5)


def test_one_leaf_trees_and_empty_batch(tmp_path):
    """Degenerate forests through the same compiled walk: 1-leaf trees
    (constant model) and 0-row batches."""
    model = "\n".join([
        "gbdt", "num_class=1", "label_index=0", "max_feature_idx=3",
        "objective=regression", "sigmoid=-1", "feature_names=f0 f1 f2 f3",
        "feature_infos=none none none none", "",
        "Tree=0", "num_leaves=1", "leaf_value=0.25", "shrinkage=1", "",
        "Tree=1", "num_leaves=1", "leaf_value=-0.05", "shrinkage=1", "",
        "\nfeature importances:", ""])
    path = tmp_path / "const.txt"
    path.write_text(model)
    bst = lgb.Booster(model_file=str(path))
    cf = bst.compile(buckets=[16, 64])
    X = np.zeros((5, 4))
    np.testing.assert_allclose(cf.predict(X, raw_score=True),
                               np.full(5, 0.2), rtol=1e-6)
    out = cf.predict(np.zeros((0, 4)), raw_score=True)
    assert out.shape == (0,)
    raw, tra = cf.batched_fn()(np.zeros((0, 4)))
    assert raw.shape == (1, 0) and tra.shape == (1, 0)
    # empty batch on a real trained forest, multiclass shape contract
    bst3, _ = _train(num_class=3, num_boost_round=2)
    cf3 = bst3.compile(buckets=[16])
    assert cf3.predict(np.zeros((0, 6)), raw_score=True).shape == (0, 3)


# ---------------------------------------------------------------------------
# shape-bucketed compile cache (acceptance: zero compiles after warmup)


def test_warmup_then_zero_new_compiles_across_batch_sizes():
    bst, X = _train(num_boost_round=4)
    cf = bst.compile(buckets=BUCKETS)
    cf.warmup()
    before = obs.snapshot()["counters"]
    for n in (1, 3, 7, 17, 33, 65, 100, 200, 400, 511):   # 10 sizes
        cf.predict(X[:n], raw_score=True)
        cf.predict(X[:n], device_binning=True)
    after = obs.snapshot()["counters"]
    new = {k: after[k] - before.get(k, 0) for k in after
           if "compiles" in k and after[k] != before.get(k, 0)}
    assert new == {}, f"post-warmup XLA compiles: {new}"
    # and the per-bucket counters exist from the warmup itself
    assert any(k.startswith("serve_forest_compiles_bucket_")
               for k in after), after


def test_booster_predict_compile_count_flat_across_mixed_sizes():
    """The recompile-per-batch-shape fix on the standard predict path:
    mixed batch sizes (the chunked-file pattern) must reuse the bucket
    ladder's compiles instead of specializing on every N."""
    bst, X = _train(n=3000, num_boost_round=4)
    sizes = [100, 700, 1100, 2900, 1500]
    for n in sizes:
        bst.predict(X[:n], raw_score=True)
    before = obs.get_counter("predict_forest_compiles")
    for n in sizes + [50, 2000]:                  # new sizes, same buckets
        bst.predict(X[:n], raw_score=True)
    assert obs.get_counter("predict_forest_compiles") == before


# ---------------------------------------------------------------------------
# micro-batcher


def test_compiled_cache_invalidated_by_rollback_retrain():
    """rollback_one_iter + retraining restores the model COUNT but not
    the trees; the cached artifact must not serve stale predictions."""
    bst, X = _train(num_boost_round=3)
    bst.compile(buckets=[64, 512, 2048])
    before = bst.predict(X[:100], raw_score=True)
    bst.rollback_one_iter()
    bst.reset_parameter({"learning_rate": 0.5})   # retrained tree differs
    bst.update()
    b = bst._booster
    assert len(b.models) == 3                     # same count as before
    host = np.zeros(100)
    for t in b.models:
        host += t.predict(X[:100])
    got = bst.predict(X[:100], raw_score=True)
    np.testing.assert_allclose(got, host, rtol=2e-6, atol=2e-6)
    assert not np.allclose(got, before)


def test_predict_buckets_param_honored():
    """The documented ``predict_buckets`` param must drive the ladder of
    every compiled predict path, not just task=serve."""
    rng = np.random.RandomState(2)
    X = rng.normal(size=(600, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1, "min_data_in_leaf": 20,
                     "predict_buckets": "48,96"},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    cf = bst.compile()
    assert cf.ladder.sizes == [48, 96]
    assert np.array_equal(cf.predict(X, raw_score=True),
                          bst.predict(X, raw_score=True))


def test_microbatcher_coalesces_concurrent_requests():
    calls = []

    def predict_fn(rows):
        calls.append(rows.shape[0])
        return (rows.T * 2.0, rows.T * 2.0)   # [F, n] per-"class" doubling

    mb = MicroBatcher(predict_fn, max_batch=64, max_delay_s=0.2)
    rng = np.random.RandomState(1)
    reqs = [rng.normal(size=(3, 2)) for _ in range(4)]
    results = [None] * 4
    barrier = threading.Barrier(4)

    def worker(i):
        barrier.wait()
        results[i] = mb.submit(reqs[i], timeout=30.0)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    mb.close()
    assert len(calls) < 4, f"no coalescing happened: {calls}"
    assert sum(calls) == 12
    for req, res in zip(reqs, results):
        np.testing.assert_allclose(res[0], req.T * 2.0)
    snap = obs.snapshot()
    assert snap["gauges"].get("serve_latency_p50_ms") is not None


def test_microbatcher_max_batch_splits_and_errors_propagate():
    def predict_fn(rows):
        if rows.shape[0] >= 100:
            raise ValueError("boom")
        return rows.T, rows.T

    mb = MicroBatcher(predict_fn, max_batch=8, max_delay_s=0.0)
    out = mb.submit(np.ones((5, 2)), timeout=30.0)
    assert out[0].shape == (2, 5)
    with pytest.raises(ValueError, match="boom"):
        mb.submit(np.ones((100, 2)), timeout=30.0)
    mb.close()
    with pytest.raises(RuntimeError):
        mb.submit(np.ones((1, 2)))


# ---------------------------------------------------------------------------
# shutdown hardening: futures complete or fail — never hang


def test_microbatcher_submit_after_close_raises_cleanly():
    mb = MicroBatcher(lambda rows: (rows.T, rows.T), max_batch=8,
                      max_delay_s=0.0)
    mb.close()
    with pytest.raises(BatcherClosed):
        mb.submit(np.ones((1, 2)))
    # idempotent + still clean after a second close
    mb.close()
    with pytest.raises(BatcherClosed):
        mb.submit(np.ones((1, 2)), timeout=1.0)


def test_microbatcher_close_fails_queued_when_not_draining():
    started = threading.Event()
    release = threading.Event()

    def slow_fn(rows):
        started.set()
        release.wait(10.0)
        return rows.T, rows.T

    mb = MicroBatcher(slow_fn, max_batch=1, max_delay_s=0.0)
    results = []

    def submit_one():
        try:
            results.append(("ok", mb.submit(np.ones((1, 2)), timeout=30.0)))
        except BaseException as exc:
            results.append(("err", exc))

    t1 = threading.Thread(target=submit_one)   # picked up, in flight
    t1.start()
    assert started.wait(5.0)
    t2 = threading.Thread(target=submit_one)   # stays queued
    t2.start()
    while mb.queue_depth() == 0:
        time.sleep(0.005)
    mb.close(drain=False, join_timeout_s=0.2)  # worker still wedged
    # BOTH futures resolve promptly: the queued one fails on close, the
    # in-flight one fails via the post-join fallback — neither hangs
    t2.join(timeout=5.0)
    t1.join(timeout=5.0)
    assert not t1.is_alive() and not t2.is_alive(), \
        "close() left a submit() hanging"
    assert sorted(kind for kind, _ in results) == ["err", "err"]
    assert all(isinstance(v, BatcherClosed) for _, v in results)
    release.set()


def test_microbatcher_abort_fails_queued_and_inflight():
    started = threading.Event()
    release = threading.Event()

    def wedge_fn(rows):
        started.set()
        release.wait(10.0)
        return rows.T, rows.T

    mb = MicroBatcher(wedge_fn, max_batch=1, max_delay_s=0.0)
    outcomes = []

    def submit_one():
        try:
            mb.submit(np.ones((1, 2)), timeout=30.0)
            outcomes.append("ok")
        except RuntimeError as exc:
            outcomes.append(type(exc).__name__)

    threads = [threading.Thread(target=submit_one) for _ in range(3)]
    for t in threads:
        t.start()
    assert started.wait(5.0)

    class Boom(RuntimeError):
        pass

    mb.abort(Boom("replica ejected"))
    for t in threads:
        t.join(timeout=5.0)
    assert all(not t.is_alive() for t in threads), "abort left a hang"
    assert outcomes == ["Boom"] * 3
    with pytest.raises(BatcherClosed):
        mb.submit(np.ones((1, 2)))
    release.set()


# ---------------------------------------------------------------------------
# HTTP server round trip


def test_http_round_trip_and_graceful_stop():
    bst, X = _train(num_boost_round=4)
    cf = bst.compile(buckets=[16, 64])
    cf.warmup(max_bucket=64)
    srv = PredictServer(cf, port=0, max_batch=64, max_delay_ms=1.0).start()
    host, port = srv.address
    base = f"http://{host}:{port}"

    body = json.dumps({"rows": X[:5].tolist()}).encode()
    req = urllib.request.Request(base + "/predict", data=body,
                                 headers={"Content-Type":
                                          "application/json"})
    resp = json.loads(urllib.request.urlopen(req, timeout=30).read())
    got = np.asarray(resp["predictions"])
    want = cf.predict(X[:5].astype(np.float32), device_binning=True)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    assert resp["num_rows"] == 5

    # raw_score request + CSV body
    body = json.dumps({"rows": X[:3].tolist(), "raw_score": True}).encode()
    req = urllib.request.Request(base + "/predict", data=body,
                                 headers={"Content-Type":
                                          "application/json"})
    resp = json.loads(urllib.request.urlopen(req, timeout=30).read())
    np.testing.assert_allclose(
        np.asarray(resp["predictions"]),
        cf.predict(X[:3].astype(np.float32), raw_score=True,
                   device_binning=True), rtol=1e-6, atol=1e-6)
    csv = "\n".join(",".join(f"{v:.6f}" for v in row)
                    for row in X[:2]).encode()
    req = urllib.request.Request(base + "/predict", data=csv,
                                 headers={"Content-Type": "text/csv"})
    resp = json.loads(urllib.request.urlopen(req, timeout=30).read())
    assert len(resp["predictions"]) == 2

    health = json.loads(urllib.request.urlopen(base + "/healthz",
                                               timeout=30).read())
    assert health["status"] == "ok"
    assert health["num_trees"] == bst.num_trees()
    stats = json.loads(urllib.request.urlopen(base + "/stats",
                                              timeout=30).read())
    assert stats["counters"].get("serve_requests", 0) >= 3

    # malformed body and wrong feature width -> 400 (validated BEFORE
    # coalescing, so a bad request cannot poison a shared batch)
    for bad in (b"{nope", json.dumps({"rows": [[1.0, 2.0]]}).encode()):
        req = urllib.request.Request(base + "/predict", data=bad,
                                     headers={"Content-Type":
                                              "application/json"})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=30)
        assert err.value.code == 400

    srv.stop()
    srv.stop()                                # idempotent
    with pytest.raises(Exception):
        urllib.request.urlopen(base + "/healthz", timeout=1)


# ---------------------------------------------------------------------------
# CLI: task=serve wiring + streaming task=predict


def test_cli_streaming_predict_matches_api(tmp_path, monkeypatch):
    from lightgbm_tpu import cli

    bst, X = _train(n=1000, num_boost_round=4)
    model = tmp_path / "m.txt"
    bst.save_model(str(model))
    data = tmp_path / "rows.csv"
    np.savetxt(data, np.column_stack([np.zeros(len(X)), X]),
               delimiter=",", fmt="%.8g")
    out = tmp_path / "preds.txt"
    # force multiple chunks so the streaming writes actually interleave
    monkeypatch.setattr(lgb.Booster, "_PREDICT_CHUNK_ROWS", 256)
    rc = cli.main([f"task=predict", f"data={data}",
                   f"input_model={model}", f"output_result={out}"])
    assert rc == 0
    got = np.loadtxt(out)
    want = lgb.Booster(model_file=str(model)).predict(X)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_cli_serve_subcommand_token(monkeypatch):
    """``python -m lightgbm_tpu serve ...`` normalizes to task=serve and
    reaches run_serve with the parsed config."""
    from lightgbm_tpu import cli

    seen = {}

    def fake_serve(config, params):
        seen["task"] = config.task
        seen["port"] = config.serve_port
        seen["buckets"] = config.predict_buckets

    monkeypatch.setattr(cli, "run_serve", fake_serve)
    rc = cli.main(["serve", "input_model=nope.txt", "serve_port=12345",
                   "predict_buckets=16,64"])
    assert rc == 0
    assert seen == {"task": "serve", "port": 12345, "buckets": [16, 64]}
