"""Drift observatory acceptance (obs/drift.py + serve/lifecycle wiring).

The gates from the issue:

- the training-data fingerprint rides the model artifact through a full
  save -> load -> CompiledForest cycle, its baseline bin occupancy
  equals an exact offline rebin of the training matrix, and a model
  saved BEFORE fingerprints existed loads unchanged (section absent =
  quietly no fingerprint);
- the streaming serve collector is EXACT: under micro-batch coalescing
  and fleet dispatch, per-feature occupancy counts equal a
  single-replica offline rebin of the same rows, bit-for-bit, across
  the bucket ladder;
- chaos acceptance: ``skew_features`` shifts a known feature subset in
  the canary's served traffic — within a window, ``drift_psi`` for
  exactly those features crosses threshold, the lifecycle drift gate
  fires a named rollback listing them, and in-distribution primary
  traffic over the same windows never trips anything;
- ``drift=off`` is free: predictions bit-identical, ZERO new XLA
  programs (compile-ledger pinned), one attribute read on the hot path;
- ``train_delta`` warns (named, PSI vocabulary) on train/serve skew and
  stays silent on in-distribution refreshes;
- ``obs-report --drift`` renders the offender table from a collector
  stats dump.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import engine, obs
from lightgbm_tpu.obs import compile_ledger, prom, tracing
from lightgbm_tpu.obs.drift import (DataFingerprint, DriftCollector,
                                    compare_fingerprints, kl, linf,
                                    parse_model_fingerprint, psi)
from lightgbm_tpu.serve import Fleet, GuardrailPolicy, PromotionController
from lightgbm_tpu.serve.fleet import ModelManager
from lightgbm_tpu.serve.forest import CompiledForest
from lightgbm_tpu.testing import faults

pytestmark = [pytest.mark.serve, pytest.mark.drift]

BUCKETS = [16, 64]


@pytest.fixture
def tracer(tmp_path, monkeypatch):
    """Arm the process tracer (same pattern as tests/test_lifecycle.py)."""
    path = tmp_path / "trace_events.json"
    tracing.TRACER.reset()
    monkeypatch.setenv(tracing.ENV_PATH, str(path))
    tracing.TRACER.configure()
    yield path
    tracing.TRACER.disable()
    tracing.TRACER.reset()
    tracing.TRACER.path = None


def _train_and_save(tmp_path, name, rounds=3, lr=0.1, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(800, 6))
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                     "min_data_in_leaf": 20, "learning_rate": lr},
                    lgb.Dataset(X, label=y), num_boost_round=rounds)
    path = str(tmp_path / name)
    bst.save_model(path)
    return path, X


def _forest(path):
    return CompiledForest.from_booster(lgb.Booster(model_file=path),
                                       buckets=BUCKETS)


def _prom_counter(name):
    parsed = prom.parse_text(prom.render())
    vals = [v for n, labels, v in parsed["samples"]
            if n == f"lightgbm_tpu_{name}" and not labels]
    return vals[0] if vals else 0.0


def _prom_labeled(name, **want):
    parsed = prom.parse_text(prom.render())
    vals = [v for n, labels, v in parsed["samples"]
            if n == f"lightgbm_tpu_{name}" and labels == want]
    return vals[0] if vals else 0.0


def _replicas(fleet, model="primary"):
    with fleet._cond:
        rs = fleet._primary if model == "primary" else fleet._canary
        return list(rs.replicas) if rs is not None else []


# ---------------------------------------------------------------------------
# PSI / KL / L-inf math
# ---------------------------------------------------------------------------


def test_divergence_math_identity_and_known_values():
    a = np.array([50, 50], np.float64)
    assert psi(a, a) == 0.0
    assert kl(a, a) == 0.0
    assert linf(a, a) == 0.0
    b = np.array([90, 10], np.float64)
    # (0.9-0.5)ln(0.9/0.5) + (0.1-0.5)ln(0.1/0.5) = 0.8789...
    assert abs(psi(a, b) - 0.8789) < 0.01
    assert psi(a, b) == pytest.approx(psi(b, a))  # PSI is symmetric
    assert abs(linf(a, b) - 0.4) < 1e-6
    assert kl(a, b) > 0.0
    # smoothing keeps an empty expected bin finite, not inf
    assert np.isfinite(psi(np.array([100, 0]), np.array([50, 50])))


def test_coarsened_psi_measures_drift_not_sampling_noise():
    from lightgbm_tpu.obs.drift import coarsen
    rng = np.random.RandomState(0)
    base_vals = rng.normal(size=100_000)
    edges = np.quantile(base_vals, np.linspace(0, 1, 256)[1:-1])
    base = np.bincount(np.searchsorted(edges, base_vals), minlength=255)
    small = np.bincount(np.searchsorted(edges, rng.normal(size=400)),
                        minlength=255)
    # full-resolution PSI drowns 400 in-distribution rows in noise...
    assert psi(base, small) > 0.25
    # ...grouped PSI reads them as the non-event they are
    eg, ag = coarsen(base, small)
    assert eg.size <= 16 and eg.sum() == base.sum() and ag.sum() == 400
    assert psi(eg, ag) < 0.1
    # while a genuine shift still blows past the major-shift line
    moved = np.bincount(np.searchsorted(edges,
                                        rng.normal(size=400) + 6.0),
                        minlength=255)
    eg, ag = coarsen(base, moved)
    assert psi(eg, ag) > 0.25
    # short histograms pass through untouched
    eg, ag = coarsen([1, 2, 3], [3, 2, 1])
    assert np.array_equal(eg, [1, 2, 3]) and np.array_equal(ag, [3, 2, 1])


# ---------------------------------------------------------------------------
# fingerprint round-trip through the model artifact
# ---------------------------------------------------------------------------


def test_fingerprint_rides_model_file_and_baseline_is_exact(tmp_path):
    path, X = _train_and_save(tmp_path, "fp.txt")
    with open(path) as fh:
        txt = fh.read()
    fp = parse_model_fingerprint(txt)
    assert fp is not None and fp.version == 1
    assert fp.num_rows == X.shape[0]
    assert [f["name"] for f in fp.features] == \
        [f"Column_{i}" for i in range(6)]
    # baseline occupancy is an EXACT rebin of the training matrix with
    # the serving bin assignment (NaN->bin 0), not FindBin sample counts
    for feat, counts in zip(fp.features, fp.rebin_counts(X)):
        assert np.array_equal(feat["counts"], counts), feat["name"]
    # text round-trip is lossless where it matters
    fp2 = DataFingerprint.parse(fp.to_text())
    assert fp2.num_rows == fp.num_rows
    for a, b in zip(fp.features, fp2.features):
        assert a["name"] == b["name"]
        assert np.array_equal(a["counts"], b["counts"])
        assert a["missing_rate"] == pytest.approx(b["missing_rate"])
    # self-comparison is exactly zero drift
    rep = compare_fingerprints(fp, fp)
    assert rep["max_psi"] == 0.0
    assert rep["score_psi"] == 0.0
    # and the fingerprint reaches the serve artifact
    forest = _forest(path)
    assert forest.data_fingerprint is not None
    assert forest.info()["fingerprint"] is True
    assert forest.info()["drift"] is False


def test_pre_fingerprint_model_loads_unchanged(tmp_path):
    path, X = _train_and_save(tmp_path, "old.txt")
    with open(path) as fh:
        txt = fh.read()
    start = txt.index("\ndata_fingerprint\n")
    end = txt.index("end data_fingerprint\n") + len("end data_fingerprint\n")
    stripped = txt[:start + 1] + txt[end:]
    assert "data_fingerprint" not in stripped
    old = str(tmp_path / "stripped.txt")
    with open(old, "w") as fh:
        fh.write(stripped)
    assert parse_model_fingerprint(stripped) is None
    fa, fb = _forest(path), _forest(old)
    assert fb.data_fingerprint is None
    np.testing.assert_array_equal(fa.predict(X[:64]), fb.predict(X[:64]))


# ---------------------------------------------------------------------------
# collector exactness under coalescing + fleet dispatch
# ---------------------------------------------------------------------------


def test_collector_counts_equal_offline_rebin_exactly(tmp_path):
    path, _X = _train_and_save(tmp_path, "exact.txt")
    forest = _forest(path)
    fp = forest.data_fingerprint
    col = DriftCollector(fp, model="primary", window_s=3600.0,
                         start_thread=False)
    fleet = Fleet.build(forest, devices=[None], max_batch=64,
                        max_delay_s=0.002, warm=False,
                        watchdog_interval_s=0.0)
    try:
        for rep in _replicas(fleet):
            rep.forest._drift = col
        rng = np.random.RandomState(7)
        # odd sizes around the bucket ladder so the micro-batcher both
        # coalesces and splits; a sprinkle of NaN exercises missing-rate
        sizes = [1, 3, 17, 40, 64, 5, 64, 2, 31, 16]
        batches = []
        for i, n in enumerate(sizes):
            b = rng.normal(size=(n, 6)).astype(np.float32)
            if i % 3 == 0:
                b[0, i % 6] = np.nan
            batches.append(b)
        errors = []

        def client(rows):
            try:
                fleet.submit(rows, timeout=60.0)
            except Exception as exc:
                errors.append(repr(exc))
        threads = [threading.Thread(target=client, args=(b,))
                   for b in batches]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        assert not errors, errors
        for rep in _replicas(fleet):
            rep.forest._drift = None
        win = col.flush()
        assert win is not None
        total = sum(sizes)
        assert win["rows"] == total
        allrows = np.concatenate(batches, axis=0)
        expected = fp.rebin_counts(allrows)
        for feat, want in zip(fp.features, expected):
            got = win["features"][feat["name"]]["counts"]
            assert np.array_equal(got, want), feat["name"]
        # score histogram saw every raw margin too
        assert win["score_psi"] is not None
        st = col.stats()
        assert st["rows"] == total and st["dropped"] == 0
        assert st["windows"] == 1
    finally:
        col.close()
        fleet.close()


def test_collector_bounded_buffer_drops_and_counts():
    assert DataFingerprint.parse("") is None  # absent section -> None
    # hand-rolled tiny fingerprint via training helper
    X = np.linspace(0.0, 1.0, 64).reshape(-1, 1)
    y = (X[:, 0] > 0.5).astype(np.float64)
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "min_data_in_leaf": 5, "num_leaves": 4},
                    lgb.Dataset(X, label=y), num_boost_round=1)
    fp = parse_model_fingerprint(bst.model_to_string())
    col = DriftCollector(fp, model="tiny", window_s=3600.0, max_rows=8,
                         start_thread=False)
    assert col.offer(X[:8]) is True
    assert col.offer(X[:4]) is False       # would exceed the bound
    win = col.flush()
    assert win["rows"] == 8
    assert col.stats()["dropped"] == 4
    assert col.flush() is None             # empty window closes to None
    col.close()
    assert col.offer(X[:2]) is False       # closed collector refuses


# ---------------------------------------------------------------------------
# drift=off is free: bit-identity + flat compile ledger
# ---------------------------------------------------------------------------


def test_drift_off_bit_identical_zero_new_programs(tmp_path):
    path, X = _train_and_save(tmp_path, "free.txt")
    forest = _forest(path).warmup(max_bucket=64)
    assert forest._drift is None           # off is the default
    base = forest.predict(X[:64])
    n0 = len(compile_ledger.events())
    again = forest.predict(X[:64])
    np.testing.assert_array_equal(base, again)
    # turning the collector ON changes nothing downstream either:
    # same bits out, zero new programs — pure host-side observation
    col = DriftCollector(forest.data_fingerprint, model="pin",
                         window_s=3600.0, start_thread=False)
    forest._drift = col
    observed = forest.predict(X[:64])
    forest._drift = None
    np.testing.assert_array_equal(base, observed)
    assert len(compile_ledger.events()) == n0
    assert col.flush()["rows"] == 64
    col.close()


# ---------------------------------------------------------------------------
# chaos acceptance: skewed canary -> drift gate names the features
# ---------------------------------------------------------------------------


def test_skewed_canary_trips_drift_gate_names_offenders(tmp_path, tracer):
    path_a, _ = _train_and_save(tmp_path, "a.txt", rounds=3)
    path_b, _ = _train_and_save(tmp_path, "b.txt", rounds=4, lr=0.2)
    fa, fb = _forest(path_a), _forest(path_b)
    fleet = Fleet.build(fa, devices=[None], canary_forest=fb,
                        canary_weight=0.5, max_batch=64, max_delay_s=0.0,
                        warm=False, watchdog_interval_s=0.0)
    col_c = DriftCollector(fb.data_fingerprint, model="canary",
                           window_s=3600.0, threshold=0.25,
                           consecutive=2, start_thread=False)
    col_p = DriftCollector(fa.data_fingerprint, model="primary",
                           window_s=3600.0, threshold=0.25,
                           consecutive=2, start_thread=False)
    manager = ModelManager(fleet, state_file=str(tmp_path / "state.json"))
    policy = GuardrailPolicy(min_samples=10_000, latency_ratio=100.0,
                             error_rate=1.0, drift_threshold=0.25,
                             drift_source=col_c.stats)
    ctrl = PromotionController(fleet, manager, policy, window_s=30.0,
                               max_window_s=60.0, cooldown_s=60.0,
                               interval_s=3600.0)
    rng = np.random.RandomState(11)

    def serve_round(n_batches=24):
        for _ in range(n_batches):
            fleet.submit(rng.normal(size=(32, 6)).astype(np.float32),
                         timeout=60.0)
    try:
        for rep in _replicas(fleet, "canary"):
            rep.forest._drift = col_c
        for rep in _replicas(fleet, "primary"):
            rep.forest._drift = col_p
        ctrl.begin(path_b, 2)
        r0 = _prom_counter("lifecycle_rollback_drift")
        o0 = _prom_labeled("lifecycle_drift_offenders_total",
                           feature="Column_3")
        with faults.skew_features(fleet, [1, 3], 6.0, model="canary"):
            # two completed windows of skewed canary traffic — the gate
            # abstains on one (a noisy window never votes rollback)
            serve_round()
            assert col_c.flush() is not None
            assert col_p.flush() is not None
            ctrl.tick()
            assert ctrl.stats()["last_verdict"] is None or \
                ctrl.stats()["last_verdict"]["reason"] != "drift"
            serve_round()
            assert col_c.flush() is not None
            assert col_p.flush() is not None
            # exactly the skewed features are sustained offenders; the
            # in-distribution primary stream never trips anything
            assert col_c.sustained_offenders() == ["Column_1", "Column_3"]
            assert col_p.sustained_offenders() == []
            for w in col_p.stats()["trajectory"]:
                assert w["max_psi"] < 0.25, w
            ctrl.tick()
        verdict = ctrl.stats()["last_verdict"]
        assert verdict is not None and verdict["outcome"] == "rollback"
        assert verdict["reason"] == "drift"
        gate = verdict["verdict"]["gates"]["drift"]
        assert gate["armed"] and not gate["ok"]
        assert gate["offenders"] == ["Column_1", "Column_3"]
        assert gate["max_psi"] is not None and gate["max_psi"] > 0.25
        assert not fleet.has_canary()
        assert _prom_counter("lifecycle_rollback_drift") == r0 + 1
        assert _prom_labeled("lifecycle_drift_offenders_total",
                             feature="Column_3") == o0 + 1
        # published gauges name the moved columns for the scrape
        gauges = obs.snapshot()["gauges"]
        key = obs.labeled_name("drift_psi", model="canary",
                               feature="Column_1")
        assert float(gauges[key]) > 0.25
        # the verdict trace span carries the feature names
        spans = [e for e in tracing.TRACER.events()
                 if e.get("name") == "Serve::verdict"]
        assert any((e.get("args") or {}).get("reason") == "drift"
                   and (e.get("args") or {}).get("drift_features")
                   == ["Column_1", "Column_3"] for e in spans), spans
    finally:
        ctrl.close()
        col_c.close()
        col_p.close()
        fleet.close()


def test_drift_gate_abstains_without_windows_or_source():
    policy = GuardrailPolicy(min_samples=10_000, drift_threshold=0.25,
                             drift_source=lambda: None)
    verdict = policy.evaluate(policy.snapshot(), None)
    gate = verdict["gates"]["drift"]
    assert gate["armed"] is False and gate["ok"] is True
    assert verdict["decision"] != "fail"
    # a dying collector abstains loudly, never crashes the verdict
    e0 = _prom_counter("lifecycle_drift_source_errors_total")

    def boom():
        raise RuntimeError("collector died")
    policy = GuardrailPolicy(min_samples=10_000, drift_threshold=0.25,
                             drift_source=boom)
    verdict = policy.evaluate(policy.snapshot(), None)
    assert verdict["gates"]["drift"]["ok"] is True
    assert verdict["decision"] != "fail"
    assert _prom_counter("lifecycle_drift_source_errors_total") == e0 + 1


# ---------------------------------------------------------------------------
# serve wiring: /stats drift block
# ---------------------------------------------------------------------------


def test_server_stats_drift_block(tmp_path):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.serve.server import serve_from_config

    path, X = _train_and_save(tmp_path, "srv.txt")
    conf = {"task": "serve", "input_model": path, "serve_port": 0,
            "serve_state_file": str(tmp_path / "srv_state.json"),
            "serve_max_batch": 64, "predict_buckets": [16, 64],
            "serve_watchdog_ms": 0, "drift": "on",
            "drift_window": 3600.0, "drift_top_k": 3, "verbose": -1}
    srv = serve_from_config(Config(dict(conf))).start()
    try:
        assert srv._ready.wait(120.0)
        assert "primary" in srv.drift
        host, port = srv.address
        body = json.dumps({"rows": X[:5].tolist()}).encode()
        req = urllib.request.Request(
            f"http://{host}:{port}/predict", data=body,
            headers={"Content-Type": "application/json"})
        json.loads(urllib.request.urlopen(req, timeout=30).read())
        srv.drift["primary"].flush()
        stats = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/stats", timeout=30).read())
        blk = stats["drift"]
        assert blk["enabled"] is True
        assert blk["primary"]["rows"] >= 5
        assert blk["primary"]["windows"] >= 1
        assert blk["primary"]["last"]["top"], blk
    finally:
        srv.stop()


def test_drift_params_validated():
    from lightgbm_tpu.config import Config
    assert Config({"drift": "on"}).drift == "on"
    with pytest.raises(ValueError):
        Config({"drift": "sideways"})
    with pytest.raises(ValueError):
        Config({"drift_window": 0})
    with pytest.raises(ValueError):
        Config({"drift_top_k": 0})
    with pytest.raises(ValueError):
        Config({"lifecycle_drift_threshold": -0.1})


# ---------------------------------------------------------------------------
# bench_regress passthrough (informational `drift` BENCH block)
# ---------------------------------------------------------------------------


def test_bench_regress_passes_drift_block_through(tmp_path, capsys):
    import importlib.util
    import pathlib
    spec = importlib.util.spec_from_file_location(
        "bench_regress", pathlib.Path(__file__).resolve().parent.parent
        / "tools" / "bench_regress.py")
    bench_regress = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_regress)

    # candidate carries a drift block; the old baseline predates it —
    # informational passthrough, never a gate, old baselines unaffected
    base = {"metric": "m", "value": 10.0, "unit": "iters/sec"}
    cand = {"metric": "m", "value": 10.2, "unit": "iters/sec",
            "drift": {"windows": 1, "rows": 4096, "dropped": 0,
                      "overhead_s": 0.003, "max_psi": 0.01,
                      "score_psi": 0.004}}
    b, c = tmp_path / "b.json", tmp_path / "c.json"
    b.write_text(json.dumps(base))
    c.write_text(json.dumps(cand))
    rc = bench_regress.main(["--baseline", str(b), "--candidate", str(c),
                             "--threshold", "5"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    verdict = json.loads(out)
    assert rc == 0 and verdict["ok"]
    assert verdict["drift_candidate"]["max_psi"] == 0.01
    assert "drift_baseline" not in verdict


# ---------------------------------------------------------------------------
# train_delta skew check + obs-report --drift
# ---------------------------------------------------------------------------


def test_train_delta_warns_on_skew_silent_in_distribution(tmp_path):
    path, X = _train_and_save(tmp_path, "base.txt")
    rng = np.random.RandomState(3)
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
              "min_data_in_leaf": 20}

    def fresh(shift):
        Xf = rng.normal(size=(800, 6))
        Xf[:, 2] += shift
        yf = (Xf[:, 0] + 0.3 * Xf[:, 1] > 0).astype(np.float64)
        return lgb.Dataset(Xf, label=yf)

    w0 = _prom_counter("drift_skew_warnings_total")
    engine.train_delta(path, fresh(0.0), num_trees=2, params=params)
    assert _prom_counter("drift_skew_warnings_total") == w0  # in-dist: quiet
    engine.train_delta(path, fresh(8.0), num_trees=2, params=params)
    assert _prom_counter("drift_skew_warnings_total") == w0 + 1


def test_obs_report_drift_table(tmp_path):
    from lightgbm_tpu.obs.report import (drift_summary_from_files,
                                         render_drift_table)
    path, X = _train_and_save(tmp_path, "rep.txt")
    fp = _forest(path).data_fingerprint
    col = DriftCollector(fp, model="canary", window_s=3600.0,
                         threshold=0.25, start_thread=False)
    skewed = np.array(X[:400], copy=True)
    skewed[:, 4] += 9.0
    col.offer(skewed)
    col.flush()
    dump = tmp_path / "drift_stats.json"
    dump.write_text(json.dumps(col.stats()))
    col.close()
    rep = drift_summary_from_files([str(dump)], top_k=3)
    table = render_drift_table(rep)
    assert "canary" in table
    assert "Column_4" in table
    top = rep["models"]["canary"]["offenders"] \
        if "models" in rep else rep["canary"]["offenders"]
    assert top[0]["feature"] == "Column_4"
    assert top[0]["psi"] > 0.25
