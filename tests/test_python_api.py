"""Python API surface tests, modeled on the reference's
tests/python_package_test/{test_engine,test_sklearn,test_basic}.py:
train/cv/callbacks/early stopping/custom objectives/save-load/pickle and
the sklearn wrappers, against synthetic data with quality thresholds."""

import os
import pickle

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _binary_data(n=1200, f=10, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    logit = X[:, 0] + 0.8 * X[:, 1] * X[:, 2] - 0.5 * X[:, 3]
    y = (logit + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
    return X, y


def _regression_data(n=1200, f=8, seed=4):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 2.0 + np.sin(X[:, 1]) + 0.1 * rng.normal(size=n)
    return X, y


PARAMS = {"num_leaves": 15, "min_data_in_leaf": 10,
          "min_sum_hessian_in_leaf": 1e-3, "verbose": 1}


def test_train_binary_with_valid_and_early_stopping():
    X, y = _binary_data()
    ds = lgb.Dataset(X[:800], y[:800], params=PARAMS)
    vs = ds.create_valid(X[800:], y[800:])
    evals_result = {}
    booster = lgb.train({**PARAMS, "objective": "binary",
                         "metric": ["binary_logloss", "auc"]},
                        ds, num_boost_round=50, valid_sets=[vs],
                        early_stopping_rounds=10,
                        evals_result=evals_result, verbose_eval=False)
    assert "valid_0" in evals_result
    assert evals_result["valid_0"]["binary_logloss"][-1] < 0.5
    assert booster.current_iteration() >= 10
    pred = booster.predict(X[800:])
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y[800:], pred) > 0.85


def test_train_regression_quality():
    X, y = _regression_data()
    ds = lgb.Dataset(X[:800], y[:800], params=PARAMS)
    booster = lgb.train({**PARAMS, "objective": "regression"},
                        ds, num_boost_round=60, verbose_eval=False)
    pred = booster.predict(X[800:])
    mse = float(np.mean((pred - y[800:]) ** 2))
    assert mse < 0.6, mse


def test_continued_training_init_model(tmp_path):
    X, y = _regression_data()
    ds = lgb.Dataset(X, y, params=PARAMS)
    b1 = lgb.train({**PARAMS, "objective": "regression"}, ds,
                   num_boost_round=10, verbose_eval=False)
    path = str(tmp_path / "model.txt")
    b1.save_model(path)
    ds2 = lgb.Dataset(X, y, params=PARAMS)
    b2 = lgb.train({**PARAMS, "objective": "regression"}, ds2,
                   num_boost_round=10, init_model=path, verbose_eval=False)
    assert b2.num_trees() == 20
    mse1 = float(np.mean((b1.predict(X) - y) ** 2))
    mse2 = float(np.mean((b2.predict(X) - y) ** 2))
    assert mse2 < mse1


def test_custom_objective_fobj():
    X, y = _regression_data()
    ds = lgb.Dataset(X, y, params=PARAMS)

    def l2_fobj(preds, dataset):
        grad = preds - np.asarray(dataset.get_label())
        hess = np.ones_like(grad)
        return grad, hess

    booster = lgb.train({**PARAMS, "objective": "regression"}, ds,
                        num_boost_round=30, fobj=l2_fobj,
                        verbose_eval=False)
    mse = float(np.mean((booster.predict(X) - y) ** 2))
    assert mse < 0.6


def test_feval_and_record():
    X, y = _regression_data()
    ds = lgb.Dataset(X[:800], y[:800], params=PARAMS)
    vs = ds.create_valid(X[800:], y[800:])

    def mae(preds, dataset):
        return ("my_mae",
                float(np.mean(np.abs(preds
                                     - np.asarray(dataset.get_label())))),
                False)

    res = {}
    lgb.train({**PARAMS, "objective": "regression"}, ds,
              num_boost_round=15, valid_sets=[vs], feval=mae,
              evals_result=res, verbose_eval=False)
    assert "my_mae" in res["valid_0"]
    assert res["valid_0"]["my_mae"][-1] < res["valid_0"]["my_mae"][0]


def test_learning_rate_schedule():
    X, y = _regression_data(400)
    ds = lgb.Dataset(X, y, params=PARAMS)
    booster = lgb.train({**PARAMS, "objective": "regression"}, ds,
                        num_boost_round=5,
                        learning_rates=lambda i: 0.2 * (0.5 ** i),
                        verbose_eval=False)
    assert booster.num_trees() == 5


def test_cv():
    X, y = _binary_data(600)
    ds = lgb.Dataset(X, y, params=PARAMS)
    res = lgb.cv({**PARAMS, "objective": "binary",
                  "metric": "binary_logloss"}, ds,
                 num_boost_round=8, nfold=3, stratified=True,
                 verbose_eval=False)
    key = "valid binary_logloss-mean"
    assert key in res
    assert len(res[key]) == 8
    assert res[key][-1] < res[key][0]


def test_save_load_predict_equal(tmp_path):
    X, y = _binary_data(600)
    ds = lgb.Dataset(X, y, params=PARAMS)
    b = lgb.train({**PARAMS, "objective": "binary"}, ds,
                  num_boost_round=8, verbose_eval=False)
    p1 = b.predict(X)
    path = str(tmp_path / "m.txt")
    b.save_model(path)
    b2 = lgb.Booster(model_file=path)
    p2 = b2.predict(X)
    np.testing.assert_allclose(p1, p2, rtol=1e-5, atol=1e-6)
    # pickle round trip (reference test_engine.py:136-156)
    b3 = pickle.loads(pickle.dumps(b))
    np.testing.assert_allclose(p1, b3.predict(X), rtol=1e-5, atol=1e-6)


def test_dump_model_json():
    X, y = _binary_data(400)
    ds = lgb.Dataset(X, y, params=PARAMS)
    b = lgb.train({**PARAMS, "objective": "binary"}, ds,
                  num_boost_round=3, verbose_eval=False)
    dumped = b.dump_model()
    assert dumped["num_class"] == 1
    assert len(dumped["tree_info"]) == 3


def test_sklearn_regressor():
    X, y = _regression_data()
    model = lgb.LGBMRegressor(n_estimators=40, num_leaves=15,
                              min_child_samples=10, min_child_weight=1e-3)
    model.fit(X[:800], y[:800], verbose=False)
    mse = float(np.mean((model.predict(X[800:]) - y[800:]) ** 2))
    assert mse < 0.7
    assert model.feature_importances_.sum() > 0


def test_sklearn_classifier_binary_and_proba():
    X, y = _binary_data()
    ylab = np.where(y > 0, "pos", "neg")
    model = lgb.LGBMClassifier(n_estimators=30, num_leaves=15,
                               min_child_samples=10, min_child_weight=1e-3)
    model.fit(X[:800], ylab[:800], verbose=False)
    pred = model.predict(X[800:])
    acc = float(np.mean(pred == ylab[800:]))
    assert acc > 0.85, acc
    proba = model.predict_proba(X[800:])
    assert proba.shape == (400, 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-6)


def test_sklearn_classifier_multiclass():
    rng = np.random.RandomState(5)
    X = rng.normal(size=(900, 6))
    y = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)
    model = lgb.LGBMClassifier(n_estimators=20, num_leaves=15,
                               min_child_samples=10, min_child_weight=1e-3)
    model.fit(X[:700], y[:700], verbose=False)
    acc = float(np.mean(model.predict(X[700:]) == y[700:]))
    assert acc > 0.8, acc


def test_sklearn_custom_objective():
    X, y = _regression_data()

    def objective_ls(y_true, y_pred):
        grad = y_pred - y_true
        hess = np.ones_like(y_true)
        return grad, hess

    model = lgb.LGBMRegressor(n_estimators=30, num_leaves=15,
                              objective=objective_ls,
                              min_child_samples=10, min_child_weight=1e-3)
    model.fit(X[:800], y[:800], verbose=False)
    mse = float(np.mean((model.predict(X[800:]) - y[800:]) ** 2))
    assert mse < 0.8


def test_sklearn_ranker():
    rng = np.random.RandomState(6)
    n_q, q_size = 40, 20
    n = n_q * q_size
    X = rng.normal(size=(n, 5))
    rel = np.clip((X[:, 0] + 0.5 * rng.normal(size=n)) > 0.5, 0, 4)
    y = rel.astype(int)
    group = np.full(n_q, q_size)
    model = lgb.LGBMRanker(n_estimators=10, num_leaves=7,
                           min_child_samples=5, min_child_weight=1e-3)
    model.fit(X, y, group=group, verbose=False)
    assert model.booster_.num_trees() == 10


def test_sklearn_grid_search_compatible():
    from sklearn.model_selection import GridSearchCV
    X, y = _regression_data(400)
    grid = GridSearchCV(
        lgb.LGBMRegressor(min_child_samples=10, min_child_weight=1e-3),
        {"n_estimators": [5, 8], "num_leaves": [7, 15]}, cv=2)
    grid.fit(X, y)
    assert grid.best_params_["n_estimators"] == 8


def test_pandas_dataframe_with_categoricals():
    pd = pytest.importorskip("pandas")
    X, y = _regression_data(600, f=4)
    df = pd.DataFrame(X, columns=["a", "b", "c", "d"])
    df["cat"] = pd.Categorical(
        np.random.RandomState(0).choice(["u", "v", "w"], size=600))
    y = y + (df["cat"] == "u") * 2.0
    ds = lgb.Dataset(df, y, params=PARAMS)
    booster = lgb.train({**PARAMS, "objective": "regression"}, ds,
                        num_boost_round=20, verbose_eval=False)
    assert booster.feature_name() == ["a", "b", "c", "d", "cat"]
    pred = booster.predict(df)
    assert np.isfinite(pred).all()
