"""Tree-grower correctness: against a brute-force host-side oracle that
re-states the reference's leaf-wise algorithm (histogram + right-to-left
scan + best-leaf argmax) in plain NumPy."""

import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.grow import GrowParams, grow_tree
from lightgbm_tpu.ops.split import SplitParams, find_best_split
from lightgbm_tpu.ops.histogram import build_root_histogram


def _np_hist(bins, g, h, w, B):
    F, N = bins.shape
    out = np.zeros((F, B, 3))
    for f in range(F):
        for i in range(N):
            b = bins[f, i]
            out[f, b, 0] += g[i]
            out[f, b, 1] += h[i]
            out[f, b, 2] += w[i]
    return out


def _np_best_split(hist, tg, th, tc, num_bin, is_cat, p: SplitParams):
    """Reference scan transcription (feature_histogram.hpp:75-187)."""
    F, B, _ = hist.shape
    best = dict(gain=-np.inf, feat=-1, t=-1, lg=0.0, lh=0.0, lc=0.0)
    gain_shift = _gain(tg, th, p)
    for f in range(F):
        nb = num_bin[f]
        if nb <= 1:
            continue
        if is_cat[f]:
            cands = [(t, hist[f, t, 0], hist[f, t, 1], hist[f, t, 2])
                     for t in range(nb - 1, -1, -1)]
        else:
            cum = np.cumsum(hist[f, :, :], axis=0)
            cands = [(t, cum[t, 0], cum[t, 1], cum[t, 2])
                     for t in range(nb - 2, -1, -1)]
        for t, lg, lh, lc in cands:
            rg, rh, rc = tg - lg, th - lh, tc - lc
            if lc < p.min_data_in_leaf or rc < p.min_data_in_leaf:
                continue
            if lh < p.min_sum_hessian_in_leaf or rh < p.min_sum_hessian_in_leaf:
                continue
            cur = _gain(lg, lh, p) + _gain(rg, rh, p)
            if cur <= gain_shift + p.min_gain_to_split:
                continue
            if cur > best["gain"] + gain_shift or (
                    np.isclose(cur - gain_shift, best["gain"]) and f < best["feat"]):
                # strictly-greater within a feature handled by scan order
                if cur - gain_shift > best["gain"]:
                    best = dict(gain=cur - gain_shift, feat=f, t=t,
                                lg=lg, lh=lh, lc=lc)
    return best


def _gain(G, H, p):
    reg = max(abs(G) - p.lambda_l1, 0.0)
    return reg * reg / (H + p.lambda_l2)


def _make_data(seed=0, n=400, f=5, B=16):
    rng = np.random.RandomState(seed)
    bins = rng.randint(0, B, size=(f, n)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = np.abs(rng.normal(size=n)).astype(np.float32) + 0.1
    return bins, g, h


def test_histogram_matches_numpy():
    bins, g, h = _make_data()
    w = np.ones_like(g)
    hist = np.asarray(build_root_histogram(jnp.asarray(bins), jnp.asarray(g),
                                           jnp.asarray(h), jnp.asarray(w), 16))
    expected = _np_hist(bins, g, h, w, 16)
    np.testing.assert_allclose(hist, expected, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("l1,l2,min_data,min_hess", [
    (0.0, 0.0, 5, 1e-3), (0.5, 1.0, 10, 0.5)])
def test_find_best_split_matches_oracle(seed, l1, l2, min_data, min_hess):
    bins, g, h = _make_data(seed=seed, B=16)
    F = bins.shape[0]
    w = np.ones_like(g)
    hist = _np_hist(bins, g, h, w, 16)
    p = SplitParams(min_data_in_leaf=min_data, min_sum_hessian_in_leaf=min_hess,
                    lambda_l1=l1, lambda_l2=l2, min_gain_to_split=0.0)
    num_bin = np.full(F, 16, np.int32)
    is_cat = np.zeros(F, bool)
    tg, th, tc = g.sum(), h.sum(), float(len(g))
    oracle = _np_best_split(hist, tg, th, tc, num_bin, is_cat, p)

    got = find_best_split(jnp.asarray(hist, jnp.float32), jnp.float32(tg),
                          jnp.float32(th), jnp.float32(tc),
                          jnp.asarray(num_bin), jnp.asarray(is_cat),
                          jnp.ones(F, bool), jnp.asarray(True), p)
    assert int(got.feature) == oracle["feat"]
    assert int(got.threshold) == oracle["t"]
    np.testing.assert_allclose(float(got.gain), oracle["gain"], rtol=1e-4)
    np.testing.assert_allclose(float(got.left_count), oracle["lc"], rtol=1e-5)


def test_find_best_split_categorical():
    rng = np.random.RandomState(3)
    n, B = 600, 8
    bins = rng.randint(0, B, size=(1, n)).astype(np.int32)
    # category 5 has clearly different gradient
    g = np.where(bins[0] == 5, -2.0, 0.5).astype(np.float32) \
        + rng.normal(scale=0.1, size=n).astype(np.float32)
    h = np.ones(n, np.float32)
    p = SplitParams(min_data_in_leaf=5, min_sum_hessian_in_leaf=1e-3)
    hist = _np_hist(bins, g, h, np.ones(n), B)
    got = find_best_split(jnp.asarray(hist, jnp.float32),
                          jnp.float32(g.sum()), jnp.float32(h.sum()),
                          jnp.float32(n), jnp.asarray([B], np.int32),
                          jnp.asarray([True]), jnp.asarray([True]),
                          jnp.asarray(True), p)
    assert int(got.threshold) == 5


def test_grow_tree_structure_and_fit():
    # single clean split on feature 0 at bin <= 7
    rng = np.random.RandomState(0)
    n = 1000
    bins = np.stack([rng.randint(0, 16, n), rng.randint(0, 16, n)]).astype(np.int32)
    target = np.where(bins[0] <= 7, 2.0, -1.0)
    score = np.zeros(n)
    g = (score - target).astype(np.float32)  # L2 gradients
    h = np.ones(n, np.float32)
    params = GrowParams(num_leaves=2, max_bin=16, min_data_in_leaf=5,
                        min_sum_hessian_in_leaf=1e-3)
    tree, leaf_id, delta = grow_tree(
        jnp.asarray(bins), jnp.asarray([16, 16], np.int32),
        jnp.zeros(2, bool), jnp.ones(2, bool),
        jnp.asarray(g), jnp.asarray(h), jnp.ones(n, jnp.float32),
        jnp.float32(1.0), params)
    assert int(tree.num_leaves) == 2
    assert int(tree.split_feature[0]) == 0
    assert int(tree.split_bin[0]) == 7
    # leaf outputs approximate targets (lr=1, L2 loss, one split)
    lv = np.asarray(tree.leaf_value)
    assert abs(lv[0] - 2.0) < 1e-3 and abs(lv[1] + 1.0) < 1e-3
    # partition + delta agree
    np.testing.assert_array_equal(np.asarray(leaf_id), np.where(bins[0] <= 7, 0, 1))
    np.testing.assert_allclose(np.asarray(delta), lv[np.asarray(leaf_id)], rtol=1e-6)


def test_grow_tree_depth_guard():
    bins, g, h = _make_data(n=2000, f=4, B=32)
    params = GrowParams(num_leaves=31, max_bin=32, min_data_in_leaf=5,
                        min_sum_hessian_in_leaf=1e-3, max_depth=2)
    tree, _, _ = grow_tree(
        jnp.asarray(bins), jnp.full(4, 32, np.int32),
        jnp.zeros(4, bool), jnp.ones(4, bool),
        jnp.asarray(g), jnp.asarray(h), jnp.ones(2000, jnp.float32),
        jnp.float32(0.1), params)
    # max_depth=2 means at most 4 leaves
    assert int(tree.num_leaves) <= 4
    depths = np.asarray(tree.leaf_depth)[:int(tree.num_leaves)]
    assert depths.max() <= 2


def test_grow_tree_stops_without_gain():
    # constant gradients and huge min_gain: no split possible
    n = 300
    bins = np.zeros((2, n), dtype=np.int32)  # all same bin -> no candidates
    g = np.ones(n, np.float32)
    h = np.ones(n, np.float32)
    params = GrowParams(num_leaves=15, max_bin=8, min_data_in_leaf=5,
                        min_sum_hessian_in_leaf=1e-3)
    tree, leaf_id, delta = grow_tree(
        jnp.asarray(bins), jnp.asarray([8, 8], np.int32),
        jnp.zeros(2, bool), jnp.ones(2, bool),
        jnp.asarray(g), jnp.asarray(h), jnp.ones(n, jnp.float32),
        jnp.float32(1.0), params)
    assert int(tree.num_leaves) == 1
    np.testing.assert_array_equal(np.asarray(leaf_id), 0)


def test_grow_tree_matches_oracle_sequence():
    """Full leaf-wise growth vs a host oracle that replays the same policy."""
    rng = np.random.RandomState(7)
    n, F, B, L = 800, 3, 8, 6
    bins = rng.randint(0, B, size=(F, n)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = np.ones(n, np.float32)
    p = SplitParams(min_data_in_leaf=10, min_sum_hessian_in_leaf=1e-3)
    params = GrowParams(num_leaves=L, max_bin=B, min_data_in_leaf=10,
                        min_sum_hessian_in_leaf=1e-3)

    tree, leaf_id, _ = grow_tree(
        jnp.asarray(bins), jnp.full(F, B, np.int32), jnp.zeros(F, bool),
        jnp.ones(F, bool), jnp.asarray(g), jnp.asarray(h),
        jnp.ones(n, jnp.float32), jnp.float32(1.0), params)

    # Oracle: leaf-wise growth with per-leaf exhaustive search.
    leaf = np.zeros(n, dtype=np.int64)
    num_leaves = 1
    num_bin = np.full(F, B, np.int32)
    is_cat = np.zeros(F, bool)
    splits = []
    for _step in range(L - 1):
        best = None
        for l in range(num_leaves):
            m = leaf == l
            if m.sum() == 0:
                continue
            hist = _np_hist(bins[:, m], g[m], h[m], np.ones(m.sum()), B)
            cand = _np_best_split(hist, g[m].sum(), h[m].sum(), m.sum(),
                                  num_bin, is_cat, p)
            if cand["feat"] >= 0 and (best is None or cand["gain"] > best[1]["gain"]):
                best = (l, cand)
        if best is None:
            break
        l, cand = best
        splits.append((l, cand["feat"], cand["t"]))
        m = (leaf == l) & (bins[cand["feat"]] > cand["t"])
        leaf[m] = num_leaves
        num_leaves += 1

    assert int(tree.num_leaves) == num_leaves
    got_splits = [(int(f), int(t)) for f, t in
                  zip(np.asarray(tree.split_feature)[:num_leaves - 1],
                      np.asarray(tree.split_bin)[:num_leaves - 1])]
    assert got_splits == [(f, t) for _, f, t in splits]
    np.testing.assert_array_equal(np.asarray(leaf_id), leaf)
