"""Distributed rank-failure chaos suite (marker ``dist_chaos``): real
2-process ``jax.distributed``/gloo workers (dist_chaos_worker.py, the
multiproc_worker.py pattern) driven through the rank-level fault
injectors.  Pins the acceptance bar of the distributed fault-tolerance
story end to end:

- a rank SIGKILLed mid-training aborts the SURVIVOR within the
  configured collective timeout (no hang — every launch is bounded by
  this test's own subprocess watchdog, far below the tier-1 budget)
  with the distinct launcher-facing exit code;
- a restarted pod resumes from the coordinated snapshot via cross-rank
  consensus and the final model bit-matches an uninterrupted run;
- a silently corrupted rank is caught by the consistency check:
  fail_fast names the rank and field, resync converges back to the
  clean trajectory (asserted inside the workers)."""

import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from test_multiprocess import kill_worker_tree

pytestmark = pytest.mark.dist_chaos

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

# the test's own watchdog per worker pair: far below the 870 s tier-1
# budget even across every launch in this file, yet roomy enough for
# two cold jax imports + the distributed grow compile on CPU
LAUNCH_TIMEOUT_S = 150

DISTRIBUTED_ABORT_EXIT_CODE = 75     # parallel/watchdog.py (pinned here
# as a literal: launchers key restarts on the NUMBER, not the symbol)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(scenario, workdir, tag):
    p0, p1 = _free_port(), _free_port()
    mlist = workdir / f"mlist_{tag}.txt"
    mlist.write_text(f"127.0.0.1 {p0}\n127.0.0.1 {p1}\n")
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)          # conftest's 8-device flag
        env["LIGHTGBM_TPU_PROCESS_ID"] = str(pid)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(HERE, "dist_chaos_worker.py"),
             scenario, str(mlist), str(workdir)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, start_new_session=True))
    logs, rcs = [], []
    deadline = time.monotonic() + LAUNCH_TIMEOUT_S   # one budget for the
    # whole pair, not per worker — a hung pair costs 150 s, not 300
    for p in procs:
        try:
            stdout, _ = p.communicate(
                timeout=max(1.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            kill_worker_tree(p)
            stdout, _ = p.communicate()
            stdout += "\n<<TIMEOUT: killed by the test watchdog>>"
        logs.append(stdout)
        rcs.append(p.returncode)
    return rcs, logs


def _launch_expect(scenario, workdir, expected_rcs, attempts=2):
    # free-port discovery is inherently racy (the port is released
    # before the coordinator binds it): retry once before failing
    for attempt in range(attempts):
        rcs, logs = _launch(scenario, workdir, f"{scenario}{attempt}")
        if rcs == list(expected_rcs):
            return rcs, logs
    raise AssertionError(
        f"{scenario}: worker exit codes {rcs}, expected "
        f"{list(expected_rcs)}\n--- worker 0 ---\n{logs[0]}\n"
        f"--- worker 1 ---\n{logs[1]}")


def _verdicts(workdir, scenario, tag):
    out = []
    for pid in range(2):
        path = workdir / f"verdict_{scenario}_{pid}.txt"
        assert path.exists(), f"rank {pid} wrote no {scenario} verdict"
        text = path.read_text()
        assert text.startswith(tag), text[:200]
        out.append(text)
    # both controllers materialized the identical model
    assert out[0] == out[1]
    return out


def test_rank_kill_aborts_survivor_then_pod_resumes_bit_exact(tmp_path):
    # -- phase 1: rank 1 SIGKILLed mid-training -------------------------
    rcs, logs = _launch_expect(
        "kill", tmp_path,
        [DISTRIBUTED_ABORT_EXIT_CODE, -signal.SIGKILL])
    assert "UNEXPECTED_COMPLETION" not in logs[0]
    # the survivor's abort is a NAMED event: phase, suspect rank, age
    assert "distributed training aborted" in logs[0]
    assert "Comm::grow" in logs[0]
    assert "rank 1" in logs[0]
    # rank 0 checkpointed every completed round before the abort
    snaps = sorted(os.listdir(tmp_path / "snaps"))
    assert snaps, "no snapshots written before the crash"
    # -- phase 2: both ranks restart on FRESH ports ---------------------
    # consensus resume + bit-match vs an uninterrupted run is asserted
    # inside the workers (dist_chaos_worker.py scenario "resume")
    _launch_expect("resume", tmp_path, [0, 0])
    _verdicts(tmp_path, "resume", "RESUME_OK")


def test_desync_detected_fail_fast_and_resync_heals(tmp_path):
    _launch_expect("desync", tmp_path, [0, 0])
    _verdicts(tmp_path, "desync", "DESYNC_OK")
