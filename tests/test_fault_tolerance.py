"""Fault tolerance end-to-end (docs/FAULT_TOLERANCE.md): crash-safe
snapshot/resume bit-exactness (GBDT/DART/GOSS, bagging + feature RNG +
eval history), corrupt-snapshot fallback, torn-write atomicity, NaN/Inf
containment policies, and hardened multihost bring-up — each failure
injected by ``lightgbm_tpu.testing.faults``, never simulated by poking
internals the real failure would not touch."""

import numpy as np
import pytest

from lightgbm_tpu import Booster, Dataset, LightGBMError, obs
from lightgbm_tpu import train as lgb_train
from lightgbm_tpu.config import Config
from lightgbm_tpu.snapshot import (list_snapshots, load_latest_snapshot,
                                   read_snapshot, snapshot_path,
                                   write_snapshot)
from lightgbm_tpu.testing import faults

pytestmark = pytest.mark.faults


def _data(seed=7, n=200, f=5):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    logit = 1.3 * X[:, 0] - X[:, 1] + 0.6 * X[:, 2] * X[:, 3]
    y = (logit + rng.normal(scale=0.4, size=n) > 0).astype(np.float64)
    return X, y


# bagging + feature_fraction on purpose: resume must restore BOTH RNG
# streams mid-sequence for bit-exactness
BASE = {"objective": "binary", "metric": ["binary_logloss"],
        "num_leaves": 7, "min_data_in_leaf": 5, "max_bin": 31,
        "learning_rate": 0.2, "bagging_fraction": 0.7, "bagging_freq": 1,
        "feature_fraction": 0.8}

N_ROUNDS = 6
CRASH_AT = 3        # iteration index whose after-callback dies
SNAP_FREQ = 2       # so the newest snapshot at crash time holds 2 rounds


class _Crash(RuntimeError):
    pass


def _crash_after(iteration):
    def cb(env):
        if env.iteration == iteration:
            raise _Crash(f"injected crash at iteration {iteration}")
    cb.order = 99
    return cb


def _train(params, num_rounds=N_ROUNDS, seed=7, callbacks=None):
    X, y = _data(seed)
    Xv, yv = _data(seed + 1)
    ds = Dataset(X, label=y)
    ev = {}
    bst = lgb_train(dict(params), ds, num_boost_round=num_rounds,
                    valid_sets=[ds.create_valid(Xv, yv)],
                    valid_names=["v0"], evals_result=ev,
                    verbose_eval=False, callbacks=callbacks)
    return bst, ev


def _crash_then_resume(params, tmp_path, num_rounds=N_ROUNDS,
                       crash_at=CRASH_AT):
    snap = {**params, "snapshot_dir": str(tmp_path),
            "snapshot_freq": SNAP_FREQ}
    with pytest.raises(_Crash):
        _train(snap, num_rounds, callbacks=[_crash_after(crash_at)])
    return _train(snap, num_rounds)


def _assert_bit_identical(a, ev_a, b, ev_b):
    assert a.model_to_string() == b.model_to_string()
    Xq, _ = _data(seed=99)
    assert np.array_equal(a.predict(Xq), b.predict(Xq))
    assert ev_a == ev_b


@pytest.mark.parametrize("extra", [
    {},                                                       # plain gbdt
    {"boosting_type": "dart", "drop_rate": 0.5,
     "skip_drop": 0.25},                                      # dart state
])
def test_resume_bit_exact(tmp_path, extra):
    params = {**BASE, **extra}
    plain, ev_plain = _train(params)
    resumed, ev_resumed = _crash_then_resume(params, tmp_path)
    _assert_bit_identical(plain, ev_plain, resumed, ev_resumed)


def test_resume_bit_exact_goss(tmp_path):
    # high lr so the 1/lr warmup ends mid-run and the sampling key is
    # live (and therefore snapshot-restored) across the crash boundary
    params = {"objective": "binary", "metric": ["binary_logloss"],
              "num_leaves": 7, "min_data_in_leaf": 5, "max_bin": 31,
              "learning_rate": 0.5, "boosting_type": "goss",
              "top_rate": 0.3, "other_rate": 0.2}
    plain, ev_plain = _train(params)
    resumed, ev_resumed = _crash_then_resume(params, tmp_path)
    _assert_bit_identical(plain, ev_plain, resumed, ev_resumed)


def test_corrupt_newest_snapshot_falls_back(tmp_path):
    plain, ev_plain = _train(BASE)
    snap = {**BASE, "snapshot_dir": str(tmp_path),
            "snapshot_freq": SNAP_FREQ}
    # crash during iteration 4: rounds 2 AND 4 are both on disk
    with pytest.raises(_Crash):
        _train(snap, callbacks=[_crash_after(4)])
    # torn storage on the newest file: resume must fall back to round 2
    # and STILL converge to the bit-identical model
    rounds, newest = list_snapshots(str(tmp_path))[0]
    assert rounds == 4
    faults.truncate_file(newest)
    path, state = load_latest_snapshot(str(tmp_path))
    assert path.endswith(f"snapshot_{2:010d}.bin")
    assert state["rounds_done"] == 2
    resumed, ev_resumed = _train(snap)
    _assert_bit_identical(plain, ev_plain, resumed, ev_resumed)


def test_torn_write_never_damages_previous(tmp_path):
    plain, ev_plain = _train(BASE)
    snap = {**BASE, "snapshot_dir": str(tmp_path),
            "snapshot_freq": SNAP_FREQ}
    _train(snap, num_rounds=2)               # a good round-2 snapshot
    good = read_snapshot(snapshot_path(str(tmp_path), 2))
    assert good is not None
    with faults.torn_snapshot_write(after_bytes=64):
        with pytest.raises(faults.InjectedCrash):
            _train(snap)                     # resumes, dies at round 4
    # the torn write left no committed file and the previous snapshot
    # is byte-for-byte intact
    assert [r for r, _ in list_snapshots(str(tmp_path))] == [2]
    path, state = load_latest_snapshot(str(tmp_path))
    assert state["rounds_done"] == 2
    resumed, ev_resumed = _train(snap)
    _assert_bit_identical(plain, ev_plain, resumed, ev_resumed)


def test_snapshot_cli_string_params_and_noop_resume(tmp_path):
    # CLI-style params arrive as strings; a re-run whose snapshot already
    # holds num_boost_round rounds trains nothing and returns the model
    snap = {**BASE, "snapshot_dir": str(tmp_path), "snapshot_freq": "2"}
    bst, _ = _train(snap, num_rounds=4)
    assert [r for r, _ in list_snapshots(str(tmp_path))] == [4, 2]
    bst2, _ = _train(snap, num_rounds=4)
    assert bst2.num_trees() == bst.num_trees()
    assert bst2.model_to_string() == bst.model_to_string()


def test_snapshot_file_roundtrip_and_corruption(tmp_path):
    state = {"booster": {"x": np.arange(5)}, "rounds_done": 3}
    path = snapshot_path(str(tmp_path), 3)
    write_snapshot(path, state)
    back = read_snapshot(path)
    assert np.array_equal(back["booster"]["x"], np.arange(5))
    faults.flip_byte(path)                   # silent bit rot
    assert read_snapshot(path) is None
    write_snapshot(path, state)
    faults.truncate_file(path)               # torn tail
    assert read_snapshot(path) is None
    junk = tmp_path / f"snapshot_{1:010d}.bin"
    junk.write_bytes(b"not a snapshot")      # wrong magic
    assert read_snapshot(str(junk)) is None
    assert load_latest_snapshot(str(tmp_path)) is None


def test_snapshot_config_mismatch_refuses(tmp_path):
    snap = {**BASE, "snapshot_dir": str(tmp_path), "snapshot_freq": 2}
    _train(snap, num_rounds=2)
    with pytest.raises(LightGBMError) as ei:
        _train({**snap, "num_leaves": 15}, num_rounds=4)
    assert "mismatch" in str(ei.value)


# ---------------------------------------------------------------------------
# NaN/Inf containment
# ---------------------------------------------------------------------------

def test_nan_fail_fast_names_iteration_and_objective():
    X, y = _data()
    ds = Dataset(X, label=y)
    calls = {"n": 0}

    def bad_fobj(preds, dset):
        calls["n"] += 1
        grad = preds - np.asarray(dset.get_label())
        if calls["n"] == 3:
            grad = np.full_like(grad, np.nan)
        return grad, np.ones_like(grad)

    with pytest.raises(LightGBMError) as ei:
        lgb_train({"objective": "binary", "num_leaves": 7,
                   "min_data_in_leaf": 5, "max_bin": 31,
                   "nan_policy": "fail_fast"},
                  ds, num_boost_round=6, fobj=bad_fobj,
                  verbose_eval=False)
    msg = str(ei.value)
    assert "boosting iteration 2" in msg
    assert "gradients/hessians" in msg


def test_nan_skip_tree_completes_and_records(tmp_path):
    from lightgbm_tpu.obs import EventRecorder, read_events
    X, y = _data()
    ds = Dataset(X, label=y)
    bst = Booster(params={**BASE, "nan_policy": "skip_tree"},
                  train_set=ds)
    events = tmp_path / "events.jsonl"
    rec = EventRecorder(str(events))
    bst.set_event_recorder(rec)
    dropped0 = obs.get_counter("nan_iterations_dropped")
    with faults.poison_gradients(bst, at_iteration=2):
        for _ in range(6):
            bst.update()
    n_trees = bst.num_trees()                # flushes the pipeline
    rec.close()
    # 6 update calls, one poisoned round dropped, its index re-trained
    assert n_trees == 5
    assert bst.current_iteration() == 5
    assert obs.get_counter("nan_iterations_dropped") == dropped0 + 1
    recs = read_events(str(events))
    hit = [e for e in recs if e.get("nan_poisoned")]
    assert hit and hit[0]["iter"] == 2
    assert hit[0]["nan_policy"] == "skip_tree"
    assert hit[0]["nan_poisoned"] == "gradients/hessians"
    assert np.isfinite(bst.predict(X)).all()


def test_degenerate_objective_all_rounds_skipped():
    # persistent poison: every remaining round drops, training still
    # terminates with the pre-fault model intact (graceful degradation)
    X, y = _data()
    ds = Dataset(X, label=y)
    bst = Booster(params={**BASE, "nan_policy": "skip_tree"},
                  train_set=ds)
    with faults.poison_gradients(bst, at_iteration=2, times=10 ** 6):
        for _ in range(5):
            bst.update()
    assert bst.num_trees() == 2
    assert np.isfinite(bst.predict(X)).all()


# ---------------------------------------------------------------------------
# multihost bring-up hardening
# ---------------------------------------------------------------------------

def _mlist(tmp_path):
    p = tmp_path / "mlist.txt"
    p.write_text("127.0.0.1 12400\n10.255.255.1 12401\n")
    return str(p)


def _dist_cfg(tmp_path, **over):
    return Config({"task": "train", "objective": "binary",
                   "num_machines": 2, "tree_learner": "data",
                   "machine_list_file": _mlist(tmp_path),
                   "distributed_init_backoff": 0.0, **over})


def test_distributed_init_retries_until_success(tmp_path):
    from lightgbm_tpu.parallel.multihost import maybe_initialize_distributed
    cfg = _dist_cfg(tmp_path, distributed_init_retries=3)
    with faults.fail_distributed_init(times=2) as stats:
        assert maybe_initialize_distributed(cfg) is True
    assert stats["failed"] == 2
    assert stats["succeeded"] == 1
    assert stats["kwargs"][-1] == {
        "coordinator_address": "127.0.0.1:12400",
        "num_processes": 2, "process_id": 0}


def test_distributed_init_exhaustion_diagnostic(tmp_path):
    from lightgbm_tpu.parallel.multihost import maybe_initialize_distributed
    cfg = _dist_cfg(tmp_path, distributed_init_retries=1)
    with faults.fail_distributed_init(times=10):
        with pytest.raises(LightGBMError) as ei:
            maybe_initialize_distributed(cfg)
    msg = str(ei.value)
    assert "127.0.0.1:12400" in msg
    assert "2 attempt(s)" in msg
    assert "injected coordinator connect failure" in msg


def test_process_id_env_out_of_range(monkeypatch):
    from lightgbm_tpu.parallel.multihost import find_process_id
    machines = [("a", 1), ("b", 2), ("c", 3)]
    monkeypatch.setenv("LIGHTGBM_TPU_PROCESS_ID", "7")
    with pytest.raises(LightGBMError) as ei:
        find_process_id(machines)
    assert "out of range" in str(ei.value)
    assert "0..2" in str(ei.value)
    monkeypatch.setenv("LIGHTGBM_TPU_PROCESS_ID", "-1")
    with pytest.raises(LightGBMError):
        find_process_id(machines)


# ---------------------------------------------------------------------------
# late-attached validation set memory budget
# ---------------------------------------------------------------------------

def test_valid_set_attachment_respects_memory_budget(monkeypatch):
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.models.gbdt import GBDT, estimate_train_memory
    X, y = _data(n=400)
    Xv, yv = _data(seed=9, n=400)
    ds = BinnedDataset.from_matrix(X, y, max_bin=31, min_data_in_leaf=5)
    cfg = Config({"objective": "binary", "num_leaves": 7, "max_bin": 31,
                  "min_data_in_leaf": 5})
    est = estimate_train_memory(ds.num_data, ds.num_features,
                                cfg.num_leaves, cfg.max_bin, 1,
                                bin_itemsize=ds.bins.dtype.itemsize)
    # training alone fits; training + the valid set does not
    monkeypatch.setenv("LGBT_DEVICE_MEMORY_BYTES", str(est["total"] + 512))
    gb = GBDT(cfg, ds)
    with pytest.raises(LightGBMError) as ei:
        gb.add_valid_dataset(ds.create_valid(Xv, yv))
    msg = str(ei.value)
    assert "validation set" in msg
    assert "budget" in msg
