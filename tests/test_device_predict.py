"""Device batch-prediction path (GBDT._predict_raw_device): must agree
bit-for-bit in routing with the host per-tree walk — rows are binned with
the training mappers in f64 on the host, so the integer bin compare
reproduces tree.h:197-227's double threshold compare exactly."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _train(n=6000, num_class=1, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, 6))
    X[:, 3] = np.round(X[:, 3] * 4) / 4        # heavy ties -> boundary values
    if num_class > 1:
        y = (np.digitize(X[:, 0], [-0.5, 0.5])).astype(np.float64)
        params = {"objective": "multiclass", "num_class": num_class}
    else:
        y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
        params = {"objective": "binary"}
    params.update({"num_leaves": 15, "verbose": -1, "min_data_in_leaf": 20})
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(params, ds, num_boost_round=12)
    return bst, X


@pytest.mark.parametrize("num_class", [1, 3])
def test_device_predict_matches_host_walk(num_class):
    bst, X = _train(num_class=num_class)
    b = bst._booster
    n_models = len(b.models)
    assert X.shape[0] >= b._DEVICE_PREDICT_MIN_ROWS

    host = np.zeros((b.num_class, X.shape[0]), np.float64)
    for i in range(n_models):
        host[i % b.num_class] += b.models[i].predict(X)
    dev = b._predict_raw_device(X, n_models)
    # identical routing; only f32-vs-f64 leaf-sum rounding differs
    np.testing.assert_allclose(dev, host, rtol=2e-6, atol=2e-6)

    # the public surface routes large batches to the device path
    out = bst.predict(X, raw_score=True)
    want = host[0] if num_class == 1 else host.T
    np.testing.assert_allclose(out, want, rtol=2e-6, atol=2e-6)


def test_small_batch_and_loaded_model_use_host(tmp_path):
    bst, X = _train()
    small = bst.predict(X[:100], raw_score=True)
    b = bst._booster
    host = np.zeros(100, np.float64)
    for i in range(len(b.models)):
        host += b.models[i].predict(X[:100])
    np.testing.assert_allclose(small, host, rtol=0, atol=0)  # same path

    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    loaded = lgb.Booster(model_file=path)
    # loaded model has no mappers -> host walk even for large batches
    out = loaded.predict(X, raw_score=True)
    full_host = np.zeros(X.shape[0], np.float64)
    for i in range(len(b.models)):
        full_host += b.models[i].predict(X)
    np.testing.assert_allclose(out, full_host, rtol=1e-9, atol=1e-9)


def test_nan_rows_route_like_host():
    bst, X = _train()
    Xn = X.copy()
    Xn[:500, 2] = np.nan
    b = bst._booster
    host = np.zeros(Xn.shape[0], np.float64)
    for i in range(len(b.models)):
        host += b.models[i].predict(Xn)
    dev = b._predict_raw_device(np.where(np.isnan(Xn), np.inf, Xn),
                                len(b.models))[0]
    np.testing.assert_allclose(dev, host, rtol=2e-6, atol=2e-6)
    # and through the public routing (device path for the full batch)
    out = bst.predict(Xn, raw_score=True)
    np.testing.assert_allclose(out, host, rtol=2e-6, atol=2e-6)


def test_continued_training_device_predict(tmp_path):
    """Loaded (from_string) trees lack bin-space splits; the device path
    must rebuild them via Tree.ensure_inner and still match the host
    walk."""
    bst, X = _train()
    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    rng = np.random.RandomState(5)
    y2 = (X[:, 1] > 0).astype(np.float64)
    cont = lgb.train({"objective": "binary", "num_leaves": 15,
                      "verbose": -1, "min_data_in_leaf": 20},
                     lgb.Dataset(X, label=y2), num_boost_round=5,
                     init_model=path)
    b = cont._booster
    assert len(b.models) == 17
    host = np.zeros(X.shape[0], np.float64)
    for i in range(len(b.models)):
        host += b.models[i].predict(X)
    out = cont.predict(X, raw_score=True)     # device path (6000 rows)
    np.testing.assert_allclose(out, host, rtol=2e-6, atol=2e-6)


def test_reset_training_data_refreshes_gradients():
    """reset_training_data must re-jit the objective gradients: the old
    jit baked the previous dataset's labels in as constants."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.models.gbdt import GBDT
    rng = np.random.RandomState(0)
    X = rng.normal(size=(1000, 4))
    yA = X[:, 0] * 2.0
    yB = -X[:, 0] * 2.0                      # opposite target
    cfg = Config({"objective": "regression", "num_leaves": 15,
                  "min_data_in_leaf": 20, "metric": "none"})
    dsA = BinnedDataset.from_matrix(X, yA, max_bin=63, min_data_in_leaf=20)
    dsB = BinnedDataset.from_matrix(X, yB, max_bin=63, min_data_in_leaf=20)
    b = GBDT(cfg, dsA)
    for _ in range(3):
        b.train_one_iter()
    b.reset_training_data(dsB)
    for _ in range(20):
        b.train_one_iter()
    pred = b.predict_raw(X)[0]
    mse_b = float(np.mean((pred - yB) ** 2))
    mse_a = float(np.mean((pred - yA) ** 2))
    assert mse_b < mse_a, (mse_b, mse_a)
    assert mse_b < 1.0
