"""Real 2-process distributed training over jax.distributed + gloo.

The reference demonstrates parallel learning by running two local
socket-linked processes (examples/parallel_learning/README.md,
linkers_socket.cpp:20-61); this is the same bar for the TPU rebuild:
two OS processes, a shared machine-list file, a real coordination
service, cross-process collectives, and exact parity with serial
training (asserted inside each worker — see multiproc_worker.py)."""

import os
import signal
import socket
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def kill_worker_tree(proc: subprocess.Popen) -> None:
    """SIGKILL a worker's whole process group (it was started with
    ``start_new_session=True``): a wedged distributed worker can hold
    grandchildren/threads that survive a bare ``proc.kill`` and burn the
    rest of the tier-1 budget waiting on inherited pipes."""
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.kill()
        except OSError:
            pass


def _launch_once(tmp_path, attempt):
    p0, p1 = _free_port(), _free_port()
    mlist = tmp_path / f"mlist_{attempt}.txt"
    mlist.write_text(f"127.0.0.1 {p0}\n127.0.0.1 {p1}\n")

    procs = []
    outs = []
    for pid in range(2):
        out = tmp_path / f"model_{attempt}_{pid}.txt"
        outs.append(out)
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)          # conftest's 8-device flag
        env["LIGHTGBM_TPU_PROCESS_ID"] = str(pid)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(HERE, "multiproc_worker.py"),
             str(mlist), str(out)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, start_new_session=True))

    logs = []
    rcs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            kill_worker_tree(p)
            stdout, _ = p.communicate()
            stdout += "\n<<TIMEOUT>>"
        logs.append(stdout)
        rcs.append(p.returncode)
    return rcs, logs, outs


def test_two_process_data_parallel_matches_serial(tmp_path):
    # free-port discovery is inherently racy (the port is released before
    # the coordinator binds it): retry once before declaring failure
    for attempt in range(2):
        rcs, logs, outs = _launch_once(tmp_path, attempt)
        if rcs == [0, 0]:
            break
    assert rcs == [0, 0], (
        f"worker exit codes {rcs}\n--- worker 0 ---\n{logs[0]}\n"
        f"--- worker 1 ---\n{logs[1]}")
    texts = [o.read_text() for o in outs]
    assert all(t.startswith("PARITY_OK") for t in texts)
    # both controllers materialized the identical model
    assert texts[0] == texts[1]
