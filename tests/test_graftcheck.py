"""tools/graftcheck as a tier-1 gate.

Three layers, mirroring how tests/test_phase_lint.py pins the phase
lint:

1. seeded-violation fixtures — tiny synthetic modules that MUST trip
   each rule family (a rule that cannot catch its own seeded bug is
   decoration, not a gate);
2. the suppression syntax round-trips (inline, multi-rule, file-level)
   and suppressed findings stay counted;
3. the real repo is CLEAN: ``run_checks`` over this checkout returns
   zero unsuppressed findings, which is what makes every rule a
   regression gate for future PRs rather than advice.
"""

import json
import pathlib
import subprocess
import sys
import textwrap

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.graftcheck import run_checks  # noqa: E402

pytestmark = pytest.mark.graftcheck


def _tree(tmp_path, files):
    pkg = tmp_path / "lightgbm_tpu"
    for rel, body in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(body))
    return tmp_path


def _rules(report):
    return sorted({f.rule for f in report.findings})


# -- family: locks -------------------------------------------------------

def test_lock_order_inversion_trips(tmp_path):
    root = _tree(tmp_path, {"ab.py": """
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def forward(self):
                with self._a:
                    with self._b:
                        pass

            def backward(self):
                with self._b:
                    with self._a:
                        pass
    """})
    report = run_checks(root, families=["locks"])
    assert any(f.rule == "lock-order" and "inversion" in f.message
               for f in report.findings), report.findings


def test_lock_order_inversion_via_call_graph(tmp_path):
    root = _tree(tmp_path, {"ab.py": """
        import threading

        class Fleet:
            def __init__(self):
                self._cond = threading.Lock()
                self.batcher = None

            def dispatch(self):
                with self._cond:
                    self.batcher.depth()

        class Batcher:
            def __init__(self, fleet):
                self._lock = threading.Lock()
                self.fleet = fleet

            def depth(self):
                with self._lock:
                    return 0

            def drain(self):
                with self._lock:
                    with self.fleet._cond:
                        pass
    """})
    report = run_checks(root, families=["locks"])
    assert any(f.rule == "lock-order" and "inversion" in f.message
               for f in report.findings), report.findings


def test_blocking_call_under_lock_trips(tmp_path):
    root = _tree(tmp_path, {"blk.py": """
        import subprocess
        import threading
        import time

        _lock = threading.Lock()

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._worker = threading.Thread(target=self._run,
                                                daemon=True)

            def _run(self):
                pass

            def stop(self):
                with self._lock:
                    self._worker.join()

            def nap(self):
                with self._lock:
                    time.sleep(1.0)

        def build():
            with _lock:
                subprocess.run(["true"])
    """})
    report = run_checks(root, families=["locks"])
    msgs = [f.message for f in report.findings
            if f.rule == "lock-blocking"]
    assert any("thread join" in m for m in msgs), report.findings
    assert any("time.sleep" in m for m in msgs), report.findings
    assert any("subprocess" in m for m in msgs), report.findings


def test_blocking_call_propagates_through_helper(tmp_path):
    root = _tree(tmp_path, {"blk.py": """
        import subprocess
        import threading

        _lock = threading.Lock()

        def _compile():
            subprocess.run(["g++"])

        def get():
            with _lock:
                _compile()
    """})
    report = run_checks(root, families=["locks"])
    assert any(f.rule == "lock-blocking" and "_compile" in f.message
               for f in report.findings), report.findings


def test_self_deadlock_via_call_chain_trips(tmp_path):
    root = _tree(tmp_path, {"sd.py": """
        import threading

        class B:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)

            def outer(self):
                with self._cond:
                    self.inner()

            def inner(self):
                with self._cond:
                    pass
    """})
    report = run_checks(root, families=["locks"])
    assert any(f.rule == "lock-order" and "re-acquires" in f.message
               for f in report.findings), report.findings


def test_bare_condition_reacquisition_is_reentrant(tmp_path):
    # threading.Condition() with no lock argument is RLock-backed:
    # nested acquisition is legal and must not be flagged
    root = _tree(tmp_path, {"ok.py": """
        import threading

        class B:
            def __init__(self):
                self._cond = threading.Condition()

            def outer(self):
                with self._cond:
                    with self._cond:
                        pass
    """})
    report = run_checks(root, families=["locks"])
    assert report.findings == [], report.findings


def test_condition_wait_on_held_lock_is_not_blocking(tmp_path):
    root = _tree(tmp_path, {"ok.py": """
        import threading

        class B:
            def __init__(self):
                self._cond = threading.Condition()

            def take(self):
                with self._cond:
                    self._cond.wait(timeout=0.1)
    """})
    report = run_checks(root, families=["locks"])
    assert report.findings == []


def test_shared_attr_mixed_locking_trips(tmp_path):
    root = _tree(tmp_path, {"mix.py": """
        import threading

        class R:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def locked_bump(self):
                with self._lock:
                    self.count += 1

            def bare_bump(self):
                self.count += 1
    """})
    report = run_checks(root, families=["locks"])
    assert any(f.rule == "lock-shared-attr" and "count" in f.message
               for f in report.findings), report.findings


def test_shared_attr_locked_helper_is_clean(tmp_path):
    # a *_locked helper and a helper only ever called under the lock
    # are lock-guarded in fact — no finding
    root = _tree(tmp_path, {"ok.py": """
        import threading

        class R:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def bump(self):
                with self._lock:
                    self._bump_locked()
                    self._accumulate()

            def _bump_locked(self):
                self.count += 1

            def _accumulate(self):
                self.count += 2
    """})
    report = run_checks(root, families=["locks"])
    assert report.findings == []


# -- family: tracer ------------------------------------------------------

def test_host_effects_in_jitted_fn_trip(tmp_path):
    root = _tree(tmp_path, {"jt.py": """
        import time
        import jax
        import numpy as np
        from .. import obs

        @jax.jit
        def step(x):
            obs.inc("steps")
            t = time.time()
            noise = np.random.normal()
            host = x.item()
            return x + t + noise + host
    """})
    report = run_checks(root, families=["tracer"])
    msgs = [f.message for f in report.findings
            if f.rule == "jit-host-effect"]
    assert any("registry write" in m for m in msgs), report.findings
    assert any("time." in m for m in msgs), report.findings
    assert any("RNG draw" in m for m in msgs), report.findings
    assert any(".item()" in m for m in msgs), report.findings


def test_fn_passed_to_jit_call_is_scanned(tmp_path):
    root = _tree(tmp_path, {"jt.py": """
        import jax

        def impl(x):
            print("tracing!")
            return x

        fn = jax.jit(impl)
    """})
    report = run_checks(root, families=["tracer"])
    assert any(f.rule == "jit-host-effect" and "print" in f.message
               for f in report.findings), report.findings


def test_clean_jitted_fn_passes(tmp_path):
    root = _tree(tmp_path, {"jt.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            return jnp.sum(x * 2.0)
    """})
    report = run_checks(root, families=["tracer"])
    assert report.findings == []


# -- family: jit ---------------------------------------------------------

def test_raw_jax_jit_trips(tmp_path):
    root = _tree(tmp_path, {"raw.py": """
        import functools
        import jax

        @jax.jit
        def a(x):
            return x

        @functools.partial(jax.jit, static_argnames=("n",))
        def b(x, n):
            return x * n
    """})
    report = run_checks(root, families=["jit"])
    raws = [f for f in report.findings if f.rule == "jit-raw"]
    assert len(raws) == 2, report.findings


def test_jit_of_lambda_and_jit_in_loop_trip(tmp_path):
    root = _tree(tmp_path, {"cl.py": """
        import jax

        def build(fns):
            out = []
            for f in fns:
                out.append(jax.jit(f))
            g = jax.jit(lambda x: x + 1)
            return out, g
    """})
    report = run_checks(root, families=["jit"])
    closures = [f for f in report.findings if f.rule == "jit-closure"]
    assert any("lambda" in f.message for f in closures), report.findings
    assert any("loop" in f.message for f in closures), report.findings


# -- family: lifecycle ---------------------------------------------------

def test_undaemonized_unjoined_thread_trips(tmp_path):
    root = _tree(tmp_path, {"th.py": """
        import threading

        class S:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                pass
    """})
    report = run_checks(root, families=["lifecycle"])
    assert any(f.rule == "thread-lifecycle" for f in report.findings), \
        report.findings


def test_joined_or_daemon_threads_pass(tmp_path):
    root = _tree(tmp_path, {"th.py": """
        import threading

        class S:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()
                self._d = threading.Thread(target=self._run, daemon=True)
                self._d.start()

            def stop(self):
                self._t.join(timeout=5.0)

            def _run(self):
                pass
    """})
    report = run_checks(root, families=["lifecycle"])
    assert report.findings == []


def test_socket_without_close_trips(tmp_path):
    root = _tree(tmp_path, {"so.py": """
        import socket

        class Mesh:
            def __init__(self):
                self._sock = socket.socket(socket.AF_INET,
                                           socket.SOCK_DGRAM)
    """})
    report = run_checks(root, families=["lifecycle"])
    assert any(f.rule == "handle-close" and "_sock" in f.message
               for f in report.findings), report.findings


def test_local_open_without_close_trips(tmp_path):
    root = _tree(tmp_path, {"fh.py": """
        def leak(path):
            fh = open(path)
            return fh.read()

        def fine(path):
            with open(path) as fh:
                return fh.read()

        def also_fine(path):
            fh = open(path)
            try:
                return fh.read()
            finally:
                fh.close()
    """})
    report = run_checks(root, families=["lifecycle"])
    handle = [f for f in report.findings if f.rule == "handle-close"]
    assert len(handle) == 1 and handle[0].line == 3, report.findings


def test_wall_clock_in_deadline_math_trips(tmp_path):
    root = _tree(tmp_path, {"ck.py": """
        import time

        def deadline(timeout):
            start = time.time()
            while time.time() - start < timeout:
                pass

        def stamp():
            return {"t": round(time.time(), 3)}
    """})
    report = run_checks(root, families=["lifecycle"])
    clocks = [f for f in report.findings if f.rule == "wall-clock"]
    # the two deadline-math uses trip; the pure timestamp does not
    assert {f.line for f in clocks} == {5, 6}, report.findings


# -- suppression syntax --------------------------------------------------

def test_inline_suppression_waives_and_counts(tmp_path):
    root = _tree(tmp_path, {"raw.py": """
        import jax

        @jax.jit  # graftcheck: disable=jit-raw
        def a(x):
            return x

        @jax.jit
        def b(x):
            return x
    """})
    report = run_checks(root, families=["jit"])
    assert len(report.findings) == 1          # b stays live
    assert len(report.suppressed) == 1        # a is waived, but counted
    assert report.suppressed_counts() == {"jit-raw": 1}
    assert report.exit_code == 1


def test_multi_rule_and_file_suppressions(tmp_path):
    root = _tree(tmp_path, {"multi.py": """
        # graftcheck: disable-file=jit-closure
        import jax

        g = jax.jit(lambda x: x)  # graftcheck: disable=jit-raw,unused-rule

        @jax.jit
        def b(x):
            return x
    """})
    report = run_checks(root, families=["jit"])
    assert [f.rule for f in report.findings] == ["jit-raw"]  # only b
    assert sorted(f.rule for f in report.suppressed) == [
        "jit-closure", "jit-raw"]


def test_disable_all_waives_everything_on_line(tmp_path):
    root = _tree(tmp_path, {"a.py": """
        import jax

        @jax.jit  # graftcheck: disable=all
        def a(x):
            return x
    """})
    report = run_checks(root, families=["jit"])
    assert report.findings == [] and len(report.suppressed) == 1
    assert report.exit_code == 0


# -- family: params ------------------------------------------------------

def test_param_docs_drift_trips(tmp_path):
    root = _tree(tmp_path, {"config.py": """
        _DEFAULTS = {
            "documented": 1,
            "undocumented": 2,
        }
    """})
    (root / "docs").mkdir()
    (root / "docs" / "_param_descriptions.py").write_text(
        'DESC = {"documented": "fine", "stale": "gone"}\n')
    (root / "docs" / "Parameters.md").write_text("| `documented` |\n")
    report = run_checks(root, families=["params"])
    msgs = [f.message for f in report.findings]
    assert any("'undocumented' has no description" in m for m in msgs)
    assert any("'stale' matches no _DEFAULTS key" in m for m in msgs)
    assert any("'undocumented' is missing from docs/Parameters.md" in m
               for m in msgs)


# -- family: metrics -----------------------------------------------------

def test_metrics_undocumented_series_trips(tmp_path):
    root = _tree(tmp_path, {"obs/widget.py": """
        from . import registry

        def publish(spins):
            registry.inc("widget_spins_total", spins)
            registry.set_gauge("widget_temperature", 451)
            registry.inc("widget_" + "dynamic")   # not a literal: skipped
    """})
    (root / "docs").mkdir()
    (root / "docs" / "OBSERVABILITY.md").write_text(
        "# Observability\n\n`widget_temperature` — a documented gauge.\n")
    report = run_checks(root, families=["metrics"])
    msgs = [f.message for f in report.findings if f.rule == "metrics-docs"]
    assert any("widget_spins_total" in m for m in msgs), report.findings
    assert not any("widget_temperature" in m for m in msgs)
    assert not any("dynamic" in m for m in msgs)


def test_metrics_abstains_without_docs_file(tmp_path):
    root = _tree(tmp_path, {"obs/widget.py": """
        from . import registry

        def publish():
            registry.inc("widget_spins_total")
    """})
    report = run_checks(root, families=["metrics"])
    assert report.findings == []


def test_metrics_suppression_round_trips(tmp_path):
    root = _tree(tmp_path, {"obs/widget.py": """
        from . import registry

        def publish():
            registry.inc("widget_spins_total")  # graftcheck: disable=metrics-docs
            registry.inc("widget_faults_total")
    """})
    (root / "docs").mkdir()
    (root / "docs" / "OBSERVABILITY.md").write_text("# Observability\n")
    report = run_checks(root, families=["metrics"])
    assert [f.rule for f in report.findings] == ["metrics-docs"]
    assert "widget_faults_total" in report.findings[0].message
    assert report.suppressed_counts() == {"metrics-docs": 1}


# -- family: ingress -----------------------------------------------------

def test_ingress_assert_trips(tmp_path):
    root = _tree(tmp_path, {"io/loader.py": """
        def load(path, rows):
            off = len(rows)
            assert off == 10, (off, 10)
            return rows
    """})
    report = run_checks(root, families=["ingress"])
    assert any(f.rule == "ingress-assert"
               and "LightGBMError" in f.message
               for f in report.findings), report.findings


def test_ingress_raw_parse_trips_on_split_tokens(tmp_path):
    root = _tree(tmp_path, {"io/parser.py": """
        def parse(line):
            parts = line.split(",")
            vals = [float(p) for p in parts]
            first = int(parts[0])
            return vals, first
    """})
    report = run_checks(root, families=["ingress"])
    raw = [f for f in report.findings if f.rule == "ingress-raw-parse"]
    assert len(raw) == 2, report.findings
    assert all("io/guard" in f.message for f in raw)


def test_ingress_raw_parse_ignores_non_token_conversions(tmp_path):
    # config-value coercions and guard-helper routing are NOT findings
    root = _tree(tmp_path, {
        "io/parser.py": """
            from .guard import feature_value

            def parse(line, categorical_features):
                cats = [int(c) for c in categorical_features]
                parts = line.split(",")
                vals = [feature_value(p) for p in parts]
                return vals, cats

            def convert_config(spec):
                return int(spec)
        """,
        "io/guard.py": """
            def feature_value(token):
                t = token.strip()
                return float(t)
        """,
    })
    report = run_checks(root, families=["ingress"])
    assert [f for f in report.findings
            if f.rule == "ingress-raw-parse"] == [], report.findings


def test_ingress_scoped_to_io_only(tmp_path):
    root = _tree(tmp_path, {"serve/server.py": """
        def parse(line):
            assert line
            return [float(p) for p in line.split(",")]
    """})
    report = run_checks(root, families=["ingress"])
    assert report.findings == [], report.findings


# -- family: resource ----------------------------------------------------

def test_resource_raw_open_trips_on_write_modes(tmp_path):
    root = _tree(tmp_path, {"obs/sink.py": """
        import json

        def dump(path, events, extra):
            with open(path, "w") as fh:
                json.dump(events, fh)
            fh2 = open(path + ".log", mode="a")
            fh2.write(extra)
            fh2.close()
            with open(path + ".bin", "wb") as fh3:
                fh3.write(b"x")
    """})
    report = run_checks(root, families=["resource"])
    raw = [f for f in report.findings if f.rule == "resource-raw-open"]
    assert len(raw) == 3, report.findings
    assert all("diskguard" in f.message for f in raw)


def test_resource_raw_open_ignores_reads_and_funnel_modules(tmp_path):
    root = _tree(tmp_path, {
        "obs/reader.py": """
            def load(path):
                with open(path) as fh:
                    return fh.read()

            def load_bytes(path):
                with open(path, "rb") as fh:
                    return fh.read()
        """,
        # the funnel itself and the atomic-protocol owner are exempt
        "utils/diskguard.py": """
            def guarded(path, text):
                with open(path, "w") as fh:
                    fh.write(text)
        """,
        "snapshot.py": """
            def write(path, blob):
                with open(path + ".tmp", "wb") as fh:
                    fh.write(blob)
        """,
        # fault injectors corrupt files on purpose
        "testing/faults.py": """
            def corrupt(path):
                with open(path, "r+b") as fh:
                    fh.write(b"x")
        """,
    })
    report = run_checks(root, families=["resource"])
    assert report.findings == [], report.findings


def test_resource_raw_open_suppression_counts(tmp_path):
    root = _tree(tmp_path, {"io/export.py": """
        def export(path, text):
            with open(path, "w") as fh:  # graftcheck: disable=resource-raw-open
                fh.write(text)
    """})
    report = run_checks(root, families=["resource"])
    assert report.findings == []
    assert [f.rule for f in report.suppressed] == ["resource-raw-open"]


def test_resource_skips_unjudgeable_modes(tmp_path):
    # a non-constant mode expression is not judged (zero-false-positive
    # bias, same stance as the ingress taint tracking)
    root = _tree(tmp_path, {"io/any.py": """
        def reopen(path, mode):
            return open(path, mode)
    """})
    report = run_checks(root, families=["resource"])
    assert report.findings == [], report.findings


# -- family: timing ------------------------------------------------------

def test_timing_async_dispatch_trips(tmp_path):
    # the seeded bug: wall-clocking a bare jit call measures enqueue
    # time (async dispatch), not execution — both the decorated and the
    # module-level-assigned spellings must trip
    root = _tree(tmp_path, {"tm.py": """
        import time
        import jax

        @jax.jit
        def step(x):
            return x * 2

        apply = jax.jit(lambda x: x + 1)

        def benchmark(x):
            t0 = time.perf_counter()
            y = step(x)
            return time.perf_counter() - t0, y

        def benchmark2(x):
            start = time.monotonic()
            y = apply(x)
            dt = time.monotonic() - start
            return dt, y
    """})
    report = run_checks(root, families=["timing"])
    hits = [f for f in report.findings if f.rule == "timing-async-dispatch"]
    assert len(hits) == 2, report.findings
    assert all("enqueue" in f.message for f in hits)


def test_timing_synced_window_passes(tmp_path):
    # any sync marker inside the window legitimizes the measurement:
    # block_until_ready, .item(), np.asarray, or a devprof helper
    root = _tree(tmp_path, {"ok.py": """
        import time
        import jax
        import numpy as np
        from .obs import devprof

        @jax.jit
        def step(x):
            return x * 2

        def timed_sync(x):
            t0 = time.perf_counter()
            y = jax.block_until_ready(step(x))
            return time.perf_counter() - t0, y

        def timed_materialize(x):
            t0 = time.perf_counter()
            y = np.asarray(step(x))
            return time.perf_counter() - t0, y

        def timed_devprof(x):
            t0 = time.perf_counter()
            y = step(x)
            devprof.sync(y, source="bench")
            return time.perf_counter() - t0, y

        def untimed(x):
            return step(x)
    """})
    report = run_checks(root, families=["timing"])
    assert report.findings == [], report.findings


def test_timing_suppression_round_trips(tmp_path):
    root = _tree(tmp_path, {"tm.py": """
        import time
        import jax

        @jax.jit
        def step(x):
            return x

        def dispatch_latency(x):
            # dispatch latency IS the quantity under test here
            t0 = time.perf_counter()
            step(x)
            return time.perf_counter() - t0  # graftcheck: disable=timing-async-dispatch
    """})
    report = run_checks(root, families=["timing"])
    assert report.findings == []
    assert [f.rule for f in report.suppressed] == ["timing-async-dispatch"]


# -- family: serve -------------------------------------------------------

def test_serve_strategy_parity_trips(tmp_path):
    """A strategy jit invoked outside _dispatch_binned/_dispatch_raw
    hardwires one walk strategy and bypasses the quantized-input remap
    (docs/SERVING.md §Serving strategies) — seeded bypass must trip."""
    root = _tree(tmp_path, {"serve/forest.py": """
        class F:
            def _dispatch_binned(self, bucket, bins, mask):
                return self._binned_jit(bucket, bins, mask)   # sanctioned

            def _dispatch_raw(self, bucket, Xp, mask):
                return self._walk_raw_jit(bucket, Xp, mask)   # sanctioned

            def raw_scores(self, bucket, bins, mask):
                return self._walk_binned_jit(bucket, bins, mask)
    """})
    report = run_checks(root, families=["serve"])
    assert [f.rule for f in report.findings] == ["serve-strategy-parity"]
    assert report.findings[0].line == 10
    assert "_walk_binned_jit" in report.findings[0].message


def test_serve_strategy_parity_ignores_non_serve_modules(tmp_path):
    # construction is fine everywhere; calls outside serve/ are not this
    # rule's business (no strategy exists there)
    root = _tree(tmp_path, {
        "serve/forest.py": """
            class F:
                def build(self):
                    self._binned_jit = make()        # assignment, not call
        """,
        "models/gbdt.py": """
            class G:
                def run(self, x):
                    return self._raw_jit(16, x)      # not a serve module
        """,
    })
    report = run_checks(root, families=["serve"])
    assert report.findings == [], report.findings


def test_serve_strategy_parity_suppression_round_trips(tmp_path):
    root = _tree(tmp_path, {"serve/warm.py": """
        class W:
            def warm(self, bucket, bins, mask):
                return self._binned_jit(bucket, bins, mask)  # graftcheck: disable=serve-strategy-parity
    """})
    report = run_checks(root, families=["serve"])
    assert report.findings == []
    assert [f.rule for f in report.suppressed] == ["serve-strategy-parity"]


# -- the repo itself -----------------------------------------------------

def test_repo_is_clean():
    """The merge gate: zero unsuppressed findings on this checkout.
    Waivers (inline suppressions) are allowed but must stay visible —
    a regression in any rule family fails tier-1 right here."""
    report = run_checks(REPO)
    assert report.parse_errors == []
    assert report.findings == [], "\n".join(
        f.render() for f in report.findings)


def test_repo_phase_family_matches_standalone_lint():
    """The migrated phases family and the preserved standalone entry
    point must agree (both clean, same implementation)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "lint_phase_scopes", REPO / "tools" / "lint_phase_scopes.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.check() == []


# -- CLI contract --------------------------------------------------------

def test_cli_exit_zero_and_json_on_clean_repo():
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftcheck", "--format=json"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["findings"] == []
    assert isinstance(doc["suppressed_counts"], dict)


def test_cli_exit_one_on_findings(tmp_path):
    root = _tree(tmp_path, {"raw.py": "import jax\nf = jax.jit(len)\n"})
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftcheck",
         f"--root={root}", "--rule=jit"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 1
    assert "jit-raw" in out.stderr


def test_cli_rejects_unknown_family():
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftcheck", "--rule=nonsense"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 2
