"""Data-boundary containment unit layer (io/guard.py + guarded parsers,
docs/FAULT_TOLERANCE.md §Data boundary): classification vocabulary,
fail-fast diagnostics naming file:line + token, quarantine sink format,
error budgets, two-round dedupe, NA-as-missing semantics, and the
blank-line chunk alignment fix."""

import os
from unittest import mock

import numpy as np
import pytest

from lightgbm_tpu import obs
from lightgbm_tpu.io.guard import (IngestGuard, column_index,
                                   feature_value, read_quarantine)
from lightgbm_tpu.io.parser import (_parse_delimited, _parse_libsvm,
                                    parse_file, parse_file_chunks)
from lightgbm_tpu.io.streaming import load_file_two_round
from lightgbm_tpu.utils.log import LightGBMError


def _python_parse_file(path, **kw):
    """Force the guarded Python path (native fast path mocked away)."""
    with mock.patch("lightgbm_tpu.io.native.parse_file_native",
                    return_value=None):
        return parse_file(path, **kw)


# ---------------------------------------------------------------------------
# token helpers: the single conversion point (graftcheck ingress rules)
# ---------------------------------------------------------------------------

def test_feature_value_na_spellings_are_nan():
    for tok in ("na", "NA", "NaN", "nan", "null", "NULL", "none", "",
                "  "):
        assert np.isnan(feature_value(tok)), tok
    assert feature_value(" 1.5 ") == 1.5
    assert feature_value("-2e3") == -2000.0
    with pytest.raises(ValueError):
        feature_value("1.5x")
    with pytest.raises(ValueError):
        feature_value("@@")


def test_column_index_rejects_negative_and_garbage():
    assert column_index("7") == 7
    with pytest.raises(ValueError):
        column_index("-2")          # the silent wrong-feature write
    with pytest.raises(ValueError):
        column_index("x")


# ---------------------------------------------------------------------------
# guard policy mechanics
# ---------------------------------------------------------------------------

def test_fail_fast_names_file_line_and_token(tmp_path):
    g = IngestGuard(str(tmp_path / "d.csv"))
    with pytest.raises(LightGBMError) as ei:
        g.bad_row(42, "1,xx,3", "unparseable_token", "token 'xx'")
    msg = str(ei.value)
    assert "d.csv:42" in msg and "'xx'" in msg \
        and "unparseable_token" in msg


def test_quarantine_sink_records_and_counters(tmp_path):
    p = str(tmp_path / "d.csv")
    base = obs.get_counter("bad_rows_total")
    g = IngestGuard(p, policy="quarantine")
    assert g.bad_row(3, "1,xx,3", "unparseable_token", "token 'xx'")
    assert g.bad_row(9, "1,2", "ragged_row", "2 fields")
    # dedupe: the same line classified again (two-round) is a no-op
    assert not g.bad_row(3, "1,xx,3", "unparseable_token", "token 'xx'")
    g.finish()
    assert g.bad_total == 2
    assert obs.get_counter("bad_rows_total") - base == 2
    assert obs.get_counter("bad_rows_unparseable_token") >= 1
    assert obs.get_counter("bad_rows_ragged_row") >= 1
    recs = read_quarantine(p)
    assert [r["line"] for r in recs] == [3, 9]
    assert recs[0]["reason"] == "unparseable_token"
    assert recs[0]["raw"] == "1,xx,3"


def test_stale_quarantine_file_removed_on_new_guard(tmp_path):
    p = str(tmp_path / "d.csv")
    g = IngestGuard(p, policy="quarantine")
    g.bad_row(1, "x", "empty", "no fields")
    g.finish()
    assert os.path.exists(g.quarantine_path)
    IngestGuard(p, policy="quarantine")     # fresh load, no bad rows yet
    assert not os.path.exists(g.quarantine_path)


def test_absolute_budget_exhaustion(tmp_path):
    g = IngestGuard(str(tmp_path / "d.csv"), policy="quarantine",
                    max_bad_rows=2)
    g.bad_row(1, "a", "empty", "no fields")
    g.bad_row(2, "b", "empty", "no fields")
    with pytest.raises(LightGBMError) as ei:
        g.bad_row(3, "c", "empty", "no fields")
    assert "max_bad_rows=2" in str(ei.value)


def test_fraction_budget_in_flight_and_at_finish(tmp_path):
    # in flight: past the grace window, > 10% bad aborts
    g = IngestGuard(str(tmp_path / "d.csv"), policy="quarantine",
                    max_bad_row_fraction=0.1)
    g.good_rows(99)
    g.bad_row(100, "x", "empty", "no fields")   # 1/100: at the edge, ok
    g.good_rows(900)
    with pytest.raises(LightGBMError):
        for i in range(200):                     # push past 10%
            g.bad_row(2000 + i, "x", "empty", "no fields")
    # at finish: short files get the final check
    g2 = IngestGuard(str(tmp_path / "e.csv"), policy="quarantine",
                     max_bad_row_fraction=0.1)
    g2.good_rows(4)
    g2.bad_row(5, "x", "empty", "no fields")     # 1/5 = 20%
    with pytest.raises(LightGBMError):
        g2.finish()


def test_shadow_guard_skips_without_counting(tmp_path):
    p = str(tmp_path / "d.csv")
    base = obs.get_counter("bad_rows_total")
    g = IngestGuard(p, policy="quarantine", record=False)
    assert g.bad_row(3, "1,xx,3", "unparseable_token", "token 'xx'")
    g.finish()
    assert obs.get_counter("bad_rows_total") == base
    assert not os.path.exists(g.quarantine_path)


# ---------------------------------------------------------------------------
# parser classification
# ---------------------------------------------------------------------------

def test_delimited_classification_reasons(tmp_path):
    lines = ["1,2,3", "1,zz,3", "1,2", ",,", "1,4,5"]
    g = IngestGuard(str(tmp_path / "d.csv"), policy="quarantine")
    label, feats = _parse_delimited(lines, ",", 0, guard=g)
    assert feats.shape == (2, 2)
    assert g.by_reason == {"unparseable_token": 1, "ragged_row": 1,
                           "empty": 1}


def test_delimited_na_tokens_become_nan():
    label, feats = _parse_delimited(["1,na,2", "0,3,NaN"], ",", 0)
    assert np.isnan(feats[0, 0]) and np.isnan(feats[1, 1])
    assert feats[0, 1] == 2.0


def test_libsvm_bad_column_index_classified(tmp_path):
    lines = ["1 0:1.5 2:2.5", "0 -3:9.9", "1 1:2.0"]
    # fail fast: the negative index is a NAMED error, not a silent
    # write into feature F-3
    with pytest.raises(LightGBMError) as ei:
        _parse_libsvm(lines, guard=IngestGuard("f.svm"))
    assert "bad_column_index" in str(ei.value)
    assert "-3" in str(ei.value)
    # quarantine: the row is skipped, others intact
    g = IngestGuard(str(tmp_path / "f.svm"), policy="quarantine")
    label, feats = _parse_libsvm(lines, guard=g)
    assert feats.shape == (2, 3)
    assert g.by_reason == {"bad_column_index": 1}


def test_libsvm_out_of_range_index_classified(tmp_path):
    g = IngestGuard(str(tmp_path / "f.svm"), policy="quarantine")
    label, feats = _parse_libsvm(["1 0:1 9:9", "0 1:2"], num_features=3,
                                 guard=g)
    assert feats.shape == (1, 3)
    assert g.by_reason == {"bad_column_index": 1}


def test_libsvm_malformed_tokens_classified(tmp_path):
    g = IngestGuard(str(tmp_path / "f.svm"), policy="quarantine")
    label, feats = _parse_libsvm(
        ["1 0:1.5", "0 junk", "1 2:zz", "badlabel 0:1"], guard=g)
    assert feats.shape == (1, 1)    # only the clean row survives
    assert g.by_reason == {"unparseable_token": 3}


def test_parse_file_fail_fast_is_default(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("1,2,3\n1,zz,3\n")
    with pytest.raises(LightGBMError) as ei:
        _python_parse_file(str(p))
    assert f"{p}:2" in str(ei.value) and "'zz'" in str(ei.value)
    # the native path must reroute to the same diagnostic
    with pytest.raises(LightGBMError) as ei2:
        parse_file(str(p))
    assert f"{p}:2" in str(ei2.value)


def test_parse_file_quarantine_line_numbers_with_header(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("lab,a,b\n1,2,3\n\n1,zz,3\n1,4,5\n")
    g = IngestGuard(str(p), policy="quarantine")
    label, feats, header = _python_parse_file(str(p), has_header=True,
                                              guard=g)
    assert header == ["a", "b"]
    assert feats.shape == (2, 2)
    # physical line number: header=1, blank line counted, bad row at 4
    assert [r["line"] for r in read_quarantine(str(p))] == [4]


def test_undecodable_bytes_are_classified_not_crashed(tmp_path):
    p = tmp_path / "d.csv"
    p.write_bytes(b"1,2,3\n1,\xff\xfe,3\n1,4,5\n")
    with pytest.raises(LightGBMError):
        _python_parse_file(str(p))
    g = IngestGuard(str(p), policy="quarantine")
    _, feats, _ = _python_parse_file(str(p), guard=g)
    assert feats.shape == (2, 2)


# ---------------------------------------------------------------------------
# blank-line chunk alignment (satellite: chunked-vs-whole parity)
# ---------------------------------------------------------------------------

def test_parse_file_chunks_blank_lines_do_not_drift(tmp_path):
    p = tmp_path / "b.csv"
    rows = []
    for i in range(10):
        rows.append(f"{i % 2},{i},.5")
        if i % 3 == 0:
            rows.append("")        # interior blank lines
    p.write_text("\n".join(rows) + "\n\n")
    whole_label, whole_X, _ = _python_parse_file(str(p))
    # tiny chunk size: blanks land on chunk boundaries
    got = list(parse_file_chunks(str(p), chunk_rows=2))
    X = np.concatenate([x for _, x in got], axis=0)
    lab = np.concatenate([l for l, _ in got])
    assert X.shape == whole_X.shape == (10, 2)
    np.testing.assert_array_equal(X, whole_X)
    np.testing.assert_array_equal(lab, whole_label)


def test_parse_file_chunks_fail_fast_names_line(tmp_path):
    p = tmp_path / "b.csv"
    p.write_text("1,2,3\n\n1,zz,3\n")
    with pytest.raises(LightGBMError) as ei:
        list(parse_file_chunks(str(p), chunk_rows=1))
    assert f"{p}:3" in str(ei.value)


# ---------------------------------------------------------------------------
# two-round loader accounting
# ---------------------------------------------------------------------------

def _write_tsv(path, n=60, bad_lines=(), seed=0):
    rng = np.random.RandomState(seed)
    rows = []
    for i in range(n):
        vals = [f"{int(rng.rand() < 0.5)}"] + \
            [f"{v:.6f}" for v in rng.normal(size=3)]
        rows.append("\t".join(vals))
    for ln in bad_lines:
        rows[ln - 1] = rows[ln - 1] + "\t@@junk@@"
    path.write_text("\n".join(rows) + "\n")
    return rows


def test_two_round_quarantine_crops_and_dedupes(tmp_path):
    p = tmp_path / "t.tsv"
    _write_tsv(p, n=60, bad_lines=(7, 41))
    base = obs.get_counter("bad_rows_total")
    g = IngestGuard(str(p), policy="quarantine")
    ds = load_file_two_round(str(p), max_bin=15, min_data_in_leaf=5,
                             guard=g, chunk_rows=13)
    assert ds.bins.shape[1] == 58
    assert ds.metadata.num_data == 58
    assert len(ds.metadata.label) == 58
    # sampled in round 1b AND re-met in round 2: counted ONCE
    assert obs.get_counter("bad_rows_total") - base == 2
    assert sorted(r["line"] for r in read_quarantine(str(p))) == [7, 41]


def test_two_round_all_rows_bad_is_named(tmp_path):
    p = tmp_path / "t.tsv"
    p.write_text("a\tb\nx\ty\n")
    g = IngestGuard(str(p), policy="quarantine")
    with pytest.raises(LightGBMError) as ei:
        load_file_two_round(str(p), guard=g)
    assert "quarantined" in str(ei.value)


def test_two_round_ragged_sampled_first_cannot_invert_schema(tmp_path):
    """Review pin: the expected field count is seeded from the file's
    FIRST data line (the native loader's schema rule), never from
    whichever line round 1b happens to sample first — one ragged line
    must not flip classification for the whole file."""
    p = tmp_path / "t.tsv"
    rows = _write_tsv(p, n=150, bad_lines=())
    # make line 2 ragged (drops a field); with a small sample it could
    # be the first line the guard parses
    rows[1] = "\t".join(rows[1].split("\t")[:3])
    p.write_text("\n".join(rows) + "\n")
    g = IngestGuard(str(p), policy="quarantine")
    ds = load_file_two_round(str(p), max_bin=15, min_data_in_leaf=5,
                             bin_construct_sample_cnt=5,
                             data_random_seed=1, guard=g)
    # exactly ONE row quarantined — the ragged one, not the other 149
    assert g.by_reason == {"ragged_row": 1}
    assert ds.metadata.num_data == 149


def test_two_round_sampled_good_rows_counted_once_in_budget(tmp_path):
    """Review pin: round-1b sample lines reappear in round 2; good rows
    must not double-count in the fractional budget's denominator."""
    p = tmp_path / "t.tsv"
    _write_tsv(p, n=120, bad_lines=(5,))
    g = IngestGuard(str(p), policy="quarantine")
    load_file_two_round(str(p), max_bin=15, min_data_in_leaf=5,
                        bin_construct_sample_cnt=120, guard=g)
    assert g.rows_seen == 120       # NOT 239
    assert g.bad_total == 1


def test_quarantine_refuses_row_aligned_side_files(tmp_path):
    """Review pin: a .weight/.query/.init companion is positional —
    quarantined rows make it un-alignable, so the load refuses with a
    named error instead of silently shifting every later value."""
    p = tmp_path / "t.tsv"
    _write_tsv(p, n=60, bad_lines=(7,))
    (tmp_path / "t.tsv.weight").write_text(
        "\n".join("1.0" for _ in range(60)) + "\n")
    g = IngestGuard(str(p), policy="quarantine")
    with pytest.raises(LightGBMError) as ei:
        load_file_two_round(str(p), max_bin=15, min_data_in_leaf=5,
                            guard=g)
    assert ".weight" in str(ei.value)
    assert "re-align" in str(ei.value)
    # clean file + side file: loads fine
    p2 = tmp_path / "c.tsv"
    _write_tsv(p2, n=60)
    (tmp_path / "c.tsv.weight").write_text(
        "\n".join("1.0" for _ in range(60)) + "\n")
    ds = load_file_two_round(str(p2), max_bin=15, min_data_in_leaf=5)
    assert ds.metadata.weights is not None


def test_native_degenerate_tokens_flagged(tmp_path):
    """Review pin: '-', '.', '1e', '2e+' are NOT numbers — the native
    loader must flag them (Python classifies them), not parse phantom
    values, while '.5' / '-1.5e+2' / '3e2' stay valid."""
    from lightgbm_tpu.io.native import get_lib, parse_file_native
    if get_lib() is None:
        pytest.skip("native toolchain unavailable")
    for tok in ("-", ".", "-.", "1e", "2e+"):
        p = tmp_path / "deg.csv"
        p.write_text(f"1,0.5,2\n0,{tok},3\n")
        assert parse_file_native(str(p))[3] == 2, tok
    p = tmp_path / "ok.csv"
    p.write_text("1,.5,2\n0,-1.5e+2,3e2\n")
    y, X, _, bad = parse_file_native(str(p))
    assert bad == -1
    np.testing.assert_allclose(X, [[0.5, 2.0], [-150.0, 300.0]])


def test_two_round_libsvm_bad_index_cannot_inflate_features(tmp_path):
    p = tmp_path / "t.svm"
    lines = [f"{i % 2} 0:{i}.5 2:{i}.25" for i in range(1, 40)]
    lines[10] = "1 999999:zz"     # garbage value on an absurd index
    p.write_text("\n".join(lines) + "\n")
    g = IngestGuard(str(p), policy="quarantine")
    ds = load_file_two_round(str(p), max_bin=15, min_data_in_leaf=5,
                             guard=g)
    assert ds.num_total_features == 3      # NOT 1e6
    assert ds.metadata.num_data == 38
    assert g.by_reason == {"unparseable_token": 1}
