"""Worker for tests/test_dist_chaos.py: one of two cooperating local
processes exercising the DISTRIBUTED fault-tolerance story end-to-end
over a real ``jax.distributed`` + gloo runtime (the multiproc_worker.py
pattern).  Three scenarios, selected by argv:

- ``kill``:   train with per-round snapshots while rank 1 is SIGKILLed
              mid-run (``faults.kill_rank``); rank 0 must be aborted by
              the collective watchdog within ``collective_timeout_s``
              and exit with ``DISTRIBUTED_ABORT_EXIT_CODE`` — reaching
              the end of this scenario is the FAILURE;
- ``resume``: a restarted pod agrees on the newest common snapshot via
              the cross-rank consensus, resumes, and the final model
              bit-matches an uninterrupted run trained in-process;
- ``desync``: ``corrupt_rank_state`` on rank 1 is detected by the
              ``distributed_consistency_check`` digest allgather —
              ``resync`` heals back to the uncorrupted trajectory
              (bit-match), ``fail_fast`` stops every rank with a
              diagnostic naming the diverged rank and field.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np  # noqa: E402

ROUNDS = 8
KILL_AT = 3          # rank 1 dies entering this boosting iteration
CORRUPT_AT = 2       # rank 1's score cache is poisoned after this one


def main() -> None:
    scenario, mlist, workdir = sys.argv[1], sys.argv[2], sys.argv[3]
    rank = int(os.environ["LIGHTGBM_TPU_PROCESS_ID"])

    from lightgbm_tpu import Dataset, LightGBMError
    from lightgbm_tpu import train as lgb_train
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.parallel.multihost import maybe_initialize_distributed
    from lightgbm_tpu.testing import faults

    DIST = {"objective": "binary", "metric": ["binary_logloss"],
            "num_leaves": 6, "max_bin": 32, "min_data_in_leaf": 10,
            "feature_fraction": 0.8, "learning_rate": 0.2,
            "tree_learner": "data", "num_machines": 2,
            "machine_list_file": mlist,
            "distributed_heartbeat_ms": 100.0,
            "collective_timeout_s": 8.0}
    assert maybe_initialize_distributed(Config(DIST)), \
        "distributed bring-up did not run"
    assert jax.process_count() == 2, jax.process_count()

    def dataset():
        rng = np.random.RandomState(9)
        X = rng.normal(size=(400, 6))
        y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
             + 0.1 * rng.normal(size=400) > 0).astype(np.float64)
        return Dataset(X, label=y)

    def model_string(bst):
        return bst._booster.save_model_to_string()

    snap_dir = os.path.join(workdir, "snaps")
    verdict_path = os.path.join(workdir, f"verdict_{scenario}_{rank}.txt")

    def verdict(tag, model):
        with open(verdict_path, "w") as fh:
            fh.write(tag + "\n")
            fh.write(model)

    if scenario == "kill":
        from lightgbm_tpu.parallel.watchdog import (
            DISTRIBUTED_ABORT_EXIT_CODE, DistributedAborted)
        params = dict(DIST, snapshot_dir=snap_dir, snapshot_freq=1,
                      num_iterations=ROUNDS)
        try:
            lgb_train(params, dataset(), verbose_eval=False,
                      callbacks=[faults.kill_rank(KILL_AT, rank=1)])
        except DistributedAborted as e:
            # cooperative trip (phase-entry check): same launcher
            # contract as the watchdog's hard abort — and os._exit for
            # the same reason, the dead-peer jax shutdown would SIGABRT
            print(f"worker abort: {e}", flush=True)
            sys.stderr.flush()
            os._exit(DISTRIBUTED_ABORT_EXIT_CODE)
        # rank 1 was SIGKILLed before this point; rank 0 blocks in (or
        # errors out of) the orphaned collective until the watchdog
        # aborts it with DISTRIBUTED_ABORT_EXIT_CODE.  Returning here
        # means the watchdog failed — make that loud and distinct.
        print("UNEXPECTED_COMPLETION", flush=True)
        sys.exit(1)

    elif scenario == "resume":
        from lightgbm_tpu.snapshot import coordinated_resume
        found = coordinated_resume(snap_dir)
        assert found is not None, "no coordinated snapshot to resume from"
        _, state = found
        assert int(state["rounds_done"]) == KILL_AT, state["rounds_done"]
        assert int(state["world"]["num_processes"]) == 2
        params = dict(DIST, snapshot_dir=snap_dir, snapshot_freq=1,
                      num_iterations=ROUNDS)
        resumed = lgb_train(params, dataset(), verbose_eval=False)
        ref = lgb_train(dict(DIST, num_iterations=ROUNDS), dataset(),
                        verbose_eval=False)
        assert model_string(resumed) == model_string(ref), \
            "resumed model does not bit-match the uninterrupted run"
        verdict("RESUME_OK", model_string(resumed))

    elif scenario == "desync":
        base = dict(DIST, num_iterations=6, distributed_consistency_check=1)
        healed = lgb_train(
            dict(base, desync_policy="resync"), dataset(),
            verbose_eval=False,
            callbacks=[faults.corrupt_rank_state(CORRUPT_AT, rank=1,
                                                 field="score")])
        ref = lgb_train(dict(DIST, num_iterations=6), dataset(),
                        verbose_eval=False)
        assert model_string(healed) == model_string(ref), \
            "resync did not converge back to the uncorrupted trajectory"
        # fail_fast: the allgather is symmetric, so EVERY rank stops
        # together with the named diagnostic
        try:
            lgb_train(
                dict(base, desync_policy="fail_fast"), dataset(),
                verbose_eval=False,
                callbacks=[faults.corrupt_rank_state(CORRUPT_AT, rank=1,
                                                     field="score")])
            raise AssertionError("fail_fast did not trip on a desync")
        except LightGBMError as e:
            msg = str(e)
            assert "desync" in msg, msg
            assert "rank(s) [1]" in msg, msg
            assert "'score'" in msg, msg
        verdict("DESYNC_OK", model_string(healed))

    else:
        raise SystemExit(f"unknown scenario {scenario!r}")


if __name__ == "__main__":
    main()
