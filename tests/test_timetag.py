"""TIMETAG phase profiling (utils/timetag.py): the reference's phase
taxonomy (gbdt.cpp:20-59, serial_tree_learner.cpp:10-37) accumulated
host-side with device sync, plus named_scope annotations in the grower."""

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.utils import timetag


def test_phase_accumulators():
    rng = np.random.RandomState(3)
    X = rng.normal(size=(500, 5))
    y = (X[:, 0] > 0).astype(np.float64)
    timetag.enable(True)
    timetag.reset()
    try:
        ds = lgb.Dataset(X, label=y)
        vs = ds.create_valid(X[:100], y[:100])
        lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                   "metric": "auc", "is_training_metric": True},
                  ds, num_boost_round=3, valid_sets=[vs])
        t = timetag.get_timings()
    finally:
        timetag.enable(False)
    for phase in ("GBDT::boosting", "GBDT::tree", "GBDT::train_score",
                  "GBDT::valid_score", "GBDT::host_tree", "GBDT::metric"):
        assert phase in t and t[phase] >= 0.0, (phase, t)
    timetag.reset()
    assert timetag.get_timings() == {}


def test_disabled_is_noop():
    timetag.enable(False)
    timetag.reset()
    with timetag.scope("x") as s:
        s.sync(np.zeros(3))
    assert timetag.get_timings() == {}
