"""TIMETAG phase profiling (utils/timetag.py): the reference's phase
taxonomy (gbdt.cpp:20-59, serial_tree_learner.cpp:10-37) accumulated
host-side with device sync, plus named_scope annotations in the grower."""

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.utils import timetag


def test_phase_accumulators():
    rng = np.random.RandomState(3)
    X = rng.normal(size=(500, 5))
    y = (X[:, 0] > 0).astype(np.float64)
    timetag.enable(True)
    timetag.reset()
    try:
        ds = lgb.Dataset(X, label=y)
        vs = ds.create_valid(X[:100], y[:100])
        lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                   "metric": "auc", "is_training_metric": True},
                  ds, num_boost_round=3, valid_sets=[vs])
        t = timetag.get_timings()
    finally:
        timetag.enable(False)
    # the standard path runs one fused dispatch per round: gradients +
    # growth + train-score land in GBDT::tree (models/gbdt.py
    # _make_train_step)
    for phase in ("GBDT::tree", "GBDT::valid_score", "GBDT::host_tree",
                  "GBDT::metric", "GBDT::bagging"):
        assert phase in t and t[phase] >= 0.0, (phase, t)
    timetag.reset()
    assert timetag.get_timings() == {}


def test_phase_accumulators_custom_fobj():
    """The custom-fobj path keeps the reference's per-phase taxonomy
    (gradients arrive from the host, so boosting/tree/train_score are
    separate dispatches)."""
    rng = np.random.RandomState(4)
    X = rng.normal(size=(300, 4))
    y = (X[:, 0] > 0).astype(np.float64)

    def fobj(preds, ds_):
        p = 1.0 / (1.0 + np.exp(-preds))
        return p - ds_.get_label(), p * (1 - p)

    timetag.enable(True)
    timetag.reset()
    try:
        ds = lgb.Dataset(X, label=y)
        lgb.train({"objective": "none", "num_leaves": 7, "verbose": -1},
                  ds, num_boost_round=2, fobj=fobj)
        t = timetag.get_timings()
    finally:
        timetag.enable(False)
    for phase in ("GBDT::boosting", "GBDT::tree", "GBDT::train_score",
                  "GBDT::host_tree"):
        assert phase in t and t[phase] >= 0.0, (phase, t)
    timetag.reset()


def test_disabled_is_noop():
    timetag.enable(False)
    timetag.reset()
    with timetag.scope("x") as s:
        s.sync(np.zeros(3))
    assert timetag.get_timings() == {}
