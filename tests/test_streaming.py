"""Two-round (streaming) file loading (io/streaming.py) must be
bit-identical to the one-round parse_file + from_matrix path — same
mappers, same bins, same labels — on every reference example format
(TSV, LibSVM), including the sampled-mappers path and reference-aligned
validation loading (dataset_loader.cpp:191-206 use_two_round)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.io.parser import parse_file
from lightgbm_tpu.io.streaming import load_file_two_round

REF = "/root/reference/examples"

CASES = [
    (f"{REF}/regression/regression.train", {}),
    (f"{REF}/binary_classification/binary.train", {}),
    (f"{REF}/lambdarank/rank.train", {}),          # libsvm
]


def _python_parse(path):
    """One-round parse via the PYTHON parser: the native C++ fast-atof
    differs from float() by ~1 ulp, and the streaming loader parses with
    Python — parity must be judged against the same value source."""
    from lightgbm_tpu.io.parser import _parse_delimited, _parse_libsvm
    from lightgbm_tpu.io.streaming import _data_lines, _probe_format
    fmt = _probe_format(path, False)
    lines = list(_data_lines(path, False))
    if fmt == "libsvm":
        return _parse_libsvm(lines, None)
    return _parse_delimited(lines, "," if fmt == "csv" else "\t", 0)


@pytest.mark.parametrize("path,kw", CASES)
def test_two_round_matches_one_round(path, kw):
    label, X = _python_parse(path)
    one = BinnedDataset.from_matrix(X, label, max_bin=63,
                                    min_data_in_leaf=20,
                                    bin_construct_sample_cnt=3000)
    two = load_file_two_round(path, max_bin=63, min_data_in_leaf=20,
                              bin_construct_sample_cnt=3000,
                              chunk_rows=997)      # force many chunks
    assert two.used_feature_map == one.used_feature_map
    for m1, m2 in zip(one.mappers, two.mappers):
        assert m1.num_bin == m2.num_bin
        np.testing.assert_array_equal(m1.bin_upper_bound, m2.bin_upper_bound)
    np.testing.assert_array_equal(two.bins, one.bins)
    np.testing.assert_allclose(two.metadata.label,
                               label.astype(np.float32))


def test_two_round_reference_aligned_valid():
    train = load_file_two_round(f"{REF}/binary_classification/binary.train",
                                max_bin=63, min_data_in_leaf=20)
    valid = load_file_two_round(f"{REF}/binary_classification/binary.test",
                                max_bin=63, min_data_in_leaf=20,
                                reference=train)
    assert valid.used_feature_map == train.used_feature_map
    label, X = _python_parse(f"{REF}/binary_classification/binary.test")
    direct = train.create_valid(X, label)
    np.testing.assert_array_equal(valid.bins, direct.bins)


def test_two_round_through_dataset_api():
    """use_two_round_loading=true flows through lgb.Dataset + training."""
    path = f"{REF}/binary_classification/binary.train"
    ds = lgb.Dataset(path, params={"use_two_round_loading": True,
                                   "max_bin": 63})
    ds2 = lgb.Dataset(path, params={"max_bin": 63})
    b1 = ds.construct()._binned
    b2 = ds2.construct()._binned
    # one-round uses the native fast-atof (values may differ by 1 ulp):
    # allow a vanishing fraction of boundary-straddling bin flips
    assert np.mean(b1.bins != b2.bins) < 1e-3
    np.testing.assert_array_equal(b1.metadata.label, b2.metadata.label)
    # side files (binary.train.weight) must load in both paths
    assert (b1.metadata.weights is None) == (b2.metadata.weights is None)
    bst = lgb.train({"objective": "binary", "verbose": -1, "num_leaves": 15},
                    lgb.Dataset(path,
                                params={"use_two_round_loading": True}),
                    num_boost_round=3)
    assert bst.num_trees() == 3


def test_two_round_categorical_features_respected():
    """categorical_feature must reach the streaming mapper construction
    (reviewed bug: it was silently dropped)."""
    import tempfile, os
    rng = np.random.RandomState(0)
    n = 800
    y = rng.randint(0, 2, size=n)
    num = rng.normal(size=n)
    cat = rng.randint(0, 5, size=n)
    path = os.path.join(tempfile.mkdtemp(), "cat.tsv")
    with open(path, "w") as fh:
        for i in range(n):
            fh.write(f"{y[i]}\t{num[i]:.6f}\t{cat[i]}\n")
    ds = lgb.Dataset(path, categorical_feature=[1],
                     params={"use_two_round_loading": True, "max_bin": 31,
                             "min_data_in_leaf": 10})
    b = ds.construct()._binned
    from lightgbm_tpu.io.binning import CATEGORICAL
    inner = b.real_to_inner[1]
    assert inner >= 0 and b.mappers[inner].bin_type == CATEGORICAL
