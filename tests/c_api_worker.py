"""Clean-process driver for tests/test_c_api.py.

The cffi embedding library boots an embedded CPython on its FIRST call;
that native boot spins forever when the host process already holds an
initialized jax runtime (ROADMAP item 6) — which pytest's conftest
guarantees.  So the pytest process only *builds* the library, and this
worker — a clean subprocess that has imported neither jax nor
lightgbm_tpu when it makes the first library call — drives the actual C
API flow.  One subprocess runs every scenario (one embedded boot, one
set of jit compiles) and writes a per-scenario JSON verdict the pytest
side asserts on.

Usage: python tests/c_api_worker.py <lib_path> <out_json> <tmp_dir>
"""

import ctypes
import json
import os
import sys
import traceback

import numpy as np

BINARY_TRAIN = "/root/reference/examples/binary_classification/binary.train"
BINARY_TEST = "/root/reference/examples/binary_classification/binary.test"

dtype_float32 = 0
dtype_float64 = 1
dtype_int32 = 2
dtype_int64 = 3


def _load_tsv(path):
    d = np.loadtxt(path)
    return d[:, 1:], d[:, 0].astype(np.float32)


def c_str(s):
    return ctypes.c_char_p(s.encode("ascii"))


def _check(lib, ret):
    assert ret == 0, lib.LGBM_GetLastError()


def _mat_handle(lib, X, y, params, reference=None):
    X = np.ascontiguousarray(X, np.float64)
    handle = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), dtype_float64,
        ctypes.c_int32(X.shape[0]), ctypes.c_int32(X.shape[1]), 1,
        c_str(params), reference, ctypes.byref(handle)))
    if y is not None:
        y = np.ascontiguousarray(y, np.float32)
        _check(lib, lib.LGBM_DatasetSetField(
            handle, c_str("label"), y.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(len(y)), dtype_float32))
    return handle


# ---------------------------------------------------------------------------
# scenarios (the reference tests/c_api_test/test.py flow, unchanged from
# the in-process era of tests/test_c_api.py)


def scenario_error_reporting(lib, tmp):
    handle = ctypes.c_void_p()
    ret = lib.LGBM_DatasetCreateFromFile(
        c_str("/nonexistent/file.txt"), c_str(""), None,
        ctypes.byref(handle))
    assert ret == -1
    assert b"" != lib.LGBM_GetLastError()


def scenario_dataset_io(lib, tmp):
    train = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromFile(
        c_str(BINARY_TRAIN), c_str("max_bin=15"), None, ctypes.byref(train)))
    num_data = ctypes.c_int(0)
    num_feat = ctypes.c_int(0)
    _check(lib, lib.LGBM_DatasetGetNumData(train, ctypes.byref(num_data)))
    _check(lib, lib.LGBM_DatasetGetNumFeature(train, ctypes.byref(num_feat)))
    assert num_data.value == 7000 and num_feat.value == 28

    X, y = _load_tsv(BINARY_TEST)

    # from mat, aligned to train's mappers
    test_h = _mat_handle(lib, X, y, "max_bin=15", train)
    _check(lib, lib.LGBM_DatasetGetNumData(test_h, ctypes.byref(num_data)))
    assert num_data.value == 500
    _check(lib, lib.LGBM_DatasetFree(test_h))

    # from CSR
    from scipy import sparse
    csr = sparse.csr_matrix(X)
    h = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromCSR(
        csr.indptr.ctypes.data_as(ctypes.c_void_p), dtype_int32,
        csr.indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        csr.data.ctypes.data_as(ctypes.c_void_p), dtype_float64,
        ctypes.c_int64(len(csr.indptr)), ctypes.c_int64(csr.nnz),
        ctypes.c_int64(X.shape[1]), c_str("max_bin=15"), train,
        ctypes.byref(h)))
    _check(lib, lib.LGBM_DatasetGetNumData(h, ctypes.byref(num_data)))
    assert num_data.value == 500
    _check(lib, lib.LGBM_DatasetFree(h))

    # from CSC
    csc = sparse.csc_matrix(X)
    _check(lib, lib.LGBM_DatasetCreateFromCSC(
        csc.indptr.ctypes.data_as(ctypes.c_void_p), dtype_int32,
        csc.indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        csc.data.ctypes.data_as(ctypes.c_void_p), dtype_float64,
        ctypes.c_int64(len(csc.indptr)), ctypes.c_int64(csc.nnz),
        ctypes.c_int64(X.shape[0]), c_str("max_bin=15"), train,
        ctypes.byref(h)))
    _check(lib, lib.LGBM_DatasetGetNumData(h, ctypes.byref(num_data)))
    assert num_data.value == 500
    _check(lib, lib.LGBM_DatasetFree(h))

    # save binary, reload
    bin_path = os.path.join(tmp, "train.binary.bin")
    _check(lib, lib.LGBM_DatasetSaveBinary(train, c_str(bin_path)))
    _check(lib, lib.LGBM_DatasetFree(train))
    _check(lib, lib.LGBM_DatasetCreateFromFile(
        c_str(bin_path), c_str("max_bin=15"), None, ctypes.byref(train)))
    _check(lib, lib.LGBM_DatasetGetNumData(train, ctypes.byref(num_data)))
    assert num_data.value == 7000
    _check(lib, lib.LGBM_DatasetFree(train))


def scenario_train_predict(lib, tmp):
    Xtr, ytr = _load_tsv(BINARY_TRAIN)
    Xte, yte = _load_tsv(BINARY_TEST)
    train = _mat_handle(lib, Xtr, ytr, "max_bin=63")
    test = _mat_handle(lib, Xte, yte, "max_bin=63", train)

    booster = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        train, c_str("app=binary metric=auc num_leaves=15 verbose=-1"),
        ctypes.byref(booster)))
    _check(lib, lib.LGBM_BoosterAddValidData(booster, test))

    n_classes = ctypes.c_int(0)
    _check(lib, lib.LGBM_BoosterGetNumClasses(booster,
                                              ctypes.byref(n_classes)))
    assert n_classes.value == 1

    is_finished = ctypes.c_int(0)
    aucs = []
    for _ in range(30):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(
            booster, ctypes.byref(is_finished)))
        result = np.zeros(1, dtype=np.float64)
        out_len = ctypes.c_int(0)
        _check(lib, lib.LGBM_BoosterGetEval(
            booster, 1, ctypes.byref(out_len),
            result.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        assert out_len.value == 1
        aucs.append(result[0])
    assert aucs[-1] > 0.80 and aucs[-1] >= aucs[0]

    it = ctypes.c_int(0)
    _check(lib, lib.LGBM_BoosterGetCurrentIteration(booster,
                                                    ctypes.byref(it)))
    assert it.value == 30

    # eval names
    cnt = ctypes.c_int(0)
    _check(lib, lib.LGBM_BoosterGetEvalCounts(booster, ctypes.byref(cnt)))
    assert cnt.value == 1
    bufs = [ctypes.create_string_buffer(255)]
    arr = (ctypes.c_char_p * 1)(*map(ctypes.addressof, bufs))
    _check(lib, lib.LGBM_BoosterGetEvalNames(booster, ctypes.byref(cnt),
                                             arr))
    assert bufs[0].value == b"auc"

    model_path = os.path.join(tmp, "model.txt")
    _check(lib, lib.LGBM_BoosterSaveModel(booster, -1, c_str(model_path)))
    _check(lib, lib.LGBM_BoosterFree(booster))
    _check(lib, lib.LGBM_DatasetFree(train))
    _check(lib, lib.LGBM_DatasetFree(test))

    # reload + predict
    booster2 = ctypes.c_void_p()
    n_iters = ctypes.c_int(0)
    _check(lib, lib.LGBM_BoosterCreateFromModelfile(
        c_str(model_path), ctypes.byref(n_iters), ctypes.byref(booster2)))
    assert n_iters.value == 30

    flat = np.ascontiguousarray(Xte, np.float64)
    preb = np.zeros(Xte.shape[0], dtype=np.float64)
    num_preb = ctypes.c_int64(0)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        booster2, flat.ctypes.data_as(ctypes.c_void_p), dtype_float64,
        ctypes.c_int32(Xte.shape[0]), ctypes.c_int32(Xte.shape[1]), 1,
        0, -1, ctypes.byref(num_preb),
        preb.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert num_preb.value == Xte.shape[0]
    assert 0.0 <= preb.min() and preb.max() <= 1.0

    # parity vs the python surface on the same model.  Importing
    # lightgbm_tpu (and thus jax) is safe HERE: the embedded interpreter
    # booted at the first lib call above, sharing this process's
    # CPython — the hang only occurs the other way around.
    import lightgbm_tpu as lgb
    pyb = lgb.Booster(model_file=model_path)
    np.testing.assert_allclose(preb, pyb.predict(Xte), rtol=1e-10)

    # file predict
    out_path = os.path.join(tmp, "preb.txt")
    _check(lib, lib.LGBM_BoosterPredictForFile(
        booster2, c_str(BINARY_TEST), 0, 0, -1, c_str(out_path)))
    file_pred = np.loadtxt(out_path)
    assert file_pred.shape[0] == Xte.shape[0]
    np.testing.assert_allclose(file_pred, preb, atol=5e-6)

    # leaf index predictions
    n_pred = ctypes.c_int64(0)
    _check(lib, lib.LGBM_BoosterCalcNumPredict(booster2, 5, 2, -1,
                                               ctypes.byref(n_pred)))
    leaves = np.zeros(int(n_pred.value), dtype=np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        booster2, flat.ctypes.data_as(ctypes.c_void_p), dtype_float64,
        ctypes.c_int32(5), ctypes.c_int32(Xte.shape[1]), 1,
        2, -1, ctypes.byref(num_preb),
        leaves.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert num_preb.value == 5 * 30
    assert np.all(leaves >= 0) and np.all(leaves < 15)
    _check(lib, lib.LGBM_BoosterFree(booster2))


def scenario_push_rows(lib, tmp):
    """CreateFromSampledColumn + PushRows streaming construction
    (c_api.cpp:341-415) must produce the same bins as CreateFromMat."""
    rng = np.random.RandomState(7)
    X = rng.normal(size=(400, 3)).astype(np.float64)
    y = (X[:, 0] > 0).astype(np.float32)

    cols = [np.ascontiguousarray(X[:, i]) for i in range(3)]
    col_ptrs = (ctypes.POINTER(ctypes.c_double) * 3)(
        *[c.ctypes.data_as(ctypes.POINTER(ctypes.c_double)) for c in cols])
    idxs = [np.arange(400, dtype=np.int32) for _ in range(3)]
    idx_ptrs = (ctypes.POINTER(ctypes.c_int) * 3)(
        *[i.ctypes.data_as(ctypes.POINTER(ctypes.c_int)) for i in idxs])
    num_per_col = (ctypes.c_int * 3)(400, 400, 400)

    handle = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromSampledColumn(
        col_ptrs, idx_ptrs, ctypes.c_int32(3), num_per_col,
        ctypes.c_int32(400), ctypes.c_int32(400),
        c_str("max_bin=31 min_data_in_leaf=5"), ctypes.byref(handle)))
    # push in two chunks
    for start, stop in ((0, 250), (250, 400)):
        chunk = np.ascontiguousarray(X[start:stop])
        _check(lib, lib.LGBM_DatasetPushRows(
            handle, chunk.ctypes.data_as(ctypes.c_void_p), dtype_float64,
            ctypes.c_int32(stop - start), ctypes.c_int32(3),
            ctypes.c_int32(start)))
    _check(lib, lib.LGBM_DatasetSetField(
        handle, c_str("label"), y.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int(len(y)), dtype_float32))

    direct = _mat_handle(lib, X, y, "max_bin=31 min_data_in_leaf=5")

    # verify by training boosters on both and comparing one iteration
    b1 = ctypes.c_void_p()
    b2 = ctypes.c_void_p()
    params = "app=binary num_leaves=7 verbose=-1 min_data_in_leaf=5"
    _check(lib, lib.LGBM_BoosterCreate(handle, c_str(params),
                                       ctypes.byref(b1)))
    _check(lib, lib.LGBM_BoosterCreate(direct, c_str(params),
                                       ctypes.byref(b2)))
    fin = ctypes.c_int(0)
    for b in (b1, b2):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(b, ctypes.byref(fin)))
    out = []
    for b in (b1, b2):
        pred = np.zeros(400, dtype=np.float64)
        n = ctypes.c_int64(0)
        _check(lib, lib.LGBM_BoosterPredictForMat(
            b, X.ctypes.data_as(ctypes.c_void_p), dtype_float64,
            ctypes.c_int32(400), ctypes.c_int32(3), 1, 1, -1,
            ctypes.byref(n),
            pred.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        out.append(pred)
    np.testing.assert_allclose(out[0], out[1], rtol=1e-12)
    _check(lib, lib.LGBM_BoosterFree(b1))
    _check(lib, lib.LGBM_BoosterFree(b2))
    _check(lib, lib.LGBM_DatasetFree(handle))
    _check(lib, lib.LGBM_DatasetFree(direct))


SCENARIOS = [
    # error_reporting first: the cheapest possible call boots the
    # embedded interpreter before anything heavier can time out around it
    ("error_reporting", scenario_error_reporting, False),
    ("push_rows", scenario_push_rows, False),
    ("dataset_io", scenario_dataset_io, True),
    ("train_predict", scenario_train_predict, True),
]


def main() -> int:
    lib_path, out_path, tmp = sys.argv[1], sys.argv[2], sys.argv[3]
    assert "jax" not in sys.modules, \
        "worker must not import jax before the first library call"
    lib = ctypes.cdll.LoadLibrary(lib_path)
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    results = {}
    for name, fn, needs_ref in SCENARIOS:
        if needs_ref and not os.path.exists(BINARY_TRAIN):
            results[name] = {"status": "skip",
                             "detail": "/root/reference not available"}
            continue
        try:
            fn(lib, tmp)
            results[name] = {"status": "ok"}
        except Exception:
            results[name] = {"status": "fail",
                             "detail": traceback.format_exc()}
    with open(out_path, "w") as fh:
        json.dump(results, fh, indent=2)
    # rc 0 even with failing scenarios: the pytest side asserts each
    # scenario separately, with the recorded traceback as the message
    return 0


if __name__ == "__main__":
    sys.exit(main())
