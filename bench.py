"""Benchmark: boosting iterations/sec on a Higgs-like binary task.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "iters/sec", "vs_baseline": N}

Workload (mirrors the reference's recommended operating point,
examples/binary_classification/train.conf + BASELINE.json configs):
binary logloss objective, 28 features, num_leaves=63, max_bin=255,
learning_rate=0.1, min_data_in_leaf=50.  Rows default to 1M synthetic
Higgs-like events (override with BENCH_ROWS).

vs_baseline compares against the reference LightGBM CLI (v2 C++, OpenMP,
all cores) measured on THIS repo's build box on the identical synthetic
dataset and config: see CPU_REF_ITERS_PER_SEC provenance note below.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

import numpy as np

# Reference CPU baseline, measured once on the build host:
#   /root/reference built with cmake -DCMAKE_BUILD_TYPE=Release (GCC 12,
#   OpenMP; host exposes 1 core), run on the identical synthetic 1M x 28
#   dataset (make_higgs_like seed 42, CSV) with num_leaves=63 max_bin=255
#   learning_rate=0.1 min_data_in_leaf=50 num_trees=40; steady-state
#   per-iteration wall time from the CLI's "seconds elapsed" log over
#   iterations 10..40: 4.17 iters/sec.
CPU_REF_ITERS_PER_SEC = {
    1_000_000: 4.17,
}


def make_higgs_like(num_data: int, num_features: int = 28, seed: int = 42):
    """Synthetic stand-in for the Higgs dataset: a few informative
    low-level features, quadratic 'derived' features, heavy noise."""
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(num_data, num_features)).astype(np.float32)
    X[:, 7:14] = np.abs(X[:, 7:14])            # energy-like positives
    X[:, 14:21] = X[:, 0:7] * X[:, 7:14]       # derived products
    logit = (0.8 * X[:, 0] - 0.6 * X[:, 1] + 0.5 * X[:, 14]
             - 0.4 * X[:, 15] + 0.3 * X[:, 7] * X[:, 2]
             + rng.normal(scale=1.5, size=num_data))
    y = (logit > 0).astype(np.float32)
    return X.astype(np.float64), y


def make_ctr_like(num_data: int, num_features: int = 2000,
                  block_size: int = 20, seed: int = 9):
    """Wide-sparse CTR-style synthetic: one-hot-ish blocks so real
    exclusive bundles exist (docs/SPARSE.md).

    Features come in blocks of ``block_size``; each row activates at most
    ONE feature per block (a categorical one-hot) with a small integer
    level value, so features within a block are perfectly mutually
    exclusive — exactly what EFB packs — and overall sparsity lands
    around 95-97%.  The label is a logistic read-out of a sparse subset
    of (feature, level) weights plus noise."""
    rng = np.random.RandomState(seed)
    num_blocks = max(num_features // block_size, 1)
    F = num_blocks * block_size
    X = np.zeros((num_data, F))
    logit = rng.normal(scale=0.6, size=num_data)
    w = rng.normal(scale=1.0, size=F) * (rng.rand(F) < 0.15)
    idx = np.arange(num_data)
    for b in range(num_blocks):
        act = rng.rand(num_data) < 0.6          # block fires on 60% of rows
        choice = b * block_size + rng.randint(0, block_size, num_data)
        level = rng.randint(1, 5, num_data).astype(np.float64)
        rows = idx[act]
        X[rows, choice[act]] = level[act]
        logit[rows] += w[choice[act]] * level[act] * 0.25
    y = (logit > np.median(logit)).astype(np.float32)
    return X, y


def make_piecewise_linear(num_data: int, num_features: int = 10,
                          seed: int = 5):
    """Piece-wise linear regression synthetic (docs/LINEAR_TREES.md):
    axis-aligned regions whose responses are AFFINE in a few features —
    the workload linear trees are built for.  Constant-leaf trees must
    staircase each slope; an affine leaf captures it in one fit."""
    rng = np.random.RandomState(seed)
    X = rng.uniform(-3.0, 3.0, size=(num_data, num_features))
    y = np.where(X[:, 0] > 0.0,
                 2.0 * X[:, 1] - 0.7 * X[:, 2] + 1.0,
                 np.where(X[:, 1] > 0.5,
                          -1.5 * X[:, 2] + 0.4 * X[:, 3],
                          0.8 * X[:, 3] + 0.3))
    y = y + 0.05 * rng.normal(size=num_data)
    return X, y.astype(np.float64)


def bench_linear() -> None:
    """--dataset linear: piece-wise linear trees A/B benchmark.

    Trains a constant-leaf and a linear-leaf booster on the same
    piece-wise linear synthetic and reports trees-to-target (rounds the
    linear run needs to reach the constant run's best l2), per-round
    fit seconds, and the leaf-fit fallback rate.  One BENCH-style JSON
    line; ``linear`` block passed through by tools/bench_regress.py."""
    num_data = int(os.environ.get("BENCH_LINEAR_ROWS", 100_000))
    num_iters = int(os.environ.get("BENCH_LINEAR_ITERS", 60))
    max_feats = int(os.environ.get("BENCH_LINEAR_K", 4))

    import jax
    from lightgbm_tpu.utils import compile_cache
    compile_cache.setup()
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu import obs as _obs
    _obs.devprof.configure(None)

    X, y = make_piecewise_linear(num_data)
    params = {"objective": "regression", "metric": "l2",
              "num_leaves": 31, "max_bin": 255, "learning_rate": 0.1,
              "min_data_in_leaf": 50, "num_iterations": num_iters}
    ds = BinnedDataset.from_matrix(X, y, max_bin=255, min_data_in_leaf=50,
                                   keep_raw=True)

    def run(linear: bool):
        p = dict(params)
        if linear:
            p.update({"linear_tree": True, "linear_lambda": 0.01,
                      "linear_max_leaf_features": max_feats})
        booster = GBDT(Config(p), ds)
        per_round = []
        curve = []
        for _ in range(num_iters):
            t0 = time.time()
            booster.train_one_iter()
            jax.block_until_ready(booster.train_data.score)
            per_round.append(time.time() - t0)
            curve.append(float(booster.eval_metrics()
                               .get("training", {}).get("l2", np.inf)))
        return booster, per_round, curve

    fb_before = _obs.get_counter("linear_fallback_total")
    t0 = time.time()
    _, const_rounds, const_curve = run(linear=False)
    _, lin_rounds, lin_curve = run(linear=True)
    total_s = time.time() - t0
    fb_total = _obs.get_counter("linear_fallback_total") - fb_before

    target = min(const_curve)                    # constant run's best l2
    trees_to_target = next(
        (i + 1 for i, v in enumerate(lin_curve) if v <= target), None)
    num_leaves = int(params["num_leaves"])
    fit_rate = fb_total / float(num_iters * num_leaves)

    bench_json = {
        "metric": f"linear_tree_ab_piecewise{num_data // 1000}k_"
                  f"31leaves_l2",
        "value": (round(trees_to_target / float(num_iters), 4)
                  if trees_to_target else None),
        "unit": "tree_ratio_to_const_best",
        "linear": {
            "rows": num_data,
            "iterations": num_iters,
            "max_leaf_features": max_feats,
            "const_best_l2": round(target, 6),
            "linear_best_l2": round(min(lin_curve), 6),
            "trees_to_const_best": trees_to_target,
            "const_round_s_median": round(
                statistics.median(const_rounds), 4),
            "linear_round_s_median": round(
                statistics.median(lin_rounds), 4),
            "fit_s_per_round_median": round(
                statistics.median(lin_rounds)
                - statistics.median(const_rounds), 4),
            "fallback_total": int(fb_total),
            "fallback_rate": round(fit_rate, 4),
        },
        "compile_events": None,
    }
    from lightgbm_tpu.obs import compile_ledger
    bench_json["compile_events"] = compile_ledger.summary(5)
    bench_json["profile"], bench_json["device"] = _profile_blocks()
    print(json.dumps(bench_json))
    print(f"# device={jax.devices()[0].platform} total_s={total_s:.1f} "
          f"const_best={target:.6f} linear_best={min(lin_curve):.6f} "
          f"trees_to_target={trees_to_target} fallback={fb_total}",
          file=sys.stderr)


def _profile_blocks():
    """The BENCH ``profile`` + ``device`` blocks (obs/devprof.py,
    obs/devcaps.py).  Always emitted: ``profile.mode`` records whether
    device-time attribution ran (arm it with LIGHTGBM_TPU_DEVPROF), and
    ``device`` makes every archived BENCH_r*.json self-describing about
    the hardware and peak numbers that produced it."""
    import jax
    from lightgbm_tpu.obs import report
    prof = report.profile_summary()
    caps = prof["device"]
    profile = {
        "mode": prof["mode"],
        "rounds": prof["rounds"],
        "device_seconds_est_total": prof["device_seconds_est_total"],
        "samples_total": prof["samples_total"],
        "dispatches_total": prof["dispatches_total"],
        "programs": prof["programs"],
        "transfers": prof["transfers"],
    }
    device = {
        "platform": caps.get("platform"),
        "device_kind": caps.get("device_kind"),
        "device_count": jax.device_count(),
        "peak_flops": caps.get("peak_flops"),
        "peak_bytes_per_sec": caps.get("peak_bytes_per_sec"),
        "peaks_source": caps.get("source"),
        "jax_version": jax.__version__,
    }
    return profile, device


def _fleet_scaling(booster, X32: np.ndarray, concurrency: int) -> dict:
    """``--concurrency N``: threaded closed-loop clients against the
    serving fleet at every replica count 1..len(local_devices) — the
    1->K scaling curve as numbers.  Per replica count: aggregate and
    per-replica rows/sec, shed rate, client p50/p99.  On a CPU box,
    XLA_FLAGS=--xla_force_host_platform_device_count=K simulates K
    devices (docs/SERVING.md §Benchmark)."""
    import threading

    import jax
    from lightgbm_tpu.serve.batcher import default_ladder
    from lightgbm_tpu.serve.fleet import Fleet, Overloaded
    from lightgbm_tpu.serve.forest import CompiledForest

    batch = int(os.environ.get("BENCH_PREDICT_FLEET_BATCH", 1024))
    calls = int(os.environ.get("BENCH_PREDICT_FLEET_CALLS", 30))
    queue_depth = int(os.environ.get("BENCH_PREDICT_QUEUE_DEPTH", 128))
    rows = X32.shape[0]
    batch = min(batch, rows)
    # a fleet-sized ladder: every replica warms it, so keep it at the
    # client batch instead of the offline 65536 ladder
    forest = CompiledForest.from_booster(
        booster, buckets=default_ladder(16, batch))
    devs = jax.local_devices()
    out = {}
    for R in range(1, len(devs) + 1):
        fleet = Fleet.build(forest, devices=devs[:R], max_batch=batch,
                            max_delay_s=0.002, max_queue=queue_depth)
        lat: list = []
        served = [0] * concurrency
        shed = [0] * concurrency

        def client(ci: int) -> None:
            for i in range(calls):
                off = ((i * concurrency + ci) * batch) \
                    % max(rows - batch + 1, 1)
                t0 = time.time()
                try:
                    fleet.submit(X32[off:off + batch], timeout=300.0)
                except Overloaded:
                    shed[ci] += 1
                    continue
                lat.append((time.time() - t0) * 1000.0)
                served[ci] += batch

        threads = [threading.Thread(target=client, args=(ci,))
                   for ci in range(concurrency)]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.time() - t0
        per_replica = [
            round(rep["requests"] * batch / wall, 1)
            for rep in fleet.stats()["replicas"]]
        fleet.close()
        attempts = concurrency * calls
        out[str(R)] = {
            "rows_per_sec": round(sum(served) / wall, 1),
            "per_replica_rows_per_sec": per_replica,
            "shed_rate": round(sum(shed) / attempts, 4),
            "p50_ms": round(float(np.percentile(lat, 50)), 3) if lat
            else None,
            "p99_ms": round(float(np.percentile(lat, 99)), 3) if lat
            else None,
        }
    return out


def predict_main(concurrency: int = 0) -> None:
    """--mode predict: serving throughput/latency benchmark.

    Trains a small forest at the reference operating point (63 leaves,
    255 bins, binary), freezes it into a ``serve.CompiledForest``, warms
    every bucket, then measures the fused device-binned predict path
    (the server hot path) per batch size.  One BENCH-style JSON line:
    rows/sec at the largest batch as the headline, per-batch-size
    rows/sec + p50/p99 call latency in ``batches``.  With
    ``--concurrency N`` the JSON gains a ``fleet`` block: closed-loop
    clients against 1..K device replicas (``_fleet_scaling``)."""
    rows = int(os.environ.get("BENCH_PREDICT_ROWS", 1_000_000))
    train_rows = int(os.environ.get("BENCH_PREDICT_TRAIN_ROWS", 100_000))
    trees = int(os.environ.get("BENCH_PREDICT_TREES", 40))
    calls = int(os.environ.get("BENCH_PREDICT_CALLS", 30))
    sizes = [int(s) for s in os.environ.get(
        "BENCH_PREDICT_BATCHES", "256,2048,16384,65536").split(",")]
    sizes = [s for s in sizes if s <= rows] or [rows]

    import jax
    from lightgbm_tpu.utils import compile_cache
    compile_cache.setup()
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.serve.forest import CompiledForest
    from lightgbm_tpu import obs
    # bench drives GBDT directly (no engine.train), so arm device-time
    # attribution here: LIGHTGBM_TPU_DEVPROF=sample:N|full populates the
    # BENCH `profile` block; unset leaves it off (zero overhead)
    obs.devprof.configure(None)

    X, y = make_higgs_like(rows)
    cfg = Config({"objective": "binary", "metric": "auc",
                  "num_leaves": 63, "max_bin": 255, "learning_rate": 0.1,
                  "min_data_in_leaf": 50, "num_iterations": trees})
    t0 = time.time()
    ds = BinnedDataset.from_matrix(X[:train_rows], y[:train_rows],
                                   max_bin=255, min_data_in_leaf=50)
    booster = GBDT(cfg, ds)
    for _ in range(trees):
        booster.train_one_iter()
    t_train = time.time() - t0

    t0 = time.time()
    from lightgbm_tpu.serve.batcher import default_ladder
    # ladder capped at the largest measured size (default_ladder always
    # includes its `hi` endpoint), so warmup() covers every bucket any
    # measured batch can route to — no hidden compile in the timings
    forest = CompiledForest.from_booster(
        booster, buckets=default_ladder(16, max(sizes)))
    forest.warmup()
    t_warm = time.time() - t0

    # drift observatory riding the measured traffic: a threadless
    # collector hangs off the forest so every timed batch is also drift
    # accounting — the BENCH `drift` block reports the window PSI summary
    # and the collector's own compute seconds (docs/OBSERVABILITY.md
    # §Drift).  No fingerprint on the model = no block, nothing attached.
    from lightgbm_tpu.obs.drift import DriftCollector
    drift_col = None
    if forest.data_fingerprint is not None:
        drift_col = DriftCollector(forest.data_fingerprint, model="bench",
                                   window_s=3600.0, start_thread=False)
        forest._drift = drift_col

    X32 = X.astype(np.float32)
    batches = {}
    for size in sizes:
        # touch distinct row windows so cache effects resemble traffic
        lat = []
        done = 0
        for i in range(calls):
            off = (i * size) % max(rows - size + 1, 1)
            t0 = time.time()
            raw, out = forest.batched_fn()(X32[off:off + size])
            np.asarray(out)                      # block until materialized
            lat.append((time.time() - t0) * 1000.0)
            done += size
        total_s = sum(lat) / 1000.0
        batches[str(size)] = {
            "rows_per_sec": round(done / total_s, 1),
            "p50_ms": round(float(np.percentile(lat, 50)), 3),
            "p99_ms": round(float(np.percentile(lat, 99)), 3),
        }
    # small-batch latency sweep on BOTH walk strategies (docs/SERVING.md
    # strategy matrix): batch 1/16/64/256 is the p50/p99 regime single
    # user requests live in; tools/bench_regress.py --latency-threshold
    # gates p99 per (strategy, batch) point of this block
    sweep_sizes = [int(s) for s in os.environ.get(
        "BENCH_LATENCY_BATCHES", "1,16,64,256").split(",")]
    sweep_sizes = [s for s in sweep_sizes if s <= rows] or [1]
    sweep_calls = int(os.environ.get("BENCH_LATENCY_CALLS", 15))
    latency_sweep = {"active": forest.walk_strategy, "strategies": {}}
    for strat in ("gather", "fused"):
        if forest.walk_strategy == strat:
            f2 = forest
        else:
            f2 = CompiledForest.from_booster(
                booster, buckets=default_ladder(16, max(sizes)),
                serve_walk=strat)
            f2.warmup(max_bucket=max(sweep_sizes))
        fn = f2.batched_fn()
        pts = {}
        for size in sweep_sizes:
            lat = []
            for i in range(sweep_calls):
                off = (i * size) % max(rows - size + 1, 1)
                t0 = time.time()
                raw, out = fn(X32[off:off + size])
                np.asarray(out)                  # block until materialized
                lat.append((time.time() - t0) * 1000.0)
            pts[str(size)] = {
                "p50_ms": round(float(np.percentile(lat, 50)), 3),
                "p99_ms": round(float(np.percentile(lat, 99)), 3),
            }
        latency_sweep["strategies"][strat] = pts

    drift_block = None
    if drift_col is not None:
        forest._drift = None
        win = drift_col.flush() or {}
        st = drift_col.stats()
        feats = win.get("features") or {}
        max_psi = max((d["psi"] for d in feats.values()), default=None)
        drift_block = {
            "windows": int(st["windows"]),
            "rows": int(st["rows"]),
            "dropped": int(st["dropped"]),
            "overhead_s": round(float(st["overhead_s"]), 6),
            "max_psi": (round(float(max_psi), 6)
                        if max_psi is not None else None),
            "score_psi": (round(float(win["score_psi"]), 6)
                          if win.get("score_psi") is not None else None),
        }
        drift_col.close()

    top = batches[str(max(sizes))]
    # availability bill over the fleet run (round 9, serve/health.py):
    # hedged retries / ejections / deadline sheds as counter deltas —
    # informational BENCH keys, passed through by bench_regress
    _avail_keys = ("serve_retries_total", "serve_ejections_total",
                   "serve_deadline_expired_total", "serve_shed_total")
    avail0 = {k: obs.get_counter(k) for k in _avail_keys}
    fleet = _fleet_scaling(booster, X32, concurrency) if concurrency \
        else None
    from lightgbm_tpu.obs import compile_ledger
    result = {
        "metric": f"serve_rows_per_sec_higgslike_{trees}trees_"
                  "63leaves_255bins_binary",
        "value": top["rows_per_sec"],
        "unit": "rows/sec",
        "vs_baseline": None,
        "batches": batches,
        "latency_sweep": latency_sweep,
        "warmup_s": round(t_warm, 3),
        "compile_events": compile_ledger.summary(5),
    }
    if drift_block is not None:
        result["drift"] = drift_block
    result["profile"], result["device"] = _profile_blocks()
    if fleet is not None:
        result["concurrency"] = concurrency
        result["fleet"] = fleet
        result["availability"] = {
            k: obs.get_counter(k) - avail0[k] for k in _avail_keys}
    print(json.dumps(result))
    c = obs.snapshot()["counters"]
    tail = ""
    if fleet is not None:
        tail = (" fleet_rows_per_sec=" + ",".join(
            f"{r}:{fleet[r]['rows_per_sec']:g}" for r in sorted(
                fleet, key=int)))
    print(f"# device={jax.devices()[0].platform} train_s={t_train:.1f} "
          f"warmup_s={t_warm:.1f} calls_per_size={calls} "
          f"serve_compiles={c.get('serve_forest_compiles', 0)} "
          f"post_warmup_compiles_expected=0"
          f"{tail}", file=sys.stderr)


def main(dataset: str = "higgslike") -> None:
    num_data = int(os.environ.get("BENCH_ROWS", 1_000_000))
    num_warmup = int(os.environ.get("BENCH_WARMUP", 5))
    num_timed = int(os.environ.get("BENCH_ITERS", 30))
    # median over >=3 timed windows: the tunneled device is load-noisy
    # (identical code measured 5.9-7.5 it/s across a day — see
    # docs/BENCH_NOTES_r03.md), so a single window reflects box load as
    # much as code.  Each window is num_timed iterations; the reported
    # value is the median of the per-window rates.
    num_windows = max(int(os.environ.get("BENCH_WINDOWS", 3)), 1)

    import jax
    # persistent XLA compilation cache: the grow program compiles in
    # minutes on the remote AOT service; repeat runs (and the driver's
    # bench run after any local run) hit the cache instead — the same
    # helper engine.train and the CLI now use (utils/compile_cache.py).
    from lightgbm_tpu.utils import compile_cache
    compile_cache.setup()
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu import obs as _obs_p
    # bench drives GBDT directly (no engine.train), so arm device-time
    # attribution here: LIGHTGBM_TPU_DEVPROF=sample:N|full populates the
    # BENCH `profile` block; unset leaves it off (zero overhead)
    _obs_p.devprof.configure(None)

    params = {"objective": "binary", "metric": "auc",
              "num_leaves": 63, "max_bin": 255, "learning_rate": 0.1,
              "min_data_in_leaf": 50,
              "num_iterations": num_warmup + num_windows * num_timed}
    bin_kwargs = {}
    if dataset == "ctrlike":
        # wide-sparse mode (docs/SPARSE.md §Bench recipe): ~500k x 2000
        # at ~95% sparsity with one-hot blocks, so real exclusive
        # bundles exist.  BENCH_ENABLE_BUNDLE / BENCH_SCREEN_RATIO toggle
        # the two wide-sparse optimizations for A/B BENCH runs compared
        # by tools/bench_regress.py.
        num_data = int(os.environ.get("BENCH_CTR_ROWS", 500_000))
        num_feat = int(os.environ.get("BENCH_CTR_FEATURES", 2000))
        enable_bundle = os.environ.get(
            "BENCH_ENABLE_BUNDLE", "1").lower() in ("1", "true", "yes")
        screen_ratio = float(os.environ.get("BENCH_SCREEN_RATIO", "0"))
        X, y = make_ctr_like(num_data, num_feat)
        params.update({
            "enable_bundle": enable_bundle,
            "feature_screen_ratio": screen_ratio,
            "feature_screen_warmup": int(os.environ.get(
                "BENCH_SCREEN_WARMUP", num_warmup)),
            "feature_screen_refresh": int(os.environ.get(
                "BENCH_SCREEN_REFRESH", 10)),
        })
        bin_kwargs = {"enable_bundle": enable_bundle,
                      # bound the host sample: 2000 f64 columns x 200k
                      # sampled rows would be 3.2 GB of transient RAM
                      "bin_construct_sample_cnt": int(os.environ.get(
                          "BENCH_CTR_SAMPLE", 50_000))}
        metric_name = (f"boosting_iters_per_sec_ctrlike"
                       f"{num_data // 1000}k_{X.shape[1]}f_"
                       "63leaves_255bins_binary")
    else:
        X, y = make_higgs_like(num_data)
        metric_name = (f"boosting_iters_per_sec_higgslike"
                       f"{num_data // 1000}k_63leaves_255bins_binary")
    cfg = Config(params)
    t0 = time.time()
    ds = BinnedDataset.from_matrix(X, y, max_bin=255, min_data_in_leaf=50,
                                   **bin_kwargs)
    t_bin = time.time() - t0

    booster = GBDT(cfg, ds)
    t0 = time.time()
    for _ in range(num_warmup):
        booster.train_one_iter()
    jax.block_until_ready(booster.train_data.score)
    t_warm = time.time() - t0

    rates = []
    for _ in range(num_windows):
        t0 = time.time()
        for _ in range(num_timed):
            booster.train_one_iter()
        jax.block_until_ready(booster.train_data.score)
        rates.append(num_timed / (time.time() - t0))
    # median() sorts its own copy: `rates` must stay in measurement order
    # for the stderr `windows=` diagnostic (load drift over time is the
    # signal a pre-sorted list destroys)
    iters_per_sec = statistics.median(rates)
    # the CPU reference numbers are higgslike-only: a ctrlike run whose
    # row count happens to collide must not compare across workloads
    base = (CPU_REF_ITERS_PER_SEC.get(num_data)
            if dataset == "higgslike" else None)
    vs = (iters_per_sec / base) if base else None
    auc = booster.eval_metrics().get("training", {}).get("auc")

    # cold-vs-warm warmup split: a SECOND booster over the same dataset
    # re-runs the warmup iterations.  With the shared train_step/grow
    # programs (models/gbdt.py) it must hit the in-process jit caches —
    # zero new compiles — so warm warmup measures the steady-state cost a
    # restarted-but-cache-warm run pays, while warmup_cold_s keeps the
    # first-boot compile tax.  bench_regress gates the cold number.
    from lightgbm_tpu.obs import compile_ledger
    n_cold_events = len(compile_ledger.events())
    del booster                      # free the first booster's HBM first
    t0 = time.time()
    booster = GBDT(cfg, ds)
    for _ in range(num_warmup):
        booster.train_one_iter()
    jax.block_until_ready(booster.train_data.score)
    t_warm_warm = time.time() - t0
    warm_events = compile_ledger.events()[n_cold_events:]

    bench_json = {
        "metric": metric_name,
        "value": round(iters_per_sec, 4),
        "unit": "iters/sec",
        "vs_baseline": round(vs, 4) if vs is not None else None,
        "warmup_s": round(t_warm, 3),
        "warmup_cold_s": round(t_warm, 3),
        "warmup_warm_s": round(t_warm_warm, 3),
        "warmup_warm_compiles": len(warm_events),
        "spread": [round(min(rates), 4), round(max(rates), 4)],
        "compile_events": compile_ledger.summary(5),
    }
    bench_json["profile"], bench_json["device"] = _profile_blocks()
    if auc is not None:
        bench_json["auc"] = round(float(auc), 5)
    if dataset == "ctrlike":
        # wide-sparse bill (docs/SPARSE.md): how far EFB shrank the
        # feature space and what screening kept active — informational
        # BENCH keys, passed through by bench_regress
        from lightgbm_tpu import obs as _obs2
        plan = ds.bundle_plan
        bench_json["efb"] = {
            "enabled": bool(params["enable_bundle"]),
            "num_features": int(ds.num_features),
            "columns": int(ds.num_columns),
            "bundles": len(plan.bundles) if plan is not None else 0,
            "features_bundled": (plan.features_bundled
                                 if plan is not None else 0),
            "sample_conflicts": (plan.sample_conflicts
                                 if plan is not None else 0),
        }
        bench_json["screening"] = {
            "ratio": float(params["feature_screen_ratio"]),
            "refresh": int(params["feature_screen_refresh"]),
            "warmup": int(params["feature_screen_warmup"]),
            "active_features_last": int(
                _obs2.get_gauge("screen_active_features") or 0),
            "refresh_total": int(
                _obs2.get_counter("screen_refresh_total")),
        }
    # resource bill (PR 15, utils/resource.py + utils/diskguard.py):
    # estimated vs measured peak bytes, degrade steps taken, sink write
    # errors — a throughput number from a degraded run must carry its
    # asterisk (bench_regress passes `resource` through informationally)
    from lightgbm_tpu import obs as _obs_r
    from lightgbm_tpu.obs import memwatch as _memwatch
    from lightgbm_tpu.utils.resource import DEGRADE_STEPS as _STEPS
    _mw = _memwatch.sample()
    bench_json["resource"] = {
        "estimated_peak_bytes": int(
            _obs_r.get_gauge("hbm_train_estimate_bytes") or 0),
        "measured_peak_bytes": int(
            _mw.get("device_peak_bytes",
                    _mw.get("peak_live_bytes", _mw.get("live_bytes", 0)))),
        "degrade_steps": [s for s in _STEPS if _obs_r.get_counter(
            "resource_degrade_" + s)],
        "sink_write_errors": int(
            _obs_r.get_counter("sink_write_errors_total")),
        "device_oom": int(_obs_r.get_counter("device_oom_total")),
    }
    # data-boundary bill (PR 13, io/guard.py): when a file-fed run
    # quarantined rows, say so in the BENCH JSON — a throughput number
    # from a partially-skipped dataset must carry its asterisk
    # (bench_regress passes bad_rows through informationally)
    from lightgbm_tpu import obs as _obs
    _bad_total = _obs.get_counter("bad_rows_total")
    if _bad_total:
        _counters = _obs.snapshot()["counters"]
        bench_json["bad_rows"] = {
            "total": _bad_total,
            **{k[len("bad_rows_"):]: v for k, v in sorted(
                _counters.items())
               if k.startswith("bad_rows_") and k != "bad_rows_total"},
        }
    print(json.dumps(bench_json))
    # trailing comment line only — the JSON line above is the contract.
    # LIGHTGBM_TPU_TIMETAG=1 folds the serializing per-phase breakdown in
    # so BENCH_*.json tails carry phase data; the obs counters are always
    # on (and must stay free: the acceptance gate for the telemetry layer
    # is that a disabled-telemetry run sits inside the window spread).
    from lightgbm_tpu import obs
    from lightgbm_tpu.utils import timetag
    tail = ""
    if timetag.ENABLED:
        t = timetag.get_timings()
        if t:
            tail += " phases=" + json.dumps(
                {k: round(v, 3) for k, v in sorted(t.items())},
                separators=(",", ":"))
    c = obs.snapshot()["counters"]
    tail += (f" obs_iters={c.get('iterations', 0)}"
             f" obs_trees={c.get('trees_grown', 0)}"
             f" obs_d2h={c.get('device_to_host_transfers', 0)}"
             f" obs_comm_bytes={c.get('comm_collective_bytes', 0)}")
    print(f"# device={jax.devices()[0].platform} bin_s={t_bin:.1f} "
          f"warmup_s={t_warm:.1f} warm_warmup_s={t_warm_warm:.1f} "
          f"timed_iters={num_timed} "
          f"windows={[round(r, 3) for r in rates]} "
          f"spread={min(rates):.3f}-{max(rates):.3f} "
          f"auc={auc}"
          f"{tail}",
          file=sys.stderr)


def _parse_opt(argv, name: str, default: str) -> str:
    """``--name value`` / ``--name=value`` — no argparse so the BENCH
    invocation stays copy-pasteable into constrained drivers."""
    val = default
    for i, tok in enumerate(argv):
        if tok == f"--{name}" and i + 1 < len(argv):
            val = argv[i + 1]
        elif tok.startswith(f"--{name}="):
            val = tok.split("=", 1)[1]
    return val


def _parse_mode(argv) -> str:
    return _parse_opt(argv, "mode", "train")


if __name__ == "__main__":
    if _parse_mode(sys.argv[1:]) == "predict":
        predict_main(concurrency=int(_parse_opt(
            sys.argv[1:], "concurrency",
            os.environ.get("BENCH_PREDICT_CONCURRENCY", "0"))))
    else:
        _ds = _parse_opt(sys.argv[1:], "dataset",
                         os.environ.get("BENCH_DATASET", "higgslike"))
        if _ds == "linear":
            bench_linear()
        else:
            main(dataset=_ds)
