"""Micro-benchmark: compaction (mask->cumsum->scatter) + row gather +
variant-F kernel at child sizes S.  Throwaway exploration script."""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N = 1_000_000
F = 28
B = 256

rng = np.random.RandomState(0)
bins_rm = jnp.asarray(rng.randint(0, B, size=(N, F)), jnp.uint8)
g = jnp.asarray(rng.normal(size=N), jnp.float32)
h = jnp.asarray(rng.uniform(0.1, 0.3, size=N), jnp.float32)
w = jnp.ones((N,), jnp.float32)


def timeit(name, fn, *args, reps=20):
    out = jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps * 1000
    print(f"{name:55s} {dt:8.3f} ms", flush=True)
    return out


def _kern(bins_ref, vals_ref, out_ref, acc_ref, *, nb, f_blk, bb):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    vals = vals_ref[:, :]                                   # [6, nb] bf16
    binz = bins_ref[:, :].astype(jnp.int32)                 # [nb, F]
    iota = jax.lax.broadcasted_iota(jnp.int32, (nb, bb), 1)
    for f in range(f_blk):
        b_f = binz[:, f][:, None]
        onehot = (b_f == iota).astype(jnp.bfloat16)
        part = jax.lax.dot_general(
            vals, onehot, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[f] += part

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


def hist_S(bins_s, vals6, S, nb):
    nblocks = S // nb
    return pl.pallas_call(
        functools.partial(_kern, nb=nb, f_blk=F, bb=B),
        grid=(nblocks,),
        in_specs=[pl.BlockSpec((nb, F), lambda i: (i, 0)),
                  pl.BlockSpec((6, nb), lambda i: (0, i))],
        out_specs=pl.BlockSpec((F, 6, B), lambda i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, 6, B), jnp.float32),
        scratch_shapes=[pltpu.VMEM((F, 6, B), jnp.float32)],
    )(bins_s, vals6)


@functools.partial(jax.jit, static_argnames=("S",))
def child_pass(bins_rm, g, h, w, leaf_id, target, S):
    """compact rows of `target` leaf (S static pad) + gather + kernel."""
    mask = leaf_id == target
    pos = jnp.cumsum(mask.astype(jnp.int32))
    cnt = pos[-1]
    idx = jnp.zeros((S,), jnp.int32)
    idx = idx.at[jnp.where(mask, pos - 1, S)].set(
        jnp.arange(N, dtype=jnp.int32), mode="drop")
    gathered = bins_rm[idx]                                  # [S, F] u8
    valid = (jnp.arange(S) < cnt).astype(jnp.float32)
    gs, hs, ws = g[idx] * valid, h[idx] * valid, w[idx] * valid
    vals = jnp.stack([gs, hs, ws])
    hi = vals.astype(jnp.bfloat16)
    lo = (vals - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    vals6 = jnp.concatenate([hi, lo], 0)
    nb = min(8192, S)
    out = hist_S(gathered, vals6, S, nb)
    return out[:, :3] + out[:, 3:]


@jax.jit
def compact_only(leaf_id, target):
    mask = leaf_id == target
    pos = jnp.cumsum(mask.astype(jnp.int32))
    S = N // 2
    idx = jnp.zeros((S,), jnp.int32)
    idx = idx.at[jnp.where(mask, pos - 1, S)].set(
        jnp.arange(N, dtype=jnp.int32), mode="drop")
    return idx


@functools.partial(jax.jit, static_argnames=("S",))
def gather_only(bins_rm, idx, S):
    return bins_rm[idx[:S]]


print("device:", jax.devices()[0])
# leaf assignment where target leaf has ~S rows
for frac, S in [(0.5, 524288), (0.25, 262144), (0.125, 131072),
                (0.03125, 32768), (0.0078125, 8192)]:
    leaf_id = jnp.asarray(
        (rng.uniform(size=N) < frac).astype(np.int32) * 7, jnp.int32)
    timeit(f"child_pass S={S:7d} (frac {frac})",
           lambda L=leaf_id, S=S: child_pass(bins_rm, g, h, w, L, 7, S))

leaf_id = jnp.asarray((rng.uniform(size=N) < 0.5).astype(np.int32) * 7)
idx = compact_only(leaf_id, 7)
timeit("compact_only (mask+cumsum+scatter @1M)", compact_only, leaf_id, 7)
timeit("gather_only S=512k rows [S,28] u8", gather_only, bins_rm, idx, 524288)
timeit("gather_only S=131k", gather_only, bins_rm, idx, 131072)

# full pass (root, no gather) for comparison; pad N to a block multiple
@jax.jit
def root_pass(bins_rm, g, h, w):
    nb = 8192
    pad = (-N) % nb
    b = jnp.pad(bins_rm, ((0, pad), (0, 0)))
    vals = jnp.stack([jnp.pad(g, (0, pad)), jnp.pad(h, (0, pad)),
                      jnp.pad(w, (0, pad))])
    hi = vals.astype(jnp.bfloat16)
    lo = (vals - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    vals6 = jnp.concatenate([hi, lo], 0)
    out = hist_S(b, vals6, N + pad, nb)
    return out[:, :3] + out[:, 3:]

timeit("root full pass V=6 nb=8192 (padded)", root_pass, bins_rm, g, h, w)
