"""Plotting helpers (reference examples/python-guide/plot_example.py):
metric curves, importances, and a tree, written to PNG files when
matplotlib is available."""

import numpy as np

import lightgbm_tpu as lgb

try:
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
except ImportError:
    raise SystemExit("matplotlib is not installed; nothing to plot")


def load(path):
    data = np.loadtxt(path, delimiter="\t")
    return data[:, 1:], data[:, 0]


X_train, y_train = load("../regression/regression.train")
X_test, y_test = load("../regression/regression.test")

lgb_train = lgb.Dataset(X_train, y_train)
lgb_eval = lgb.Dataset(X_test, y_test, reference=lgb_train)

evals_result = {}
gbm = lgb.train({"num_leaves": 5, "metric": ("l1", "l2"), "verbose": 0,
                 "objective": "regression"},
                lgb_train, num_boost_round=30,
                valid_sets=[lgb_train, lgb_eval],
                valid_names=["train", "eval"],
                callbacks=[lgb.record_evaluation(evals_result)])

ax = lgb.plot_metric(evals_result, metric="l1")
plt.savefig("metric.png")
ax = lgb.plot_importance(gbm, max_num_features=10)
plt.savefig("importance.png")
ax = lgb.plot_tree(gbm, tree_index=0)
plt.savefig("tree.png")
print("wrote metric.png importance.png tree.png")
