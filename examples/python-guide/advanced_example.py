"""Continued training, learning-rate decay, custom fobj/feval, and model
introspection (reference examples/python-guide/advanced_example.py flow)."""

import json

import numpy as np

import lightgbm_tpu as lgb


def load(path):
    data = np.loadtxt(path, delimiter="\t")
    return data[:, 1:], data[:, 0]


X_train, y_train = load("../binary_classification/binary.train")
X_test, y_test = load("../binary_classification/binary.test")

lgb_train = lgb.Dataset(X_train, y_train, free_raw_data=False)
lgb_eval = lgb.Dataset(X_test, y_test, reference=lgb_train,
                       free_raw_data=False)

params = {"boosting_type": "gbdt", "objective": "binary",
          "metric": "binary_logloss", "num_leaves": 31, "verbose": 0}

# train 10 rounds, persist, continue 10 more from the saved model
gbm = lgb.train(params, lgb_train, num_boost_round=10,
                valid_sets=[lgb_eval])
gbm.save_model("model.txt")
print("Dump model to JSON...")
model_json = gbm.dump_model()
with open("model.json", "w") as fh:
    json.dump(model_json, fh, indent=2)

print("Feature importances:", list(gbm.feature_importance()))

gbm = lgb.train(params, lgb_train, num_boost_round=10,
                init_model="model.txt", valid_sets=[lgb_eval])
print("Finish 10 - 20 rounds with model file...")

# learning-rate decay via reset_parameter callback
gbm = lgb.train(params, lgb_train, num_boost_round=10,
                init_model=gbm, valid_sets=[lgb_eval],
                callbacks=[lgb.reset_parameter(
                    learning_rate=lambda it: 0.05 * (0.99 ** it))])
print("Finish 20 - 30 rounds with decay learning rates...")


# custom objective (log-likelihood) + custom eval metric
def loglikelood(preds, train_data):
    labels = train_data.get_label()
    preds = 1.0 / (1.0 + np.exp(-preds))
    return preds - labels, preds * (1.0 - preds)


def binary_error(preds, train_data):
    labels = train_data.get_label()
    return "error", float(np.mean(labels != (preds > 0.5))), False


gbm = lgb.train({**params, "objective": "none", "metric": "None"},
                lgb_train, num_boost_round=10, init_model=gbm,
                fobj=loglikelood, feval=binary_error,
                valid_sets=[lgb_eval])
print("Finish 30 - 40 rounds with self-defined objective and eval...")
