"""Train / early-stop / predict with the core train() API
(reference examples/python-guide/simple_example.py flow)."""

import numpy as np

import lightgbm_tpu as lgb


def load(path):
    data = np.loadtxt(path, delimiter="\t")
    return data[:, 1:], data[:, 0]


X_train, y_train = load("../regression/regression.train")
X_test, y_test = load("../regression/regression.test")

lgb_train = lgb.Dataset(X_train, y_train)
lgb_eval = lgb.Dataset(X_test, y_test, reference=lgb_train)

params = {
    "boosting_type": "gbdt",
    "objective": "regression",
    "metric": "l2",
    "num_leaves": 31,
    "learning_rate": 0.05,
    "feature_fraction": 0.9,
    "bagging_fraction": 0.8,
    "bagging_freq": 5,
    "verbose": 0,
}

print("Start training...")
gbm = lgb.train(params, lgb_train, num_boost_round=20,
                valid_sets=[lgb_eval], early_stopping_rounds=5)

print("Save model...")
gbm.save_model("model.txt")

print("Start predicting...")
y_pred = gbm.predict(X_test, num_iteration=gbm.best_iteration)
rmse = float(np.sqrt(np.mean((y_pred - y_test) ** 2)))
print(f"The rmse of prediction is: {rmse}")
