"""sklearn-style estimator + GridSearchCV
(reference examples/python-guide/sklearn_example.py flow)."""

import numpy as np

import lightgbm_tpu as lgb


def load(path):
    data = np.loadtxt(path, delimiter="\t")
    return data[:, 1:], data[:, 0]


X_train, y_train = load("../regression/regression.train")
X_test, y_test = load("../regression/regression.test")

gbm = lgb.LGBMRegressor(objective="regression", num_leaves=31,
                        learning_rate=0.05, n_estimators=20)
gbm.fit(X_train, y_train, eval_set=[(X_test, y_test)], eval_metric="l1",
        early_stopping_rounds=5)

y_pred = gbm.predict(X_test, num_iteration=gbm.best_iteration_)
print("The rmse of prediction is:",
      float(np.sqrt(np.mean((y_pred - y_test) ** 2))))
print("Feature importances:", list(gbm.feature_importances_))

try:
    from sklearn.model_selection import GridSearchCV
    estimator = lgb.LGBMRegressor()
    param_grid = {"learning_rate": [0.01, 0.1], "n_estimators": [10, 20]}
    gbm = GridSearchCV(estimator, param_grid, cv=3)
    gbm.fit(X_train, y_train)
    print("Best parameters found by grid search are:", gbm.best_params_)
except ImportError:
    print("scikit-learn not installed; skipping the grid-search half")
