#!/usr/bin/env python
"""Generate the synthetic datasets used by the example conf files.

Run once from the examples/ directory:  python gen_data.py

Produces, per task directory, <name>.train / <name>.test files in the same
TSV (label first) or LibSVM layouts the reference's bundled examples use
(the reference ships real data files; we synthesize equivalents instead of
copying them)."""

import os

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def _write_tsv(path, y, X):
    with open(path, "w") as fh:
        for yi, row in zip(y, X):
            fh.write(f"{yi:g}\t" + "\t".join(f"{v:.6g}" for v in row) + "\n")


def regression(n=7000, f=28, seed=1):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] * 2 + np.sin(3 * X[:, 1]) + X[:, 2] * X[:, 3]
         + 0.3 * rng.normal(size=n))
    d = os.path.join(HERE, "regression")
    _write_tsv(os.path.join(d, "regression.train"), y[:5000], X[:5000])
    _write_tsv(os.path.join(d, "regression.test"), y[5000:], X[5000:])


def binary(n=7000, f=28, seed=2):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    logit = X[:, 0] + 0.8 * X[:, 1] * X[:, 2] - 0.6 * X[:, 3]
    y = (logit + 0.5 * rng.normal(size=n) > 0).astype(int)
    d = os.path.join(HERE, "binary_classification")
    _write_tsv(os.path.join(d, "binary.train"), y[:5000], X[:5000])
    _write_tsv(os.path.join(d, "binary.test"), y[5000:], X[5000:])
    # weight side file (reference binary.train.weight)
    w = rng.uniform(0.5, 1.5, size=5000)
    with open(os.path.join(d, "binary.train.weight"), "w") as fh:
        fh.writelines(f"{v:.4g}\n" for v in w)


def multiclass(n=6000, f=20, k=5, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    centers = rng.normal(scale=2.0, size=(k, f))
    logits = X @ centers.T + rng.normal(scale=2.0, size=(n, k))
    y = np.argmax(logits, axis=1)
    d = os.path.join(HERE, "multiclass_classification")
    _write_tsv(os.path.join(d, "multiclass.train"), y[:4500], X[:4500])
    _write_tsv(os.path.join(d, "multiclass.test"), y[4500:], X[4500:])


def lambdarank(n_query=200, f=30, seed=4):
    rng = np.random.RandomState(seed)
    d = os.path.join(HERE, "lambdarank")
    for split, nq in (("train", n_query), ("test", n_query // 4)):
        rows = []
        qsizes = []
        for _ in range(nq):
            sz = rng.randint(5, 25)
            qsizes.append(sz)
            Xq = rng.normal(size=(sz, f))
            rel = np.clip((Xq[:, 0] + 0.5 * Xq[:, 1]
                           + 0.5 * rng.normal(size=sz)) * 1.2, 0, 4)
            for r, x in zip(rel.astype(int), Xq):
                feats = " ".join(f"{j}:{v:.5g}" for j, v in enumerate(x)
                                 if abs(v) > 0.05)
                rows.append(f"{r} {feats}")
        with open(os.path.join(d, f"rank.{split}"), "w") as fh:
            fh.write("\n".join(rows) + "\n")
        with open(os.path.join(d, f"rank.{split}.query"), "w") as fh:
            fh.writelines(f"{q}\n" for q in qsizes)


if __name__ == "__main__":
    regression()
    binary()
    multiclass()
    lambdarank()
    print("example datasets written")
