#!/usr/bin/env python
"""Regenerate docs/Parameters.md from the live config system."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
from lightgbm_tpu.config import (_BOOL_KEYS, _DEFAULTS, _FLOAT_KEYS,
                                 _INT_KEYS, _LIST_KEYS, PARAM_ALIASES)

DESC_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "_param_descriptions.py")
DESC = {}
if os.path.exists(DESC_PATH):
    ns = {}
    exec(open(DESC_PATH).read(), ns)
    DESC = ns.get("DESC", {})


def main():
    lines = ["# Parameters", "",
             "All parameters of lightgbm_tpu, with defaults and aliases. "
             "The same",
             "key=value surface is accepted by the CLI (conf files + argv), "
             "the C-ABI-free",
             "Python `params` dicts, and the sklearn wrappers. Alias "
             "resolution matches the",
             "reference's ParameterAlias::KeyAliasTransform "
             "(config.h:322-416): canonical",
             "keys win over aliases.", "",
             "| Parameter | Default | Type | Aliases | Description |",
             "|---|---|---|---|---|"]
    rev = {}
    for alias, canon in PARAM_ALIASES.items():
        if alias != canon:
            rev.setdefault(canon, []).append(alias)
    for key in sorted(_DEFAULTS):
        d = _DEFAULTS[key]
        t = ("list" if key in _LIST_KEYS else "bool" if key in _BOOL_KEYS
             else "int" if key in _INT_KEYS
             else "float" if key in _FLOAT_KEYS else "str")
        aliases = ", ".join(sorted(rev.get(key, []))) or "—"
        dv = repr(d) if d != "" else "''"
        lines.append(f"| `{key}` | {dv} | {t} | {aliases} | "
                     f"{DESC.get(key, '')} |")
    lines += ["", "Generated from `lightgbm_tpu/config.py` "
                  "(`_DEFAULTS` + `PARAM_ALIASES`).",
              "Regenerate with `python docs/gen_parameters.py`.", ""]
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "Parameters.md")
    with open(out, "w") as fh:
        fh.write("\n".join(lines))
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
