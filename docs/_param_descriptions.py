"""One-line descriptions for docs/gen_parameters.py."""

DESC = {
    "task": "train or predict",
    "objective": "regression | regression_l1 | huber | fair | poisson | "
                 "binary | multiclass | lambdarank | none (custom fobj)",
    "boosting_type": "gbdt | dart | goss",
    "data": "training data file path",
    "valid_data": "validation data file path(s), comma separated",
    "num_iterations": "number of boosting rounds",
    "learning_rate": "shrinkage rate",
    "shrinkage_decay": "default decay in (0, 1] applied to the merged "
                       "model's leaf outputs in Booster.merge (1 = "
                       "verbatim; the train->serve->retrain loop's "
                       "delta-forest damping)",
    "num_leaves": "max leaves per tree (leaf-wise growth)",
    "tree_learner": "serial | feature | data | voting — distributed learner "
                    "over the device mesh",
    "serial_grow": "ordered | cached | fused — serial-learner strategy "
                   "(leaf-ordered physical layout, original-order cached "
                   "learner, or full-pass growth through the fused "
                   "histogram→split-gain kernel; TPU-specific extension)",
    "compile_cache_dir": "persistent XLA compilation cache directory so "
                         "repeated/resumed runs skip the warmup compile "
                         "tax ('' = the /tmp default, 'off' disables; "
                         "LIGHTGBM_TPU_COMPILE_CACHE env wins; "
                         "docs/OBSERVABILITY.md §Warmup & compile caching)",
    "row_buckets": "pad training rows up a shared shape ladder "
                   "(utils/compile_cache.py bucket_rows; zero row_weight "
                   "pad rows, exact histogram sums) so "
                   "train_step/grow_tree programs are shared across "
                   "nearby dataset sizes instead of compiling per N",
    "serve_host": "task=serve: HTTP bind address (docs/SERVING.md)",
    "serve_port": "task=serve: HTTP port",
    "serve_max_batch": "task=serve: row cap per coalesced device batch "
                       "(micro-batcher, serve/batcher.py)",
    "serve_max_delay_ms": "task=serve: micro-batch coalescing deadline "
                          "measured from the oldest queued request",
    "predict_buckets": "batch bucket ladder for the compiled-forest "
                       "predict paths (comma-separated sizes; empty = "
                       "powers of two 16..65536; docs/SERVING.md)",
    "serve_replicas": "task=serve: device replicas in the fleet — one "
                      "CompiledForest + micro-batcher per local device "
                      "(0 = all of jax.local_devices(); serve/fleet.py, "
                      "docs/SERVING.md §Fleet)",
    "serve_queue_depth": "task=serve: pending-request cap per replica "
                         "queue; beyond it requests shed with 429 + "
                         "Retry-After (0 = unbounded)",
    "serve_max_inflight": "task=serve: fleet-wide cap on admitted "
                          "requests in flight; beyond it requests shed "
                          "with 429 + Retry-After (0 = unbounded)",
    "serve_canary_model": "task=serve: optional second model file served "
                          "at serve_canary_weight traffic share (A/B "
                          "routing; metrics labeled model=canary)",
    "serve_canary_weight": "task=serve: canary traffic share in [0, 1) — "
                           "deterministic rotation, exact split",
    "serve_retry_limit": "task=serve: hedged retries per request onto a "
                         "different replica after a replica-attributable "
                         "failure (0 = none; serve/health.py, "
                         "docs/FAULT_TOLERANCE.md §Serving)",
    "serve_error_threshold": "task=serve: consecutive request errors "
                             "before a replica is marked suspect (the "
                             "watchdog then ejects it)",
    "serve_watchdog_ms": "task=serve: replica health watchdog interval — "
                         "ejection, synthetic probes, re-admission "
                         "(0 disables the whole health machine)",
    "serve_stall_ms": "task=serve: how long a replica's worker may sit "
                      "inside one device batch before it counts as "
                      "wedged (stall detector; 0 = off)",
    "serve_latency_outlier": "task=serve: EWMA service-time multiple of "
                             "the fleet median beyond which a replica is "
                             "a straggler (suspect after 2 ticks)",
    "serve_state_file": "task=serve: JSON file recording the last-good "
                        "model per slot after each successful reload; a "
                        "restarted server boots it instead of "
                        "input_model (crash restore)",
    "serve_shadow": "task=serve: fraction of primary traffic mirrored "
                    "onto the canary OFF the response path (bounded "
                    "queue, dropped under load — never sheds or slows "
                    "real requests; serve/lifecycle.py, "
                    "docs/FAULT_TOLERANCE.md §Model lifecycle)",
    "lifecycle_window_s": "task=serve: guarded-promotion observation "
                          "window after a canary reload — the "
                          "PromotionController ends it in promote / "
                          "rollback / extend (0 disables the guarded "
                          "lifecycle)",
    "lifecycle_max_window_s": "task=serve: hard cap on the extended "
                              "observation window; a candidate still "
                              "unproven at the cap is rolled back, "
                              "never promoted by timeout (0 = 4x "
                              "lifecycle_window_s)",
    "lifecycle_min_samples": "task=serve: canary requests each guardrail "
                             "gate needs in the window before it may "
                             "vote (promote or rollback)",
    "lifecycle_latency_ratio": "task=serve: rollback when windowed "
                               "canary p99 latency exceeds this multiple "
                               "of the primary's (0 disables the "
                               "latency gate)",
    "lifecycle_error_rate": "task=serve: rollback when the canary's "
                            "windowed (errors + ejections) / requests "
                            "exceeds this rate",
    "lifecycle_cooldown_s": "task=serve: sticky cooldown after a "
                            "rollback — a re-reloaded candidate inside "
                            "it is rolled back immediately; doubles per "
                            "consecutive rollback (0 = none)",
    "drift": "task=serve: off | on — streaming drift collector over the "
             "served rows vs the model's training-data fingerprint "
             "(docs/OBSERVABILITY.md §Drift; off is one attribute read "
             "on the predict path)",
    "drift_window": "task=serve: collector window seconds — each window "
                    "computes per-feature PSI/KL/L-inf and score PSI on "
                    "a host thread; shorter windows detect faster but "
                    "sample fewer rows",
    "drift_top_k": "task=serve: offending features labeled per window "
                   "in drift_psi{feature=} gauges and named in drift "
                   "verdicts (the full set is always in /stats)",
    "lifecycle_drift_threshold": "task=serve: per-feature PSI above this "
                                 "for consecutive canary windows votes "
                                 "rollback with reason 'drift'; also the "
                                 "train_delta skew-warning bar "
                                 "(0 disables the gate; 0.25 = classic "
                                 "major-shift reading)",
    "serve_walk": "auto | fused | gather — forest-walk serving strategy "
                  "(docs/SERVING.md §Serving strategies): 'fused' runs "
                  "the single-pass Pallas walk kernel with the forest "
                  "pinned in VMEM, 'gather' keeps the classic per-depth "
                  "gather programs byte-identical, 'auto' picks fused "
                  "when the forest's VMEM footprint fits the "
                  "LIGHTGBM_TPU_WALK_VMEM_BYTES budget (gather "
                  "otherwise, and always off-TPU)",
    "serve_quantize_leaves": "task=serve: with serve_walk=fused, "
                             "accumulate leaf values in bfloat16 when "
                             "the per-class worst-case rounding bound "
                             "stays within QUANTIZE_LEAF_ATOL — "
                             "otherwise falls back to float32 and "
                             "increments forest_quantize_fallback "
                             "(docs/SERVING.md §Bin quantization)",
    "serve_max_body_bytes": "task=serve: request body size cap — larger "
                            "payloads are shed with 413 before any "
                            "parsing or device time (0 = no cap)",
    "serve_nonfinite_policy": "reject | propagate — NaN/Inf feature "
                              "values in /predict payloads either 400 "
                              "naming the offending row, or pass "
                              "through to the forest",
    "events_file": "per-iteration JSONL telemetry stream path "
                   "(docs/OBSERVABILITY.md; --events-file on the CLI)",
    "trace_dir": "device trace output dir; LIGHTGBM_TPU_TRACE_DIR env "
                 "overrides (docs/OBSERVABILITY.md)",
    "trace_start_iter": "first traced iteration (default 5, skips "
                        "compile/warmup)",
    "trace_num_iters": "trace window length in iterations (default 2)",
    "metrics_port": "port of the training /metrics listener serving the "
                    "obs registry in Prometheus text exposition 0.0.4 "
                    "(0 = off; LIGHTGBM_TPU_METRICS_PORT env wins; "
                    "docs/OBSERVABILITY.md)",
    "metrics_host": "bind address of the training /metrics listener "
                    "(default 127.0.0.1)",
    "compile_ledger_file": "append-only JSONL of every XLA compilation "
                           "(program, abstract shapes, seconds); "
                           "LIGHTGBM_TPU_COMPILE_LEDGER env wins "
                           "(docs/OBSERVABILITY.md)",
    "memwatch": "sample HBM watermark gauges (live/peak device bytes, "
                "per phase) at span boundaries; off by default, "
                "LIGHTGBM_TPU_MEMWATCH env wins",
    "devprof": "device-time attribution: off | full | sample:N forces a "
               "sync on every Nth dispatch per XLA program and records "
               "per-program device seconds, roofline gauges, and the "
               "per-round host/device split; off by default (zero "
               "overhead), LIGHTGBM_TPU_DEVPROF env wins "
               "(docs/OBSERVABILITY.md)",
    "trace_events_file": "Chrome trace-event JSON export of the causal "
                         "span tree (one trace per serve request / "
                         "boosting round; load in Perfetto); "
                         "LIGHTGBM_TPU_TRACE_EVENTS env wins",
    "use_two_round_loading": "stream the data file in two rounds instead of "
                             "materializing the full float matrix "
                             "(io/streaming.py)",
    "num_machines": "mesh device count for distributed learners",
    "max_bin": "max feature histogram bins",
    "min_data_in_leaf": "minimum rows per leaf",
    "min_sum_hessian_in_leaf": "minimum hessian sum per leaf",
    "feature_fraction": "per-tree feature subsample ratio",
    "bagging_fraction": "row subsample ratio",
    "bagging_freq": "re-bag every k iterations (0 = off)",
    "lambda_l1": "L1 regularization",
    "lambda_l2": "L2 regularization",
    "min_gain_to_split": "minimum gain to accept a split",
    "linear_tree": "fit an affine model in each leaf over its split-path "
                   "features (batched on-device ridge solve after growth; "
                   "needs raw feature values — docs/LINEAR_TREES.md)",
    "linear_lambda": "ridge strength on the affine leaves' slope terms "
                     "(linear_tree; lambda_l2 regularizes the intercept)",
    "linear_max_leaf_features": "K: path features per affine leaf, a "
                                "static pad width so every leaf fit "
                                "shares one compiled program (0 "
                                "degenerates linear_tree to constant "
                                "leaves, bit-identical to linear_tree="
                                "false; docs/LINEAR_TREES.md)",
    "max_depth": "depth limit (-1 = none)",
    "early_stopping_round": "stop when no metric improves in this many "
                            "rounds",
    "metric": "evaluation metric list",
    "num_class": "number of classes (multiclass)",
    "is_unbalance": "reweight unbalanced binary labels",
    "scale_pos_weight": "positive class weight (binary)",
    "sigmoid": "sigmoid sharpness (binary/lambdarank)",
    "huber_delta": "delta for huber loss",
    "fair_c": "c for fair loss",
    "gaussian_eta": "hessian smoothing width for L1/huber",
    "poisson_max_delta_step": "poisson optimization safeguard",
    "max_position": "NDCG truncation for lambdarank",
    "label_gain": "per-label gains for lambdarank",
    "ndcg_eval_at": "NDCG/MAP evaluation positions",
    "drop_rate": "dart: tree drop probability",
    "skip_drop": "dart: probability of skipping dropout",
    "max_drop": "dart: max dropped trees per iteration",
    "uniform_drop": "dart: uniform dropping",
    "xgboost_dart_mode": "dart: xgboost normalization mode",
    "top_rate": "goss: large-gradient keep ratio",
    "other_rate": "goss: small-gradient sample ratio",
    "top_k": "voting-parallel: candidates per shard",
    "output_model": "model save path (train)",
    "input_model": "model load path (predict / continued training)",
    "output_result": "prediction output path",
    "is_training_metric": "also print metrics on training data",
    "output_freq": "metric print frequency",
    "bin_construct_sample_cnt": "rows sampled for bin boundary construction",
    "min_data_in_bin": "minimum rows per histogram bin",
    "data_random_seed": "binning/partition seed",
    "bagging_seed": "bagging seed",
    "feature_fraction_seed": "feature subsample seed",
    "drop_seed": "dart dropout seed",
    "has_header": "data files have a header line",
    "label_column": "label column index",
    "categorical_column": "categorical feature indices",
    "ignore_column": "feature indices to drop",
    "is_predict_raw_score": "predict: output raw scores",
    "is_predict_leaf_index": "predict: output leaf indices",
    "verbose": "log level (alias verbosity)",
    "seed": "master seed; derived seeds cover bagging/feature/dart draws "
            "unless set explicitly",
    "num_threads": "host thread hint (accepted for conf compatibility; "
                   "device parallelism comes from the mesh)",
    "num_iteration_predict": "predict with only the first K iterations "
                             "(-1 = all)",
    "is_pre_partition": "distributed: data files are already partitioned "
                        "per machine (accepted for conf compatibility)",
    "is_enable_sparse": "enable sparse-aware optimizations: false also "
                        "disables EFB bundling candidate selection (the "
                        "TPU bin matrix itself stays dense either way)",
    "is_save_binary_file": "save the parsed dataset as a binary sidecar "
                           "for faster reloads",
    "enable_load_from_binary_file": "load the binary sidecar when present "
                                    "instead of re-parsing text",
    "max_conflict_rate": "EFB: max share of conflicting rows (both "
                         "features non-default) a bundle may absorb, in "
                         "[0, 1); 0 bundles only perfectly exclusive "
                         "features (docs/SPARSE.md)",
    "enable_bundle": "bundle mutually-exclusive sparse features into "
                     "shared columns (EFB, io/bundling.py): the device "
                     "bin matrix and histogram pass shrink from F to "
                     "F_bundled while trees/models stay in original "
                     "feature space (docs/SPARSE.md)",
    "feature_screen_ratio": "EMA-FS gain screening: share of the feature "
                            "space masked out of screened rounds by the "
                            "split-gain EWMA (0 = off; screened rounds "
                            "also compact the histogram pass to the "
                            "active columns; docs/SPARSE.md)",
    "feature_screen_refresh": "screening: every K-th post-warmup round "
                              "scans the FULL feature set so dormant "
                              "features can re-enter; the active set is "
                              "re-drawn once per period",
    "feature_screen_warmup": "screening: unscreened warm-up rounds that "
                             "seed the per-feature gain EWMA before any "
                             "mask applies",
    "feature_screen_decay": "screening: per-round EWMA decay of realized "
                            "split gains (closer to 1 = longer memory)",
    "weight_column": "per-row weight column index/name in the data file",
    "group_column": "query/group column index/name (lambdarank)",
    "histogram_pool_size": "reference histogram cache budget in MB "
                           "(-1 = unbounded; accepted for conf "
                           "compatibility — the TPU learner keeps leaf "
                           "histograms on device)",
    "local_listen_port": "distributed: first TCP port from the reference "
                         "machine-list protocol; the coordinator binds "
                         "entry 0's port, the heartbeat mesh datagrams "
                         "each rank's own (parallel/multihost.py)",
    "time_out": "distributed: socket/connect timeout in minutes from the "
                "reference conf surface (coordinator connects use "
                "distributed_init_retries/backoff)",
    "machine_list_file": "distributed: one 'host port' line per rank — "
                         "numbers the processes, locates the "
                         "coordinator, and seeds the watchdog heartbeat "
                         "mesh (docs/FAULT_TOLERANCE.md §Distributed)",
    "tpu_histogram_impl": "auto | scatter | onehot | pallas — histogram "
                          "kernel selection (ops/histogram.py; auto "
                          "picks pallas on TPU, onehot elsewhere)",
    "tpu_double_hist": "accumulate histograms in float64 (CPU parity "
                       "tests; TPUs run f32)",
    # fault tolerance (docs/FAULT_TOLERANCE.md)
    "snapshot_dir": "crash-safe snapshot directory; also enables "
                    "auto-resume (multihost: rank 0 writes, resume runs "
                    "the cross-rank consensus)",
    "snapshot_freq": "checkpoint every K iterations (0 = off; alias "
                     "save_period)",
    "snapshot_keep": "newest snapshot files retained (0 = keep all)",
    "nan_policy": "none | fail_fast | skip_tree — non-finite "
                  "gradient/score containment",
    "memory_policy": "fail_fast | degrade — HBM admission control: an "
                     "over-budget config either refuses up front with "
                     "the per-component estimate table, or walks the "
                     "footprint-reduction ladder (score donation → drop "
                     "the leaf-histogram cache → cap the row-bucket "
                     "pad) before refusing "
                     "(docs/FAULT_TOLERANCE.md §Resource exhaustion)",
    "sink_error_policy": "disable | fatal — what a guarded telemetry/"
                         "state sink does on a classified write error "
                         "(ENOSPC/EROFS/EDQUOT/EMFILE): disable itself "
                         "with one warning + sink_write_errors_total, "
                         "or raise a named SinkWriteError "
                         "(docs/FAULT_TOLERANCE.md §Resource exhaustion)",
    "events_flush_every": "events JSONL flush cadence in committed "
                          "records — a crash loses at most this many "
                          "trailing records (default 1: every record "
                          "is on disk when note() returns)",
    "bad_data_policy": "fail_fast | quarantine — malformed input rows at "
                       "file load either raise a LightGBMError naming "
                       "file:line + token, or are skipped into "
                       "<data>.quarantine under the error budget "
                       "(docs/FAULT_TOLERANCE.md §Data boundary)",
    "max_bad_rows": "absolute quarantine budget: abort the load after "
                    "this many bad rows (0 = no absolute cap)",
    "max_bad_row_fraction": "relative quarantine budget: abort when bad "
                            "rows exceed this fraction of rows seen "
                            "(0 = no fractional cap)",
    "distributed_init_retries": "coordinator-connect retries with "
                                "exponential backoff",
    "distributed_init_backoff": "first coordinator-connect retry delay, "
                                "seconds (doubles each retry)",
    "distributed_heartbeat_ms": "out-of-band UDP rank-heartbeat interval "
                                "for the collective watchdog (0 = off; "
                                "docs/FAULT_TOLERANCE.md §Distributed)",
    "collective_timeout_s": "per-round collective deadline / peer "
                            "staleness bound; 0 = auto, derived from "
                            "the comm_seconds EWMA with a 60 s floor",
    "distributed_consistency_check": "allgather a replicated-state digest "
                                     "every K iterations to catch rank "
                                     "desync (0 = off; zero overhead "
                                     "single-process)",
    "desync_policy": "fail_fast | resync — stop the pod with a named "
                     "diagnostic, or broadcast rank 0's state to the "
                     "diverged ranks and continue",
}
