"""Micro-benchmark of histogram kernel variants on the real TPU chip.

Times the current production kernel plus redesign candidates, at
1M x 28 x 256 (the bench shape).  Throwaway exploration script.
"""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N = 1_000_000
F = 28
B = 256

rng = np.random.RandomState(0)
bins_fm = jnp.asarray(rng.randint(0, B, size=(F, N)), jnp.int8)   # feature-major
bins_rm = jnp.asarray(np.ascontiguousarray(np.asarray(bins_fm).T))  # row-major [N, F]
g = jnp.asarray(rng.normal(size=N), jnp.float32)
h = jnp.asarray(rng.uniform(0.1, 0.3, size=N), jnp.float32)
w = jnp.ones((N,), jnp.float32)
leaf = jnp.asarray(rng.randint(0, 2, size=N), jnp.int32)


def timeit(name, fn, *args, reps=10):
    out = jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps * 1000
    print(f"{name:55s} {dt:8.2f} ms")
    return out


# --- current production kernel ------------------------------------------
from lightgbm_tpu.ops.pallas_histogram import children_histograms_pallas

timeit("current children_histograms_pallas (f32, per-f dot)",
       lambda: children_histograms_pallas(bins_fm, g, h, w, leaf, 0, 1, 255))


# --- variant A: fused one-hot over all features, one dot per block ------
def _kern_fused(bins_ref, vals_ref, out_ref, acc_ref, *, nb, f_blk, bb):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    vals = vals_ref[:, :]                                  # [6, nb]
    binz = bins_ref[:, :]                                  # [f_blk, nb] i32
    iota = jax.lax.broadcasted_iota(jnp.int32, (nb, f_blk, bb), 2)
    # onehot[i, f, b] = bins[f, i] == b  -> reshape [nb, f_blk*bb]
    onehot = (binz.T[:, :, None] == iota).astype(jnp.float32)
    onehot = onehot.reshape(nb, f_blk * bb)
    acc_ref[:, :] += jax.lax.dot_general(
        vals, onehot, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


@functools.partial(jax.jit, static_argnames=("nb",))
def fused_f32(bins, g, h, w, leaf, nb=256):
    is_l = (leaf == 0).astype(jnp.float32)
    is_r = (leaf == 1).astype(jnp.float32)
    vals = jnp.stack([g * is_l, h * is_l, w * is_l,
                      g * is_r, h * is_r, w * is_r])
    nblocks = N // nb
    return pl.pallas_call(
        functools.partial(_kern_fused, nb=nb, f_blk=F, bb=B),
        grid=(nblocks,),
        in_specs=[pl.BlockSpec((F, nb), lambda i: (0, i)),
                  pl.BlockSpec((6, nb), lambda i: (0, i))],
        out_specs=pl.BlockSpec((6, F * B), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((6, F * B), jnp.float32),
        scratch_shapes=[pltpu.VMEM((6, F * B), jnp.float32)],
    )(bins.astype(jnp.int32), vals)


# --- variant B: per-feature dot but bf16 hi/lo split --------------------
def _kern_bf16(bins_ref, vals_ref, out_ref, acc_ref, *, nb, f_blk, bb):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    vals = vals_ref[:, :]                                  # [12, nb] bf16
    binz = bins_ref[:, :]
    iota = jax.lax.broadcasted_iota(jnp.int32, (nb, bb), 1)
    for f in range(f_blk):
        b_f = jax.lax.broadcast_in_dim(binz[f], (nb, bb), (0,))
        onehot = (b_f == iota).astype(jnp.bfloat16)
        part = jax.lax.dot_general(
            vals, onehot, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [12, bb]
        acc_ref[f] += part

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


@functools.partial(jax.jit, static_argnames=("nb",))
def perf_bf16(bins, g, h, w, leaf, nb=2048):
    is_l = (leaf == 0).astype(jnp.float32)
    is_r = (leaf == 1).astype(jnp.float32)
    vals = jnp.stack([g * is_l, h * is_l, w * is_l,
                      g * is_r, h * is_r, w * is_r])       # [6, N] f32
    hi = vals.astype(jnp.bfloat16)
    lo = (vals - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    vals12 = jnp.concatenate([hi, lo], axis=0)             # [12, N] bf16
    nblocks = N // nb
    out = pl.pallas_call(
        functools.partial(_kern_bf16, nb=nb, f_blk=F, bb=B),
        grid=(nblocks,),
        in_specs=[pl.BlockSpec((F, nb), lambda i: (0, i)),
                  pl.BlockSpec((12, nb), lambda i: (0, i))],
        out_specs=pl.BlockSpec((F, 12, B), lambda i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, 12, B), jnp.float32),
        scratch_shapes=[pltpu.VMEM((F, 12, B), jnp.float32)],
    )(bins.astype(jnp.int32), vals12)
    return out[:, :6] + out[:, 6:]


# --- variant C: like current but int8 bins widened in-kernel ------------
def _kern_i8(bins_ref, vals_ref, out_ref, acc_ref, *, nb, f_blk, bb):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    vals = vals_ref[:, :]
    binz = bins_ref[:, :].astype(jnp.int32)                # widen in VMEM
    iota = jax.lax.broadcasted_iota(jnp.int32, (nb, bb), 1)
    for f in range(f_blk):
        b_f = jax.lax.broadcast_in_dim(binz[f], (nb, bb), (0,))
        onehot = (b_f == iota).astype(jnp.float32)
        part = jax.lax.dot_general(
            vals, onehot, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)
        acc_ref[f] += part

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


@functools.partial(jax.jit, static_argnames=("nb",))
def perf_i8(bins, g, h, w, leaf, nb=2048):
    is_l = (leaf == 0).astype(jnp.float32)
    is_r = (leaf == 1).astype(jnp.float32)
    vals = jnp.stack([g * is_l, h * is_l, w * is_l,
                      g * is_r, h * is_r, w * is_r])
    nblocks = N // nb
    return pl.pallas_call(
        functools.partial(_kern_i8, nb=nb, f_blk=F, bb=B),
        grid=(nblocks,),
        in_specs=[pl.BlockSpec((F, nb), lambda i: (0, i)),
                  pl.BlockSpec((6, nb), lambda i: (0, i))],
        out_specs=pl.BlockSpec((F, 6, B), lambda i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, 6, B), jnp.float32),
        scratch_shapes=[pltpu.VMEM((F, 6, B), jnp.float32)],
    )(bins, vals)


# --- variant D: bf16 hi/lo + int8 bins ----------------------------------
@functools.partial(jax.jit, static_argnames=("nb",))
def bf16_i8(bins, g, h, w, leaf, nb=2048):
    is_l = (leaf == 0).astype(jnp.float32)
    is_r = (leaf == 1).astype(jnp.float32)
    vals = jnp.stack([g * is_l, h * is_l, w * is_l,
                      g * is_r, h * is_r, w * is_r])
    hi = vals.astype(jnp.bfloat16)
    lo = (vals - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    vals12 = jnp.concatenate([hi, lo], axis=0)

    def kern(bins_ref, vals_ref, out_ref, acc_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        vals = vals_ref[:, :]
        binz = bins_ref[:, :].astype(jnp.int32)
        iota = jax.lax.broadcasted_iota(jnp.int32, (nb, B), 1)
        for f in range(F):
            b_f = jax.lax.broadcast_in_dim(binz[f], (nb, B), (0,))
            onehot = (b_f == iota).astype(jnp.bfloat16)
            part = jax.lax.dot_general(
                vals, onehot, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            acc_ref[f] += part

        @pl.when(i == pl.num_programs(0) - 1)
        def _():
            out_ref[:] = acc_ref[:]

    nblocks = N // nb
    out = pl.pallas_call(
        kern,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec((F, nb), lambda i: (0, i)),
                  pl.BlockSpec((12, nb), lambda i: (0, i))],
        out_specs=pl.BlockSpec((F, 12, B), lambda i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, 12, B), jnp.float32),
        scratch_shapes=[pltpu.VMEM((F, 12, B), jnp.float32)],
    )(bins, vals12)
    return out[:, :6] + out[:, 6:]


# --- variant E: ROW-MAJOR bins [nb, F]; col-slice puts rows on sublanes,
# --- broadcast across B lanes is the cheap direction ---------------------
def _kern_rm(bins_ref, vals_ref, out_ref, acc_ref, *, nb, f_blk, bb, prec):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    vals = vals_ref[:, :]                                   # [V, nb]
    binz = bins_ref[:, :].astype(jnp.int32)                 # [nb, F]
    iota = jax.lax.broadcasted_iota(jnp.int32, (nb, bb), 1)
    dt = jnp.float32 if prec else jnp.bfloat16
    for f in range(f_blk):
        b_f = binz[:, f][:, None]                           # [nb, 1] sublanes
        onehot = (b_f == iota).astype(dt)                   # lane-broadcast
        part = jax.lax.dot_general(
            vals, onehot, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST if prec else None)
        acc_ref[f] += part

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


@functools.partial(jax.jit, static_argnames=("nb", "prec"))
def rowmajor(bins_rm, g, h, w, leaf, nb=2048, prec=True):
    is_l = (leaf == 0).astype(jnp.float32)
    is_r = (leaf == 1).astype(jnp.float32)
    vals = jnp.stack([g * is_l, h * is_l, w * is_l,
                      g * is_r, h * is_r, w * is_r])
    if prec:
        valsx = vals
        V = 6
    else:
        hi = vals.astype(jnp.bfloat16)
        lo = (vals - hi.astype(jnp.float32)).astype(jnp.bfloat16)
        valsx = jnp.concatenate([hi, lo], axis=0)
        V = 12
    nblocks = N // nb
    out = pl.pallas_call(
        functools.partial(_kern_rm, nb=nb, f_blk=F, bb=B, prec=prec),
        grid=(nblocks,),
        in_specs=[pl.BlockSpec((nb, F), lambda i: (i, 0)),
                  pl.BlockSpec((V, nb), lambda i: (0, i))],
        out_specs=pl.BlockSpec((F, V, B), lambda i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, V, B), jnp.float32),
        scratch_shapes=[pltpu.VMEM((F, V, B), jnp.float32)],
    )(bins_rm, valsx)
    if prec:
        return out
    return out[:, :6] + out[:, 6:]


print("device:", jax.devices()[0])
r4 = timeit("E row-major int8 f32 (nb=2048)",
            lambda: rowmajor(bins_rm, g, h, w, leaf, prec=True))
r5 = timeit("F row-major int8 bf16 hi/lo (nb=2048)",
            lambda: rowmajor(bins_rm, g, h, w, leaf, prec=False))
r6 = timeit("E row-major nb=8192",
            lambda: rowmajor(bins_rm, g, h, w, leaf, nb=8192, prec=True))
r7 = timeit("F row-major bf16 nb=8192",
            lambda: rowmajor(bins_rm, g, h, w, leaf, nb=8192, prec=False))
r0 = timeit("A fused onehot f32 (nb=1024)", fused_f32, bins_fm, g, h, w, leaf)
r1 = timeit("B per-f dot bf16 hi/lo (nb=2048)", perf_bf16, bins_fm, g, h, w, leaf)
r2 = timeit("C per-f dot f32, int8 bins in-kernel", perf_i8, bins_fm, g, h, w, leaf)
r3 = timeit("D per-f dot bf16 hi/lo + int8 bins", bf16_i8, bins_fm, g, h, w, leaf)

# correctness cross-check vs numpy on a small slice
ref = np.zeros((F, 6, B), np.float64)
bn = np.asarray(bins_fm).astype(np.uint8)
vals = np.stack([np.asarray(g) * (np.asarray(leaf) == 0),
                 np.asarray(h) * (np.asarray(leaf) == 0),
                 np.asarray(w) * (np.asarray(leaf) == 0),
                 np.asarray(g) * (np.asarray(leaf) == 1),
                 np.asarray(h) * (np.asarray(leaf) == 1),
                 np.asarray(w) * (np.asarray(leaf) == 1)])
for f in range(2):
    for v in range(6):
        ref[f, v] = np.bincount(bn[f].astype(np.int64), weights=vals[v],
                                minlength=B)[:B]
for name, r in [("B", np.asarray(r1)), ("C", np.asarray(r2)),
                ("D", np.asarray(r3)), ("E", np.asarray(r4)),
                ("F", np.asarray(r5))]:
    err = np.max(np.abs(r[:2] - ref[:2]) / (np.abs(ref[:2]) + 1))
    print(f"variant {name} max rel err vs f64: {err:.3e}")
