"""Validate the suspicious 0.02ms result: correctness + honest timing."""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N = 1_000_000
F = 28
B = 256

rng = np.random.RandomState(0)
bins_np = rng.randint(0, B, size=(N, F)).astype(np.uint8)
bins_rm = jnp.asarray(bins_np)
g = jnp.asarray(rng.normal(size=N), jnp.float32)
h = jnp.asarray(rng.uniform(0.1, 0.3, size=N), jnp.float32)
w = jnp.ones((N,), jnp.float32)


def _kern(bins_ref, vals_ref, out_ref, acc_ref, *, nb, f_blk, bb):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    vals = vals_ref[:, :]
    binz = bins_ref[:, :].astype(jnp.int32)
    iota = jax.lax.broadcasted_iota(jnp.int32, (nb, bb), 1)
    for f in range(f_blk):
        b_f = binz[:, f][:, None]
        onehot = (b_f == iota).astype(jnp.bfloat16)
        part = jax.lax.dot_general(
            vals, onehot, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[f] += part

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


@jax.jit
def root_pass(bins_rm, g, h, w):
    nb = 8192
    pad = (-N) % nb
    b = jnp.pad(bins_rm, ((0, pad), (0, 0)))
    vals = jnp.stack([jnp.pad(g, (0, pad)), jnp.pad(h, (0, pad)),
                      jnp.pad(w, (0, pad))])
    hi = vals.astype(jnp.bfloat16)
    lo = (vals - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    vals6 = jnp.concatenate([hi, lo], 0)
    S = N + pad
    out = pl.pallas_call(
        functools.partial(_kern, nb=nb, f_blk=F, bb=B),
        grid=(S // nb,),
        in_specs=[pl.BlockSpec((nb, F), lambda i: (i, 0)),
                  pl.BlockSpec((6, nb), lambda i: (0, i))],
        out_specs=pl.BlockSpec((F, 6, B), lambda i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, 6, B), jnp.float32),
        scratch_shapes=[pltpu.VMEM((F, 6, B), jnp.float32)],
    )(b, vals6)
    return out[:, :3] + out[:, 3:]


out = jax.block_until_ready(root_pass(bins_rm, g, h, w))
out_np = np.asarray(out)

# correctness vs numpy f64 bincount on 4 features
ok = True
for f in range(4):
    for v, arr in enumerate([np.asarray(g), np.asarray(h), np.asarray(w)]):
        ref = np.bincount(bins_np[:, f].astype(np.int64),
                          weights=arr.astype(np.float64), minlength=B)
        err = np.max(np.abs(out_np[f, v] - ref) / (np.abs(ref) + 1.0))
        if err > 1e-5:
            ok = False
            print(f"f={f} v={v} rel err {err:.2e}")
print("correct:", ok, flush=True)

# honest timing: many reps, total wall clock
for reps in (10, 100):
    t0 = time.perf_counter()
    outs = None
    for _ in range(reps):
        outs = root_pass(bins_rm, g, h, w)
    jax.block_until_ready(outs)
    dt = (time.perf_counter() - t0) / reps * 1000
    print(f"reps={reps}: {dt:.3f} ms per call", flush=True)
