"""Isolate the row-major kernel bug: counts-only, small N, dtype variants."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N = 16384
F = 28
B = 256
NB = 8192

rng = np.random.RandomState(0)
bins_np = rng.randint(0, B, size=(N, F)).astype(np.uint8)
w = jnp.ones((N,), jnp.float32)


def _kern(bins_ref, vals_ref, out_ref, acc_ref, *, nb, f_blk, bb, widen):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    vals = vals_ref[:, :]
    binz = bins_ref[:, :]
    if widen:
        binz = binz.astype(jnp.int32)
    iota = jax.lax.broadcasted_iota(jnp.int32, (nb, bb), 1)
    for f in range(f_blk):
        b_f = binz[:, f][:, None].astype(jnp.int32)
        onehot = (b_f == iota).astype(jnp.float32)
        part = jax.lax.dot_general(
            vals, onehot, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)
        acc_ref[f] += part

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


def run(dtype, widen, interpret=False):
    b = jnp.asarray(bins_np.astype(dtype))
    vals = w[None]
    out = pl.pallas_call(
        functools.partial(_kern, nb=NB, f_blk=F, bb=B, widen=widen),
        grid=(N // NB,),
        in_specs=[pl.BlockSpec((NB, F), lambda i: (i, 0)),
                  pl.BlockSpec((1, NB), lambda i: (0, i))],
        out_specs=pl.BlockSpec((F, 1, B), lambda i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, 1, B), jnp.float32),
        scratch_shapes=[pltpu.VMEM((F, 1, B), jnp.float32)],
        interpret=interpret,
    )(b, vals)
    return np.asarray(out)[:, 0]


ref = np.stack([np.bincount(bins_np[:, f].astype(np.int64), minlength=B)
                for f in range(F)]).astype(np.float64)

for dtype, widen, tag in [(np.uint8, True, "u8 widen-in-kern"),
                          (np.int32, False, "i32 input"),
                          (np.uint8, True, "u8 interp")]:
    interp = tag.endswith("interp")
    got = run(dtype, widen, interp)
    bad = [f for f in range(F) if not np.allclose(got[f], ref[f])]
    print(f"{tag:20s} bad features: {bad[:8]}{'...' if len(bad)>8 else ''} "
          f"total_count_ok={np.allclose(got.sum(1), N)}", flush=True)
    if bad:
        f = bad[0]
        d = got[f] - ref[f]
        nz = np.nonzero(d)[0]
        print(f"  f={f}: first diffs at bins {nz[:6]} delta {d[nz[:6]]}")
