"""Test int8 MXU dot support + 3-stream decompositions (bf16 vs int8)."""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N = 1_000_000
F = 28
B = 256

rng = np.random.RandomState(0)
bins_np = rng.randint(0, B, size=(N, F)).astype(np.uint8)
bins_rm = jnp.asarray(bins_np)
g = jnp.asarray(rng.normal(size=N), jnp.float32)
h = jnp.asarray(rng.uniform(0.1, 0.3, size=N), jnp.float32)
w = jnp.ones((N,), jnp.float32)

NB = 8192


def timeit(name, fn, *args, reps=50):
    out = jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    outs = [fn(*args) for _ in range(reps)]
    jax.block_until_ready(outs[-1])
    dt = (time.perf_counter() - t0) / reps * 1000
    print(f"{name:50s} {dt:8.3f} ms", flush=True)
    return out


# ---------------- int8 kernel -------------------------------------------
def _kern_i8(bins_ref, vals_ref, out_ref, acc_ref, *, nb, f_blk, bb, V):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    vals = vals_ref[:, :]                                   # [V, nb] int8
    binz = bins_ref[:, :].astype(jnp.int32)
    iota = jax.lax.broadcasted_iota(jnp.int32, (nb, bb), 1)
    for f in range(f_blk):
        b_f = binz[:, f][:, None]
        onehot = (b_f == iota).astype(jnp.int8)
        part = jax.lax.dot_general(
            vals, onehot, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)               # [V, bb] i32
        acc_ref[f] += part

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


def decompose_int24(vals, scales):
    """vals [V, S] f32, scales [V] -> [3V, S] int8 balanced radix-256 of
    round(vals/scale * 2^22)."""
    q = jnp.round(vals / scales[:, None] * (1 << 22)).astype(jnp.int32)
    b2 = jnp.round(q.astype(jnp.float32) / 65536.0).astype(jnp.int32)
    r = q - b2 * 65536
    b1 = jnp.round(r.astype(jnp.float32) / 256.0).astype(jnp.int32)
    b0 = r - b1 * 256
    return jnp.concatenate([b2, b1, b0]).astype(jnp.int8)


@jax.jit
def root_int8(bins_rm, g, h, w):
    pad = (-N) % NB
    b = jnp.pad(bins_rm, ((0, pad), (0, 0)))
    vals = jnp.stack([jnp.pad(g, (0, pad)), jnp.pad(h, (0, pad)),
                      jnp.pad(w, (0, pad))])
    scales = jnp.maximum(jnp.max(jnp.abs(vals), axis=1), 1e-30)
    v9 = decompose_int24(vals, scales)                      # [9, S] i8
    S = N + pad
    out = pl.pallas_call(
        functools.partial(_kern_i8, nb=NB, f_blk=F, bb=B, V=9),
        grid=(S // NB,),
        in_specs=[pl.BlockSpec((NB, F), lambda i: (i, 0)),
                  pl.BlockSpec((9, NB), lambda i: (0, i))],
        out_specs=pl.BlockSpec((F, 9, B), lambda i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, 9, B), jnp.int32),
        scratch_shapes=[pltpu.VMEM((F, 9, B), jnp.int32)],
    )(b, v9)
    # combine: value = (s2*2^16 + s1*2^8 + s0) * scale / 2^22, in f64-ish
    # via f32 parts (each term exact-ish in f32 at 1M rows)
    s2 = out[:, 0:3].astype(jnp.float32)
    s1 = out[:, 3:6].astype(jnp.float32)
    s0 = out[:, 6:9].astype(jnp.float32)
    comb = (s2 * 65536.0 + s1 * 256.0 + s0)
    return comb * (scales[None, :, None] / (1 << 22))


# ---------------- bf16 x3 kernel ----------------------------------------
def _kern_bf(bins_ref, vals_ref, out_ref, acc_ref, *, nb, f_blk, bb, V):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    vals = vals_ref[:, :]
    binz = bins_ref[:, :].astype(jnp.int32)
    iota = jax.lax.broadcasted_iota(jnp.int32, (nb, bb), 1)
    for f in range(f_blk):
        b_f = binz[:, f][:, None]
        onehot = (b_f == iota).astype(jnp.bfloat16)
        part = jax.lax.dot_general(
            vals, onehot, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[f] += part

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


@jax.jit
def root_bf3(bins_rm, g, h, w):
    pad = (-N) % NB
    b = jnp.pad(bins_rm, ((0, pad), (0, 0)))
    vals = jnp.stack([jnp.pad(g, (0, pad)), jnp.pad(h, (0, pad)),
                      jnp.pad(w, (0, pad))])
    hi = vals.astype(jnp.bfloat16)
    r1 = vals - hi.astype(jnp.float32)
    mid = r1.astype(jnp.bfloat16)
    lo = (r1 - mid.astype(jnp.float32)).astype(jnp.bfloat16)
    v9 = jnp.concatenate([hi, mid, lo])
    S = N + pad
    out = pl.pallas_call(
        functools.partial(_kern_bf, nb=NB, f_blk=F, bb=B, V=9),
        grid=(S // NB,),
        in_specs=[pl.BlockSpec((NB, F), lambda i: (i, 0)),
                  pl.BlockSpec((9, NB), lambda i: (0, i))],
        out_specs=pl.BlockSpec((F, 9, B), lambda i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, 9, B), jnp.float32),
        scratch_shapes=[pltpu.VMEM((F, 9, B), jnp.float32)],
    )(b, v9)
    return out[:, 0:3] + out[:, 3:6] + out[:, 6:9]


for name, fn in [("int8 x3 (int32 exact)", root_int8),
                 ("bf16 x3 (f32 acc)", root_bf3)]:
    try:
        out = jax.block_until_ready(fn(bins_rm, g, h, w))
        out_np = np.asarray(out, np.float64)
        maxerr = 0.0
        for f in range(3):
            for v, arr in enumerate([np.asarray(g), np.asarray(h),
                                     np.asarray(w)]):
                ref = np.bincount(bins_np[:, f].astype(np.int64),
                                  weights=arr.astype(np.float64),
                                  minlength=B)
                err = np.max(np.abs(out_np[f, v] - ref) / (np.abs(ref) + 1.0))
                maxerr = max(maxerr, err)
        print(f"{name}: max rel err {maxerr:.2e}", flush=True)
        timeit(name, fn, bins_rm, g, h, w)
    except Exception as e:
        print(f"{name} FAILED: {type(e).__name__}: {str(e)[:300]}")
