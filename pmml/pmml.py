#!/usr/bin/env python
"""Convert a LightGBM-TPU model text file to PMML.

Role-parity with the reference's pmml/pmml.py tool: reads the saved model
(the reference-compatible text format written by Booster.save_model) and
emits a PMML 4.3 MiningModel of segmented TreeModels (sum aggregation).

Usage: python pmml/pmml.py <model_file> [output_file]
"""

from __future__ import annotations

import sys
from itertools import count
from xml.sax.saxutils import quoteattr


def _parse_model(text):
    """Parse the model text into header fields + per-tree dicts."""
    header = {}
    trees = []
    cur = None
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("Tree="):
            cur = {}
            trees.append(cur)
            continue
        if line.startswith("feature importances"):
            cur = None
            continue
        if "=" in line:
            key, value = line.split("=", 1)
            if cur is None:
                header[key] = value
            else:
                cur[key] = value
    return header, trees


def _arr(tree, key, conv=float):
    return [conv(t) for t in tree.get(key, "").split()] if tree.get(key) \
        else []


def _tree_to_pmml(tree, feature_names, out, tree_idx):
    num_leaves = int(tree["num_leaves"])
    split_feature = _arr(tree, "split_feature", int)
    threshold = _arr(tree, "threshold", float)
    decision_type = _arr(tree, "decision_type", int)
    left_child = _arr(tree, "left_child", int)
    right_child = _arr(tree, "right_child", int)
    leaf_value = _arr(tree, "leaf_value", float)
    leaf_count = _arr(tree, "leaf_count", int) or [0] * num_leaves
    internal_value = _arr(tree, "internal_value", float) or [0.0] * max(
        num_leaves - 1, 0)
    internal_count = _arr(tree, "internal_count", int) or [0] * max(
        num_leaves - 1, 0)
    leaf_parent = _arr(tree, "leaf_parent", int) or [-1] * num_leaves
    uid = count(1)

    out.append(f'\t\t<Segment id="{tree_idx + 1}">')
    out.append('\t\t\t<True />')
    out.append('\t\t\t<TreeModel functionName="regression" '
               'splitCharacteristic="binarySplit">')
    out.append('\t\t\t\t<MiningSchema>')
    for name in feature_names:
        out.append(f'\t\t\t\t\t<MiningField name={quoteattr(name)} />')
    out.append('\t\t\t\t</MiningSchema>')

    def predicate(tabs, node_id, is_left, parent_idx, is_leaf):
        idx = leaf_parent[node_id] if is_leaf else parent_idx
        if idx < 0:
            out.append("\t" * (tabs + 1) + "<True />")
            return
        field = feature_names[split_feature[idx]]
        if is_left:
            op = "equal" if decision_type[idx] == 1 else "lessOrEqual"
        else:
            op = "notEqual" if decision_type[idx] == 1 else "greaterThan"
        out.append("\t" * (tabs + 1)
                   + f'<SimplePredicate field={quoteattr(field)} '
                   f'operator="{op}" value="{threshold[idx]:g}" />')

    def emit(node_id, tabs, is_left, parent_idx):
        if node_id < 0:
            leaf = ~node_id
            score, record = leaf_value[leaf], leaf_count[leaf]
            is_leaf = True
            nid = leaf
        else:
            score, record = internal_value[node_id], internal_count[node_id]
            is_leaf = False
            nid = node_id
        out.append("\t" * tabs + f'<Node id="{next(uid)}" score="{score:g}" '
                                 f'recordCount="{record}">')
        predicate(tabs, nid, is_left, parent_idx, is_leaf)
        if not is_leaf:
            emit(left_child[node_id], tabs + 1, True, node_id)
            emit(right_child[node_id], tabs + 1, False, node_id)
        out.append("\t" * tabs + "</Node>")

    if num_leaves > 1:
        emit(0, 4, True, -1)
    else:
        out.append(f'\t\t\t\t<Node id="1" score='
                   f'"{leaf_value[0] if leaf_value else 0.0:g}" '
                   'recordCount="0"><True /></Node>')
    out.append('\t\t\t</TreeModel>')
    out.append('\t\t</Segment>')


def model_to_pmml(text: str) -> str:
    header, trees = _parse_model(text)
    feature_names = header.get("feature_names", "").split()
    out = ['<?xml version="1.0" encoding="UTF-8"?>',
           '<PMML version="4.3" xmlns="http://www.dmg.org/PMML-4_3">',
           '\t<Header copyright="lightgbm_tpu" />',
           '\t<DataDictionary>']
    for name in feature_names:
        out.append(f'\t\t<DataField name={quoteattr(name)} '
                   'optype="continuous" dataType="double" />')
    out.append('\t</DataDictionary>')
    out.append('\t<MiningModel functionName="regression">')
    out.append('\t\t<MiningSchema>')
    for name in feature_names:
        out.append(f'\t\t\t<MiningField name={quoteattr(name)} />')
    out.append('\t\t</MiningSchema>')
    out.append('\t<Segmentation multipleModelMethod="sum">')
    for i, tree in enumerate(trees):
        _tree_to_pmml(tree, feature_names, out, i)
    out.append('\t</Segmentation>')
    out.append('\t</MiningModel>')
    out.append('</PMML>')
    return "\n".join(out) + "\n"


def main(argv):
    if len(argv) < 2:
        print(__doc__)
        return 1
    with open(argv[1]) as fh:
        pmml = model_to_pmml(fh.read())
    out_path = argv[2] if len(argv) > 2 else argv[1] + ".pmml"
    with open(out_path, "w") as fh:
        fh.write(pmml)
    print(f"wrote {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
