"""Training and cross-validation entry points (reference engine.py).

train() (engine.py:17-203): callback-driven boosting loop with valid sets,
custom fobj/feval, continued training from init_model, per-iteration
learning rates, early stopping, evals_result capture.

cv() (engine.py:204-416): n-fold (optionally stratified) cross validation
aggregating mean/std per metric through a CVBooster.
"""

from __future__ import annotations

import collections
import copy
from typing import Dict, List, Optional

import numpy as np

from . import callback, obs
from .basic import Booster, Dataset
from .utils import log
from .utils.log import LightGBMError


def train(params, train_set, num_boost_round=100,
          valid_sets=None, valid_names=None,
          fobj=None, feval=None, init_model=None,
          feature_name="auto", categorical_feature="auto",
          early_stopping_rounds=None, evals_result=None,
          verbose_eval=True, learning_rates=None, callbacks=None,
          events_file=None):
    """Train with given parameters; returns a Booster.

    ``events_file`` (or the ``events_file`` params key / CLI
    ``--events-file``) streams one JSONL telemetry record per boosting
    iteration — phase timings, eval values, tree shape, cumulative
    collective bytes (lightgbm_tpu/obs/, docs/OBSERVABILITY.md).

    ``metrics_port`` (or ``LIGHTGBM_TPU_METRICS_PORT``) starts a
    daemon-thread ``GET /metrics`` listener for the duration of the run,
    serving the obs registry in Prometheus text exposition so standard
    monitoring can scrape a multi-hour boosting run mid-flight
    (``obs/metrics_server.py``; stopped cleanly when training exits).

    ``snapshot_dir`` + ``snapshot_freq`` params make the run crash-safe
    (docs/FAULT_TOLERANCE.md): every K iterations the full booster state
    is checkpointed atomically, and a later call with the same
    ``snapshot_dir`` auto-resumes from the newest valid snapshot,
    bit-exactly — corrupt/partial snapshot files are detected by
    checksum and fall back to the previous one."""
    params = dict(params or {})
    events_file = events_file or params.get("events_file") or None
    # -- persistent XLA compile cache (utils/compile_cache.py): applied
    # BEFORE any device work so the training programs themselves are
    # covered — repeated/resumed runs load executables from disk instead
    # of paying the 34-321 s warmup tax again.  On by default;
    # compile_cache_dir=off disables, LIGHTGBM_TPU_COMPILE_CACHE wins.
    from .utils import compile_cache as _compile_cache
    _compile_cache.setup(params.get("compile_cache_dir") or None)
    # -- deep observability (lightgbm_tpu/obs/, docs/OBSERVABILITY.md):
    # compile ledger / HBM watermarks / causal trace export.  All off
    # unless configured; the matching env vars win inside configure().
    from .obs import compile_ledger as _compile_ledger
    from .obs import devprof as _devprof
    from .obs import memwatch as _memwatch
    from .obs import tracing as _tracing
    _compile_ledger.configure(params.get("compile_ledger_file") or None)
    _devprof.configure(params.get("devprof"))
    _memwatch.configure(params.get("memwatch"))
    _tracing.TRACER.configure(params.get("trace_events_file") or None)
    # -- disk-full-safe sinks (utils/diskguard.py): each run's policy is
    # authoritative, and sinks a previous run's full disk disabled are
    # re-armed — this run may write to a different, healthy volume.
    from .utils import diskguard as _diskguard
    _diskguard.set_default_policy(params.get("sink_error_policy") or None)
    _diskguard.reset_disabled()
    # -- crash-safe snapshot/resume (lightgbm_tpu/snapshot.py) ----------
    snapshot_dir = str(params.get("snapshot_dir") or "") or None
    try:
        snapshot_freq = int(params.get("snapshot_freq", 0) or 0)
    except (TypeError, ValueError):
        raise ValueError(f"snapshot_freq={params['snapshot_freq']!r} "
                         "is not an integer")
    try:
        snapshot_keep = int(params.get("snapshot_keep", 3) or 0)
    except (TypeError, ValueError):
        snapshot_keep = 3
    if snapshot_freq > 0 and not snapshot_dir:
        log.warning("snapshot_freq=%d but no snapshot_dir given; "
                    "snapshots are DISABLED", snapshot_freq)
    resume_state = None
    if snapshot_dir:
        # multihost resume goes through the cross-rank consensus
        # (docs/FAULT_TOLERANCE.md §Distributed): all ranks agree on the
        # minimum common valid iteration and verify byte-identical files
        # before any round trains; single-process keeps the plain path.
        from .parallel.multihost import process_rank_world
        from .snapshot import coordinated_resume, load_latest_snapshot
        found = (coordinated_resume(snapshot_dir)
                 if process_rank_world()[1] > 1
                 else load_latest_snapshot(snapshot_dir))
        if found is not None:
            resume_path, resume_state = found
            if init_model is not None:
                log.warning("snapshot %s takes precedence over "
                            "init_model for resume", resume_path)
                init_model = None
            log.info("Resuming from snapshot %s (%d rounds done)",
                     resume_path, int(resume_state.get("rounds_done", 0)))
    if fobj is not None:
        params["objective"] = "none"
    for alias in ("num_boost_round", "num_iterations", "num_iteration",
                  "num_tree", "num_trees", "num_round", "num_rounds"):
        if alias in params:
            num_boost_round = int(params.pop(alias))
            break

    # continued training setup (engine.py:94-112)
    predictor = None
    if isinstance(init_model, str):
        predictor = Booster(model_file=init_model)
    elif isinstance(init_model, Booster):
        predictor = init_model._to_predictor()
    init_iteration = 0
    if predictor is not None:
        # total prior rounds, including any the predictor itself continued
        # from (chained continued training)
        init_iteration = len(predictor._booster.models) // max(
            predictor._booster.num_class, 1)

    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")
    train_set._update_params(params) \
             ._set_predictor(predictor) \
             .set_feature_name(feature_name) \
             .set_categorical_feature(categorical_feature)

    booster = Booster(params=params, train_set=train_set)
    if predictor is not None:
        # bring forward the previous model's trees (GBDT::MergeFrom role)
        booster._booster.models = list(predictor._booster.models) + \
            booster._booster.models
        booster._booster.num_init_iteration = init_iteration
        booster._booster.iter_ = init_iteration

    is_valid_contain_train = False
    train_data_name = "training"
    reduced_valid_sets = []
    name_valid_sets = []
    if valid_sets is not None:
        if isinstance(valid_sets, Dataset):
            valid_sets = [valid_sets]
        if isinstance(valid_names, str):
            valid_names = [valid_names]
        for i, valid_data in enumerate(valid_sets):
            if valid_data is train_set:
                is_valid_contain_train = True
                if valid_names is not None:
                    train_data_name = valid_names[i]
                continue
            if not isinstance(valid_data, Dataset):
                raise TypeError("Validation data should be Dataset instance")
            valid_data._update_params(params)
            reduced_valid_sets.append(valid_data)
            name_valid_sets.append(valid_names[i] if valid_names is not None
                                   else f"valid_{i}")
    booster.set_train_data_name(train_data_name)
    for vs, name in zip(reduced_valid_sets, name_valid_sets):
        booster.add_valid(vs, name)

    # Apply the resume state AFTER valid sets are attached so their
    # saved score caches land on the right _DeviceData buffers (the
    # replays above ran against an empty model and were no-ops).
    resume_done = 0
    if resume_state is not None:
        from .snapshot import restore_booster_state
        resume_done = restore_booster_state(booster, resume_state)
        init_iteration = booster._booster.num_init_iteration
        if early_stopping_rounds is not None:
            # the callback's best-score baseline is closure state the
            # snapshot cannot reach: it re-arms from the resume point, so
            # a run that would have early-stopped may run longer
            log.warning("resuming with early_stopping_rounds=%d: the "
                        "early-stopping counter restarts at iteration %d "
                        "(its pre-crash best-score baseline is not part "
                        "of the snapshot)", early_stopping_rounds,
                        init_iteration + resume_done)
        if resume_done >= num_boost_round:
            log.warning("snapshot already holds %d rounds >= "
                        "num_boost_round=%d; nothing left to train",
                        resume_done, num_boost_round)

    # telemetry event stream (lightgbm_tpu/obs/): the recorder is owned
    # here — attached to the booster for per-iteration notes, fed eval
    # values by log_telemetry, drained+closed after the loop.
    recorder = None
    if events_file:
        from .obs import EventRecorder
        try:
            flush_every = int(params.get("events_flush_every", 1) or 1)
        except (TypeError, ValueError):
            flush_every = 1
        recorder = EventRecorder(str(events_file),
                                 flush_every=flush_every)
        booster._booster.set_event_recorder(recorder)

    # callbacks (engine.py:113-142)
    cbs = set(callbacks or [])
    if recorder is not None:
        cbs.add(callback.log_telemetry())
    if verbose_eval is True:
        cbs.add(callback.print_evaluation())
    elif isinstance(verbose_eval, int) and verbose_eval is not False:
        cbs.add(callback.print_evaluation(verbose_eval))
    if early_stopping_rounds is not None:
        cbs.add(callback.early_stopping(early_stopping_rounds,
                                        verbose=bool(verbose_eval)))
    if learning_rates is not None:
        cbs.add(callback.reset_parameter(learning_rate=learning_rates))
    if evals_result is not None:
        cbs.add(callback.record_evaluation(evals_result))
    callbacks_before = {cb for cb in cbs
                        if getattr(cb, "before_iteration", False)}
    callbacks_after = cbs - callbacks_before
    callbacks_before = sorted(callbacks_before,
                              key=lambda cb: getattr(cb, "order", 0))
    callbacks_after = sorted(callbacks_after,
                             key=lambda cb: getattr(cb, "order", 0))

    # resumed eval history: restore AFTER record_evaluation's factory
    # cleared the dict, so the resumed run's evals_result continues the
    # interrupted one seamlessly
    if resume_state is not None and evals_result is not None \
            and resume_state.get("evals_result"):
        evals_result.update(copy.deepcopy(resume_state["evals_result"]))

    # -- scrapeable /metrics listener (obs/metrics_server.py): started
    # when metrics_port / LIGHTGBM_TPU_METRICS_PORT asks for one, so a
    # multi-hour run is visible to standard monitoring mid-flight.
    # Started HERE, after all setup that can raise, so a bad-params call
    # can never leak the bound port/thread; the finally below always
    # stops it.
    from .obs.metrics_server import maybe_start as _maybe_start_metrics
    metrics_server = _maybe_start_metrics(params)

    # a collective-watchdog hard abort (parallel/watchdog.py) bypasses
    # this function's finally block (os._exit while the loop is wedged
    # in a collective): hand the recorder to the watchdog so the event
    # stream is drained before the process dies
    from .parallel.watchdog import active_watchdog
    _watchdog = active_watchdog()
    if _watchdog is not None and recorder is not None:
        _watchdog.register_flush(recorder.close)

    # boosting loop (engine.py:143-203)
    try:
        for i in range(init_iteration + resume_done,
                       init_iteration + num_boost_round):
            for cb in callbacks_before:
                cb(callback.CallbackEnv(model=booster, params=params,
                                        iteration=i,
                                        begin_iteration=init_iteration,
                                        end_iteration=init_iteration
                                        + num_boost_round,
                                        evaluation_result_list=None))
            finished = booster.update(fobj=fobj)

            evaluation_result_list = []
            if is_valid_contain_train:
                evaluation_result_list.extend(booster.eval_train(feval))
            if reduced_valid_sets:
                evaluation_result_list.extend(booster.eval_valid(feval))
            try:
                for cb in callbacks_after:
                    cb(callback.CallbackEnv(
                        model=booster, params=params, iteration=i,
                        begin_iteration=init_iteration,
                        end_iteration=init_iteration + num_boost_round,
                        evaluation_result_list=evaluation_result_list))
            except callback.EarlyStopException as e:
                booster.best_iteration = e.best_iteration + 1
                break
            if snapshot_dir and snapshot_freq > 0 \
                    and (i + 1 - init_iteration) % snapshot_freq == 0:
                from .snapshot import save_snapshot
                save_snapshot(snapshot_dir, booster,
                              rounds_done=i + 1 - init_iteration,
                              evals_result=evals_result,
                              keep=snapshot_keep)
            if finished:
                # No leaf met the split requirements: the model is saturated
                # and further rounds would re-do full histogram work for
                # nothing (the CLI loop breaks the same way,
                # application.cpp:231).
                break
    finally:
        # a trace window the run ended inside must stop now, not at exit
        booster._booster.close_trace()
        if recorder is not None:
            # drain the pipelined last iteration so its tree shape lands in
            # the final record; best-effort, because if the loop is already
            # unwinding an exception the pending device arrays may be
            # poisoned and the flush must not mask the root cause (or skip
            # the close that writes the drained records out)
            try:
                booster._booster._flush_pending()
            except Exception:
                pass
            recorder.close()
            booster._booster.set_event_recorder(None)
        if metrics_server is not None:
            metrics_server.stop()
        if _watchdog is not None and recorder is not None:
            _watchdog.unregister_flush(recorder.close)
        # flush the causal span tree (one trace per boosting round) to
        # the configured Chrome trace-event file
        _tracing.TRACER.maybe_export()
    return booster


def _base_fingerprint(base_model):
    """The base model's training-data fingerprint (obs/drift.py), from a
    live Booster/engine or parsed straight out of a model-file tail.
    None when the artifact predates fingerprints — the skew check then
    quietly abstains."""
    from .obs.drift import parse_model_fingerprint
    try:
        if isinstance(base_model, str):
            with open(base_model) as fh:
                return parse_model_fingerprint(fh.read())
        inner = getattr(base_model, "_booster", base_model)
        return getattr(inner, "data_fingerprint", None)
    except Exception:
        # a garbled section raises its NAMED error on the train() load
        # path; the advisory check never preempts that diagnosis
        return None


def train_delta(base_model, fresh_data, num_trees=100, params=None,
                **kwargs):
    """Warm-start retrain for the serve→retrain loop (docs/SERVING.md
    §Promotion): boost ``num_trees`` new rounds on ``fresh_data`` on top
    of ``base_model`` (a Booster or model-file path) via the
    ``init_model`` path.  The base trees are carried over untouched —
    the returned booster's first ``base.num_trees()`` trees bit-match
    the base model — so the delta can be evaluated, merged
    (``Booster.merge``), or served as a canary candidate on its own.

    Train/serve skew check (docs/OBSERVABILITY.md §Drift): the fresh
    data's RAW rows are rebinned under the base artifact's fingerprint
    edges — the same comparison the serve collector makes (two
    fingerprints each bin their own data under their own quantile
    ladders, so shifted data re-binned by its own quantiles looks
    uniform again; data-vs-fingerprint is not fooled).  Drifted
    features become a named WARNING (plus the
    ``drift_skew_warnings_total`` counter), never a refusal: retraining
    on shifted data is the point of the delta loop, but it should say
    which columns moved."""
    base_fp = _base_fingerprint(base_model)  # before the data swap below
    raw = getattr(fresh_data, "data", None)  # before free_raw_data drops it
    raw = None if isinstance(raw, str) else raw
    booster = train(dict(params or {}), fresh_data,
                    num_boost_round=num_trees, init_model=base_model,
                    **kwargs)
    cmp = None
    threshold = float((params or {}).get("lifecycle_drift_threshold",
                                         0.25) or 0.25)
    top_k = int((params or {}).get("drift_top_k", 5) or 5)
    if base_fp is not None and raw is not None:
        from .obs.drift import compare_to_data
        try:
            cmp = compare_to_data(base_fp, raw, top_k=top_k)
        except Exception:
            cmp = None  # ragged/exotic raw payloads abstain, never fail
    if cmp is not None:
        offenders = [f for f in cmp["features"] if f["psi"] > threshold]
        if offenders:
            obs.inc("drift_skew_warnings_total")
            log.warning(
                "train_delta: fresh data drifted from the base model's "
                "training distribution (train/serve skew): %s "
                "(PSI threshold %g; rows %d -> %d)",
                ", ".join(f"{f['feature']} psi={f['psi']:g}"
                          for f in offenders),
                threshold, cmp["expected_rows"], cmp["actual_rows"])
    return booster


class CVBooster:
    """Auxiliary data struct holding all fold boosters (engine.py:204-240)."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster):
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler_function


def _make_n_folds(full_data: Dataset, data_splitter, nfold, params, seed,
                  fpreproc=None, stratified=False, shuffle=True):
    """Fold construction (engine.py:242-276)."""
    full_data.construct()
    num_data = full_data.num_data()
    if data_splitter is not None:
        if not hasattr(data_splitter, "split"):
            raise AttributeError("data_splitter has no method 'split'")
        folds = data_splitter.split(np.arange(num_data))
    elif stratified:
        label = np.asarray(full_data.get_label())
        classes, y = np.unique(label, return_inverse=True)
        rng = np.random.RandomState(seed)
        fold_id = np.zeros(num_data, np.int64)
        for c in range(len(classes)):
            idx = np.where(y == c)[0]
            if shuffle:
                rng.shuffle(idx)
            fold_id[idx] = np.arange(len(idx)) % nfold
        folds = [(np.where(fold_id != k)[0], np.where(fold_id == k)[0])
                 for k in range(nfold)]
    else:
        if shuffle:
            randidx = np.random.RandomState(seed).permutation(num_data)
        else:
            randidx = np.arange(num_data)
        test_id = [randidx[i::nfold] for i in range(nfold)]
        folds = [(np.setdiff1d(randidx, test_id[k], assume_unique=False),
                  test_id[k]) for k in range(nfold)]

    ret = CVBooster()
    for train_idx, test_idx in folds:
        train_subset = full_data.subset(np.sort(train_idx))
        valid_subset = full_data.subset(np.sort(test_idx))
        if fpreproc is not None:
            train_subset, valid_subset, tparam = fpreproc(
                train_subset, valid_subset, params.copy())
        else:
            tparam = params
        cvbooster = Booster(tparam, train_subset)
        cvbooster.add_valid(valid_subset, "valid")
        ret.append(cvbooster)
    return ret


def _agg_cv_result(raw_results):
    """Aggregate per-fold eval results (engine.py:278-290)."""
    cvmap = collections.OrderedDict()
    metric_type = {}
    for one_result in raw_results:
        for one_line in one_result:
            key = f"{one_line[0]} {one_line[1]}"
            metric_type[key] = one_line[3]
            cvmap.setdefault(key, [])
            cvmap[key].append(one_line[2])
    return [("cv_agg", k, float(np.mean(v)), metric_type[k],
             float(np.std(v))) for k, v in cvmap.items()]


def cv(params, train_set, num_boost_round=10,
       data_splitter=None, nfold=5, stratified=False, shuffle=True,
       metrics=None, fobj=None, feval=None, init_model=None,
       feature_name="auto", categorical_feature="auto",
       early_stopping_rounds=None, fpreproc=None,
       verbose_eval=None, show_stdv=True, seed=0, callbacks=None):
    """Cross-validation; returns {metric-name: [mean...], -stdv: [...]}."""
    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")
    params = dict(params or {})
    if fobj is not None:
        params["objective"] = "none"
    for alias in ("num_boost_round", "num_iterations", "num_iteration",
                  "num_tree", "num_trees", "num_round", "num_rounds"):
        if alias in params:
            num_boost_round = int(params.pop(alias))
            break
    if metrics is not None:
        params["metric"] = metrics
    train_set._update_params(params) \
             .set_feature_name(feature_name) \
             .set_categorical_feature(categorical_feature)

    results = collections.defaultdict(list)
    cvfolds = _make_n_folds(train_set, data_splitter, nfold, params, seed,
                            fpreproc=fpreproc, stratified=stratified,
                            shuffle=shuffle)

    cbs = set(callbacks or [])
    if early_stopping_rounds is not None:
        cbs.add(callback.early_stopping(early_stopping_rounds,
                                        verbose=False))
    if verbose_eval is True:
        cbs.add(callback.print_evaluation(show_stdv=show_stdv))
    elif isinstance(verbose_eval, int):
        cbs.add(callback.print_evaluation(verbose_eval, show_stdv=show_stdv))
    callbacks_before = {cb for cb in cbs
                        if getattr(cb, "before_iteration", False)}
    callbacks_after = cbs - callbacks_before
    callbacks_before = sorted(callbacks_before,
                              key=lambda cb: getattr(cb, "order", 0))
    callbacks_after = sorted(callbacks_after,
                             key=lambda cb: getattr(cb, "order", 0))

    for i in range(num_boost_round):
        for cb in callbacks_before:
            cb(callback.CallbackEnv(model=cvfolds, params=params,
                                    iteration=i, begin_iteration=0,
                                    end_iteration=num_boost_round,
                                    evaluation_result_list=None))
        for fold in cvfolds.boosters:
            fold.update(fobj=fobj)
        res = _agg_cv_result([fold.eval_valid(feval)
                              for fold in cvfolds.boosters])
        for _, key, mean, _, std in res:
            results[key + "-mean"].append(mean)
            results[key + "-stdv"].append(std)
        try:
            for cb in callbacks_after:
                cb(callback.CallbackEnv(model=cvfolds, params=params,
                                        iteration=i, begin_iteration=0,
                                        end_iteration=num_boost_round,
                                        evaluation_result_list=res))
        except callback.EarlyStopException as e:
            cvfolds.best_iteration = e.best_iteration + 1
            for k in results:
                results[k] = results[k][:cvfolds.best_iteration]
            break
    return dict(results)
