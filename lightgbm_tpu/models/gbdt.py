"""GBDT boosting engine: the full training loop state machine.

Reference: src/boosting/gbdt.{h,cpp}.  One boosting iteration
(GBDT::TrainOneIter, gbdt.cpp:295-382) becomes: a jitted objective pass, a
host-side bagging/feature-fraction mask draw, one jitted whole-tree growth
per class (ops/grow.py), and jitted score updates — scores never leave the
device during training; metrics pull them once per eval.
"""

from __future__ import annotations

import functools
import io
import os
import time
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from .. import obs
from ..config import Config
from ..io.binning import CATEGORICAL
from ..io.bundling import BundlePlan
from ..io.dataset import BinnedDataset
from ..ops.bundle import BundleDecode
from ..metric import Metric, create_metric
from ..objective import ObjectiveFunction, create_objective
from ..ops.grow import (GrowParams, SerialComm, grow_tree, pack_tree_arrays,
                        unpack_tree_arrays)
from ..ops.ordered_grow import grow_tree_ordered, pack_u8_words
from ..ops.predict import (predict_binned_forest,
                           predict_binned_forest_linear,
                           predict_binned_tree)
from ..utils import compile_cache, log, timetag
from ..utils.log import LightGBMError
from .linear import (LinearParams, affine_epilogue, attach_linear,
                     fit_leaf_models, pack_linear, unpack_linear)
from .screening import GainScreener
from .tree import Tree


class _HistView(NamedTuple):
    """One round's histogram-side data view: the (possibly EFB-bundled,
    possibly screening-compacted) column matrix plus its decode tables.
    Passed as a runtime pytree into the shared train_step / grow
    programs, so switching views never rebuilds a closure — the full
    view and the compacted view each trace once and are reused."""
    bins: Any                  # [C, N] column bin codes
    bins_rm: Any               # [N, C] row-major copy or None
    bins_words: Any            # word-packed lanes (ordered grower) or None
    bundle: Any                # ops.bundle.BundleDecode or None


def estimate_train_memory(num_data: int, num_features: int, num_leaves: int,
                          max_bin: int, num_models: int,
                          bin_itemsize: int = 1, *,
                          donate_score: bool = False,
                          fused_scratch: bool = False,
                          leaf_cache: bool = True,
                          linear_k: int = 0) -> Dict[str, int]:
    """Rough per-device HBM footprint (bytes) of training, by component.

    The dense-on-device design (SURVEY §7.2) has no sparse-bin fallback
    (reference sparse_bin.hpp stores sparse data ~20x smaller) and keeps
    the per-leaf histogram cache fully resident instead of LRU-bounding it
    (reference HistogramPool, feature_histogram.hpp:299-455) — so unlike
    the reference, an oversize problem cannot spill; the admission gate
    (``_check_memory_budget`` + ``utils/resource.py``,
    docs/FAULT_TOLERANCE.md §Resource exhaustion) must refuse or degrade
    at construction with this estimate instead of dying in XLA
    allocation.

    Components mirror what training actually allocates: column- and
    row-major bin copies (+ word-packed lanes for the ordered grower,
    padded to the largest window class), the 9-stream int8 digit payload,
    per-class score buffers, the [L, F, 9, B] int32 histogram cache
    (``leaf_cache=False`` — the fused kernel and the ``hist_cache``
    degrade step — zeroes it), the score-update double buffer
    (``donate_score=True`` — in-place XLA aliasing — zeroes it), and the
    fused kernel's VMEM scratch (``fused_scratch``: both children's
    histogram tiles live in VMEM during the pass instead of HBM).
    ``num_data`` is the PADDED row count when row bucketing is on — the
    pad rows allocate like real ones.  ``working`` doubles the sort
    payload: lax.sort and the window update-slices hold one extra copy
    of their operands live."""
    from ..ops.ordered_grow import _size_classes

    n, f = num_data, num_features
    pad = _size_classes(max(n, 1))[-1]
    words = -(-f // 4) if bin_itemsize == 1 else 0
    bins_cm = n * f * bin_itemsize
    bins_rm = n * f * bin_itemsize
    bins_words = (n + pad) * words * 4
    digits = (n + pad) * 16 + n * 9          # dig_w (3 words) + row_ord + [N,9]
    # score, grad, hess, and the per-class prediction delta are all live
    # at once at the peak of a boosting step
    scores = num_models * n * 4 * 4
    # without donation XLA materializes the updated [K, N] score cache
    # NEXT TO the old one at the update peak
    double_buf = 0 if donate_score else num_models * n * 4
    cache = (num_leaves * f * 9 * max_bin * 4) if leaf_cache else 0
    # fused histogram->split-gain kernel: both children's [F, B, 3] f32
    # tiles are scratch resident during the pass (never landed in HBM,
    # but the budget must still cover them — VMEM pressure spills)
    vmem = (2 * f * max_bin * 3 * 4) if fused_scratch else 0
    # linear_tree (docs/LINEAR_TREES.md): the resident [F, N] f32 raw
    # copy, the per-row [N, K+1] covariate/phi gather (x2: phi and the
    # per-slot segment-sum operand are live together), and the batched
    # normal equations [L, M, M] (A, its Cholesky factor, and the
    # right-hand sides — ~3 copies at the solve peak)
    linear = 0
    if linear_k > 0:
        m = linear_k + 1
        linear = (n * f * 4 + 2 * n * m * 4
                  + 3 * num_leaves * m * m * 4)
    payload = bins_words + digits
    return {
        "bins_device": bins_cm + bins_rm,
        "packed_payload": payload,
        "scores_and_gradients": scores,
        "score_double_buffer": double_buf,
        "histogram_cache": cache,
        "vmem_scratch": vmem,
        "linear_fit": linear,
        "working": payload,
        "total": (bins_cm + bins_rm + 2 * payload + scores + double_buf
                  + cache + vmem + linear),
    }


def estimate_valid_memory(num_data: int, num_features: int,
                          num_models: int,
                          bin_itemsize: int = 1) -> Dict[str, int]:
    """Per-device HBM footprint (bytes) of ATTACHING a validation set.

    A valid set allocates a column-major device bin matrix and a
    per-class f32 score buffer (``_DeviceData`` with
    ``with_row_major=False``); replaying/scoring holds one per-class
    prediction delta live on top.  Counted separately from
    ``estimate_train_memory`` so ``add_valid_dataset`` can fail fast
    instead of dying in a late XLA allocation when the valid set is
    attached after training state already fills the device."""
    n = num_data
    bins = n * num_features * bin_itemsize
    scores = num_models * n * 4
    working = n * 4                 # one class's delta during replay/score
    return {
        "bins_device": bins,
        "scores": scores,
        "working": working,
        "total": bins + scores + working,
    }


def _device_memory_limit() -> Optional[int]:
    """Per-device memory budget in bytes, or None when unknown.

    LGBT_DEVICE_MEMORY_BYTES overrides (test rigs, CPU backends whose
    memory_stats report nothing useful)."""
    env = os.environ.get("LGBT_DEVICE_MEMORY_BYTES")
    if env:
        try:
            return int(env)
        except ValueError:
            log.warning("LGBT_DEVICE_MEMORY_BYTES=%r is not an integer; "
                        "ignoring", env)
    try:
        stats = jax.devices()[0].memory_stats()
        if stats:
            return stats.get("bytes_limit")
    except Exception:  # pragma: no cover - backend without memory_stats
        pass
    return None


class _DeviceData:
    """Device-resident binned dataset + per-dataset score buffer
    (ScoreUpdater, score_updater.hpp:23-99).

    ``padded_rows`` > num_data pads every row-dimension array up to a
    shared shape bucket (utils/compile_cache.py bucket_rows): pad rows
    carry bin 0, zero gradients (via zero ``row_weight``, exactly how
    bagging excludes rows) and a score nobody reads — ``host_score``
    crops them.  Histogram sums are EXACT (the digit path is int32 and
    pad digits are zero), so splits match the unpadded run; only the f32
    leaf-total reductions may re-associate across shapes, the same
    last-bit wiggle any row-count change causes.  In exchange every
    jitted training program is shared across nearby dataset sizes."""

    def __init__(self, dataset: BinnedDataset, num_models: int,
                 with_row_major: bool = False,
                 padded_rows: Optional[int] = None,
                 with_raw: bool = False):
        self.dataset = dataset
        self.num_data = dataset.num_data
        self.padded_rows = max(int(padded_rows or 0), dataset.num_data)
        pad = self.padded_rows - dataset.num_data
        bins_np = dataset.bins if pad == 0 else \
            np.pad(dataset.bins, ((0, 0), (0, pad)))
        h2d_xfers, h2d_bytes = 1, int(bins_np.nbytes)
        # Native uint8/uint16 on device (int32 would 4x the HBM footprint
        # and the histogram kernel's read traffic).
        self.bins = jnp.asarray(bins_np)
        # Row-major copy for the cached serial learner's leaf gathers
        # (ops/leafhist.py needs rows contiguous).
        self.bins_rm = (jnp.asarray(np.ascontiguousarray(bins_np.T))
                        if with_row_major else None)
        if self.bins_rm is not None:
            h2d_xfers += 1
            h2d_bytes += int(bins_np.nbytes)
        # Word-packed payload lanes for the leaf-ordered grower, shared
        # across trees (uint8 bins only; uint16 routes to the cached
        # learner).
        self.bins_words = None
        if with_row_major and self.bins_rm is not None \
                and self.bins_rm.dtype == jnp.uint8:
            from ..ops.ordered_grow import _size_classes
            self.bins_words = _pack_words_padded(
                self.bins_rm, _size_classes(self.padded_rows)[-1])
        # raw f32 feature values for the linear-tree fit and its replay
        # epilogues (docs/LINEAR_TREES.md): NaN imputed to 0.0 ON UPLOAD
        # so the device fit and every predict path agree exactly; pad
        # rows read as zero and their zero row_weight keeps them out of
        # the normal equations anyway.
        self.raw = None
        if with_raw and dataset.raw is not None:
            raw_np = np.where(np.isnan(dataset.raw), np.float32(0.0),
                              dataset.raw).astype(np.float32)
            if pad:
                raw_np = np.pad(raw_np, ((0, 0), (0, pad)))
            self.raw = jnp.asarray(raw_np)
            h2d_xfers += 1
            h2d_bytes += int(raw_np.nbytes)
        init = np.zeros((num_models, self.padded_rows), np.float32)
        if dataset.metadata.init_score is not None:
            init[:, :self.num_data] += np.asarray(
                dataset.metadata.init_score,
                np.float32).reshape(num_models, self.num_data)
        self.score = jnp.asarray(init)
        obs.devprof.transfer("h2d", "dataset",
                             h2d_bytes + int(init.nbytes),
                             transfers=h2d_xfers + 1)

    def host_score(self, dtype=np.float64) -> np.ndarray:
        """[num_models, num_data] host copy of the score cache with the
        row-bucket pad cropped — what metrics/snapshots/C-API readers
        must consume instead of the raw (padded) device buffer."""
        return np.asarray(self.score, dtype)[:, :self.num_data]

    def set_score(self, score) -> None:
        """Replace the score cache from a host array of real rows,
        re-padding up to the bucket (snapshot restore)."""
        score = np.asarray(score, np.float32)
        if score.shape[-1] < self.padded_rows:
            score = np.pad(score, ((0, 0),
                                   (0, self.padded_rows - score.shape[-1])))
        self.score = jnp.asarray(score)

    def add_tree(self, tree_arrays, is_cat, cls: int, max_steps: int,
                 bundle=None):
        n = tree_arrays.split_feature.shape[0]
        delta, _ = predict_binned_tree(
            tree_arrays.split_feature, tree_arrays.split_bin,
            is_cat[jnp.maximum(tree_arrays.split_feature, 0)],
            tree_arrays.left_child, tree_arrays.right_child,
            tree_arrays.leaf_value, self.bins, max_steps, bundle=bundle)
        self.score = self.score.at[cls].add(delta)


@obs.instrumented_jit(program="finite_guard")
def _all_finite(*arrays):
    """One device scalar: every element of every array is finite.  The
    NaN/Inf containment guard (``nan_policy``) reads this per iteration;
    the reduction is jitted and cheap, but *reading* it synchronizes the
    async pipeline — which is why the guard is opt-in."""
    ok = jnp.asarray(True)
    for a in arrays:
        ok = ok & jnp.isfinite(a).all()
    return ok


@obs.instrumented_jit(program="bag_mask",
                      static_argnames=("n", "bag_cnt", "n_real"))
def _device_bag_mask(key, n: int, bag_cnt: int, n_real: int = -1):
    """EXACT-count sample without replacement (reference bag_data_cnt_).

    Ranks rows by raw 32-bit random words with the row index as a total-
    order tie-break: f32 uniforms sit on a ~2^-23 grid, so at N=1M the
    kth order statistic collides with another row in roughly 1 of 8
    draws and a value-only threshold would keep bag_cnt+1 rows.  The
    (word, index) pair is unique, so exactly bag_cnt rows satisfy
    pair <= pair_sorted[bag_cnt - 1].

    ``n_real < n`` marks the tail as row-bucket padding
    (utils/compile_cache.py): pad rows draw the max word, so every real
    (word, index) pair sorts before them and the bag is drawn from real
    rows only."""
    if bag_cnt <= 0:
        # matches the host-draw degenerate case (reference bag_data_cnt=0
        # keeps nothing); the wrapped [-1] index would keep EVERYTHING
        return jnp.zeros((n,), jnp.float32)
    n_real = n if n_real < 0 else n_real
    r = jax.random.bits(key, (n,), jnp.uint32)
    iota = jnp.arange(n, dtype=jnp.int32)
    if n_real < n:
        r = jnp.where(iota < n_real, r, jnp.uint32(0xFFFFFFFF))
    r_sorted, i_sorted = jax.lax.sort((r, iota), num_keys=1,
                                      is_stable=True)
    thr_r = r_sorted[bag_cnt - 1]
    thr_i = i_sorted[bag_cnt - 1]
    keep = (r < thr_r) | ((r == thr_r) & (iota <= thr_i))
    if n_real < n:
        keep &= iota < n_real
    return keep.astype(jnp.float32)


@obs.instrumented_jit(program="pack_words", static_argnames=("pad",))
def _pack_words_padded(rm, pad: int):
    """Word-pack a row-major bin matrix and pad each word lane by the
    ordered grower's largest window class.  Module-level (pad is a
    static argument, not a closure) so every booster over the same
    shapes shares ONE compiled program."""
    return tuple(jnp.pad(w, (0, pad)) for w in pack_u8_words(rm))


_PACK_TREE = obs.instrumented_jit(pack_tree_arrays, program="pack_tree")


def _donation_enabled() -> bool:
    """Round-to-round buffer donation is gated to accelerator backends.
    On this jax build XLA:CPU's input-output aliasing intermittently
    corrupts donated buffers (freed-buffer reads that surface as
    segfaults in LATER host conversions — reproduced in the round-7
    suite by running training files together), and the double-allocation
    donation avoids only matters for HBM-sized buffers anyway.
    ``LIGHTGBM_TPU_DONATION`` (1/0) overrides for experiments."""
    env = os.environ.get("LIGHTGBM_TPU_DONATION", "").strip().lower()
    if env:
        return env in ("1", "true", "yes", "on")
    return _donation_safe()


def _donation_safe() -> bool:
    """Whether the backend's input-output aliasing is trustworthy at all
    (accelerators yes, XLA:CPU no — see ``_donation_enabled``).  The
    ``score_donation`` degrade step may re-enable donation an env
    override turned off, but never on a backend where aliasing corrupts
    buffers: a memory degrade must not trade OOM for wrong answers."""
    try:
        return jax.default_backend() != "cpu"
    except Exception:  # pragma: no cover - backend not initialized
        return False


@obs.instrumented_jit(program="score_update", static_argnames=("cls",),
                      donate_argnums=(0,))
def _score_add_donated(score, delta, cls: int):
    """In-place (donated) per-class score update: XLA writes the new
    score into the old buffer instead of double-allocating the
    [num_class, N] cache every round.  Only used when nan_policy is off
    (containment keeps a pre-iteration reference alive for rollback,
    which donation would invalidate) AND _donation_enabled() says the
    backend supports aliasing safely."""
    return score.at[cls].add(delta)


# ---------------------------------------------------------------------------
# Process-wide training-program registry.
#
# Every GBDT instance used to build its own train_step/train_gradients
# closures, capturing the dataset's bins/labels as compile-time
# constants — so the SECOND same-config booster in a process (rebuilt
# after snapshot-resume, a second engine.train call, the bench's warm
# pass) re-traced and re-compiled everything from scratch.  With the
# objective's functional-gradients interface every per-dataset array is
# now a runtime ARGUMENT, so one traced program per (objective key,
# class count, guard, grow strategy, grow params) serves every booster;
# repeated runs hit the jit's executable cache and record ZERO new
# train_step compiles in the ledger.

_SHARED_JITS: Dict[tuple, Any] = {}

# Entries retain only scalar-bearing objective HOLDERS (program_holder
# strips the per-dataset arrays), so a cached program costs bytes, not a
# dead dataset's HBM.  The cap is a leak backstop for pathological key
# churn (legacy id-keyed objectives in a long sweep); eviction only
# costs a recompile if that config returns.
_SHARED_JITS_MAX = 64


def _shared_jit(key: tuple, make, program: str, **jit_kwargs):
    fn = _SHARED_JITS.get(key)
    if fn is None:
        while len(_SHARED_JITS) >= _SHARED_JITS_MAX:
            _SHARED_JITS.pop(next(iter(_SHARED_JITS)))
        fn = obs.instrumented_jit(make(), program=program, **jit_kwargs)
        _SHARED_JITS[key] = fn
    return fn


def _shared_gradients_fn(objective):
    """Shared jitted gradients program for this objective configuration
    (arrays travel as arguments; scalars key the program)."""
    holder = objective.program_holder()
    return _shared_jit(("train_gradients", objective.program_key()),
                       lambda: holder.gradients_with,
                       program="train_gradients")


def _build_shared_train_step(objective, num_class: int, guard: bool,
                             kind: str, params: GrowParams,
                             linear: Optional[LinearParams] = None):
    """One fused boosting iteration as a PURE function of device arrays:
    gradients -> per-class grow -> score update -> packed host vectors.
    ``kind`` picks the serial growth strategy; the inner grow jits
    inline under this trace (obs/compile_ledger.py passthrough).

    ``linear`` (docs/LINEAR_TREES.md) appends the batched per-leaf
    affine fit after each class's growth: the fitted intercepts replace
    the grown leaf values, the fitted delta replaces the grower's
    constant delta, and the packed transfer grows the (feat, coeff)
    vectors.  ``linear=None`` leaves the trace — and the registry key —
    byte-identical to the pre-linear program."""
    fused_comm = SerialComm(leaf_cache=False, fused_gain=True)
    nocache_comm = SerialComm(leaf_cache=False)

    def step_fn(score, feat_masks, row_weight, lr, bins, num_bin, is_cat,
                grad_arrays, bins_rm, bins_words, bundle, raw=None):
        grad, hess = objective.gradients_with(grad_arrays, score)
        ok = (_all_finite(grad, hess) if guard else jnp.asarray(True))
        outs = []
        for cls in range(num_class):
            args = (bins, num_bin, is_cat, feat_masks[cls], grad[cls],
                    hess[cls], row_weight, lr)
            if kind == "ordered":
                # the leaf-ordered grower has no column decode; kind
                # selection guarantees bundle is None here
                ta, _, delta = grow_tree_ordered(*args, params,
                                                 bins_rm=bins_rm,
                                                 bins_words=bins_words)
            elif kind == "fused":
                ta, _, delta = grow_tree(*args, params, fused_comm, bins_rm,
                                         bundle=bundle)
            elif kind == "nocache":
                # hist_cache degrade step: full-pass growth, no resident
                # [L, F, 9, B] cache (memory_policy=degrade)
                ta, _, delta = grow_tree(*args, params, nocache_comm,
                                         bins_rm, bundle=bundle)
            else:
                ta, _, delta = grow_tree(*args, params, bins_rm=bins_rm,
                                         bundle=bundle)
            if linear is not None:
                ta, coeff, feat, delta, fb = fit_leaf_models(
                    ta, bins, is_cat, raw, grad[cls], hess[cls],
                    row_weight, lr, linear, bundle=bundle)
                score = score.at[cls].add(delta)
                outs.append((pack_tree_arrays(ta)
                             + pack_linear(coeff, feat, fb),
                             ta, delta, (coeff, feat)))
            else:
                score = score.at[cls].add(delta)
                outs.append((pack_tree_arrays(ta), ta, delta))
        return score, outs, ok
    return step_fn


def _shared_train_step(objective, num_class: int, guard: bool, kind: str,
                       params: GrowParams, donate: bool,
                       linear: Optional[LinearParams] = None):
    key = ("train_step", objective.program_key(), num_class, guard, kind,
           params, donate, linear)
    holder = objective.program_holder()
    return _shared_jit(
        key,
        lambda: _build_shared_train_step(holder, num_class, guard,
                                         kind, params, linear),
        program="train_step",
        # round-to-round state donation: the score cache is the only
        # argument that is dead after the call (the caller immediately
        # rebinds it to the output), so XLA may update it in place
        # instead of double-allocating [num_class, N] every iteration
        donate_argnums=(0,) if donate else ())


def _shared_linear_fit(linear: LinearParams):
    """Shared jitted program for the PER-STAGE path's batched leaf fit
    (GOSS, custom fobj, LGBT_NO_FUSED_STEP — the fused path inlines
    fit_leaf_models into train_step instead).  Keyed on the static
    LinearParams alone: every per-dataset array travels as an argument,
    so rebuilt boosters reuse the compiled program."""
    def make():
        def fit(tree_arrays, bins, is_cat, raw, grad, hess, row_weight,
                lr, bundle):
            return fit_leaf_models(tree_arrays, bins, is_cat, raw, grad,
                                   hess, row_weight, lr, linear,
                                   bundle=bundle)
        return fit
    return _shared_jit(("linear_fit", linear), make, program="linear_fit")


_PACK_LINEAR = obs.instrumented_jit(pack_linear, program="pack_tree")


class GBDT:
    """Gradient Boosting Decision Tree (reference gbdt.h:20-351).

    Training is PIPELINED: ``train_one_iter`` materializes the *previous*
    iteration's trees (one batched device->host transfer) and then
    dispatches this iteration's device work, so the host never blocks on
    the iteration it just dispatched and per-field sync round-trips are
    gone.  ``models`` is a property that flushes the pending iteration, so
    every reader sees the synchronous view.  Subclasses needing tree bodies
    right after training (DART's Normalize) set ``_pipeline = False``.
    """

    submodel_name = "gbdt"
    _pipeline = True
    _pending_iter = None          # [tree_arrays] of the last iteration
    _pending_shrinkage = 1.0
    _no_more_splits = False
    # -- wide-sparse subsystem (docs/SPARSE.md; None/off on loaded
    # prediction-only boosters) ----------------------------------------
    _bundle = None                # ops.bundle.BundleDecode (EFB)
    _bundle_plan = None
    _screener = None              # models/screening.py GainScreener
    _screen_mask_dev = None
    _parallel_grow_active = False
    # -- piece-wise linear trees (models/linear.py, docs/LINEAR_TREES.md;
    # None = constant leaves, the default) ------------------------------
    _linear: Optional[LinearParams] = None
    # -- telemetry (lightgbm_tpu/obs/; all optional, None/zero = off) ----
    _telemetry = None             # obs.EventRecorder (set_event_recorder)
    _trace = None                 # obs.TraceCapture window (env/config)
    _comm_traffic = None          # static per-tree collective account
    _comm_traffic_totals = (0, 0)  # (calls, bytes) per tree, precomputed
    _cum_comm_bytes = 0
    _cum_comm_calls = 0
    _bag_cnt = 0                  # rows in the current bagging draw
    _pending_iter_idx = -1        # iteration index of _pending_iter
    # -- fault tolerance (docs/FAULT_TOLERANCE.md) ----------------------
    _nan_policy = "none"          # none | fail_fast | skip_tree
    _nan_skips = 0                # poisoned iterations dropped (skip_tree)
    # -- resource degrade ladder (memory_policy=degrade; utils/resource.py,
    # docs/FAULT_TOLERANCE.md §Resource exhaustion) ---------------------
    _degrade_steps: Tuple[str, ...] = ()   # applied steps, in order
    _degrade_force_donate = False  # score_donation step fired
    _degrade_leaf_cache_off = False  # hist_cache step fired
    # -- drift observatory (obs/drift.py, docs/OBSERVABILITY.md §Drift):
    # training-data fingerprint carried in the model artifact.  Distinct
    # from snapshot_state's config "fingerprint" (resume compatibility).
    data_fingerprint = None

    def __init__(self, config: Config, train_set: Optional[BinnedDataset],
                 objective: Optional[ObjectiveFunction] = None):
        self.config = config
        self.iter_ = 0
        self.models: List[Tree] = []  # num_iter * num_class, class-major rows
        self.best_iteration = -1
        self.best_score: Dict[Tuple[int, str], float] = {}
        self.best_msg: Dict[int, str] = {}
        self.num_init_iteration = 0
        self.label_idx = 0
        self.sigmoid = (config.sigmoid if config.objective == "binary" else -1.0)
        if train_set is not None:
            self._setup(train_set, objective)

    # ------------------------------------------------------------------
    def _setup(self, train_set: BinnedDataset, objective) -> None:
        cfg = self.config
        self.train_set = train_set
        self.data_fingerprint = getattr(train_set, "data_fingerprint", None)
        self.objective = objective or create_objective(cfg)
        self.objective.init(train_set.metadata, train_set.num_data)
        self.num_class = self.objective.num_tree_per_iteration
        self.num_data = train_set.num_data
        self.num_features = train_set.num_features
        self.max_feature_idx = train_set.num_total_features - 1
        self.feature_names = list(train_set.feature_names)

        self.num_bin = jnp.asarray(train_set.num_bin_per_feature())
        self.is_cat = jnp.asarray(train_set.is_categorical_per_feature())
        self.max_bin = cfg.max_bin
        self.num_columns = train_set.num_columns
        self._setup_bundle(train_set, cfg)
        self.grow_params = self._make_grow_params(cfg)
        self.shrinkage_rate = cfg.learning_rate

        # shape-bucketed training rows (utils/compile_cache.py): nearby
        # dataset sizes share one compiled train_step/grow program.
        # Legacy custom objectives (pre-round-7 gradients() overrides)
        # close over unpadded arrays, so they opt out.
        self._padded_rows = (compile_cache.bucket_rows(self.num_data)
                             if self._row_buckets_enabled(cfg)
                             and not self.objective.uses_legacy_gradients()
                             else self.num_data)
        self._linear = self._setup_linear(cfg, train_set)
        self._check_memory_budget(cfg, train_set)
        self.train_data = _DeviceData(train_set, self.num_class,
                                      with_row_major=True,
                                      padded_rows=self._padded_rows,
                                      with_raw=self._linear is not None)
        self.valid_data: List[_DeviceData] = []
        self.valid_metrics: List[List[Metric]] = []
        self.train_metrics = self._make_metrics(cfg, train_set)

        self._trace = obs.TraceCapture.from_config(cfg)
        self._nan_policy = str(getattr(cfg, "nan_policy", "none") or "none")
        self._nan_skips = 0
        # distributed desync detection (docs/FAULT_TOLERANCE.md
        # §Distributed): every K rounds, allgather a cheap digest of the
        # replicated state and verify every rank agrees.  Zero overhead
        # single-process: the gate short-circuits on world size before
        # touching anything (no collectives, no compiles).
        self._consistency_every = int(
            getattr(cfg, "distributed_consistency_check", 0) or 0)
        self._desync_policy = str(
            getattr(cfg, "desync_policy", "fail_fast") or "fail_fast")
        self._bag_cnt = self.num_data
        self._bag_key = jax.random.PRNGKey(cfg.bagging_seed)
        self._feature_rng = np.random.RandomState(cfg.feature_fraction_seed)
        self._init_row_state()
        self._grad_arrays = self.objective.gradient_arrays(self._padded_rows)
        self._grad_fn = self._make_grad_fn()
        self._setup_screening(cfg)
        self._grow_fn = self._make_grow_fn()
        self._full_view = self._make_full_view()
        # device-constant caches (avoid a host->device transfer per iter)
        self._full_feat_mask = jnp.ones(self.num_features, bool)
        self._full_feat_masks = jnp.ones((self.num_class, self.num_features),
                                         bool)
        self._lr_cache: Tuple[float, jax.Array] = (-1.0, jnp.float32(0))
        self._train_step = None

    def _setup_bundle(self, train_set: BinnedDataset, cfg: Config) -> None:
        """Device decode tables for an EFB-bundled dataset
        (io/bundling.py plan -> ops/bundle.py BundleDecode)."""
        plan = getattr(train_set, "bundle_plan", None)
        self._bundle_plan = plan
        self._bundle = None
        if plan is None:
            self._bundle_col_np = np.arange(self.num_features, dtype=np.int64)
            return
        dn = plan.decode_arrays(
            [m.num_bin for m in train_set.mappers],
            [m.default_bin for m in train_set.mappers], cfg.max_bin)
        self._bundle = BundleDecode(
            col=jnp.asarray(dn["col"]), off=jnp.asarray(dn["off"]),
            width=jnp.asarray(dn["width"]),
            slot_map=jnp.asarray(dn["slot_map"]),
            default_bin=jnp.asarray(dn["default_bin"]))
        self._bundle_col_np = dn["col"].astype(np.int64)
        log.info("EFB active: %d feature(s) in %d column(s) "
                 "(%d bundle(s))", self.num_features, self.num_columns,
                 len(plan.bundles))

    def _setup_screening(self, cfg: Config) -> None:
        """EMA-FS gain screening state (models/screening.py)."""
        ratio = float(getattr(cfg, "feature_screen_ratio", 0.0) or 0.0)
        self._screener = None
        self._screen_mask_dev = None
        self._screen_mask_np = None
        self._screen_period = -1
        self._active_view = None
        self._identity_decode = None
        if ratio <= 0.0:
            return
        self._screener = GainScreener(
            self.num_features, self.num_columns, self._bundle_col_np,
            ratio=ratio,
            refresh=int(getattr(cfg, "feature_screen_refresh", 10) or 10),
            warmup=int(getattr(cfg, "feature_screen_warmup", 20) or 0),
            decay=float(getattr(cfg, "feature_screen_decay", 0.9) or 0.9))

    def _setup_linear(self, cfg: Config,
                      train_set: BinnedDataset) -> Optional[LinearParams]:
        """Piece-wise linear leaf config (models/linear.py,
        docs/LINEAR_TREES.md), or None when the subsystem is off/inert.
        Unsupportable combinations REFUSE with a named error instead of
        silently training a different model."""
        if not bool(getattr(cfg, "linear_tree", False)):
            return None
        k = int(getattr(cfg, "linear_max_leaf_features", 0) or 0)
        if k <= 0:
            # the documented degenerate case: zero covariate slots means
            # constant leaves — the whole subsystem stays inert, so the
            # run is bit/ledger-identical to linear_tree=false
            log.warn_once(
                "linear_tree_k0",
                "linear_tree=true with linear_max_leaf_features=0: "
                "leaves stay constant (the linear subsystem is inert "
                "and output is identical to linear_tree=false)")
            return None
        parallel = bool(getattr(cfg, "is_parallel", False))
        try:
            parallel = parallel or jax.process_count() > 1
        except Exception:  # pragma: no cover - uninitialized backend
            pass
        if parallel:
            raise LightGBMError(
                "linear_tree is not supported with distributed training "
                "(the per-leaf ridge solve needs the full raw feature "
                "matrix on one device); use tree_learner=serial on a "
                "single process, or set linear_tree=false")
        if train_set.raw is None:
            raise LightGBMError(
                "linear_tree requires the raw feature values, but this "
                "dataset carries none (streamed ingest, or a binary "
                "file saved without linear_tree).  Rebuild the Dataset "
                "from an in-memory matrix with linear_tree=true in its "
                "params, or re-save the binary with it")
        return LinearParams(k, float(cfg.linear_lambda),
                            float(cfg.lambda_l2))

    def _make_full_view(self) -> _HistView:
        td = self.train_data
        return _HistView(bins=td.bins, bins_rm=td.bins_rm,
                         bins_words=td.bins_words, bundle=self._bundle)

    @staticmethod
    def _row_buckets_enabled(cfg: Config) -> bool:
        """Row-bucket padding applies to single-process serial training
        only: the distributed learners shard rows across a device mesh
        (ingest owns their layout), and multihost arrays are promoted
        per process — padding either would change those invariants."""
        if not bool(getattr(cfg, "row_buckets", True)):
            return False
        if getattr(cfg, "is_parallel", False):
            return False
        try:
            if jax.process_count() > 1:
                return False
        except Exception:  # pragma: no cover - uninitialized backend
            pass
        return True

    def _init_row_state(self) -> None:
        """Row-dimension device state at the padded shape: the real-row
        mask and the all-ones (real rows only) weight vector every
        un-bagged iteration reuses."""
        mask = np.zeros(self._padded_rows, bool)
        mask[:self.num_data] = True
        self._real_rows = jnp.asarray(mask)
        self._ones_weight = jnp.asarray(mask.astype(np.float32))
        self._row_weight = self._ones_weight

    def _make_grad_fn(self):
        """Per-booster binding of the SHARED gradients program: the
        arrays travel per call, so a rebuilt booster (resume, second
        run) reuses the compiled program instead of re-tracing one that
        baked the previous dataset's labels in as constants."""
        jit = _shared_gradients_fn(self.objective)
        arrays = self._grad_arrays
        return lambda score: jit(arrays, score)

    def _serial_grow_kind(self) -> str:
        cfg = self.config
        if cfg.serial_grow == "fused":
            return "fused"
        # the hist_cache degrade step (memory_policy=degrade) dropped
        # the per-leaf histogram cache: route through the cacheless
        # full-pass learner (exact parity with the cached one — both
        # scan the same histograms; only the reuse strategy differs)
        if self._degrade_leaf_cache_off:
            return "nocache"
        # EFB columns and screening's compacted views both need the
        # per-split column decode, which the leaf-ordered grower's packed
        # word lanes do not carry — route to the cached learner (exact
        # parity with ordered is pinned by tests/test_ordered_grow.py)
        needs_decode = (self._bundle is not None
                        or self._screener is not None)
        if needs_decode:
            if cfg.serial_grow == "ordered":
                log.warn_once(
                    "serial_grow_decode",
                    "serial_grow=ordered: using the cached serial "
                    "learner instead (EFB bundling / feature screening "
                    "need the column-decode path)")
            return "cached"
        if cfg.serial_grow == "ordered" \
                and self.train_data.bins_words is not None:
            return "ordered"
        return "cached"

    # -- HBM admission control (docs/FAULT_TOLERANCE.md §Resource
    # exhaustion).  The estimate/gate/degrade machinery is host-side
    # arithmetic by construction: ZERO new XLA programs (ledger-pinned
    # by tests/test_resource_chaos.py).

    def _estimate_now(self, cfg: Config, train_set: BinnedDataset,
                      guard: bool) -> Dict[str, int]:
        """The training estimate under the CURRENT construction state —
        re-evaluated after each degrade step so the ladder can stop as
        soon as the footprint fits."""
        fused = cfg.serial_grow == "fused"
        return estimate_train_memory(
            self._padded_rows, train_set.num_columns, cfg.num_leaves,
            cfg.max_bin, self.num_class,
            bin_itemsize=train_set.bins.dtype.itemsize,
            donate_score=not guard and self._donation_on(),
            fused_scratch=fused,
            leaf_cache=not fused and not self._degrade_leaf_cache_off,
            linear_k=(self._linear.max_features
                      if self._linear is not None else 0))

    def _donation_on(self) -> bool:
        """This booster's round-to-round donation decision (before the
        nan-guard veto): the env/default gate, plus the ``score_donation``
        degrade step's override — which only ever fires where
        ``_donation_safe`` says aliasing is trustworthy."""
        if self._degrade_force_donate and _donation_safe():
            return True
        return _donation_enabled()

    def _check_memory_budget(self, cfg: Config,
                             train_set: BinnedDataset) -> None:
        """Pre-flight HBM admission gate: compare the per-component
        estimate against the device budget and apply ``memory_policy``:

        - ``fail_fast`` (default): refuse an over-budget config with a
          named ``MemoryBudgetExceeded`` carrying the component table —
          instead of dying hours later in an opaque XLA allocation;
        - ``degrade``: walk the documented footprint ladder
          (``utils/resource.py DEGRADE_STEPS``) — re-enable score
          donation where safe (drops the score double buffer), drop the
          per-leaf histogram cache (children recompute instead of
          sibling-subtraction; also honors ``histogram_pool_size`` as a
          real bound), cap the row-bucket pad — one ``warn_once`` +
          ``resource_degrade_*`` counter per applied step, refusing only
          if the ladder bottoms out still over budget."""
        from ..utils import resource
        guard = str(getattr(cfg, "nan_policy", "none") or "none") != "none"
        policy = resource.check_memory_policy(
            getattr(cfg, "memory_policy", "fail_fast"))
        est = self._estimate_now(cfg, train_set, guard)
        pool_mb = float(getattr(cfg, "histogram_pool_size", -1.0) or -1.0)
        if pool_mb > 0 and est["histogram_cache"] > pool_mb * (1 << 20):
            if policy == "degrade":
                # the reference's HistogramPool bound, honored the only
                # way fixed-shape jits can: the resident cache goes away
                # entirely and children recompute their histograms
                self._apply_degrade(
                    "hist_cache", est["histogram_cache"],
                    f"histogram_pool_size={pool_mb:g}MB bounds the "
                    f"per-leaf histogram cache "
                    f"({est['histogram_cache'] / (1 << 20):.0f}MB "
                    f"resident): dropping the cache — children "
                    f"recompute instead of sibling-subtraction")
                est = self._estimate_now(cfg, train_set, guard)
            else:
                log.warn_once(
                    "histogram_pool_size",
                    "histogram_pool_size=%.0fMB requested but the TPU "
                    "design keeps the whole per-leaf histogram cache "
                    "resident (%.0fMB for num_leaves=%d x %d columns x 9 "
                    "x %d bins); under memory_policy=fail_fast the "
                    "parameter does NOT bound memory — lower "
                    "num_leaves/max_bin, or set memory_policy=degrade "
                    "to make the bound real", pool_mb,
                    est["histogram_cache"] / (1 << 20), cfg.num_leaves,
                    train_set.num_columns, cfg.max_bin)
        limit = _device_memory_limit()
        obs.set_gauge("hbm_budget_bytes", int(limit) if limit else -1)
        if limit and est["total"] > limit and policy == "degrade":
            est = self._walk_degrade_ladder(cfg, train_set, guard, est,
                                            limit)
        obs.set_gauge("hbm_train_estimate_bytes", int(est["total"]))
        obs.set_gauge("hbm_histogram_cache_bytes",
                      int(est["histogram_cache"]))
        # publish the table for the DeviceOOM diagnosis (the gate's
        # prediction next to what the allocator saw)
        resource.set_budget_table(
            est, f"train rows={self._padded_rows} "
                 f"cols={train_set.num_columns} "
                 f"leaves={cfg.num_leaves} bins={cfg.max_bin}")
        if limit and est["total"] > limit:
            raise resource.refuse(est, limit, "training",
                                  self._degrade_steps)
        # running account for add_valid_dataset's incremental re-check
        self._train_mem_est = int(est["total"])
        self._valid_mem_bytes = 0

    def _apply_degrade(self, step: str, saved_bytes: int,
                       detail: str) -> None:
        from ..utils import resource
        if step == "score_donation":
            self._degrade_force_donate = True
        elif step == "hist_cache":
            self._degrade_leaf_cache_off = True
        elif step == "row_pad":
            self._padded_rows = self.num_data
        self._degrade_steps = self._degrade_steps + (step,)
        resource.note_degrade(step, saved_bytes, detail)

    def _walk_degrade_ladder(self, cfg: Config, train_set: BinnedDataset,
                             guard: bool, est: Dict[str, int],
                             limit: int) -> Dict[str, int]:
        """Apply the footprint ladder in order until the estimate fits
        (or every available step is spent).  Unavailable steps (nan
        guard pins the rollback buffer, CPU aliasing is unsafe, pad
        already zero) are skipped with a debug line — degrading must
        never trade memory for wrong answers."""
        from ..utils import resource
        for step in resource.DEGRADE_STEPS:
            if est["total"] <= limit:
                break
            if step == "score_donation":
                if guard or self._donation_on() or not _donation_safe():
                    log.debug("degrade step score_donation unavailable "
                              "(guard=%s, donation already on=%s, "
                              "backend aliasing safe=%s)", guard,
                              self._donation_on(), _donation_safe())
                    continue
                saved = est["score_double_buffer"]
                detail = ("re-enabling in-place score-buffer donation "
                          "(the [num_class, N] cache updates in place "
                          "instead of double-allocating)")
            elif step == "hist_cache":
                if self._degrade_leaf_cache_off \
                        or cfg.serial_grow == "fused" \
                        or est["histogram_cache"] <= 0:
                    continue
                saved = est["histogram_cache"]
                detail = ("dropping the [L, F, 9, B] per-leaf histogram "
                          "cache — children recompute instead of "
                          "sibling-subtraction (slower, never wrong)")
            elif step == "row_pad":
                if self._padded_rows <= self.num_data:
                    continue
                pad = self._padded_rows - self.num_data
                saved = est["total"] - self._estimate_probe_rows(
                    cfg, train_set, guard)["total"]
                detail = (f"capping the row-bucket pad ({pad} pad rows "
                          f"released; this run compiles per-N programs "
                          f"instead of sharing the bucket ladder)")
            else:  # pragma: no cover - DEGRADE_STEPS is closed
                continue
            self._apply_degrade(step, max(int(saved), 0), detail)
            est = self._estimate_now(cfg, train_set, guard)
        return est

    def _estimate_probe_rows(self, cfg: Config, train_set: BinnedDataset,
                             guard: bool) -> Dict[str, int]:
        """The estimate as it WOULD look with the pad capped (savings
        math for the ``row_pad`` step, without mutating state yet)."""
        fused = cfg.serial_grow == "fused"
        return estimate_train_memory(
            self.num_data, train_set.num_columns, cfg.num_leaves,
            cfg.max_bin, self.num_class,
            bin_itemsize=train_set.bins.dtype.itemsize,
            donate_score=not guard and self._donation_on(),
            fused_scratch=fused,
            leaf_cache=not fused and not self._degrade_leaf_cache_off,
            linear_k=(self._linear.max_features
                      if self._linear is not None else 0))

    @staticmethod
    def _make_grow_params(cfg: Config) -> GrowParams:
        # bagging / GOSS produce zero-weight rows every round: compact
        # them out of the leaf-ordered layout so tree cost tracks the
        # subsample (gbdt.cpp:271-278's bag-subset dataset switch).
        # GOSS qualifies only when it can actually sample (top+other < 1);
        # its 1/learning_rate warmup rounds still pay the compaction sort
        # on an all-active mask — accepted, the steady state dominates.
        goss_samples = (cfg.boosting_type == "goss"
                        and (cfg.top_rate + cfg.other_rate) < 1.0)
        subsampled = (goss_samples
                      or (cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0))
        return GrowParams(
            num_leaves=cfg.num_leaves, max_bin=cfg.max_bin,
            min_data_in_leaf=cfg.min_data_in_leaf,
            min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
            lambda_l1=cfg.lambda_l1, lambda_l2=cfg.lambda_l2,
            min_gain_to_split=cfg.min_gain_to_split,
            max_depth=cfg.max_depth,
            compact_inactive=subsampled)

    @staticmethod
    def _make_metrics(cfg: Config, dataset: BinnedDataset) -> List[Metric]:
        out = []
        for name in cfg.metric:
            m = create_metric(name, cfg)
            if m is not None:
                m.init(dataset.metadata, dataset.num_data)
                out.append(m)
        return out

    def _make_grow_fn(self):
        """Pick the tree learner (TreeLearner::CreateTreeLearner,
        tree_learner.cpp:1-26): serial, or a distributed learner over a
        device mesh when tree_learner != serial and >1 device is present.
        num_machines bounds the mesh size (it is the reference's machine
        count; here it is a device count)."""
        cfg = self.config
        self._comm_traffic = None           # serial: no collectives
        self._comm_traffic_totals = (0, 0)
        self._parallel_grow_active = False
        if getattr(cfg, "is_parallel", False):
            ndev = len(jax.devices())
            # single-controller-per-host: num_machines counts HOSTS (the
            # reference's machine list, wired up by parallel/multihost.py);
            # under a multi-process runtime the mesh spans every global
            # device.  In one process it bounds the local mesh instead
            # (the virtual-device test rigs).
            k = ndev if jax.process_count() > 1 \
                else min(cfg.num_machines, ndev)
            if k > 1:
                from jax.sharding import Mesh
                from ..parallel import make_parallel_grow
                mesh = Mesh(np.array(jax.devices()[:k]), ("data",))
                log.info("Using %s-parallel tree learner over %d devices",
                         cfg.tree_learner, k)
                fn = make_parallel_grow(mesh, cfg.tree_learner,
                                        self.grow_params, top_k=cfg.top_k)
                # static per-tree collective account (obs layer): computed
                # once from shapes, accumulated per iteration.  Under EFB
                # data-parallel reduces COLUMN-shaped histograms (and is
                # forced to the full psum — mirrored by bundled=True);
                # voting/feature ship per-ORIGINAL-feature payloads.
                from ..parallel.comm import traffic_totals
                traffic_f = (self.num_columns if cfg.tree_learner == "data"
                             else self.num_features)
                self._comm_traffic = fn.traffic_per_tree(
                    traffic_f, bundled=self._bundle is not None)
                self._comm_traffic_totals = traffic_totals(self._comm_traffic)
                self._parallel_grow_active = True
                if jax.process_count() > 1:
                    # multi-controller runtime: promote per-process inputs
                    # to global arrays / gather sharded outputs back
                    # (bundling is disabled under multihost loading, so
                    # the wrapped signature never carries a bundle)
                    from ..parallel.multihost import globalize_grow_fn
                    fn = globalize_grow_fn(fn, mesh)
                    return (lambda view, nb, ic, fm, g, h, w, lr:
                            fn(view.bins, nb, ic, fm, g, h, w, lr))
                if self._bundle is None:
                    return (lambda view, nb, ic, fm, g, h, w, lr:
                            fn(view.bins, nb, ic, fm, g, h, w, lr))
                return (lambda view, nb, ic, fm, g, h, w, lr:
                        fn(view.bins, nb, ic, fm, g, h, w, lr,
                           bundle=view.bundle))
            log.warning("tree_learner=%s requested but only %d device(s) "
                        "available; falling back to serial",
                        cfg.tree_learner, ndev)
        params = self.grow_params
        kind = self._serial_grow_kind()
        if kind == "ordered":
            # leaf-ordered physical layout: partition cost ~ parent
            # segment, no gathers (ops/ordered_grow.py; exact-parity
            # tested against the unordered cached learner).  Its i32 lane
            # packing is uint8-only; >256-bin datasets use the cached
            # learner (logged so the throughput change is visible).
            return (lambda view, nb, ic, fm, g, h, w, lr:
                    grow_tree_ordered(view.bins, nb, ic, fm, g, h, w, lr,
                                      params, bins_rm=view.bins_rm,
                                      bins_words=view.bins_words))
        if kind == "fused":
            # full-pass growth through the fused histogram->split-gain
            # kernel (ops/pallas_histogram.py): both children's
            # per-feature BestSplit candidates come straight out of the
            # histogram pass — the [2, F, B, 3] tensor never lands in HBM
            comm = SerialComm(leaf_cache=False, fused_gain=True)
            return (lambda view, nb, ic, fm, g, h, w, lr:
                    grow_tree(view.bins, nb, ic, fm, g, h, w, lr, params,
                              comm, view.bins_rm, bundle=view.bundle))
        if kind == "nocache":
            # hist_cache degrade step (memory_policy=degrade): full-pass
            # growth without the resident per-leaf histogram cache
            comm = SerialComm(leaf_cache=False)
            return (lambda view, nb, ic, fm, g, h, w, lr:
                    grow_tree(view.bins, nb, ic, fm, g, h, w, lr, params,
                              comm, view.bins_rm, bundle=view.bundle))
        if cfg.serial_grow == "ordered" and self._bundle is None \
                and self._screener is None:
            log.info("max_bin > 256: using the cached (original-order) "
                     "serial learner; the leaf-ordered fast path is "
                     "uint8-only")
        return (lambda view, nb, ic, fm, g, h, w, lr:
                grow_tree(view.bins, nb, ic, fm, g, h, w, lr, params,
                          bins_rm=view.bins_rm, bundle=view.bundle))

    def reset_config(self, config: Config) -> None:
        """Booster::ResetConfig (c_api.cpp:96-134): re-derive learner
        parameters and metrics against the existing training data (used by
        the reset_parameter callback, e.g. learning-rate schedules)."""
        old_cfg, self.config = getattr(self, "config", None), config
        if not hasattr(self, "train_set"):
            return
        # The pending iteration was packed under the OLD grow_params; it must
        # be unpacked with them before num_leaves can change.
        self._flush_pending()
        self.shrinkage_rate = config.learning_rate
        # feature_screen_* changes (reset_parameter callback) rebuild the
        # screener — ONLY on a real change, so per-round learning-rate
        # schedules don't wipe the gain EWMA every iteration
        def _screen_key(cfg):
            return tuple(float(getattr(cfg, k, 0) or 0) for k in
                         ("feature_screen_ratio", "feature_screen_refresh",
                          "feature_screen_warmup", "feature_screen_decay"))
        if old_cfg is not None and _screen_key(old_cfg) != _screen_key(config):
            self._setup_screening(config)
            self._grow_fn = self._make_grow_fn()
            self._full_view = self._make_full_view()
            self._train_step = None
        new_params = self._make_grow_params(config)
        if new_params != self.grow_params or (
                old_cfg is not None
                and old_cfg.tree_learner != config.tree_learner):
            # Rebuild only when the jitted growth program actually changes:
            # a fresh closure would force an XLA recompile every iteration
            # under reset_parameter schedules (learning_rate is a runtime
            # argument, not part of the compiled program).
            self.grow_params = new_params
            self._grow_fn = self._make_grow_fn()
            self._train_step = None
        self.train_metrics = self._make_metrics(config, self.train_set)
        for vi, dd in enumerate(self.valid_data):
            self.valid_metrics[vi] = self._make_metrics(config, dd.dataset)

    def reset_training_data(self, train_set: BinnedDataset) -> None:
        """GBDT::ResetTrainingData (gbdt.cpp:101-167 via c_api.cpp:70-97):
        swap the training dataset (mapper-aligned), re-init objective and
        training metrics against it, and replay the existing models into a
        fresh score buffer."""
        self._flush_pending()
        old = getattr(self, "train_set", None)
        if old is not None and not _mappers_aligned(old, train_set):
            # Dataset::CheckAlign (gbdt.cpp ResetTrainingData): bin-space
            # tree state is only meaningful against identical mappers
            log.fatal("Cannot reset training data, since new training data "
                      "has different bin mappers")
        cfg = self.config
        self.train_set = train_set
        # the fingerprint follows the data: a delta-trained model ships
        # the FRESH data's fingerprint (train_delta compares it against
        # the base model's before the swap)
        new_fp = getattr(train_set, "data_fingerprint", None)
        if new_fp is not None:
            self.data_fingerprint = new_fp
        self.num_data = train_set.num_data
        self.objective.init(train_set.metadata, train_set.num_data)
        self.num_bin = jnp.asarray(train_set.num_bin_per_feature())
        self.is_cat = jnp.asarray(train_set.is_categorical_per_feature())
        self.num_columns = train_set.num_columns
        self._setup_bundle(train_set, cfg)
        self._padded_rows = (compile_cache.bucket_rows(self.num_data)
                             if self._row_buckets_enabled(cfg)
                             and not self.objective.uses_legacy_gradients()
                             else self.num_data)
        # re-run the HBM admission gate against the NEW dataset: the
        # recomputed pad would otherwise silently undo a row_pad degrade
        # step, and a larger reset dataset must be refused/degraded here
        # — not hours later in an opaque XLA RESOURCE_EXHAUSTED.  The
        # valid-set accounting survives the gate's reset (valid sets
        # are not touched by a training-data swap).
        valid_bytes = getattr(self, "_valid_mem_bytes", 0)
        self._linear = self._setup_linear(cfg, train_set)
        self._check_memory_budget(cfg, train_set)
        self._valid_mem_bytes = valid_bytes
        self.train_data = _DeviceData(train_set, self.num_class,
                                      with_row_major=True,
                                      padded_rows=self._padded_rows,
                                      with_raw=self._linear is not None)
        self.train_metrics = self._make_metrics(cfg, train_set)
        self._init_row_state()
        self._full_feat_mask = jnp.ones(self.num_features, bool)
        self._full_feat_masks = jnp.ones((self.num_class, self.num_features),
                                         bool)
        # rebind the SHARED gradients program to this dataset's arrays
        # (no retrace unless the shapes changed — the labels are runtime
        # arguments now, not compile-time constants)
        self._grad_arrays = self.objective.gradient_arrays(self._padded_rows)
        self._grad_fn = self._make_grad_fn()
        self._setup_screening(cfg)
        self._grow_fn = self._make_grow_fn()
        self._full_view = self._make_full_view()
        self._train_step = None
        for i, tree in enumerate(self._models):
            self._add_host_tree_to(self.train_data, tree, i % self.num_class)

    def add_valid_dataset(self, valid_set: BinnedDataset) -> None:
        """GBDT::AddValidDataset (gbdt.cpp:169-199)."""
        if not _mappers_aligned(self.train_set, valid_set):
            # Dataset::CheckAlign: bin-space replay/scoring is only
            # meaningful when the valid set shares the training mappers
            # (create it with reference=train / LGBM_DatasetCreateFromX
            # with the train handle as reference)
            log.fatal("Cannot add validation data, since it has different "
                      "bin mappers with training data")
        # Re-run the fail-fast memory budget with this valid set counted:
        # the late-attach path is exactly where the original construction
        # check cannot see the allocation coming and training would die
        # in an XLA OOM after hours of work.
        est = estimate_valid_memory(
            valid_set.num_data, valid_set.num_columns, self.num_class,
            bin_itemsize=valid_set.bins.dtype.itemsize)
        valid_bytes = getattr(self, "_valid_mem_bytes", 0) + int(est["total"])
        total = getattr(self, "_train_mem_est", 0) + valid_bytes
        obs.set_gauge("hbm_total_estimate_bytes", int(total))
        limit = _device_memory_limit()
        if limit and total > limit:
            log.fatal(
                "attaching this validation set (%d rows: bins=%.0fMB, "
                "scores=%.0fMB) brings the estimated device footprint to "
                "%.0fMB, over the budget %.0fMB (training state %.0fMB + "
                "valid sets %.0fMB).  Evaluate on fewer/smaller valid "
                "sets, or shrink the training state (num_leaves/max_bin).",
                valid_set.num_data, est["bins_device"] / (1 << 20),
                est["scores"] / (1 << 20), total / (1 << 20),
                limit / (1 << 20),
                getattr(self, "_train_mem_est", 0) / (1 << 20),
                valid_bytes / (1 << 20))
        self._valid_mem_bytes = valid_bytes
        if self._linear is not None and valid_set.raw is None:
            log.fatal("linear_tree validation scoring needs the valid "
                      "set's raw feature values (the per-leaf affine "
                      "epilogue reads them); create the valid set with "
                      "reference=train from an in-memory matrix")
        dd = _DeviceData(valid_set, self.num_class,
                         padded_rows=(
                             compile_cache.bucket_rows(valid_set.num_data)
                             if self._row_buckets_enabled(self.config)
                             else valid_set.num_data),
                         with_raw=self._linear is not None)
        # replay existing trees (continued training)
        for i, tree in enumerate(self.models):
            cls = i % self.num_class
            self._add_host_tree_to(dd, tree, cls)
        self.valid_data.append(dd)
        metrics = []
        for name in self.config.metric:
            m = create_metric(name, self.config)
            if m is not None:
                m.init(valid_set.metadata, valid_set.num_data)
                metrics.append(m)
        self.valid_metrics.append(metrics)

    # ------------------------------------------------------------------
    def _bagging_mask(self, iter_: int) -> jax.Array:
        """Bagging (gbdt.cpp:201-280): pick bagging_fraction*N rows without
        replacement every bagging_freq iterations.

        The draw runs ON DEVICE (uniforms + order-statistic threshold):
        a host-side np.random.choice without replacement at 1M rows costs
        tens of ms plus a 4 MB upload EVERY round at bagging_freq=1 —
        more than the tree it was supposed to shrink."""
        cfg = self.config
        if cfg.bagging_freq <= 0 or cfg.bagging_fraction >= 1.0:
            self._bag_cnt = self.num_data
            return self._ones_weight
        if iter_ % cfg.bagging_freq == 0:
            bag_cnt = int(cfg.bagging_fraction * self.num_data)
            self._bag_key, sub = jax.random.split(self._bag_key)
            self._row_weight = _device_bag_mask(sub, self._padded_rows,
                                                bag_cnt, self.num_data)
            self._bag_cnt = bag_cnt
            obs.inc("bagging_draws")
        return self._row_weight

    def _feature_mask(self) -> jax.Array:
        """feature_fraction sampling per tree (serial_tree_learner.cpp:226+)
        intersected with this round's gain-screening mask (EMA-FS,
        models/screening.py) when one is active."""
        frac = self.config.feature_fraction
        screen = self._screen_mask_dev
        if frac >= 1.0:
            return (self._full_feat_mask if screen is None
                    else self._full_feat_mask & screen)
        used = max(1, int(self.num_features * frac))
        idx = self._feature_rng.choice(self.num_features, used, replace=False)
        mask = np.zeros(self.num_features, bool)
        mask[idx] = True
        out = jnp.asarray(mask)
        return out if screen is None else out & screen

    def _feature_masks_all(self) -> jax.Array:
        """[num_class, F] per-class feature masks for the fused step (same
        RNG draw order as per-class _feature_mask calls)."""
        frac = self.config.feature_fraction
        if frac >= 1.0:
            screen = self._screen_mask_dev
            return (self._full_feat_masks if screen is None
                    else self._full_feat_masks & screen[None, :])
        return jnp.stack([self._feature_mask()
                          for _ in range(self.num_class)])

    # -- gain-informed screening views (docs/SPARSE.md) ----------------
    def _select_view(self) -> "_HistView":
        """Pick this round's histogram view and screening mask.

        Warmup and refresh rounds run the FULL view with every feature
        unmasked; screened rounds run the compacted active-column view
        (when available) under the EWMA-derived mask.  Both views and
        the masks are runtime arguments to the shared programs, so
        toggling costs zero recompiles after each view's first trace
        (ledger-pinned in tests/test_screening.py)."""
        scr = self._screener
        if scr is None:
            return self._full_view
        it = self.iter_ - self.num_init_iteration
        mode = scr.round_mode(it)
        if mode != "screened":
            self._screen_mask_dev = None
            self._screen_mask_np = None
            obs.set_gauge("screen_active_features", self.num_features)
            if mode == "refresh":
                obs.inc("screen_refresh_total")
                scr.refresh_total += 1
            return self._full_view
        period = scr.period(it)
        if period != self._screen_period:
            self._screen_period = period
            cols = scr.active_columns()
            self._screen_mask_np = scr.screen_mask(cols)
            self._screen_mask_dev = jnp.asarray(self._screen_mask_np)
            self._active_view = self._build_active_view(cols)
        obs.set_gauge("screen_active_features",
                      int(self._screen_mask_np.sum()))
        return (self._active_view if self._active_view is not None
                else self._full_view)

    def _screen_decode_base(self) -> BundleDecode:
        """Decode tables the compacted view derives from: the EFB tables
        when the dataset is bundled, else identity tables (a trivial
        all-singleton plan)."""
        if self._bundle is not None:
            return self._bundle
        if self._identity_decode is None:
            plan = BundlePlan([[f] for f in range(self.num_features)],
                              [[0]] * self.num_features, self.num_features)
            dn = plan.decode_arrays(
                [m.num_bin for m in self.train_set.mappers],
                [m.default_bin for m in self.train_set.mappers],
                self.config.max_bin)
            self._identity_decode = BundleDecode(
                col=jnp.asarray(dn["col"]), off=jnp.asarray(dn["off"]),
                width=jnp.asarray(dn["width"]),
                slot_map=jnp.asarray(dn["slot_map"]),
                default_bin=jnp.asarray(dn["default_bin"]))
        return self._identity_decode

    def _build_active_view(self, cols: np.ndarray) -> Optional["_HistView"]:
        """Gather the active columns into a fixed-budget [C_pad, N]
        block (one device gather per refresh period).  C_pad is the
        compile-cache bucket of the CONSTANT keep_cols budget, so every
        screened round of the run shares one compiled program.  Returns
        None (mask-only screening) under the distributed learners or
        when compaction would not shrink the pass."""
        if self._parallel_grow_active:
            return None
        try:
            if jax.process_count() > 1:
                return None
        except Exception:  # pragma: no cover - uninitialized backend
            pass
        c_pad = compile_cache.bucket_rows(len(cols))
        if c_pad >= self.num_columns:
            return None
        idx = np.full(c_pad, 1 << 30, np.int64)
        idx[:len(cols)] = cols
        idx_dev = jnp.asarray(idx)
        td = self.train_data
        bins_act = jnp.take(td.bins, idx_dev, axis=0,
                            mode="fill", fill_value=0)
        bins_rm_act = (jnp.take(td.bins_rm, idx_dev, axis=1,
                                mode="fill", fill_value=0)
                       if td.bins_rm is not None else None)
        base = self._screen_decode_base()
        pos = np.zeros(self.num_features, np.int32)
        pos_of = {int(c): i for i, c in enumerate(cols)}
        for f in range(self.num_features):
            # dropped features point at column 0; they are masked out of
            # the scan, so the junk expansion is never consulted
            pos[f] = pos_of.get(int(self._bundle_col_np[f]), 0)
        bundle_act = base._replace(col=jnp.asarray(pos))
        obs.inc("screen_compactions_total")
        return _HistView(bins=bins_act, bins_rm=bins_rm_act,
                         bins_words=None, bundle=bundle_act)

    # ------------------------------------------------------------------
    def _gradients(self) -> Tuple[jax.Array, jax.Array]:
        return self._grad_fn(self.train_data.score)

    def _transform_host_gradients(self, grad, hess):
        """Hook for subclasses that post-process gradients regardless of
        their source (GOSS sampling/amplification); identity here."""
        return grad, hess

    def _make_train_step(self):
        """One fused jit for a full boosting iteration on the standard
        (non-fobj) path: gradients -> per-class grow -> score update ->
        packed host transfer vectors.  A single device dispatch per
        iteration instead of ~5: each dispatch over the remote axon link
        costs ~1-5 ms of submit latency, which at >10 iters/sec is a
        first-order cost (docs/BENCH_NOTES_r03.md).

        Serial growth binds the process-wide SHARED train_step program
        (every per-dataset array is an argument), so a rebuilt booster —
        snapshot resume, a second run in the same process — reuses the
        compiled program: zero new train_step compiles in the ledger.
        The score argument is DONATED when nan_policy is off and the
        backend is an accelerator (_donation_enabled), so XLA updates
        the [num_class, N] cache in place instead of double-allocating
        it every round."""
        # NaN/Inf containment: the grad/hess finiteness reduction runs
        # INSIDE the fused jit (the gradients never visit the host), so
        # the guarded path pays one extra scalar in the transfer — the
        # ungated path compiles the check away entirely.
        guard = self._nan_policy != "none"
        if self._parallel_grow_active:
            return self._make_train_step_local(guard)
        jit = _shared_train_step(self.objective, self.num_class, guard,
                                 self._serial_grow_kind(), self.grow_params,
                                 donate=not guard and self._donation_on(),
                                 linear=self._linear)
        num_bin, is_cat = self.num_bin, self.is_cat
        grad_arrays = self._grad_arrays
        raw = self.train_data.raw if self._linear is not None else None

        def step(score, feat_masks, row_weight, lr, view):
            return jit(score, feat_masks, row_weight, lr, view.bins,
                       num_bin, is_cat, grad_arrays, view.bins_rm,
                       view.bins_words, view.bundle, raw)
        return step

    def _make_train_step_local(self, guard: bool):
        """Per-booster fused step for the distributed learners: their
        grow fn closes over a device mesh (shard_map), which the shared
        registry cannot key portably."""
        grow = self._grow_fn
        obj_grad = self._grad_fn
        num_bin, is_cat = self.num_bin, self.is_cat
        num_class = self.num_class

        @obs.instrumented_jit(program="train_step")
        def step_fn(score, feat_masks, row_weight, lr, view):
            grad, hess = obj_grad(score)
            ok = (_all_finite(grad, hess) if guard else jnp.asarray(True))
            outs = []
            for cls in range(num_class):
                ta, _, delta = grow(view, num_bin, is_cat, feat_masks[cls],
                                    grad[cls], hess[cls], row_weight, lr)
                score = score.at[cls].add(delta)
                outs.append((pack_tree_arrays(ta), ta, delta))
            return score, outs, ok
        return step_fn

    # -- pipelined host materialization --------------------------------
    @property
    def models(self) -> List[Tree]:
        """Host trees, class-major rows.  Flushes the pending iteration so
        external readers (save/predict/DART/R bindings) always see the
        synchronous view."""
        self._flush_pending()
        return self._models

    @models.setter
    def models(self, value: List[Tree]) -> None:
        self._flush_pending()
        self._models = value

    def _flush_pending(self) -> None:
        """Materialize the pending iteration's trees.  The 13 TreeArrays
        fields travel as TWO packed vectors per class (device->host
        round-trips are ~10ms each over a remote device link).  Detects
        reference-style saturation (GBDT::TrainOneIter, gbdt.cpp:362-378):
        an iteration where no class could split is popped and marks
        training stopped."""
        pend = self._pending_iter
        if not pend:
            return
        self._pending_iter = None
        pend_idx, self._pending_iter_idx = self._pending_iter_idx, -1
        with timetag.scope("GBDT::host_tree"):
            host = jax.device_get([packed for packed, _, _ in pend])
        obs.devprof.transfer(
            "d2h", "host_tree",
            sum(int(a.nbytes) for vecs in host for a in vecs))
        L = self.grow_params.num_leaves
        lin = self._linear
        trees = []
        for vecs in host:
            tree = Tree.from_arrays(
                unpack_tree_arrays(vecs[0], vecs[1], L),
                self.train_set.mappers,
                self.train_set.used_feature_map,
                self._pending_shrinkage)
            if len(vecs) > 2 and lin is not None:
                # linear transport rides the SAME device_get: two more
                # packed vectors per class (models/linear.py)
                coeff, feat, fb = unpack_linear(vecs[2], vecs[3], L,
                                                lin.max_features)
                attach_linear(tree, coeff, feat,
                              self.train_set.used_feature_map)
                if fb:
                    obs.inc("linear_fallback_total", fb)
            trees.append(tree)
        if self._screener is not None:
            # realized split gains feed the EMA-FS feature EWMA
            # (models/screening.py); 1-leaf saturated trees contribute
            # nothing, so observing before the saturation check is safe
            self._screener.observe_trees(trees)
        rec = self._telemetry
        shapes = ([{"num_leaves": int(t.num_leaves),
                    "max_depth": int(t.max_depth())} for t in trees]
                  if rec is not None and pend_idx >= 0 else None)
        if all(t.num_leaves <= 1 for t in trees):
            log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements.")
            self._no_more_splits = True
            obs.inc("saturated_iterations")
            self.iter_ -= 1
            if shapes is not None:
                rec.note(pend_idx, saturated=True, trees=shapes)
        else:
            self._models.extend(trees)
            obs.inc("trees_grown", len(trees))
            if shapes is not None:
                rec.note(pend_idx, trees=shapes)

    # -- telemetry (lightgbm_tpu/obs/) ---------------------------------
    def set_event_recorder(self, recorder) -> None:
        """Attach an ``obs.EventRecorder``: one JSONL record per boosting
        iteration (phase wall times, bag count, grown-tree shape,
        cumulative collective bytes; eval values arrive via
        ``callback.log_telemetry``).  ``None`` detaches."""
        self._telemetry = recorder

    def _note_iter_event(self, it: int, t0: float, tt0, *,
                         discarded: bool = False) -> None:
        """Per-iteration telemetry epilogue: close the trace window and
        note this iteration's host-side fields.  ``tt0`` is the timetag
        accumulator baseline captured at iteration start (None when the
        serializing TIMETAG mode is off — then only the honest async wall
        time is recorded)."""
        if self._trace is not None:
            self._trace.iter_end(it, sync=self.train_data.score)
        rec = self._telemetry
        if rec is None:
            return
        phases = {}
        if tt0 is not None:
            now = timetag.get_timings()
            phases = {k: round(v - tt0.get(k, 0.0), 6)
                      for k, v in now.items() if v > tt0.get(k, 0.0)}
        rec.note(it, wall_s=round(time.perf_counter() - t0, 6),
                 phases=phases, bag_cnt=int(self._bag_cnt),
                 comm_bytes_cum=int(self._cum_comm_bytes),
                 comm_calls_cum=int(self._cum_comm_calls))
        if discarded:
            # dispatched but undone (the previous iteration saturated);
            # the reference would never have trained it
            rec.note(it, discarded=True, trees=[])

    def close_trace(self) -> None:
        """Stop a trace window the training loop ended inside (otherwise
        it would keep recording unrelated work until process exit)."""
        if self._trace is not None:
            self._trace.close()

    def train_one_iter(self, grad=None, hess=None) -> bool:
        """One boosting round (gbdt.cpp:295-382).  Returns True when
        training should stop (no more splits possible on every class).
        The whole round is timed by an ``obs.span``: one observe into the
        ``phase_seconds_gbdt_iteration`` wall-time histogram per call —
        host bookkeeping only, the async device pipeline is never synced
        by it (docs/OBSERVABILITY.md)."""
        # desync check runs at round ENTRY, before this round's gradients
        # consume the (possibly diverged) state: for the built-in
        # objectives (gradients computed inside the impl) a resync here
        # restores the clean trajectory BEFORE a poisoned rank's
        # gradients can leak into the round's cross-process histogram
        # sums.  Custom-fobj gradients arrive precomputed from upstream
        # — one post-resync round still trains on them (consistent
        # pod-wide, flagged below; the next round is clean).
        resynced = self._maybe_check_consistency()
        if resynced and grad is not None:
            log.warning(
                "desync resync at iteration %d arrived after this "
                "round's custom-objective gradients were computed from "
                "the pre-resync scores; the pod stays consistent but "
                "this one round ingests the stale gradients", self.iter_)
        # round_scope splits the span's wall time into host vs device
        # shares from the device-seconds estimate accumulated inside it
        # (no-op unless devprof is on — the span itself never syncs)
        with obs.devprof.round_scope(), obs.span("GBDT::iteration"):
            return self._train_one_iter_impl(grad, hess)

    # -- distributed desync detection ----------------------------------
    def _maybe_check_consistency(self) -> bool:
        """Every ``distributed_consistency_check`` rounds under a
        multi-process runtime, verify the replication invariant the
        module header of parallel/multihost.py only states in prose:
        every rank holds identical trees, score caches and RNG streams.
        Single-process (or K=0): returns before touching jax — no new
        collectives, no new compiles.  Returns True when a resync
        restored state on any rank."""
        K = self._consistency_every
        if K <= 0:
            return False
        from ..parallel.multihost import process_rank_world
        rank, world = process_rank_world()
        if world <= 1:
            return False
        it = self.iter_ - self.num_init_iteration
        if it <= 0 or it % K != 0:
            return False
        # same guard as Comm::grow: a rank dying during THIS allgather
        # must become a bounded named abort, not a silent hang
        from ..parallel.watchdog import active_watchdog
        wd = active_watchdog()
        with obs.span("Dist::consistency"):
            if wd is not None:
                with wd.guard("Dist::consistency"):
                    return self._check_distributed_consistency(rank, world)
            return self._check_distributed_consistency(rank, world)

    def _consistency_digests(self) -> Dict[str, int]:
        """Cheap per-field uint64 digests of the replicated training
        state (flushes the pipelined iteration first so every rank
        digests the synchronous view).  Field granularity is what makes
        the divergence diagnostic name WHAT desynced, not just that
        something did."""
        import hashlib
        import pickle

        self._flush_pending()

        def d(blob: bytes) -> int:
            return int.from_bytes(hashlib.sha256(blob).digest()[:8],
                                  "little")

        return {
            "iter": d(np.int64([self.iter_, len(self._models)]).tobytes()),
            "trees": d(pickle.dumps(self._models,
                                    protocol=pickle.HIGHEST_PROTOCOL)),
            "score": d(self.train_data.host_score(np.float32).tobytes()),
            "rng": d(np.asarray(self._bag_key).tobytes()
                     + pickle.dumps(self._feature_rng.get_state())
                     + np.asarray(self._row_weight).tobytes()),
        }

    def _check_distributed_consistency(self, rank: int,
                                       world: int) -> bool:
        """Allgather the per-field digests, compare, and apply
        ``desync_policy`` on divergence: ``fail_fast`` dies with a
        diagnostic naming the diverged rank(s) and field(s) (the same
        allgather runs on every rank, so the whole pod stops together);
        ``resync`` broadcasts rank 0's full snapshot state and restores
        it on the divergent ranks, then training continues (returns
        True)."""
        from ..parallel.comm import allgather_host_array, \
            broadcast_host_bytes
        fields = self._consistency_digests()
        names = list(fields)
        mine = np.array([fields[n] for n in names], np.uint64)
        gathered = np.asarray(allgather_host_array(mine))  # [world, F]
        if bool((gathered == gathered[0]).all()):
            return False
        obs.inc("desync_detected_total")
        diverged: Dict[str, List[int]] = {}
        for fi, name in enumerate(names):
            col = gathered[:, fi]
            vals, counts = np.unique(col, return_counts=True)
            top = int(counts.max())
            majority = {int(v) for v, c in zip(vals, counts)
                        if int(c) == top}
            # majority wins; ties (e.g. any 2-process pod) defer to
            # rank 0, consistent with resync trusting rank 0's state
            ref = (int(col[0]) if int(col[0]) in majority
                   else next(iter(sorted(majority))))
            bad = [r for r in range(world) if int(col[r]) != ref]
            if bad:
                diverged[name] = bad
        detail = "; ".join(
            f"field {name!r} diverged on rank(s) {bad}"
            for name, bad in diverged.items())
        if self._desync_policy == "fail_fast":
            log.fatal(
                "distributed state desync detected at iteration %d "
                "(%d-process run): %s.  Every rank must hold identical "
                "replicated training state; set desync_policy=resync to "
                "broadcast rank 0's state instead of stopping, and see "
                "docs/FAULT_TOLERANCE.md §Distributed.",
                self.iter_, world, detail)
        if any(0 in bad for bad in diverged.values()):
            # resync trusts rank 0; the majority just voted rank 0 THE
            # diverged one (only possible at world >= 3 — 2-rank ties
            # defer to rank 0).  Broadcasting its state would propagate
            # the corruption pod-wide while logging "healed": refuse.
            log.fatal(
                "distributed state desync detected at iteration %d: %s — "
                "rank 0 is the resync source of truth but is itself the "
                "diverged rank; refusing to propagate its state "
                "(desync_policy=resync falls back to failing fast here).",
                self.iter_, detail)
        import pickle
        log.warning("distributed state desync detected at iteration %d: "
                    "%s — resyncing every rank from rank 0's state",
                    self.iter_, detail)
        payload = (pickle.dumps(self.snapshot_state(),
                                protocol=pickle.HIGHEST_PROTOCOL)
                   if rank == 0 else None)
        blob = broadcast_host_bytes(payload, is_source=(rank == 0))
        if rank != 0:
            self.restore_state(pickle.loads(blob))
        obs.inc("desync_resyncs_total")
        return True

    def _train_one_iter_impl(self, grad=None, hess=None) -> bool:
        """Body of one boosting round.

        With ``_pipeline`` the saturation signal arrives one call later than
        the reference's (the saturated iteration is detected when the NEXT
        call flushes it, AFTER that call has dispatched its own device work
        — the dispatch must come first so the host transfer overlaps device
        growth).  The resulting model and scores are identical: a saturated
        iteration's trees are 1-leaf with value 0 (_GrowState.cur_value is
        only written on splits), so their score deltas are exactly zero; the
        trees are popped like GBDT::TrainOneIter's pop (gbdt.cpp:362-378),
        and the extra dispatched iteration is discarded with its (possibly
        nonzero, under bagging) deltas subtracted back out.  The only
        observable deviation from the reference is one extra eval/callback
        round for the popped iteration, with metrics unchanged from the
        round before.  The flag is cleared on detection so an explicit retry
        re-attempts growth, as the reference would."""
        if self._no_more_splits:
            # saturation detected by an out-of-band flush (models getter,
            # reset_config, rollback): deliver the stop signal without
            # dispatching — and clear it so a later retry trains afresh
            self._no_more_splits = False
            return True
        # -- telemetry (obs layer): iteration index, wall clock, optional
        # timetag baseline for per-phase deltas, trace window entry.  All
        # gated so the disabled path costs two attribute reads.
        it = self.iter_
        rec = self._telemetry
        t_iter0 = time.perf_counter() if rec is not None else 0.0
        tt0 = (timetag.get_timings()
               if rec is not None and timetag.ENABLED else None)
        if self._trace is not None:
            self._trace.iter_begin(it)
        # The fused step computes gradients INSIDE the jit and never calls
        # the _gradients / _transform_host_gradients hooks, so it only
        # applies when this instance uses the base implementations of ALL
        # per-round hooks (GOSS sampling/amplification and custom boosters
        # override them and need the per-stage path; _bagging_mask is
        # checked too, conservatively, so any hook override routes through
        # the path that visibly runs every hook).  LGBT_NO_FUSED_STEP=1/
        # true also forces per-stage (same results; smaller XLA programs
        # for compile-constrained setups).
        fused = (grad is None and hess is None
                 and type(self)._gradients is GBDT._gradients
                 and type(self)._transform_host_gradients
                 is GBDT._transform_host_gradients
                 and type(self)._bagging_mask is GBDT._bagging_mask
                 and jax.process_count() == 1  # multihost grow fn is a
                 # host-side bridge (globalize_grow_fn), not jit-traceable
                 and os.environ.get("LGBT_NO_FUSED_STEP", "").lower()
                 not in ("1", "true", "yes"))
        if self._lr_cache[0] != self.shrinkage_rate:
            self._lr_cache = (self.shrinkage_rate,
                              jnp.float32(self.shrinkage_rate))
        lr_dev = self._lr_cache[1]
        # NaN/Inf containment (nan_policy != "none"): keep handles to the
        # pre-iteration score arrays — device arrays are immutable, so a
        # poisoned iteration rolls back by reassignment, no arithmetic
        # undo (which NaN would defeat: x + NaN - NaN != x).
        guard = self._nan_policy != "none"
        # one donation decision per round: rollback references and the
        # backend gate both veto in-place score updates (the
        # score_donation degrade step may re-enable an env opt-out)
        donate = not guard and self._donation_on()
        poisoned = None               # which check tripped, for diagnostics
        if guard:
            score0 = self.train_data.score
            vscores0 = [dd.score for dd in self.valid_data]
        # gain screening (models/screening.py): pick this round's
        # histogram view + feature mask BEFORE any mask draw reads it
        view = self._select_view()
        cur = []
        if fused:
            # standard objective: ONE device dispatch for the whole round
            with timetag.scope("GBDT::bagging"):
                row_weight = self._bagging_mask(self.iter_)
            if self._train_step is None:
                self._train_step = self._make_train_step()
            feat_masks = self._feature_masks_all()
            with timetag.scope("GBDT::tree") as tt:
                self.train_data.score, outs, gh_ok = self._train_step(
                    self.train_data.score, feat_masks, row_weight, lr_dev,
                    view)
                tt.sync(self.train_data.score)
            if guard:
                ok_gh, ok_sc = jax.device_get(
                    (gh_ok, _all_finite(self.train_data.score)))
                if not bool(ok_gh):
                    poisoned = "gradients/hessians"
                elif not bool(ok_sc):
                    poisoned = "scores"
            if poisoned is None:
                for cls, out in enumerate(outs):
                    # linear steps append (coeff, feat) as a 4th element
                    # (docs/LINEAR_TREES.md) — the valid replay epilogue
                    # needs them
                    packed, tree_arrays, delta = out[0], out[1], out[2]
                    lin = out[3] if len(out) > 3 else None
                    vdeltas = []
                    with timetag.scope("GBDT::valid_score") as tt:
                        for dd in self.valid_data:
                            vd = self._device_tree_delta(dd, tree_arrays,
                                                         lin)
                            dd.score = self._score_add(dd.score, vd,
                                                       cls, donate)
                            vdeltas.append(vd)
                        tt.sync(vdeltas)
                    cur.append((packed, delta, vdeltas))
        else:
            # per-stage path: custom fobj, GOSS-style _gradients hooks, or
            # LGBT_NO_FUSED_STEP.  Gradients BEFORE the bagging mask:
            # GOSS._gradients draws this round's sample and the mask read
            # must see it (gbdt.cpp Bagging-before-Boosting ordering).
            with timetag.scope("GBDT::boosting") as tt:
                if grad is None or hess is None:
                    grad, hess = self._gradients()
                else:
                    grad = jnp.asarray(grad, jnp.float32).reshape(
                        self.num_class, -1)
                    hess = jnp.asarray(hess, jnp.float32).reshape(
                        self.num_class, -1)
                    if grad.shape[1] < self._padded_rows:
                        # host fobj gradients cover the REAL rows; pad up
                        # to the shared row bucket (the pad's zero
                        # row_weight keeps it out of every tree)
                        w = ((0, 0), (0, self._padded_rows - grad.shape[1]))
                        grad, hess = jnp.pad(grad, w), jnp.pad(hess, w)
                    # GOSS-style subclasses sample/amplify host-provided
                    # gradients too (the reference Bagging step is
                    # objective-agnostic)
                    grad, hess = self._transform_host_gradients(grad, hess)
                tt.sync((grad, hess))
            if guard and not bool(_all_finite(grad, hess)):
                # caught BEFORE growing: the poisoned round skips the
                # whole tree pass, not just its bookkeeping
                poisoned = "gradients/hessians"
            with timetag.scope("GBDT::bagging"):
                row_weight = self._bagging_mask(self.iter_)
            classes = range(self.num_class) if poisoned is None else ()
            for cls in classes:
                feat_mask = self._feature_mask()
                with timetag.scope("GBDT::tree") as tt:
                    tree_arrays, leaf_id, delta = self._grow_fn(
                        view, self.num_bin, self.is_cat,
                        feat_mask, grad[cls], hess[cls], row_weight, lr_dev)
                    tt.sync(delta)
                lin = None
                if self._linear is not None:
                    # batched per-leaf affine fit (models/linear.py):
                    # intercepts replace the grown leaf values and the
                    # fitted delta replaces the grower's constant delta
                    with timetag.scope("Bin::linear_fit") as tt:
                        (tree_arrays, l_coeff, l_feat, delta,
                         l_fb) = _shared_linear_fit(self._linear)(
                            tree_arrays, view.bins, self.is_cat,
                            self.train_data.raw, grad[cls], hess[cls],
                            row_weight, lr_dev, view.bundle)
                        lin = (l_coeff, l_feat)
                        tt.sync(delta)
                with timetag.scope("GBDT::train_score") as tt:
                    self.train_data.score = self._score_add(
                        self.train_data.score, delta, cls, donate)
                    tt.sync(self.train_data.score)
                vdeltas = []
                with timetag.scope("GBDT::valid_score") as tt:
                    for dd in self.valid_data:
                        vd = self._device_tree_delta(dd, tree_arrays, lin)
                        dd.score = self._score_add(dd.score, vd, cls,
                                                   donate)
                        vdeltas.append(vd)
                    tt.sync(vdeltas)
                packed = _PACK_TREE(tree_arrays)
                if lin is not None:
                    packed = tuple(packed) + tuple(
                        _PACK_LINEAR(l_coeff, l_feat, l_fb))
                cur.append((packed, delta, vdeltas))
            if guard and poisoned is None \
                    and not bool(_all_finite(self.train_data.score)):
                # finite gradients can still yield a non-finite tree
                # (degenerate hessian sums); catch it after the update
                poisoned = "scores"
        if poisoned is not None:
            return self._contain_poisoned_iter(it, poisoned, score0,
                                               vscores0)
        self.iter_ += 1
        obs.inc("iterations")
        if self._comm_traffic_totals[1]:
            # static per-tree collective account × trees dispatched now
            calls, nbytes = self._comm_traffic_totals
            self._cum_comm_calls += calls * self.num_class
            self._cum_comm_bytes += nbytes * self.num_class
            obs.inc("comm_collective_calls", calls * self.num_class)
            obs.inc("comm_collective_bytes", nbytes * self.num_class)
            # distribution series (comm_bytes / comm_bytes_<kind>): one
            # sample per tree dispatched this round (parallel/comm.py)
            from ..parallel.comm import observe_traffic
            observe_traffic(self._comm_traffic, trees=self.num_class)
        shrink = self.shrinkage_rate
        if not self._pipeline:
            self._pending_iter = cur
            self._pending_iter_idx = it
            self._pending_shrinkage = shrink
            self._flush_pending()
            self._note_iter_event(it, t_iter0, tt0)
            if self._no_more_splits:
                self._no_more_splits = False
                return True
            return False
        # Materialize the PREVIOUS iteration while the device runs this one.
        # If it saturated, the reference would never have trained this
        # iteration: undo its score deltas and discard it.
        self._flush_pending()
        if self._no_more_splits:
            self._no_more_splits = False
            for cls, (_, delta, vds) in enumerate(cur):
                self.train_data.score = \
                    self.train_data.score.at[cls].add(-delta)
                for dd, vd in zip(self.valid_data, vds):
                    dd.score = dd.score.at[cls].add(-vd)
            self.iter_ -= 1
            self._note_iter_event(it, t_iter0, tt0, discarded=True)
            return True
        self._pending_iter = cur
        self._pending_iter_idx = it
        self._pending_shrinkage = shrink
        self._note_iter_event(it, t_iter0, tt0)
        return False

    @staticmethod
    def _score_add(score, delta, cls: int, donate: bool):
        """Per-class score update; donated (in-place for XLA) unless a
        NaN-containment rollback reference must stay alive."""
        if donate:
            return _score_add_donated(score, delta, cls)
        return score.at[cls].add(delta)

    def _contain_poisoned_iter(self, it: int, what: str, score0,
                               vscores0) -> bool:
        """NaN/Inf containment (``nan_policy``): a check tripped for
        iteration ``it``.  Roll the score caches back to their
        pre-iteration arrays, record the event, then either die with a
        real diagnostic (``fail_fast``) or drop the round and continue
        (``skip_tree``).  The dropped round's dispatched device work is
        simply discarded — nothing was committed to ``models``.  Always
        returns False (training continues) on the skip path; the next
        call re-attempts the same iteration index."""
        self.train_data.score = score0
        for dd, s0 in zip(self.valid_data, vscores0):
            dd.score = s0
        obs.inc("nan_iterations_dropped")
        rec = self._telemetry
        if rec is not None:
            rec.note(it, nan_poisoned=what, nan_policy=self._nan_policy)
        if self._trace is not None:
            self._trace.iter_end(it, sync=self.train_data.score)
        obj = getattr(getattr(self, "objective", None), "name", "?")
        if self._nan_policy == "fail_fast":
            log.fatal(
                "non-finite %s at boosting iteration %d (objective=%s).  "
                "The model up to iteration %d is intact; inspect the "
                "objective/labels (or a custom fobj), or set "
                "nan_policy=skip_tree to drop poisoned iterations and "
                "continue.", what, it, obj, it)
        self._nan_skips += 1
        log.warning("nan_policy=skip_tree: dropping boosting iteration %d "
                    "(non-finite %s, objective=%s); %d iteration(s) "
                    "dropped so far", it, what, obj, self._nan_skips)
        return False

    def rollback_one_iter(self) -> None:
        """GBDT::RollbackOneIter (gbdt.cpp:384-402)."""
        # Flush BEFORE the iter_ guard: a pending saturated iteration is
        # popped by the flush (decrementing iter_), and rolling back must
        # target the last REAL iteration.
        self._flush_pending()
        if self.iter_ <= 0:
            return
        for cls in reversed(range(self.num_class)):
            tree = self.models.pop()
            if tree.num_leaves > 1:
                neg = _negate_tree(tree)
                self._add_host_tree_to(self.train_data, neg, cls)
                for dd in self.valid_data:
                    self._add_host_tree_to(dd, neg, cls)
        self.iter_ -= 1

    # ------------------------------------------------------------------
    # Crash-safe snapshot/resume state hooks (lightgbm_tpu/snapshot.py).
    # Everything ``init_model`` continued training DISCARDS lives here:
    # score caches, RNG streams, bag state, best-iteration bookkeeping.
    # Subclasses with extra mutable state (DART drop weights, GOSS
    # sampling key) extend both hooks.

    def snapshot_state(self) -> Dict:
        """Full resumable training state, host-side.  Flushes the
        pipelined iteration first so the captured view is synchronous.
        Restoring this onto a same-config booster over the same data is
        bit-exact: scores are saved as arrays (not re-derived by tree
        replay, which would re-order float additions) and every RNG
        stream resumes mid-sequence."""
        if not hasattr(self, "train_set"):
            log.fatal("snapshot_state requires a training booster "
                      "(loaded prediction-only models have no "
                      "resumable state)")
        self._flush_pending()
        return {
            "submodel": self.submodel_name,
            "fingerprint": {
                "objective": getattr(self.objective, "name", "?"),
                "num_class": int(self.num_class),
                "num_data": int(self.num_data),
                "num_features": int(self.num_features),
                "num_leaves": int(self.grow_params.num_leaves),
            },
            "models": list(self._models),
            "iter_": int(self.iter_),
            "num_init_iteration": int(self.num_init_iteration),
            "best_iteration": int(self.best_iteration),
            "best_score": dict(self.best_score),
            "best_msg": dict(self.best_msg),
            "shrinkage_rate": float(self.shrinkage_rate),
            "no_more_splits": bool(self._no_more_splits),
            # saved at the REAL row count (row-bucket pad cropped): the
            # pad region is derived state nobody reads, and cropping
            # keeps snapshots portable across row_buckets settings
            "train_score": self.train_data.host_score(np.float32),
            "valid_scores": [dd.host_score(np.float32)
                             for dd in self.valid_data],
            "bag_key": np.asarray(self._bag_key),
            "row_weight": np.asarray(self._row_weight)[:self.num_data],
            "bag_cnt": int(self._bag_cnt),
            "feature_rng": self._feature_rng.get_state(),
            "cum_comm": (int(self._cum_comm_calls),
                         int(self._cum_comm_bytes)),
            "nan_skips": int(self._nan_skips),
            # EMA-FS screener EWMA (models/screening.py): without it a
            # resumed run would re-warm the gain estimates from zero
            "screen_state": (self._screener.state()
                             if self._screener is not None else None),
        }

    def restore_state(self, state: Dict) -> None:
        """Inverse of ``snapshot_state``, applied to a freshly built
        booster (same params, same data).  Valid sets attached before
        the restore get their saved score caches back by position; any
        extra valid set (attached on resume but absent from the
        snapshot) is brought up to date by replaying the restored
        trees."""
        if state.get("submodel") != self.submodel_name:
            log.fatal("snapshot was taken by a %r booster; this run is "
                      "configured as %r", state.get("submodel"),
                      self.submodel_name)
        fp = state.get("fingerprint", {})
        mine = {
            "objective": getattr(self.objective, "name", "?"),
            "num_class": int(self.num_class),
            "num_data": int(self.num_data),
            "num_features": int(self.num_features),
            "num_leaves": int(self.grow_params.num_leaves),
        }
        if fp and fp != mine:
            diff = {k: (fp.get(k), mine[k]) for k in mine
                    if fp.get(k) != mine[k]}
            log.fatal("snapshot/config mismatch, refusing to resume "
                      "(snapshot vs current): %s", diff)
        self._flush_pending()
        self._models = list(state["models"])
        self.iter_ = int(state["iter_"])
        self.num_init_iteration = int(state["num_init_iteration"])
        self.best_iteration = int(state["best_iteration"])
        self.best_score = dict(state["best_score"])
        self.best_msg = dict(state["best_msg"])
        self.shrinkage_rate = float(state["shrinkage_rate"])
        self._no_more_splits = bool(state["no_more_splits"])
        self.train_data.set_score(state["train_score"])
        saved_valid = state.get("valid_scores", [])
        for vi, dd in enumerate(self.valid_data):
            saved = saved_valid[vi] if vi < len(saved_valid) else None
            if saved is not None and np.shape(saved)[0] == self.num_class \
                    and np.shape(saved)[-1] in (dd.num_data,
                                                dd.padded_rows):
                dd.set_score(np.asarray(saved)[:, :dd.num_data])
            else:
                for i, tree in enumerate(self._models):
                    self._add_host_tree_to(dd, tree, i % self.num_class)
        self._bag_key = jnp.asarray(state["bag_key"], jnp.uint32)
        rw = np.zeros(self._padded_rows, np.float32)
        saved_rw = np.asarray(state["row_weight"], np.float32)
        rw[:min(len(saved_rw), self.num_data)] = saved_rw[:self.num_data]
        self._row_weight = jnp.asarray(rw)
        self._bag_cnt = int(state["bag_cnt"])
        self._feature_rng.set_state(state["feature_rng"])
        self._cum_comm_calls, self._cum_comm_bytes = \
            (int(v) for v in state["cum_comm"])
        self._nan_skips = int(state.get("nan_skips", 0))
        if self._screener is not None:
            self._screener.restore(state.get("screen_state"))
            # force the active view/mask to rebuild from restored EWMA
            self._screen_period = -1
            self._screen_mask_dev = None
            self._active_view = None

    # ------------------------------------------------------------------
    def _device_tree_delta(self, dd: _DeviceData, tree_arrays,
                           lin=None) -> jax.Array:
        delta, leaf = predict_binned_tree(
            tree_arrays.split_feature, tree_arrays.split_bin,
            self.is_cat[jnp.maximum(tree_arrays.split_feature, 0)],
            tree_arrays.left_child, tree_arrays.right_child,
            tree_arrays.leaf_value, dd.bins,
            self.grow_params.num_leaves, bundle=self._bundle)
        if lin is not None:
            # per-leaf affine epilogue (models/linear.py); ``lin`` is the
            # device (coeff [L, K], feat [L, K] inner-index) pair
            delta = delta + affine_epilogue(leaf, lin[0], lin[1], dd.raw)
        return delta

    def _add_host_tree_to(self, dd: _DeviceData, tree: Tree, cls: int):
        if tree.num_leaves <= 1:
            dd.score = dd.score.at[cls].add(float(tree.leaf_value[0])
                                            if tree.num_leaves else 0.0)
            return
        # loaded (from_string) trees carry raw thresholds only; rebuild the
        # bin-space split representation against THIS dataset's mappers
        if not tree.ensure_inner(self.train_set.real_to_inner,
                                 self.train_set.mappers):
            log.fatal("Cannot replay a loaded tree on this dataset: it "
                      "splits on a feature the dataset binned as trivial")
        delta, leaf = predict_binned_tree(
            jnp.asarray(tree.split_feature_inner),
            jnp.asarray(tree.threshold_in_bin),
            jnp.asarray(tree.decision_type == 1),
            jnp.asarray(tree.left_child), jnp.asarray(tree.right_child),
            jnp.asarray(tree.leaf_value, jnp.float32), dd.bins,
            int(tree.num_leaves), bundle=self._bundle)
        if tree.has_linear():
            if dd.raw is None:
                log.fatal("Cannot replay a linear tree on this dataset: "
                          "no raw feature values are resident (build the "
                          "booster with linear_tree=true so the device "
                          "raw copy is uploaded)")
            inner = self._linear_inner_feat(tree)
            delta = delta + affine_epilogue(
                leaf, jnp.asarray(tree.leaf_coeff, jnp.float32),
                jnp.asarray(inner), dd.raw)
        dd.score = dd.score.at[cls].add(delta)

    def _linear_inner_feat(self, tree: Tree) -> np.ndarray:
        """A linear tree's leaf_feat (REAL feature indices, like
        split_feature) mapped into the training dataset's inner used-
        feature space — what the device raw matrix is indexed by.
        Refuses when an affine model reads a feature this dataset
        binned as trivial (there is no raw column to read)."""
        r2i = np.asarray(self.train_set.real_to_inner, np.int64)
        lf = np.asarray(tree.leaf_feat, np.int64)
        inner = np.where(lf >= 0, r2i[np.maximum(lf, 0)], -1)
        bad = (lf >= 0) & (inner < 0) \
            & (np.asarray(tree.leaf_coeff) != 0.0)
        if np.any(bad):
            log.fatal("Cannot replay a linear tree on this dataset: a "
                      "leaf's affine model reads feature(s) %s, which "
                      "the dataset binned as trivial",
                      sorted(set(lf[bad].tolist())))
        return inner.astype(np.int32)

    # ------------------------------------------------------------------
    def eval_and_check_early_stopping(self) -> bool:
        """Metric evaluation + early-stop bookkeeping (gbdt.cpp:404-509).
        Returns True to stop training."""
        cfg = self.config
        out_lines = []
        if cfg.is_training_metric and self.train_metrics:
            with timetag.scope("GBDT::metric"):
                score = self.train_data.host_score()
                for m in self.train_metrics:
                    for name, v in zip(m.names, m.eval(score)):
                        out_lines.append(
                            f"Iteration:{self.iter_}, training {name} : {v:g}")
        stop = False
        for vi, (dd, metrics) in enumerate(zip(self.valid_data,
                                               self.valid_metrics)):
            score = dd.host_score()
            for mi, m in enumerate(metrics):
                values = m.eval(score)
                for name, v in zip(m.names, values):
                    out_lines.append(
                        f"Iteration:{self.iter_}, valid_{vi + 1} {name} : {v:g}")
                key = (vi, m.names[0])
                cur = m.factor_to_bigger_better * values[0]
                if key not in self.best_score or cur > self.best_score[key]:
                    self.best_score[key] = cur
                    if mi == 0:
                        self.best_iteration = self.iter_
                        self.best_msg[vi] = "\n".join(out_lines)
                elif cfg.early_stopping_round > 0 and mi == 0:
                    if self.iter_ - self.best_iteration >= cfg.early_stopping_round:
                        log.info("Early stopping at iteration %d, best iteration %d",
                                 self.iter_, self.best_iteration)
                        stop = True
        if out_lines and (self.iter_ % max(cfg.output_freq, 1) == 0):
            for line in out_lines:
                log.info("%s", line)
        return stop

    def eval_metrics(self) -> Dict[str, Dict[str, float]]:
        """All current metric values, for callbacks/evals_result."""
        with timetag.scope("GBDT::metric"):
            return self._eval_metrics_impl()

    def _eval_metrics_impl(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        if self.train_metrics:
            score = self.train_data.host_score()
            out["training"] = {}
            for m in self.train_metrics:
                for name, v in zip(m.names, m.eval(score)):
                    out["training"][name] = v
        for vi, (dd, metrics) in enumerate(zip(self.valid_data,
                                               self.valid_metrics)):
            key = f"valid_{vi + 1}"
            score = dd.host_score()
            out[key] = {}
            for m in metrics:
                for name, v in zip(m.names, m.eval(score)):
                    out[key][name] = v
        return out

    # ------------------------------------------------------------------
    def train(self, num_iterations: Optional[int] = None) -> None:
        """Application::Train equivalent loop (application.cpp:224-240)."""
        n = num_iterations or self.config.num_iterations
        try:
            for it in range(n):
                stop = self.train_one_iter()
                if not stop and (self.valid_data
                                 or self.config.is_training_metric):
                    stop = self.eval_and_check_early_stopping() or stop
                if stop:
                    break
        finally:
            self.close_trace()

    # ------------------------------------------------------------------
    # Prediction (host entry: raw feature values)

    _DEVICE_PREDICT_MIN_ROWS = 4096

    def predict_raw(self, X: np.ndarray, num_iteration: int = -1) -> np.ndarray:
        """[K, n] raw scores (GBDT::PredictRaw, gbdt.cpp:791-798).

        Large batches take the device path (the parallel-Predictor
        equivalent, predictor.hpp:81-129): rows are binned with the
        training mappers on the host (f64-exact, so integer bin compares
        ROUTE rows identically to the reference's double threshold
        compares; the forest sum itself is Kahan-compensated f32, ~1e-7
        relative of the f64 host sum).  Small batches and mapper-less
        loaded models use the vectorized host walk."""
        X = np.asarray(X, np.float64)
        n_models = len(self.models)
        if num_iteration > 0:
            n_models = min(n_models, num_iteration * self.num_class)
        if (X.shape[0] >= self._DEVICE_PREDICT_MIN_ROWS and n_models > 0
                and getattr(self, "train_set", None) is not None
                and self.train_set.mappers
                and all(t.ensure_inner(self.train_set.real_to_inner,
                                       self.train_set.mappers)
                        for t in self.models[:n_models])
                and self._linear_device_ok(n_models)):
            return self._predict_raw_device(X, n_models)
        out = np.zeros((self.num_class, X.shape[0]), np.float64)
        for i in range(n_models):
            out[i % self.num_class] += self.models[i].predict(X)
        return out

    def _linear_device_ok(self, n_models: int) -> bool:
        """Device batch predict serves linear trees only when every
        affine feature maps into this dataset's inner (used-feature)
        space — the device raw matrix has no column for a trivially
        binned feature.  Unmappable models take the host walk, which
        reads REAL indices directly."""
        r2i = np.asarray(self.train_set.real_to_inner, np.int64)
        for t in self.models[:n_models]:
            if not t.has_linear():
                continue
            lf = np.asarray(t.leaf_feat, np.int64)
            used = (lf >= 0) & (np.asarray(t.leaf_coeff) != 0.0)
            if np.any(used & (r2i[np.maximum(lf, 0)] < 0)):
                return False
        return True

    def _predict_raw_device(self, X: np.ndarray, n_models: int) -> np.ndarray:
        ts = self.train_set
        n = X.shape[0]
        # host walk sends NaN right (numerical: NaN <= th is False;
        # categorical: int64(NaN) equals no category).  Route identically:
        # numerical NaN -> +inf before binning (last bin > any threshold),
        # categorical NaN -> bin -1, which equals no split's threshold bin
        # (a real category's bin would be routed left at a split on it).
        bins_np = np.zeros((len(ts.used_feature_map), n), dtype=np.int32)
        for inner, f in enumerate(ts.used_feature_map):
            col = X[:, f]
            isnan = np.isnan(col)
            if ts.mappers[inner].bin_type == CATEGORICAL:
                b = ts.mappers[inner].value_to_bin(
                    np.where(isnan, 0.0, col))
                bins_np[inner] = np.where(isnan, -1, b)
            else:
                bins_np[inner] = ts.mappers[inner].value_to_bin(
                    np.where(isnan, np.inf, col))
        # Shape-bucketed dispatch (serve/batcher.py): the forest jit
        # specializes on N, so pad rows up the bucket ladder instead of
        # compiling a fresh program for every batch size the caller
        # happens to send (chunked file predict alone produces two).
        # The padded bin matrix transfers to device ONCE per chunk and
        # is shared by every class's tree stack.
        from ..serve.batcher import BucketLadder
        ladder = BucketLadder(
            list(getattr(self.config, "predict_buckets", []) or []) or None)
        counting = _counting_forest_jit()
        # linear forests also ship the raw f32 covariates per chunk
        # (NaN imputed to 0.0, exactly the training upload's policy)
        linear = any(t.has_linear() for t in self.models[:n_models])
        raw_np = None
        if linear:
            Xr = X[:, list(ts.used_feature_map)].T.astype(np.float32)
            raw_np = np.where(np.isnan(Xr), np.float32(0.0), Xr)
        dev_chunks = []
        for off, m, bucket in ladder.chunks(n):
            bpad = np.zeros((bins_np.shape[0], bucket), np.int32)
            bpad[:, :m] = bins_np[:, off:off + m]
            rdev = None
            nbytes = int(bpad.nbytes)
            if linear:
                rpad = np.zeros((raw_np.shape[0], bucket), np.float32)
                rpad[:, :m] = raw_np[:, off:off + m]
                rdev = jnp.asarray(rpad)
                nbytes += int(rpad.nbytes)
            dev_chunks.append((off, m, bucket, jnp.asarray(bpad), rdev))
            obs.devprof.transfer("h2d", "predict", nbytes)
        # continued training may hold trees larger than grow_params allows
        L = max(max(t.num_leaves for t in self.models[:n_models]), 2)
        out = np.zeros((self.num_class, n), np.float64)
        for cls in range(self.num_class):
            trees = self.models[cls:n_models:self.num_class]
            if not trees:
                continue
            T = len(trees)
            sf = np.zeros((T, max(L - 1, 1)), np.int32)
            sb = np.zeros((T, max(L - 1, 1)), np.int32)
            ic = np.zeros((T, max(L - 1, 1)), bool)
            lc = np.zeros((T, max(L - 1, 1)), np.int32)
            rc = np.zeros((T, max(L - 1, 1)), np.int32)
            lv = np.zeros((T, L), np.float32)
            kf = (max([t.leaf_feat.shape[1] for t in trees
                       if t.has_linear()] or [1]) if linear else 0)
            lcf = np.zeros((T, L, max(kf, 1)), np.float32)
            lft = np.full((T, L, max(kf, 1)), -1, np.int32)
            for t, tree in enumerate(trees):
                k = tree.num_leaves - 1
                if k <= 0:
                    lv[t, 0] = tree.leaf_value[0] if tree.num_leaves else 0.0
                    # no nodes: make the walk stay at node 0 -> leaf 0
                    lc[t, 0] = ~0
                    rc[t, 0] = ~0
                    continue
                sf[t, :k] = tree.split_feature_inner
                sb[t, :k] = tree.threshold_in_bin
                ic[t, :k] = tree.decision_type == 1
                lc[t, :k] = tree.left_child
                rc[t, :k] = tree.right_child
                lv[t, :tree.num_leaves] = tree.leaf_value
                if linear and tree.has_linear():
                    nl, tk = tree.leaf_coeff.shape
                    lcf[t, :nl, :tk] = tree.leaf_coeff
                    lft[t, :nl, :tk] = self._linear_inner_feat(tree)
            args = (jnp.asarray(sf), jnp.asarray(sb), jnp.asarray(ic),
                    jnp.asarray(lc), jnp.asarray(rc), jnp.asarray(lv))
            if linear:
                lin_args = (jnp.asarray(lcf), jnp.asarray(lft))
                counting_lin = _counting_forest_linear_jit()
                for off, m, bucket, bdev, rdev in dev_chunks:
                    val = counting_lin(bucket, *args, *lin_args, bdev,
                                       rdev, max_steps=L)
                    out[cls, off:off + m] = np.asarray(val, np.float64)[:m]
            else:
                for off, m, bucket, bdev, _ in dev_chunks:
                    val = counting(bucket, *args, bdev, max_steps=L)
                    out[cls, off:off + m] = np.asarray(val, np.float64)[:m]
        return out

    def predict(self, X: np.ndarray, num_iteration: int = -1) -> np.ndarray:
        """With sigmoid/softmax transform (gbdt.cpp:799-815)."""
        raw = self.predict_raw(X, num_iteration)
        return np.asarray(self.objective.convert_output(raw)) \
            if hasattr(self, "objective") and self.objective is not None else raw

    def predict_leaf_index(self, X: np.ndarray,
                           num_iteration: int = -1) -> np.ndarray:
        X = np.asarray(X, np.float64)
        n_models = len(self.models)
        if num_iteration > 0:
            n_models = min(n_models, num_iteration * self.num_class)
        return np.stack([self.models[i].predict_leaf_index(X)
                         for i in range(n_models)], axis=1)

    # ------------------------------------------------------------------
    # Model serialization (gbdt.cpp:625-760)
    def save_model_to_string(self, num_iteration: int = -1) -> str:
        buf = io.StringIO()
        buf.write(self.submodel_name + "\n")
        buf.write(f"num_class={self.num_class}\n")
        buf.write(f"label_index={self.label_idx}\n")
        buf.write(f"max_feature_idx={self.max_feature_idx}\n")
        if getattr(self, "objective", None) is not None:
            buf.write(f"objective={self.objective.name}\n")
        buf.write(f"sigmoid={self.sigmoid:g}\n")
        buf.write("feature_names=" + " ".join(self.feature_names) + "\n")
        buf.write("feature_infos=" + " ".join(
            self.train_set.feature_infos() if hasattr(self, "train_set")
            else getattr(self, "feature_infos_", [])) + "\n")
        buf.write("\n")
        n_models = len(self.models)
        if num_iteration > 0:
            n_models = min(n_models, num_iteration * self.num_class)
        for i in range(n_models):
            buf.write(f"Tree={i}\n")
            buf.write(self.models[i].to_string())
            buf.write("\n")
        buf.write("\nfeature importances:\n")
        for name, cnt in self.feature_importance():
            buf.write(f"{name}={cnt}\n")
        # optional drift fingerprint section (obs/drift.py) AFTER the
        # footer: old readers ignore the tail, absent section = no
        # fingerprint — the PR 18 linear-section back-compat pattern
        fp = getattr(self, "data_fingerprint", None)
        if fp is not None:
            if fp.score_hist is None and getattr(self, "train_data",
                                                 None) is not None:
                # raw-margin training-score histogram, filled lazily at
                # first save (serve compares raw scores — no transform
                # disagreement between objectives)
                fp.set_score_hist(self.train_data.host_score(np.float64))
            buf.write("\n" + fp.to_text())
        return buf.getvalue()

    def save_model_to_file(self, path: str, num_iteration: int = -1) -> None:
        # atomic artifact write (utils/diskguard.py): a full disk fails
        # the save with a named, classified error, and the tmp+replace
        # protocol keeps the PREVIOUS good file — never a half-written
        # model mistaken for a good one, never a truncated-in-place
        # last-good destroyed by the failure
        from ..utils.diskguard import write_artifact_atomic
        text = self.save_model_to_string(num_iteration)
        write_artifact_atomic(path, text.encode(), "model_file")

    def feature_importance(self):
        """Split-count importance (gbdt.cpp:765-789)."""
        counts = np.zeros(self.max_feature_idx + 1, np.int64)
        for tree in self.models:
            for f in tree.split_feature[:tree.num_leaves - 1]:
                counts[f] += 1
        pairs = [(self.feature_names[f], int(counts[f]))
                 for f in range(len(counts)) if counts[f] > 0]
        pairs.sort(key=lambda kv: -kv[1])
        return pairs

    def load_model_from_string(self, text: str) -> None:
        """gbdt.cpp:679-760.

        Truncation/corruption containment (docs/FAULT_TOLERANCE.md
        §Data boundary): every header field, tree section, and the
        footer is validated, and any damage raises ``LightGBMError``
        naming the section, the tree index, and the file line — a
        half-written model file is a clean client error through the
        serve ``/reload`` 400 path and the CLI ``input_model``, never
        an index crash mid-predict."""
        import re

        lines = text.splitlines()
        kv: Dict[str, str] = {}
        for ln in lines:
            if ln.startswith("Tree="):
                break
            if "=" in ln:
                k, v = ln.split("=", 1)
                kv[k.strip()] = v.strip()
        if "num_class" not in kv:
            log.fatal("Model file doesn't specify the number of classes")

        def _header_int(key, default):
            raw = kv.get(key, default)
            try:
                return int(raw)
            except ValueError:
                log.fatal("Model file header: %s=%r is not an integer "
                          "— corrupt model file?", key, raw)

        def _header_float(key, default):
            raw = kv.get(key, default)
            try:
                return float(raw)
            except ValueError:
                log.fatal("Model file header: %s=%r is not a number "
                          "— corrupt model file?", key, raw)

        self.num_class = _header_int("num_class", "1")
        if self.num_class < 1:
            log.fatal("Model file header: num_class=%d must be >= 1",
                      self.num_class)
        self.label_idx = _header_int("label_index", 0)
        self.max_feature_idx = _header_int("max_feature_idx", 0)
        self.sigmoid = _header_float("sigmoid", -1.0)
        self.feature_names = kv.get("feature_names", "").split()
        self.feature_infos_ = kv.get("feature_infos", "").split()
        self.objective_name = kv.get("objective", "")
        # parse tree blocks; the footer ("feature importances:",
        # written by every save — reference gbdt.cpp too) doubles as
        # the truncation sentinel: a file chopped anywhere before it
        # is detectably incomplete even when the chop lands exactly on
        # a tree boundary
        footer_pos = text.find("\nfeature importances")
        if footer_pos < 0:
            log.fatal("Model file ends without the 'feature importances' "
                      "footer — truncated mid-write? (re-save the model "
                      "or restore from a good copy)")
        tree_marks = list(re.finditer(r"(?m)^Tree=(.*)$", text))
        tree_marks = [m for m in tree_marks if m.start() < footer_pos]
        self.models = []
        for i, m in enumerate(tree_marks):
            idx_s = m.group(1).strip()
            line_no = text.count("\n", 0, m.start()) + 1
            if idx_s != str(i):
                log.fatal("Model file: expected Tree=%d, found Tree=%s "
                          "(line %d) — trees missing or reordered; "
                          "corrupt model file?", i, idx_s, line_no)
            start = m.end()
            end = tree_marks[i + 1].start() if i + 1 < len(tree_marks) \
                else footer_pos
            try:
                self.models.append(Tree.from_string(text[start:end]))
            except LightGBMError as exc:
                log.fatal("Model file: Tree=%s (line %d): %s",
                          idx_s, line_no, exc)
        if self.models and len(self.models) % self.num_class != 0:
            log.fatal("Model file: %d tree(s) is not a multiple of "
                      "num_class=%d — trees missing; truncated model "
                      "file?", len(self.models), self.num_class)
        self.num_init_iteration = len(self.models) // max(self.num_class, 1)
        self.iter_ = self.num_init_iteration
        if not hasattr(self, "objective") or self.objective is None:
            self.objective = _objective_for_prediction(
                self.objective_name, self.sigmoid, self.num_class)
        # optional drift fingerprint after the footer (obs/drift.py):
        # absent -> None, truncated/garbled -> named LightGBMError with
        # the model-file framing the rest of this loader uses
        from ..obs.drift import DataFingerprint
        try:
            self.data_fingerprint = DataFingerprint.parse(
                text[footer_pos:])
        except LightGBMError as exc:
            log.fatal("%s", exc)

    def num_trees(self) -> int:
        return len(self.models)

    # -- merge (Boosting::MergeFrom) -----------------------------------
    def _merge_identity(self):
        """(num_class, feature width, objective name) for compatibility
        checks.  Objective name is '' when unknown (bare loaded model),
        in which case the objective gate abstains."""
        name = getattr(getattr(self, "objective", None), "name", "") \
            or getattr(self, "objective_name", "")
        if name == "none":
            name = ""
        return self.num_class, self.max_feature_idx, name

    def merge_from(self, other: "GBDT",
                   shrinkage_decay: float = 1.0) -> None:
        """Append ``other``'s trees to this model with their leaf outputs
        scaled by ``shrinkage_decay`` — Boosting::MergeFrom with decay.
        Refuses (named LightGBMError) rather than silently corrupting
        predictions when the two boosters are structurally incompatible."""
        d = float(shrinkage_decay)
        if not (0.0 < d <= 1.0) or d != d:
            raise LightGBMError(
                f"Cannot merge: shrinkage_decay must be in (0, 1], "
                f"got {shrinkage_decay!r}")
        nc_a, fw_a, obj_a = self._merge_identity()
        nc_b, fw_b, obj_b = other._merge_identity()
        if nc_a != nc_b:
            raise LightGBMError(
                f"Cannot merge: num_class mismatch "
                f"(base={nc_a}, other={nc_b})")
        if fw_a != fw_b:
            raise LightGBMError(
                f"Cannot merge: feature width mismatch "
                f"(base max_feature_idx={fw_a}, other={fw_b})")
        if obj_a and obj_b and obj_a != obj_b:
            raise LightGBMError(
                f"Cannot merge: objective mismatch "
                f"(base={obj_a!r}, other={obj_b!r})")
        merged = list(self.models)
        merged.extend(t.scaled_copy(d) for t in other.models)
        self.models = merged
        self.iter_ = len(self.models) // max(self.num_class, 1)


_COUNTING_FOREST_JIT = None


def _counting_forest_jit():
    """Process-wide compile-counting wrapper around the shared
    ``predict_binned_forest`` jit.  A single instance, so the shape-key
    fallback (jax builds without ``_cache_size``) accumulates across
    calls instead of recounting warm hits as compiles."""
    global _COUNTING_FOREST_JIT
    if _COUNTING_FOREST_JIT is None:
        from ..serve.batcher import CountingJit
        _COUNTING_FOREST_JIT = CountingJit(predict_binned_forest,
                                           "predict_forest")
    return _COUNTING_FOREST_JIT


_COUNTING_FOREST_LINEAR_JIT = None


def _counting_forest_linear_jit():
    """Linear-forest twin of ``_counting_forest_jit``: one process-wide
    compile-counting wrapper around ``predict_binned_forest_linear``.
    A separate entry point so constant-leaf predict keeps its exact
    pre-linear program (docs/LINEAR_TREES.md)."""
    global _COUNTING_FOREST_LINEAR_JIT
    if _COUNTING_FOREST_LINEAR_JIT is None:
        from ..serve.batcher import CountingJit
        _COUNTING_FOREST_LINEAR_JIT = CountingJit(
            predict_binned_forest_linear, "predict_forest")
    return _COUNTING_FOREST_LINEAR_JIT


def _mappers_aligned(a: BinnedDataset, b: BinnedDataset) -> bool:
    """True when two datasets share identical bin mappers (feature map,
    bin counts, and boundaries) — Dataset::CheckAlign equivalent.  With
    EFB the bundle plans must match too: replay/scoring runs on the
    bundled column matrix, so both sides need one column layout."""
    if a.used_feature_map != b.used_feature_map:
        return False
    pa, pb = getattr(a, "bundle_plan", None), getattr(b, "bundle_plan", None)
    if (pa is None) != (pb is None):
        return False
    if pa is not None and pa is not pb and pa.signature() != pb.signature():
        return False
    for ma, mb in zip(a.mappers, b.mappers):
        if ma is mb:
            continue
        if ma.num_bin != mb.num_bin or ma.bin_type != mb.bin_type:
            return False
        if not np.array_equal(ma.bin_upper_bound, mb.bin_upper_bound):
            return False
        if list(ma.bin_2_categorical) != list(mb.bin_2_categorical):
            return False
    return True


def _negate_tree(tree: Tree) -> Tree:
    """Copy with every leaf OUTPUT negated (DART drop / rollback replay).
    Routed through the single leaf-mutation point so affine leaves
    negate their slopes too (docs/LINEAR_TREES.md)."""
    return tree.scaled_copy(-1.0)


class _PredictionObjective(ObjectiveFunction):
    """Stand-in objective for loaded models (transform only)."""

    def __init__(self, name, sigmoid, num_class):
        self.name = name or "none"
        self.sigmoid = sigmoid
        self.num_class = num_class
        self.num_tree_per_iteration = num_class

    def convert_output(self, score):
        if self.num_class > 1:
            e = np.exp(score - score.max(axis=0, keepdims=True))
            return e / e.sum(axis=0, keepdims=True)
        if self.sigmoid > 0:
            return 1.0 / (1.0 + np.exp(-self.sigmoid * score))
        return score


def _objective_for_prediction(name, sigmoid, num_class):
    return _PredictionObjective(name, sigmoid, num_class)
