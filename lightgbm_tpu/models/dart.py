"""DART: Dropouts meet Multiple Additive Regression Trees.

Reference: src/boosting/dart.hpp.  Per iteration: drop a random subset of
trees (weighted by tree weight unless uniform_drop; skip probability
skip_drop; cap max_drop), compute gradients against the dropped score, train
with shrinkage lr/(1+k), then Normalize: scale the dropped trees by
k/(k+1) (or the xgboost-mode variant) and patch train/valid scores
(dart.hpp:84-178).
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..utils import log
from .gbdt import GBDT, _negate_tree
from .tree import Tree


class DART(GBDT):
    submodel_name = "dart"
    # Normalize reads/rewrites this iteration's host trees immediately
    # after training, so DART cannot run the one-iteration-behind pipeline.
    _pipeline = False

    def __init__(self, config, train_set, objective=None):
        super().__init__(config, train_set, objective)
        self.drop_rate = config.drop_rate
        self.max_drop = config.max_drop
        self.skip_drop = config.skip_drop
        self.uniform_drop = config.uniform_drop
        self.xgboost_dart_mode = config.xgboost_dart_mode
        self._drop_rng = np.random.RandomState(config.drop_seed)
        self.tree_weights: List[float] = []
        self.sum_weight = 0.0
        self.drop_index: List[int] = []
        self.shrinkage_rate = config.learning_rate

    # -- drop bookkeeping (dart.hpp:84-128) ------------------------------
    def _select_dropping_trees(self) -> None:
        """DroppingTrees (dart.hpp:84-128): per-tree Bernoulli draws;
        max_drop caps the drop *rate* (not the count); xgboost mode uses
        shrinkage lr/(lr+k) instead of lr/(1+k)."""
        self.drop_index = []
        lr = self.config.learning_rate
        num_iters = self.iter_
        if num_iters > 0 and not (self._drop_rng.uniform() < self.skip_drop):
            rate = self.drop_rate
            if not self.uniform_drop:
                inv_avg = num_iters / max(self.sum_weight, 1e-12)
                if self.max_drop > 0:
                    rate = min(rate, self.max_drop * inv_avg
                               / max(self.sum_weight, 1e-12))
                for i in range(num_iters):
                    if (self._drop_rng.uniform()
                            < rate * self.tree_weights[i] * inv_avg):
                        self.drop_index.append(i)
            else:
                if self.max_drop > 0:
                    rate = min(rate, self.max_drop / float(num_iters))
                for i in range(num_iters):
                    if self._drop_rng.uniform() < rate:
                        self.drop_index.append(i)
        k = len(self.drop_index)
        if not self.xgboost_dart_mode:
            self.shrinkage_rate = lr / (1.0 + k)
        else:
            self.shrinkage_rate = lr if k == 0 else lr / (lr + k)

    def _apply_drop(self) -> None:
        """Subtract dropped trees from all scores."""
        for it in self.drop_index:
            for cls in range(self.num_class):
                tree = self.models[it * self.num_class + cls]
                neg = _negate_tree(tree)
                self._add_host_tree_to(self.train_data, neg, cls)
                for dd in self.valid_data:
                    self._add_host_tree_to(dd, neg, cls)

    def _normalize(self) -> None:
        """Normalize (dart.hpp:139-178): re-add dropped trees scaled by
        k/(k+1), or k/(k+lr) in xgboost mode; weight bookkeeping mirrors
        the reference (including its 1/(k+lr) subtraction quirk)."""
        k = float(len(self.drop_index))
        lr = self.config.learning_rate
        if not self.xgboost_dart_mode:
            factor_dropped = k / (k + 1.0)
            weight_sub = 1.0 / (k + 1.0)
        else:
            factor_dropped = k / (k + lr)
            weight_sub = 1.0 / (k + lr)
        # The new tree is already added with shrinkage lr/(1+k) (or
        # lr/(lr+k)): matches the reference, which shrinks at train time and
        # then normalizes only the dropped trees.
        for it in self.drop_index:
            for cls in range(self.num_class):
                idx = it * self.num_class + cls
                tree = self.models[idx]
                # scale tree in place by factor, and add back factor * tree
                scaled = _scale_tree(tree, factor_dropped)
                self.models[idx] = scaled
                self._add_host_tree_to(self.train_data, scaled, cls)
                for dd in self.valid_data:
                    self._add_host_tree_to(dd, scaled, cls)
            if not self.uniform_drop:
                self.sum_weight -= self.tree_weights[it] * weight_sub
                self.tree_weights[it] *= factor_dropped

    # -- crash-safe snapshot/resume (lightgbm_tpu/snapshot.py) -----------
    def snapshot_state(self):
        state = super().snapshot_state()
        state["dart"] = {
            "tree_weights": list(self.tree_weights),
            "sum_weight": float(self.sum_weight),
            "drop_rng": self._drop_rng.get_state(),
        }
        return state

    def restore_state(self, state):
        super().restore_state(state)
        d = state.get("dart")
        if d is None:
            log.fatal("snapshot has no DART state; it was not taken from "
                      "a dart booster")
        self.tree_weights = list(d["tree_weights"])
        self.sum_weight = float(d["sum_weight"])
        self._drop_rng.set_state(d["drop_rng"])
        # drop_index is intra-iteration scratch: snapshots are taken at
        # iteration boundaries, after Normalize re-added the drops
        self.drop_index = []

    def train_one_iter(self, grad=None, hess=None) -> bool:
        self._select_dropping_trees()
        self._apply_drop()
        stop = super().train_one_iter(grad, hess)
        if not stop:
            self.tree_weights.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
            self._normalize()
        else:
            # training produced no tree: restore dropped trees untouched
            for it in self.drop_index:
                for cls in range(self.num_class):
                    tree = self.models[it * self.num_class + cls]
                    self._add_host_tree_to(self.train_data, tree, cls)
                    for dd in self.valid_data:
                        self._add_host_tree_to(dd, tree, cls)
        return stop


def _scale_tree(tree: Tree, factor: float) -> Tree:
    """DART normalization scaling, routed through the single leaf-output
    mutation point (Tree.scale_leaf_outputs) so affine leaves scale
    their slopes with their intercepts (docs/LINEAR_TREES.md)."""
    return tree.scaled_copy(factor)
