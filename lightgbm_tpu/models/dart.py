"""DART: Dropouts meet Multiple Additive Regression Trees.

Reference: src/boosting/dart.hpp.  Per iteration: drop a random subset of
trees (weighted by tree weight unless uniform_drop; skip probability
skip_drop; cap max_drop), compute gradients against the dropped score, train
with shrinkage lr/(1+k), then Normalize: scale the dropped trees by
k/(k+1) (or the xgboost-mode variant) and patch train/valid scores
(dart.hpp:84-178).
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..utils import log
from .gbdt import GBDT, _negate_tree
from .tree import Tree


class DART(GBDT):
    submodel_name = "dart"

    def __init__(self, config, train_set, objective=None):
        super().__init__(config, train_set, objective)
        self.drop_rate = config.drop_rate
        self.max_drop = config.max_drop
        self.skip_drop = config.skip_drop
        self.uniform_drop = config.uniform_drop
        self.xgboost_dart_mode = config.xgboost_dart_mode
        self._drop_rng = np.random.RandomState(config.drop_seed)
        self.tree_weights: List[float] = []
        self.sum_weight = 0.0
        self.drop_index: List[int] = []
        self.shrinkage_rate = config.learning_rate

    # -- drop bookkeeping (dart.hpp:84-137) ------------------------------
    def _select_dropping_trees(self) -> None:
        self.drop_index = []
        num_iters = self.iter_
        if num_iters <= 0:
            self.shrinkage_rate = self.config.learning_rate
            return
        if self._drop_rng.uniform() < self.skip_drop:
            # skip dropout this round
            self.shrinkage_rate = self.config.learning_rate
            return
        rate = self.drop_rate
        if self.uniform_drop:
            for i in range(num_iters):
                if self._drop_rng.uniform() < rate:
                    self.drop_index.append(i)
        else:
            inv_avg = num_iters / max(self.sum_weight, 1e-12)
            for i in range(num_iters):
                if self._drop_rng.uniform() < rate * self.tree_weights[i] * inv_avg:
                    self.drop_index.append(i)
        if len(self.drop_index) > self.max_drop:
            keep = self._drop_rng.choice(len(self.drop_index), self.max_drop,
                                         replace=False)
            self.drop_index = [self.drop_index[i] for i in sorted(keep)]
        k = len(self.drop_index)
        self.shrinkage_rate = self.config.learning_rate / (1.0 + k)

    def _apply_drop(self) -> None:
        """Subtract dropped trees from all scores."""
        for it in self.drop_index:
            for cls in range(self.num_class):
                tree = self.models[it * self.num_class + cls]
                neg = _negate_tree(tree)
                self._add_host_tree_to(self.train_data, neg, cls)
                for dd in self.valid_data:
                    self._add_host_tree_to(dd, neg, cls)

    def _normalize(self) -> None:
        """dart.hpp:139-178: re-add dropped trees scaled by k/(k+1)."""
        k = len(self.drop_index)
        new_tree_idx = self.iter_ - 1  # tree just trained
        if self.xgboost_dart_mode:
            scale_new = self.shrinkage_rate  # lr/(1+k) already applied at train
            factor_dropped = k / (k + 1.0)
        else:
            factor_dropped = k / (k + 1.0)
        # new tree already added with shrinkage lr/(1+k): matches reference,
        # which shrinks by shrinkage_rate_ then Normalize.
        for it in self.drop_index:
            for cls in range(self.num_class):
                idx = it * self.num_class + cls
                tree = self.models[idx]
                # scale tree in place by factor, and add back factor * tree
                scaled = _scale_tree(tree, factor_dropped)
                self.models[idx] = scaled
                self._add_host_tree_to(self.train_data, scaled, cls)
                for dd in self.valid_data:
                    self._add_host_tree_to(dd, scaled, cls)
                self.tree_weights[it] *= factor_dropped
        # weight bookkeeping for the new tree
        if k > 0:
            self.sum_weight = sum(self.tree_weights)

    def train_one_iter(self, grad=None, hess=None) -> bool:
        self._select_dropping_trees()
        self._apply_drop()
        stop = super().train_one_iter(grad, hess)
        if not stop:
            self.tree_weights.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
            self._normalize()
        else:
            # training produced no tree: restore dropped trees untouched
            for it in self.drop_index:
                for cls in range(self.num_class):
                    tree = self.models[it * self.num_class + cls]
                    self._add_host_tree_to(self.train_data, tree, cls)
                    for dd in self.valid_data:
                        self._add_host_tree_to(dd, tree, cls)
        return stop


def _scale_tree(tree: Tree, factor: float) -> Tree:
    import copy
    out = copy.deepcopy(tree)
    out.leaf_value = out.leaf_value * factor
    out.shrinkage *= factor
    return out
