"""GOSS: Gradient-based One-Side Sampling.

Reference: src/boosting/goss.hpp.  Keep all rows with |g*h| in the top
``top_rate`` fraction; randomly keep ``other_rate`` of the rest with
gradient amplification x (1-a)/b (goss.hpp:79-124); no sampling during the
first 1/learning_rate iterations (goss.hpp:129); bagging combination is
forbidden (checked at config time).

TPU formulation: instead of the reference's per-thread ArgMaxAtK partition,
the threshold is the (top_cnt)-th largest |g*h| from one device sort, and
the random keep/amplify decision is a vectorized mask.  Amplification is
applied to gradients AND hessians (like the reference, goss.hpp:108-118)
while the 0/1 row mask keeps leaf counts meaning true row counts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import log
from .gbdt import GBDT


class GOSS(GBDT):
    submodel_name = "goss"

    def __init__(self, config, train_set, objective=None):
        super().__init__(config, train_set, objective)
        self.top_rate = float(config.top_rate)
        self.other_rate = float(config.other_rate)
        if self.top_rate + self.other_rate >= 1.0:
            log.warning("top_rate + other_rate >= 1.0 in GOSS: no sampling")
        self._goss_key = jax.random.PRNGKey(config.bagging_seed)

    # GBDT.train_one_iter drives these hooks: _gradients() (objective
    # path) and _transform_host_gradients() (custom-fobj / C API path)
    # both run the GOSS draw, so sampling happens regardless of where the
    # gradients come from (the reference's Bagging step is
    # objective-agnostic, goss.hpp); _bagging_mask serves the mask back.
    def _gradients(self):
        grad, hess = super()._gradients()
        return self._transform_host_gradients(grad, hess)

    def _transform_host_gradients(self, grad, hess):
        warmup = int(1.0 / max(self.config.learning_rate, 1e-12))
        if self.iter_ < warmup:
            # all real rows active (row-bucket pad rows stay at weight 0)
            self._row_weight = self._ones_weight
            self._bag_cnt = self.num_data
            return grad, hess
        mask, grad, hess = self._sample(grad, hess)
        self._row_weight = mask
        # telemetry: the GOSS draw is this round's "bag" (a*N + b*N rows)
        top_cnt = int(self.top_rate * self.num_data)
        other_cnt = int(self.other_rate * self.num_data)
        kept = top_cnt + other_cnt
        self._bag_cnt = kept if 0 < top_cnt and kept < self.num_data \
            else self.num_data
        from .. import obs
        obs.inc("bagging_draws")
        return grad, hess

    def _bagging_mask(self, iter_):
        return self._row_weight

    # -- crash-safe snapshot/resume (lightgbm_tpu/snapshot.py) -----------
    # _row_weight/_bag_cnt ride in the base state; only the sampling key
    # is GOSS-specific (the warmup gate derives from iter_).
    def snapshot_state(self):
        state = super().snapshot_state()
        state["goss"] = {"key": np.asarray(self._goss_key)}
        return state

    def restore_state(self, state):
        super().restore_state(state)
        g = state.get("goss")
        if g is None:
            log.fatal("snapshot has no GOSS state; it was not taken from "
                      "a goss booster")
        self._goss_key = jnp.asarray(g["key"], jnp.uint32)

    def _sample(self, grad, hess):
        n = self.num_data
        top_cnt = int(self.top_rate * n)
        other_cnt = int(self.other_rate * n)
        if top_cnt + other_cnt >= n or top_cnt == 0:
            return self._ones_weight, grad, hess
        # gradients arrive at the padded row-bucket shape; pad rows must
        # never be drawn (their gradients are real numbers computed off a
        # zero label), so they rank at -inf and are masked from the
        # random keep below.  Direct callers (tests) may pass bare [K, N]
        # gradients — bring them up to the bucket first.
        np_rows = self._padded_rows
        if grad.shape[1] < np_rows:
            w = ((0, 0), (0, np_rows - grad.shape[1]))
            grad, hess = jnp.pad(grad, w), jnp.pad(hess, w)
        # |g * h| summed over classes (goss.hpp:90: multiclass sums classes)
        score = jnp.abs(grad * hess).sum(axis=0)
        score = jnp.where(self._real_rows, score, -jnp.inf)
        # EXACTLY top_cnt rows kept (ArgMaxAtK, goss.hpp:79-124): rank by
        # score with row index as the tie-break, not a >= threshold test —
        # low-entropy gradients (many equal |g*h|) would otherwise keep
        # every tie of the top_cnt-th score and overshoot a*N
        # (round-2 VERDICT weak #8).
        order = jnp.argsort(-score, stable=True)
        rank = jnp.zeros(np_rows, jnp.int32).at[order].set(
            jnp.arange(np_rows, dtype=jnp.int32), unique_indices=True)
        self._goss_key, sub = jax.random.split(self._goss_key)
        rand = jax.random.uniform(sub, (np_rows,))
        keep_prob = self.other_rate / max(1e-12, 1.0 - self.top_rate)
        is_top = rank < top_cnt
        is_other_kept = (~is_top) & (rand < keep_prob) & self._real_rows
        mask = (is_top | is_other_kept).astype(jnp.float32)
        amp = (1.0 - self.top_rate) / max(self.other_rate, 1e-12)
        factor = jnp.where(is_other_kept, amp, 1.0)
        return mask, grad * factor[None, :], hess * factor[None, :]
