"""EMA-FS gain-informed feature screening (docs/SPARSE.md).

"EMA-FS: Accelerating GBDT Training via Gain-Informed Feature Screening"
(PAPERS.md): most features stop earning splits after the early rounds,
yet every round still pays their full histogram pass.  The screener
keeps a per-feature exponentially-weighted moving average of *realized*
split gains and masks the bottom ``feature_screen_ratio`` of the feature
space out of each round's ``feat_masks`` — which are already runtime
arguments to the shared ``train_step`` program (models/gbdt.py), so
toggling masks never triggers an XLA recompile (ledger-pinned in
tests/test_screening.py).

Schedule:
  * ``feature_screen_warmup`` unscreened rounds seed the EWMA,
  * then every ``feature_screen_refresh``-th round is a full-feature
    REFRESH round (all features scan, so a dormant feature whose signal
    appears late can re-enter),
  * all other rounds are SCREENED.

Masking alone only saves split-finder work; the histogram pass still
reads every column.  ``GBDT`` therefore also *compacts* screened rounds:
the active COLUMNS (screening is column-granular so it composes with EFB
bundles — a column stays active while any member feature does) are
gathered into a fixed-budget ``[C_active_padded, N]`` block whose padded
shape is chosen ONCE (compile-cache bucket ladder), so every screened
round of a run shares one compiled program regardless of which columns
are active.  The active set is re-drawn once per refresh period; the
EWMA itself updates every round.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np


class GainScreener:
    """Per-feature split-gain EWMA + the screening schedule."""

    def __init__(self, num_features: int, num_columns: int,
                 feature_col: np.ndarray, *, ratio: float, refresh: int,
                 warmup: int, decay: float):
        self.num_features = int(num_features)
        self.num_columns = int(num_columns)
        self.feature_col = np.asarray(feature_col, np.int64)
        self.ratio = float(ratio)
        self.refresh = max(int(refresh), 1)
        self.warmup = max(int(warmup), 0)
        self.decay = float(decay)
        self.keep_cols = max(
            1, int(math.ceil((1.0 - self.ratio) * self.num_columns)))
        self.ewma = np.zeros(self.num_features, np.float64)
        self._round_gain = np.zeros(self.num_features, np.float64)
        self.refresh_total = 0

    # -- gain observation ------------------------------------------------
    def observe_trees(self, trees) -> None:
        """Fold one iteration's materialized trees into the EWMA.

        Split features arrive in inner (used-original) space
        (Tree.split_feature_inner, models/tree.py from_arrays)."""
        acc = self._round_gain
        for t in trees:
            n = int(t.num_leaves) - 1
            if n <= 0:
                continue
            feats = np.asarray(t.split_feature_inner[:n], np.int64)
            gains = np.maximum(np.asarray(t.split_gain[:n], np.float64), 0.0)
            ok = (feats >= 0) & (feats < self.num_features)
            np.add.at(acc, feats[ok], gains[ok])
        self.ewma = self.decay * self.ewma + (1.0 - self.decay) * acc
        acc[:] = 0.0

    # -- schedule --------------------------------------------------------
    def round_mode(self, it: int) -> str:
        """'warmup' | 'refresh' | 'screened' for 0-based round ``it``."""
        if it < self.warmup:
            return "warmup"
        if (it - self.warmup) % self.refresh == 0:
            return "refresh"
        return "screened"

    def period(self, it: int) -> int:
        """Refresh-period index; the active set is redrawn when this
        changes (once per ``feature_screen_refresh`` rounds)."""
        return max(it - self.warmup, 0) // self.refresh

    # -- active set ------------------------------------------------------
    def active_columns(self) -> np.ndarray:
        """Top ``keep_cols`` columns by max member-feature EWMA (sorted
        ascending; ties prefer the lower column index, deterministic)."""
        score = np.full(self.num_columns, -np.inf)
        np.maximum.at(score, self.feature_col, self.ewma)
        # stable argsort on (-score, col): best columns first
        order = np.lexsort((np.arange(self.num_columns), -score))
        return np.sort(order[:self.keep_cols]).astype(np.int64)

    def screen_mask(self, active_cols: np.ndarray) -> np.ndarray:
        """[F] bool: feature's column is in the active set."""
        keep = np.zeros(self.num_columns, bool)
        keep[np.asarray(active_cols, np.int64)] = True
        return keep[self.feature_col]

    # -- snapshot/resume (lightgbm_tpu/snapshot.py) ----------------------
    def state(self) -> Dict:
        return {"ewma": self.ewma.copy(),
                "refresh_total": int(self.refresh_total)}

    def restore(self, state: Optional[Dict]) -> None:
        if not state:
            return
        saved = np.asarray(state.get("ewma", ()), np.float64)
        if saved.shape == self.ewma.shape:
            self.ewma = saved.copy()
        self.refresh_total = int(state.get("refresh_total", 0))
